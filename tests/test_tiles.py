"""Key-range tiled maintenance (``REFLOW_TILE_BYTES``): the bucket/plan
partition must be deterministic and never split a bucket; tiled
compaction must fold to exact replay parity, survive a crash at either
per-tile seam and resume finished tiles instead of refolding them; a
torn final *tiled* delta element must fall back one element with the
WAL covering the gap; an untiled reader must restore a tiled
checkpoint (the knob is write-side only); replica snapshots must reuse
clean tiles by identity (zero-copy) and rebuild only dirty ones; and
the tile-unit bootstrap protocol must NACK-and-retry a single corrupt
unit, fall back whole when retries exhaust, and never stage a
traversal or an incomplete transfer."""

import glob
import os

import numpy as np
import pytest

from reflow_tpu import DirtyScheduler
from reflow_tpu.serve import ReplicaScheduler
from reflow_tpu.utils import tiles
from reflow_tpu.utils.checkpoint import CheckpointChain
from reflow_tpu.utils.faults import CrashInjector, CrashPoint
from reflow_tpu.wal import (DurableScheduler, SegmentShipper, WalCompactor,
                            recover)
from reflow_tpu.wal.compact import read_compact_manifest
from reflow_tpu.wal.log import _MAGIC
from reflow_tpu.workloads import wordcount


# -- helpers ----------------------------------------------------------------

def make_feed(seed, n_ticks, tag="", vocab=25):
    """Deterministic per-tick [(batch_id, batch)] lists with retractions
    mixed in (same shape as the compaction tests')."""
    rng = np.random.default_rng(seed)
    feed = []
    for t in range(n_ticks):
        batches = []
        for j in range(int(rng.integers(1, 3))):
            words = " ".join(
                f"w{int(x)}" for x in rng.integers(0, vocab,
                                                   int(rng.integers(2, 8))))
            weight = -1 if (t > 2 and rng.random() < 0.2) else 1
            batches.append((f"{tag}t{t}b{j}",
                            wordcount.ingest_lines([words], weight=weight)))
        feed.append(batches)
    return feed


def build_log(wal_dir, feed, segment_bytes=1 << 12):
    g, src, sink = wordcount.build_graph()
    sched = DurableScheduler(g, wal_dir=wal_dir, fsync="tick",
                             segment_bytes=segment_bytes)
    for batches in feed:
        for bid, b in batches:
            sched.push(src, b, batch_id=bid)
        sched.tick()
    view = dict(sched.view(sink.name))
    tick = sched._tick
    sched.close()
    return view, tick


def recovered_view(wal_dir, ckpt_dir=None):
    g, _src, sink = wordcount.build_graph()
    sched = DirtyScheduler(g)
    recover(sched, wal_dir, ckpt_dir)
    return dict(sched.view(sink.name)), sched._tick


def live_view(sched, sink):
    return {kv: w for kv, w in sched.view(sink.name).items() if w != 0}


# -- bucketing / planning primitives ----------------------------------------

def test_bucket_of_stable_across_processes():
    # crc32-based, NOT hash(): these exact values are what every other
    # process (leader, compactor, replica, shipper) computes — a change
    # here silently scatters tiles, so the constants are pinned
    assert tiles.bucket_of("alpha") == 22
    assert tiles.bucket_of(("w1", "w1")) == 3
    assert tiles.bucket_of(7) == 2
    assert tiles.bucket_of((b"x", 3.5)) == 24


def test_bucket_of_numpy_scalar_matches_python():
    # a replayed key often comes back as np.int64 where the live one
    # was int: scalarization must land both in the same bucket
    assert tiles.bucket_of(np.int64(7)) == tiles.bucket_of(7)
    arr = np.arange(3, dtype=np.float32)
    assert tiles.bucket_of(arr) == tiles.bucket_of(arr.copy())


def test_approx_row_bytes_estimates():
    assert tiles.approx_row_bytes("abc", None) == 3 + 16
    arr = np.arange(3, dtype=np.float32)
    assert tiles.approx_row_bytes(arr, None) == arr.nbytes + 16
    assert tiles.approx_row_bytes("ab", "cd") == 2 + 2 + 16


def test_plan_tiles_contiguous_cover_never_splits_bucket():
    rng = np.random.default_rng(0)
    hist = [float(x) for x in rng.integers(1, 200, tiles.N_BUCKETS)]
    plan = tiles.plan_tiles(hist, 400)
    assert len(plan) > 1
    assert plan[0][0] == 0 and plan[-1][1] == tiles.N_BUCKETS
    for (_, a_hi), (b_lo, _) in zip(plan, plan[1:]):
        assert a_hi == b_lo  # contiguous, no gap, no overlap
    assert all(hi > lo for lo, hi in plan)
    # an oversized bucket becomes its OWN tile rather than being split
    hot = [1.0] * tiles.N_BUCKETS
    hot[10] = 10_000.0
    plan = tiles.plan_tiles(hot, 100)
    i = tiles.owning_tile(plan, 10)
    assert plan[i] == (10, 11)


def test_plan_budget_zero_is_monolithic_and_owning_tile_raises():
    assert tiles.plan_tiles([1.0] * tiles.N_BUCKETS, 0) \
        == [(0, tiles.N_BUCKETS)]
    with pytest.raises(KeyError):
        tiles.owning_tile([(0, 32)], 40)


# -- tiled compaction -------------------------------------------------------

def test_tiled_fold_parity_and_manifest(tmp_path):
    # straddling keys: every tile folds its own bucket slice of every
    # source record, and the union replays to the exact oracle
    wal_dir = str(tmp_path / "wal")
    oracle, tick = build_log(wal_dir, make_feed(7, 30))
    comp = WalCompactor(wal_dir=wal_dir, min_segments=2, keep_segments=1,
                        tile_bytes=512)
    assert comp.compact_once() is not None
    while comp.compact_once() is not None:
        pass
    m = read_compact_manifest(wal_dir)
    ent = next(e for e in m["ranges"] if "tiles" in e)
    ti = ent["tiles"]
    assert ti["n"] >= 2 and ti["n"] == len(ti["plan"])
    assert ti["plan"][0][0] == 0 \
        and ti["plan"][-1][1] == tiles.N_BUCKETS
    assert all(g >= 1 for g in ti["gens"])
    assert 0 < ti["peak_tile_bytes"] <= 2 * 512
    got, got_tick = recovered_view(wal_dir)
    assert got == oracle and got_tick == tick


@pytest.mark.parametrize("seam", ["compact_tile_before_progress",
                                  "compact_tile_after_progress"])
def test_tiled_fold_crash_resumes_finished_tiles(tmp_path, seam):
    wal_dir = str(tmp_path / "wal")
    oracle, tick = build_log(wal_dir, make_feed(3, 30))
    inj = CrashInjector(2, only=seam)
    comp = WalCompactor(wal_dir=wal_dir, min_segments=2, keep_segments=1,
                        tile_bytes=512, crash=inj)
    with pytest.raises(CrashPoint):
        comp.compact_once()
    assert inj.fired_seam == seam
    # the originals are untouched mid-pass: recovery BEFORE the resume
    # sees exact parity (the tmp segment + sidecar are invisible)
    got, got_tick = recovered_view(wal_dir)
    assert got == oracle and got_tick == tick
    # a fresh compactor (new process) resumes: finished tiles are kept
    # from the sidecar, only the rest refold under attempt 2
    comp2 = WalCompactor(wal_dir=wal_dir, min_segments=2, keep_segments=1,
                         tile_bytes=512)
    ev = comp2.compact_once()
    assert ev is not None
    ti = read_compact_manifest(wal_dir)["ranges"][-1]["tiles"]
    assert ti["attempts"] == 2
    if seam == "compact_tile_after_progress":
        # two tiles were recorded done before the crash; their gen-1
        # output survives verbatim while the rest carry gen 2
        assert ti["resumed_tiles"] >= 1
        assert set(ti["gens"]) == {1, 2}
    got, got_tick = recovered_view(wal_dir)
    assert got == oracle and got_tick == tick


# -- tiled checkpoint chains ------------------------------------------------

def drive_chain(tmp_path, saves=3, per_save=5):
    """Leader + chain with a save every ``per_save`` ticks, plus an
    unsaved tail; returns (wal_dir, root, final view, tick, chain)."""
    wal_dir = str(tmp_path / "wal")
    root = str(tmp_path / "ckpt")
    g, src, sink = wordcount.build_graph()
    sched = DurableScheduler(g, wal_dir=wal_dir, fsync="tick",
                             segment_bytes=1 << 12)
    chain = CheckpointChain(root, delta_every=4)
    t = 0
    for _ in range(saves):
        for batches in make_feed(t, per_save, tag=f"s{t}"):
            for bid, b in batches:
                sched.push(src, b, batch_id=bid)
            sched.tick()
        t += per_save
        chain.save(sched)
    for batches in make_feed(99, 2, tag="tail"):
        for bid, b in batches:
            sched.push(src, b, batch_id=bid)
        sched.tick()
    view = live_view(sched, sink)
    tick = sched._tick
    sched.close()
    return wal_dir, root, view, tick, chain


def test_torn_final_tiled_delta_falls_back_one_element(
        tmp_path, monkeypatch):
    monkeypatch.setenv("REFLOW_TILE_BYTES", "512")
    wal_dir, root, view, tick, chain = drive_chain(tmp_path)
    assert chain.tile_count >= 2  # the elements really tiled
    deltas = sorted(glob.glob(os.path.join(root, "delta-*.ckd")))
    assert deltas
    with open(deltas[-1], "rb+") as f:
        f.truncate(os.path.getsize(deltas[-1]) - 4)  # tear a tile frame
    # validation happens before a single frame is applied, so the torn
    # element mutates nothing; truncation lags one element, so the WAL
    # tail still covers the dropped window — exact parity
    got, got_tick = recovered_view(wal_dir, root)
    assert {kv: w for kv, w in got.items() if w != 0} == view
    assert got_tick == tick


@pytest.mark.parametrize("seam", ["ckpt_tile_full_append",
                                  "ckpt_tile_append"])
def test_tiled_chain_crash_seam_recovers(tmp_path, monkeypatch, seam):
    # kill the element writer between tile appends: the chain manifest
    # never flipped, so recovery restores the previous element (or
    # replays from scratch) plus the untruncated WAL tail
    monkeypatch.setenv("REFLOW_TILE_BYTES", "512")
    wal_dir = str(tmp_path / "wal")
    root = str(tmp_path / "ckpt")
    g, src, sink = wordcount.build_graph()
    sched = DurableScheduler(g, wal_dir=wal_dir, fsync="tick",
                             segment_bytes=1 << 12)
    inj = CrashInjector(2, only=seam)
    chain = CheckpointChain(root, delta_every=4, crash=inj)
    fired = False
    for i in range(4):
        for batches in make_feed(20 + i, 5, tag=f"c{i}"):
            for bid, b in batches:
                sched.push(src, b, batch_id=bid)
            sched.tick()
        if not fired:
            try:
                chain.save(sched)
            except CrashPoint:
                fired = True
    assert fired and inj.fired_seam == seam
    view = live_view(sched, sink)
    tick = sched._tick
    sched.close()
    got, got_tick = recovered_view(wal_dir, root)
    assert {kv: w for kv, w in got.items() if w != 0} == view
    assert got_tick == tick


def test_untiled_reader_restores_tiled_chain(tmp_path, monkeypatch):
    # the knob is write-side only: a reader with REFLOW_TILE_BYTES
    # unset walks the same manifest and streams the same frames
    monkeypatch.setenv("REFLOW_TILE_BYTES", "512")
    wal_dir, root, view, tick, chain = drive_chain(tmp_path)
    assert chain.tile_count >= 2
    assert glob.glob(os.path.join(root, "*", "tiles", "*.ckt"))
    monkeypatch.delenv("REFLOW_TILE_BYTES")
    got, got_tick = recovered_view(wal_dir, root)
    assert {kv: w for kv, w in got.items() if w != 0} == view
    assert got_tick == tick


# -- tiled replica snapshots ------------------------------------------------

def make_pair(tmp_path, tile_bytes=512):
    g, src, sink = wordcount.build_graph()
    sched = DurableScheduler(g, wal_dir=str(tmp_path / "wal"),
                             fsync="tick")
    ship = SegmentShipper(sched.wal, leader_tick=lambda: sched._tick)
    g2, _s2, _k2 = wordcount.build_graph()
    rep = ReplicaScheduler(g2, str(tmp_path / "r0"), name="r0",
                           tile_bytes=tile_bytes)
    ship.attach(rep)
    return sched, src, sink, ship, rep


def pump(sched, ship, rep):
    sched.wal.sync()
    for _ in range(100):
        ship.pump_once()
        if rep.published_horizon() == sched._tick:
            return
    raise AssertionError("replica stuck")


def test_snapshot_reuses_clean_tiles_by_identity(tmp_path):
    sched, src, sink, ship, rep = make_pair(tmp_path)
    for batches in make_feed(5, 12):
        for bid, b in batches:
            sched.push(src, b, batch_id=bid)
        sched.tick()
    pump(sched, ship, rep)
    s1 = rep._snapshot(sink.name)
    assert len(s1.plan) >= 2
    # one tick touching one key: only the owning tile may rebuild
    sched.push(src, wordcount.ingest_lines(["w3 w3"]), batch_id="hot")
    sched.tick()
    pump(sched, ship, rep)
    s2 = rep._snapshot(sink.name)
    assert s2.plan == s1.plan and s2.horizon > s1.horizon
    reused = sum(1 for a, b in zip(s1.tiles, s2.tiles) if a is b)
    assert reused >= 1  # zero-copy: same array objects, same gen
    assert reused < len(s2.tiles)  # but the dirty tile DID rebuild
    for a, b in zip(s1.tiles, s2.tiles):
        assert (b.gen == a.gen) if (a is b) else (b.gen == a.gen + 1)
    assert rep.snapshot_tiles_reused >= reused
    h, got = rep.view_at(sink.name)
    assert h == sched._tick and got == live_view(sched, sink)
    sched.close()
    rep.close()


def test_snapshot_empty_window_reuses_whole_tuple(tmp_path):
    sched, src, sink, ship, rep = make_pair(tmp_path)
    for batches in make_feed(6, 8):
        for bid, b in batches:
            sched.push(src, b, batch_id=bid)
        sched.tick()
    pump(sched, ship, rep)
    s1 = rep._snapshot(sink.name)
    sched.tick()  # an empty tick: horizon advances, no sink delta
    pump(sched, ship, rep)
    s2 = rep._snapshot(sink.name)
    assert s2.horizon == s1.horizon + 1
    assert s2.tiles is s1.tiles  # the whole tuple carried by identity
    sched.close()
    rep.close()


def test_replica_tile_gauges_lifecycle(tmp_path):
    from reflow_tpu.obs import MetricsRegistry

    sched, src, sink, ship, rep = make_pair(tmp_path)
    reg = MetricsRegistry()
    rep.publish_metrics(reg)
    for batches in make_feed(8, 6):
        for bid, b in batches:
            sched.push(src, b, batch_id=bid)
        sched.tick()
    pump(sched, ship, rep)
    rep._snapshot(sink.name)
    assert reg.value("replica.r0.snapshot_tiles") >= 2
    assert reg.value("replica.r0.snapshot_tiles_reused") >= 0
    rep.close()
    assert reg.value("replica.r0.snapshot_tiles") is None
    sched.close()


# -- tile-unit bootstrap protocol -------------------------------------------

def tiled_leader_with_chain(tmp_path, monkeypatch):
    monkeypatch.setenv("REFLOW_TILE_BYTES", "512")
    g, src, sink = wordcount.build_graph()
    sched = DurableScheduler(g, wal_dir=str(tmp_path / "wal"),
                             fsync="tick", segment_bytes=1 << 12)
    chain = CheckpointChain(str(tmp_path / "ckpt"), delta_every=4)
    for batches in make_feed(11, 10):
        for bid, b in batches:
            sched.push(src, b, batch_id=bid)
        sched.tick()
    chain.save(sched)
    sched.wal.sync()
    assert chain.tile_count >= 2
    return sched, src, sink, str(tmp_path / "ckpt")


class FlakyTransport:
    """Delegating replica proxy that corrupts the first N tile units in
    flight (payload flipped after the CRC was stamped)."""

    def __init__(self, inner, corrupt_first=1):
        self.inner = inner
        self.left = corrupt_first

    def __getattr__(self, name):
        return getattr(self.inner, name)

    def receive_ckpt_tile(self, unit):
        if self.left > 0 and unit.get("payload"):
            self.left -= 1
            unit = dict(unit)
            unit["payload"] = b"\xff" + unit["payload"][1:]
        return self.inner.receive_ckpt_tile(unit)


def test_tile_unit_corrupt_in_flight_nacked_and_retried(
        tmp_path, monkeypatch):
    sched, src, sink, root = tiled_leader_with_chain(tmp_path, monkeypatch)
    ship = SegmentShipper(sched.wal, ckpt_dir=root,
                          leader_tick=lambda: sched._tick)
    g2, _s2, _k2 = wordcount.build_graph()
    rep = ReplicaScheduler(g2, str(tmp_path / "r0"), name="r0")
    ship.attach(FlakyTransport(rep))
    # the corrupt unit was NACKed (per-unit CRC) and ONLY that unit was
    # re-sent; the transfer completed as a tile bootstrap, not whole
    assert rep.crc_rejects == 1
    assert ship.tile_unit_retries == 1
    assert ship.tile_bootstraps == 1
    assert ship.tile_units_shipped > 2
    pump(sched, ship, rep)
    h, got = rep.view_at(sink.name)
    assert h == sched._tick and got == live_view(sched, sink)
    sched.close()
    rep.close()


def test_tile_unit_retries_exhaust_falls_back_whole(tmp_path, monkeypatch):
    monkeypatch.setenv("REFLOW_TILE_SHIP_RETRIES", "2")
    sched, src, sink, root = tiled_leader_with_chain(tmp_path, monkeypatch)
    ship = SegmentShipper(sched.wal, ckpt_dir=root,
                          leader_tick=lambda: sched._tick)
    g2, _s2, _k2 = wordcount.build_graph()
    rep = ReplicaScheduler(g2, str(tmp_path / "r0"), name="r0")
    ship.attach(FlakyTransport(rep, corrupt_first=10 ** 6))
    # every attempt NACKs -> the shipper gives up on the unit protocol
    # and the plain whole-directory bootstrap still anchors the replica
    assert ship.tile_bootstraps == 0
    assert ship.tile_unit_retries == 2
    pump(sched, ship, rep)
    h, got = rep.view_at(sink.name)
    assert h == sched._tick and got == live_view(sched, sink)
    sched.close()
    rep.close()


def test_receive_ckpt_tile_rejects_bad_units(tmp_path):
    import zlib

    g, _s, _k = wordcount.build_graph()
    rep = ReplicaScheduler(g, str(tmp_path / "r0"), name="r0")
    assert rep.receive_ckpt_tile({"schema": "nope"})["ok"] is False
    body = b"payload"
    unit = {"schema": "reflow.tile_ship/1", "rel": "../evil", "idx": 0,
            "total": 2, "payload": body,
            "crc": zlib.crc32(body) & 0xFFFFFFFF, "last": False}
    resp = rep.receive_ckpt_tile(unit)
    assert resp["ok"] is False and "relpath" in resp["reason"]
    assert not os.path.exists(str(tmp_path / "evil"))
    # a "last" unit arriving before every index staged is an incomplete
    # transfer: NACK whole, nothing anchors
    unit = {"schema": "reflow.tile_ship/1", "rel": "meta.pkl", "idx": 1,
            "total": 3, "payload": body,
            "crc": zlib.crc32(body) & 0xFFFFFFFF, "last": True}
    resp = rep.receive_ckpt_tile(unit)
    assert resp["ok"] is False and "incomplete" in resp["reason"]
    rep.close()


def test_follower_reanchor_into_tile_compacted_range(tmp_path, monkeypatch):
    # the PR-10 stale-cursor re-anchor, with the rewritten segment now
    # holding per-tile part records: the re-anchored follower replays
    # cover + parts through the checkpoint bootstrap and converges
    monkeypatch.setenv("REFLOW_TILE_BYTES", "512")
    wal_dir = str(tmp_path / "wal")
    ckpt_dir = str(tmp_path / "ckpt")
    g, src, sink = wordcount.build_graph()
    sched = DurableScheduler(g, wal_dir=wal_dir, fsync="tick",
                             segment_bytes=1 << 12)
    chain = CheckpointChain(ckpt_dir, delta_every=4)
    chain.save(sched)
    ship = SegmentShipper(sched.wal, ckpt_dir=ckpt_dir,
                          leader_tick=lambda: sched._tick)
    g2, _s2, sink2 = wordcount.build_graph()
    replica = ReplicaScheduler(g2, str(tmp_path / "r0"), name="r0")
    ship.attach(replica)
    for batches in make_feed(4, 3):
        for bid, b in batches:
            sched.push(src, b, batch_id=bid)
        sched.tick()
    sched.wal.sync()
    ship.pump_once()
    stale = replica.subscribe()
    assert stale is not None and stale[1] > len(_MAGIC)
    ship.detach("r0")
    for batches in make_feed(6, 30, tag="x"):
        for bid, b in batches:
            sched.push(src, b, batch_id=bid)
        sched.tick()
    sched.wal.sync()
    comp = WalCompactor(sched.wal, ckpt_dir=ckpt_dir, min_segments=1,
                        keep_segments=1)
    ev = comp.compact_once()
    assert ev is not None and ev["covers"][0] == stale[0]
    ti = read_compact_manifest(wal_dir)["ranges"][-1]["tiles"]
    assert ti["n"] >= 2  # the range really was rewritten tile-wise
    ship.attach(replica)
    sched.wal.sync()
    for _ in range(200):
        ship.pump_once()
        if replica.published_horizon() == sched._tick:
            break
    assert ship.compact_reanchors >= 1
    h, got = replica.view_at(sink2.name)
    assert h == sched._tick and got == live_view(sched, sink)
    sched.close()
    replica.close()
