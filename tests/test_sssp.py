"""Incremental SSSP: the min-plus fixpoint workload (workloads/sssp.py).

Exercises retraction-capable device min/max inside the on-device
fixpoint: distance improvements retract the previously-emitted best
through the loop, and edge deletions retract relaxation candidates.
"""

import numpy as np
import pytest

from reflow_tpu import DirtyScheduler
from reflow_tpu.executors import CpuExecutor, get_executor
from reflow_tpu.workloads import sssp

N = 48


def random_graph(rng, n_edges=160):
    src = rng.integers(0, N, n_edges)
    dst = rng.integers(0, N, n_edges)
    w = rng.integers(1, 10, n_edges).astype(np.float32)
    return src, dst, w


def drive(executor, src, dst, w, extra_ticks=()):
    sg = sssp.build_graph(N)
    sched = DirtyScheduler(sg.graph, executor,
                           max_loop_iters=sssp.max_loop_iters(N))
    sched.push(sg.seeds, sssp.seed_batch(0))
    sched.push(sg.edges, sssp.edge_batch(src, dst, w))
    r = sched.tick()
    assert r.quiesced
    for batch in extra_ticks:
        sched.push(sg.edges, batch)
        r = sched.tick()
        assert r.quiesced
    return sched.read_table(sg.best)


def as_dict(table):
    return {int(k): float(np.asarray(v).reshape(())) for k, v in
            table.items()}


def test_cpu_matches_bellman_ford():
    rng = np.random.default_rng(3)
    src, dst, w = random_graph(rng)
    got = as_dict(drive(CpuExecutor(), src, dst, w))
    ref = sssp.reference_distances(N, src, dst, w, 0)
    assert got == ref


@pytest.mark.parametrize("executor", ["tpu", "sharded"])
def test_device_matches_cpu_including_churn(executor):
    """Cold build + an edge-deletion tick + an edge-insertion tick: the
    deletion retracts relaxation candidates (device min-Reduce must
    survive them within its candidate buffer) and distances can both
    grow (deletion) and shrink (insertion)."""
    rng = np.random.default_rng(7)
    src, dst, w = random_graph(rng)
    # delete 12 random edges, then add 12 fresh ones
    ix = rng.choice(len(src), 12, replace=False)
    delete = sssp.edge_batch(src[ix], dst[ix], w[ix], weight=-1)
    ns = rng.integers(0, N, 12)
    nd = rng.integers(0, N, 12)
    nw = rng.integers(1, 10, 12).astype(np.float32)
    insert = sssp.edge_batch(ns, nd, nw)

    views = {}
    for name in ("cpu", executor):
        if name == "cpu":
            ex = CpuExecutor()
        elif name == "sharded":
            from reflow_tpu.parallel import make_mesh
            from reflow_tpu.parallel.shard import ShardedTpuExecutor
            ex = ShardedTpuExecutor(make_mesh(8))
        else:
            ex = get_executor(name)
        views[name] = as_dict(drive(ex, src, dst, w,
                                    extra_ticks=(delete, insert)))
    assert views[executor] == views["cpu"]

    # and the final state equals a from-scratch oracle on the final graph
    keep = np.setdiff1d(np.arange(len(src)), ix)
    fs = np.concatenate([src[keep], ns])
    fd = np.concatenate([dst[keep], nd])
    fw = np.concatenate([w[keep], nw])
    ref = sssp.reference_distances(N, fs, fd, fw, 0)
    assert views["cpu"] == ref


def test_incremental_tick_is_cheaper_than_rebuild():
    """The deletion tick must touch far fewer rows than the cold build
    (the incremental-vs-full property on the min-plus loop)."""
    rng = np.random.default_rng(11)
    src, dst, w = random_graph(rng, n_edges=200)
    sg = sssp.build_graph(N)
    sched = DirtyScheduler(sg.graph, get_executor("tpu"))
    sched.push(sg.seeds, sssp.seed_batch(0))
    sched.push(sg.edges, sssp.edge_batch(src, dst, w))
    cold = sched.tick()
    # delete one non-tree-critical edge
    sched.push(sg.edges, sssp.edge_batch(src[:1], dst[:1], w[:1],
                                         weight=-1))
    warm = sched.tick()
    assert warm.quiesced
    assert warm.delta_ops < cold.delta_ops / 2


def test_orphaned_cycle_detected_and_rebuilt():
    """Deleting the only edge into a cycle leaves its nodes sustaining
    each other's distances — the loop cannot quiesce (the incremental-
    SSSP invalidation problem). With max_loop_iters = n_nodes + 2 the
    divergence is DETECTED (quiesced=False) instead of trusted, and the
    documented fallback — rebuild from scratch over the surviving edges
    — restores the oracle answer."""
    src = np.array([0, 1, 2])
    dst = np.array([1, 2, 1])
    w = np.ones(3, np.float32)
    sg = sssp.build_graph(N)
    sched = DirtyScheduler(sg.graph, CpuExecutor(),
                           max_loop_iters=sssp.max_loop_iters(N))
    sched.push(sg.seeds, sssp.seed_batch(0))
    sched.push(sg.edges, sssp.edge_batch(src, dst, w))
    assert sched.tick().quiesced
    assert as_dict(sched.read_table(sg.best)) == {0: 0.0, 1: 1.0, 2: 2.0}

    # retract 0->1: nodes 1 and 2 become unreachable but feed each other
    sched.push(sg.edges, sssp.edge_batch(src[:1], dst[:1], w[:1],
                                         weight=-1))
    r = sched.tick()
    assert not r.quiesced            # detected, not silently wrong

    # fallback: from-scratch rebuild over the surviving edge set
    sg2 = sssp.build_graph(N)
    sched2 = DirtyScheduler(sg2.graph, CpuExecutor(),
                            max_loop_iters=sssp.max_loop_iters(N))
    sched2.push(sg2.seeds, sssp.seed_batch(0))
    sched2.push(sg2.edges, sssp.edge_batch(src[1:], dst[1:], w[1:]))
    assert sched2.tick().quiesced
    got = as_dict(sched2.read_table(sg2.best))
    assert got == sssp.reference_distances(N, src[1:], dst[1:], w[1:], 0)
    assert got == {0: 0.0}           # 1 and 2 correctly unreachable


# -- in-place deletion repair (VERDICT r4 #7) ------------------------------

@pytest.mark.parametrize("executor", ["cpu", "tpu"])
def test_orphaned_cycle_repaired_in_place(executor):
    """The orphaned-cycle divergence is repaired WITHOUT a fresh
    scheduler: a max_loop_iters halt now PAUSES (in-flight loop deltas
    re-enter as pending), and sssp.repair retracts/re-inserts the
    affected set's surviving in-edges — the retraction wave shrinks
    monotonically, so it quiesces even from the paused divergent state,
    and the re-insertion re-derives from valid boundary distances."""
    src = np.array([0, 1, 2])
    dst = np.array([1, 2, 1])
    w = np.ones(3, np.float32)
    sg = sssp.build_graph(N)
    ex = CpuExecutor() if executor == "cpu" else get_executor("tpu")
    sched = DirtyScheduler(sg.graph, ex,
                           max_loop_iters=sssp.max_loop_iters(N))
    sched.push(sg.seeds, sssp.seed_batch(0))
    sched.push(sg.edges, sssp.edge_batch(src, dst, w))
    assert sched.tick().quiesced
    dist_prev = as_dict(sched.read_table(sg.best))

    # retract 0->1: nodes 1 and 2 orphan into a sustaining cycle
    sched.push(sg.edges, sssp.edge_batch(src[:1], dst[:1], w[:1],
                                         weight=-1))
    assert not sched.tick().quiesced      # divergence detected (paused)

    surv_s, surv_d, surv_w = src[1:], dst[1:], w[1:]
    aff = sssp.affected_set(N, surv_s, surv_d, surv_w, dist_prev,
                            src[:1], dst[:1], w[:1])
    assert aff == {1, 2}
    r1, r2 = sssp.repair(sched, sg, surv_s, surv_d, surv_w, aff)
    assert r1.quiesced and r2.quiesced
    got = as_dict(sched.read_table(sg.best))
    assert got == sssp.reference_distances(N, surv_s, surv_d, surv_w, 0)
    assert got == {0: 0.0}                # 1, 2 correctly unreachable


def test_tree_edge_deletion_repair_is_incremental():
    """A tree-edge deletion that strands a sub-cycle on a LARGER graph:
    the repair touches the affected region only (delta-ops far below the
    cold build) and lands on the from-scratch oracle, same scheduler."""
    rng = np.random.default_rng(5)
    # dense reachable region on keys {0} ∪ [8, N): a spanning star from
    # the seed plus random internal edges (big cold cascade), all
    # DISJOINT from the fragile chain so its repair can't touch them
    star_d = np.arange(8, N)
    n_base = 200
    bsrc = np.where(rng.random(n_base) < 0.2, 0,
                    rng.integers(8, N, n_base))
    bdst = rng.integers(8, N, n_base)
    src = np.concatenate([np.zeros(len(star_d), np.int64), bsrc,
                          # chain 0 -> 1 -> 2 -> 3 -> 1 cycle, 3 -> 4 -> 5
                          [0, 1, 2, 3, 3, 4]])
    dst = np.concatenate([star_d, bdst, [1, 2, 3, 1, 4, 5]])
    w = np.concatenate([rng.integers(1, 10, len(star_d) + n_base),
                        np.ones(6)]).astype(np.float32)

    sg = sssp.build_graph(N)
    sched = DirtyScheduler(sg.graph, get_executor("tpu"),
                           max_loop_iters=sssp.max_loop_iters(N))
    sched.push(sg.seeds, sssp.seed_batch(0))
    sched.push(sg.edges, sssp.edge_batch(src, dst, w))
    cold = sched.tick()
    assert cold.quiesced
    dist_prev = as_dict(sched.read_table(sg.best))

    # delete 0->1: the cycle {1,2,3} + tail {4,5} orphan together
    del_ix = len(src) - 6
    sched.push(sg.edges, sssp.edge_batch(src[del_ix:del_ix + 1],
                                         dst[del_ix:del_ix + 1],
                                         w[del_ix:del_ix + 1], weight=-1))
    halted = sched.tick()
    assert not halted.quiesced

    keep = np.r_[0:del_ix, del_ix + 1:len(src)]
    aff = sssp.affected_set(N, src[keep], dst[keep], w[keep], dist_prev,
                            src[del_ix:del_ix + 1],
                            dst[del_ix:del_ix + 1], w[del_ix:del_ix + 1])
    assert {1, 2, 3} <= aff
    r1, r2 = sssp.repair(sched, sg, src[keep], dst[keep], w[keep], aff)
    assert r1.quiesced and r2.quiesced
    # the halted tick stashed a device-resident carry, so delta counts
    # may still be lazy — block() forces them
    repair_ops = r1.block().delta_ops + r2.block().delta_ops
    assert repair_ops < cold.block().delta_ops / 2, (repair_ops,
                                                     cold.delta_ops)
    got = as_dict(sched.read_table(sg.best))
    ref = sssp.reference_distances(N, src[keep], dst[keep], w[keep], 0)
    assert got == ref


def test_paused_iteration_resumes_exactly():
    """A tick halted at max_loop_iters no longer drops in-flight loop
    deltas: re-ticking with a raised budget finishes the SAME fixpoint a
    single big-budget tick reaches (pause/resume is lossless)."""
    rng = np.random.default_rng(9)
    src, dst, w = random_graph(rng, n_edges=200)

    def run(budget_first):
        sg = sssp.build_graph(N)
        sched = DirtyScheduler(sg.graph, get_executor("tpu"),
                               max_loop_iters=budget_first)
        sched.push(sg.seeds, sssp.seed_batch(0))
        sched.push(sg.edges, sssp.edge_batch(src, dst, w))
        r = sched.tick()
        sched.max_loop_iters = sssp.max_loop_iters(N)
        while not r.quiesced:
            r = sched.tick()
        return as_dict(sched.read_table(sg.best))

    assert run(3) == run(sssp.max_loop_iters(N))
