"""Image-embed ETL (config 5): ViT Map + incremental groupby-mean on all
three executors, including sharded data-parallel inference."""

import numpy as np
import pytest

from reflow_tpu import DirtyScheduler
from reflow_tpu.executors import CpuExecutor, get_executor
from reflow_tpu.models import VIT_TINY, init_vit
from reflow_tpu.parallel import make_mesh
from reflow_tpu.parallel.shard import ShardedTpuExecutor
from reflow_tpu.workloads import image_embed

N_IMG, N_GRP = 64, 8


@pytest.fixture(scope="module")
def params():
    return init_vit(0, **VIT_TINY)


def _drive(executor, params):
    ig = image_embed.build_graph(N_IMG, N_GRP, params)
    sched = DirtyScheduler(ig.graph, executor)
    stream = image_embed.ImageStream(params, seed=4)
    rng = np.random.default_rng(9)
    ids = np.arange(24)
    sched.push(ig.images, stream.insert(ids, rng.integers(0, N_GRP, 24)))
    sched.tick()
    # second batch + a group move + a delete, all in one tick
    from reflow_tpu.delta import DeltaBatch

    batch = DeltaBatch.concat([
        stream.insert(np.arange(24, 40), rng.integers(0, N_GRP, 16)),
        stream.move(3, (stream.groups[3] + 1) % N_GRP),
        stream.delete(7),
    ])
    sched.push(ig.images, batch)
    sched.tick()
    return sched, ig, stream


def _check(sched, ig, stream, atol=2e-3):
    got = sched.read_table(ig.centroids)
    ref = stream.reference_centroids()
    assert set(int(k) for k in got) == set(ref)
    for grp, cent in ref.items():
        np.testing.assert_allclose(
            np.asarray(got[grp], np.float64), cent, atol=atol)


def test_cpu_executor_matches_oracle(params):
    _check(*_drive(CpuExecutor(), params))


def test_tpu_executor_matches_oracle(params):
    _check(*_drive(get_executor("tpu"), params))


def test_sharded_dataparallel_matches_oracle(params):
    _check(*_drive(ShardedTpuExecutor(make_mesh(8)), params))


def test_vit_b_config_builds():
    """ViT-B/16 parameters materialize with the right feature dim."""
    from reflow_tpu.models import VIT_B_16, init_vit as iv

    p = iv(1, **{**VIT_B_16, "depth": 1})  # one block: keep CI light
    assert p["proj_w"].shape == (16 * 16 * 3, 768)
    assert p["blocks"][0]["w1"].shape == (768, 3072)
