"""Image-embed ETL (config 5): ViT Map + incremental groupby-mean on all
three executors, including sharded data-parallel inference."""

import numpy as np
import pytest

from reflow_tpu import DirtyScheduler
from reflow_tpu.executors import CpuExecutor, get_executor
from reflow_tpu.models import VIT_TINY, init_vit
from reflow_tpu.parallel import make_mesh
from reflow_tpu.parallel.shard import ShardedTpuExecutor
from reflow_tpu.workloads import image_embed

N_IMG, N_GRP = 64, 8


@pytest.fixture(scope="module")
def params():
    return init_vit(0, **VIT_TINY)


def _drive(executor, params):
    ig = image_embed.build_graph(N_IMG, N_GRP, params)
    sched = DirtyScheduler(ig.graph, executor)
    stream = image_embed.ImageStream(params, seed=4)
    rng = np.random.default_rng(9)
    ids = np.arange(24)
    sched.push(ig.images, stream.insert(ids, rng.integers(0, N_GRP, 24)))
    sched.tick()
    # second batch + a group move + a delete, all in one tick
    from reflow_tpu.delta import DeltaBatch

    batch = DeltaBatch.concat([
        stream.insert(np.arange(24, 40), rng.integers(0, N_GRP, 16)),
        stream.move(3, (stream.groups[3] + 1) % N_GRP),
        stream.delete(7),
    ])
    sched.push(ig.images, batch)
    sched.tick()
    return sched, ig, stream


def _check(sched, ig, stream, atol=2e-3):
    got = sched.read_table(ig.centroids)
    ref = stream.reference_centroids()
    assert set(int(k) for k in got) == set(ref)
    for grp, cent in ref.items():
        np.testing.assert_allclose(
            np.asarray(got[grp], np.float64), cent, atol=atol)


def test_cpu_executor_matches_oracle(params):
    _check(*_drive(CpuExecutor(), params))


def test_tpu_executor_matches_oracle(params):
    _check(*_drive(get_executor("tpu"), params))


def test_sharded_dataparallel_matches_oracle(params):
    _check(*_drive(ShardedTpuExecutor(make_mesh(8)), params))


def test_vit_b_config_builds():
    """ViT-B/16 parameters materialize with the right feature dim."""
    from reflow_tpu.models import VIT_B_16, init_vit as iv

    p = iv(1, **{**VIT_B_16, "depth": 1})  # one block: keep CI light
    assert p["proj_w"].shape == (16 * 16 * 3, 768)
    assert p["blocks"][0]["w1"].shape == (768, 3072)


def test_compiled_program_embeds_no_params():
    """VERDICT r2 #2: ViT weights must enter the tick program as
    ARGUMENTS, not traced constants — the lowered HLO's size must not
    scale with the model size."""
    import jax

    from reflow_tpu.executors.fixpoint import _abstract_delta
    from reflow_tpu.executors.tpu import TpuExecutor

    big = dict(VIT_TINY, dim=256, mlp_dim=1024)  # ~64x the parameters

    def hlo_len(cfg):
        p = init_vit(0, **cfg)
        ig = image_embed.build_graph(N_IMG, N_GRP, p)
        ig.graph.validate()
        ex = TpuExecutor()
        ex.bind(ig.graph)
        fn = jax.jit(ex.build_pass_fn(list(ig.graph.nodes)))
        states_abs = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), ex.states)
        ingress = {ig.images.id: _abstract_delta(ig.images.spec, 64)}
        return len(fn.lower(states_abs, ingress).as_text())

    tiny, bigger = hlo_len(VIT_TINY), hlo_len(big)
    assert bigger < 1.5 * tiny, (
        f"HLO grew {bigger / tiny:.1f}x with a 64x model: params are being "
        f"traced as constants")


def test_update_params_swaps_without_recompile(params):
    """Params are arguments: swapping them changes results on the next
    tick and compiles nothing new."""
    ig = image_embed.build_graph(N_IMG, N_GRP, params)
    ex = get_executor("tpu")
    sched = DirtyScheduler(ig.graph, ex)
    stream = image_embed.ImageStream(params, seed=4)
    sched.push(ig.images, stream.insert(np.arange(8), np.zeros(8, int)))
    sched.tick()
    before = dict(sched.read_table(ig.centroids))
    n_programs = len(ex._cache)

    params2 = init_vit(1, **VIT_TINY)  # different weights, same shapes
    embed_node = ig.graph.nodes[1]
    assert embed_node.name == "embed"
    ex.update_params(embed_node, {k: v for k, v in params2.items()
                                  if k != "_cfg"})
    # replay the same rows so the centroid recomputes under new weights
    batch = stream.insert(np.arange(8, 16), np.zeros(8, int))
    sched.push(ig.images, batch)
    sched.tick()
    after = dict(sched.read_table(ig.centroids))
    assert len(ex._cache) == n_programs, "param swap forced a recompile"
    assert not np.allclose(np.asarray(after[0]), np.asarray(before[0]))


def test_tensor_parallel_vit_matches_oracle(params):
    """VERDICT r4 #8: the 2-D (delta, model) mesh — ViT-TINY params
    sharded tensor-parallel over a 4-way model axis (vit_param_specs /
    vit_forward_tp: column-sharded QKV+MLP-in, row-sharded attn-out +
    MLP-out with one psum each) while deltas stay row-sharded on the
    2-way delta axis. Centroids must match the host oracle like every
    other executor, and each device must hold only its 1/4 slice of the
    sharded weight matrices."""
    from reflow_tpu.parallel.mesh import make_model_mesh

    mesh = make_model_mesh(2, 4)
    ex = ShardedTpuExecutor(mesh, model_axis="model")
    assert ex.axis == "delta" and ex.n == 2

    ig = image_embed.build_graph(N_IMG, N_GRP, params, model_axis="model")
    sched = DirtyScheduler(ig.graph, ex)
    stream = image_embed.ImageStream(params, seed=4)
    rng = np.random.default_rng(9)
    ids = np.arange(24)
    sched.push(ig.images, stream.insert(ids, rng.integers(0, N_GRP, 24)))
    sched.tick()
    from reflow_tpu.delta import DeltaBatch

    batch = DeltaBatch.concat([
        stream.insert(np.arange(24, 40), rng.integers(0, N_GRP, 16)),
        stream.move(3, (stream.groups[3] + 1) % N_GRP),
        stream.delete(7),
    ])
    sched.push(ig.images, batch)
    sched.tick()
    _check(sched, ig, stream)

    # param bytes per device: sharded matrices hold 1/4 slices
    embed_node = next(n for n in ig.graph.nodes if n.name == "embed")
    wq = ex.states[embed_node.id]["params"]["blocks"][0]["wq"]
    dim = VIT_TINY["dim"]
    assert wq.shape == (dim, dim)                      # global shape
    local = wq.addressable_shards[0].data
    assert local.shape == (dim, dim // 4), local.shape  # 1/4 per device

    # update_params re-shards (not replicates) under param_specs
    ex.update_params(embed_node, {k: v for k, v in params.items()
                                  if k != "_cfg"})
    wq2 = ex.states[embed_node.id]["params"]["blocks"][0]["wq"]
    assert wq2.addressable_shards[0].data.shape == (dim, dim // 4)


def test_tensor_parallel_rejects_nondivisible_heads():
    """A model axis that doesn't divide the head count must fail LOUDLY
    at trace time — heads=4 over m=8 would otherwise silently fuse
    fractional heads (every pure-shape check passes)."""
    import pytest

    from reflow_tpu.parallel.mesh import make_model_mesh

    mesh = make_model_mesh(1, 8)          # m=8; VIT_TINY heads=4
    ex = ShardedTpuExecutor(mesh, model_axis="model")
    p = init_vit(0, **VIT_TINY)
    p["_cfg"] = VIT_TINY
    ig = image_embed.build_graph(N_IMG, N_GRP, p, model_axis="model")
    sched = DirtyScheduler(ig.graph, ex)
    stream = image_embed.ImageStream(p, seed=4)
    sched.push(ig.images, stream.insert(np.arange(8), np.zeros(8, int)))
    with pytest.raises(ValueError, match="must divide heads"):
        sched.tick()
