"""Topo-partitioned (staged) execution: per-stage programs on separate
devices with seeded-ingress handoff, differential vs unpartitioned
(SURVEY.md §2 parallelism checklist — the pp analog)."""

import numpy as np
import pytest

from reflow_tpu import DeltaBatch, DirtyScheduler, FlowGraph, Spec
from reflow_tpu.executors import CpuExecutor
from reflow_tpu.executors.tpu import TpuExecutor
from reflow_tpu.graph import GraphError
from reflow_tpu.parallel.topo import StagedTpuExecutor

K = 64


def _two_stage_graph():
    """Stage 0: map+reduce; stage 1: join against a second source."""
    spec = Spec((), np.float32, key_space=K)
    g = FlowGraph("staged")
    src = g.source("src", spec)
    doubled = g.map(src, lambda v: 2.0 * v, vectorized=True, name="x2")
    totals = g.reduce(doubled, "sum", name="totals")
    rsrc = g.source("right", spec)
    j = g.join(totals, rsrc, merge=lambda k, a, b: a + b, spec=spec,
               name="j", arena_capacity=1 << 10)
    g.sink(j, "out")
    for node in (doubled, totals):
        node.stage = 0
    j.stage = 1
    return g, src, rsrc


def _drive(sched, src, rsrc):
    rng = np.random.default_rng(3)
    views = []
    for t in range(3):
        n = 40 + 10 * t
        sched.push(src, DeltaBatch(rng.integers(0, K, n),
                                   rng.integers(1, 9, n).astype(np.float32),
                                   np.where(rng.random(n) < 0.2, -1, 1)))
        kb = rng.integers(0, K, 16)
        sched.push(rsrc, DeltaBatch(kb, np.ones(16, np.float32),
                                    np.ones(16, np.int64)))
        sched.tick()
        views.append({(int(k), float(v)): int(w)
                      for (k, v), w in sched.view("out").items()})
    return views


def test_staged_matches_unpartitioned_and_cpu():
    import jax

    outs = {}
    for name, ex in (("staged", StagedTpuExecutor()),
                     ("tpu", TpuExecutor()), ("cpu", CpuExecutor())):
        g, src, rsrc = _two_stage_graph()
        outs[name] = _drive(DirtyScheduler(g, ex), src, rsrc)
    assert outs["staged"] == outs["tpu"] == outs["cpu"]


def test_staged_states_live_on_stage_devices():
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")
    g, src, rsrc = _two_stage_graph()
    ex = StagedTpuExecutor()
    sched = DirtyScheduler(g, ex)
    _drive(sched, src, rsrc)
    totals = next(n for n in g.nodes if n.name == "totals")
    j = next(n for n in g.nodes if n.name == "j")
    dev_of = lambda st: next(iter(
        jax.tree.leaves(st)[0].devices()))
    assert dev_of(ex.states[totals.id]) == jax.devices()[0]
    assert dev_of(ex.states[j.id]) == jax.devices()[1]
    assert dev_of(ex.states[totals.id]) != dev_of(ex.states[j.id])


def test_staged_rejects_backwards_stage_edge():
    spec = Spec((), np.float32, key_space=K)
    g = FlowGraph("bad")
    src = g.source("s", spec)
    a = g.map(src, lambda v: v, vectorized=True, name="a")
    r = g.reduce(a, "sum", name="r")
    g.sink(r, "out")
    a.stage = 1
    r.stage = 0   # consumes stage-1 output in stage 0: backwards
    with pytest.raises(GraphError, match="backwards in stages"):
        DirtyScheduler(g, StagedTpuExecutor())


def test_staged_overhead_is_bounded():
    """VERDICT r4 weak #4: the staged executor's pipelining cannot win on
    THIS runtime (the virtual CPU platform executes device programs
    serially across devices — measured 2.3x serial ratio in
    tools/staged_pipeline_probe.py), so the honest asserted property is
    the other half of the claim: splitting a compute-bound two-stage
    graph across 2 devices costs at most a bounded handoff overhead vs
    the same staged code path on 1 device (measured 0.95-1.04x)."""
    import time

    import jax
    import jax.numpy as jnp

    K, D, ROWS, TICKS, CHAIN = 64, 256, 128, 6, 4

    def heavy(p, v):
        for _ in range(CHAIN):
            v = jnp.tanh(v @ p)
        return v

    def run(n_dev):
        g = FlowGraph("pipe")
        src = g.source("x", Spec((D,), np.float32, key_space=K))
        rng = np.random.default_rng(0)
        W = (rng.standard_normal((D, D)) * 0.05).astype(np.float32)
        m0 = g.map(src, heavy, vectorized=True, params=W, name="m0")
        m1 = g.map(m0, heavy, vectorized=True, params=W.copy(), name="m1")
        gb = g.group_by(m1, key_fn=lambda k, v: k % K, vectorized=True)
        red = g.reduce(gb, "sum", name="agg")
        m0.stage = 0
        for n in (m1, gb, red):
            n.stage = 1
        sched = DirtyScheduler(g, StagedTpuExecutor(
            devices=jax.devices()[:n_dev]))
        rng = np.random.default_rng(7)

        def batch():
            return DeltaBatch(
                np.arange(ROWS) % K,
                rng.standard_normal((ROWS, D)).astype(np.float32),
                np.ones(ROWS, np.int64))

        sched.push(src, batch())
        sched.tick(sync=False)
        _ = sched.read_table(red)      # compile + barrier
        t0 = time.perf_counter()
        for _ in range(TICKS):
            sched.push(src, batch())
            sched.tick(sync=False)
        table = sched.read_table(red)  # barrier
        return time.perf_counter() - t0, table

    w1, t1 = run(1)
    w2, t2 = run(2)
    assert set(t1) == set(t2)
    for k in t1:
        np.testing.assert_allclose(t1[k], t2[k], rtol=1e-5)
    # generous bound: CI machines are noisy; the point is "no pathology"
    assert w2 < 2.0 * w1 + 0.25, (w1, w2)
