"""Topo-partitioned (staged) execution: per-stage programs on separate
devices with seeded-ingress handoff, differential vs unpartitioned
(SURVEY.md §2 parallelism checklist — the pp analog)."""

import numpy as np
import pytest

from reflow_tpu import DeltaBatch, DirtyScheduler, FlowGraph, Spec
from reflow_tpu.executors import CpuExecutor
from reflow_tpu.executors.tpu import TpuExecutor
from reflow_tpu.graph import GraphError
from reflow_tpu.parallel.topo import StagedTpuExecutor

K = 64


def _two_stage_graph():
    """Stage 0: map+reduce; stage 1: join against a second source."""
    spec = Spec((), np.float32, key_space=K)
    g = FlowGraph("staged")
    src = g.source("src", spec)
    doubled = g.map(src, lambda v: 2.0 * v, vectorized=True, name="x2")
    totals = g.reduce(doubled, "sum", name="totals")
    rsrc = g.source("right", spec)
    j = g.join(totals, rsrc, merge=lambda k, a, b: a + b, spec=spec,
               name="j", arena_capacity=1 << 10)
    g.sink(j, "out")
    for node in (doubled, totals):
        node.stage = 0
    j.stage = 1
    return g, src, rsrc


def _drive(sched, src, rsrc):
    rng = np.random.default_rng(3)
    views = []
    for t in range(3):
        n = 40 + 10 * t
        sched.push(src, DeltaBatch(rng.integers(0, K, n),
                                   rng.integers(1, 9, n).astype(np.float32),
                                   np.where(rng.random(n) < 0.2, -1, 1)))
        kb = rng.integers(0, K, 16)
        sched.push(rsrc, DeltaBatch(kb, np.ones(16, np.float32),
                                    np.ones(16, np.int64)))
        sched.tick()
        views.append({(int(k), float(v)): int(w)
                      for (k, v), w in sched.view("out").items()})
    return views


def test_staged_matches_unpartitioned_and_cpu():
    import jax

    outs = {}
    for name, ex in (("staged", StagedTpuExecutor()),
                     ("tpu", TpuExecutor()), ("cpu", CpuExecutor())):
        g, src, rsrc = _two_stage_graph()
        outs[name] = _drive(DirtyScheduler(g, ex), src, rsrc)
    assert outs["staged"] == outs["tpu"] == outs["cpu"]


def test_staged_states_live_on_stage_devices():
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")
    g, src, rsrc = _two_stage_graph()
    ex = StagedTpuExecutor()
    sched = DirtyScheduler(g, ex)
    _drive(sched, src, rsrc)
    totals = next(n for n in g.nodes if n.name == "totals")
    j = next(n for n in g.nodes if n.name == "j")
    dev_of = lambda st: next(iter(
        jax.tree.leaves(st)[0].devices()))
    assert dev_of(ex.states[totals.id]) == jax.devices()[0]
    assert dev_of(ex.states[j.id]) == jax.devices()[1]
    assert dev_of(ex.states[totals.id]) != dev_of(ex.states[j.id])


def test_staged_rejects_backwards_stage_edge():
    spec = Spec((), np.float32, key_space=K)
    g = FlowGraph("bad")
    src = g.source("s", spec)
    a = g.map(src, lambda v: v, vectorized=True, name="a")
    r = g.reduce(a, "sum", name="r")
    g.sink(r, "out")
    a.stage = 1
    r.stage = 0   # consumes stage-1 output in stage 0: backwards
    with pytest.raises(GraphError, match="backwards in stages"):
        DirtyScheduler(g, StagedTpuExecutor())
