"""Observability tests (``reflow_tpu.obs`` + the inspect CLIs).

The contract under test: (a) tracing is a strict no-op while disabled
and a correct decomposition while enabled — every sampled ticket's six
stage durations tile its measured end-to-end latency exactly, (b) the
chrome-trace export is valid trace-event JSON with per-component
tracks, (c) the metrics registry is JSON-clean under numpy/deque
values, degrades (never raises) on a failing gauge, and is cleaned up
when the publishing component closes, (d) the shared ``percentile``
helper and the ``to_dict()`` schemas round-trip ``json.dumps``.
"""

from __future__ import annotations

import collections
import importlib.util
import json
import os
import sys
import time
import types

import numpy as np
import pytest

from reflow_tpu import obs
from reflow_tpu.obs import trace as trace_mod
from reflow_tpu.scheduler import DirtyScheduler
from reflow_tpu.serve import (CoalesceWindow, GraphConfig, IngestFrontend,
                              ServeTier)
from reflow_tpu.utils.metrics import (percentile, profile_trace,
                                      summarize_serve, summarize_tier,
                                      summarize_wal)
from reflow_tpu.wal import DurableScheduler
from reflow_tpu.workloads import wordcount

WINDOW = CoalesceWindow(max_rows=256, max_ticks=8, max_latency_s=0.002)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def traced(monkeypatch):
    """Tracing on, every ticket sampled; rings cleared before/after."""
    obs.disable()
    trace_mod.reset()
    monkeypatch.setattr(trace_mod, "SAMPLE_EVERY", 1)
    obs.enable()
    yield
    obs.disable()
    trace_mod.reset()


def lines(*words):
    return wordcount.ingest_lines([" ".join(words)])


def drive_frontend(sched_factory, n=12):
    g, src, _sink = wordcount.build_graph()
    sched = sched_factory(g)
    fe = IngestFrontend(sched, window=WINDOW)
    tickets = [fe.submit(src, lines(f"w{j}", f"w{j % 3}"))
               for j in range(n)]
    for t in tickets:
        assert t.result(timeout=10).applied
    fe.close()
    return fe, sched


# -- tracing disabled: strict no-op -----------------------------------------

def test_disabled_records_nothing():
    obs.disable()
    trace_mod.reset()
    drive_frontend(DirtyScheduler)
    assert obs.chrome_events() == []
    assert not obs.enabled()


def test_mint_not_called_when_disabled():
    obs.disable()
    trace_mod.reset()
    fe, _ = drive_frontend(DirtyScheduler, n=3)
    # no TraceCtx was attached to any ticket on the disabled path
    assert trace_mod.evt("x", 0.0, 1.0) is None  # evt is a no-op too
    assert obs.chrome_events() == []


# -- ticket stage decomposition ---------------------------------------------

def test_ticket_stages_tile_e2e_exactly(tmp_path, traced):
    drive_frontend(
        lambda g: DurableScheduler(g, wal_dir=str(tmp_path / "wal"),
                                   fsync="record"))
    events = obs.chrome_events()
    timelines = obs.ticket_timelines(events)
    assert timelines, "sampling every ticket must yield timelines"
    for tl in timelines.values():
        assert set(tl["stages"]) == set(trace_mod.STAGES)
        assert all(d >= 0.0 for d in tl["stages"].values())
        # the six stages tile [t0, t_res]: sum == e2e (float roundoff
        # only — far inside the 10% acceptance bound)
        assert tl["sum_us"] == pytest.approx(tl["e2e_us"], rel=1e-6,
                                             abs=0.01)
    # a durable run must attribute real WAL time somewhere
    assert sum(tl["stages"]["fsync"] for tl in timelines.values()) >= 0.0


def test_export_tracks_and_event_shape(tmp_path, traced):
    drive_frontend(
        lambda g: DurableScheduler(g, wal_dir=str(tmp_path / "wal"),
                                   fsync="record"))
    path = str(tmp_path / "trace.json")
    assert obs.export_chrome_trace(path) == path
    doc = json.loads(open(path).read())
    evs = doc["traceEvents"]
    names = {e["name"] for e in evs if e.get("ph") == "M"
             if e["name"] == "thread_name"}
    tracks = {e["args"]["name"] for e in evs
              if e.get("ph") == "M" and e["name"] == "thread_name"}
    assert names == {"thread_name"}
    assert "wal" in tracks
    # the pipelined fsync runs (and is recorded) on the committer's own
    # timeline, not the pump's
    assert "wal-committer" in tracks
    assert any(t.startswith("ticket/") for t in tracks)
    for e in evs:
        if e.get("ph") == "X":
            assert e["ts"] >= 0 and e["dur"] >= 0 and e["tid"] >= 1
    # WAL spans recorded on the pump thread
    spans = {e["name"] for e in evs if e.get("ph") == "X"}
    assert {"wal_append", "wal_fsync", "submit", "window"} <= spans


def test_tier_records_pool_pick_and_sched_delay(traced):
    tier = ServeTier(max_bytes=8 << 20, pump_threads=2)
    g, src, _sink = wordcount.build_graph()
    h = tier.register("g0", DirtyScheduler(g), GraphConfig(window=WINDOW))
    tickets = [h.submit(src, lines(f"w{j}")) for j in range(8)]
    for t in tickets:
        assert t.result(timeout=10).applied
    tier.close()
    spans = {e["name"] for e in obs.chrome_events()
             if e.get("ph") == "X"}
    assert "pool_pick" in spans
    timelines = obs.ticket_timelines(obs.chrome_events())
    assert timelines
    for tl in timelines.values():
        assert tl["sum_us"] == pytest.approx(tl["e2e_us"], rel=1e-6,
                                             abs=0.01)


def test_ring_overflow_keeps_newest(monkeypatch):
    obs.disable()
    trace_mod.reset()
    monkeypatch.setattr(trace_mod, "RING_CAPACITY", 8)
    obs.enable()
    try:
        for i in range(50):
            trace_mod.evt(f"e{i}", float(i), 1.0)
        evs = [e for e in obs.chrome_events() if e.get("ph") == "X"]
        assert len(evs) == 8
        # oldest-first within the ring, newest 8 survive
        assert [e["name"] for e in evs] == [f"e{i}" for i in range(42, 50)]
    finally:
        obs.disable()
        trace_mod.reset()


def test_sampling_rate_respected(monkeypatch):
    obs.disable()
    trace_mod.reset()
    monkeypatch.setattr(trace_mod, "SAMPLE_EVERY", 4)
    obs.enable()
    try:
        ctxs = [trace_mod.mint(f"b{i}", time.perf_counter())
                for i in range(16)]
        assert sum(c.sampled for c in ctxs) == 4
    finally:
        obs.disable()
        trace_mod.reset()


# -- metrics registry -------------------------------------------------------

def test_registry_snapshot_is_json_clean():
    reg = obs.MetricsRegistry()
    reg.counter("a").inc()
    reg.counter("a").inc(2)
    reg.gauge("g", lambda: np.float32(1.5))
    reg.gauge("depth").set(np.int64(7))
    reg.register_source("src", lambda: {
        "d": collections.deque([1, 2, 3]),
        "arr": np.arange(3),
        "scalar": np.float64(0.25)})
    snap = reg.snapshot()
    txt = json.dumps(snap)  # must not raise on numpy/deque
    back = json.loads(txt)
    assert back["counters"]["a"] == 3
    assert back["gauges"]["g"] == 1.5
    assert back["gauges"]["depth"] == 7
    assert back["sources"]["src"]["d"] == [1, 2, 3]
    assert back["sources"]["src"]["arr"] == [0, 1, 2]


def test_registry_degrades_on_failing_gauge():
    reg = obs.MetricsRegistry()
    reg.gauge("bad", lambda: 1 / 0)
    reg.register_source("badsrc", lambda: {}[3])
    snap = reg.snapshot()
    assert "error" in str(snap["gauges"]["bad"])
    assert "error" in snap["sources"]["badsrc"]
    json.dumps(snap)


def test_snapshot_emitter_writes_schema_lines(tmp_path):
    reg = obs.MetricsRegistry()
    reg.counter("n").inc(5)
    path = str(tmp_path / "telemetry.jsonl")
    em = obs.SnapshotEmitter(path, interval_s=0.02, registry=reg)
    em.start()
    time.sleep(0.1)
    em.stop()
    rows = [json.loads(ln) for ln in open(path) if ln.strip()]
    assert len(rows) >= 2  # periodic + the final snapshot on stop()
    assert all(r["schema"] == obs.SNAPSHOT_SCHEMA for r in rows)
    assert all(r["counters"]["n"] == 5 for r in rows)
    assert all("ts" in r for r in rows)


def test_snapshot_emitter_fixed_rate_rearm_does_not_drift(tmp_path):
    """The interval-drift regression: re-arming from *now* (fixed
    delay) would push every deadline late by the emit cost; the fix
    re-arms from the previous deadline, so a slow emit shrinks the next
    sleep instead of shifting the cadence."""
    clk = {"t": 0.0}
    em = obs.SnapshotEmitter(str(tmp_path / "t.jsonl"), interval_s=1.0,
                             registry=obs.MetricsRegistry(),
                             clock=lambda: clk["t"])
    em._deadline = 1.0  # as armed at loop entry with the clock at 0
    clk["t"] = 1.4      # the emit burned 0.4s past the deadline
    em._rearm()
    assert em._deadline == pytest.approx(2.0)  # fixed-delay bug: 2.4
    assert em._sleep_s() == pytest.approx(0.6)
    # an emit that overran a whole interval snaps forward — one beat
    # is skipped rather than burst-emitted to catch up
    clk["t"] = 4.3
    em._rearm()
    assert em._deadline == pytest.approx(5.3)
    assert em._sleep_s() == pytest.approx(1.0)


def test_ring_overflow_export_carries_drop_marker(monkeypatch):
    """A wrapped ring has silently overwritten its oldest spans — the
    export must say so (per-track ``dropped_events`` metadata with the
    exact overwrite count) instead of letting readers assume the window
    starts at the first surviving event."""
    obs.disable()
    trace_mod.reset()
    monkeypatch.setattr(trace_mod, "RING_CAPACITY", 8)
    obs.enable()
    try:
        for i in range(13):
            trace_mod.evt(f"e{i}", float(i), 1.0)
        evs = obs.chrome_events()
        drops = [e for e in evs if e.get("ph") == "M"
                 and e["name"] == "dropped_events"]
        assert len(drops) == 1
        assert drops[0]["args"]["count"] == 5  # 13 puts - 8 capacity
        tracks = {e["tid"]: e["args"]["name"] for e in evs
                  if e.get("ph") == "M" and e["name"] == "thread_name"}
        assert drops[0]["args"]["track"] == tracks[drops[0]["tid"]]
    finally:
        obs.disable()
        trace_mod.reset()


def test_unwrapped_ring_has_no_drop_marker(monkeypatch):
    obs.disable()
    trace_mod.reset()
    monkeypatch.setattr(trace_mod, "RING_CAPACITY", 8)
    obs.enable()
    try:
        for i in range(8):  # exactly full: nothing overwritten
            trace_mod.evt(f"e{i}", float(i), 1.0)
        evs = obs.chrome_events()
        assert not any(e.get("name") == "dropped_events" for e in evs)
        assert len([e for e in evs if e.get("ph") == "X"]) == 8
    finally:
        obs.disable()
        trace_mod.reset()


def test_frontend_publish_unregisters_on_close():
    reg = obs.MetricsRegistry()
    g, src, _sink = wordcount.build_graph()
    fe = IngestFrontend(DirtyScheduler(g), window=WINDOW)
    key = fe.publish_metrics(reg)
    t = fe.submit(src, lines("a", "b"))
    assert t.result(timeout=10).applied
    snap = reg.snapshot()
    assert snap["sources"][key]["applied"] == 1
    assert snap["sources"][key]["policy"] == fe.policy
    fe.close()
    assert key not in reg.snapshot()["sources"]


def test_tier_publish_unregisters_on_close():
    reg = obs.MetricsRegistry()
    tier = ServeTier(max_bytes=8 << 20, pump_threads=1)
    g, src, _sink = wordcount.build_graph()
    h = tier.register("g0", DirtyScheduler(g), GraphConfig(window=WINDOW))
    key = tier.publish_metrics(reg)
    assert h.submit(src, lines("x")).result(timeout=10).applied
    snap = reg.snapshot()
    assert snap["sources"][key]["graphs"] == 1
    assert "g0" in snap["sources"][key]["per_graph"]
    assert 0.0 <= snap["gauges"][f"{key}.pump_utilization"] <= 1.0
    tier.close()
    after = reg.snapshot()
    assert key not in after["sources"]
    assert f"{key}.pump_utilization" not in after["gauges"]


def test_scheduler_and_wal_publish(tmp_path):
    reg = obs.MetricsRegistry()
    g, src, _sink = wordcount.build_graph()
    sched = DurableScheduler(g, wal_dir=str(tmp_path / "wal"),
                             fsync="record")
    skey = sched.publish_metrics(reg)
    wkey = sched.wal.publish_metrics(reg)
    sched.push(src, lines("a", "b"))
    sched.tick()
    snap = reg.snapshot()
    assert snap["gauges"][f"{skey}.tick"] == 1
    assert snap["gauges"][f"{skey}.forced_syncs"] == 0
    assert snap["sources"][wkey]["appends"] >= 1
    assert snap["gauges"][f"{wkey}.fsync_rate"] > 0
    json.dumps(snap)
    sched.wal.close()


def test_wal_pipeline_gauges_publish(tmp_path):
    """The committer-pipeline gauges: ``queue_depth`` is the in-memory
    commit backlog, ``durable_lag_s`` the age of the oldest pending
    durability request — both drop to zero once a barrier lands."""
    reg = obs.MetricsRegistry()
    g, src, _sink = wordcount.build_graph()
    sched = DurableScheduler(g, wal_dir=str(tmp_path / "wal"),
                             fsync="tick")
    wkey = sched.wal.publish_metrics(reg)
    sched.push(src, lines("a", "b"))
    sched.tick()
    snap = reg.snapshot()
    assert snap["gauges"][f"{wkey}.queue_depth"] >= 0
    assert snap["gauges"][f"{wkey}.durable_lag_s"] >= 0.0
    sched.wal.sync()  # policy-independent barrier: backlog fully lands
    snap2 = reg.snapshot()
    assert snap2["gauges"][f"{wkey}.queue_depth"] == 0
    assert snap2["gauges"][f"{wkey}.durable_lag_s"] == 0.0
    json.dumps(snap2)
    sched.wal.close()


# -- shared percentile + to_dict round-trips --------------------------------

def test_percentile_empty_and_single():
    assert percentile([], 99) == 0.0
    assert percentile([0.5], 50) == 0.5
    assert percentile([0.5], 99) == 0.5
    assert percentile(collections.deque([1.0, 2.0, 3.0]), 50) == 2.0
    assert isinstance(percentile(np.arange(5), 95), float)


def test_to_dicts_round_trip_json(tmp_path):
    fe, sched = drive_frontend(
        lambda g: DurableScheduler(g, wal_dir=str(tmp_path / "wal"),
                                   fsync="record"), n=6)
    sm = json.loads(json.dumps(summarize_serve(fe).to_dict()))
    assert sm["applied"] == 6
    wm = json.loads(json.dumps(summarize_wal(sched.wal).to_dict()))
    assert wm["appends"] >= 1 and wm["fsync_policy"] == "record"

    tier = ServeTier(max_bytes=8 << 20, pump_threads=1)
    g, src, _sink = wordcount.build_graph()
    h = tier.register("g0", DirtyScheduler(g), GraphConfig(window=WINDOW))
    assert h.submit(src, lines("x")).result(timeout=10).applied
    tm = json.loads(json.dumps(summarize_tier(tier).to_dict()))
    tier.close()
    assert tm["graphs"] == 1 and "g0" in tm["per_graph"]


def test_profile_trace_degrades_without_jax_profiler(tmp_path,
                                                     monkeypatch):
    monkeypatch.setitem(sys.modules, "jax",
                        types.SimpleNamespace())  # no .profiler
    with pytest.warns(RuntimeWarning, match="profile_trace"):
        with profile_trace(str(tmp_path)):
            pass  # block must still run


# -- the inspect CLIs -------------------------------------------------------

def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_trace_inspect_cli(tmp_path, traced, capsys):
    drive_frontend(
        lambda g: DurableScheduler(g, wal_dir=str(tmp_path / "wal"),
                                   fsync="record"))
    path = str(tmp_path / "trace.json")
    obs.export_chrome_trace(path)
    ti = _load_tool("trace_inspect")
    assert ti.main([path, "--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["schema"] == "reflow.trace_inspect/2"
    assert out["trace_files"] == [path]
    assert out["tickets"] > 0
    assert out["decomposition_max_dev_frac"] < 0.10
    assert set(out["critical_path"]) == set(trace_mod.STAGES)
    # default committer="thread": the durability split must see every
    # fsync off the dispatch path
    dur = out["durability"]
    assert dur["offpath_fsyncs"] > 0 and dur["onpath_fsyncs"] == 0
    assert dur["offpath_fsync_frac"] == 1.0
    assert dur["fsync_covered_mean"] >= 1.0
    assert ti.main([path]) == 0  # human mode renders too
    human = capsys.readouterr().out
    assert "critical path:" in human
    assert "off the dispatch path" in human


def test_wal_inspect_json_schema(tmp_path, capsys):
    g, src, _sink = wordcount.build_graph()
    sched = DurableScheduler(g, wal_dir=str(tmp_path / "wal"),
                             fsync="tick")
    for j in range(4):
        sched.push(src, lines(f"w{j}"))
        sched.tick()
    sched.wal.close()
    wi = _load_tool("wal_inspect")
    assert wi.main([str(tmp_path / "wal"), "--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["schema"] == "reflow.wal_inspect/1"
    assert out["records"] == 8 and out["commit_windows"] == 4
    assert out["commit_window_pushes"] == [1, 1, 1, 1]
    seg = out["segments_detail"]
    assert sum(s["records"] for s in seg) == 8
    assert sum(s["bytes"] for s in seg) == out["bytes"]
