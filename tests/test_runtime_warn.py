"""utils/runtime.note_forced_sync: the one-time tunnel-degradation
advisory — warns exactly once per process on a tunnel runtime, never
when JAX is forced to CPU (this suite's conftest does exactly that)."""

import warnings

import pytest

import reflow_tpu.utils.runtime as rt


@pytest.fixture(autouse=True)
def reset_warned():
    """The advisory is once-per-PROCESS state; isolate each case."""
    old = rt._warned
    rt._warned = False
    yield
    rt._warned = old


def test_warns_exactly_once_on_tunnel_runtime(monkeypatch):
    monkeypatch.setattr(rt, "_tunnel_active", lambda: True)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        rt.note_forced_sync("first readback")
        rt.note_forced_sync("second readback")
        rt.note_forced_sync("third readback")
    assert len(caught) == 1, [str(w.message) for w in caught]
    msg = str(caught[0].message)
    assert "first readback" in msg and "tick(sync=False)" in msg


def test_never_warns_when_jax_forced_to_cpu(monkeypatch):
    # the axon plugin can be importable/registered while the backend is
    # forced to CPU (conftest.py) — no degradation happens, no warning
    monkeypatch.setattr(rt, "remote_tunnel_runtime", lambda: True)
    import jax

    assert jax.default_backend() == "cpu"
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        rt.note_forced_sync("cpu readback")
        rt.note_forced_sync("cpu readback again")
    assert caught == []


def test_scheduler_counts_but_advisory_stays_quiet_on_cpu():
    """The forced-sync COUNTER still advances on the CPU oracle path
    (read_table on a cpu executor is not a forced sync at all)."""
    import numpy as np

    from reflow_tpu import DirtyScheduler, FlowGraph
    from reflow_tpu.delta import DeltaBatch, Spec

    g = FlowGraph()
    src = g.source("s", Spec((), np.float32, key_space=8))
    red = g.reduce(src, "sum")
    g.sink(red, "out")
    sched = DirtyScheduler(g)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        sched.push(src, DeltaBatch(np.array([1]),
                                   np.array([2.0], np.float32)))
        sched.tick()
        sched.read_table(red)
    assert sched.forced_syncs == 0  # cpu executor: no forced syncs
    assert caught == []
