"""Streaming TF-IDF (config 2): edit-delta ingestion, incremental tables
vs brute-force oracle, on all three executors."""

from reflow_tpu import DirtyScheduler
from reflow_tpu.executors import CpuExecutor, get_executor
from reflow_tpu.parallel import make_mesh
from reflow_tpu.parallel.shard import ShardedTpuExecutor
from reflow_tpu.workloads import tfidf

DOCS = [
    "the quick brown fox jumps over the lazy dog",
    "the cat sat on the mat",
    "a quick brown cat",
    "dogs and cats living together",
    "the dog chased the cat over the mat",
]


def _drive(executor):
    tg = tfidf.build_graph(n_pairs=256, n_terms=64, n_docs=16)
    sched = DirtyScheduler(tg.graph, executor)
    corpus = tfidf.Corpus(256, 64)
    # initial corpus, one doc per tick (streaming)
    for i, text in enumerate(DOCS[:3]):
        sched.push(tg.tokens, corpus.edit(i, text))
        sched.tick()
    # batch tick with two more docs
    from reflow_tpu.delta import DeltaBatch

    sched.push(tg.tokens, DeltaBatch.concat(
        [corpus.edit(3, DOCS[3]), corpus.edit(4, DOCS[4])]))
    sched.tick()
    # edit an existing doc (retract+insert deltas), delete another
    sched.push(tg.tokens, corpus.edit(1, "the cat sat on a new hat"))
    sched.tick()
    sched.push(tg.tokens, corpus.edit(2, None))
    sched.tick()
    return sched, tg, corpus


def _check(sched, tg, corpus):
    got = tfidf.tfidf_view(sched, tg, corpus)
    ref = corpus.reference_tfidf()
    assert set(got) == set(ref)
    for k in ref:
        assert abs(got[k] - ref[k]) < 1e-5, (k, got[k], ref[k])
    # N table
    (n,) = sched.read_table(tg.ndocs).values()
    assert int(n) == len(corpus.docs)


def test_cpu_matches_oracle():
    _check(*_drive(CpuExecutor()))


def test_tpu_matches_oracle():
    _check(*_drive(get_executor("tpu")))


def test_sharded_matches_oracle():
    _check(*_drive(ShardedTpuExecutor(make_mesh(8))))


def test_cpu_tpu_tables_identical():
    s1, tg1, _ = _drive(CpuExecutor())
    s2, tg2, _ = _drive(get_executor("tpu"))
    for node1, node2 in ((tg1.tf, tg2.tf), (tg1.df, tg2.df)):
        t1 = {int(k): float(v) for k, v in s1.read_table(node1).items()}
        t2 = {int(k): float(v) for k, v in s2.read_table(node2).items()}
        assert t1 == t2


def test_large_vocab_term_ids_exact():
    """VERDICT r2 item 9: real vocabularies (~10^6 terms) must be exact.
    Term ids far beyond the old 2**14 bound survive the radix-split
    presence path bit-exactly."""
    import numpy as np

    from reflow_tpu.delta import DeltaBatch

    n_terms = 1 << 20
    terms = [937_211, 16_384, (1 << 20) - 1, 12]
    tg = tfidf.build_graph(n_pairs=64, n_terms=n_terms, n_docs=8)
    sched = DirtyScheduler(tg.graph, get_executor("tpu"))
    rows = [(0, terms[0], 3), (1, terms[0], 1), (1, terms[1], 2),
            (0, terms[2], 1), (1, terms[3], 5)]  # (doc, term, count)
    keys = np.arange(len(rows))
    vals = np.array([[t, d] for d, t, _ in rows], np.float32)
    w = np.array([c for *_, c in rows], np.int64)
    sched.push(tg.tokens, DeltaBatch(keys, vals, w))
    sched.tick()
    df = {int(k): float(v) for k, v in sched.read_table(tg.df).items()}
    assert df == {terms[0]: 2.0, terms[1]: 1.0, terms[2]: 1.0, terms[3]: 1.0}
    # full retraction of doc 0's copy of terms[0] -> its df drops to 1
    sched.push(tg.tokens, DeltaBatch(keys[:1], vals[:1],
                                     np.array([-3], np.int64)))
    sched.tick()
    df = {int(k): float(v) for k, v in sched.read_table(tg.df).items()}
    assert df[terms[0]] == 1.0


def test_macro_tick_loop_free_matches_sequential():
    """tick_many on a loop-free sink-free graph scans the PLAIN pass
    program (one device execution for K ticks) and must match K
    sequential ticks bit for bit."""
    def drive_seq():
        tg = tfidf.build_graph(n_pairs=256, n_terms=64, n_docs=16)
        sched = DirtyScheduler(tg.graph, get_executor("tpu"))
        corpus = tfidf.Corpus(256, 64)
        for i, text in enumerate(DOCS):
            sched.push(tg.tokens, corpus.edit(i, text))
            sched.tick(sync=False)
        return sched, tg, corpus

    def drive_macro():
        tg = tfidf.build_graph(n_pairs=256, n_terms=64, n_docs=16)
        sched = DirtyScheduler(tg.graph, get_executor("tpu"))
        corpus = tfidf.Corpus(256, 64)
        feeds = [{tg.tokens: corpus.edit(i, t)} for i, t in enumerate(DOCS)]
        agg = sched.tick_many(feeds).block()
        assert agg.quiesced and agg.passes == len(DOCS)
        # pin the fused path: the scan program must have been cached (a
        # silent fallback to the per-tick loop would also pass the
        # value checks below)
        assert any(isinstance(k, tuple) and k and k[0] == "pass_many"
                   for k in sched.executor._cache), "scan path not taken"
        return sched, tg, corpus

    s1, g1, c1 = drive_seq()
    s2, g2, c2 = drive_macro()
    assert tfidf.tfidf_view(s1, g1, c1) == tfidf.tfidf_view(s2, g2, c2)
    _check(s2, g2, c2)
