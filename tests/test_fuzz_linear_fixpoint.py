"""Structural fuzzing of the declared-linear fixpoint (SURVEY.md §2 #13).

Random linear loop regions over the full chain grammar analyze_linear
matches — ``loop -> Join(linear_left) -> [GroupBy] -> [linear Maps] ->
Union(base) -> Reduce('sum', tol) -> close_loop`` — with random
contraction coefficients (per-source |coef| mass bounded so the
iteration provably converges), random base injections, and churn ticks
that retract exact edge rows. Four executions per seed:

  cpu            host oracle (host-driven loop)
  tpu (linear)   the fused delta-vector program (asserted engaged)
  tpu (row)      the row-based lax.while_loop program
  sharded        the shard_map'd fused loop on the 8-device mesh

All four must agree on the converged Reduce table (atol 2e-3: f32
emission vs the host's f64, both tol-gated at 1e-4).
"""

import numpy as np
import pytest

from reflow_tpu import DirtyScheduler, FlowGraph
from reflow_tpu.delta import DeltaBatch, Spec
from reflow_tpu.executors import CpuExecutor
from reflow_tpu.executors.tpu import TpuExecutor
from reflow_tpu.parallel import make_mesh
from reflow_tpu.parallel.shard import ShardedTpuExecutor

K = 64
N_EDGES = 320
CHURN_TICKS = 3


def _edge_merge(k, x, vb):
    """[dst, coef] routed-contribution merge (ndim-branching contract)."""
    if getattr(vb, "ndim", 1) <= 1:
        return np.asarray([vb[0], x * vb[1]])
    import jax.numpy as jnp

    return jnp.stack([vb[:, 0], x * vb[:, 1]], axis=-1)


def build_linear_loop(rng: np.random.Generator, defer=None):
    """Random declared-linear region; returns (graph, base, edges, reduce,
    uses_groupby)."""
    rank_spec = Spec((), np.float32, key_space=K, unique=True)
    scalar = Spec((), np.float32, key_space=K)
    edge2 = Spec((2,), np.float32, key_space=K)
    use_groupby = bool(rng.random() < 0.7)
    # the grammar's key_fn reads only the arena value (v[:, 0]), so the
    # stable_key declaration is always legal here; drawing it randomly
    # covers both dense tiers (raw scatter vs destination-sorted)
    stable = bool(rng.random() < 0.5)
    n_maps = int(rng.integers(0, 3))
    map_cs = [int(rng.integers(1, 3)) for _ in range(n_maps)]

    g = FlowGraph("linfuzz")
    base = g.source("base", scalar)
    edges = g.source("edges", edge2 if use_groupby else scalar)
    x = g.loop("x", rank_spec)
    if use_groupby:
        j = g.join(x, edges, merge=_edge_merge, spec=edge2,
                   linear_left=True, arena_capacity=1 << 13)
        node = g.group_by(j, key_fn=lambda k, v: v[:, 0].astype("int32"),
                          value_fn=lambda k, v: v[:, 1],
                          vectorized=True, spec=scalar, stable_key=stable)
    else:
        # per-key decay: x'[k] = base[k] + coef_sum[k] * x[k]
        node = g.join(x, edges, merge=lambda k, xa, vb: xa * vb,
                      spec=scalar, linear_left=True,
                      arena_capacity=1 << 13)
    for c in map_cs:
        node = g.map(node, lambda v, c=c: v * np.float32(c),
                     vectorized=True, linear=True)
    u = g.union(node, base)
    red = g.reduce(u, "sum", tol=1e-4, spec=rank_spec)
    g.close_loop(x, red, defer_passes=defer)
    return g, base, edges, red, use_groupby, map_cs


#: keys [K - EDGE_FREE, K) never receive edge contributions: their
#: emissions exist iff their base row does, so base retractions on them
#: exercise true emission-vanish (and reinsert) transitions — including
#: retractions IN FLIGHT under the deferred schedules
EDGE_FREE = 8


def edge_rows(rng, n, use_groupby, map_scale, mass):
    """Random edges drawing coefficients from each source's REMAINING
    contraction budget (0.9 / map_scale total per source, across ALL live
    edges — ``mass`` tracks what's already spent), so the loop contracts
    even as churn adds edges. Updates ``mass`` in place."""
    src = rng.integers(0, K, n)
    dst = rng.integers(0, K - EDGE_FREE, n)
    raw = rng.random(n) + 0.1
    per_src = np.zeros(K)
    np.add.at(per_src, src, raw)
    budget = np.maximum(0.9 / map_scale - mass, 0.0)
    coef = np.round(raw * budget[src] / per_src[src], 4)
    coef = coef.astype(np.float32)
    np.add.at(mass, src, np.abs(coef))
    if use_groupby:
        vals = np.stack([dst.astype(np.float32), coef], axis=1)
    else:
        vals = coef
    return src.astype(np.int64), vals


def drive(executor, g, base, edges, red, ticks, deferred=False):
    sched = DirtyScheduler(g, executor, max_loop_iters=500)
    for tick in ticks:
        for src_node, batch in tick:
            sched.push({"base": base, "edges": edges}[src_node], batch)
        r = sched.tick(sync=not deferred)
        if not deferred:
            assert r.quiesced
    if deferred:
        sched.drain(edges)
    return sched.read_table(red)


def make_ticks(rng, use_groupby, map_scale):
    mass = np.zeros(K)
    src, vals = edge_rows(rng, N_EDGES, use_groupby, map_scale, mass)
    w = np.ones(N_EDGES, np.int64)
    bkeys = np.arange(K, dtype=np.int64)
    bvals = np.round(rng.random(K), 3).astype(np.float32) + 0.05
    ticks = [[("base", DeltaBatch(bkeys, bvals, np.ones(K, np.int64))),
              ("edges", DeltaBatch(src, vals, w))]]
    live = list(range(N_EDGES))
    #: retracted edge-free base keys (their emission is gone while here)
    gone: set = set()
    for _ in range(CHURN_TICKS):
        n_ch = int(rng.integers(4, 20))
        pick = rng.choice(len(live), size=min(n_ch, len(live)),
                          replace=False)
        idx = [live[p] for p in sorted(pick, reverse=True)]
        for p in sorted(pick, reverse=True):
            live.pop(p)
        retract = DeltaBatch(src[idx], vals[idx],
                             -np.ones(len(idx), np.int64))
        # retracted coefficient mass returns to its source's budget
        rcoef = vals[idx][:, 1] if use_groupby else vals[idx]
        np.add.at(mass, src[idx], -np.abs(rcoef.astype(np.float64)))
        nsrc, nvals = edge_rows(rng, len(idx), use_groupby, map_scale,
                                mass)
        # appended rows extend the live set for later churn of churn
        src = np.concatenate([src, nsrc])
        vals = np.concatenate([vals, nvals])
        live.extend(range(len(src) - len(idx), len(src)))
        insert = DeltaBatch(nsrc, nvals, np.ones(len(idx), np.int64))
        # toggle one edge-free key's base row: a retraction makes that
        # key's emission VANISH (no contributions reach it), a reinsert
        # brings it back — covering retraction-in-flight under deferral
        k_t = int(rng.integers(K - EDGE_FREE, K))
        w_t = -1 if k_t not in gone else 1
        (gone.discard if k_t in gone else gone.add)(k_t)
        ticks.append([
            ("edges", DeltaBatch.concat([retract, insert])),
            ("base", DeltaBatch(np.array([k_t], np.int64),
                                bvals[k_t:k_t + 1],
                                np.array([w_t], np.int64)))])
    return ticks


def as_vec(table):
    v = np.zeros(K)
    for k, val in table.items():
        v[int(k)] = float(np.asarray(val).reshape(()))
    return v


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_random_linear_loop_all_programs_agree(seed):
    rng = np.random.default_rng(100 + seed)
    graph_seed = int(rng.integers(0, 1 << 30))
    tick_seed = int(rng.integers(0, 1 << 30))

    def fresh():
        return build_linear_loop(np.random.default_rng(graph_seed))

    g0, _, _, _, use_groupby, map_cs = fresh()
    map_scale = float(np.prod(map_cs)) if map_cs else 1.0
    ticks = make_ticks(np.random.default_rng(tick_seed), use_groupby,
                       map_scale)

    tables = {}
    execs = {
        "cpu": (lambda: CpuExecutor(), None),
        "tpu_linear": (lambda: TpuExecutor(), None),
        "tpu_row": (lambda: TpuExecutor(linear_fixpoint=False), None),
        "sharded": (lambda: ShardedTpuExecutor(make_mesh(8)), None),
        # cross-tick residual deferral: capped passes/tick + drain must
        # land on the same fixpoint (covers retraction-in-flight via the
        # edge-free base-key toggles)
        "tpu_defer1": (lambda: TpuExecutor(), 1),
        "sharded_defer2": (lambda: ShardedTpuExecutor(make_mesh(8)), 2),
    }
    for name, (mk, defer) in execs.items():
        g, base, edges, red, _, _ = fresh() if defer is None else \
            build_linear_loop(np.random.default_rng(graph_seed),
                              defer=defer)
        ex = mk()
        tables[name] = drive(ex, g, base, edges, red, ticks,
                             deferred=defer is not None)
        if name == "tpu_linear":
            assert ex._linear_structure is not None, (
                f"seed {seed}: analyze_linear did not match the region "
                f"(groupby={use_groupby}, maps={map_cs})")

    ref = as_vec(tables["cpu"])
    for name in ("tpu_linear", "tpu_row", "sharded", "tpu_defer1",
                 "sharded_defer2"):
        # tol-gated emission lag amplifies through the contraction like
        # tol/(1-c) — proportional to the key's VALUE — so the bound is
        # assert_allclose's additive atol + rtol*|ref|: a 1e-3 absolute
        # floor (10x the grammar's tol=1e-4, TIGHTER than the old pure
        # 2e-3 atol for small keys) plus a 5e-4 relative allowance for
        # large keys (an extended-seed sweep found a value-4.5 key at
        # abs 2.1e-3 / rel 1.8e-4: pure tol-lag, not divergence)
        np.testing.assert_allclose(
            as_vec(tables[name]), ref, rtol=5e-4, atol=1e-3,
            err_msg=f"seed {seed}: {name} diverges "
                    f"(groupby={use_groupby}, maps={map_cs})")


def test_violated_stable_key_raises_sticky_error():
    """ADVICE r4: a GroupBy declaring stable_key=True whose key_fn in fact
    reads the loop value must fail LOUDLY (the dense destination-sorted
    tier checks its precomputed destinations against the runtime keys and
    routes a mismatch into the join's sticky error) — never silently
    produce tier-selection-dependent ranks."""
    rank_spec = Spec((), np.float32, key_space=K, unique=True)
    scalar = Spec((), np.float32, key_space=K)
    edge2 = Spec((2,), np.float32, key_space=K)

    def bad_key(k, v):
        # at CSR build the loop value is zeroed -> v[:, 1] == 0 -> dst;
        # at runtime v[:, 1] = x*coef != 0 -> dst + 1: a genuine
        # loop-value-dependent key, misdeclared stable
        import jax.numpy as jnp
        return (v[:, 0] + (jnp.abs(v[:, 1]) > 1e-12)).astype("int32") % K

    g = FlowGraph("badstable")
    base = g.source("base", scalar)
    edges = g.source("edges", edge2)
    x = g.loop("x", rank_spec)
    j = g.join(x, edges, merge=_edge_merge, spec=edge2, linear_left=True,
               arena_capacity=1 << 10)
    gb = g.group_by(j, key_fn=bad_key, value_fn=lambda k, v: v[:, 1],
                    vectorized=True, spec=scalar, stable_key=True)
    u = g.union(gb, base)
    red = g.reduce(u, "sum", tol=1e-4, spec=rank_spec)
    g.close_loop(x, red)

    sched = DirtyScheduler(g, TpuExecutor(), max_loop_iters=200)
    keys = np.arange(K, dtype=np.int64)
    sched.push(base, DeltaBatch(keys, np.full(K, 0.5, np.float32),
                                np.ones(K, np.int64)))
    src = np.arange(K, dtype=np.int64)
    vals = np.stack([((src + 1) % K).astype(np.float32),
                     np.full(K, 0.5, np.float32)], axis=1)
    sched.push(edges, DeltaBatch(src, vals, np.ones(K, np.int64)))
    with pytest.raises(RuntimeError, match="stable_key"):
        sched.tick()
        sched.tick()  # in case the error latches a tick later
