"""Follow-the-write: cross-process causality tokens, the ack→push
freshness decomposition, and the crash-surviving flight recorder.

Three contracts under test, hermetically (the kill -9 chaos twin is
``REFLOW_BENCH_E2ETRACE=1 python bench.py``):

- **wire compatibility** — the causality token is a defaulted trailing
  field on ``SubmitReq``/``SubmitAck``/``DeltaFrame``, trimmed when
  tracing is off, so an unstamped message pickles byte-identically to
  the pre-trace protocol and a stamped sender interoperates with an
  unstamped receiver (and vice versa).
- **sampling coherence** — the 1-in-N decision is made ONCE at the
  producer and rides the token: every process records the same writes;
  an unsampled write appears nowhere (no torn chains).
- **decomposition & post-mortem** — ``trace_inspect`` stitches
  token-keyed chains across files and tiles each write's ack→deliver
  freshness exactly, even when the replica's replay span encloses the
  fan-out (synchronous on_window) or an ack was lost and the write was
  re-admitted; the flight recorder's ring survives rotation, respawn
  (``.prev``) and torn tails, and ``reflow_flight`` merges the corners
  into one timeline.
"""

import importlib.util
import json
import os
import pickle

from reflow_tpu import obs
from reflow_tpu.net import LoopbackTransport
from reflow_tpu.obs import trace
from reflow_tpu.obs.flight import FlightRecorder
from reflow_tpu.obs.fleet import FleetAggregator
from reflow_tpu.serve import (APPLIED, IngestFrontend, RemoteProducer,
                              RpcIngestServer)
from reflow_tpu.serve.rpc import SubmitAck, SubmitReq, _trim
from reflow_tpu.subs.query import (DeltaFrame, frames_from_wire,
                                   frames_to_wire)
from reflow_tpu.wal import DurableScheduler
from reflow_tpu.workloads import wordcount

_TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools")


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_TOOLS, f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# -- wire compatibility -----------------------------------------------------

def test_submit_req_unstamped_pickles_byte_identical():
    req = SubmitReq("b0", "src", ("payload",), 5.0)
    assert req.cause is None
    legacy = ("b0", "src", ("payload",), 5.0)   # pre-trace 4-tuple
    assert pickle.dumps(_trim(tuple(req))) == pickle.dumps(legacy)
    # an old sender's 4-tuple fills the receiving default
    assert SubmitReq(*legacy).cause is None


def test_submit_req_stamped_round_trips():
    req = SubmitReq("b0", "src", (), None, "p#1#7")
    wire = _trim(tuple(req))
    assert len(wire) == 5
    assert SubmitReq(*wire).cause == "p#1#7"


def test_submit_ack_trim_and_one_sided_tolerance():
    ack = SubmitAck("b0", "pending")
    legacy = ("b0", "pending", None, None)
    assert pickle.dumps(_trim(tuple(ack))) == pickle.dumps(legacy)
    assert SubmitAck(*legacy).cause is None
    stamped = SubmitAck("b0", "pending", cause="p#0#3")
    assert _trim(tuple(stamped))[-1] == "p#0#3"


def test_delta_frame_unstamped_wire_identity_and_stamped():
    fr = DeltaFrame(0, 4, "view", ((("k", 1.0), 1),), False)
    legacy = ((0, 4, "view", ((("k", 1.0), 1),), False),)
    assert pickle.dumps(frames_to_wire([fr])) == pickle.dumps(legacy)
    # an unstamped wire frame from an old hub reads back cause-less
    assert frames_from_wire(legacy)[0].cause is None
    stamped = DeltaFrame(0, 4, "view", (), False, ("p#0#1", "p#0#2"))
    wire = frames_to_wire([stamped])
    assert wire[0][-1] == ("p#0#1", "p#0#2")
    assert frames_from_wire(wire)[0].cause == ("p#0#1", "p#0#2")


# -- cross-process sampling coherence ---------------------------------------

def _rpc_stack(tmp_path):
    g, src, sink = wordcount.build_graph()
    sched = DurableScheduler(g, wal_dir=str(tmp_path / "wal"),
                             fsync="tick")
    fe = IngestFrontend(sched, start=True)
    lt = LoopbackTransport()
    srv = RpcIngestServer(fe, lt).start()
    return sched, fe, lt, srv, src


def _spans(path):
    with open(path) as f:
        return [e for e in json.load(f)["traceEvents"]
                if e.get("ph") == "X"]


def test_sampled_write_recorded_at_every_hop(tmp_path, monkeypatch):
    monkeypatch.setattr(trace, "SAMPLE_EVERY", 1)   # every write draws
    sched, fe, lt, srv, src = _rpc_stack(tmp_path)
    obs.enable()
    trace.reset()
    prod = RemoteProducer(lt, srv.address, name="p0")
    try:
        t = prod.submit(src, wordcount.ingest_lines(["aa bb"]),
                        batch_id="b0")
        res = t.result(10)
        assert res.status == APPLIED
        tok = t.cause
        assert tok and tok.startswith("p0#0#")   # origin#epoch#seq
        out = tmp_path / "trace.json"
        obs.export_chrome_trace(str(out))
        by_name = {}
        for e in _spans(str(out)):
            if (e.get("args") or {}).get("cause") == tok:
                by_name.setdefault(e["name"], []).append(e)
        # producer, RPC server, frontend admission, and the WAL all
        # recorded THIS write under the SAME token — no re-rolling
        for name in ("producer_submit", "rpc_admit", "admission",
                     "wal_append"):
            assert name in by_name, (name, sorted(by_name))
        assert by_name["wal_append"][0]["args"]["lsn"] is not None
    finally:
        obs.disable()
        trace.reset()
        prod.close()
        srv.close()
        fe.close()
        sched.wal.close()


def test_unsampled_write_appears_nowhere(tmp_path, monkeypatch):
    monkeypatch.setattr(trace, "SAMPLE_EVERY", 1 << 30)
    trace.sample()   # burn the counter's possible zero-phase draw
    sched, fe, lt, srv, src = _rpc_stack(tmp_path)
    obs.enable()
    trace.reset()
    prod = RemoteProducer(lt, srv.address, name="p0")
    try:
        t = prod.submit(src, wordcount.ingest_lines(["aa"]),
                        batch_id="b0")
        assert t.result(10).status == APPLIED
        assert t.cause is None
        out = tmp_path / "trace.json"
        obs.export_chrome_trace(str(out))
        causes = [e for e in _spans(str(out))
                  if (e.get("args") or {}).get("cause")
                  or (e.get("args") or {}).get("causes")]
        assert causes == []          # no torn chain anywhere
        assert not any(e["name"] == "rpc_admit"
                       for e in _spans(str(out)))
    finally:
        obs.disable()
        trace.reset()
        prod.close()
        srv.close()
        fe.close()
        sched.wal.close()


# -- trace_inspect: chains, freshness tiling, schema ------------------------

TOK = "p0#0#1"      # the write's own token
CHUNK = "n0#0#9"    # the shipped chunk's token (bridges net_send)


def _ev(name, ts, dur, **args):
    return {"ph": "X", "name": name, "ts": ts, "dur": dur,
            "tid": 1, "pid": 1, "args": args or None}


def _chain_events(*, replay_dur=500.0, extra=()):
    evs = [
        _ev("producer_submit", 0.0, 1000.0, cause=TOK),
        _ev("rpc_admit", 100.0, 100.0, cause=TOK),
        _ev("admission", 120.0, 50.0, cause=TOK),
        _ev("wal_append", 300.0, 200.0, cause=TOK, lsn=3),
        _ev("ship_segment", 600.0, 300.0, cause=CHUNK, causes=[TOK]),
        _ev("net_send", 620.0, 100.0, cause=CHUNK),
        _ev("replica_replay", 1000.0, replay_dur, cause=CHUNK,
            causes=[TOK]),
        _ev("sub_fanout", 1600.0, 100.0, causes=[TOK]),
        _ev("sub_deliver", 1800.0, 50.0, causes=[TOK]),
    ]
    evs.extend(extra)
    return evs


def _write_trace(path, events, base_s=10.0, node="n0"):
    with open(path, "w") as f:
        json.dump({"traceEvents": events, "baseTimeS": base_s,
                   "node": node}, f)
    return str(path)


def test_inspect_stitches_full_chain_across_files(tmp_path):
    ti = _load_tool("trace_inspect")
    evs = _chain_events()
    # split producer / leader / replica+sub spans across three files
    # with different baseTimeS — the merge must re-anchor them
    producer = [e for e in evs if e["name"] == "producer_submit"]
    leader = [e for e in evs
              if e["name"] in ("rpc_admit", "admission", "wal_append",
                               "ship_segment", "net_send")]
    rest = [e for e in evs if e not in producer and e not in leader]
    for e in rest:      # this file's clock starts 1ms later
        e["ts"] -= 1000.0
    files = [
        _write_trace(tmp_path / "p.json", producer, node="p0"),
        _write_trace(tmp_path / "l.json", leader, node="leader"),
        _write_trace(tmp_path / "r.json", rest, base_s=10.001,
                     node="r0"),
    ]
    rep = ti.inspect(files, require_chain=list(ti.FULL_CHAIN))
    assert rep["schema"] == "reflow.trace_inspect/2"
    assert rep["causal"]["full_chains"] == 1
    assert rep["causal"]["required_chains"] == 1
    fresh = rep["freshness"]
    assert fresh["chains"] == 1
    assert fresh["max_dev_frac"] == 0.0
    assert fresh["e2e_p50_us"] == 1850.0
    assert fresh["stages"]["admission"]["p50_us"] == 200.0
    assert fresh["stages"]["durability"]["p50_us"] == 300.0
    assert fresh["worst"]["token"] == TOK


def test_require_chain_fails_on_missing_link(tmp_path):
    ti = _load_tool("trace_inspect")
    evs = [e for e in _chain_events() if e["name"] != "net_send"]
    f = _write_trace(tmp_path / "t.json", evs)
    rep = ti.inspect([f], require_chain=list(ti.FULL_CHAIN))
    assert rep["causal"]["required_chains"] == 0
    assert rep["causal"]["full_chains"] == 0


def test_freshness_tiles_when_replay_encloses_fanout(tmp_path):
    # the hub fans out synchronously inside the replay span, so the
    # replay can CLOSE after the push — and even after the delivery.
    # The apply cut must take the earlier of (replay end, push end) or
    # the fanout stage goes negative and the tiling breaks.
    ti = _load_tool("trace_inspect")
    f = _write_trace(tmp_path / "t.json",
                     _chain_events(replay_dur=900.0))   # ends at 1900
    rep = ti.inspect([f], require_chain=list(ti.FULL_CHAIN))
    fresh = rep["freshness"]
    assert fresh["max_dev_frac"] == 0.0
    assert fresh["worst"]["raw_stage_us"]["fanout"] == 0.0
    assert fresh["worst"]["raw_stage_us"]["apply"] == 700.0


def test_freshness_uses_first_admit_of_a_resubmitted_write(tmp_path):
    # a lost ack makes the producer resubmit; the dedup re-admit emits
    # a SECOND rpc_admit much later. Freshness reads the FIRST admit
    # end (the write was in the system from then on), so the tiling
    # still closes exactly.
    ti = _load_tool("trace_inspect")
    f = _write_trace(
        tmp_path / "t.json",
        _chain_events(extra=[_ev("rpc_admit", 900.0, 100.0,
                                 cause=TOK)]))
    rep = ti.inspect([f], require_chain=list(ti.FULL_CHAIN))
    assert rep["freshness"]["max_dev_frac"] == 0.0


def test_chain_freshness_two_element_bounds_fallback():
    # report data predating min-end tracking carries 2-element bounds;
    # the cut helper must fall back to the max end instead of blowing
    # up on the missing slot
    ti = _load_tool("trace_inspect")
    bounds = {"producer_submit": [0.0, 100.0],
              "rpc_admit": [10.0, 20.0],
              "wal_append": [30.0, 40.0],
              "replica_replay": [50.0, 60.0],
              "sub_fanout": [70.0, 80.0],
              "sub_deliver": [90.0, 95.0]}
    stages, e2e, dev, _raw = ti._chain_freshness(bounds)
    assert e2e == 95.0
    assert dev == 0.0
    assert stages["admission"] == 20.0


def test_read_report_backfills_v1_to_v2_keys():
    ti = _load_tool("trace_inspect")
    old = {"causal": {"chains": 2, "links": 5},
           "trace_file": "x.json", "tickets": 4}
    rep = ti.read_report(old)
    assert rep["schema"] == "reflow.trace_inspect/1"
    assert rep["freshness"] is None
    assert rep["trace_files"] == ["x.json"]
    assert rep["causal"]["groups"] == 2       # chains alias
    assert rep["causal"]["full_chains"] == 0


# -- flight recorder --------------------------------------------------------

def test_flight_ring_rotates_and_respawn_archives_prev(tmp_path):
    corner = str(tmp_path / "n0" / "flight")
    rec = FlightRecorder(corner, node="n0", cap_bytes=8192,
                         flush_every=1)
    for i in range(200):
        rec.record("ship_segment", float(i), 1.0, "wal",
                   {"cause": f"n0#0#{i}"})
    assert rec.rotations_total >= 1
    rec.note("promote", epoch=1, horizon=42)    # eager flush
    rec.close()
    # a respawn reopens the same corner; the dead incarnation's ring
    # must survive as .prev, not be truncated over
    rec2 = FlightRecorder(corner, node="n0", cap_bytes=8192,
                          flush_every=1)
    rec2.note("breaker_open", graph="g0")
    rec2.close()
    names = sorted(os.listdir(corner))
    assert any(n.endswith(".prev") for n in names)
    # torn tail: a kill -9 mid-write leaves half a line — the reader
    # must drop it, not die on it
    with open(os.path.join(corner, "flight-a.jsonl"), "a") as f:
        f.write('{"seq": 999, "kind": "sp')
    rf = _load_tool("reflow_flight")
    merged = rf.merge([str(tmp_path)])
    assert "n0" in merged["nodes"]
    node = merged["nodes"]["n0"]
    assert node["files"] >= 2            # live ring + .prev generation
    names = [ev["name"] for ev in merged["events"]]
    assert "promote" in names and "breaker_open" in names
    assert not any(ev.get("seq") == 999 for ev in merged["events"])


def test_flight_publish_metrics_unregisters_on_close(tmp_path):
    reg = obs.MetricsRegistry()
    rec = FlightRecorder(str(tmp_path / "flight"), node="n0",
                         flush_every=4)
    rec.publish_metrics(reg)
    rec.record("sub_push", 0.0, 1.0, None, {"cause": "x#0#0"})
    snap = reg.snapshot()["gauges"]
    assert snap["flight.events_total"] == 1
    rec.close()
    assert "flight.events_total" not in reg.snapshot()["gauges"]


# -- fleet aggregation: new gauges with pre-upgrade tolerance ---------------

def test_fleet_freshness_and_flight_gauges_backfill_tolerant():
    agg = FleetAggregator(retention=4, stale_after_s=60.0)
    agg.ingest("new", {"gauges": {"subs.freshness_p50": 0.002,
                                  "subs.freshness_p99": 0.010,
                                  "flight.events_total": 42}})
    agg.ingest("old", {"gauges": {}})       # pre-upgrade node
    snap = agg.fleet_snapshot()
    assert snap["nodes"]["old"]["sub_freshness_p50"] is None
    assert snap["nodes"]["old"]["flight_events"] is None
    assert snap["nodes"]["new"]["sub_freshness_p99"] == 0.010
    assert snap["gauges"]["subs.freshness_p50"] == 0.002
    assert snap["gauges"]["flight.events_total"] == 42
    assert not snap["alerts"]


def test_fleet_gauges_none_when_no_node_ships_them():
    agg = FleetAggregator(retention=4, stale_after_s=60.0)
    agg.ingest("old", {"gauges": {"r0.horizon": 7}})
    g = agg.fleet_snapshot()["gauges"]
    assert g["subs.freshness_p50"] is None
    assert g["subs.freshness_p99"] is None
    assert g["flight.events_total"] is None


# -- hub freshness gauges feed the fleet plane ------------------------------

def test_hub_freshness_gauge_populates_after_fanout(tmp_path):
    import numpy as np
    from reflow_tpu.serve import ReplicaScheduler
    from reflow_tpu.subs import SubscriptionHub
    from reflow_tpu.wal import SegmentShipper
    g, src, sink = wordcount.build_graph()
    sched = DurableScheduler(g, wal_dir=str(tmp_path / "wal"),
                             fsync="tick")
    ship = SegmentShipper(sched.wal, leader_tick=lambda: sched._tick)
    g2, _s, _k = wordcount.build_graph()
    rep = ReplicaScheduler(g2, str(tmp_path / "r0"), name="r0")
    ship.attach(rep)
    hub = SubscriptionHub(rep, name="r0", idle_poll_s=0.005)
    rep.attach_hub(hub)
    reg = obs.MetricsRegistry()
    hub.publish_metrics(reg)
    try:
        h = hub.open(sink.name, "view")
        rng = np.random.default_rng(0)
        for t in range(3):
            words = " ".join(f"w{int(x)}"
                             for x in rng.integers(0, 20, 8))
            sched.push(src, wordcount.ingest_lines([words]),
                       batch_id=f"t{t}")
            sched.tick()
        sched.wal.sync()
        for _ in range(200):
            ship.pump_once()
            if rep.published_horizon() == sched._tick:
                break
        assert h.wait_horizon(rep.published_horizon())
        snap = reg.snapshot()["gauges"]
        # the in-hub slice of ack->push freshness is live and sane
        assert snap["subs.freshness_p50"] > 0.0
        assert snap["subs.freshness_p99"] >= snap["subs.freshness_p50"]
    finally:
        hub.close()
        sched.close()


# -- the promoted leader advertises its true epoch --------------------------

def test_durable_scheduler_exposes_wal_epoch(tmp_path):
    g, _src, _sink = wordcount.build_graph()
    sched = DurableScheduler(g, wal_dir=str(tmp_path / "wal"),
                             fsync="tick", epoch=3)
    try:
        # the ingestion RPC's hello reads getattr(sched, "epoch", 0) —
        # before this property existed a promoted leader advertised 0
        # and reconnecting producers minted stale epoch-0 tokens
        assert sched.epoch == 3
        assert sched.epoch == sched.wal.epoch
    finally:
        sched.wal.close()
