"""Multi-host (DCN-axis) groundwork test (VERDICT r3 #6).

Spawns TWO jax.distributed processes on the CPU platform (4 forced
devices each -> 8 global), builds the 2-axis (dcn=2, ici=4) mesh, and
runs the sharded PageRank build + churn tick with process-local
ingestion, each process verifying its addressable rank shards against
the dense reference (tests/multihost_worker.py).

If jax.distributed cannot initialize in this harness (sandboxed
networking), the test SKIPS with the manual recipe — the documented
fallback VERDICT r3 #6 allows.
"""

import os
import socket
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.xfail(
    strict=False,
    reason="the workers force JAX_PLATFORMS=cpu, and this jaxlib's CPU "
           "backend has no multiprocess collectives (cross-process "
           "psum over the dcn axis fails inside the churn tick); runs "
           "for real on a multi-host TPU/GPU fleet")
def test_two_process_dcn_mesh_tick():
    port = _free_port()
    coord = f"127.0.0.1:{port}"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append("--xla_force_host_platform_device_count=4")
    env["XLA_FLAGS"] = " ".join(flags)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")

    import tempfile
    ckpt_dir = tempfile.mkdtemp(prefix="reflow_mh_ckpt_")
    env["REFLOW_MH_CKPT"] = ckpt_dir

    worker = os.path.join(_REPO, "tests", "multihost_worker.py")
    procs = [subprocess.Popen(
        [sys.executable, worker, coord, str(i), "2"],
        env=env, cwd=_REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True) for i in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=600)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("multihost workers timed out")

    joined = "\n".join(outs)
    if any(p.returncode for p in procs):
        # distributed init unavailable in this sandbox -> documented skip
        # with the manual recipe; any OTHER failure is a real bug
        init_markers = ("DEADLINE_EXCEEDED", "UNAVAILABLE",
                        "Failed to connect", "barrier timed out",
                        "coordination service")
        if any(m in joined for m in init_markers):
            pytest.skip(
                "jax.distributed could not initialize here; run manually:"
                " for i in 0 1; do JAX_PLATFORMS=cpu XLA_FLAGS="
                "--xla_force_host_platform_device_count=4 python "
                "tests/multihost_worker.py 127.0.0.1:12345 $i 2 & done")
        pytest.fail(f"multihost worker failed:\n{joined[-4000:]}")
    assert "proc 0: verified" in joined and "proc 1: verified" in joined
    assert ("proc 0: exactly-once + ckpt/restore continuation OK" in joined
            and "proc 1: exactly-once + ckpt/restore continuation OK"
            in joined)
