"""Worker for the 2-process DCN-mesh test (spawned by test_multihost.py).

Each process: jax.distributed.initialize against a shared coordinator,
build the 2-axis (dcn=2, ici=4) mesh over the 8 global CPU devices, run
the incremental-PageRank build + churn ticks with process-local
ingestion (shard_batch_process_local), and verify THIS process's
addressable shards of the converged rank table against the dense NumPy
reference. SPMD contract: both processes execute the identical driver.
"""

import os
import sys

import numpy as np


def main() -> None:
    coord = sys.argv[1]
    pid = int(sys.argv[2])
    nproc = int(sys.argv[3])

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(coordinator_address=coord,
                               num_processes=nproc, process_id=pid)
    assert jax.process_count() == nproc
    assert len(jax.devices()) == 8, len(jax.devices())

    from reflow_tpu.delta import DeltaBatch
    from reflow_tpu.parallel import make_mesh
    from reflow_tpu.parallel.mesh import shard_batch_process_local
    from reflow_tpu.parallel.shard import ShardedTpuExecutor
    from reflow_tpu.scheduler import DirtyScheduler
    from reflow_tpu.workloads import pagerank

    N_NODES, N_EDGES = 256, 2048
    mesh = make_mesh(dcn=nproc)
    assert mesh.axis_names == ("dcn", "delta")
    ex = ShardedTpuExecutor(mesh)
    assert ex.axis == ("dcn", "delta") and ex.n == 8

    pr = pagerank.build_graph(N_NODES, tol=5e-5, arena_capacity=1 << 16)
    sched = DirtyScheduler(pr.graph, ex, max_loop_iters=500)
    web = pagerank.WebGraph.random(N_NODES, N_EDGES, seed=0)

    def split(batch: DeltaBatch) -> DeltaBatch:
        """This process's half of a deterministic global batch (striped
        so both processes derive identical global content SPMD-style)."""
        return DeltaBatch(np.asarray(batch.keys)[pid::nproc],
                          np.asarray(batch.values)[pid::nproc],
                          np.asarray(batch.weights)[pid::nproc])

    def push_local(node, batch, capacity):
        sched.push(node, shard_batch_process_local(
            split(batch), node.spec, mesh, capacity=capacity))

    push_local(pr.teleport, pagerank.teleport_batch(N_NODES), 1 << 9)
    push_local(pr.edges, web.initial_batch(), 1 << 12)
    r = sched.tick(sync=False)

    # one churn tick: the steady incremental shape over the DCN mesh
    push_local(pr.edges, web.churn(0.02), 1 << 9)
    r2 = sched.tick(sync=False)
    r.block()
    r2.block()
    assert r.quiesced and r2.quiesced, (r.quiesced, r2.quiesced)

    # verify THIS process's addressable shards of the converged table
    # against the dense reference (global np.asarray is illegal on a
    # partially-addressable multi-host array)
    ref = pagerank.reference_ranks(web)
    emitted = ex.states[pr.new_rank.id]["emitted"]
    has = ex.states[pr.new_rank.id]["emitted_has"]
    checked = 0
    for sh, sh_has in zip(emitted.addressable_shards,
                          has.addressable_shards):
        lo = sh.index[0].start or 0
        got = np.asarray(sh.data)
        hv = np.asarray(sh_has.data)
        for i in range(got.shape[0]):
            want = ref[lo + i]
            if hv[i]:
                rel = abs(got[i] - want) / max(abs(want), 1.0)
                assert rel < 5e-4, (lo + i, got[i], want)
                checked += 1
    assert checked > 0
    print(f"proc {pid}: verified {checked} owned ranks OK", flush=True)

    # -- round 5: cross-controller exactly-once + collective ckpt ---------
    # batch ids minted from the shared cursor are SPMD-identical by
    # construction; a redelivered (duplicate) push must dedup on BOTH
    # processes, keeping the dedup windows digest-equal
    import tempfile

    from reflow_tpu.scheduler import SourceCursor
    from reflow_tpu.utils.checkpoint import (load_checkpoint, meta_digest,
                                             save_checkpoint)

    cur = SourceCursor(pr.edges)
    churn2 = web.churn(0.02)
    bid = cur.next_id()
    acc1 = sched.push(pr.edges, shard_batch_process_local(
        split(churn2), pr.edges.spec, mesh, capacity=1 << 9),
        batch_id=bid)
    # redelivery replay: same id -> dropped, no tick content
    acc2 = sched.push(pr.edges, shard_batch_process_local(
        split(churn2), pr.edges.spec, mesh, capacity=1 << 9),
        batch_id=bid)
    assert acc1 and not acc2, (acc1, acc2)
    r3 = sched.tick(sync=False)
    r3.block()
    assert r3.quiesced

    # digest agreement (what save_checkpoint verifies collectively)
    from jax.experimental import multihost_utils
    mine = np.uint64(meta_digest(sched._tick, sched._seen_batch_ids))
    digs = np.asarray(multihost_utils.process_allgather(mine))
    assert len({int(x) for x in digs.ravel()}) == 1, digs

    # collective checkpoint -> restore into a FRESH scheduler -> both
    # continue with one more churn tick -> owned shards must agree
    # reflow-lint: waive env-knob-direct -- test-harness plumbing (driver->worker channel), not a user knob
    ckpt_dir = os.environ.get("REFLOW_MH_CKPT")
    assert ckpt_dir, "driver must pass a shared ckpt dir"
    save_checkpoint(sched, ckpt_dir)

    pr2 = pagerank.build_graph(N_NODES, tol=5e-5, arena_capacity=1 << 16)
    ex2 = ShardedTpuExecutor(make_mesh(dcn=nproc))
    sched2 = DirtyScheduler(pr2.graph, ex2, max_loop_iters=500)
    load_checkpoint(sched2, ckpt_dir)
    cur2 = SourceCursor.resume(sched2, pr2.edges)
    assert cur2.seq == cur.seq, (cur2.seq, cur.seq)

    churn3 = web.churn(0.02)
    for s, prx, c in ((sched, pr, cur), (sched2, pr2, cur2)):
        s.push(prx.edges, shard_batch_process_local(
            split(churn3), prx.edges.spec, s.executor.mesh,
            capacity=1 << 9), batch_id=c.next_id())
        rr = s.tick(sync=False)
        rr.block()
        assert rr.quiesced

    em_a = ex.states[pr.new_rank.id]["emitted"]
    em_b = ex2.states[pr2.new_rank.id]["emitted"]
    for sa, sb in zip(em_a.addressable_shards, em_b.addressable_shards):
        np.testing.assert_allclose(np.asarray(sa.data),
                                   np.asarray(sb.data), atol=1e-5)
    print(f"proc {pid}: exactly-once + ckpt/restore continuation OK",
          flush=True)


if __name__ == "__main__":
    main()
