"""Pipelined window execution (docs/guide.md "Pipelined windows").

The contract under test: splitting the fused window lifecycle into
stage → dispatch → retire with a bounded in-flight depth changes WHEN
work happens, never WHAT is computed —

- depth 2/4 drives through an ``IngestFrontend`` produce tables EXACTLY
  equal (bitwise) to the depth-1 drive on identical batches, and both
  match the per-tick CPU oracle;
- staging window N+1 never writes a buffer set an in-flight window
  program owns (generation rotation), including when the pump crashes
  with windows dispatched but unretired — every ticket still resolves;
- a producer blocked on the admission budget wakes at STAGE-complete
  (the chunk's rows live in the device queue, their host bytes no
  longer occupy the frontend), not at retire;
- the ingress queue refuses int64 keys outside the int32 slot range
  instead of silently wrapping them.
"""

import threading
import time

import numpy as np
import pytest

from reflow_tpu import DirtyScheduler, FlowGraph
from reflow_tpu.delta import DeltaBatch, Spec
from reflow_tpu.executors import get_executor
from reflow_tpu.executors.device_delta import DeviceDelta
from reflow_tpu.executors.ingress_queue import DeviceIngressQueue, slot_nbytes
from reflow_tpu.serve import CoalesceWindow, IngestFrontend, PumpCrashed
from reflow_tpu.utils.faults import CrashInjector, DeliveryError

K_SPACE = 32
ROWS = 6


def _batch(rows):
    return DeltaBatch(np.array([r[0] for r in rows], np.int64),
                      np.array([r[1] for r in rows], np.float32),
                      np.array([r[2] for r in rows], np.int64))


def _graph():
    """source -> map -> reduce(sum): loop-free, sink-free, ONE source so
    every feed is uniform and the fused window path always engages."""
    g = FlowGraph("pipeline")
    spec = Spec((), np.float32, key_space=K_SPACE)
    s = g.source("s", spec)
    m = g.map(s, lambda v: v * np.float32(2), vectorized=True)
    r = g.reduce(m, "sum", tol=0.0)
    return g, s, r


def _mk_batches(seed, n=8, rows=ROWS):
    rng = np.random.default_rng(seed)
    return [_batch([(int(rng.integers(0, K_SPACE)),
                     float(rng.integers(0, 8)), 1) for _ in range(rows)])
            for _ in range(n)]


def _table(sched, node, nd=None):
    return {int(k): (float(np.asarray(v).reshape(()))
                     if nd is None
                     else round(float(np.asarray(v).reshape(())), nd))
            for k, v in sched.read_table(node).items()}


def _oracle(batches):
    g, s, r = _graph()
    sched = DirtyScheduler(g, get_executor("cpu"))
    for b in batches:
        sched.push(s, b)
        sched.tick()
    return _table(sched, r, nd=3)


def _frontend_drive(batches, depth, k):
    """One paused wave through a frontend pump: all batches queue, then
    resume drains them as one multi-chunk backlog (chunks of ``k``
    ticks), which is what makes consecutive windows actually pipeline
    at depth > 1. Returns (exact table, sched, frontend)."""
    g, s, r = _graph()
    sched = DirtyScheduler(g, get_executor("tpu"))
    fe = IngestFrontend(sched, depth=depth, window=CoalesceWindow(
        max_rows=ROWS, max_ticks=k, max_latency_s=0.001))
    try:
        fe.pause()
        tks = [fe.submit(s, b) for b in batches]
        fe.resume()
        fe.flush(timeout=30)
        assert all(t.result(timeout=10).applied for t in tks)
    finally:
        fe.close()
    return _table(sched, r), sched, fe


def _queue(sched) -> DeviceIngressQueue:
    qkeys = [key for key in sched.executor._cache
             if isinstance(key, tuple) and key and key[0] == "ingress_q"]
    assert len(qkeys) == 1
    return sched.executor._cache[qkeys[0]]


# -- differential fuzz: depths x window sizes x seeds ----------------------

@pytest.mark.parametrize("k", [2, 4])
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_depth_fuzz_parity(seed, k):
    """Depth 2 and 4 are bit-for-bit depth 1 (same fused program, same
    slot contents, same dispatch order), and all match the oracle."""
    batches = _mk_batches(seed)
    want = _oracle(batches)
    t1, s1, fe1 = _frontend_drive(batches, depth=1, k=k)
    t2, s2, fe2 = _frontend_drive(batches, depth=2, k=k)
    t4, s4, fe4 = _frontend_drive(batches, depth=4, k=k)
    assert t2 == t1 and t4 == t1          # EXACT float equality
    assert {key: round(v, 3) for key, v in t1.items()} == want
    for sched in (s1, s2, s4):
        assert sched.megatick_fallbacks == 0
        assert sched.megatick_windows == len(batches) // k
    # depth 1 is literally the serial tick_many path; deeper drives
    # stage every chunk and overlap all but each wave's first
    assert fe1.windows_staged == 0 and fe1.stage_overlap_frac == 0.0
    for fe in (fe2, fe4):
        assert fe.windows_staged == len(batches) // k
        assert fe.windows_pipelined >= 1
        assert fe.stage_overlap_frac > 0.0


# -- stage never touches an in-flight generation ---------------------------

def test_stage_rotates_off_inflight_generation():
    """While window A is dispatched-but-unretired, staging window B
    lands in a DIFFERENT buffer generation: no array object of A's
    donated stack is reused, so B's slot writes can't corrupt A."""
    g, s, red = _graph()
    sched = DirtyScheduler(g, get_executor("tpu"))
    waves = [_mk_batches(5, n=2), _mk_batches(6, n=2)]

    h1 = sched.stage_window([{s: b} for b in waves[0]])
    assert h1 is not None
    bufs1 = {id(arr) for dd in h1.sw.stack.values()
             for arr in (dd.keys, dd.values, dd.weights)}
    sched.dispatch_staged(h1)
    q = _queue(sched)
    assert q.in_flight == 1

    h2 = sched.stage_window([{s: b} for b in waves[1]])
    assert h2 is not None
    assert h2.sw.gen != h1.sw.gen
    bufs2 = {id(arr) for dd in h2.sw.stack.values()
             for arr in (dd.keys, dd.values, dd.weights)}
    assert not (bufs1 & bufs2)
    assert q.generations == 2
    sched.dispatch_staged(h2)
    assert q.in_flight == 2

    sched.retire_staged(h1)
    sched.retire_staged(h2)
    assert q.in_flight == 0
    assert sched.megatick_fallbacks == 0
    # both windows' rows landed: views equal the per-tick oracle
    g2, s2, r2 = _graph()
    per = DirtyScheduler(g2, get_executor("cpu"))
    for b in waves[0] + waves[1]:
        per.push(s2, b)
        per.tick()
    assert _table(sched, red, nd=3) == _table(per, r2, nd=3)


def test_depth1_pingpong_reuses_generation_zero():
    """The serial flow (seal -> dispatch -> retire -> seal) never
    allocates a second generation — same memory footprint as before
    pipelining."""
    g, s, _r = _graph()
    sched = DirtyScheduler(g, get_executor("tpu"))
    for seed in (7, 8, 9):
        res = sched.tick_many([{s: b} for b in _mk_batches(seed, n=2)])
        res.block()
    q = _queue(sched)
    assert sched.megatick_windows == 3
    assert q.generations == 1
    assert q.in_flight == 0


def test_crash_with_window_in_flight_fails_every_ticket():
    """Kill the pump between chunk dispatches (chunk 1 dispatched and
    unretired, chunk 2 about to stage): the crash path must fail BOTH
    chunks' tickets — the in-flight window's ids stay in the dedup
    mirror, so a replay after recovery dedups instead of double-folding."""
    g, s, _r = _graph()
    sched = DirtyScheduler(g, get_executor("tpu"))
    crash = CrashInjector(2, only="pump_before_tick")
    fe = IngestFrontend(sched, crash=crash, depth=2,
                        window=CoalesceWindow(max_rows=ROWS, max_ticks=2,
                                              max_latency_s=0.001))
    fe.pause()
    tks = [fe.submit(s, b, batch_id=f"b{i}")
           for i, b in enumerate(_mk_batches(3, n=4))]
    fe.resume()
    for t in tks:
        with pytest.raises(PumpCrashed):
            t.result(timeout=10)
    assert crash.fired
    assert not fe._inflight
    assert fe._pending_res == 0
    # executed-but-unresolved ids stay admitted: a resend dedups
    assert "b0" in fe._admitted and "b3" in fe._admitted
    fe.close()


# -- stage-complete budget release -----------------------------------------

def test_stage_release_unblocks_producer_before_retire():
    """A budget-blocked producer wakes when the current chunk finishes
    STAGING (its rows now live in the device queue), not when the window
    retires — the regression for release-at-stage-complete. Settling is
    stubbed out, so only the stage-complete release can unblock it."""
    g, s, _r = _graph()
    sched = DirtyScheduler(g, get_executor("tpu"))
    rows = 4
    fe = IngestFrontend(sched, start=False, depth=2, policy="block",
                        max_bytes=slot_nbytes(s.spec, rows),
                        window=CoalesceWindow(max_rows=rows, max_ticks=2,
                                              max_latency_s=0.001))
    mk = lambda v: _batch([(i, float(v), 1) for i in range(rows)])
    t1 = fe.submit(s, mk(1))
    admitted = threading.Event()
    t2_box = []

    def produce():
        t2_box.append(fe.submit(s, mk(2)))
        admitted.set()

    th = threading.Thread(target=produce, daemon=True)
    th.start()
    time.sleep(0.05)
    assert not admitted.is_set()       # genuinely blocked on the budget
    real_settle = fe._settle_all
    fe._settle_all = lambda: None
    try:
        with fe._lock:
            drained = fe._take_window()
        fe._run_window(drained)
        assert fe._inflight            # dispatched, NOT retired
        assert admitted.wait(5), ("producer still blocked after "
                                  "stage-complete")
    finally:
        fe._settle_all = real_settle
    fe._settle_all()
    with fe._lock:
        fe._finish_window()
    with fe._lock:
        drained = fe._take_window()
    fe._run_window(drained)
    with fe._lock:
        fe._finish_window()
    th.join(timeout=5)
    assert t1.result(timeout=5).applied
    assert t2_box[0].result(timeout=5).applied
    fe.close()


# -- ingress queue: generation rotation + key-range guard ------------------

def _unit_queue(k=2, cap=4, key_space=8):
    spec = Spec((), np.float32, key_space=key_space)
    return DeviceIngressQueue({0: spec}, {0: cap}, k), spec


def _fresh_stack(k, cap):
    import jax.numpy as jnp

    return {0: DeviceDelta(jnp.zeros((k, cap), jnp.int32),
                           jnp.zeros((k, cap), jnp.float32),
                           jnp.zeros((k, cap), jnp.int32))}


def test_seal_rotates_and_retire_frees():
    q, _spec = _unit_queue()
    q.write(0, 0, _batch([(1, 2.0, 1)]))
    st1 = q.stacked()
    g0 = q.seal()
    assert q.in_flight == 1
    q.write(0, 0, _batch([(2, 3.0, 1)]))   # rotates onto a fresh gen
    st2 = q.stacked()
    assert q.generations == 2
    assert {id(a) for dd in st1.values()
            for a in (dd.keys, dd.values, dd.weights)}.isdisjoint(
        {id(a) for dd in st2.values()
         for a in (dd.keys, dd.values, dd.weights)})
    # the sealed gen's contents are untouched by the new gen's writes
    assert int(np.asarray(st1[0].weights[0]).sum()) == 1
    q.retire(g0, _fresh_stack(2, 4))
    assert q.in_flight == 0
    with pytest.raises(ValueError):
        q.retire(g0, _fresh_stack(2, 4))   # no longer in flight
    with pytest.raises(ValueError):
        q.retire(99, _fresh_stack(2, 4))


def test_retire_validates_stack_keys():
    q, _spec = _unit_queue()
    q.write(0, 0, _batch([(1, 1.0, 1)]))
    g0 = q.seal()
    with pytest.raises(ValueError):
        q.retire(g0, {5: _fresh_stack(2, 4)[0]})


def test_cancel_returns_generation_without_adoption():
    q, _spec = _unit_queue()
    q.write(0, 0, _batch([(1, 1.0, 1)]))
    g0 = q.seal()
    q.cancel(g0)
    assert q.in_flight == 0
    q.write(1, 0, _batch([(2, 1.0, 1)]))   # reuses g0: no new allocation
    assert q.generations == 1
    assert q._staging == g0


def test_rebind_requires_inflight_generation():
    q, _spec = _unit_queue()
    with pytest.raises(ValueError):
        q.rebind(_fresh_stack(2, 4))


def test_int64_keys_beyond_int32_rejected():
    """Keys >= 2^31 used to be silently truncated by the int32 slot
    assignment (wrapping to a DIFFERENT key and corrupting the fold);
    now the host boundary refuses them."""
    q, _spec = _unit_queue(key_space=2 ** 40)
    with pytest.raises(DeliveryError):
        q.write(0, 0, _batch([(2 ** 31, 1.0, 1)]))
    with pytest.raises(DeliveryError):
        q.write(0, 0, _batch([(-2 ** 31 - 1, 1.0, 1)]))
    # boundary values are fine
    q.write(0, 0, _batch([(2 ** 31 - 1, 1.0, 1)]))
    assert q.writes == 1
