"""Replication over the wire: framed transport, fault injection,
reconnect/backoff, and partition tolerance.

The protocol matrix runs over BOTH transports — the in-process
``LoopbackTransport`` (tier-1, hermetic) and real ``TcpTransport``
sockets (marked ``slow``; the chaos bench soaks TCP further) — through
the same shipping protocol the in-process followers speak. Fault-path
tests drive ``WireFaults``/``FaultyTransport`` deterministically
(scripted partitions/resets and probability-1 rates, never dice), and
the backoff/debounce state machines run on fake clocks with no real
sleeps.
"""

import importlib.util
import json
import os
import time

import numpy as np
import pytest

from reflow_tpu.net import (FaultyTransport, LoopbackTransport,
                            ReconnectPolicy, RemoteFollower,
                            ReplicaServer, TcpTransport, TransportError,
                            WireTimeout)
from reflow_tpu.net.framing import (HEADER, MAGIC, FrameError,
                                    decode_frame, encode_frame,
                                    frame_size, split_frames)
from reflow_tpu.obs import REGISTRY
from reflow_tpu.serve import (FailoverCoordinator, ReadTier,
                              ReplicaScheduler)
from reflow_tpu.utils.faults import WireFaults
from reflow_tpu.wal import DurableScheduler, SegmentShipper
from reflow_tpu.workloads import wordcount

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt
        return self.t


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def make_leader(tmp_path, **kw):
    g, src, sink = wordcount.build_graph()
    sched = DurableScheduler(g, wal_dir=str(tmp_path / "wal"),
                             fsync="tick", **kw)
    return sched, src, sink


def make_replica(tmp_path, name="r0"):
    g, _src, _sink = wordcount.build_graph()
    return ReplicaScheduler(g, str(tmp_path / name), name=name)


def drive(sched, src, n_ticks, seed=0, start=0):
    rng = np.random.default_rng(seed + start)
    for t in range(start, start + n_ticks):
        for j in range(2):
            words = " ".join(
                f"w{int(x)}" for x in rng.integers(0, 40, 8))
            sched.push(src, wordcount.ingest_lines([words]),
                       batch_id=f"t{t}b{j}")
        sched.tick()


def live_view(sched, sink):
    return {kv: w for kv, w in sched.view(sink.name).items() if w != 0}


def fast_policy(name, **kw):
    """Real-clock policy tuned so tests never wait perceptibly."""
    kw.setdefault("base_s", 0.001)
    kw.setdefault("cap_s", 0.005)
    kw.setdefault("jitter", 0.0)
    return ReconnectPolicy(name, **kw)


def pump_until_caught(ship, sched, replicas, timeout_s=20.0):
    """Pump tolerant of link stalls: a remote follower mid-backoff
    makes whole passes report zero progress without being done."""
    sched.wal.sync()
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        ship.pump_once()
        if all(r.published_horizon() == sched._tick for r in replicas):
            return
        time.sleep(0.002)
    raise AssertionError(
        f"replicas stuck: leader tick {sched._tick}, horizons "
        f"{[r.published_horizon() for r in replicas]}")


# -- transports: one matrix, two implementations ----------------------------

TRANSPORTS = [
    "loopback",
    pytest.param("tcp", marks=pytest.mark.slow),
]


def make_transports(kind):
    """(server_transport, client_transport) — loopback must share the
    instance (addresses are process-local), TCP must not."""
    if kind == "loopback":
        t = LoopbackTransport()
        return t, t
    return TcpTransport(), TcpTransport()


# -- framing ----------------------------------------------------------------

def test_frame_round_trip_and_split():
    msgs = [("subscribe",), ("ack", (0, 128), 7),
            ("blob", b"\x00" * 4096)]
    buf = b"".join(encode_frame(m) for m in msgs)
    got, consumed = split_frames(buf)
    assert got == msgs and consumed == len(buf)
    # a partial tail frame stays unconsumed in the buffer
    buf2 = buf + encode_frame(("tail",))[:-3]
    got2, consumed2 = split_frames(buf2)
    assert got2 == msgs and consumed2 == len(buf)


def test_frame_crc_and_magic_are_enforced():
    raw = encode_frame(("hello", 1))
    hdr = len(MAGIC) + HEADER.size
    header, payload = raw[:hdr], raw[hdr:]
    assert frame_size(header) == len(payload)
    assert decode_frame(header, payload) == ("hello", 1)
    flipped = bytearray(payload)
    flipped[-1] ^= 0x01            # payload bit flip: CRC mismatch
    with pytest.raises(FrameError):
        decode_frame(header, bytes(flipped))
    with pytest.raises(FrameError):
        decode_frame(b"XXNOPE00" + header[8:], payload)
    with pytest.raises(FrameError):
        decode_frame(header, payload[:-1])     # truncated payload


# -- transport matrix -------------------------------------------------------

@pytest.mark.parametrize("kind", TRANSPORTS)
def test_transport_round_trip_and_close(kind):
    st, ct = make_transports(kind)
    lst = st.listen()
    conn = ct.connect(lst.address, timeout_s=2.0)
    srv = lst.accept(timeout_s=2.0)
    big = ("payload", b"\xab" * (1 << 20))
    conn.send_msg(big, 2.0)
    assert srv.recv_msg(2.0) == big
    srv.send_msg(("ok",), 2.0)
    assert conn.recv_msg(2.0) == ("ok",)
    srv.close()
    with pytest.raises(TransportError):
        for _ in range(64):        # close may race one buffered frame
            conn.recv_msg(0.2)
    lst.close()


@pytest.mark.parametrize("kind", TRANSPORTS)
def test_transport_idle_timeout_is_wire_timeout(kind):
    st, ct = make_transports(kind)
    lst = st.listen()
    conn = ct.connect(lst.address, timeout_s=2.0)
    srv = lst.accept(timeout_s=2.0)
    t0 = time.monotonic()
    with pytest.raises(WireTimeout):
        conn.recv_msg(0.05)
    assert time.monotonic() - t0 < 5.0
    # an idle timeout is NOT fatal: the link still works afterwards
    srv.send_msg(("late",), 2.0)
    assert conn.recv_msg(2.0) == ("late",)
    conn.close()
    srv.close()
    lst.close()


# -- server/client protocol matrix ------------------------------------------

@pytest.mark.parametrize("kind", TRANSPORTS)
def test_remote_follower_ships_exact_parity(tmp_path, kind):
    st, ct = make_transports(kind)
    sched, src, sink = make_leader(tmp_path, segment_bytes=2048)
    replica = make_replica(tmp_path)
    srv = ReplicaServer(replica, st).start()
    ship = SegmentShipper(sched.wal, leader_tick=lambda: sched._tick)
    link = RemoteFollower(ct, srv.address, name="r0",
                          policy=fast_policy("r0"), io_timeout_s=2.0)
    ship.attach(link)
    drive(sched, src, 8)
    pump_until_caught(ship, sched, [replica])
    h, got = replica.view_at(sink.name)
    assert h == sched._tick
    assert got == live_view(sched, sink)
    assert link.conn_state == "healthy"
    snap = link.transport_snapshot()
    assert snap["state"] == "healthy" and snap["failures"] == 0
    ping = link.ping()
    assert ping["name"] == "r0" and ping["horizon"] == sched._tick
    srv.close()
    sched.close()
    replica.close()


@pytest.mark.parametrize("kind", TRANSPORTS)
def test_server_answers_err_for_unknown_op(kind):
    st, ct = make_transports(kind)
    replica = object()  # never reached by an unknown op
    srv = ReplicaServer(replica, st).start()
    conn = ct.connect(srv.address, timeout_s=2.0)
    conn.send_msg(("warp", 9), 2.0)
    resp = conn.recv_msg(2.0)
    assert resp[0] == "err" and "warp" in resp[1]
    conn.close()
    srv.close()


# -- fault paths (deterministic: scripted switches, probability-1 rates) ----

def _wired_cluster(tmp_path, faults, **link_kw):
    t = LoopbackTransport()
    sched, src, sink = make_leader(tmp_path)
    replica = make_replica(tmp_path)
    srv = ReplicaServer(replica, t).start()
    link_kw.setdefault("policy", fast_policy("r0"))
    link_kw.setdefault("io_timeout_s", 0.2)
    link = RemoteFollower(FaultyTransport(t, faults), srv.address,
                          name="r0", **link_kw)
    ship = SegmentShipper(sched.wal, leader_tick=lambda: sched._tick)
    ship.attach(link)
    return sched, src, sink, replica, srv, link, ship


def test_partition_drives_unreachable_then_heal_resyncs(tmp_path):
    faults = WireFaults()
    sched, src, sink, replica, srv, link, ship = _wired_cluster(
        tmp_path, faults)
    drive(sched, src, 2)
    pump_until_caught(ship, sched, [replica])
    faults.partition("c2s")
    sched_tick_before = replica.published_horizon()
    drive(sched, src, 2, start=2)
    sched.wal.sync()
    deadline = time.monotonic() + 10
    while link.conn_state != "unreachable" \
            and time.monotonic() < deadline:
        ship.pump_once()
        time.sleep(0.002)
    assert link.conn_state == "unreachable"
    assert replica.published_horizon() == sched_tick_before  # no leak
    assert ship.link_stalls > 0 and ship.nacks == 0
    faults.heal()
    pump_until_caught(ship, sched, [replica])
    assert link.conn_state == "healthy"
    assert link.reconnects_total >= 1
    h, got = replica.view_at(sink.name)
    assert h == sched._tick and got == live_view(sched, sink)
    # loss forced the WAL-as-retransmit-buffer path for real
    assert ship.retransmit_bytes > 0
    srv.close()
    sched.close()
    replica.close()


def test_scripted_reset_reconnects_idempotently(tmp_path):
    faults = WireFaults()
    sched, src, sink, replica, srv, link, ship = _wired_cluster(
        tmp_path, faults)
    drive(sched, src, 3)
    pump_until_caught(ship, sched, [replica])
    before = live_view(sched, sink)
    faults.reset_once(1)
    drive(sched, src, 3, start=3)
    pump_until_caught(ship, sched, [replica])
    assert link.reconnects_total >= 1
    h, got = replica.view_at(sink.name)
    assert h == sched._tick and got == live_view(sched, sink)
    assert got != before  # the post-reset windows actually landed
    srv.close()
    sched.close()
    replica.close()


def test_corrupt_payload_is_nacked_by_record_crc(tmp_path):
    # frame CRC passes (the flip happens before framing); the replica's
    # record-level CRC must reject the shipment and NACK its cursor
    faults = WireFaults()
    sched, src, sink, replica, srv, link, ship = _wired_cluster(
        tmp_path, faults)
    drive(sched, src, 2)
    pump_until_caught(ship, sched, [replica])
    faults.set_rates(corrupt_payload=1.0)
    drive(sched, src, 2, start=2)
    sched.wal.sync()
    deadline = time.monotonic() + 10
    while ship.nacks == 0 and time.monotonic() < deadline:
        ship.pump_once()
        time.sleep(0.002)
    assert ship.nacks >= 1
    faults.quiesce()
    pump_until_caught(ship, sched, [replica])
    h, got = replica.view_at(sink.name)
    assert h == sched._tick and got == live_view(sched, sink)
    srv.close()
    sched.close()
    replica.close()


def test_corrupt_frame_resets_connection_then_recovers(tmp_path):
    faults = WireFaults()
    sched, src, sink, replica, srv, link, ship = _wired_cluster(
        tmp_path, faults)
    drive(sched, src, 2)
    pump_until_caught(ship, sched, [replica])
    faults.set_rates(corrupt_frame=1.0)
    drive(sched, src, 2, start=2)
    sched.wal.sync()
    deadline = time.monotonic() + 10
    while link.link_failures == 0 and time.monotonic() < deadline:
        ship.pump_once()
        time.sleep(0.002)
    assert link.link_failures >= 1      # desynced stream = link failure
    faults.quiesce()
    pump_until_caught(ship, sched, [replica])
    assert srv.frame_resets >= 1
    h, got = replica.view_at(sink.name)
    assert h == sched._tick and got == live_view(sched, sink)
    srv.close()
    sched.close()
    replica.close()


def test_duplicates_and_reorders_never_skew_state(tmp_path):
    # every ack/nack carries the receiver's authoritative cursor, so a
    # mis-paired response is still a true statement — parity must hold
    faults = WireFaults()
    sched, src, sink, replica, srv, link, ship = _wired_cluster(
        tmp_path, faults)
    faults.set_rates(dup=0.5, reorder=0.5)
    drive(sched, src, 6)
    sched.wal.sync()
    deadline = time.monotonic() + 20
    while replica.published_horizon() != sched._tick \
            and time.monotonic() < deadline:
        ship.pump_once()
        time.sleep(0.002)
    faults.quiesce()
    pump_until_caught(ship, sched, [replica])
    assert faults.stats["dup"] + faults.stats["reorder"] > 0
    h, got = replica.view_at(sink.name)
    assert h == sched._tick and got == live_view(sched, sink)
    srv.close()
    sched.close()
    replica.close()


def test_drop_s2c_applies_but_retransmits(tmp_path):
    # a dropped RESPONSE means the server applied and the client never
    # heard: the re-offer is counted as retransmission and the dedup/
    # cursor machinery keeps the replay exactly-once
    faults = WireFaults()
    sched, src, sink, replica, srv, link, ship = _wired_cluster(
        tmp_path, faults)
    drive(sched, src, 2)
    pump_until_caught(ship, sched, [replica])
    faults.set_rates(drop_s2c=1.0)
    drive(sched, src, 2, start=2)
    sched.wal.sync()
    for _ in range(8):
        ship.pump_once()
        time.sleep(0.002)
    faults.quiesce()
    pump_until_caught(ship, sched, [replica])
    assert faults.stats["drop_s2c"] >= 1
    assert ship.retransmit_bytes > 0
    h, got = replica.view_at(sink.name)
    assert h == sched._tick and got == live_view(sched, sink)
    srv.close()
    sched.close()
    replica.close()


# -- backoff state machine (fake clock, no sleeps) --------------------------

def test_backoff_growth_caps_and_states():
    clk = FakeClock()
    p = ReconnectPolicy("r0", base_s=0.1, cap_s=0.8, jitter=0.0,
                        degraded_after=1, unreachable_after=4,
                        clock=clk)
    assert p.state == "connecting"
    delays = [p.failed() for _ in range(6)]
    assert delays == [0.1, 0.2, 0.4, 0.8, 0.8, 0.8]  # 2^n capped
    assert p.state == "unreachable"
    assert not p.due()                      # gated until the clock moves
    assert p.seconds_until_due() == pytest.approx(0.8)
    clk.advance(0.8)
    assert p.due()
    assert p.ok() is True                   # a failure run just ended
    assert p.state == "healthy" and p.failures == 0
    assert p.reconnects == 1
    assert p.ok() is False                  # steady-state ok: no event
    snap = p.snapshot()
    assert snap["state"] == "healthy" and snap["reconnects"] == 1


def test_backoff_jitter_is_bounded_and_seeded():
    clk = FakeClock()
    a = ReconnectPolicy("r0", base_s=0.1, cap_s=10.0, jitter=0.25,
                        seed=7, clock=clk)
    b = ReconnectPolicy("r0", base_s=0.1, cap_s=10.0, jitter=0.25,
                        seed=7, clock=clk)
    da = [a.failed() for _ in range(8)]
    db = [b.failed() for _ in range(8)]
    assert da == db                          # same seed+name: same storm
    for i, d in enumerate(da):
        raw = min(10.0, 0.1 * 2 ** i)
        assert raw * 0.75 <= d <= raw * 1.25
    c = ReconnectPolicy("r1", base_s=0.1, cap_s=10.0, jitter=0.25,
                        seed=7, clock=clk)
    assert [c.failed() for _ in range(8)] != da  # per-name decorrelated


def test_backoff_degraded_threshold_and_recovery_cycle():
    clk = FakeClock()
    p = ReconnectPolicy("r0", base_s=0.05, cap_s=1.0, jitter=0.0,
                        degraded_after=2, unreachable_after=3,
                        clock=clk)
    p.failed()
    assert p.state == "connecting"      # below degraded_after, no flap
    p.failed()
    assert p.state == "degraded"
    p.failed()
    assert p.state == "unreachable"
    clk.advance(10)
    p.ok()
    assert p.state == "healthy"
    p.failed()
    # one failure below degraded_after: still nominally healthy, and
    # the backoff growth restarted from base
    assert p.state == "healthy" and p.failures == 1
    assert p.last_backoff_s == pytest.approx(0.05)


# -- partition detection (fake clock, _stub_coord style) --------------------

class _StubReplica:
    def __init__(self, name, horizon):
        self.name = name
        self._h = horizon
        self.promoted = False

    def published_horizon(self):
        return self._h


def _stub_coord(sample, **kw):
    calls = []

    def promote_fn(winner, epoch):
        calls.append((winner.name, epoch))
        return object()

    kw.setdefault("confirm_intervals", 2)
    coord = FailoverCoordinator(
        [_StubReplica("a", 5), _StubReplica("b", 7)],
        sampler=sample, promote_fn=promote_fn, **kw)
    return coord, calls


def test_partitioned_sample_fires_debounced():
    clk = FakeClock()
    part = {"v": False}
    coord, calls = _stub_coord(
        lambda now: {"committer_dead": False, "pump_failed": False,
                     "beat": 1, "partitioned": part["v"]})
    assert coord.step(clk.advance(0.05)) == []
    part["v"] = True
    assert coord.step(clk.advance(0.05)) == []        # streak 1 of 2
    acts = coord.step(clk.advance(0.05))              # streak 2: fire
    assert [a["kind"] for a in acts] == ["failover_promote"]
    assert acts[0]["reason"] == "leader_partitioned"
    assert calls == [("b", 1)]
    assert coord.partitions_detected == 1


def test_partition_flapping_never_fires():
    clk = FakeClock()
    seq = iter([True, False] * 10)
    coord, calls = _stub_coord(
        lambda now: {"committer_dead": False, "pump_failed": False,
                     "beat": 1, "partitioned": next(seq)})
    for _ in range(10):
        assert coord.step(clk.advance(0.05)) == []
    assert calls == [] and coord.partitions_detected == 0


def test_heartbeat_stall_with_live_committer_is_partition():
    # a stalled beat while the committer provably lives is a partition,
    # not a death — the reason must say so (the bare-stall label
    # "heartbeat_timeout" is pinned by test_failover)
    clk = FakeClock()
    coord, calls = _stub_coord(
        lambda now: {"committer_dead": False, "pump_failed": False,
                     "beat": 1, "committer_alive": True},
        heartbeat_timeout_s=0.2, confirm_intervals=2)
    coord.step(clk.advance(0.05))
    coord.step(clk.advance(0.3))                      # stale: streak 1
    acts = coord.step(clk.advance(0.3))               # streak 2: fire
    assert acts[0]["reason"] == "leader_partitioned"
    assert coord.partitions_detected == 1


# -- read tier ejection / restore -------------------------------------------

class _FakeLink:
    def __init__(self, state="healthy"):
        self.conn_state = state


class _FakeReplica:
    def __init__(self, name, horizon=10, fail=None):
        self.name = name
        self._h = horizon
        self.fail = fail
        self.reads = 0

    def published_horizon(self):
        return self._h

    def lag_ticks(self):
        return 0

    def top_k(self, sink, k, by="weight"):
        if self.fail is not None:
            raise self.fail
        self.reads += 1
        return self._h, [((sink, "x"), 1.0)]


def test_read_tier_ejects_unreachable_link_and_restores():
    r0, r1 = _FakeReplica("r0"), _FakeReplica("r1")
    link = _FakeLink()
    tier = ReadTier([r0, r1])
    tier.bind_link(r0, link)
    link.conn_state = "unreachable"
    for _ in range(4):
        res = tier.top_k("s", 1)
        assert res.source == "r1"
    assert tier.ejects == 1
    assert any(r is r0 for r in tier.ejected_replicas)
    assert r0.reads == 0
    link.conn_state = "healthy"
    sources = {tier.top_k("s", 1).source for _ in range(4)}
    assert sources == {"r0", "r1"}        # restored into rotation
    assert tier.restores == 1


def test_read_tier_ejects_on_link_flavored_read_error():
    r0 = _FakeReplica("r0", fail=ConnectionError("peer gone"))
    r1 = _FakeReplica("r1")
    tier = ReadTier([r0, r1])
    tier.bind_link(r0, _FakeLink("unreachable"))
    res = tier.top_k("s", 1)
    assert res.source == "r1" and tier.ejects == 1
    # a StaleRead-path value error still propagates (not link-flavored)
    r1.fail = ValueError("boom")
    with pytest.raises(ValueError):
        tier.top_k("s", 1)


# -- observability surfaces --------------------------------------------------

def test_conn_state_gauges_and_transport_sidecar(tmp_path):
    t = LoopbackTransport()
    sched, src, sink = make_leader(tmp_path)
    replica = make_replica(tmp_path)
    srv = ReplicaServer(replica, t).start()
    ship = SegmentShipper(sched.wal, leader_tick=lambda: sched._tick)
    link = RemoteFollower(t, srv.address, name="r0",
                          policy=fast_policy("r0"), io_timeout_s=0.5)
    ship.attach(link)
    ship.publish_metrics()
    try:
        drive(sched, src, 3)
        pump_until_caught(ship, sched, [replica])
        assert REGISTRY.value("replica.r0.conn_state", "?") == "healthy"
        assert REGISTRY.value("net.reconnects_total", -1) == 0
        assert REGISTRY.value("net.retransmit_bytes", -1) >= 0

        state = json.load(
            open(os.path.join(sched.wal.wal_dir, "ship-state.json")))
        assert state["transport"]["r0"]["state"] == "healthy"

        wi = _load_tool("wal_inspect")
        summary = wi.inspect(sched.wal.wal_dir, verbose=False)
        tsec = summary["shipping"]["transport"]
        assert tsec["r0"]["state"] == "healthy"
        assert tsec["r0"]["reconnects"] == 0
        assert tsec["r0"]["retransmit_bytes"] == 0
        assert "last_backoff_s" in tsec["r0"]
    finally:
        ship.close()
        srv.close()
        sched.close()
        replica.close()


def test_net_trace_spans_surface_in_trace_inspect(tmp_path, capsys):
    from reflow_tpu import obs
    obs.enable()
    try:
        t = LoopbackTransport()
        sched, src, sink = make_leader(tmp_path)
        replica = make_replica(tmp_path)
        srv = ReplicaServer(replica, t).start()
        ship = SegmentShipper(sched.wal,
                              leader_tick=lambda: sched._tick)
        link = RemoteFollower(t, srv.address, name="r0",
                              policy=fast_policy("r0"),
                              io_timeout_s=0.5)
        ship.attach(link)
        drive(sched, src, 3)
        pump_until_caught(ship, sched, [replica])
        path = str(tmp_path / "trace.json")
        obs.export_chrome_trace(path)
        ti = _load_tool("trace_inspect")
        assert ti.main([path, "--json"]) == 0
        out = json.loads(capsys.readouterr().out)
        net = out["network"]["r0"]
        assert net["sends"] >= 1 and net["send_failures"] == 0
        assert "receive" in net["ops"]
        assert net["last_state"] == "healthy"
        ship.close()
        srv.close()
        sched.close()
        replica.close()
    finally:
        obs.disable()


# -- protocol responses remain the shipping tuples --------------------------

def test_remote_follower_receive_speaks_ack_nack(tmp_path):
    t = LoopbackTransport()
    sched, src, sink = make_leader(tmp_path)
    replica = make_replica(tmp_path)
    srv = ReplicaServer(replica, t).start()
    ship = SegmentShipper(sched.wal, leader_tick=lambda: sched._tick)
    link = RemoteFollower(t, srv.address, name="r0",
                          policy=fast_policy("r0"), io_timeout_s=0.5)
    cur = link.subscribe()
    assert cur is None or isinstance(cur, tuple)
    drive(sched, src, 1)
    sched.wal.sync()
    ship.attach(link)
    deadline = time.monotonic() + 10
    while replica.published_horizon() != sched._tick \
            and time.monotonic() < deadline:
        ship.pump_once()
        time.sleep(0.002)
    # the link's receive() really returned ShipAck objects to the
    # shipper (cursor advanced past subscribe, zero nacks)
    st = ship._followers["r0"]
    assert st.nacks == 0 and st.cursor is not None
    assert replica.published_horizon() == sched._tick
    srv.close()
    sched.close()
    replica.close()
