"""Incremental checkpoint chains (``utils.checkpoint.CheckpointChain``):
base-plus-deltas restore parity, the lag-one WAL truncation contract (a
torn FINAL delta falls back one element and replays its window from the
log; a broken mid-chain link fails loud), differential crash tests at
the manifest-flip seams, replica bootstrap from a leader chain, and the
tier-wide checkpoint barrier — one consistent macro-tick cut across
every graph in a ServeTier."""

import os

import numpy as np
import pytest

from reflow_tpu import DirtyScheduler
from reflow_tpu.graph import GraphError
from reflow_tpu.serve import (CoalesceWindow, GraphConfig, ReplicaScheduler,
                              ServeTier)
from reflow_tpu.utils.checkpoint import (CheckpointChain, CheckpointError,
                                         chain_head_wal_pos,
                                         checkpoint_exists, load_chain,
                                         load_checkpoint,
                                         read_chain_manifest)
from reflow_tpu.utils.faults import CrashInjector, CrashPoint
from reflow_tpu.wal import DurableScheduler, SegmentShipper, recover
from reflow_tpu.wal.log import list_segments
from reflow_tpu.workloads import wordcount

WINDOW = CoalesceWindow(max_rows=256, max_ticks=8, max_latency_s=0.002)


def make_leader(tmp_path, **kw):
    g, src, sink = wordcount.build_graph()
    kw.setdefault("segment_bytes", 1 << 12)
    sched = DurableScheduler(g, wal_dir=str(tmp_path / "wal"),
                             fsync="tick", **kw)
    return sched, src, sink


def drive(sched, src, n_ticks, seed=0, start=0):
    rng = np.random.default_rng(seed + start)
    for t in range(start, start + n_ticks):
        for j in range(2):
            words = " ".join(f"w{int(x)}" for x in rng.integers(0, 40, 8))
            sched.push(src, wordcount.ingest_lines([words]),
                       batch_id=f"t{t}b{j}")
        sched.tick()


def fresh_view(tmp_path, ckpt_dir=None):
    """Recover a fresh scheduler from the leader's WAL (+ chain) and
    return (view, tick, report)."""
    g, _src, sink = wordcount.build_graph()
    sched = DirtyScheduler(g)
    rep = recover(sched, str(tmp_path / "wal"), ckpt_dir)
    return dict(sched.view(sink.name)), sched._tick, rep


# -- save/restore parity ----------------------------------------------------

def test_chain_full_delta_restore_parity(tmp_path):
    sched, src, sink = make_leader(tmp_path)
    root = str(tmp_path / "ckpt")
    chain = CheckpointChain(root, delta_every=4)
    infos = []
    for r in range(8):
        drive(sched, src, 3, start=3 * r)
        infos.append(chain.save(sched))
    want = dict(sched.view(sink.name))
    tick = sched._tick
    ids = dict(sched._seen_batch_ids)
    sched.close()
    # save cadence: first save full, then delta_every-1 deltas per full
    assert [i["kind"] for i in infos[:5]] \
        == ["full", "delta", "delta", "delta", "full"]
    assert chain.fulls == 2 and chain.deltas == 6
    m = read_chain_manifest(root)
    assert m["horizon"] == tick and len(m["deltas"]) == 3
    assert checkpoint_exists(root)
    g2, _s2, sink2 = wordcount.build_graph()
    sched2 = DirtyScheduler(g2)
    meta = load_chain(sched2, root)
    assert meta["chain"]["deltas_applied"] == 3
    assert meta["chain"]["fallback"] is None
    assert dict(sched2.view(sink2.name)) == want
    assert sched2._tick == tick
    assert dict(sched2._seen_batch_ids) == ids  # exactly-once horizon
    # load_checkpoint dispatches on the chain manifest transparently
    g3, _s3, sink3 = wordcount.build_graph()
    sched3 = DirtyScheduler(g3)
    assert load_checkpoint(sched3, root)["tick"] == tick
    assert dict(sched3.view(sink3.name)) == want


def test_chain_recover_replays_post_anchor_tail(tmp_path):
    # ticks after the last chain element live only in the WAL; recover
    # must restore the chain then replay exactly that window
    sched, src, sink = make_leader(tmp_path)
    root = str(tmp_path / "ckpt")
    chain = CheckpointChain(root, delta_every=3)
    drive(sched, src, 5)
    chain.save(sched)
    drive(sched, src, 4, start=5)
    chain.save(sched)
    drive(sched, src, 6, start=9)      # un-checkpointed tail
    want = dict(sched.view(sink.name))
    tick = sched._tick
    sched.close()
    got, got_tick, rep = fresh_view(tmp_path, root)
    assert got == want and got_tick == tick
    assert rep.checkpoint_loaded and rep.checkpoint_tick == 9
    assert rep.replayed_ticks == 6
    # lag-one truncation bounded the log: segments before the PREVIOUS
    # element's anchor are gone
    anchor = chain_head_wal_pos(root)
    segs = [s for s, _ in list_segments(str(tmp_path / "wal"))]
    assert segs and segs[-1] >= anchor[0]


def test_torn_final_delta_falls_back_one_element(tmp_path):
    # the torn tail of the CHAIN: restore falls back one element and
    # the WAL window the lag-one truncation kept replays the gap
    sched, src, sink = make_leader(tmp_path)
    root = str(tmp_path / "ckpt")
    chain = CheckpointChain(root, delta_every=8)
    drive(sched, src, 4)
    chain.save(sched)
    drive(sched, src, 4, start=4)
    chain.save(sched)
    drive(sched, src, 4, start=8)
    chain.save(sched)
    want = dict(sched.view(sink.name))
    tick = sched._tick
    sched.close()
    last = read_chain_manifest(root)["deltas"][-1]
    path = os.path.join(root, last)
    with open(path, "r+b") as f:
        f.truncate(os.path.getsize(path) - 7)
    g2, _s2, _k2 = wordcount.build_graph()
    meta = load_chain(DirtyScheduler(g2), root)
    assert meta["chain"]["fallback"] is not None
    assert meta["chain"]["deltas_applied"] == 1  # fell back one link
    got, got_tick, rep = fresh_view(tmp_path, root)
    assert got == want and got_tick == tick
    assert rep.replayed_ticks == 4  # the torn element's window, from WAL


def test_broken_mid_chain_link_fails_loud(tmp_path):
    # corruption anywhere NOT at the tail is real damage: no silent
    # partial restore, no guessing — CheckpointError
    sched, src, _sink = make_leader(tmp_path)
    root = str(tmp_path / "ckpt")
    chain = CheckpointChain(root, delta_every=8)
    for r in range(3):
        drive(sched, src, 3, start=3 * r)
        chain.save(sched)
    sched.close()
    first_delta = read_chain_manifest(root)["deltas"][0]
    with open(os.path.join(root, first_delta), "r+b") as f:
        f.seek(12)
        f.write(b"\xff\xff\xff")
    g2, _s2, _k2 = wordcount.build_graph()
    with pytest.raises(CheckpointError):
        load_chain(DirtyScheduler(g2), root)
    os.remove(os.path.join(root, first_delta))
    g3, _s3, _k3 = wordcount.build_graph()
    with pytest.raises(CheckpointError):
        load_chain(DirtyScheduler(g3), root)


# -- crash seams ------------------------------------------------------------

@pytest.mark.parametrize("seam,full_crash", [
    ("ckpt_full_before_flip", True),
    ("ckpt_delta_before_flip", False),
    ("ckpt_delta_after_flip", False),
])
def test_chain_crash_seam_differential(tmp_path, seam, full_crash):
    # kill a save at each manifest seam: before the flip the OLD chain
    # plus its replay tail must reconstruct the crash-time state; after
    # the flip the NEW one must (truncation lags, replay dedups)
    # each seam occurs once in the two setup saves (full #1 + delta #2)
    # and once in the killed save below — at=2 targets the latter
    crash = CrashInjector(2, only=seam)
    sched, src, sink = make_leader(tmp_path)
    root = str(tmp_path / "ckpt")
    chain = CheckpointChain(root, delta_every=4, crash=crash)
    drive(sched, src, 4)
    chain.save(sched)                      # full #1
    drive(sched, src, 4, start=4)
    chain.save(sched)                      # delta #2
    drive(sched, src, 4, start=8)
    want = dict(sched.view(sink.name))
    tick = sched._tick
    with pytest.raises(CrashPoint):
        chain.save(sched, full=full_crash)
    sched.close()
    got, got_tick, rep = fresh_view(tmp_path, root)
    assert got == want and got_tick == tick, f"{seam}: diverged"
    assert rep.checkpoint_loaded


# -- replica bootstrap from a leader chain ----------------------------------

def test_replica_bootstrap_from_chain_dir(tmp_path):
    # a fresh replica attaching to a chain-checkpointed leader must
    # bootstrap O(state) — chain restore + compacted/short tail — and
    # land on exact view parity
    sched, src, sink = make_leader(tmp_path)
    root = str(tmp_path / "ckpt")
    chain = CheckpointChain(root, delta_every=4)
    ship = SegmentShipper(sched.wal, ckpt_dir=root,
                          leader_tick=lambda: sched._tick)
    for r in range(4):
        drive(sched, src, 3, start=3 * r)
        chain.save(sched)
    drive(sched, src, 3, start=12)
    sched.wal.sync()
    g2, _s2, sink2 = wordcount.build_graph()
    replica = ReplicaScheduler(g2, str(tmp_path / "r0"), name="r0")
    ship.attach(replica)
    assert replica.bootstraps == 1
    for _ in range(200):
        ship.pump_once()
        if replica.published_horizon() == sched._tick:
            break
    h, got = replica.view_at(sink2.name)
    want = {kv: w for kv, w in sched.view(sink.name).items() if w != 0}
    assert h == sched._tick and got == want
    # the replica restored through the chain, not by full-history replay
    assert replica.restored_from is not None or replica.bootstraps == 1
    sched.close()


# -- tier-wide checkpoint barrier -------------------------------------------

def test_tier_checkpoint_barrier_consistent_cut(tmp_path):
    tier = ServeTier(max_bytes=1 << 20, pump_threads=2)
    handles = {}
    for i in range(3):
        g, src, sink = wordcount.build_graph()
        sched = DirtyScheduler(g)
        h = tier.register(f"g{i}", sched, GraphConfig(window=WINDOW))
        handles[f"g{i}"] = (h, src, sink, sched)
    for name, (h, src, _sink, _sched) in handles.items():
        for j in range(6):
            h.submit(src, wordcount.ingest_lines([f"{name} w{j}"])) \
                .result(timeout=10)
        h.flush(timeout=10)
    chains = {n: CheckpointChain(str(tmp_path / n), delta_every=4)
              for n in handles}

    def saver(name, h):
        return chains[name].save(h.frontend.sched)

    out = tier.checkpoint_barrier(saver)
    assert out["barrier"] == 1 and tier.barriers == 1
    assert set(out["horizons"]) == set(handles)
    for name, (h, _src, sink, sched) in handles.items():
        # the recorded horizon is the quiesced macro-tick cut, and the
        # chain manifest agrees with it
        assert out["horizons"][name] == sched._tick
        assert read_chain_manifest(str(tmp_path / name))["horizon"] \
            == sched._tick
        assert out["results"][name]["kind"] == "full"
        g2, _s2, sink2 = wordcount.build_graph()
        s2 = DirtyScheduler(g2)
        load_chain(s2, str(tmp_path / name))
        assert dict(s2.view(sink2.name)) == dict(sched.view(sink.name))
    # the tier keeps serving after the barrier
    for name, (h, src, _sink, _sched) in handles.items():
        assert h.submit(src, wordcount.ingest_lines(["after barrier"])) \
            .result(timeout=10).applied
    tier.close()


def test_tier_checkpoint_barrier_resumes_after_saver_error(tmp_path):
    tier = ServeTier(max_bytes=1 << 20, pump_threads=1)
    g, src, _sink = wordcount.build_graph()
    h = tier.register("g", DirtyScheduler(g), GraphConfig(window=WINDOW))
    h.submit(src, wordcount.ingest_lines(["a b"])).result(timeout=10)

    def bad_saver(name, handle):
        raise RuntimeError("disk full")

    with pytest.raises(RuntimeError, match="disk full"):
        tier.checkpoint_barrier(bad_saver)
    # every frontend was resumed on the way out
    assert h.submit(src, wordcount.ingest_lines(["c d"])) \
        .result(timeout=10).applied
    tier.close()
    with pytest.raises(GraphError):
        tier.checkpoint_barrier(lambda n, hh: None)
