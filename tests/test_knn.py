"""k-NN re-index (config 4): kernel parity, CPU-vs-TPU differential, and
the incremental insert path vs the full-rescan path."""

import numpy as np
import pytest

from reflow_tpu import DeltaBatch, DirtyScheduler
from reflow_tpu.executors import CpuExecutor, get_executor
from reflow_tpu.workloads import knn

Q, D, DIM, K = 16, 256, 32, 4


def _drive(executor, seed=0, retract=True):
    kg = knn.build_graph(Q, D, DIM, K, scan_chunk=D)
    sched = DirtyScheduler(kg.graph, executor)
    store = knn.EmbeddingStore.create(DIM, seed=seed)
    rng = np.random.default_rng(seed + 100)
    qvecs = rng.normal(size=(Q, DIM)).astype(np.float32)
    sched.push(kg.queries, DeltaBatch(np.arange(Q), qvecs))
    sched.push(kg.docs, store.insert_batch(np.arange(0, 64)))
    sched.tick()
    # pure insert tick (incremental path on device)
    sched.push(kg.docs, store.insert_batch(np.arange(64, 128)))
    sched.tick()
    if retract:
        # retraction tick (full rescan path on device)
        sched.push(kg.docs, store.retract_batch(np.arange(10, 30)))
        sched.tick()
        sched.push(kg.docs, store.insert_batch(np.arange(128, 160)))
        sched.tick()
    return sched, kg, store, qvecs


def _ids_table(sched, kg):
    return {q: row[:, 0].astype(np.int64)
            for q, row in sched.read_table(kg.index).items()}


def test_cpu_matches_bruteforce_oracle():
    sched, kg, store, qvecs = _drive(CpuExecutor())
    ref_ids, _ = store.reference_topk(qvecs, K)
    table = _ids_table(sched, kg)
    for q in range(Q):
        np.testing.assert_array_equal(table[q], ref_ids[q])


def test_tpu_matches_bruteforce_oracle():
    sched, kg, store, qvecs = _drive(get_executor("tpu"))
    ref_ids, ref_s = store.reference_topk(qvecs, K)
    table = _ids_table(sched, kg)
    for q in range(Q):
        np.testing.assert_array_equal(table[q], ref_ids[q])


def test_cpu_tpu_views_match():
    s_cpu, kg_cpu, _, _ = _drive(CpuExecutor(), seed=3)
    s_tpu, kg_tpu, _, _ = _drive(get_executor("tpu"), seed=3)
    t_cpu = s_cpu.read_table(kg_cpu.index)
    t_tpu = s_tpu.read_table(kg_tpu.index)
    assert set(t_cpu) == set(t_tpu)
    for q in t_cpu:
        np.testing.assert_array_equal(
            t_cpu[q][:, 0].astype(np.int64),
            t_tpu[q][:, 0].astype(np.int64))
        np.testing.assert_allclose(t_cpu[q][:, 1], t_tpu[q][:, 1],
                                   atol=1e-5)


def test_incremental_vs_full_oracle_property():
    """Rebuilding from scratch on the accumulated corpus equals the
    incrementally maintained index (SURVEY.md §4b, for knn)."""
    sched, kg, store, qvecs = _drive(get_executor("tpu"), seed=7)
    # fresh graph fed the *current* corpus in one shot
    kg2 = knn.build_graph(Q, D, DIM, K, scan_chunk=D)
    sched2 = DirtyScheduler(kg2.graph, get_executor("tpu"))
    sched2.push(kg2.queries, DeltaBatch(np.arange(Q),
                                        qvecs))
    ids = np.array(sorted(store.vecs), np.int64)
    vals = np.stack([store.vecs[int(i)] for i in ids])
    sched2.push(kg2.docs, DeltaBatch(ids, vals))
    sched2.tick()
    a, b = _ids_table(sched, kg), _ids_table(sched2, kg2)
    assert set(a) == set(b)
    for q in a:
        np.testing.assert_array_equal(a[q], b[q])


def test_query_retraction_removes_row():
    ex = get_executor("tpu")
    sched, kg, store, qvecs = _drive(ex, retract=False)
    sink_view_before = len(sched.read_table(kg.index))
    assert sink_view_before == Q
    sched.push(kg.queries, DeltaBatch(np.arange(3), qvecs[:3],
                                      -np.ones(3, np.int64)))
    sched.tick()
    assert len(sched.read_table(kg.index)) == Q - 3


def test_sharded_knn_matches_single_device():
    """VERDICT r2 item 7: corpus row-sharded k-NN on the 8-device mesh —
    per-shard chunked scan + all_gather candidate merge — must reproduce
    the single-device tables exactly (incremental AND rescan paths)."""
    from reflow_tpu.parallel import make_mesh
    from reflow_tpu.parallel.shard import ShardedTpuExecutor

    mesh = make_mesh(8)
    s_sh, kg_sh, store, qvecs = _drive(ShardedTpuExecutor(mesh), seed=6)
    s_tp, kg_tp, _, _ = _drive(get_executor("tpu"), seed=6)
    t_sh = s_sh.read_table(kg_sh.index)
    t_tp = s_tp.read_table(kg_tp.index)
    assert set(t_sh) == set(t_tp)
    for q in t_tp:
        a, b = np.asarray(t_sh[q]), np.asarray(t_tp[q])
        np.testing.assert_array_equal(a[:, 0], b[:, 0])  # ids exact
        # scores: per-shard contraction order differs by ~1 ulp
        np.testing.assert_allclose(a[:, 1], b[:, 1], rtol=1e-5)
    ref_ids, _ = store.reference_topk(qvecs, K)
    for q in range(Q):
        np.testing.assert_array_equal(
            np.asarray(t_sh[q])[:, 0].astype(np.int64), ref_ids[q])


def test_bf16_embeddings_high_recall():
    """bf16 embedding storage (halved HBM + halved per-tick upload, the
    bandwidth-bound cost of config 4) must keep near-perfect recall vs
    the f32 brute-force oracle — scoring still accumulates in f32."""
    import jax.numpy as jnp

    kg = knn.build_graph(Q, D, DIM, K, scan_chunk=D,
                         dtype=jnp.bfloat16, precision="default")
    sched = DirtyScheduler(kg.graph, get_executor("tpu"))
    store = knn.EmbeddingStore.create(DIM, seed=3)
    rng = np.random.default_rng(103)
    qvecs = rng.normal(size=(Q, DIM)).astype(np.float32)
    sched.push(kg.queries, DeltaBatch(np.arange(Q), qvecs))
    sched.push(kg.docs, store.insert_batch(np.arange(0, 64)))
    sched.tick()
    sched.push(kg.docs, store.insert_batch(np.arange(64, 160)))
    sched.tick()

    ref_ids, ref_s = store.reference_topk(qvecs, K)
    table = _ids_table(sched, kg)
    hits = total = 0
    for q in range(Q):
        hits += len(set(table[q]) & set(ref_ids[q]))
        total += K
    assert hits / total >= 0.95, f"bf16 recall {hits/total:.3f}"


def test_in_place_doc_update_matches_oracle():
    """Re-inserting a LIVE doc id with a new vector is an in-place
    update; the stale score may sit in emitted top-k rows, so the device
    path must take the full rescan (the incremental merge would keep the
    stale candidate alive forever)."""
    kg = knn.build_graph(Q, D, DIM, K, scan_chunk=D)
    sched = DirtyScheduler(kg.graph, get_executor("tpu"))
    store = knn.EmbeddingStore.create(DIM, seed=9)
    rng = np.random.default_rng(109)
    qvecs = rng.normal(size=(Q, DIM)).astype(np.float32)
    sched.push(kg.queries, DeltaBatch(np.arange(Q), qvecs))
    sched.push(kg.docs, store.insert_batch(np.arange(0, 64)))
    sched.tick()
    # overwrite docs 0..16 with fresh vectors via plain inserts
    sched.push(kg.docs, store.insert_batch(np.arange(0, 16)))
    sched.tick()
    ref_ids, _ = store.reference_topk(qvecs, K)
    table = _ids_table(sched, kg)
    for q in range(Q):
        np.testing.assert_array_equal(table[q], ref_ids[q])


def test_device_retraction_never_consults_values():
    """ADVICE r3: bench config 4 fabricates ZERO-valued retraction rows,
    relying on the device lowering's contract that a doc retraction only
    clears the live bit (lowerings._fold_vectors) and never reads the
    row's value. Pin that contract: retracting with garbage (NaN) values
    must behave exactly like retracting with the true vectors."""
    ex_true = get_executor("tpu")
    ex_junk = get_executor("tpu")
    tables = []
    for ex, junk in ((ex_true, False), (ex_junk, True)):
        kg = knn.build_graph(Q, D, DIM, K, scan_chunk=D)
        sched = DirtyScheduler(kg.graph, ex)
        store = knn.EmbeddingStore.create(DIM, seed=9)
        rng = np.random.default_rng(42)
        qvecs = rng.normal(size=(Q, DIM)).astype(np.float32)
        sched.push(kg.queries, DeltaBatch(np.arange(Q), qvecs))
        sched.push(kg.docs, store.insert_batch(np.arange(0, 96)))
        sched.tick()
        ids = np.arange(16, 48)
        if junk:
            vals = np.full((len(ids), DIM), np.nan, np.float32)
            batch = DeltaBatch(ids, vals, -np.ones(len(ids), np.int64))
        else:
            batch = store.retract_batch(ids)
        sched.push(kg.docs, batch)
        sched.tick()
        tables.append(sched.read_table(kg.index))
    a, b = tables
    assert set(a) == set(b)
    for q in a:
        np.testing.assert_array_equal(np.asarray(a[q]), np.asarray(b[q]))


def test_int8_embeddings_high_recall():
    """int8 quantized ingest (VERDICT r4 #3a): round(unit_vec * 127) on
    the wire — 1 byte/dim, halving the upload AGAIN vs bf16 — must keep
    near-perfect recall vs the f64 brute-force oracle. Scoring
    dequantizes to bf16 on chip (kernels.topk.score_form); retractions
    and in-place updates exercise both the rescan and incremental
    paths at int8."""
    import jax.numpy as jnp

    kg = knn.build_graph(Q, D, DIM, K, scan_chunk=D,
                         doc_dtype=jnp.int8, precision="default")
    sched = DirtyScheduler(kg.graph, get_executor("tpu"))
    store = knn.EmbeddingStore.create(DIM, seed=5)
    rng = np.random.default_rng(105)
    qvecs = rng.normal(size=(Q, DIM)).astype(np.float32)
    sched.push(kg.queries, DeltaBatch(np.arange(Q), qvecs))
    sched.push(kg.docs, store.insert_batch(np.arange(0, 64),
                                           quantize=True))
    sched.tick()
    # incremental insert path at int8
    sched.push(kg.docs, store.insert_batch(np.arange(64, 160),
                                           quantize=True))
    sched.tick()
    # retraction (full rescan path at int8): wire replays the SAME
    # quantized rows
    gone = np.arange(10, 20)
    raw = np.stack([store.vecs.pop(int(i)) for i in gone])
    sched.push(kg.docs, DeltaBatch(gone.astype(np.int64),
                                   knn.quantize_int8(raw),
                                   -np.ones(len(gone), np.int64)))
    sched.tick()

    ref_ids, _ = store.reference_topk(qvecs, K)
    table = _ids_table(sched, kg)
    hits = total = 0
    for q in range(Q):
        hits += len(set(table[q]) & set(ref_ids[q]))
        total += K
    assert hits / total >= 0.95, f"int8 recall {hits/total:.3f}"
