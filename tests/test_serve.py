"""Ingestion-frontend tests (``reflow_tpu.serve``).

The contract under test: N concurrent producers ``submit()`` to a
frontend-owned scheduler and (a) every micro-batch's fate is reported
through its ticket (applied / deduped / rejected / shed — never silent),
(b) the coalesced macro-tick results equal the bare one-tick-per-batch
loop's (the differential property), (c) lifecycle edges — blocked
producers at ``close()``, a crashing pump, a durable crash + recover —
leave no ticket unresolved and no batch folded twice.

Tests that need a deterministically full queue use ``pause()`` (the
pump stops draining, admission keeps queueing), which is exactly the
backpressure regime a slow device executor produces.
"""

from __future__ import annotations

import threading

import numpy as np
import pytest

from reflow_tpu.delta import DeltaBatch
from reflow_tpu.graph import GraphError
from reflow_tpu.scheduler import DirtyScheduler, SourceCursor
from reflow_tpu.serve import (APPLIED, DEDUPED, REJECTED, SHED,
                              CoalesceWindow, FrontendClosed, IngestFrontend,
                              PumpCrashed, build_feeds)
from reflow_tpu.serve.queues import Entry, batch_nbytes
from reflow_tpu.serve.tickets import Ticket
from reflow_tpu.utils.faults import CrashInjector, CrashPoint
from reflow_tpu.utils.metrics import summarize_serve
from reflow_tpu.workloads import wordcount

WINDOW = CoalesceWindow(max_rows=256, max_ticks=8, max_latency_s=0.002)


def make_frontend(**kw):
    g, src, sink = wordcount.build_graph()
    sched = DirtyScheduler(g)
    kw.setdefault("window", WINDOW)
    return IngestFrontend(sched, **kw), sched, src, sink


def lines_batch(*words: str) -> DeltaBatch:
    return wordcount.ingest_lines([" ".join(words)])


# -- the happy path ---------------------------------------------------------

def test_submit_applies_and_reports_tick():
    fe, sched, src, sink = make_frontend()
    with fe:
        t = fe.submit(src, lines_batch("a", "b", "a"))
        r = t.result(timeout=5)
        assert r.applied and r.status == APPLIED
        assert r.tick >= 1
        fe.flush()
        assert dict(sched.view(sink.name)) == {("a", 2.0): 1, ("b", 1.0): 1}


def test_multi_producer_differential_matches_bare_loop():
    fe, sched, src, sink = make_frontend()
    n_prod, per = 8, 25
    payload = lambda p, j: lines_batch(f"w{p}", f"w{(p + j) % 5}", "c")

    def produce(p):
        for j in range(per):
            fe.submit(src, payload(p, j)).result(timeout=10)

    threads = [threading.Thread(target=produce, args=(p,))
               for p in range(n_prod)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    fe.flush()
    fe.close()

    g2, src2, sink2 = wordcount.build_graph()
    bare = DirtyScheduler(g2)
    for p in range(n_prod):
        for j in range(per):
            bare.push(src2, payload(p, j))
            bare.tick()
    assert dict(sched.view(sink.name)) == dict(bare.view(sink2.name))
    # coalescing actually engaged: fewer ticks than micro-batches
    assert sched._tick < n_prod * per
    sm = summarize_serve(fe)
    assert sm.applied == n_prod * per
    assert sm.coalesce_factor > 1.0


def test_empty_batch_is_reported_applied_without_a_tick():
    fe, sched, src, _sink = make_frontend()
    with fe:
        r = fe.submit(src, DeltaBatch.empty()).result(timeout=5)
        assert r.applied and r.tick is None and r.reason == "empty batch"


def test_submit_to_non_source_rejected():
    fe, sched, _src, sink = make_frontend()
    with fe:
        with pytest.raises(GraphError):
            fe.submit(sink, lines_batch("a"))


# -- exactly-once admission -------------------------------------------------

def test_duplicate_batch_id_resolves_deduped():
    fe, sched, src, sink = make_frontend()
    with fe:
        r1 = fe.submit(src, lines_batch("a"), batch_id="b0").result(timeout=5)
        fe.flush()
        r2 = fe.submit(src, lines_batch("a"), batch_id="b0").result(timeout=5)
        assert r1.status == APPLIED
        assert r2.status == DEDUPED
        fe.flush()
        assert dict(sched.view(sink.name)) == {("a", 1.0): 1}


def test_duplicate_within_one_window_deduped_before_tick():
    fe, sched, src, sink = make_frontend()
    with fe:
        fe.pause()
        t1 = fe.submit(src, lines_batch("a"), batch_id="dup")
        t2 = fe.submit(src, lines_batch("a"), batch_id="dup")
        assert t2.result(timeout=5).status == DEDUPED  # before any tick
        fe.resume()
        assert t1.result(timeout=5).status == APPLIED
        fe.flush()
        assert dict(sched.view(sink.name)) == {("a", 1.0): 1}


def test_minted_ids_resume_past_recovered_window(tmp_path):
    from reflow_tpu.wal import DurableScheduler, recover

    g, src, sink = wordcount.build_graph()
    sched = DurableScheduler(g, wal_dir=str(tmp_path / "wal"))
    fe = IngestFrontend(sched, window=WINDOW)
    for w in ("a", "b"):
        fe.submit(src, lines_batch(w))
    fe.flush()
    fe.close()

    g2, src2, sink2 = wordcount.build_graph()
    fresh = DurableScheduler(g2, wal_dir=str(tmp_path / "wal"))
    recover(fresh, str(tmp_path / "wal"))
    fe2 = IngestFrontend(fresh, window=WINDOW)
    # the new frontend's mint must not collide with recovered ids
    r = fe2.submit(src2, lines_batch("c")).result(timeout=5)
    assert r.status == APPLIED
    fe2.flush()
    fe2.close()
    assert dict(fresh.view(sink2.name)) == {
        ("a", 1.0): 1, ("b", 1.0): 1, ("c", 1.0): 1}


# -- backpressure policies --------------------------------------------------

def test_reject_policy_resolves_rejected_when_full():
    fe, sched, src, _sink = make_frontend(policy="reject", queue_batches=2)
    fe.pause()
    try:
        t1 = fe.submit(src, lines_batch("a"))
        t2 = fe.submit(src, lines_batch("b"))
        t3 = fe.submit(src, lines_batch("c"))
        r3 = t3.result(timeout=5)
        assert r3.status == REJECTED and "backpressure" in r3.reason
        assert not t1.done() and not t2.done()
    finally:
        fe.resume()
        fe.close()
    assert t1.result(timeout=5).applied and t2.result(timeout=5).applied


def test_block_policy_waits_for_room_then_applies():
    fe, sched, src, sink = make_frontend(policy="block", queue_batches=1)
    fe.pause()
    fe.submit(src, lines_batch("a"))
    done = threading.Event()
    holder = {}

    def blocked_producer():
        holder["r"] = fe.submit(src, lines_batch("b")).result(timeout=10)
        done.set()

    th = threading.Thread(target=blocked_producer)
    th.start()
    assert not done.wait(0.1)       # genuinely blocked on admission
    fe.resume()                     # pump drains; room opens
    assert done.wait(5)
    th.join()
    assert holder["r"].applied
    fe.flush()
    fe.close()
    assert dict(sched.view(sink.name)) == {("a", 1.0): 1, ("b", 1.0): 1}


def test_block_policy_timeout_resolves_rejected():
    fe, _sched, src, _sink = make_frontend(policy="block", queue_batches=1)
    fe.pause()
    try:
        fe.submit(src, lines_batch("a"))
        r = fe.submit(src, lines_batch("b"),
                      timeout=0.05).result(timeout=5)
        assert r.status == REJECTED and "timed out" in r.reason
    finally:
        fe.resume()
        fe.close()


def test_shed_oldest_policy_evicts_and_reports():
    fe, sched, src, sink = make_frontend(policy="shed-oldest",
                                         queue_batches=2)
    fe.pause()
    t1 = fe.submit(src, lines_batch("a"))
    t2 = fe.submit(src, lines_batch("b"))
    t3 = fe.submit(src, lines_batch("c"))
    r1 = t1.result(timeout=5)
    assert r1.status == SHED and "re-send" in r1.reason
    fe.resume()
    fe.flush()
    fe.close()
    assert t2.result(timeout=5).applied and t3.result(timeout=5).applied
    # the shed batch's rows were NOT folded
    assert dict(sched.view(sink.name)) == {("b", 1.0): 1, ("c", 1.0): 1}


def test_shed_batch_resent_with_same_id_is_admitted():
    # the SHED contract: the ticket tells the upstream to re-send, so a
    # re-send with the SAME batch_id must be admitted (the batch never
    # reached the scheduler), not swallowed as DEDUPED
    fe, sched, src, sink = make_frontend(policy="shed-oldest",
                                         queue_batches=2)
    fe.pause()
    t1 = fe.submit(src, lines_batch("a"), batch_id="r0")
    fe.submit(src, lines_batch("b"), batch_id="r1")
    fe.submit(src, lines_batch("c"), batch_id="r2")
    assert t1.result(timeout=5).status == SHED
    fe.resume()
    fe.flush()
    r = fe.submit(src, lines_batch("a"), batch_id="r0").result(timeout=5)
    assert r.status == APPLIED
    fe.flush()
    fe.close()
    assert dict(sched.view(sink.name)) == {
        ("a", 1.0): 1, ("b", 1.0): 1, ("c", 1.0): 1}


def test_blocked_duplicate_submits_fold_exactly_once():
    # two producers race the same batch_id through a full queue under
    # the block policy: the admission wait drops the lock, so the loser
    # must re-check dedup on wakeup — exactly one APPLIED, one DEDUPED
    fe, sched, src, sink = make_frontend(policy="block", queue_batches=1)
    fe.pause()
    fe.submit(src, lines_batch("x"), batch_id="seed")   # fills the queue
    results = []

    def dup_producer():
        results.append(
            fe.submit(src, lines_batch("d"), batch_id="dup").result(
                timeout=10))

    threads = [threading.Thread(target=dup_producer) for _ in range(2)]
    for th in threads:
        th.start()
    import time
    time.sleep(0.05)               # both reach the admission wait
    fe.resume()
    for th in threads:
        th.join(timeout=10)
    fe.flush()
    fe.close()
    assert sorted(r.status for r in results) == [APPLIED, DEDUPED]
    assert dict(sched.view(sink.name)) == {("x", 1.0): 1, ("d", 1.0): 1}


def test_oversized_batch_rejected_not_shed():
    fe, _sched, src, _sink = make_frontend(policy="shed-oldest",
                                           max_bytes=8)
    with fe:
        r = fe.submit(src, lines_batch("a", "b", "c")).result(timeout=5)
        assert r.status == REJECTED and "budget" in r.reason


# -- lifecycle --------------------------------------------------------------

def test_close_releases_blocked_producers():
    fe, _sched, src, _sink = make_frontend(policy="block", queue_batches=1)
    fe.pause()
    fe.submit(src, lines_batch("a"))
    errs = []
    started = threading.Event()

    def blocked_producer():
        started.set()
        try:
            fe.submit(src, lines_batch("b"))
        except FrontendClosed as e:
            errs.append(e)

    th = threading.Thread(target=blocked_producer)
    th.start()
    started.wait(5)
    import time
    time.sleep(0.05)               # let it reach the admission wait
    fe.close()                     # must release, not deadlock
    th.join(timeout=5)
    assert not th.is_alive()
    assert len(errs) == 1
    with pytest.raises(FrontendClosed):
        fe.submit(src, lines_batch("c"))


def test_close_with_flush_ticks_remaining_backlog():
    fe, sched, src, sink = make_frontend()
    fe.pause()
    t = fe.submit(src, lines_batch("a"))
    fe.close(flush=True)
    assert t.result(timeout=5).applied
    assert dict(sched.view(sink.name)) == {("a", 1.0): 1}


def test_close_without_flush_fails_queued_tickets():
    fe, sched, src, sink = make_frontend()
    fe.pause()
    t = fe.submit(src, lines_batch("a"))
    fe.close(flush=False)
    with pytest.raises(FrontendClosed):
        t.result(timeout=5)
    assert dict(sched.view(sink.name)) == {}


def test_close_timeout_does_not_seal_while_pump_drains():
    # a close() whose join times out mid-macro-tick must NOT report
    # closed / seal the scheduler's WAL while the pump can still append
    fe, sched, src, _sink = make_frontend()
    sealed = []
    sched.close = lambda: sealed.append(1)
    entered, release = threading.Event(), threading.Event()
    orig = sched.tick_many

    def slow_tick_many(*a, **kw):
        entered.set()
        release.wait(10)
        return orig(*a, **kw)

    sched.tick_many = slow_tick_many
    t = fe.submit(src, lines_batch("a"))
    assert entered.wait(5)          # pump is mid-macro-tick
    with pytest.raises(TimeoutError):
        fe.close(timeout=0.05)
    assert not sealed               # WAL-seal must not have run
    with pytest.raises(FrontendClosed):
        fe.submit(src, lines_batch("b"))   # admission already refused
    release.set()
    fe.close()                      # retry finishes the shutdown
    assert sealed
    assert t.result(timeout=5).applied


def test_close_is_idempotent():
    fe, _sched, _src, _sink = make_frontend()
    fe.close()
    fe.close()


def test_drain_runs_scheduler_drain_under_pause():
    fe, sched, src, sink = make_frontend()
    fe.submit(src, lines_batch("a")).result(timeout=5)
    # wordcount quiesces per tick: one probe tick confirms it
    assert fe.drain() <= 1
    fe.close()
    assert dict(sched.view(sink.name)) == {("a", 1.0): 1}


def test_latency_trigger_fires_under_light_traffic():
    # neither the rows nor the ticks trigger can fire for one tiny
    # batch; only the latency bound gets it ticked
    fe, _sched, src, _sink = make_frontend(window=CoalesceWindow(
        max_rows=1 << 20, max_ticks=1 << 20, max_latency_s=0.01))
    with fe:
        r = fe.submit(src, lines_batch("a")).result(timeout=5)
        assert r.applied


# -- pump crash -------------------------------------------------------------

def test_pump_crash_fails_tickets_and_closes_frontend():
    crash = CrashInjector(1, only="pump_before_tick")
    fe, _sched, src, _sink = make_frontend(crash=crash)
    t = fe.submit(src, lines_batch("a"))
    with pytest.raises(PumpCrashed):
        t.result(timeout=5)
    assert crash.fired
    assert isinstance(fe.pump_error, CrashPoint)
    with pytest.raises(FrontendClosed):
        fe.submit(src, lines_batch("b"))
    with pytest.raises(PumpCrashed):
        fe.flush()
    fe.close()                      # still clean to close


def test_revive_restarts_a_dead_pump_thread():
    """When the pump THREAD died with the crash (vs only the state
    flag flipping from another thread), revive() must re-arm the loop
    itself — otherwise nothing drains the queues and flush() waits
    forever (the chaos-bench failover hang)."""
    crash = CrashInjector(1, only="pump_before_tick")
    fe, sched, src, sink = make_frontend(crash=crash)
    with pytest.raises(PumpCrashed):
        fe.submit(src, lines_batch("a")).result(timeout=5)
    fe._thread.join(timeout=5)
    assert not fe._thread.is_alive()     # the loop really exited
    fe.revive()
    assert fe._thread.is_alive()         # ...and revive re-armed it
    assert fe.submit(src, lines_batch("z")).result(timeout=5).applied
    fe.flush(timeout=5)                  # regression: hung forever
    assert dict(sched.view(sink.name)).get(("z", 1.0)) == 1
    fe.close()


def test_producer_submit_crash_dies_in_submitting_thread():
    """producer_submit is a PRODUCER-thread seam: the kill surfaces out
    of submit() itself, before any frontend state mutates — the pump
    survives and the next submit applies normally."""
    crash = CrashInjector(1, only="producer_submit")
    fe, _sched, src, _sink = make_frontend(crash=crash)
    with pytest.raises(CrashPoint):
        fe.submit(src, lines_batch("a"))
    assert crash.fired and crash.fired_seam == "producer_submit"
    assert fe.submitted == 0 and fe.pump_error is None
    r = fe.submit(src, lines_batch("a")).result(timeout=5)
    assert r.applied
    fe.close()


def test_producer_admitted_crash_batch_survives_and_resend_dedups():
    """producer_admitted fires AFTER the batch is queued and its id
    noted: the producer dies, but the pump still applies the batch, and
    the upstream's resend (it cannot know the fate) dedups — the
    exactly-once story for a producer killed mid-return."""
    crash = CrashInjector(1, only="producer_admitted")
    fe, sched, src, sink = make_frontend(crash=crash)
    with pytest.raises(CrashPoint):
        fe.submit(src, lines_batch("a"), batch_id="k0")
    assert crash.fired and crash.fired_seam == "producer_admitted"
    r = fe.submit(src, lines_batch("a"), batch_id="k0").result(timeout=5)
    assert r.status == DEDUPED
    fe.flush()
    fe.close()
    assert dict(sched.view(sink.name)) == {("a", 1.0): 1}


def test_pump_coalesce_crash_fails_window_tickets():
    """pump_coalesce cuts between the host-side merge and everything
    durable/device-side: the whole drained window's tickets must fail
    PumpCrashed (nothing was pushed, so nothing half-applied)."""
    crash = CrashInjector(1, only="pump_coalesce")
    fe, _sched, src, _sink = make_frontend(crash=crash)
    t = fe.submit(src, lines_batch("a"))
    with pytest.raises(PumpCrashed):
        t.result(timeout=5)
    assert crash.fired and crash.fired_seam == "pump_coalesce"
    assert isinstance(fe.pump_error, CrashPoint)
    fe.close()


def test_durable_pump_crash_then_recover_exactly_once(tmp_path):
    """The acceptance differential: kill the pump mid-stream on a
    durable scheduler, recover a fresh one, re-send EVERYTHING (the
    upstream can't know what committed), and the final views must equal
    a clean run's — committed batches dedup, lost ones apply."""
    from reflow_tpu.wal import DurableScheduler, recover

    batches = [(f"b{i}", lines_batch(f"w{i % 3}", "c")) for i in range(12)]

    g, src, sink = wordcount.build_graph()
    sched = DurableScheduler(g, wal_dir=str(tmp_path / "wal"))
    crash = CrashInjector(3, only="pump_after_tick")
    fe = IngestFrontend(sched, crash=crash, window=CoalesceWindow(
        max_rows=4, max_ticks=2, max_latency_s=0.001))
    outcomes = {}
    for bid, b in batches:
        try:
            outcomes[bid] = fe.submit(src, b, batch_id=bid).result(timeout=5)
        except (PumpCrashed, FrontendClosed):
            break
    assert crash.fired
    fe.close()

    g2, src2, sink2 = wordcount.build_graph()
    fresh = DurableScheduler(g2, wal_dir=str(tmp_path / "wal"))
    report = recover(fresh, str(tmp_path / "wal"))
    fe2 = IngestFrontend(fresh, window=WINDOW)
    statuses = {bid: fe2.submit(src2, b, batch_id=bid).result(timeout=5)
                for bid, b in batches}
    fe2.flush()
    fe2.close()
    # everything the first run confirmed applied must now dedup
    for bid, r in outcomes.items():
        if r.applied:
            assert statuses[bid].status == DEDUPED, bid

    g3, src3, sink3 = wordcount.build_graph()
    clean = DirtyScheduler(g3)
    for bid, b in batches:
        clean.push(src3, b, batch_id=bid)
        clean.tick()
    assert dict(fresh.view(sink2.name)) == dict(clean.view(sink3.name))
    assert report.wal_records > 0


# -- coalescing unit tests --------------------------------------------------

def _entry(source, batch, bid, device=False, rows=None):
    return Entry(Ticket(bid), source, batch, bid, batch_nbytes(batch),
                 0.0, device,
                 0 if device else (len(batch) if rows is None else rows))


def test_build_feeds_merges_host_runs_up_to_max_rows():
    g, src, _sink = wordcount.build_graph()
    entries = [_entry(src, lines_batch(f"w{i}"), f"b{i}") for i in range(5)]
    feeds = build_feeds({src.id: entries}, max_rows=2)
    # 5 one-row batches at max_rows=2 -> 3 feeds: [2, 2, 1]
    assert [len(f.ids[src]) for f in feeds] == [2, 2, 1]
    assert len(feeds[0].batches[src]) == 2
    assert feeds[0].ids[src] == ["b0", "b1"]


def test_build_feeds_device_batch_rides_alone():
    class FakeDevice:
        # quacks like a device-resident batch (scheduler detection is
        # hasattr(batch, "nonzero")); concat with it would force a sync
        nonzero = None
        keys = values = weights = None

    g, src, _sink = wordcount.build_graph()
    dev = FakeDevice()
    entries = [_entry(src, lines_batch("a"), "h0"),
               _entry(src, dev, "d0", device=True),
               _entry(src, lines_batch("b"), "h1"),
               _entry(src, lines_batch("c"), "h2")]
    feeds = build_feeds({src.id: entries}, max_rows=256)
    # the device batch splits the host run: [h0], [d0], [h1+h2]
    assert [f.ids[src] for f in feeds] == [["h0"], ["d0"], ["h1", "h2"]]
    assert feeds[1].batches[src] is dev


def test_build_feeds_parallel_across_sources():
    g, src, _sink = wordcount.build_graph()
    g2, src2, _sink2 = wordcount.build_graph()
    a = [_entry(src, lines_batch("a"), "a0")]
    b = [_entry(src2, lines_batch("b"), "b0"),
         _entry(src2, lines_batch("c"), "b1")]
    # distinct queue keys: build_feeds groups by the frontend's queue
    # key, the Node objects inside the entries carry the identity
    feeds = build_feeds({0: a, 1: b}, max_rows=1)
    # feed 0 carries BOTH sources' first chunks (one macro-tick, not
    # one tick per source); feed 1 carries only src2's leftover
    assert len(feeds) == 2
    assert set(feeds[0].batches) == {src, src2}
    assert set(feeds[1].batches) == {src2}


def test_degenerate_window_rejected():
    with pytest.raises(ValueError):
        CoalesceWindow(max_rows=0)
    with pytest.raises(ValueError):
        CoalesceWindow(max_ticks=0)


# -- SourceCursor.resume edge cases (satellite) -----------------------------

def test_cursor_resume_skips_malformed_and_foreign_ids():
    g, src, _sink = wordcount.build_graph()
    sched = DirtyScheduler(g)
    for bid in ("words@3", "words@xyz", "words@", "other@9",
                "words7", "@5", "words@1"):
        sched._seen_batch_ids[bid] = None
    cur = SourceCursor.resume(sched, src)
    assert cur.next_id() == "words@4"   # max valid own id (3) + 1


def test_cursor_resume_empty_window_starts_at_zero():
    g, src, _sink = wordcount.build_graph()
    sched = DirtyScheduler(g)
    assert SourceCursor.resume(sched, src).next_id() == "words@0"


# -- dedup-window eviction order (satellite) --------------------------------

def test_rejected_replay_does_not_refresh_eviction_order():
    g, src, _sink = wordcount.build_graph()
    sched = DirtyScheduler(g, dedup_window=3)
    for bid in ("a", "b", "c"):
        assert sched.push(src, lines_batch("x"), batch_id=bid)
    # replaying "a" is rejected and must NOT move it to the back
    assert not sched.push(src, lines_batch("x"), batch_id="a")
    assert list(sched._seen_batch_ids) == ["a", "b", "c"]
    # a new accepted id evicts "a" (the oldest ACCEPTED), not "b"
    assert sched.push(src, lines_batch("x"), batch_id="d")
    assert list(sched._seen_batch_ids) == ["b", "c", "d"]
    # "a" is now past the horizon: a replay is silently re-accepted —
    # exactly the documented at-least-once boundary
    assert sched.push(src, lines_batch("x"), batch_id="a")


def test_replay_past_horizon_order_under_interleaving():
    g, src, _sink = wordcount.build_graph()
    sched = DirtyScheduler(g, dedup_window=2)
    assert sched.push(src, lines_batch("x"), batch_id="p0")
    assert sched.push(src, lines_batch("x"), batch_id="p1")
    assert not sched.push(src, lines_batch("x"), batch_id="p0")  # in window
    assert sched.push(src, lines_batch("x"), batch_id="p2")      # evicts p0
    assert list(sched._seen_batch_ids) == ["p1", "p2"]
    assert not sched.push(src, lines_batch("x"), batch_id="p1")
    assert sched.push(src, lines_batch("x"), batch_id="p0")      # past it
