"""Persistent-CSR cache of the fused linear fixpoint (VERDICT r3 #2).

The sorted arena base persists across ticks on the EXECUTOR (one cache
per join, shared by all program signatures) and only the append tail is
sorted per tick; a full rebuild happens in-program when the tail
overflows its window or a compaction bumps the arena generation. These
tests drive all three regimes against the CPU oracle.
"""

import numpy as np
import pytest

from reflow_tpu import DirtyScheduler
from reflow_tpu.executors import get_executor
from reflow_tpu.workloads import pagerank

TOL = 1e-5


def _drive(executor_name, web, churn, ticks, arena_capacity):
    pg = pagerank.build_graph(web.n_nodes, tol=TOL,
                              arena_capacity=arena_capacity)
    sched = DirtyScheduler(pg.graph, get_executor(executor_name),
                           max_loop_iters=500)
    sched.push(pg.teleport, pagerank.teleport_batch(web.n_nodes))
    sched.push(pg.edges, web.initial_batch())
    assert sched.tick().quiesced
    for _ in range(ticks):
        sched.push(pg.edges, web.churn(churn))
        assert sched.tick().quiesced
    return pagerank.ranks_to_array(sched.read_table(pg.new_rank),
                                   web.n_nodes), sched


def _linear_programs(sched):
    from reflow_tpu.executors.linear_fixpoint import LinearFixpointProgram

    return [p for p in sched.executor._cache.values()
            if isinstance(p, LinearFixpointProgram)]


def test_tail_accumulation_and_overflow_rebuild_match_oracle():
    """arena 1<<15 -> tail window 4096; churn(1.0) appends 1024 rows/tick,
    so the tail overflows (forcing the in-program rebuild) every ~4 ticks
    across 10 ticks, with plain tail-merge ticks in between."""
    web_a = pagerank.WebGraph.random(64, 512, seed=31)
    web_b = pagerank.WebGraph.random(64, 512, seed=31)
    ranks_t, sched = _drive("tpu", web_a, 1.0, 10, 1 << 15)
    ranks_c, _ = _drive("cpu", web_b, 1.0, 10, 1 << 15)
    assert np.array_equal(web_a.dst, web_b.dst)
    np.testing.assert_allclose(ranks_t, ranks_c, atol=2e-3)
    progs = _linear_programs(sched)
    assert progs, "fused linear program did not engage"
    # the cache genuinely persisted: the executor-held base covers rows
    csrs = sched.executor._csr_cache
    assert csrs and any(int(np.asarray(c["count"])[0]) > 0
                        for c in csrs.values())


def test_compaction_gen_bump_invalidates_csr():
    """A tiny arena (1024 rows) compacts repeatedly under heavy churn
    (retract+insert pairs cancel at high water); every compaction bumps
    the arena gen, which must force a CSR rebuild — ranks must keep
    matching the oracle afterwards."""
    web_a = pagerank.WebGraph.random(48, 384, seed=33)
    web_b = pagerank.WebGraph.random(48, 384, seed=33)
    ranks_t, sched = _drive("tpu", web_a, 0.5, 8, 1 << 10)
    ranks_c, _ = _drive("cpu", web_b, 0.5, 8, 1 << 10)
    assert np.array_equal(web_a.dst, web_b.dst)
    np.testing.assert_allclose(ranks_t, ranks_c, atol=2e-3)
    # compaction actually happened (the arena can't hold 8 x 384 churn
    # rows on top of the initial 384 without cancelling pairs)
    jst = sched.executor.states[
        [n.id for n in sched.graph.nodes
         if n.kind == "op" and n.op.kind == "join"][0]]
    assert int(np.asarray(jst["gen"]).reshape(-1)[0]) > 0
    assert int(np.asarray(jst["rcount"]).reshape(-1)[0]) <= 1 << 10


def test_csr_cache_sharded_matches_single_device():
    """The per-shard CSR cache under shard_map: same churn sequence on the
    8-device mesh and the single-device executor. Accumulation orders
    differ (psum_scatter vs direct scatter), so the bound is the two-
    tol-converged-fixpoints one (cf. test_sharded.py), not bitwise."""
    from reflow_tpu.parallel import make_mesh
    from reflow_tpu.parallel.shard import ShardedTpuExecutor

    jax_mesh = make_mesh(8)
    results = {}
    for name in ("sharded", "single"):
        web = pagerank.WebGraph.random(64, 512, seed=35)
        pg = pagerank.build_graph(64, tol=TOL, arena_capacity=1 << 15)
        ex = (ShardedTpuExecutor(jax_mesh) if name == "sharded"
              else get_executor("tpu"))
        sched = DirtyScheduler(pg.graph, ex, max_loop_iters=500)
        sched.push(pg.teleport, pagerank.teleport_batch(64))
        sched.push(pg.edges, web.initial_batch())
        sched.tick()
        for _ in range(6):
            sched.push(pg.edges, web.churn(1.0))
            assert sched.tick().quiesced
        results[name] = sched.read_table(pg.new_rank)
    assert set(results["sharded"]) == set(results["single"])
    bound = TOL / (1.0 - pagerank.DAMPING) + 1e-4
    for k in results["single"]:
        a = float(results["sharded"][k])
        b = float(results["single"][k])
        assert abs(a - b) < bound, (k, a, b)


def test_checkpoint_restore_invalidates_csr_cache(tmp_path):
    """Two lineages can share a (gen, rcount) pair over different arena
    rows, so restore must explicitly drop the sorted-arena cache
    (executor.on_states_replaced). Diverge after a save, restore, replay
    the original churn — ranks must match a from-scratch run."""
    from reflow_tpu.utils.checkpoint import load_checkpoint, save_checkpoint

    web = pagerank.WebGraph.random(64, 512, seed=37)
    pg = pagerank.build_graph(64, tol=TOL, arena_capacity=1 << 15)
    sched = DirtyScheduler(pg.graph, get_executor("tpu"),
                           max_loop_iters=500)
    sched.push(pg.teleport, pagerank.teleport_batch(64))
    sched.push(pg.edges, web.initial_batch())
    sched.tick()
    sched.push(pg.edges, web.churn(1.0))
    sched.tick()
    ckpt = str(tmp_path / "ck")
    save_checkpoint(sched, ckpt)
    dst_at_save = web.dst.copy()

    # diverge: more churn ticks advance (and re-sort) the arena + cache
    for _ in range(3):
        sched.push(pg.edges, web.churn(1.0))
        sched.tick()

    # restore the earlier lineage into the SAME warm scheduler/executor
    load_checkpoint(sched, ckpt)
    web.dst = dst_at_save          # host cursor back to the save point
    replay = web.churn(1.0)
    sched.push(pg.edges, replay)
    assert sched.tick().quiesced
    restored = pagerank.ranks_to_array(sched.read_table(pg.new_rank), 64)

    # fresh run over the identical delta sequence
    web2 = pagerank.WebGraph.random(64, 512, seed=37)
    pg2 = pagerank.build_graph(64, tol=TOL, arena_capacity=1 << 15)
    s2 = DirtyScheduler(pg2.graph, get_executor("tpu"), max_loop_iters=500)
    s2.push(pg2.teleport, pagerank.teleport_batch(64))
    s2.push(pg2.edges, web2.initial_batch())
    s2.tick()
    s2.push(pg2.edges, web2.churn(1.0))
    s2.tick()
    s2.push(pg2.edges, replay)
    assert s2.tick().quiesced
    fresh = pagerank.ranks_to_array(s2.read_table(pg2.new_rank), 64)
    # not bitwise: the restored run's CSR rebuilds with a different
    # base/tail split than the fresh run's (different scatter-add order
    # within float tolerance). The stale-cache bug this guards against
    # pushes values through the WRONG arena rows — errors ~1e-1.
    bound = TOL / (1.0 - pagerank.DAMPING) + 1e-4
    np.testing.assert_allclose(restored, fresh, atol=bound)
