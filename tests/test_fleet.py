"""Fleet telemetry plane: cross-process trace correlation, snapshot
shipping, and the fleet consumers (docs/guide.md "Fleet telemetry").

The contract under test: (a) causality tokens stitch one shipment's
``ship_segment`` → ``net_send`` → ``replica_replay`` spans into a
single chain, while unstamped legacy ``Shipment`` frames stay
byte-identical on the wire, (b) the subscribe handshake piggybacks a
display-only clock anchor that old servers may omit, (c) telemetry
loss is always tolerated — a dead aggregator is a dropped-snapshot
counter, a silent node is a stale-marked entry, never an exception,
(d) the aggregator derives the cross-node gauges (lag spread, epoch
agreement, read QPS from ring deltas) correctly, and (e) the consumers
— ``fleet_inspect``, ``reflow_top``, ``ControlPlane(fleet=)`` —
render/act on the same ``reflow.fleet/1`` snapshot.
"""

from __future__ import annotations

import importlib.util
import json
import os
import pickle

import numpy as np
import pytest

from reflow_tpu import obs
from reflow_tpu.net import (ReconnectPolicy, RemoteFollower,
                            ReplicaServer, TcpTransport)
from reflow_tpu.net.framing import TransportError
from reflow_tpu.obs import trace as trace_mod
from reflow_tpu.obs.fleet import (FLEET_SCHEMA, FleetAggregator,
                                  TelemetryShipper)
from reflow_tpu.obs.wire import TelemetryLink, TelemetryServer, node_id
from reflow_tpu.serve import ReplicaScheduler, ServeTier
from reflow_tpu.serve.control import ControlPlane
from reflow_tpu.wal import DurableScheduler, SegmentShipper
from reflow_tpu.wal.ship import Shipment
from reflow_tpu.workloads import wordcount

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture
def traced():
    obs.disable()
    trace_mod.reset()
    obs.enable()
    yield
    obs.disable()
    trace_mod.reset()


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt
        return self.t


def drive(sched, src, n_ticks, seed=0):
    rng = np.random.default_rng(seed)
    for t in range(n_ticks):
        words = " ".join(f"w{int(x)}" for x in rng.integers(0, 40, 8))
        sched.push(src, wordcount.ingest_lines([words]),
                   batch_id=f"t{t}")
        sched.tick()


def pump_until_caught(ship, sched, replicas, max_rounds=200):
    sched.wal.sync()
    for _ in range(max_rounds):
        ship.pump_once()
        if all(r.published_horizon() == sched._tick for r in replicas):
            return
    raise AssertionError("replicas never caught up")


# -- the Shipment wire frame (legacy compat + cause stamping) ---------------

class _ScriptConn:
    def __init__(self, replies):
        self.sent = []
        self._replies = list(replies)

    def send_msg(self, msg, timeout_s=None):
        self.sent.append(msg)

    def recv_msg(self, timeout_s=None):
        if not self._replies:
            raise TransportError("script exhausted")
        return self._replies.pop(0)

    def close(self):
        pass


class _ScriptTransport:
    def __init__(self, conn):
        self._conn = conn

    def connect(self, address):
        return self._conn


def _follower(conn, name="r0"):
    return RemoteFollower(
        _ScriptTransport(conn), ("stub", 0), name=name,
        policy=ReconnectPolicy(name, base_s=0.001, cap_s=0.01, seed=0))


def test_legacy_shipment_frame_is_byte_identical(tmp_path):
    """An unstamped shipment's receive frame pickles to exactly the
    pre-trace 8-field protocol — the trailing None cause never reaches
    the wire, so mixed-version fleets interoperate."""
    obs.disable()
    legacy = Shipment(0, 0, b"xx", 2, False, None, 3, 1)
    assert legacy.cause is None  # pre-trace constructor still valid
    conn = _ScriptConn([("ok", None),               # subscribe (legacy)
                        ("ack", (0, 2), 3)])
    f = _follower(conn)
    f.receive(legacy)  # first call dials + resyncs
    ack = f.receive(legacy)
    assert ack.horizon == 3
    sent = conn.sent[-1]
    assert sent == ("receive", 0, 0, b"xx", 2, False, None, 3, 1)
    # exactly what a pre-cause client pickled: op + 8 fields, no cause
    pre_trace = ("receive",) + tuple(legacy)[:8]
    assert pickle.dumps(sent) == pickle.dumps(pre_trace)


def test_stamped_shipment_carries_cause_and_span_echoes_it(traced):
    stamped = Shipment(0, 0, b"xx", 2, False, None, 3, 1,
                       trace_mod.mint_cause("leader", 1))
    conn = _ScriptConn([("ok", None), ("ack", (0, 2), 3)])
    f = _follower(conn)
    f.receive(stamped)
    f.receive(stamped)
    sent = conn.sent[-1]
    assert len(sent) == 10 and sent[-1] == stamped.cause
    sends = [e for e in obs.chrome_events()
             if e.get("ph") == "X" and e["name"] == "net_send"]
    assert any(e.get("args", {}).get("cause") == stamped.cause
               for e in sends)


def test_subscribe_anchor_captured_and_legacy_server_tolerated():
    anchored = _ScriptConn([("ok", None,
                             {"node": "r0", "mono": 1.0, "wall": 2.0})])
    f = _follower(anchored)
    f.subscribe()
    assert f.anchor is not None
    assert f.anchor["node"] == "r0"
    assert f.anchor["rtt_s"] >= 0.0
    assert "wall_offset_s" in f.anchor  # display-only skew estimate
    legacy = _ScriptConn([("ok", None)])  # pre-anchor 2-tuple reply
    f2 = _follower(legacy, name="r1")
    assert f2.subscribe() is None
    assert f2.anchor is None


def test_cause_tokens_stitch_ship_send_replay_over_tcp(tmp_path,
                                                       traced):
    """The tentpole proof at test scale: one leader, one TCP replica,
    and every shipped chunk's three hops share one causality token."""
    g, src, _sink = wordcount.build_graph()
    sched = DurableScheduler(g, wal_dir=str(tmp_path / "wal"),
                             fsync="tick")
    gr, _s, _k = wordcount.build_graph()
    r = ReplicaScheduler(gr, str(tmp_path / "r0"), name="r0")
    srv = ReplicaServer(r, TcpTransport()).start()
    link = RemoteFollower(
        TcpTransport(), srv.address, name="r0",
        policy=ReconnectPolicy("r0", base_s=0.005, cap_s=0.05, seed=0),
        io_timeout_s=2.0)
    ship = SegmentShipper(sched.wal, leader_tick=lambda: sched._tick)
    ship.attach(link)
    try:
        drive(sched, src, 4)
        pump_until_caught(ship, sched, [r])
        by_cause = {}
        for e in obs.chrome_events():
            if e.get("ph") != "X":
                continue
            cause = e.get("args", {}).get("cause")
            if cause:
                by_cause.setdefault(cause, set()).add(e["name"])
        full = [c for c, names in by_cause.items()
                if {"ship_segment", "net_send",
                    "replica_replay"} <= names]
        assert full, f"no complete chain in {by_cause}"
        origin = node_id()
        for c in full:
            assert c.startswith(f"{origin}#")  # origin#epoch#seq
        path = str(tmp_path / "trace.json")
        obs.export_chrome_trace(path)
        ti = _load_tool("trace_inspect")
        out = ti.inspect(path, require_chain=[
            "ship_segment", "net_send", "replica_replay"])
        assert out["causal"]["required_chains"] >= 1
        assert ti.main([path, "--require-chain",
                        "ship_segment,net_send,replica_replay",
                        "--json"]) == 0
    finally:
        ship.close()
        link.close()
        srv.close()
        r.close()
        sched.wal.close()


def test_tracing_disabled_ships_no_cause(tmp_path):
    obs.disable()
    g, src, _sink = wordcount.build_graph()
    sched = DurableScheduler(g, wal_dir=str(tmp_path / "wal"),
                             fsync="tick")
    gr, _s, _k = wordcount.build_graph()
    r = ReplicaScheduler(gr, str(tmp_path / "r0"), name="r0")
    ship = SegmentShipper(sched.wal, leader_tick=lambda: sched._tick)
    seen = []
    orig = r.receive

    def spy(sh):
        seen.append(sh)
        return orig(sh)

    r.receive = spy
    ship.attach(r)
    try:
        drive(sched, src, 2)
        pump_until_caught(ship, sched, [r])
        assert seen and all(sh.cause is None for sh in seen)
    finally:
        ship.close()
        r.close()
        sched.wal.close()


# -- FleetAggregator derivation ---------------------------------------------

def _snap(mono, **gauges):
    return {"schema": obs.SNAPSHOT_SCHEMA, "ts_mono": mono,
            "ts_wall": 1000.0 + mono, "gauges": gauges}


def test_aggregator_derives_lag_spread_epochs_and_qps():
    clk = FakeClock()
    agg = FleetAggregator(retention=8, stale_after_s=5.0, clock=clk,
                          wall=lambda: 42.0)
    agg.ingest("r0", _snap(1.0, **{"replica.r0.horizon": 10,
                                   "replica.r0.lag_ticks": 0,
                                   "replica.r0.epoch": 1,
                                   "replica.r0.conn_state": "healthy",
                                   "tier.replica_reads": 100}))
    agg.ingest("r0", _snap(3.0, **{"replica.r0.horizon": 12,
                                   "replica.r0.lag_ticks": 0,
                                   "replica.r0.epoch": 1,
                                   "replica.r0.conn_state": "healthy",
                                   "tier.replica_reads": 200}))
    agg.ingest("r1", _snap(1.0, **{"replica.r1.horizon": 4,
                                   "replica.r1.lag_ticks": 8,
                                   "replica.r1.epoch": 1}))
    snap = agg.fleet_snapshot()
    assert snap["schema"] == FLEET_SCHEMA and snap["ts_wall"] == 42.0
    g = snap["gauges"]
    assert g["nodes_total"] == 2 and g["nodes_stale"] == 0
    assert g["lag_spread"] == 8          # 12 - 4
    assert g["epochs"] == [1] and g["epoch_agree"] is True
    # 100 reads over 2s of the sender's monotonic clock
    assert g["aggregate_read_qps"] == pytest.approx(50.0)
    assert snap["nodes"]["r0"]["horizon"] == 12
    assert snap["nodes"]["r1"]["lag_ticks"] == 8
    assert snap["nodes"]["r0"]["conn_states"] == {
        "replica.r0.conn_state": "healthy"}
    assert snap["alerts"] == []  # spread 8 <= default limit
    json.dumps(snap)
    agg.close()


def test_aggregator_epoch_disagreement_and_spread_alerts():
    clk = FakeClock()
    agg = FleetAggregator(retention=4, stale_after_s=5.0, clock=clk)
    agg.lag_spread_max = 16
    agg.ingest("r0", _snap(1.0, **{"replica.r0.horizon": 100,
                                   "replica.r0.epoch": 2}))
    agg.ingest("r1", _snap(1.0, **{"replica.r1.horizon": 10,
                                   "replica.r1.epoch": 1}))
    snap = agg.fleet_snapshot()
    assert snap["gauges"]["epoch_agree"] is False
    assert snap["gauges"]["epochs"] == [1, 2]
    assert any("epoch disagreement" in a for a in snap["alerts"])
    assert any("lag spread 90 ticks exceeds 16" in a
               for a in snap["alerts"])
    agg.close()


def test_aggregator_stale_marks_but_keeps_serving():
    """A silent node stays in the fleet view with an honest age on it
    — staleness is a display state, never an eviction or an error."""
    clk = FakeClock()
    agg = FleetAggregator(retention=4, stale_after_s=1.0, clock=clk)
    agg.ingest("r0", _snap(1.0, **{"replica.r0.horizon": 5}))
    agg.ingest("r1", _snap(1.0, **{"replica.r1.horizon": 5}))
    clk.advance(0.5)
    assert agg.stale_nodes() == []
    clk.advance(2.0)
    agg.ingest("r1", _snap(4.0, **{"replica.r1.horizon": 7}))
    snap = agg.fleet_snapshot()
    assert agg.stale_nodes() == ["r0"]
    assert snap["nodes"]["r0"]["stale"] is True
    assert snap["nodes"]["r0"]["horizon"] == 5  # last-known, served
    assert snap["nodes"]["r1"]["stale"] is False
    assert snap["gauges"]["nodes_stale"] == 1
    assert any(a.startswith("stale: r0") for a in snap["alerts"])
    agg.close()


def test_aggregator_retention_bounds_ring():
    agg = FleetAggregator(retention=3, stale_after_s=5.0,
                          clock=FakeClock())
    for i in range(10):
        agg.ingest("r0", _snap(float(i)))
    snap = agg.fleet_snapshot()
    assert snap["nodes"]["r0"]["snapshots"] == 3
    assert snap["gauges"]["snapshots_total"] == 10
    agg.close()


def test_aggregator_publish_metrics_and_unregister():
    reg = obs.MetricsRegistry()
    agg = FleetAggregator(retention=4, stale_after_s=5.0,
                          clock=FakeClock())
    agg.ingest("r0", _snap(1.0, **{"replica.r0.horizon": 5}))
    agg.publish_metrics(reg)
    snap = reg.snapshot()
    assert snap["gauges"]["fleet.nodes_total"] == 1
    assert snap["gauges"]["fleet.snapshots_total"] == 1
    agg.close()
    assert "fleet.nodes_total" not in reg.snapshot()["gauges"]


# -- snapshot shipping over the wire ----------------------------------------

def test_shipper_to_aggregator_over_tcp_and_fleet_query():
    reg = obs.MetricsRegistry()
    reg.counter("serve.applied").inc(7)
    reg.gauge("replica.r0.horizon", lambda: 9)
    agg = FleetAggregator(retention=8, stale_after_s=5.0)
    tsrv = TelemetryServer(agg, TcpTransport()).start()
    sh = TelemetryShipper(
        reg, TcpTransport(), tsrv.address, node="r0",
        policy=ReconnectPolicy("tele/r0", base_s=0.005, cap_s=0.05,
                               seed=0),
        io_timeout_s=2.0)
    probe = TelemetryLink(TcpTransport(), tsrv.address,
                          node="probe", io_timeout_s=2.0)
    try:
        snap = sh.build_snapshot()
        assert snap["schema"] == obs.SNAPSHOT_SCHEMA
        assert snap["node"] == "r0" and "ts_mono" in snap
        assert sh.ship_once() and sh.shipped == 1
        assert agg.node_count() == 1
        # the hello handshake recorded r0's clock anchor
        assert "r0" in agg.fleet_snapshot()["anchors"]
        fleet = probe.fetch_fleet()
        assert fleet is not None and fleet["schema"] == FLEET_SCHEMA
        assert fleet["nodes"]["r0"]["horizon"] == 9
        assert probe.anchor is not None and probe.anchor["rtt_s"] >= 0
    finally:
        probe.close()
        sh.close()
        tsrv.close()
        agg.close()


def test_telemetry_loss_tolerated_never_raises():
    """A dead aggregator: every beat is a dropped counter, the data
    path never sees an exception, and the link state degrades."""

    class _DeadTransport:
        def connect(self, address):
            raise TransportError("nothing listening")

    reg = obs.MetricsRegistry()
    sh = TelemetryShipper(
        reg, _DeadTransport(), ("nowhere", 0), node="r0",
        policy=ReconnectPolicy("tele/r0", base_s=0.0, cap_s=0.0,
                               seed=0))
    for _ in range(5):
        assert sh.ship_once() is False
    assert sh.dropped == 5 and sh.shipped == 0
    assert sh.link.conn_state != "healthy"
    sh.close()


def test_telemetry_server_survives_poison_and_keeps_serving():
    agg = FleetAggregator(retention=4, stale_after_s=5.0)
    tsrv = TelemetryServer(agg, TcpTransport()).start()
    try:
        conn = TcpTransport().connect(tsrv.address)
        conn.send_msg(("bogus-op", 1, 2), 2.0)
        resp = conn.recv_msg(2.0)
        assert resp[0] == "err"
        conn.send_msg("not-a-tuple", 2.0)
        assert conn.recv_msg(2.0)[0] == "err"
        # malformed snap degrades, then a healthy request still works
        conn.send_msg(("snap", "r0"), 2.0)
        assert conn.recv_msg(2.0)[0] == "err"
        conn.send_msg(("ping",), 2.0)
        ok, info = conn.recv_msg(2.0)
        assert ok == "ok" and info["nodes"] == 0
        conn.close()
    finally:
        tsrv.close()
        agg.close()


def test_shipper_publishes_its_own_metrics():
    reg = obs.MetricsRegistry()
    agg = FleetAggregator(retention=4, stale_after_s=5.0)
    tsrv = TelemetryServer(agg, TcpTransport()).start()
    sh = TelemetryShipper(reg, TcpTransport(), tsrv.address, node="r0",
                          io_timeout_s=2.0)
    sh.publish_metrics()
    try:
        sh.ship_once()
        snap = reg.snapshot()
        assert snap["gauges"]["telemetry.shipped"] == 1
        assert snap["gauges"]["telemetry.dropped"] == 0
        assert snap["gauges"]["telemetry.conn_state"] == "healthy"
    finally:
        sh.close()
        tsrv.close()
        agg.close()
    assert "telemetry.shipped" not in reg.snapshot()["gauges"]


# -- consumers --------------------------------------------------------------

def _fleet_fixture():
    agg = FleetAggregator(retention=4, stale_after_s=5.0,
                          clock=FakeClock())
    agg.ingest("r0", _snap(1.0, **{"replica.r0.horizon": 12,
                                   "replica.r0.lag_ticks": 0,
                                   "replica.r0.epoch": 1,
                                   "replica.r0.conn_state": "healthy"}))
    agg.ingest("r1", _snap(1.0, **{"replica.r1.horizon": 4,
                                   "replica.r1.lag_ticks": 8,
                                   "replica.r1.epoch": 1}))
    snap = agg.fleet_snapshot()
    agg.close()
    return snap


def test_fleet_inspect_file_json_and_fail_on_alert(tmp_path, capsys):
    snap = _fleet_fixture()
    path = str(tmp_path / "fleet.json")
    with open(path, "w") as f:
        json.dump(snap, f)
    fi = _load_tool("fleet_inspect")
    assert fi.main([path, "--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["schema"] == FLEET_SCHEMA
    assert out["gauges"]["lag_spread"] == 8
    assert fi.main([path]) == 0  # human table renders
    human = capsys.readouterr().out
    assert "r0" in human and "lag spread" in human
    # alerts are reported, not fatal — unless the CI smoke asks
    snap["alerts"] = ["stale: r1 last seen 9.0s ago"]
    with open(path, "w") as f:
        json.dump(snap, f)
    assert fi.main([path]) == 0
    capsys.readouterr()
    assert fi.main([path, "--fail-on-alert"]) == 1
    with open(path, "w") as f:
        f.write(json.dumps({"schema": "other/1"}))
    with pytest.raises(SystemExit):
        fi.main([path, "--json"])


def test_fleet_inspect_bench_dir_backfill_tolerant(tmp_path, capsys):
    (tmp_path / "new.json").write_text(json.dumps(
        {"schema": "reflow.bench/1", "mode": "fleetobs",
         "rows_per_s": 1}))
    (tmp_path / "old.json").write_text(json.dumps(
        {"metric": "x", "rows_per_s": 2.0}))  # pre-stamp bench
    (tmp_path / "other.json").write_text(json.dumps(
        {"schema": "reflow.fleet/1"}))        # not a bench result
    (tmp_path / "junk.json").write_text("{broken")
    fi = _load_tool("fleet_inspect")
    assert fi.main(["--bench-dir", str(tmp_path), "--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["schema"] == "reflow.fleet_benchdir/1"
    assert out["stamped"] == 1 and out["unstamped"] == 1
    by_file = {e["file"]: e for e in out["benches"]}
    assert by_file["new.json"]["mode"] == "fleetobs"
    assert by_file["old.json"]["mode"] is None
    assert "other.json" not in by_file


def test_reflow_top_render_marks_stale_and_disconnect():
    rt = _load_tool("reflow_top")
    snap = _fleet_fixture()
    snap["nodes"]["r1"]["stale"] = True
    snap["nodes"]["r1"]["age_s"] = 9.3
    snap["alerts"] = ["stale: r1 last seen 9.3s ago"]
    frame = rt.render(snap)
    assert "reflow-top" in frame and "2 node(s)" in frame
    assert "STALE(9.3s)" in frame
    assert "ALERT: stale: r1" in frame
    assert "lag spread 8" in frame
    # the console survives a dead aggregator: last frame, flagged
    assert "[disconnected]" in rt.render(snap, stale_link=True)


def test_reflow_top_once_renders_saved_snapshot(tmp_path, capsys):
    snap = _fleet_fixture()
    path = str(tmp_path / "fleet.json")
    with open(path, "w") as f:
        json.dump(snap, f)
    rt = _load_tool("reflow_top")
    assert rt.main([path, "--once"]) == 0
    out = capsys.readouterr().out
    assert "r0" in out and "r1" in out


def test_control_plane_fleet_advisory_edge_triggered():
    """The lag-spread breach surfaces exactly one advisory action per
    episode (plus one on recovery) and never actuates anything."""

    class _FakeFleet:
        lag_spread_max = 4

        def __init__(self):
            self.spread = 10

        def fleet_snapshot(self):
            return {"gauges": {"lag_spread": self.spread,
                               "nodes_stale": 1},
                    "alerts": [f"lag spread {self.spread} ticks "
                               f"exceeds 4"]}

    tier = ServeTier(max_bytes=1 << 20, pump_threads=1)
    fleet = _FakeFleet()
    clk = FakeClock()
    reg = obs.MetricsRegistry()
    sampler = lambda now: {"graphs": {}, "ready_depth": 0,
                           "live_workers": tier.live_workers}
    cp = ControlPlane(tier, registry=reg, clock=clk, sampler=sampler,
                      fleet=fleet)
    a1 = cp.step(clk.advance(0.05))
    assert [a["kind"] for a in a1] == ["fleet_lag_spread"]
    assert a1[0]["advisory"] is True and a1[0]["lag_spread"] == 10
    assert cp.step(clk.advance(0.05)) == []  # still breached: no spam
    fleet.spread = 1
    a2 = cp.step(clk.advance(0.05))
    assert [a["kind"] for a in a2] == ["fleet_lag_recovered"]
    assert cp.step(clk.advance(0.05)) == []
    assert reg.value("control.fleet_lag_breaches") == 1
    cp.stop()
    tier.close()


def test_control_plane_tolerates_fleet_snapshot_failure():
    class _BrokenFleet:
        lag_spread_max = 4

        def fleet_snapshot(self):
            raise RuntimeError("telemetry weather")

    tier = ServeTier(max_bytes=1 << 20, pump_threads=1)
    clk = FakeClock()
    cp = ControlPlane(tier, registry=obs.MetricsRegistry(), clock=clk,
                      sampler=lambda now: {"graphs": {},
                                           "ready_depth": 0,
                                           "live_workers": 0},
                      fleet=_BrokenFleet())
    assert cp.step(clk.advance(0.05)) == []  # loss tolerated
    assert cp.errors == 0
    cp.stop()
    tier.close()
