"""Reactive reads (``reflow_tpu/subs/``): standing queries with
per-window delta fan-out.

The load-bearing invariants, each a hard assert here:

- **Exactness**: a delta-reconstructed answer equals the pull path
  (`view_at` / `lookup` / `top_k`) at the same horizon, for every
  query kind — including through conflation, shedding, crash-rebase,
  and reconnect.
- **Gap-free, duplicate-free resume**: a wire subscriber that loses
  its link mid-stream resumes from a one-integer cursor with
  ``gaps_total == 0`` and no double-applied frame (the client-side
  contiguity rule *counts* violations, so the assertion is direct).
- **Apply never blocks on fan-out**: a subscriber that never drains
  keeps a bounded outbox (conflated, then shed to snapshot) while the
  replica applies at full speed.
- **Crash seam** ``sub_fanout``: killing the fan-out thread after a
  window is consumed but before the mirror folds it loses freshness,
  never truth — restart rebases every subscriber from replica state.
"""

import time
from types import SimpleNamespace

import numpy as np
import pytest

from reflow_tpu.net import LoopbackTransport, ReconnectPolicy
from reflow_tpu.obs import SNAPSHOT_SCHEMA, MetricsRegistry
from reflow_tpu.obs.fleet import FleetAggregator
from reflow_tpu.serve import ReplicaScheduler
from reflow_tpu.serve.control import ControlConfig, ControlPlane
from reflow_tpu.subs import (DeltaFrame, QueryState, Subscriber,
                             SubscriptionHub, SubscriptionServer,
                             canon_query, merge_frames)
from reflow_tpu.subs.query import topk_rows
from reflow_tpu.subs.cli import SUB_SCHEMA, make_update, render_update
from reflow_tpu.utils.faults import CrashInjector
from reflow_tpu.wal import DurableScheduler, SegmentShipper
from reflow_tpu.workloads import wordcount


def make_stack(tmp_path, **hub_kw):
    """Leader -> shipper -> replica -> hub, all in-process."""
    g, src, sink = wordcount.build_graph()
    sched = DurableScheduler(g, wal_dir=str(tmp_path / "wal"),
                             fsync="tick")
    ship = SegmentShipper(sched.wal, leader_tick=lambda: sched._tick)
    g2, _s, _k = wordcount.build_graph()
    rep = ReplicaScheduler(g2, str(tmp_path / "r0"), name="r0")
    ship.attach(rep)
    hub_kw.setdefault("idle_poll_s", 0.005)
    hub = SubscriptionHub(rep, name="r0", **hub_kw)
    rep.attach_hub(hub)
    return sched, ship, rep, hub, src, sink


def drive(sched, src, n_ticks, seed=0, start=0, vocab=40):
    rng = np.random.default_rng(seed + start)
    for t in range(start, start + n_ticks):
        for j in range(2):
            words = " ".join(
                f"w{int(x)}" for x in rng.integers(0, vocab, 8))
            sched.push(src, wordcount.ingest_lines([words]),
                       batch_id=f"t{t}b{j}")
        sched.tick()


def pump_until_caught(ship, sched, rep, max_rounds=200):
    sched.wal.sync()
    for _ in range(max_rounds):
        ship.pump_once()
        if rep.published_horizon() == sched._tick:
            return
    raise AssertionError(
        f"replica stuck at {rep.published_horizon()}, "
        f"leader at {sched._tick}")


def close_stack(sched, ship, hub):
    hub.close()
    sched.close()


def pull_value(rep, sink, query):
    """The pull-path answer for ``query`` (the parity oracle). For
    topk the oracle is the deterministic ranking over the pull view —
    ``replica.top_k``'s argpartition breaks weight ties arbitrarily,
    so raw list equality would flake; the ranked *weights* are still
    cross-checked against it."""
    if query.kind == "view":
        return rep.view_at(sink.name)[1]
    if query.kind == "lookup":
        return rep.lookup(sink.name, query.params[0])[1]
    k, by = query.params
    ranked = topk_rows(rep.view_at(sink.name)[1], k, by)
    pulled = rep.top_k(sink.name, k, by=by)[1]
    assert [w for _kv, w in ranked] == [w for _kv, w in pulled]
    return ranked


# -- the frame contiguity rule (pure) ---------------------------------------

def test_query_state_contiguity_counts_dups_and_gaps():
    q = canon_query("s", "view")
    st = QueryState(q)
    # pre-snapshot delta: a gap (no base to apply onto)
    assert not st.apply(DeltaFrame(0, 1, "view", ((("a", 1.0), 1),),
                                   False))
    assert st.gaps == 1 and st.horizon == -1
    assert st.apply(DeltaFrame(-1, 3, "view", ((("a", 1.0), 2),), True))
    assert st.horizon == 3 and st.value() == {("a", 1.0): 2}
    # contiguous delta applies; the changeless overlap (from_h < h) too
    assert st.apply(DeltaFrame(3, 5, "view", ((("b", 1.0), 1),), False))
    assert st.apply(DeltaFrame(4, 7, "view", ((("a", 1.0), -2),),
                               False))
    assert st.horizon == 7 and st.value() == {("b", 1.0): 1}
    # duplicate (to_h <= h): skipped, counted, state unchanged
    assert not st.apply(DeltaFrame(5, 7, "view", ((("b", 1.0), 9),),
                                   False))
    assert st.dups_skipped == 1 and st.value() == {("b", 1.0): 1}
    # gap (from_h > h): counted, NOT applied — wrong is worse than late
    assert not st.apply(DeltaFrame(9, 11, "view", ((("c", 1.0), 1),),
                                   False))
    assert st.gaps == 2 and st.horizon == 7
    # an empty poll carrying the fan-out horizon advances past
    # changeless windows; a stale heartbeat never rewinds
    st.note_horizon(10)
    assert st.horizon == 10
    st.note_horizon(4)
    assert st.horizon == 10
    # snapshot at a LOWER horizon is a deliberate rewind (bootstrap /
    # promote moved replica state non-monotonically): accepted
    assert st.apply(DeltaFrame(-1, 2, "view", (), True))
    assert st.horizon == 2 and st.value() == {}


def test_merge_frames_matches_sequential_apply():
    frames = [
        DeltaFrame(-1, 2, "view", ((("a", 1.0), 2), (("b", 1.0), 1)),
                   True),
        DeltaFrame(2, 4, "view", ((("a", 1.0), -2), (("c", 1.0), 3)),
                   False),
        DeltaFrame(4, 5, "view", ((("c", 1.0), -1),), False),
    ]
    seq = QueryState(canon_query("s", "view"))
    for f in frames:
        seq.apply(f)
    merged = merge_frames(frames)
    assert merged.snapshot and merged.to_h == 5
    one = QueryState(canon_query("s", "view"))
    one.apply(merged)
    assert one.value() == seq.value() and one.horizon == seq.horizon
    # zero-net rows are dropped from the merged frame entirely
    assert not any(kv == ("a", 1.0) for kv, _w in merged.rows)
    # topk conflation keeps only the newest ranked list
    t1 = DeltaFrame(0, 1, "topk", ((("a", 1.0), 5),), False)
    t2 = DeltaFrame(1, 3, "topk", ((("b", 1.0), 9),), False)
    m = merge_frames([t1, t2])
    assert m.rows == t2.rows and (m.from_h, m.to_h) == (0, 3)


# -- in-process: parity with the pull path ----------------------------------

def test_inprocess_parity_all_kinds(tmp_path):
    sched, ship, rep, hub, src, sink = make_stack(tmp_path)
    try:
        drive(sched, src, 3)
        pump_until_caught(ship, sched, rep)
        h_view = hub.open(sink.name)
        h_top = hub.open(sink.name, "topk", (5,))
        key = sorted(rep.view_at(sink.name)[1])[0]
        h_look = hub.open(sink.name, "lookup", (key,))
        # more windows after subscribing: snapshots first, then deltas
        drive(sched, src, 5, start=3)
        pump_until_caught(ship, sched, rep)
        horizon = rep.published_horizon()
        for h in (h_view, h_top, h_look):
            assert h.wait_horizon(horizon), \
                f"{h.state.query.kind} stuck at {h.horizon}"
            assert h.value() == pull_value(rep, sink, h.state.query)
            assert h.state.gaps == 0
        # the view handle saw real deltas, not a snapshot per window
        assert h_view.state.applied > 1
        h_view.close()
        assert hub.active_subs() == 2
    finally:
        close_stack(sched, ship, hub)


def test_changeless_windows_advance_horizon_without_frames(tmp_path):
    sched, ship, rep, hub, src, sink = make_stack(tmp_path)
    try:
        drive(sched, src, 2)
        pump_until_caught(ship, sched, rep)
        # a lookup on a key this workload never produces: every window
        # is changeless for it, yet the horizon must still advance
        # (freshness is part of the answer)
        h = hub.open(sink.name, "lookup", (("never", -1.0),))
        assert h.wait_horizon(rep.published_horizon())
        drive(sched, src, 4, start=2)
        pump_until_caught(ship, sched, rep)
        assert h.wait_horizon(rep.published_horizon())
        assert h.value() == 0.0
        assert h.state.applied == 1          # the seed snapshot only
        assert h.state.gaps == 0
    finally:
        close_stack(sched, ship, hub)


# -- slow subscribers: conflate / shed, never stall apply -------------------

def test_slow_subscriber_conflates_and_never_blocks_apply(tmp_path):
    sched, ship, rep, hub, src, sink = make_stack(tmp_path,
                                                  outbox_max=4)
    try:
        slow = hub.open(sink.name)            # never drained below
        fast = hub.open(sink.name, "topk", (3,))
        for leg in range(6):
            drive(sched, src, 4, start=leg * 4)
            pump_until_caught(ship, sched, rep)   # apply NEVER stalls
            fast.drain(wait_s=0.05)
        horizon = rep.published_horizon()
        assert horizon == 24
        assert fast.wait_horizon(horizon)
        deadline = time.monotonic() + 5.0
        while hub.conflations_total == 0 \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        assert hub.conflations_total > 0
        # the un-drained outbox is bounded by conflation, not unbounded
        shard = hub._shard(slow.token)
        assert len(shard.subs[slow.token].outbox) <= 4 + 1
        # and the conflated stream still reconstructs exactly
        assert slow.wait_horizon(horizon)
        assert slow.value() == pull_value(rep, sink, slow.state.query)
        assert slow.state.gaps == 0
    finally:
        close_stack(sched, ship, hub)


def test_overloaded_subscriber_sheds_to_snapshot(tmp_path):
    # a backlog too large even to conflate (conflate_max_rows tiny) is
    # shed: outbox cleared, one fresh snapshot on the next round
    sched, ship, rep, hub, src, sink = make_stack(
        tmp_path, outbox_max=2, conflate_max_rows=4)
    try:
        slow = hub.open(sink.name)
        for leg in range(4):
            drive(sched, src, 3, start=leg * 3)
            pump_until_caught(ship, sched, rep)
        deadline = time.monotonic() + 5.0
        while hub.sheds_total == 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert hub.sheds_total > 0
        horizon = rep.published_horizon()
        assert slow.wait_horizon(horizon)
        assert slow.value() == pull_value(rep, sink, slow.state.query)
        # the un-drained outbox held only a snapshot at shed time (a
        # shed clears it and a fresh snapshot replaces it), so the
        # client sees exactly one rebase — and zero gaps: shedding is
        # invisible to the contiguity rule
        assert slow.state.rebases >= 1
        assert slow.state.gaps == 0
    finally:
        close_stack(sched, ship, hub)


def test_shed_level_two_pauses_emission_then_rebases(tmp_path):
    sched, ship, rep, hub, src, sink = make_stack(tmp_path)
    try:
        h = hub.open(sink.name, "topk", (3,))
        drive(sched, src, 2)
        pump_until_caught(ship, sched, rep)
        assert h.wait_horizon(rep.published_horizon())
        hub.set_shed_level(2)                 # brownout: pause pushes
        drive(sched, src, 3, start=2)
        pump_until_caught(ship, sched, rep)
        frozen = h.horizon
        time.sleep(0.1)
        h.drain(wait_s=0.05)
        assert h.horizon == frozen          # nothing emitted
        hub.set_shed_level(0)                 # recover: snapshot rebase
        assert h.wait_horizon(rep.published_horizon())
        assert h.value() == pull_value(rep, sink, h.state.query)
        assert h.state.gaps == 0
    finally:
        close_stack(sched, ship, hub)


# -- min_horizon: read-your-writes for subscriptions ------------------------

def test_min_horizon_parks_snapshot_until_caught_up(tmp_path):
    sched, ship, rep, hub, src, sink = make_stack(tmp_path)
    try:
        drive(sched, src, 2)
        pump_until_caught(ship, sched, rep)
        want = rep.published_horizon() + 3
        h = hub.open(sink.name, min_horizon=want)
        h.drain(wait_s=0.1)
        assert h.horizon == -1              # parked, not served stale
        drive(sched, src, 3, start=2)
        pump_until_caught(ship, sched, rep)
        assert h.wait_horizon(want)
        assert h.state.rebases == 1
        assert h.value() == pull_value(rep, sink, h.state.query)
    finally:
        close_stack(sched, ship, hub)


# -- the crash seam ---------------------------------------------------------

def test_crash_seam_sub_fanout_rebases_on_restart(tmp_path):
    # CrashInjector(only='sub_fanout') kills the fan-out thread at the
    # worst point: the window queue is drained, the mirrors have not
    # folded it. Restart must rebase from replica state — freshness
    # lost, truth kept.
    crash = CrashInjector(1, only="sub_fanout")
    sched, ship, rep, hub, src, sink = make_stack(tmp_path,
                                                  crash=crash)
    try:
        h = hub.open(sink.name)
        drive(sched, src, 3)
        pump_until_caught(ship, sched, rep)
        deadline = time.monotonic() + 5.0
        while not crash.fired and time.monotonic() < deadline:
            time.sleep(0.01)
        assert crash.fired and crash.fired_seam == "sub_fanout"
        deadline = time.monotonic() + 5.0
        while hub.alive and time.monotonic() < deadline:
            time.sleep(0.01)
        assert not hub.alive                  # the thread really died
        drive(sched, src, 2, start=3)         # writes continue meanwhile
        pump_until_caught(ship, sched, rep)
        hub.start()                           # supervision revives it
        assert h.wait_horizon(rep.published_horizon())
        assert h.value() == pull_value(rep, sink, h.state.query)
        assert h.state.gaps == 0
        assert hub.rebases_total >= 1
    finally:
        close_stack(sched, ship, hub)


# -- over the wire: reconnect-resume ----------------------------------------

def wire_policy(name):
    return ReconnectPolicy(name, base_s=0.01, cap_s=0.05, jitter=0.0)


def pump_to(sub, horizon, timeout_s=10.0):
    deadline = time.monotonic() + timeout_s
    while sub.horizon < horizon and time.monotonic() < deadline:
        sub.pump(wait_s=0.05)
    assert sub.horizon >= horizon, \
        f"subscriber stuck at {sub.horizon} (< {horizon})"


def test_wire_reconnect_resumes_gap_free_dup_free(tmp_path):
    sched, ship, rep, hub, src, sink = make_stack(tmp_path)
    lt = LoopbackTransport()
    srv = SubscriptionServer(hub, lt).start()
    sub = Subscriber(lt, srv.address, sink.name, kind="view",
                     policy=wire_policy("sub-p0"))
    srv2 = None
    try:
        drive(sched, src, 3)
        pump_until_caught(ship, sched, rep)
        pump_to(sub, rep.published_horizon())
        assert sub.mode == "snapshot"
        applied_before = sub.frames_applied_total

        srv.close()                           # the partition
        for _ in range(3):
            sub.pump(wait_s=0.01)             # never raises while down
        drive(sched, src, 4, start=3)         # writes continue
        pump_until_caught(ship, sched, rep)

        srv2 = SubscriptionServer(hub, lt).start()   # the heal
        sub.retarget(srv2.address)
        pump_to(sub, rep.published_horizon())
        # the resume contract, asserted mechanically:
        assert sub.mode == "resume"           # cursor, not re-snapshot
        assert sub.gaps_total == 0
        assert sub.dups_skipped_total == 0
        assert sub.rebases_total == 1         # only the initial seed
        assert sub.frames_applied_total > applied_before
        assert sub.value() == pull_value(rep, sink, sub.query)
        assert sub.reconnects_total >= 1
    finally:
        sub.close()
        for s in (srv, srv2):
            if s is not None:
                s.close()
        close_stack(sched, ship, hub)


def test_wire_expired_subscription_answers_gone_then_reregisters(
        tmp_path):
    sched, ship, rep, hub, src, sink = make_stack(tmp_path,
                                                  expire_s=0.2)
    lt = LoopbackTransport()
    srv = SubscriptionServer(hub, lt).start()
    sub = Subscriber(lt, srv.address, sink.name, kind="topk",
                     params=(4,), policy=wire_policy("sub-p1"))
    try:
        drive(sched, src, 2)
        pump_until_caught(ship, sched, rep)
        pump_to(sub, rep.published_horizon())
        time.sleep(0.5)                       # idle past expire_s
        deadline = time.monotonic() + 5.0
        while hub.reaped_total == 0 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert hub.reaped_total >= 1          # server forgot us
        drive(sched, src, 2, start=2)
        pump_until_caught(ship, sched, rep)
        pump_to(sub, rep.published_horizon())  # "gone" -> re-handshake
        assert sub.handshakes_total >= 2
        assert sub.gaps_total == 0
        assert sub.value() == pull_value(rep, sink, sub.query)
    finally:
        sub.close()
        srv.close()
        close_stack(sched, ship, hub)


# -- control plane: the conflate -> pause ladder ----------------------------

class _FakeTier:
    _closed = False
    live_workers = 1
    pump_threads = 1

    def graphs(self):
        return {}

    def ensure_workers(self):
        return 0


class _FakeHub:
    def __init__(self):
        self.levels = []
        self.backlog = 0

    def load(self):
        return {"active": 7, "backlog_windows": self.backlog,
                "slowest_lag": 0, "shed_level": 0, "horizon": 5}

    def set_shed_level(self, level):
        self.levels.append(level)


def test_control_plane_sub_shed_ladder_steps_and_recovers():
    fh = _FakeHub()
    cp = ControlPlane(
        _FakeTier(), registry=MetricsRegistry(),
        sampler=lambda now: {"graphs": {}, "ready_depth": 0,
                             "live_workers": 1},
        config=ControlConfig(sub_backlog_windows_max=4,
                             sub_breach_intervals=2,
                             sub_recover_intervals=2),
        subs=fh)
    now = 0.0

    def step():
        nonlocal now
        now += 0.05
        return cp.step(now)

    fh.backlog = 10                           # breached
    assert step() == []                       # hysteresis: 1st breach
    acts = step()                             # 2nd -> conflate
    assert [a["kind"] for a in acts] == ["sub_shed_step"]
    assert acts[0]["mode"] == "conflate" and acts[0]["level"] == 1
    assert acts[0]["active_subs"] == 7
    step()
    acts = step()                             # 2 more -> pause
    assert [a["kind"] for a in acts] == ["sub_shed_step"]
    assert acts[0]["mode"] == "pause" and cp.sub_shed_level == 2
    fh.backlog = 0                            # healthy again
    step()
    acts = step()                             # recover one rung
    assert [a["kind"] for a in acts] == ["sub_shed_recover"]
    assert acts[0]["level"] == 1
    step()
    acts = step()
    assert acts[0]["level"] == 0 and cp.sub_shed_level == 0
    assert fh.levels == [1, 2, 1, 0]


def test_control_plane_survives_hub_load_errors():
    class _Broken(_FakeHub):
        def load(self):
            raise RuntimeError("hub closing")

    cp = ControlPlane(
        _FakeTier(), registry=MetricsRegistry(),
        sampler=lambda now: {"graphs": {}, "ready_depth": 0,
                             "live_workers": 1},
        config=ControlConfig(sub_backlog_windows_max=1,
                             sub_breach_intervals=1),
        subs=_Broken())
    assert cp.step(0.05) == []                # tolerated, not fatal
    assert cp.sub_shed_level == 0


# -- consoles and telemetry -------------------------------------------------

def _snap(mono, **gauges):
    return {"schema": SNAPSHOT_SCHEMA, "ts_mono": mono,
            "ts_wall": 1000.0 + mono, "gauges": gauges}


def test_fleet_derives_sub_gauges_with_backfill_tolerance():
    clk_v = [10.0]
    agg = FleetAggregator(retention=8, stale_after_s=5.0,
                          clock=lambda: clk_v[0])
    agg.ingest("r0", _snap(1.0, **{"subs.active": 3,
                                   "subs.fanout_rows_total": 100,
                                   "subs.slowest_lag": 1,
                                   "subs.conflations_total": 2,
                                   "subs.sheds_total": 1}))
    agg.ingest("r0", _snap(3.0, **{"subs.active": 5,
                                   "subs.fanout_rows_total": 300,
                                   "subs.slowest_lag": 4,
                                   "subs.conflations_total": 2,
                                   "subs.sheds_total": 1}))
    agg.ingest("r1", _snap(1.0))              # pre-subs node: tolerated
    snap = agg.fleet_snapshot()
    r0, r1 = snap["nodes"]["r0"], snap["nodes"]["r1"]
    assert r0["subs_active"] == 5
    assert r0["sub_rows_s"] == pytest.approx(100.0)   # (300-100)/2s
    assert r0["sub_conflations"] == 3
    assert r0["sub_lag_windows"] == 4
    assert r1["subs_active"] is None and r1["sub_rows_s"] is None
    g = snap["gauges"]
    assert g["subs_active"] == 5
    assert g["sub_rows_s"] == pytest.approx(100.0)
    assert g["sub_lag_windows"] == 4
    # a fleet with no subs anywhere reports None, not zero
    agg2 = FleetAggregator(retention=4, stale_after_s=5.0,
                           clock=lambda: clk_v[0])
    agg2.ingest("r0", _snap(1.0))
    g2 = agg2.fleet_snapshot()["gauges"]
    assert g2["subs_active"] is None and g2["sub_rows_s"] is None


def test_hub_publishes_sub_gauges(tmp_path):
    sched, ship, rep, hub, src, sink = make_stack(tmp_path)
    reg = MetricsRegistry()
    try:
        hub.publish_metrics(reg)
        h = hub.open(sink.name)
        drive(sched, src, 2)
        pump_until_caught(ship, sched, rep)
        assert h.wait_horizon(rep.published_horizon())
        gauges = reg.snapshot()["gauges"]
        assert gauges["subs.active"] == 1
        assert gauges["subs.horizon"] == rep.published_horizon()
        assert gauges["subs.fanout_rows_total"] >= 1
        assert gauges["subs.shed_level"] == 0
    finally:
        close_stack(sched, ship, hub)
        assert "subs.active" not in reg.snapshot()["gauges"]


def test_cli_update_schema_and_render():
    q = canon_query("counts", "topk", (3,))
    ranked = ((("the", 2.0), 9), (("a", 1.0), 7))
    sub = SimpleNamespace(query=q, horizon=42,
                          value=lambda: ranked,
                          frames_applied_total=5, gaps_total=0,
                          dups_skipped_total=1, rebases_total=1,
                          conn_state="healthy")
    upd = make_update(sub, ts_wall=123.456)
    assert upd["schema"] == SUB_SCHEMA == "reflow.sub/1"
    assert upd["horizon"] == 42 and upd["kind"] == "topk"
    assert upd["rows"] == [[["the", 2.0], 9], [["a", 1.0], 7]]
    line = render_update(upd)
    assert "h=42" in line and "counts/topk" in line
    assert "gaps=0" in line
    # lookup updates carry the bare number
    sub.query = canon_query("counts", "lookup", (("the", 2.0),))
    sub.value = lambda: 9.0
    upd = make_update(sub, ts_wall=123.5)
    assert upd["rows"] == 9.0
    assert "value=9.0" in render_update(upd)
