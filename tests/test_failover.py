"""Promote-on-failure: differential kill-tests at every seam (mid-window,
mid-shipment, mid-checkpoint, mid-promotion), the zombie-writer fencing
invariant (rejected bytes are *never* merged), StaleRead/leader-fallback
behaviour through the promotion window, fake-clock detection logic with
zero sleeps, epoch recovery/persistence, and the failover gauges.

The differential oracle: a fresh ``DirtyScheduler`` folds the same
batch windows; exactly-once survives failover iff the promoted leader's
view equals the oracle's — no lost acked write, no double fold."""

import glob
import os

import numpy as np
import pytest

from reflow_tpu.obs import MetricsRegistry
from reflow_tpu.scheduler import DirtyScheduler
from reflow_tpu.serve import (ControlPlane, FailoverCoordinator,
                              HighestHorizonElection, LeaderReadAdapter,
                              ReadTier, ReplicaScheduler, ServeTier,
                              StaleRead)
from reflow_tpu.wal import (DurableScheduler, FencedWrite, SegmentShipper,
                            recover)
from reflow_tpu.wal.log import FENCE_STATE_SCHEMA, _FENCE_STATE_FILE
from reflow_tpu.workloads import wordcount


# -- helpers (test_replica.py idiom) ----------------------------------------

def make_leader(tmp_path, **kw):
    g, src, sink = wordcount.build_graph()
    kw.setdefault("fsync", "tick")
    sched = DurableScheduler(g, wal_dir=str(tmp_path / "wal"), **kw)
    return sched, src, sink


def make_replica(tmp_path, name="r0"):
    g, _src, _sink = wordcount.build_graph()
    return ReplicaScheduler(g, str(tmp_path / name), name=name)


def gen_windows(n, start=0, tag=""):
    """Deterministic commit windows: 2 batches per tick, stable ids —
    the same list feeds the system under test AND the oracle."""
    rng = np.random.default_rng(7 + start)
    out = []
    for t in range(start, start + n):
        out.append([(f"{tag}t{t}b{j}",
                     " ".join(f"w{int(x)}"
                              for x in rng.integers(0, 40, 8)))
                    for j in range(2)])
    return out


def apply_windows(sched, src, windows):
    for win in windows:
        for bid, text in win:
            sched.push(src, wordcount.ingest_lines([text]), batch_id=bid)
        sched.tick()


def oracle_view(windows):
    g, src, sink = wordcount.build_graph()
    ref = DirtyScheduler(g)
    apply_windows(ref, src, windows)
    return {kv: w for kv, w in ref.view(sink.name).items() if w != 0}


def live_view(sched, sink):
    return {kv: w for kv, w in sched.view(sink.name).items() if w != 0}


def pump_until_caught(ship, sched, replicas, max_rounds=100):
    sched.wal.sync()
    for _ in range(max_rounds):
        ship.pump_once()
        if all(r.published_horizon() == sched._tick for r in replicas):
            return
    raise AssertionError(
        f"replicas stuck: leader tick {sched._tick}, horizons "
        f"{[r.published_horizon() for r in replicas]}")


def make_cluster(tmp_path, n_replicas=2, **leader_kw):
    sched, src, sink = make_leader(tmp_path, **leader_kw)
    ship = SegmentShipper(sched.wal, leader_tick=lambda: sched._tick)
    replicas = [make_replica(tmp_path, f"r{i}") for i in range(n_replicas)]
    for r in replicas:
        ship.attach(r)
    return sched, src, sink, ship, replicas


def mirror_bytes(replica):
    return sum(os.path.getsize(p) for p in
               glob.glob(os.path.join(replica.mirror_dir, "*.wal")))


class FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt
        return self.t


# -- kill seam 1: mid-window ------------------------------------------------

def test_kill_mid_window_partial_window_truncated_and_replayed_once(tmp_path):
    # leader dies after pushing (and even syncing) half of window 4 but
    # before its tick marker: the promoted view must be exactly windows
    # 0..3 (holdback truncates the orphan), and resubmitting window 4
    # folds it exactly once — an already-acked batch dedups
    sched, src, sink, ship, replicas = make_cluster(tmp_path)
    done = gen_windows(4)
    apply_windows(sched, src, done)
    pump_until_caught(ship, sched, replicas)
    orphan = gen_windows(1, start=4)[0]
    for bid, text in orphan:
        sched.push(src, wordcount.ingest_lines([text]), batch_id=bid)
    sched.wal.sync()          # the partial window is even on disk
    ship.pump_once()          # ...and may be mirrored (staged, held back)

    coord = FailoverCoordinator(replicas, shipper=ship,
                                durable_kw={"committer": "inline"})
    acts = coord.promote_now(reason="test")
    assert acts and acts[0]["kind"] == "failover_promote"
    new = coord.leader_sched
    assert new.wal.epoch == 1 and new._tick == 4
    assert live_view(new, sink) == oracle_view(done)

    # producer resubmits: the orphan window folds exactly once...
    assert all(new.push(src, wordcount.ingest_lines([text]), batch_id=bid)
               for bid, text in orphan)
    new.tick()
    assert live_view(new, sink) == oracle_view(done + [orphan])
    # ...and an acked batch from the old reign dedups
    bid, text = done[2][0]
    assert not new.push(src, wordcount.ingest_lines([text]), batch_id=bid)
    coord.close()
    new.close()
    sched.close()


# -- kill seam 2: mid-shipment ----------------------------------------------

def test_kill_mid_shipment_final_drain_preserves_every_acked_window(tmp_path):
    # the leader dies with half its synced log still unshipped: the
    # coordinator's final drain must ship the rest before electing —
    # zero acked-write loss (acked ⊆ synced ⊆ shipped-after-drain)
    sched, src, sink, ship, replicas = make_cluster(tmp_path)
    windows = gen_windows(6)
    apply_windows(sched, src, windows[:3])
    pump_until_caught(ship, sched, replicas)
    apply_windows(sched, src, windows[3:])
    sched.wal.sync()          # acked (durable) but never shipped
    assert max(r.published_horizon() for r in replicas) == 3  # mid-flight

    coord = FailoverCoordinator(replicas, shipper=ship,
                                durable_kw={"committer": "inline"})
    acts = coord.promote_now(reason="test")
    assert coord.drained_bytes > 0 and acts[0]["drained_bytes"] > 0
    new = coord.leader_sched
    assert new._tick == 6
    assert live_view(new, sink) == oracle_view(windows)
    coord.close()
    new.close()
    sched.close()


# -- kill seam 3: mid-checkpoint --------------------------------------------

def test_kill_mid_checkpoint_promotes_from_checkpoint_plus_tail(tmp_path):
    # the winner checkpointed at window 3 and dies mid-save later (torn
    # meta.pkl.tmp on disk): promotion must recover from the good
    # checkpoint and replay the mirrored tail — exact parity at tick 6
    sched, src, sink, ship, replicas = make_cluster(tmp_path)
    early = gen_windows(3)
    apply_windows(sched, src, early)
    pump_until_caught(ship, sched, replicas)
    replicas[0].checkpoint()
    late = gen_windows(3, start=3)
    apply_windows(sched, src, late)
    pump_until_caught(ship, sched, replicas)
    with open(os.path.join(replicas[0].ckpt_dir, "meta.pkl.tmp"),
              "wb") as f:
        f.write(b"\x00garbage torn mid-checkpoint")

    coord = FailoverCoordinator(replicas, shipper=ship,
                                durable_kw={"committer": "inline"})
    coord.promote_now(reason="test")
    assert coord.winner is replicas[0] or coord.winner is replicas[1]
    new = coord.leader_sched
    assert new._tick == 6
    assert live_view(new, sink) == oracle_view(early + late)
    coord.close()
    new.close()
    sched.close()


# -- kill seam 4: mid-promotion (double failure) ----------------------------

def test_kill_mid_promotion_second_failover_epoch_two(tmp_path):
    # leader dies, A is promoted (epoch 1), commits one window — then A
    # dies too, mid-reign: a second coordinator must exclude A from the
    # election, promote B at epoch 2 with A's window intact, dedup A's
    # reign, and fence A's zombie writes
    sched, src, sink, ship, replicas = make_cluster(tmp_path, n_replicas=3)
    windows = gen_windows(4)
    apply_windows(sched, src, windows)
    pump_until_caught(ship, sched, replicas)

    c1 = FailoverCoordinator(replicas, shipper=ship,
                             durable_kw={"committer": "inline"})
    c1.promote_now(reason="test")
    a, a_sched = c1.winner, c1.leader_sched
    assert a_sched.wal.epoch == 1
    a_win = gen_windows(1, start=4, tag="a")[0]
    apply_windows(a_sched, src, [a_win])
    survivors = [r for r in replicas if r is not a]
    pump_until_caught(c1.new_shipper, a_sched, survivors)

    c2 = FailoverCoordinator(replicas, shipper=c1.new_shipper,
                             durable_kw={"committer": "inline"})
    c2.promote_now(reason="test")
    b, b_sched = c2.winner, c2.leader_sched
    assert b is not a and b_sched.wal.epoch == 2
    assert b._epoch == 2
    assert b_sched._tick == 5
    assert live_view(b_sched, sink) == oracle_view(windows + [a_win])
    # a batch A committed-and-shipped dedups on B
    bid, text = a_win[0]
    assert not b_sched.push(src, wordcount.ingest_lines([text]),
                            batch_id=bid)
    # both dead leaders are zombies now
    with pytest.raises(FencedWrite):
        a_sched.push(src, wordcount.ingest_lines(["zombie a"]),
                     batch_id="za")
    with pytest.raises(FencedWrite):
        sched.push(src, wordcount.ingest_lines(["zombie 0"]),
                   batch_id="z0")
    c1.close()
    c2.close()
    b_sched.close()
    a_sched.close()
    sched.close()


# -- zombie writer: rejected, never merged ----------------------------------

def test_zombie_writer_every_fenced_byte_rejected_never_merged(tmp_path):
    # partition scenario: the old leader was never locally fenced (it
    # can't see the coordinator) and keeps committing + shipping epoch-0
    # bytes. Every one of them must be NACKed by epoch before a single
    # byte hits a mirror — view, horizon, and mirror bytes unchanged
    sched, src, sink, ship, replicas = make_cluster(tmp_path)
    windows = gen_windows(4)
    apply_windows(sched, src, windows)
    pump_until_caught(ship, sched, replicas)

    winner, survivor = replicas
    new = winner.promote(epoch=1, committer="inline")
    survivor.reanchor(1)
    want = oracle_view(windows)
    before_bytes = mirror_bytes(survivor)
    before_h = survivor.published_horizon()

    # the unfenced zombie commits two more windows and ships them
    apply_windows(sched, src, gen_windows(2, start=4, tag="zombie"))
    sched.wal.sync()
    ship.pump_once()
    assert ship.fence_nacks > 0
    assert survivor.fence_rejected_shipments > 0
    assert winner.fence_rejected_shipments > 0
    assert survivor.published_horizon() == before_h
    assert mirror_bytes(survivor) == before_bytes       # zero bytes merged
    _h, got = survivor.view_at(sink.name)
    assert got == want
    # the shipper marked both followers fenced: it stops offering
    assert ship.pump_once() == 0
    new.close()
    sched.close()


# -- satellite: ReadTier through the promotion window -----------------------

def test_read_tier_stale_then_leader_fallback_through_promotion(tmp_path):
    sched, src, sink, ship, replicas = make_cluster(tmp_path)
    windows = gen_windows(3)
    apply_windows(sched, src, windows)
    pump_until_caught(ship, sched, replicas)
    tier = ReadTier(replicas, leader=LeaderReadAdapter(sched))

    # leader just died: reads beyond the replicas' horizon go stale
    tier.leader = None
    with pytest.raises(StaleRead):
        tier.view_at(sink.name, min_horizon=4)
    assert tier.stale_reads == 1
    # replica-served reads keep working through the outage
    res = tier.view_at(sink.name, min_horizon=3)
    assert res.source.startswith("r") and res.horizon == 3

    new = tier.promote(replicas[0], epoch=1, committer="inline")
    assert all(x is not replicas[0] for x in tier.replicas)
    apply_windows(new, src, gen_windows(1, start=3))
    res = tier.view_at(sink.name, min_horizon=4)
    assert res.source == "leader" and res.horizon == 4
    assert tier.leader_fallbacks == 1
    assert res.value == oracle_view(windows + gen_windows(1, start=3))
    new.close()
    sched.close()


# -- fake-clock detection (no sleeps) ---------------------------------------

class _StubReplica:
    def __init__(self, name, horizon):
        self.name = name
        self._h = horizon
        self.promoted = False

    def published_horizon(self):
        return self._h


def _stub_coord(sample, **kw):
    calls = []

    def promote_fn(winner, epoch):
        calls.append((winner.name, epoch))
        return object()

    kw.setdefault("confirm_intervals", 2)
    coord = FailoverCoordinator(
        [_StubReplica("a", 5), _StubReplica("b", 7)],
        sampler=sample, promote_fn=promote_fn, **kw)
    return coord, calls


def test_coordinator_fires_after_confirm_intervals_single_shot():
    clk = FakeClock()
    dead = {"v": False}
    coord, calls = _stub_coord(
        lambda now: {"committer_dead": dead["v"], "pump_failed": False,
                     "beat": 1})
    assert coord.step(clk.advance(0.05)) == []
    dead["v"] = True
    assert coord.step(clk.advance(0.05)) == []        # streak 1 of 2
    acts = coord.step(clk.advance(0.05))              # streak 2: fire
    assert [a["kind"] for a in acts] == ["failover_promote"]
    assert acts[0]["winner"] == "b"                   # highest horizon
    assert acts[0]["reason"] == "committer_dead"
    assert calls == [("b", 1)] and coord.epoch == 1
    # single-fire: the coordinator never promotes twice
    assert coord.step(clk.advance(0.05)) == []
    assert calls == [("b", 1)]


def test_coordinator_flapping_never_fires():
    clk = FakeClock()
    seq = iter([True, False] * 10)
    coord, calls = _stub_coord(
        lambda now: {"committer_dead": next(seq), "pump_failed": False,
                     "beat": 1})
    for _ in range(20):
        assert coord.step(clk.advance(0.05)) == []
    assert calls == [] and not coord.promoted


def test_coordinator_heartbeat_timeout_and_beat_reset():
    clk = FakeClock()
    beat = {"v": 1}
    coord, calls = _stub_coord(
        lambda now: {"committer_dead": False, "pump_failed": False,
                     "beat": beat["v"]},
        heartbeat_timeout_s=0.2, confirm_intervals=2)
    coord.step(clk.advance(0.05))
    beat["v"] = 2                                     # fresh beat: age 0
    coord.step(clk.advance(0.3))
    assert coord.heartbeat_age_s == 0.0
    coord.step(clk.advance(0.25))                     # stale: streak 1
    assert coord.heartbeat_age_s > 0.2 and not coord.promoted
    acts = coord.step(clk.advance(0.25))              # streak 2: fire
    assert acts[0]["reason"] == "heartbeat_timeout"
    assert calls == [("b", 1)]


def test_control_plane_steps_failover_coordinator(tmp_path):
    clk = FakeClock()
    coord, calls = _stub_coord(
        lambda now: {"committer_dead": True, "pump_failed": False,
                     "beat": 1},
        confirm_intervals=1)
    tier = ServeTier()
    cp = ControlPlane(
        tier, specs={}, clock=clk, failover=coord,
        sampler=lambda now: {"graphs": {}, "ready_depth": 0,
                             "live_workers": 1, "target_workers": 1})
    acts = cp.step(clk.advance(0.05))
    assert any(a["kind"] == "failover_promote" for a in acts)
    assert calls == [("b", 1)]
    tier.close()


# -- end to end: tier-hosted leader killed mid-stream, rebound in place -----

def test_tier_hosted_failover_resubmit_exactly_once(tmp_path):
    # the full serving path: a tier-hosted durable leader is killed
    # mid-window by a crash seam; the coordinator detects the failed
    # pump through its default sampler, promotes a replica, swings the
    # ReadTier fallback and revives the SAME handle over the new
    # leader. Producers resubmit every id: committed-and-shipped ids
    # dedup, the orphaned window folds exactly once — differential
    # equality against a bare fold of every batch
    import time as _time

    from reflow_tpu.serve import (CoalesceWindow, FrontendClosed,
                                  GraphConfig, PumpCrashed)
    from reflow_tpu.utils.faults import CrashInjector

    crash = CrashInjector(at=2, only="pump_before_tick@wal")
    tier = ServeTier(max_bytes=8 << 20, pump_threads=2, crash=crash)
    g, src, sink = wordcount.build_graph()
    dsched = DurableScheduler(g, wal_dir=str(tmp_path / "wal"),
                              fsync="record")
    ship = SegmentShipper(dsched.wal, leader_tick=lambda: dsched._tick)
    replicas = [make_replica(tmp_path, f"r{i}") for i in range(2)]
    for r in replicas:
        ship.attach(r)
    cfg = GraphConfig(window=CoalesceWindow(max_rows=256, max_ticks=8,
                                            max_latency_s=0.002))
    h = tier.register("wal", dsched, cfg)
    read_tier = ReadTier(replicas, leader=LeaderReadAdapter(dsched))
    coord = FailoverCoordinator(
        replicas, shipper=ship, handle=h, read_tier=read_tier,
        confirm_intervals=1, durable_kw={"committer": "inline"})

    sent = [(f"m{j}", wordcount.ingest_lines([f"w{j % 4} x{j % 7}"]))
            for j in range(30)]
    tks = []
    for bid, batch in sent:
        try:
            tks.append(h.submit(src, batch, batch_id=bid))
        except FrontendClosed:
            break
        ship.pump_once()
        _time.sleep(0.001)  # several windows
    crashed = 0
    for t in tks:
        try:
            t.result(timeout=10)
        except PumpCrashed:
            crashed += 1
    assert crash.fired and crashed > 0

    # detection through the *default* sampler: the pump is "failed"
    acts = coord.step()
    assert [a["kind"] for a in acts] == ["failover_promote"]
    assert acts[0]["reason"] == "pump_failed" and acts[0]["rebound"]
    new = coord.leader_sched
    assert new.wal.epoch == 1
    assert read_tier.leader.sched is new

    # resubmit EVERY id through the same handle: exactly-once
    results = [h.submit(src, batch, batch_id=bid).result(10)
               for bid, batch in sent]
    h.flush(timeout=10)
    assert any(r.status == "deduped" for r in results)
    assert any(r.applied for r in results)
    ref_g, ref_src, ref_sink = wordcount.build_graph()
    ref = DirtyScheduler(ref_g)
    for _bid, batch in sent:
        ref.push(ref_src, batch)
        ref.tick()
    assert live_view(new, sink) == {
        kv: w for kv, w in ref.view(ref_sink.name).items() if w != 0}
    # the old leader is fenced: a zombie append is rejected, counted
    with pytest.raises(FencedWrite):
        dsched.push(src, wordcount.ingest_lines(["zombie"]), batch_id="z")
    assert dsched.wal.fence_rejected_appends == 1
    coord.close()
    tier.close()
    new.close()
    dsched.close()


# -- epoch persistence / recovery -------------------------------------------

def test_recovery_adopts_highest_record_epoch(tmp_path):
    g, src, sink = wordcount.build_graph()
    d = str(tmp_path / "wal")
    sched = DurableScheduler(g, wal_dir=d, fsync="tick",
                             committer="inline", epoch=3)
    apply_windows(sched, src, gen_windows(2))
    sched.close()

    g2, src2, sink2 = wordcount.build_graph()
    fresh = DurableScheduler(g2, wal_dir=d, fsync="tick",
                             committer="inline")
    report = recover(fresh, d)
    assert report.epoch == 3
    assert fresh.wal.epoch == 3
    assert live_view(fresh, sink2) == oracle_view(gen_windows(2))
    fresh.close()


def test_restarted_zombie_stays_fenced(tmp_path):
    import json
    g, src, sink = wordcount.build_graph()
    d = str(tmp_path / "wal")
    sched = DurableScheduler(g, wal_dir=d, fsync="tick",
                             committer="inline")
    apply_windows(sched, src, gen_windows(1))
    assert sched.wal.fence(2)
    with pytest.raises(FencedWrite):
        sched.push(src, wordcount.ingest_lines(["x"]), batch_id="zz")
    sched.close()
    # fencing survives on disk next to the segments...
    with open(os.path.join(d, _FENCE_STATE_FILE)) as f:
        saved = json.load(f)
    assert saved["schema"] == FENCE_STATE_SCHEMA
    assert saved["fenced_by"] == 2
    # ...so a restarted zombie process is still a zombie
    g2, src2, _ = wordcount.build_graph()
    again = DurableScheduler(g2, wal_dir=d, fsync="tick",
                             committer="inline")
    assert again.wal.fenced
    with pytest.raises(FencedWrite):
        again.push(src2, wordcount.ingest_lines(["x"]), batch_id="z2")
    again.close()


# -- inspection tools -------------------------------------------------------

def _load_tool(name):
    import importlib.util
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(repo, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_inspect_tools_surface_failover(tmp_path, capsys):
    import json

    from reflow_tpu import obs
    from reflow_tpu.obs import trace as trace_mod
    trace_mod.reset()
    obs.enable()
    try:
        sched, src, sink, ship, replicas = make_cluster(tmp_path)
        apply_windows(sched, src, gen_windows(3))
        pump_until_caught(ship, sched, replicas)
        coord = FailoverCoordinator(replicas, shipper=ship,
                                    durable_kw={"committer": "inline"})
        coord.promote_now(reason="test")
        with pytest.raises(FencedWrite):
            sched.push(src, wordcount.ingest_lines(["z"]), batch_id="z")
        # an unfenced survivor of the partition ships one zombie chunk
        apply_windows(coord.leader_sched, src, gen_windows(1, start=3))
        coord.leader_sched.wal.sync()
        trace_path = str(tmp_path / "trace.json")
        obs.export_chrome_trace(trace_path)
    finally:
        obs.disable()
        trace_mod.reset()

    # wal_inspect --json: the zombie's log carries its fenced lineage
    wi = _load_tool("wal_inspect")
    assert wi.main([str(tmp_path / "wal"), "--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    ep = out["epochs"]
    assert ep["record_max"] == 0 and ep["epoch"] == 0
    assert ep["fenced"] and ep["fenced_by"] == 1
    assert ep["rejected_appends"] == 1
    assert wi.main([str(tmp_path / "wal")]) == 0
    assert "FENCED by epoch 1" in capsys.readouterr().out
    # ...and the promoted winner's log is on the new epoch, unfenced
    assert wi.main([coord.leader_sched.wal.wal_dir, "--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["epochs"]["epoch"] == 1 and not out["epochs"]["fenced"]
    assert out["segments_detail"][-1]["epoch"] == 1

    # trace_inspect: the promotion timeline, span by span
    ti = _load_tool("trace_inspect")
    assert ti.main([trace_path, "--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    fo = out["failover"]
    assert fo["promotions"] == 1
    assert fo["fence_rejects"]["append"] == 1
    kinds = {e["event"] for e in fo["events"]}
    assert kinds == {"elect", "replay"}
    assert ti.main([trace_path]) == 0
    human = capsys.readouterr().out
    assert "failover: 1 promotion(s)" in human
    coord.close()
    coord.leader_sched.close()
    sched.close()


# -- metrics ----------------------------------------------------------------

def test_failover_metrics_published(tmp_path):
    sched, src, sink, ship, replicas = make_cluster(tmp_path)
    apply_windows(sched, src, gen_windows(2))
    pump_until_caught(ship, sched, replicas)
    coord = FailoverCoordinator(replicas, shipper=ship,
                                durable_kw={"committer": "inline"})
    reg = MetricsRegistry()
    coord.publish_metrics(reg)
    assert reg.value("failover.epoch") == 0
    assert reg.value("failover.promotions_total") == 0
    coord.promote_now(reason="test")
    with pytest.raises(FencedWrite):
        sched.push(src, wordcount.ingest_lines(["z"]), batch_id="z")
    apply_windows(coord.leader_sched, src, gen_windows(1, start=2))
    snap = reg.snapshot()
    assert snap["gauges"]["failover.epoch"] == 1
    assert snap["gauges"]["failover.promotions_total"] == 1
    assert snap["gauges"]["fence.rejected_appends"] == 1
    assert snap["gauges"]["leader.heartbeat_age_s"] >= 0.0
    coord.close()
    coord.leader_sched.close()
    sched.close()
