import numpy as np

from reflow_tpu.delta import DeltaBatch, Spec, collection_counter


def test_empty():
    b = DeltaBatch.empty()
    assert len(b) == 0
    assert len(DeltaBatch.concat([b, b])) == 0


def test_from_pairs_and_consolidate():
    b = DeltaBatch.from_pairs([("a", 1), ("b", 2), ("a", 1)])
    assert len(b) == 3
    c = b.consolidate()
    assert c.to_counter() == {("a", 1): 2, ("b", 2): 1}


def test_retraction_cancels():
    ins = DeltaBatch.from_pairs([("a", 1)])
    ret = DeltaBatch.from_pairs([("a", 1)], weight=-1)
    assert DeltaBatch.concat([ins, ret]).consolidate().to_counter() == {}


def test_numeric_columns():
    b = DeltaBatch(np.array([3, 1, 3]), np.array([1.0, 2.0, 3.0]),
                   np.array([1, 1, -1]))
    acc = collection_counter([b])
    assert acc == {(3, 1.0): 1, (1, 2.0): 1, (3, 3.0): -1}


def test_spec():
    s = Spec((768,), np.float32).with_key_space(1000)
    assert s.key_space == 1000
    e = DeltaBatch.empty(s)
    assert e.values.shape == (0, 768)
