"""PageRank end-to-end: fixpoint iteration, both executors, vs NumPy oracle
(SURVEY.md §4e — small-scale benchmark-config test)."""

import numpy as np
import pytest

from reflow_tpu import DirtyScheduler
from reflow_tpu.executors import get_executor
from reflow_tpu.workloads import pagerank

N, E = 40, 160
TOL = 1e-5


def run_pagerank(executor_name, web, churn_ticks=0):
    pg = pagerank.build_graph(web.n_nodes, tol=TOL)
    sched = DirtyScheduler(pg.graph, get_executor(executor_name),
                           max_loop_iters=500)
    sched.push(pg.teleport, pagerank.teleport_batch(web.n_nodes))
    sched.push(pg.edges, web.initial_batch())
    r = sched.tick()
    assert r.quiesced, "fixpoint did not converge"
    churn_results = []
    for _ in range(churn_ticks):
        sched.push(pg.edges, web.churn(0.05))
        cr = sched.tick()
        assert cr.quiesced
        churn_results.append(cr)
    ranks = sched.read_table(pg.new_rank)
    return ranks, churn_results, sched


as_array = pagerank.ranks_to_array


def test_pagerank_cpu_matches_numpy_reference():
    web = pagerank.WebGraph.random(N, E, seed=1)
    ranks, _, _ = run_pagerank("cpu", web)
    ref = pagerank.reference_ranks(web)
    np.testing.assert_allclose(as_array(ranks, N), ref, atol=5e-4)


def test_pagerank_tpu_matches_numpy_reference():
    web = pagerank.WebGraph.random(N, E, seed=1)
    ranks, _, _ = run_pagerank("tpu", web)
    ref = pagerank.reference_ranks(web)
    np.testing.assert_allclose(as_array(ranks, N), ref, atol=5e-4)


def test_pagerank_incremental_churn_differential():
    """After churn ticks, cpu and tpu agree with each other AND with a
    from-scratch NumPy recompute on the churned graph (incremental-vs-full)."""
    web_cpu = pagerank.WebGraph.random(N, E, seed=7)
    web_tpu = pagerank.WebGraph.random(N, E, seed=7)
    ranks_cpu, _, _ = run_pagerank("cpu", web_cpu, churn_ticks=3)
    ranks_tpu, _, _ = run_pagerank("tpu", web_tpu, churn_ticks=3)
    assert np.array_equal(web_cpu.dst, web_tpu.dst)  # same churn sequence
    a, b = as_array(ranks_cpu, N), as_array(ranks_tpu, N)
    np.testing.assert_allclose(a, b, atol=2e-3)
    ref = pagerank.reference_ranks(web_cpu)
    np.testing.assert_allclose(a, ref, atol=2e-3)


def test_churn_tick_is_incremental():
    """A churn tick must touch far fewer deltas than the cold start."""
    web = pagerank.WebGraph.random(200, 800, seed=3)
    pg = pagerank.build_graph(web.n_nodes, tol=1e-4)
    sched = DirtyScheduler(pg.graph, max_loop_iters=500)
    sched.push(pg.teleport, pagerank.teleport_batch(web.n_nodes))
    sched.push(pg.edges, web.initial_batch())
    cold = sched.tick()
    sched.push(pg.edges, web.churn(0.01))
    warm = sched.tick()
    assert warm.quiesced
    assert warm.delta_ops < cold.delta_ops / 5


def test_loop_requires_close():
    g = pagerank.build_graph(8).graph
    assert g.loops[0].back_input is not None


def test_pagerank_streaming_matches_synced():
    """VERDICT r2 weak #6: tick(sync=False) had zero test coverage. The
    pipelined streaming path must produce bit-for-bit the same converged
    state as synchronous ticking over the same churn sequence."""
    web_a = pagerank.WebGraph.random(N, E, seed=11)
    web_b = pagerank.WebGraph.random(N, E, seed=11)

    def run(web, sync):
        pg = pagerank.build_graph(web.n_nodes, tol=TOL)
        sched = DirtyScheduler(pg.graph, get_executor("tpu"),
                               max_loop_iters=500)
        sched.push(pg.teleport, pagerank.teleport_batch(web.n_nodes))
        sched.push(pg.edges, web.initial_batch())
        sched.tick()  # cold build synced in both runs
        results = []
        for _ in range(4):
            sched.push(pg.edges, web.churn(0.05))
            results.append(sched.tick(sync=sync))
        for r in results:
            r.block()  # streaming sync point (no-op when sync=True)
        assert all(r.quiesced for r in results)
        return sched.read_table(pg.new_rank), results

    ranks_sync, res_sync = run(web_a, True)
    ranks_stream, res_stream = run(web_b, False)
    assert np.array_equal(web_a.dst, web_b.dst)  # same churn sequence
    assert set(ranks_sync) == set(ranks_stream)
    for k in ranks_sync:
        assert ranks_sync[k] == ranks_stream[k]  # same programs: bitwise
    # streaming reports the same per-tick pass/row counts after block()
    assert [r.passes for r in res_sync] == [r.passes for r in res_stream]
    assert ([r.deltas_in for r in res_sync]
            == [r.deltas_in for r in res_stream])


def test_pagerank_macro_tick_matches_sequential():
    """tick_many (K ticks lax.scan-fused into ONE device execution — the
    tunnel-overhead amortization fast path) must produce bit-for-bit the
    same state and the same aggregate tick metadata as K sequential
    streaming ticks over the same churn sequence."""
    web_a = pagerank.WebGraph.random(N, E, seed=13)
    web_b = pagerank.WebGraph.random(N, E, seed=13)
    K = 3

    def prep(web):
        pg = pagerank.build_graph(web.n_nodes, tol=TOL)
        sched = DirtyScheduler(pg.graph, get_executor("tpu"),
                               max_loop_iters=500)
        sched.push(pg.teleport, pagerank.teleport_batch(web.n_nodes))
        sched.push(pg.edges, web.initial_batch())
        sched.tick()
        return pg, sched, [web.churn(0.05) for _ in range(K)]

    pg_a, sched_a, churns_a = prep(web_a)
    results = []
    for b in churns_a:
        sched_a.push(pg_a.edges, b)
        results.append(sched_a.tick(sync=False))
    for r in results:
        r.block()

    pg_b, sched_b, churns_b = prep(web_b)
    agg = sched_b.tick_many([{pg_b.edges: b} for b in churns_b]).block()

    ranks_a = sched_a.read_table(pg_a.new_rank)
    ranks_b = sched_b.read_table(pg_b.new_rank)
    assert set(ranks_a) == set(ranks_b)
    for k in ranks_a:
        assert ranks_a[k] == ranks_b[k]
    assert agg.quiesced
    assert agg.passes == sum(r.passes for r in results)
    assert agg.deltas_in == sum(r.deltas_in for r in results)
    assert agg.tick == sched_a._tick


def test_macro_tick_fallback_cpu_executor():
    """tick_many on an executor without the fused path (the CPU oracle)
    falls back to sequential ticks with identical semantics."""
    web = pagerank.WebGraph.random(N, E, seed=17)
    web2 = pagerank.WebGraph.random(N, E, seed=17)

    def prep(web, name):
        pg = pagerank.build_graph(web.n_nodes, tol=TOL)
        sched = DirtyScheduler(pg.graph, get_executor(name),
                               max_loop_iters=500)
        sched.push(pg.teleport, pagerank.teleport_batch(web.n_nodes))
        sched.push(pg.edges, web.initial_batch())
        sched.tick()
        return pg, sched

    pg, sched = prep(web, "cpu")
    churns = [web.churn(0.05) for _ in range(2)]
    agg = sched.tick_many([{pg.edges: b} for b in churns]).block()
    assert agg.quiesced

    pg2, sched2 = prep(web2, "cpu")
    for b in churns:
        sched2.push(pg2.edges, b)
        sched2.tick()
    assert (sched.read_table(pg.new_rank)
            == sched2.read_table(pg2.new_rank))


# -- deferred fixpoint (cross-tick residual deferral, VERDICT r4 #1) -------

def _run_deferred(executor_name, defer, seed=21, churn_ticks=6,
                  drain=True, arena=4096, settle=False):
    web = pagerank.WebGraph.random(N, E, seed=seed)
    pg = pagerank.build_graph(web.n_nodes, tol=TOL, arena_capacity=arena,
                              defer_passes=defer)
    sched = DirtyScheduler(pg.graph, get_executor(executor_name),
                           max_loop_iters=500)
    sched.push(pg.teleport, pagerank.teleport_batch(web.n_nodes))
    sched.push(pg.edges, web.initial_batch())
    sched.tick(sync=False)
    if settle:
        # converge the cold build before streaming churn: mid-stream
        # accuracy then reflects steady-state churn-tracking lag, not
        # the (deliberately amortized) initial convergence
        sched.drain(pg.edges)
    for _ in range(churn_ticks):
        sched.push(pg.edges, web.churn(0.05))
        sched.tick(sync=False)
    if drain:
        sched.drain(pg.edges)
    return web, pg, sched


def test_deferred_drain_matches_reference():
    """defer_passes caps loop passes per tick; drain() flushes the carried
    residue to the same fixpoint a quiescent schedule reaches (within the
    tol-lag band of the independent NumPy oracle)."""
    for defer in (1, 2, 4):
        web, pg, sched = _run_deferred("tpu", defer)
        ranks = as_array(sched.read_table(pg.new_rank), N)
        ref = pagerank.reference_ranks(web)
        np.testing.assert_allclose(ranks, ref, atol=5e-4)


def test_deferred_left_table_consistency():
    """After drain the Join's folded left table must equal the Reduce's
    emitted table exactly — the deferred left-table patch (A = emitted -
    resid) reduces to the quiescent formula at resid == 0."""
    web, pg, sched = _run_deferred("tpu", 2)
    jt = sched.read_table(pg.join)
    rt = sched.read_table(pg.new_rank)
    assert set(jt) == set(rt)
    for k in rt:
        assert jt[k] == rt[k]


def test_deferred_mid_stream_accuracy_bounded():
    """Without drain, ranks lag full convergence by the in-flight mass;
    for PageRank the lag is geometrically damped (d/(1-d) amplification),
    so mid-stream views stay within a small multiple of the drained
    band. This is the accuracy contract of docs/guide.md."""
    web, pg, sched = _run_deferred("tpu", 2, drain=False, settle=True)
    ranks = as_array(sched.read_table(pg.new_rank), N)
    ref = pagerank.reference_ranks(web)
    mid_err = np.abs(ranks - ref).max()
    sched.drain(pg.edges)
    drained = as_array(sched.read_table(pg.new_rank), N)
    drained_err = np.abs(drained - ref).max()
    # 5% churn/tick at defer=2 on a 64-node graph is a brutal regime (the
    # whole rank vector reshuffles every few ticks); the contract is that
    # the lag stays within a small multiple of the per-tick injected mass
    # and collapses to the drained band on drain
    assert mid_err < 0.2, mid_err
    assert drained_err < 5e-4, drained_err


def test_deferred_sharded_matches_tpu():
    """The sharded executor runs the identical deferred schedule inside
    one shard_map region — results agree with the single-device program
    to f32 reduction-order noise."""
    web_a, pg_a, sched_a = _run_deferred("tpu", 2)
    web_b, pg_b, sched_b = _run_deferred("sharded", 2)
    assert np.array_equal(web_a.dst, web_b.dst)
    a = as_array(sched_a.read_table(pg_a.new_rank), N)
    b = as_array(sched_b.read_table(pg_b.new_rank), N)
    np.testing.assert_allclose(a, b, atol=1e-5)


def test_deferred_checkpoint_roundtrip_with_live_residue():
    """The carried residue is SEMANTIC state: a checkpoint taken
    mid-stream (residue live) must restore it, or in-flight rank mass
    would be silently lost. Restore drops the derived CSR cache, so
    agreement is to f32 reduction-order noise, not bitwise."""
    import tempfile

    from reflow_tpu.utils.checkpoint import load_checkpoint, save_checkpoint

    web = pagerank.WebGraph.random(N, E, seed=23)
    pg = pagerank.build_graph(N, tol=TOL, arena_capacity=4096,
                              defer_passes=2)
    sched = DirtyScheduler(pg.graph, get_executor("tpu"), max_loop_iters=500)
    sched.push(pg.teleport, pagerank.teleport_batch(N))
    sched.push(pg.edges, web.initial_batch())
    sched.tick(sync=False)
    churns = [web.churn(0.05) for _ in range(6)]
    for b in churns[:3]:
        sched.push(pg.edges, b)
        sched.tick(sync=False)
    with tempfile.TemporaryDirectory() as td:
        save_checkpoint(sched, td)
        pg2 = pagerank.build_graph(N, tol=TOL, arena_capacity=4096,
                                   defer_passes=2)
        sched2 = DirtyScheduler(pg2.graph, get_executor("tpu"),
                                max_loop_iters=500)
        load_checkpoint(sched2, td)
    # the restored residue must be live (mid-stream, defer=2)
    resid = np.asarray(sched2.executor.states[pg2.ranks.id]["resid"])
    assert np.any(resid != 0)
    for sch, pgx in ((sched, pg), (sched2, pg2)):
        for b in churns[3:]:
            sch.push(pgx.edges, b)
            sch.tick(sync=False)
        sch.drain(pgx.edges)
    a = as_array(sched.read_table(pg.new_rank), N)
    b = as_array(sched2.read_table(pg2.new_rank), N)
    np.testing.assert_allclose(a, b, atol=1e-5)


def test_deferred_macro_tick_matches_sequential():
    """tick_many carries the residue through its lax.scan (it lives in
    the op-state carry): K fused deferred ticks == K sequential streaming
    deferred ticks, bitwise."""
    web_a = pagerank.WebGraph.random(N, E, seed=29)
    web_b = pagerank.WebGraph.random(N, E, seed=29)

    def prep(web):
        pg = pagerank.build_graph(web.n_nodes, tol=TOL, arena_capacity=4096,
                                  defer_passes=2)
        sched = DirtyScheduler(pg.graph, get_executor("tpu"),
                               max_loop_iters=500)
        sched.push(pg.teleport, pagerank.teleport_batch(web.n_nodes))
        sched.push(pg.edges, web.initial_batch())
        sched.tick(sync=False)
        return pg, sched, [web.churn(0.05) for _ in range(3)]

    pg_a, sched_a, churns_a = prep(web_a)
    for b in churns_a:
        sched_a.push(pg_a.edges, b)
        sched_a.tick(sync=False)

    pg_b, sched_b, churns_b = prep(web_b)
    sched_b.tick_many([{pg_b.edges: b} for b in churns_b]).block()

    ranks_a = sched_a.read_table(pg_a.new_rank)
    ranks_b = sched_b.read_table(pg_b.new_rank)
    assert set(ranks_a) == set(ranks_b)
    for k in ranks_a:
        assert ranks_a[k] == ranks_b[k]
    ra = np.asarray(sched_a.executor.states[pg_a.ranks.id]["resid"])
    rb = np.asarray(sched_b.executor.states[pg_b.ranks.id]["resid"])
    assert np.array_equal(ra, rb)
