"""WAL shipping + read replicas: protocol round-trip parity, torn and
tampered shipments (never apply a partial commit window), restart-resume
from checkpoint + shipped tail (never from segment 0), checkpoint-
anchored bootstrap, leader-truncation re-anchor, and horizon-aware read
routing with leader fallback."""

import json
import os

import numpy as np
import pytest

from reflow_tpu.serve import (LeaderReadAdapter, ReadTier,
                              ReplicaScheduler, StaleRead)
from reflow_tpu.utils.checkpoint import save_checkpoint
from reflow_tpu.utils.faults import tear_wal_tail
from reflow_tpu.wal import DurableScheduler, SegmentShipper
from reflow_tpu.wal.log import _MAGIC, list_segments
from reflow_tpu.wal.ship import ShipAck, Shipment, ShipNack, iter_frames
from reflow_tpu.workloads import wordcount

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def make_leader(tmp_path, **kw):
    g, src, sink = wordcount.build_graph()
    sched = DurableScheduler(g, wal_dir=str(tmp_path / "wal"),
                             fsync="tick", **kw)
    return sched, src, sink


def make_replica(tmp_path, name="r0"):
    g, _src, _sink = wordcount.build_graph()
    return ReplicaScheduler(g, str(tmp_path / name), name=name)


def drive(sched, src, n_ticks, seed=0, start=0):
    rng = np.random.default_rng(seed + start)
    for t in range(start, start + n_ticks):
        for j in range(2):
            words = " ".join(
                f"w{int(x)}" for x in rng.integers(0, 40, 8))
            sched.push(src, wordcount.ingest_lines([words]),
                       batch_id=f"t{t}b{j}")
        sched.tick()


def live_view(sched, sink):
    return {kv: w for kv, w in sched.view(sink.name).items() if w != 0}


def pump_until_caught(ship, sched, replicas, max_rounds=100):
    sched.wal.sync()
    for _ in range(max_rounds):
        ship.pump_once()
        if all(r.published_horizon() == sched._tick for r in replicas):
            return
    raise AssertionError(
        f"replicas stuck: leader tick {sched._tick}, horizons "
        f"{[r.published_horizon() for r in replicas]}")


# -- round trip -------------------------------------------------------------

def test_ship_round_trip_exact_parity(tmp_path):
    # small segments force rotations mid-stream: the protocol must walk
    # seals and segment hops, and every replica must land on the exact
    # leader view (max_abs_diff == 0 — replay is the same machinery)
    sched, src, sink = make_leader(tmp_path, segment_bytes=2048)
    ship = SegmentShipper(sched.wal, leader_tick=lambda: sched._tick)
    replicas = [make_replica(tmp_path, f"r{i}") for i in range(2)]
    for r in replicas:
        ship.attach(r)
    drive(sched, src, 8)
    pump_until_caught(ship, sched, replicas)
    want = live_view(sched, sink)
    for r in replicas:
        h, got = r.view_at(sink.name)
        assert h == sched._tick
        assert got == want
        assert r.lag_ticks() == 0
    assert ship.nacks == 0
    assert len(list_segments(sched.wal.wal_dir)) > 1  # rotations happened
    sched.close()


def test_shipper_only_ships_synced_prefix(tmp_path):
    # records sitting in the committer queue (written, not fsynced) are
    # not durable; the shipper must not hand them to a replica
    sched, src, sink = make_leader(tmp_path)
    ship = SegmentShipper(sched.wal, leader_tick=lambda: sched._tick)
    r = make_replica(tmp_path)
    ship.attach(r)
    drive(sched, src, 3)
    sched.wal.sync()
    before = sched.wal.synced_position()
    sched.push(src, wordcount.ingest_lines(["alpha beta"]),
               batch_id="unsynced")
    # no sync: the new record may be beyond the synced watermark
    ship.pump_once()
    assert r.subscribe() is not None
    cur = r.subscribe()
    assert tuple(cur) <= tuple(sched.wal.synced_position())
    assert tuple(cur) >= tuple(before) or True  # monotone vs. before
    sched.close()


# -- torn / tampered shipments ---------------------------------------------

def test_tampered_shipment_nacked_and_rerequested(tmp_path):
    # flip one payload byte in transit: the receiver must reject the
    # shipment whole (NACK carrying its cursor), apply nothing, and the
    # shipper must re-read from disk and converge on the exact view
    sched, src, sink = make_leader(tmp_path)

    class Corrupting:
        """Wraps a replica, corrupting the first shipment in flight."""

        def __init__(self, inner):
            self.inner = inner
            self.name = inner.name
            self.corrupted = 0

        def subscribe(self):
            return self.inner.subscribe()

        def bootstrap(self, ckpt_dir):
            return self.inner.bootstrap(ckpt_dir)

        def receive(self, sh):
            if self.corrupted == 0 and sh.payload:
                self.corrupted += 1
                bad = bytearray(sh.payload)
                bad[len(bad) // 2] ^= 0xFF
                return self.inner.receive(sh._replace(payload=bytes(bad)))
            return self.inner.receive(sh)

    r = make_replica(tmp_path)
    wrapped = Corrupting(r)
    ship = SegmentShipper(sched.wal, leader_tick=lambda: sched._tick)
    ship.attach(wrapped)
    drive(sched, src, 4)
    h_before = r.published_horizon()
    sched.wal.sync()
    ship.pump_once()  # first chunk corrupted -> NACK, nothing applied
    assert wrapped.corrupted == 1
    assert r.crc_rejects == 1
    assert ship.nacks == 1
    pump_until_caught(ship, sched, [r])
    assert r.published_horizon() == sched._tick > h_before
    _h, got = r.view_at(sink.name)
    assert got == live_view(sched, sink)
    sched.close()


def test_partial_commit_window_never_applied(tmp_path):
    # deliver a window's pushes WITHOUT their tick marker: the replica
    # must stage them (not even pending), publish the old horizon, and
    # apply only when the marker lands
    sched, src, sink = make_leader(tmp_path)
    drive(sched, src, 1)
    sched.push(src, wordcount.ingest_lines(["held back words"]),
               batch_id="hb1")
    sched.tick()
    sched.wal.sync()
    sched.close()

    seq, path = list_segments(str(tmp_path / "wal"))[0]
    with open(path, "rb") as f:
        data = f.read()
    entries, valid, reason = iter_frames(data[len(_MAGIC):], seq,
                                         len(_MAGIC))
    assert reason is None
    # split at the LAST tick marker: everything before it is complete
    # windows, the marker itself withheld to fake a mid-window transport
    last_tick = max(i for i, (_p, _e, rec) in enumerate(entries)
                    if rec["kind"] == "tick")
    cut = entries[last_tick][0].offset  # start of the final marker

    r = make_replica(tmp_path)
    first = Shipment(seq, len(_MAGIC),
                     data[len(_MAGIC):cut], cut, False, None, 2)
    ack = r.receive(first)
    assert isinstance(ack, ShipAck)
    assert r.published_horizon() == 1          # first window applied
    assert len(r._staged) > 0                   # second window held back
    assert not any(r.sched._pending.values())   # not even pending
    _h, got = r.view_at(sink.name)
    assert ("held", 1) not in got

    rest = Shipment(seq, cut, data[cut:], len(data), False, None, 2)
    ack = r.receive(rest)
    assert isinstance(ack, ShipAck)
    assert r.published_horizon() == 2
    assert r._staged == []
    _h, got = r.view_at(sink.name)
    assert got.get(("held", 1)) == 1


def test_out_of_order_shipment_nacked(tmp_path):
    sched, src, sink = make_leader(tmp_path)
    ship = SegmentShipper(sched.wal, leader_tick=lambda: sched._tick)
    r = make_replica(tmp_path)
    ship.attach(r)
    drive(sched, src, 2)
    pump_until_caught(ship, sched, [r])
    cur = r.subscribe()
    dup = Shipment(0, len(_MAGIC), b"", len(_MAGIC), False, None, 0)
    nack = r.receive(dup)
    assert isinstance(nack, ShipNack)
    assert tuple(nack.cursor) == tuple(cur)  # authoritative resume point
    assert r.order_rejects == 1
    sched.close()


def test_torn_leader_tail_never_ships(tmp_path):
    # a leader crash mid-append leaves a torn final frame; a cold
    # shipper (no live WAL, horizon = on-disk bytes) must stop at the
    # valid prefix and the replica must end on a whole-window horizon
    sched, src, sink = make_leader(tmp_path)
    drive(sched, src, 3)
    sched.push(src, wordcount.ingest_lines(["torn tail words"]),
               batch_id="torn")
    sched.wal.sync()
    view3 = live_view(sched, sink)
    sched.wal.close()  # crash stand-in: no recovery pass over this dir
    tear_wal_tail(str(tmp_path / "wal"), 7)

    ship = SegmentShipper(wal_dir=str(tmp_path / "wal"))
    r = make_replica(tmp_path)
    ship.attach(r)
    for _ in range(10):
        ship.pump_once()
    assert ship.crc_stops > 0          # hit the tear, refused to ship it
    assert r.crc_rejects == 0          # torn bytes never reached the wire
    assert r.published_horizon() == 3  # whole windows only
    _h, got = r.view_at(sink.name)
    assert got == view3


# -- restart-resume (the satellite regression) ------------------------------

def test_replica_restart_resumes_from_tail_not_segment0(tmp_path):
    # mid-stream kill with NO local checkpoint: restart must rebuild
    # from the mirrored tail and re-subscribe past segment 0
    sched, src, sink = make_leader(tmp_path, segment_bytes=2048)
    ship = SegmentShipper(sched.wal, leader_tick=lambda: sched._tick)
    r = make_replica(tmp_path)
    ship.attach(r)
    drive(sched, src, 6)
    pump_until_caught(ship, sched, [r])
    cur_before = r.subscribe()
    assert cur_before[0] > 0  # past segment 0 (rotations happened)
    shipped_before = ship.bytes_total
    del r  # kill: no close, no checkpoint

    r2 = ReplicaScheduler(wordcount.build_graph()[0],
                          str(tmp_path / "r0"), name="r0")
    assert r2.restored_from == "tail"
    assert tuple(r2.subscribe()) == tuple(cur_before)  # resume, not seg 0
    assert r2.published_horizon() == 6

    ship2 = SegmentShipper(sched.wal, leader_tick=lambda: sched._tick)
    ship2.attach(r2)
    drive(sched, src, 3, start=6)
    pump_until_caught(ship2, sched, [r2])
    # the resumed replica fetched only the new tail, not history
    assert ship2.bytes_total < shipped_before
    _h, got = r2.view_at(sink.name)
    assert got == live_view(sched, sink)
    sched.close()


def test_replica_restart_with_checkpoint_and_torn_mirror(tmp_path):
    # kill mid-append: local checkpoint + torn mirror tail. Restart
    # repairs the tear, resumes from checkpoint + valid tail, and the
    # shipper re-sends only the missing bytes
    sched, src, sink = make_leader(tmp_path)
    ship = SegmentShipper(sched.wal, leader_tick=lambda: sched._tick)
    r = make_replica(tmp_path)
    ship.attach(r)
    drive(sched, src, 4)
    pump_until_caught(ship, sched, [r])
    r.checkpoint()
    drive(sched, src, 4, start=4)
    pump_until_caught(ship, sched, [r])
    assert r.published_horizon() == 8
    del r
    tear_wal_tail(str(tmp_path / "r0" / "wal"), 9)  # torn mid-frame

    r2 = ReplicaScheduler(wordcount.build_graph()[0],
                          str(tmp_path / "r0"), name="r0")
    assert r2.restored_from == "checkpoint+tail"
    assert r2.published_horizon() >= 4  # at least the checkpoint
    cur = r2.subscribe()
    assert cur is not None and tuple(cur) > (0, len(_MAGIC))
    ship2 = SegmentShipper(sched.wal, leader_tick=lambda: sched._tick)
    ship2.attach(r2)
    pump_until_caught(ship2, sched, [r2])
    _h, got = r2.view_at(sink.name)
    assert got == live_view(sched, sink)
    sched.close()


# -- checkpoint-anchored bootstrap / leader truncation ----------------------

def test_fresh_replica_bootstraps_from_leader_checkpoint(tmp_path):
    sched, src, sink = make_leader(tmp_path)
    drive(sched, src, 5)
    ck = str(tmp_path / "ckpt")
    save_checkpoint(sched, ck)  # rotates + truncates covered segments
    drive(sched, src, 3, start=5)
    ship = SegmentShipper(sched.wal, ckpt_dir=ck,
                          leader_tick=lambda: sched._tick)
    r = make_replica(tmp_path)
    ship.attach(r)
    assert r.bootstraps == 1
    assert r.published_horizon() == 5  # the checkpoint, before any ship
    pump_until_caught(ship, sched, [r])
    assert r.published_horizon() == 8
    _h, got = r.view_at(sink.name)
    assert got == live_view(sched, sink)
    # anchored: shipped only the post-checkpoint tail
    total = sum(os.path.getsize(p)
                for _s, p in list_segments(sched.wal.wal_dir))
    assert ship.bytes_total <= total
    sched.close()


def test_leader_truncation_reanchors_lagging_follower(tmp_path):
    # a follower whose cursor segment was truncated away by a leader
    # checkpoint must re-anchor on the checkpoint, not wedge
    sched, src, sink = make_leader(tmp_path, segment_bytes=2048)
    ck = str(tmp_path / "ckpt")
    ship = SegmentShipper(sched.wal, ckpt_dir=ck,
                          leader_tick=lambda: sched._tick)
    r = make_replica(tmp_path)
    ship.attach(r)
    drive(sched, src, 4)
    pump_until_caught(ship, sched, [r])
    # the follower now points INTO pre-checkpoint history; checkpoint
    # truncates those segments out from under it
    drive(sched, src, 4, start=4)
    save_checkpoint(sched, ck)
    drive(sched, src, 2, start=8)
    pump_until_caught(ship, sched, [r])
    assert r.bootstraps == 1  # re-anchored once
    _h, got = r.view_at(sink.name)
    assert got == live_view(sched, sink)
    sched.close()


# -- read tier --------------------------------------------------------------

def test_read_tier_routing_and_leader_fallback(tmp_path):
    sched, src, sink = make_leader(tmp_path)
    ship = SegmentShipper(sched.wal, leader_tick=lambda: sched._tick)
    r1, r2 = make_replica(tmp_path, "r1"), make_replica(tmp_path, "r2")
    ship.attach(r1)
    drive(sched, src, 4)
    pump_until_caught(ship, sched, [r1])  # r1 caught up; r2 never attached
    leader = LeaderReadAdapter(sched)
    tier = ReadTier([r1, r2], leader=leader)

    res = tier.top_k(sink.name, 3, min_horizon=4, by="value")
    assert res.source == "r1" and res.horizon == 4
    assert tier.replica_reads == 1 and tier.leader_fallbacks == 0

    # push past every replica: only the leader can satisfy this floor
    sched.push(src, wordcount.ingest_lines(["fresh words"]),
               batch_id="fresh")
    sched.tick()
    res = tier.view_at(sink.name, min_horizon=5)
    assert res.source == "leader" and res.horizon == 5
    assert tier.leader_fallbacks == 1
    assert res.value == live_view(sched, sink)

    tier_noleader = ReadTier([r1, r2])
    with pytest.raises(StaleRead):
        tier_noleader.top_k(sink.name, 3, min_horizon=5)
    assert tier_noleader.stale_reads == 1

    assert tier.max_lag_ticks() >= 0
    # promote() is real now (PR 11): r1 leaves the read rotation and
    # becomes the leader fallback in a new epoch (the full failover
    # sequence is covered in test_failover.py)
    new_sched = tier.promote(r1, committer="inline")
    assert r1.promoted and new_sched.wal.epoch == 1
    assert all(x is not r1 for x in tier.replicas)
    res = tier.view_at(sink.name, min_horizon=4)
    assert res.source == "leader" and res.horizon == 4
    new_sched.close()
    sched.close()


def test_read_tier_round_robins_eligible_replicas(tmp_path):
    sched, src, sink = make_leader(tmp_path)
    ship = SegmentShipper(sched.wal, leader_tick=lambda: sched._tick)
    replicas = [make_replica(tmp_path, f"r{i}") for i in range(3)]
    for r in replicas:
        ship.attach(r)
    drive(sched, src, 2)
    pump_until_caught(ship, sched, replicas)
    tier = ReadTier(replicas)
    sources = {tier.top_k(sink.name, 2).source for _ in range(9)}
    assert sources == {"r0", "r1", "r2"}  # spread, not pinned
    sched.close()


# -- tooling ----------------------------------------------------------------

def test_wal_inspect_reports_ship_watermarks(tmp_path):
    import sys
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import wal_inspect
    finally:
        sys.path.pop(0)

    sched, src, sink = make_leader(tmp_path, segment_bytes=2048)
    ship = SegmentShipper(sched.wal, leader_tick=lambda: sched._tick)
    r = make_replica(tmp_path)
    ship.attach(r)
    drive(sched, src, 5)
    pump_until_caught(ship, sched, [r])
    summary = wal_inspect.inspect(str(tmp_path / "wal"), verbose=False)
    ship_sum = summary["shipping"]
    assert ship_sum is not None
    assert ship_sum["leader_tick"] == 5
    f = ship_sum["followers"]["r0"]
    assert f["applied_horizon"] == 5 and f["lag_ticks"] == 0
    assert tuple(f["shipped"]) == tuple(r.subscribe())
    # sealed segments are fully shipped; the detail rows say so
    sealed = summary["segments_detail"][:-1]
    assert sealed and all(s["shipped_fully"] for s in sealed)
    assert json.dumps(summary)  # JSON-serializable end to end
    sched.close()


def test_cursor_file_persisted_next_to_checkpoint(tmp_path):
    sched, src, sink = make_leader(tmp_path)
    ship = SegmentShipper(sched.wal, leader_tick=lambda: sched._tick)
    r = make_replica(tmp_path)
    ship.attach(r)
    drive(sched, src, 2)
    pump_until_caught(ship, sched, [r])
    with open(tmp_path / "r0" / "cursor.json") as f:
        state = json.load(f)
    assert state["schema"] == "reflow.replica_cursor/1"
    assert tuple(state["cursor"]) == tuple(r.subscribe())
    assert state["horizon"] == 2
    sched.close()


def test_fully_shipped_segment_seal_travels_as_empty_shipment(tmp_path):
    # regression: ship EVERYTHING in the open segment, then rotate. No
    # frame remains to piggyback the seal on, so the seal must travel
    # as an empty shipment that advances the replica's (authoritative)
    # cursor — a shipper-local cursor hop strands the replica at the
    # old segment's end and every later chunk NACK-livelocks.
    sched, src, sink = make_leader(tmp_path)
    ship = SegmentShipper(sched.wal, leader_tick=lambda: sched._tick)
    r = make_replica(tmp_path)
    ship.attach(r)
    drive(sched, src, 3)
    pump_until_caught(ship, sched, [r])   # open segment fully shipped
    cur_before = r._cursor
    assert cur_before.offset > len(_MAGIC)
    sched.wal.rotate()                    # seals it with no new bytes
    drive(sched, src, 2, start=3)
    pump_until_caught(ship, sched, [r])
    assert ship.nacks == 0 and r.order_rejects == 0
    assert r._cursor.segment > cur_before.segment
    assert live_view(r.sched, sink) == live_view(sched, sink)
    # the empty seal landed in the mirror too: the sealed segment's
    # mirror copy is byte-identical to the leader's
    segs = dict(list_segments(str(tmp_path / "wal")))
    mirror = dict(list_segments(os.path.join(str(tmp_path / "r0"), "wal")))
    assert (os.path.getsize(mirror[cur_before.segment])
            == os.path.getsize(segs[cur_before.segment]))
