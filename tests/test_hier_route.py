"""Hierarchical two-stage routing on 2-axis (dcn, ici) meshes
(VERDICT r4 #4 / ROADMAP r4 #1).

On a (2, 4) mesh the routed owner-delivery path must (a) deliver exactly
the same multiset the flat product-axis route delivers, and (b) cross
the DCN axis in ONE aggregated exchange — verified structurally in the
compiled HLO: exactly one all-to-all whose replica groups span slices.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from reflow_tpu.executors.device_delta import DeviceDelta
from reflow_tpu.parallel import make_mesh
from reflow_tpu.parallel.shard import shard_map
from reflow_tpu.parallel.shard_lowerings import deliver_to_owner

N, N_DCN, N_ICI = 8, 2, 4
K = 1024
KL = K // N
C = 2048                      # global rows; Cl = 256 -> routing engages


def _mesh():
    return make_mesh(N, dcn=N_DCN)


def _delta(mesh, seed=0):
    rng = np.random.default_rng(seed)
    keys = rng.integers(0, K, C).astype(np.int32)
    vals = rng.standard_normal(C).astype(np.float32)
    w = rng.integers(-2, 3, C).astype(np.int32)   # includes dead rows
    sh = NamedSharding(mesh, P(("dcn", "delta")))
    return DeviceDelta(jax.device_put(jnp.asarray(keys), sh),
                       jax.device_put(jnp.asarray(vals), sh),
                       jax.device_put(jnp.asarray(w), sh)), keys, vals, w


def _routed(mesh, d):
    dspec = DeviceDelta(P(("dcn", "delta")), P(("dcn", "delta")),
                        P(("dcn", "delta")))
    fn = shard_map(
        lambda dd: deliver_to_owner(dd, ("dcn", "delta"), N, KL,
                                    sizes=(N_DCN, N_ICI)),
        mesh=mesh, in_specs=(dspec,),
        out_specs=(dspec, P()), check_vma=False)
    return jax.jit(fn), dspec


def test_hier_route_delivers_exact_multiset():
    mesh = _mesh()
    d, keys, vals, w = _delta(mesh)
    fn, _ = _routed(mesh, d)
    out, err = fn(d)
    assert not bool(np.asarray(err).any())
    out_k = np.asarray(out.keys)
    out_v = np.asarray(out.values)
    out_w = np.asarray(out.weights)
    cap = len(out_k) // N
    shard = np.repeat(np.arange(N), cap)
    gkey = shard * KL + out_k
    live = out_w != 0
    # ownership: every live row landed on its key's owner shard
    assert np.all((gkey[live] // KL) == shard[live])
    # exact multiset: per-(key, value-bits, weight-sign) weighted sums
    got = {}
    for k, v, ww in zip(gkey[live], out_v[live], out_w[live]):
        got[(int(k), float(v))] = got.get((int(k), float(v)), 0) + int(ww)
    exp = {}
    for k, v, ww in zip(keys, vals, w):
        if ww:
            exp[(int(k), float(v))] = exp.get((int(k), float(v)), 0) + int(ww)
    assert got == exp


def test_hier_route_one_dcn_leg_in_hlo():
    """Structural proof of the hierarchy: the compiled program carries
    exactly one all-to-all whose replica groups cross slices (the DCN
    exchange) and one intra-slice all-to-all (the ICI leg)."""
    mesh = _mesh()
    d, *_ = _delta(mesh)
    fn, _ = _routed(mesh, d)
    txt = jax.jit(fn).lower(d).compile().as_text()
    import re
    dcn_patterns = set()
    ici_patterns = set()
    n_dcn_instr = 0
    for m in re.finditer(r"all-to-all[^\n]*replica_groups=(\{\{[\d,{}]*\}\})",
                         txt):
        pat = m.group(1)
        ids = [[int(x) for x in g.split(",")]
               for g in re.findall(r"\{([\d,]+)\}", pat)]
        crosses = any(len({i // N_ICI for i in g}) > 1 for g in ids)
        if crosses:
            dcn_patterns.add(pat)
            n_dcn_instr += 1
        else:
            ici_patterns.add(pat)
    # ONE logical DCN exchange: a single slice-crossing group pattern,
    # instantiated once per delta column (keys/values/weights = 3
    # instructions on one channel), plus the intra-slice ICI leg
    assert len(dcn_patterns) == 1, (dcn_patterns, ici_patterns)
    assert n_dcn_instr <= 3
    assert len(ici_patterns) >= 1


def test_flat_mesh_unchanged_single_leg():
    """1-axis meshes keep the flat single all_to_all route."""
    mesh = make_mesh(8)
    rng = np.random.default_rng(1)
    keys = rng.integers(0, K, C).astype(np.int32)
    sh = NamedSharding(mesh, P("delta"))
    d = DeviceDelta(
        jax.device_put(jnp.asarray(keys), sh),
        jax.device_put(jnp.asarray(rng.standard_normal(C), np.float32), sh),
        jax.device_put(jnp.asarray(np.ones(C, np.int32)), sh))
    dspec = DeviceDelta(P("delta"), P("delta"), P("delta"))
    fn = shard_map(
        lambda dd: deliver_to_owner(dd, "delta", N, KL),
        mesh=mesh, in_specs=(dspec,), out_specs=(dspec, P()),
        check_vma=False)
    txt = jax.jit(fn).lower(d).compile().as_text()
    import re
    patterns = set(re.findall(
        r"= [^\n]*all-to-all\([^\n]*replica_groups=(\{\{[\d,{}]*\}\})", txt))
    assert len(patterns) == 1, patterns   # one logical exchange
