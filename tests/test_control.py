"""Control-plane tests (``reflow_tpu.serve.control``) plus the
robustness seams it actuates.

Three layers:

1. **State machines on a fake clock** — :class:`BrownoutLadder`,
   :class:`CircuitBreaker`, :class:`Autoscaler` are pure policies fed
   synthetic observations; no tier, no threads, NO sleeps. These pin the
   control theory: breach/recover hysteresis, K-crashes-in-window
   opening, half-open probe semantics, exponential backoff with bounded
   jitter, min/max clamping.

2. **Actuator seams** — ``AdmissionBudget.resize`` (live floor/ceiling
   retune), ``WriteAheadLog.wait_durable(timeout=)`` (bounded,
   non-consuming), ``WriteAheadLog.restart_committer`` (respawn after a
   committer death), ``IngestFrontend.revive`` (re-arm a failed graph),
   ``ServeTier.ensure_workers``/``scale_pool`` (pool supervision — the
   pool-capacity-leak regression lives here).

3. **ControlPlane integration** — injected samplers drive the real
   actuators on a live tier: brownout flips the real admission policy,
   idle reclaim shrinks and restores the real budget floor, the breaker
   quarantines a crash-storming graph and heals it through half-open
   once the storm ends.
"""

from __future__ import annotations

import threading
import time

import pytest

from reflow_tpu.graph import GraphError
from reflow_tpu.scheduler import DirtyScheduler
from reflow_tpu.serve import (AdmissionBudget, Autoscaler, BrownoutLadder,
                              CircuitBreaker, CoalesceWindow, ControlConfig,
                              ControlPlane, FrontendClosed, GraphConfig,
                              IngestFrontend, PumpCrashed, SLOSpec,
                              ServeTier, load_slo_specs)
from reflow_tpu.obs import MetricsRegistry
from reflow_tpu.utils.faults import CrashInjector, CrashPoint, StormInjector
from reflow_tpu.wal import WriteAheadLog
from reflow_tpu.wal.log import scan_wal
from reflow_tpu.workloads import wordcount

WINDOW = CoalesceWindow(max_rows=256, max_ticks=8, max_latency_s=0.002)


def make_graph():
    g, src, sink = wordcount.build_graph()
    return DirtyScheduler(g), src, sink


def lines_batch(*words: str):
    return wordcount.ingest_lines([" ".join(words)])


def config(**kw):
    kw.setdefault("window", WINDOW)
    return GraphConfig(**kw)


def wait_until(pred, timeout=10.0, interval=0.005, msg="condition"):
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        if pred():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


# -- 1: brownout ladder ------------------------------------------------------

def test_ladder_steps_down_after_breach_intervals():
    lad = BrownoutLadder("block", ("reject", "shed-oldest"),
                         breach_intervals=3, recover_intervals=2)
    assert lad.policy == "block" and lad.level == 0
    assert lad.observe(True) is None
    assert lad.observe(True) is None
    assert lad.observe(True) == "reject"          # 3rd consecutive breach
    assert lad.level == 1
    # the streak restarts per rung: two more breaches don't move yet
    assert lad.observe(True) is None
    assert lad.observe(True) is None
    assert lad.observe(True) == "shed-oldest"
    assert lad.level == 2
    # bottom rung: further breaches are absorbed
    for _ in range(5):
        assert lad.observe(True) is None
    assert lad.policy == "shed-oldest"


def test_ladder_recovery_hysteresis_per_rung():
    lad = BrownoutLadder("block", ("reject", "shed-oldest"),
                         breach_intervals=1, recover_intervals=3)
    assert lad.observe(True) == "reject"
    assert lad.observe(True) == "shed-oldest"
    # two clean samples then a breach: the ok-streak resets, the level
    # holds — a flapping gauge can't pump the ladder
    assert lad.observe(False) is None
    assert lad.observe(False) is None
    assert lad.observe(True) is None
    assert lad.level == 2
    # a full clean streak recovers exactly ONE rung...
    assert lad.observe(False) is None
    assert lad.observe(False) is None
    assert lad.observe(False) == "reject"
    assert lad.level == 1
    # ...and the next rung needs a fresh full streak
    assert lad.observe(False) is None
    assert lad.observe(False) is None
    assert lad.observe(False) == "block"
    assert lad.level == 0
    # at level 0 clean samples are a no-op
    assert lad.observe(False) is None


def test_ladder_collapses_duplicate_rungs():
    lad = BrownoutLadder("reject", ("reject", "shed-oldest"),
                         breach_intervals=1, recover_intervals=1)
    assert lad.levels == ("reject", "shed-oldest")
    assert lad.observe(True) == "shed-oldest"
    assert lad.level == 1


def test_slo_spec_validates_and_breaches():
    with pytest.raises(ValueError):
        SLOSpec(ladder=("bogus",))
    with pytest.raises(ValueError):
        SLOSpec(breach_intervals=0)
    spec = SLOSpec(sched_delay_p99_s=0.1, durable_lag_s=0.5,
                   budget_occupancy=0.8)
    assert not spec.breached({})
    assert spec.breached({"sched_delay_p99_s": 0.2})
    assert spec.breached({"durable_lag_s": 1.0})
    assert spec.breached({"occupancy": 0.9})
    assert not spec.breached({"sched_delay_p99_s": 0.05,
                              "durable_lag_s": 0.1, "occupancy": 0.5})
    # None thresholds are skipped entirely
    assert not SLOSpec(budget_occupancy=None).breached({"occupancy": 9.0})


# -- 1: circuit breaker ------------------------------------------------------

def breaker(**kw):
    kw.setdefault("max_crashes", 3)
    kw.setdefault("window_s", 10.0)
    kw.setdefault("backoff_s", 0.1)
    kw.setdefault("backoff_max_s", 1.0)
    kw.setdefault("cooldown_s", 5.0)
    kw.setdefault("cooldown_max_s", 20.0)
    kw.setdefault("probe_intervals", 2)
    kw.setdefault("jitter_frac", 0.0)   # deterministic unless overridden
    return CircuitBreaker(**kw)


def test_breaker_opens_on_k_crashes_in_window():
    br = breaker()
    assert br.record_crash(0.0) == "closed"
    assert br.record_crash(1.0) == "closed"
    assert br.record_crash(2.0) == "open"
    assert br.state == "open" and br.opens == 1
    # open: no respawns, cooldown not yet elapsed
    assert br.poll(3.0, healthy=False) is None


def test_breaker_window_expiry_prevents_opening():
    br = breaker(window_s=5.0)
    br.record_crash(0.0)
    br.record_crash(1.0)
    # the first two crashes age out of the window before the third
    assert br.record_crash(20.0) == "closed"
    assert br.state == "closed"


def test_breaker_closed_backoff_is_exponential_with_jitter():
    br = breaker(backoff_s=0.1, backoff_max_s=1.0, jitter_frac=0.5,
                 rng=lambda: 1.0, window_s=1e9, max_crashes=100)
    t = 0.0
    waits = []
    for _ in range(6):
        br.record_crash(t)
        # not ready before the scheduled instant
        assert br.poll(t, healthy=False) is None
        lo = t
        while br.poll(lo + 1e-9, healthy=False) != "respawn":
            lo += 0.01
        waits.append(lo - t)
        t = lo + 1e-9
    # base 0.1 doubling each consecutive respawn, rng=1.0 → ×1.5 jitter,
    # capped at backoff_max 1.0 (→ 1.5 with jitter)
    expect = [0.15, 0.3, 0.6, 1.2, 1.5, 1.5]
    for got, want in zip(waits, expect):
        assert abs(got - want) < 0.02, (waits, expect)


def test_breaker_backoff_resets_after_sustained_health():
    br = breaker(probe_intervals=2, window_s=1e9, max_crashes=100)
    br.record_crash(0.0)
    br.poll(10.0, healthy=False)  # consume the respawn
    assert br.respawn_delay() > br.backoff_s  # backed off
    br.poll(11.0, healthy=True)
    br.poll(12.0, healthy=True)   # probe_intervals healthy polls
    assert br.respawn_delay() == br.backoff_s


def test_breaker_half_open_probe_then_close():
    br = breaker(cooldown_s=5.0, probe_intervals=2)
    for t in (0.0, 1.0, 2.0):
        br.record_crash(t)
    assert br.state == "open"
    assert br.poll(6.0, healthy=False) is None        # cooldown running
    assert br.poll(7.1, healthy=False) == "probe"     # 2.0 + 5.0 elapsed
    assert br.state == "half_open"
    # only ONE probe: further polls while unhealthy do nothing
    assert br.poll(7.2, healthy=False) is None
    assert br.poll(8.0, healthy=True) is None          # 1st healthy
    assert br.poll(9.0, healthy=True) == "close"       # 2nd → closed
    assert br.state == "closed"
    # full reset: the old crashes don't count toward the next storm
    assert br.record_crash(10.0) == "closed"


def test_breaker_probe_crash_reopens_with_doubled_cooldown():
    br = breaker(cooldown_s=5.0, cooldown_max_s=8.0)
    for t in (0.0, 1.0, 2.0):
        br.record_crash(t)
    assert br.poll(7.1, healthy=False) == "probe"
    assert br.record_crash(7.5) == "open"             # probe crashed
    assert br.opens == 2
    # doubled cooldown: 7.5 + 10 → but capped at 8.0
    assert br.poll(14.0, healthy=False) is None
    assert br.poll(15.6, healthy=False) == "probe"
    # a successful probe restores the base cooldown
    br.poll(16.0, healthy=True)
    br.poll(17.0, healthy=True)
    assert br.state == "closed" and br._cooldown == br.cooldown_base_s


# -- 1: autoscaler -----------------------------------------------------------

def test_autoscaler_grows_on_sustained_backlog():
    au = Autoscaler(min_workers=1, max_workers=4, grow_intervals=3,
                    shrink_intervals=5)
    assert au.observe(5, 2) is None
    assert au.observe(5, 2) is None
    assert au.observe(5, 2) == 3          # 3rd sustained sample
    # streak restarts after a grow, and an intervening calm sample
    # resets it
    assert au.observe(5, 3) is None
    assert au.observe(3, 3) is None       # ready == live: calm
    assert au.observe(5, 3) is None
    assert au.observe(5, 3) is None
    assert au.observe(5, 3) == 4
    # at max: sustained backlog is absorbed
    for _ in range(6):
        assert au.observe(9, 4) is None


def test_autoscaler_shrinks_on_sustained_idle_and_clamps():
    au = Autoscaler(min_workers=2, max_workers=4, grow_intervals=2,
                    shrink_intervals=3)
    assert au.observe(0, 3) is None
    assert au.observe(0, 3) is None
    assert au.observe(0, 3) == 2
    # at min: sustained idle is absorbed
    for _ in range(4):
        assert au.observe(0, 2) is None
    # out-of-range live counts clamp immediately, no streak needed
    assert au.observe(0, 1) == 2
    assert au.observe(0, 9) == 4
    with pytest.raises(ValueError):
        Autoscaler(min_workers=3, max_workers=2)


# -- 2: budget resize --------------------------------------------------------

def test_budget_resize_retunes_and_validates():
    b = AdmissionBudget(1000)
    a = b.register("a", floor=300, ceiling=600)
    c = b.register("c", floor=200)
    # shrinking a's floor grows c's guaranteed headroom
    before = c.max_alone
    b.resize("a", floor=0)
    assert a.floor == 0 and c.max_alone == before + 300
    b.resize("a", floor=300)   # restorable while reservable
    assert a.floor == 300
    with pytest.raises(KeyError):
        b.resize("nope", floor=1)
    with pytest.raises(ValueError):
        b.resize("a", floor=-1)
    with pytest.raises(ValueError):
        b.resize("a", floor=700, ceiling=600)
    with pytest.raises(ValueError):
        b.resize("a", ceiling=2000)
    with pytest.raises(ValueError):
        b.resize("a", floor=900)   # c's 200 floor stays reserved
    # ceiling below current usage: legal, nothing evicted
    a.acquire(500)
    b.resize("a", ceiling=400)
    assert a.used == 500 and a.ceiling == 400
    assert not a.room_for(1)


# -- 2: bounded durability waits --------------------------------------------

def test_wait_durable_timeout_is_bounded_and_non_consuming(tmp_path):
    wal = WriteAheadLog(str(tmp_path), fsync="record")
    # wedge the committer: holding _sync_lock blocks its write/fsync
    wal._sync_lock.acquire()
    try:
        wal.append({"k": 1}, wait=False)
        lsn = wal.last_lsn()
        t0 = time.perf_counter()
        with pytest.raises(TimeoutError):
            wal.wait_durable(lsn, timeout=0.2)
        assert time.perf_counter() - t0 < 5.0
    finally:
        wal._sync_lock.release()
    # non-consuming: the request stayed queued, a re-wait succeeds
    wal.wait_durable(lsn, timeout=10.0)
    assert wal.durable_lsn() >= lsn
    wal.close()


def test_ticket_result_timeout_is_bounded_and_non_consuming():
    sched, src, _sink = make_graph()
    # a window that only fires on flush: the ticket stays pending
    fe = IngestFrontend(sched, window=CoalesceWindow(
        max_rows=1 << 20, max_ticks=1 << 20, max_latency_s=60.0))
    t = fe.submit(src, lines_batch("hello"))
    with pytest.raises(TimeoutError):
        t.result(timeout=0.1)
    fe.flush(timeout=10)
    assert t.result(timeout=10).applied   # same ticket, later success
    fe.close()


# -- 2: committer respawn ----------------------------------------------------

def test_restart_committer_recovers_a_dead_wal(tmp_path):
    inj = CrashInjector(at=1, only="wal_before_fsync")
    wal = WriteAheadLog(str(tmp_path), fsync="record", crash=inj)
    with pytest.raises(CrashPoint):
        wal.append({"k": 1})          # committer dies at the fsync seam
    assert wal.committer_error is not None
    with pytest.raises(CrashPoint):
        wal.append({"k": 2})          # dead committer poisons appends
    assert wal.restart_committer() is True
    assert wal.committer_error is None
    assert wal.committer_restarts == 1
    assert isinstance(wal.last_committer_error, CrashPoint)
    # the respawned committer serves appends and durability again
    wal.append({"k": 3})
    wal.wait_durable(wal.last_lsn(), timeout=10.0)
    wal.close()
    # the log stays scannable end to end (tail repaired at restart)
    records, torn = scan_wal(str(tmp_path))
    assert torn is None
    assert {"k": 3} in [r for _pos, r in records]


def test_restart_committer_noop_when_healthy(tmp_path):
    wal = WriteAheadLog(str(tmp_path), fsync="record")
    assert wal.restart_committer() is False
    assert wal.committer_restarts == 0
    wal.close()


# -- 2: pool supervision (the capacity-leak regression) ----------------------

def test_worker_death_is_healed_and_throughput_restored():
    inj = CrashInjector(at=1, only="pool_worker@g0")
    tier = ServeTier(max_bytes=8 << 20, pump_threads=2, crash=inj)
    sched, src, sink = make_graph()
    h = tier.register("g0", sched, config())
    assert tier.live_workers == 2
    # the seam fires between windows: the batch lands, the worker dies
    assert h.submit(src, lines_batch("a", "b")).result(timeout=10).applied
    wait_until(lambda: tier.worker_deaths == 1, msg="worker death")
    wait_until(lambda: tier.live_workers == 1, msg="thread exit")
    # before this PR the pool stayed at 1 thread forever; the
    # supervisor restores it to the configured size
    assert tier.ensure_workers() == 1
    assert tier.live_workers == 2
    assert tier.worker_respawns == 1
    # post-crash throughput parity: the restored pool serves everything
    tickets = [h.submit(src, lines_batch(f"w{j}")) for j in range(40)]
    assert all(t.result(timeout=10).applied for t in tickets)
    assert dict(sched.view(sink.name))[("a", 1.0)] == 1
    tier.close()


def test_scale_pool_grows_and_shrinks_live_workers():
    tier = ServeTier(max_bytes=8 << 20, pump_threads=2)
    assert tier.live_workers == 2
    assert tier.scale_pool(4) == 4
    wait_until(lambda: tier.live_workers == 4, msg="scale up")
    assert tier.pump_threads == 4   # utilization denominator follows
    assert tier.scale_pool(1) == 1
    wait_until(lambda: tier.live_workers == 1, msg="scale down")
    # clamped at 1: the pool can never scale to zero
    assert tier.scale_pool(0) == 1
    tier.close()


def test_revive_rearms_a_failed_graph():
    inj = CrashInjector(at=1, only="pool_window@doomed")
    tier = ServeTier(max_bytes=8 << 20, pump_threads=2, crash=inj)
    sched, src, sink = make_graph()
    h = tier.register("doomed", sched, config())
    t = h.submit(src, lines_batch("x"))
    with pytest.raises(PumpCrashed):
        t.result(timeout=10)
    wait_until(lambda: h.frontend._state == "failed", msg="failed state")
    with pytest.raises(FrontendClosed):
        h.submit(src, lines_batch("y"))   # failed: submissions refused
    h.frontend.revive()
    assert h.frontend.revives == 1
    # the revived graph serves new traffic (injector is one-shot)
    assert h.submit(src, lines_batch("z")).result(timeout=10).applied
    assert dict(sched.view(sink.name)).get(("z", 1.0)) == 1
    # revive() on a running frontend is an error
    with pytest.raises(GraphError):
        h.frontend.revive()
    tier.close()


# -- 3: ControlPlane integration --------------------------------------------

class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt
        return self.t


def make_tier_with(name, **cfg_kw):
    tier = ServeTier(max_bytes=1 << 20, pump_threads=2)
    sched, src, sink = make_graph()
    h = tier.register(name, sched, config(**cfg_kw))
    return tier, h, src, sink


def test_control_brownout_actuates_and_recovers_policy():
    tier, h, _src, _sink = make_tier_with("hot")
    clk = FakeClock()
    occ = {"v": 0.95}
    sampler = lambda now: {"graphs": {"hot": {
        "state": "running", "occupancy": occ["v"]}},
        "ready_depth": 0, "live_workers": tier.live_workers}
    reg = MetricsRegistry()
    cp = ControlPlane(
        tier, specs={"hot": SLOSpec(budget_occupancy=0.8,
                                    breach_intervals=2,
                                    recover_intervals=3)},
        registry=reg, clock=clk, sampler=sampler)
    cp.step(clk.advance(0.05))
    assert h.frontend.policy == "block"
    cp.step(clk.advance(0.05))            # 2nd breach → level 1
    assert h.frontend.policy == "reject" and cp.level("hot") == 1
    for _ in range(2):
        cp.step(clk.advance(0.05))
    assert h.frontend.policy == "shed-oldest" and cp.level("hot") == 2
    occ["v"] = 0.1
    for _ in range(6):
        cp.step(clk.advance(0.05))
    assert h.frontend.policy == "block" and cp.level("hot") == 0
    assert reg.value("control.brownouts_entered") == 1
    assert reg.value("control.brownouts_exited") == 1
    cp.stop()
    tier.close()


def test_control_protect_weight_exempts_high_qos_graph():
    tier = ServeTier(max_bytes=1 << 20, pump_threads=2)
    s1, _, _ = make_graph()
    s2, _, _ = make_graph()
    tier.register("hot", s1, config(weight=1.0))
    tier.register("vip", s2, config(weight=4.0))
    clk = FakeClock()
    sampler = lambda now: {"graphs": {
        "hot": {"state": "running", "occupancy": 0.99},
        "vip": {"state": "running", "occupancy": 0.99}},
        "ready_depth": 0, "live_workers": tier.live_workers}
    cp = ControlPlane(
        tier,
        config=ControlConfig(
            default_slo=SLOSpec(budget_occupancy=0.8, breach_intervals=1),
            protect_weight=2.0),
        registry=MetricsRegistry(), clock=clk, sampler=sampler)
    for _ in range(3):
        cp.step(clk.advance(0.05))
    assert cp.level("hot") > 0
    assert tier.handle("hot").frontend.policy != "block"
    # the protected tenant is never browned out
    assert cp.level("vip") == 0
    assert tier.handle("vip").frontend.policy == "block"
    cp.stop()
    tier.close()


def test_control_idle_reclaim_shrinks_and_restores_floor():
    tier, h, _src, _sink = make_tier_with("quiet", floor_bytes=1 << 16)
    clk = FakeClock()
    busy = {"v": False}
    sampler = lambda now: {"graphs": {"quiet": {
        "state": "running",
        "queued_batches": 1 if busy["v"] else 0,
        "bytes_used": 64 if busy["v"] else 0,
        "windows": 0}},
        "ready_depth": 0, "live_workers": tier.live_workers}
    reg = MetricsRegistry()
    cp = ControlPlane(tier, config=ControlConfig(reclaim_idle_intervals=3),
                      registry=reg, clock=clk, sampler=sampler)
    share = tier.budget.shares()["quiet"]
    for _ in range(2):
        cp.step(clk.advance(0.05))
    assert share.floor == 1 << 16        # not yet: streak too short
    cp.step(clk.advance(0.05))
    assert share.floor == 0              # reclaimed tier-wide
    assert reg.value("control.reclaims") == 1
    busy["v"] = True
    cp.step(clk.advance(0.05))
    assert share.floor == 1 << 16        # restored on first traffic
    assert reg.value("control.floor_restores") == 1
    cp.stop()
    tier.close()


def test_control_autoscaler_resizes_the_real_pool():
    tier, _h, _src, _sink = make_tier_with("g")
    clk = FakeClock()
    depth = {"v": 8}
    sampler = lambda now: {"graphs": {},
                           "ready_depth": depth["v"],
                           "live_workers": tier.live_workers}
    reg = MetricsRegistry()
    cp = ControlPlane(
        tier, config=ControlConfig(min_workers=1, max_workers=4,
                                   grow_intervals=2, shrink_intervals=3),
        registry=reg, clock=clk, sampler=sampler)
    for _ in range(2):
        cp.step(clk.advance(0.05))
    wait_until(lambda: tier.live_workers == 3, msg="scale up")
    assert reg.value("control.scale_ups") == 1
    assert reg.value("pool.live_workers") == 3
    depth["v"] = 0
    for _ in range(6):
        cp.step(clk.advance(0.05))
        time.sleep(0.01)   # let retiring workers notice between steps
    wait_until(lambda: tier.live_workers == 1, msg="scale down")
    assert reg.value("control.scale_downs") >= 1
    cp.stop()
    assert reg.value("pool.live_workers") is None   # unregistered at stop
    tier.close()


def test_control_heals_crash_storm_through_breaker():
    storm = StormInjector(only="pool_window@stormy")
    tier = ServeTier(max_bytes=1 << 20, pump_threads=2, crash=storm)
    sched, src, sink = make_graph()
    h = tier.register("stormy", sched, config())
    reg = MetricsRegistry()
    cp = ControlPlane(
        tier,
        config=ControlConfig(max_crashes=3, crash_window_s=30.0,
                             respawn_backoff_s=0.0,
                             respawn_backoff_max_s=0.01,
                             breaker_cooldown_s=0.02,
                             breaker_cooldown_max_s=0.1,
                             probe_intervals=2),
        registry=reg)
    # storm: every revive crashes again until the breaker opens
    deadline = time.perf_counter() + 30
    while (cp.breaker_state("stormy") != "open"
           and time.perf_counter() < deadline):
        try:
            h.submit(src, lines_batch("x"), timeout=0.1)
        except Exception:
            pass
        cp.step()
        time.sleep(0.005)
    assert cp.breaker_state("stormy") == "open"
    assert reg.value("control.breaker_opens") == 1
    assert storm.crashes >= 3
    # quarantined: submissions fail fast, no respawn churn
    with pytest.raises(Exception):
        h.submit(src, lines_batch("y"))
    # storm ends → half-open probe → closed, no manual intervention
    storm.disarm()
    wait_until(lambda: (cp.step(), time.sleep(0.005),
                        cp.breaker_state("stormy") == "closed")[-1],
               timeout=30, msg="breaker close")
    assert reg.value("control.breaker_probes") >= 1
    assert reg.value("control.breaker_closes") == 1
    assert h.submit(src, lines_batch("back")).result(timeout=10).applied
    assert dict(sched.view(sink.name)).get(("back", 1.0)) == 1
    cp.stop()
    tier.close()


def test_control_loop_thread_survives_sampler_errors():
    tier, h, src, _sink = make_tier_with("g")
    boom = {"n": 0}

    def sampler(now):
        boom["n"] += 1
        if boom["n"] < 3:
            raise RuntimeError("flaky gauge")
        return {"graphs": {}, "ready_depth": 0,
                "live_workers": tier.live_workers}

    reg = MetricsRegistry()
    cp = ControlPlane(tier, config=ControlConfig(interval_s=0.005),
                      registry=reg, sampler=sampler)
    with cp:
        wait_until(lambda: cp.ticks >= 2, msg="loop survived errors")
        assert reg.value("control.errors") == 2
    assert cp.errors == 2
    # stop() tears the control.* metrics down with it
    assert reg.value("control.errors") is None
    # the tier still serves traffic throughout
    assert h.submit(src, lines_batch("ok")).result(timeout=10).applied
    tier.close()


def test_control_default_sampler_reads_live_tier_without_deadlock():
    tier, h, src, _sink = make_tier_with("g")
    cp = ControlPlane(tier, registry=MetricsRegistry())
    assert h.submit(src, lines_batch("a", "b")).result(timeout=10).applied
    actions = cp.step()
    assert actions == []                  # healthy tier: nothing to do
    info = cp._default_sample()["graphs"]["g"]
    assert info["state"] == "running" and not info["committer_dead"]
    assert 0.0 <= info["occupancy"] <= 1.0
    cp.stop()
    tier.close()


# -- SLO specs from a config file (ControlPlane(config_path=)) --------------

def _write_slo_config(tmp_path, payload):
    import json
    path = tmp_path / "slo.json"
    path.write_text(json.dumps(payload))
    return str(path)


def test_load_slo_specs_parses_defaults_and_overrides(tmp_path):
    path = _write_slo_config(tmp_path, {
        "default_slo": {"sched_delay_p99_s": 0.5, "breach_intervals": 2},
        "specs": {
            "hot": {"budget_occupancy": 0.9,
                    "ladder": ["reject", "shed-oldest"]},
            "cold": {"sched_delay_p99_s": 2.0},
        }})
    specs = load_slo_specs(path)
    assert set(specs) == {"hot", "cold"}
    # default inherited, per-spec field layered on top
    assert specs["hot"].sched_delay_p99_s == 0.5
    assert specs["hot"].budget_occupancy == 0.9
    assert specs["hot"].breach_intervals == 2
    assert specs["hot"].ladder == ("reject", "shed-oldest")
    # per-spec override beats the default
    assert specs["cold"].sched_delay_p99_s == 2.0
    assert isinstance(specs["cold"], SLOSpec)


def test_load_slo_specs_fails_loudly_on_typos(tmp_path):
    with pytest.raises(ValueError, match="unknown fields"):
        load_slo_specs(_write_slo_config(tmp_path, {
            "specs": {"g": {"sched_delay_p99s": 0.5}}}))  # missing _
    with pytest.raises(ValueError, match="unknown top-level"):
        load_slo_specs(_write_slo_config(tmp_path, {
            "spec": {}}))
    with pytest.raises(ValueError, match="ladder policy"):
        load_slo_specs(_write_slo_config(tmp_path, {
            "specs": {"g": {"ladder": ["reject", "nuke-from-orbit"]}}}))
    with pytest.raises(ValueError, match="default_slo has unknown"):
        load_slo_specs(_write_slo_config(tmp_path, {
            "default_slo": {"durable_lags": 1.0}, "specs": {}}))


def test_control_plane_config_path_with_explicit_override(tmp_path):
    path = _write_slo_config(tmp_path, {
        "specs": {"g": {"sched_delay_p99_s": 0.25},
                  "other": {"budget_occupancy": 0.8}}})
    tier, h, src, _sink = make_tier_with("g")
    pinned = SLOSpec(sched_delay_p99_s=9.0)
    cp = ControlPlane(tier, config_path=path, specs={"g": pinned},
                      registry=MetricsRegistry())
    # file supplies the fleet, explicit specs= pins the exceptions
    assert cp.specs["g"] is pinned
    assert cp.specs["other"].budget_occupancy == 0.8
    assert h.submit(src, lines_batch("x")).result(timeout=10).applied
    assert cp.step() == []  # healthy: file-loaded specs drive the loop
    cp.stop()
    tier.close()
