"""End-to-end config 1 + the incremental-vs-full oracle (SURVEY.md §4b,e)."""

from collections import Counter

import numpy as np

from reflow_tpu import DirtyScheduler
from reflow_tpu.delta import DeltaBatch
from reflow_tpu.workloads import wordcount

LINES_T1 = ["the quick brown fox", "jumps over the lazy dog"]
LINES_T2 = ["the dog barks", "quick quick quick"]


def run_incremental(tick_lines):
    g, src, sink = wordcount.build_graph()
    sched = DirtyScheduler(g)
    for lines in tick_lines:
        sched.push(src, wordcount.ingest_lines(lines))
        sched.tick()
    return sched.view_dict(sink)


def brute_counts(tick_lines):
    c = Counter()
    for lines in tick_lines:
        for line in lines:
            c.update(wordcount.tokenize(line))
    return dict(c)


def test_wordcount_two_ticks_matches_brute_force():
    got = run_incremental([LINES_T1, LINES_T2])
    assert got == brute_counts([LINES_T1, LINES_T2])


def test_incremental_equals_full_recompute():
    incremental = run_incremental([LINES_T1, LINES_T2])
    full = run_incremental([LINES_T1 + LINES_T2])
    assert incremental == full


def test_retraction_of_a_line():
    g, src, sink = wordcount.build_graph()
    sched = DirtyScheduler(g)
    sched.push(src, wordcount.ingest_lines(LINES_T1))
    sched.tick()
    # retract the first line entirely
    sched.push(src, wordcount.ingest_lines([LINES_T1[0]], weight=-1))
    r = sched.tick()
    assert r.quiesced
    assert sched.view_dict(sink) == brute_counts([[LINES_T1[1]]])


def test_dirty_set_skips_untouched_subgraph():
    """Only the touched sources' downstream nodes are dirty."""
    from reflow_tpu.delta import Spec
    from reflow_tpu.graph import FlowGraph
    g = FlowGraph()
    a = g.source("a")
    b = g.source("b")
    ma = g.map(a, lambda v: v)
    mb = g.map(b, lambda v: v)
    g.sink(ma, "sa")
    g.sink(mb, "sb")
    sched = DirtyScheduler(g)
    sched.push(a, DeltaBatch.from_pairs([("k", 1)]))
    r = sched.tick()
    # dirty = a, ma, sa only
    assert r.dirty_nodes == 3


def test_random_delta_oracle():
    """Property (SURVEY.md §4b): incremental(state, deltas) == full(acc input)
    for random keyed delta sequences through Map->Reduce."""
    rng = np.random.default_rng(42)
    g, src, sink = wordcount.build_graph()
    sched = DirtyScheduler(g)
    acc = Counter()
    words = [f"w{i}" for i in range(20)]
    for _ in range(30):
        n = int(rng.integers(1, 8))
        ks = rng.choice(words, size=n)
        ws = []
        for k in ks:
            # only retract what exists, keeping the multiset valid
            w = -1 if (acc[k] > 0 and rng.random() < 0.4) else 1
            acc[k] += w
            ws.append(w)
        batch = DeltaBatch(np.array(ks, dtype=object),
                           np.ones(n, dtype=np.float32),
                           np.array(ws, dtype=np.int64))
        sched.push(src, batch)
        sched.tick()
    expect = {k: c for k, c in acc.items() if c > 0}
    assert sched.view_dict(sink) == expect
