"""The multi-process harness: ownership layout, the cross-process
horizon barrier, the chaos seams, and one real child-process cycle.

The hermetic half exercises the parent-side machinery without spawning
anything: ``OwnershipMap`` round-robin + per-node disk layout,
``horizon_barrier`` convergence and timeout semantics on closure
probes, and the harness's crash seams (``proc_spawn@<node>`` /
``proc_kill9@<node>`` / ``proc_respawn@<node>``) driven by a
``CrashInjector`` exactly like the WAL/serve seams. The subprocess half
spawns a real leader + replica + producer fleet (``python -m
reflow_tpu.proc``), kill -9s the replica mid-stream, respawns it over
the same state directory and requires it to rejoin through the
barrier; children are reaped with timeouts so a wedged child fails the
test instead of hanging the suite.
"""

import json
import os
import subprocess
import sys
import time

import pytest

from reflow_tpu.net import TcpTransport
from reflow_tpu.proc import (BarrierTimeout, OwnershipMap, ProcHarness,
                             horizon_barrier)
from reflow_tpu.proc.harness import ControlClient
from reflow_tpu.proc.worker import producer_batch_words
from reflow_tpu.serve import ReplicaScheduler
from reflow_tpu.utils.faults import CrashInjector, CrashPoint
from reflow_tpu.workloads import wordcount

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- ownership + barrier (hermetic) ------------------------------------


def test_ownership_map_round_robin_and_layout(tmp_path):
    m = OwnershipMap(str(tmp_path), ["a", "b"], sources=["s0", "s1",
                                                         "s2"])
    assert m.owner("s0") == "a" and m.owner("s1") == "b"
    assert m.owner("s2") == "a"
    assert m.sources_of("a") == ["s0", "s2"]
    for d in (m.wal_dir("a"), m.ckpt_dir("a")):
        assert os.path.isdir(d)
    # mirror_dir only NAMES the path — the ReplicaScheduler lays it out
    assert m.mirror_dir("b") == os.path.join(str(tmp_path), "b", "wal")
    m2 = OwnershipMap.from_spec(m.spec())
    assert m2.owner("s2") == "a" and m2.sources_of("b") == ["s1"]


def test_horizon_barrier_waits_for_the_straggler():
    horizons = {"a": 5, "b": 2}

    def probe(name):
        def read():
            h = horizons[name]
            horizons[name] = h + 1       # advances on every poll
            return h
        return read

    out = horizon_barrier({n: probe(n) for n in horizons},
                          timeout_s=5.0, poll_s=0.001)
    # the target was pinned on the first full pass (max = 5); everyone
    # reached it even though "b" started behind
    assert out["a"] >= 5 and out["b"] >= 5


def test_horizon_barrier_timeout_reports_last_observations():
    probes = {"up": lambda: 7, "down": lambda: None}  # never reachable
    with pytest.raises(BarrierTimeout) as ei:
        horizon_barrier(probes, min_horizon=7, timeout_s=0.2,
                        poll_s=0.01)
    assert ei.value.horizons["up"] == 7
    assert ei.value.horizons["down"] is None


def test_deterministic_producer_batches():
    # the bench oracle refolds acked batches from (index, seq) alone
    assert producer_batch_words(0, 0) == producer_batch_words(0, 0)
    assert producer_batch_words(0, 1) != producer_batch_words(0, 0)
    assert producer_batch_words(1, 0) != producer_batch_words(0, 0)


# -- chaos seams (hermetic) --------------------------------------------


def test_spawn_seam_cuts_before_the_child_exists(tmp_path):
    crash = CrashInjector(1, only="proc_spawn@r0")
    h = ProcHarness(str(tmp_path), crash=crash, fleet=False)
    try:
        with pytest.raises(CrashPoint):
            h.spawn_replica("r0")
        assert crash.fired_seam == "proc_spawn@r0"
        assert "r0" not in h.children    # nothing leaked half-spawned
    finally:
        h.close()


def test_kill9_and_respawn_seams_cut_before_acting(tmp_path):
    crash = CrashInjector(1, only="proc_kill9@r0")
    h = ProcHarness(str(tmp_path), crash=crash, fleet=False)
    try:
        with pytest.raises(CrashPoint):
            h.kill9("r0")
        assert h.kills == 0              # the seam fired before the kill
        crash2 = CrashInjector(1, only="proc_respawn@r0")
        h._crash = crash2
        with pytest.raises(CrashPoint):
            h.respawn("r0")
        assert h.respawns == 0
    finally:
        h.close()


# -- port 0 / OS-assigned addressing -----------------------------------


def test_parallel_replica_servers_get_distinct_ports(tmp_path):
    """Two fleets' worth of replica endpoints bind port 0 side by side:
    the OS assigns every port, nothing collides, and each reported
    address is dialable."""
    from reflow_tpu.net import ReplicaServer

    g, _src, sink = wordcount.build_graph()
    servers = []
    try:
        for i in range(3):
            rep = ReplicaScheduler(g, str(tmp_path / f"r{i}"),
                                   name=f"r{i}")
            servers.append(ReplicaServer(rep, TcpTransport()).start())
        ports = [s.address[1] for s in servers]
        assert len(set(ports)) == 3 and all(p > 0 for p in ports)
        for s in servers:
            ok, horizon, view = ControlClient(s.address).call(
                "view", sink.name)
            assert ok == "ok" and horizon == 0 and view == {}
    finally:
        for s in servers:
            s.close()


# -- one real child-process cycle --------------------------------------


def test_child_kill9_respawn_rejoins_the_barrier(tmp_path):
    """A real proc_spawn / proc_kill9 / proc_respawn cycle: the replica
    child dies by SIGKILL mid-stream, comes back over the same state
    directory, and rejoins the fleet at a consistent horizon while a
    paced producer keeps writing."""
    h = ProcHarness(str(tmp_path), fleet=False,
                    child_env={"JAX_PLATFORMS": "cpu"})
    try:
        ready = h.spawn_leader()
        assert ready["ingest"][1] > 0        # OS-assigned, reported
        h.spawn_replica("r0")
        assert h.replica_address("r0")[1] > 0
        h.attach_replicas()
        h.spawn_producer("p0", index=0, pace_s=0.02)
        time.sleep(0.5)

        h.kill9("r0")
        assert not h.child("r0").alive
        h.respawn("r0")
        h.attach_replicas(["r0"])
        out = h.barrier(timeout_s=60.0)      # recovered AND caught up
        assert out["r0"] >= 0
        assert h.kills == 1 and h.respawns == 1

        st = h.child("p0").stop()
        assert st is not None and st["ok"]
        assert st["in_doubt"] == []          # every batch fully acked
        assert len(st["acked"]) >= 1
    finally:
        h.close()


def test_cli_role_replica_json_status(tmp_path):
    """tools/reflow_proc.py --role replica --json: first stdout line is
    the ready JSON with the OS-assigned address, EOF on stdin is a
    clean stop, and the last line is the exit-status JSON."""
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tools", "reflow_proc.py"),
         "--role", "replica", "--name", "rx",
         "--root", str(tmp_path / "rx"), "--json"],
        cwd=REPO, text=True, stdin=subprocess.PIPE,
        stdout=subprocess.PIPE,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    try:
        ready = json.loads(proc.stdout.readline())
        assert ready["event"] == "ready" and ready["name"] == "rx"
        assert ready["addr"][1] > 0
        proc.stdin.close()                   # EOF doubles as stop
        out = proc.stdout.read()
        assert proc.wait(timeout=30) == 0
        status = json.loads(out.strip().splitlines()[-1])
        assert status["event"] == "exit" and status["ok"]
        assert not status["promoted"]
    finally:
        if proc.poll() is None:
            proc.kill()
