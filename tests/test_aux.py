"""Aux subsystems (SURVEY.md §5): durable checkpoint/resume (orbax for
device state), exactly-once ingestion, metrics summary, device min/max."""

import numpy as np
import pytest

from reflow_tpu import DeltaBatch, DirtyScheduler, FlowGraph, Spec
from reflow_tpu.executors import CpuExecutor, get_executor
from reflow_tpu.utils import load_checkpoint, save_checkpoint, summarize
from reflow_tpu.workloads import pagerank

N, E = 48, 200


def _pagerank_sched(executor):
    pg = pagerank.build_graph(N, tol=1e-5)
    sched = DirtyScheduler(pg.graph, executor, max_loop_iters=500)
    web = pagerank.WebGraph.random(N, E, seed=2)
    sched.push(pg.teleport, pagerank.teleport_batch(N))
    sched.push(pg.edges, web.initial_batch())
    sched.tick()
    return sched, pg, web


@pytest.mark.parametrize("executor_name", ["cpu", "tpu"])
def test_checkpoint_resume_replays_identically(tmp_path, executor_name):
    sched, pg, web = _pagerank_sched(get_executor(executor_name))
    save_checkpoint(sched, str(tmp_path / "ckpt"))

    churn = web.churn(0.05)
    sched.push(pg.edges, churn)
    sched.tick()
    after = sched.read_table(pg.new_rank)

    # fresh scheduler over the same graph: restore + replay the same churn
    sched2 = DirtyScheduler(pg.graph, get_executor(executor_name),
                            max_loop_iters=500)
    load_checkpoint(sched2, str(tmp_path / "ckpt"))
    sched2.push(pg.edges, churn)
    sched2.tick()
    replay = sched2.read_table(pg.new_rank)
    assert set(after) == set(replay)
    for k in after:
        assert abs(float(after[k]) - float(replay[k])) < 1e-6


def test_checkpoint_resume_sharded(tmp_path):
    from reflow_tpu.parallel import make_mesh
    from reflow_tpu.parallel.shard import ShardedTpuExecutor

    mesh = make_mesh(8)
    pg = pagerank.build_graph(64, tol=1e-5, arena_capacity=1 << 13)
    sched = DirtyScheduler(pg.graph, ShardedTpuExecutor(mesh),
                           max_loop_iters=500)
    web = pagerank.WebGraph.random(64, 256, seed=5)
    sched.push(pg.teleport, pagerank.teleport_batch(64))
    sched.push(pg.edges, web.initial_batch())
    sched.tick()
    before = sched.read_table(pg.new_rank)
    save_checkpoint(sched, str(tmp_path / "ck"))

    sched2 = DirtyScheduler(pg.graph, ShardedTpuExecutor(mesh),
                            max_loop_iters=500)
    load_checkpoint(sched2, str(tmp_path / "ck"))
    restored = sched2.read_table(pg.new_rank)
    assert {k: float(v) for k, v in before.items()} == \
           {k: float(v) for k, v in restored.items()}


def test_exactly_once_ingestion():
    g, src, sink = _wordcountish()
    sched = DirtyScheduler(g)
    b = DeltaBatch(np.array([1, 2]), np.ones(2, np.float32))
    assert sched.push(src, b, batch_id="b-1")
    assert not sched.push(src, b, batch_id="b-1")  # duplicate dropped
    sched.tick()
    v = sched.view_dict("out")
    assert v == {1: 1.0, 2: 1.0}, v


def test_exactly_once_survives_checkpoint(tmp_path):
    g, src, sink = _wordcountish()
    sched = DirtyScheduler(g)
    sched.push(src, DeltaBatch(np.array([1]), np.ones(1, np.float32)),
               batch_id="b-7")
    sched.tick()
    save_checkpoint(sched, str(tmp_path / "ck"))
    # fresh scheduler on the same graph: restore must reject redelivery
    sched2 = DirtyScheduler(g)
    load_checkpoint(sched2, str(tmp_path / "ck"))
    assert not sched2.push(src, DeltaBatch(np.array([1]),
                                           np.ones(1, np.float32)),
                           batch_id="b-7")


def _wordcountish():
    g = FlowGraph("wc")
    spec = Spec((), np.float32, key_space=64)
    src = g.source("src", spec)
    counts = g.reduce(g.map(src, lambda v: v * 0 + 1, vectorized=True),
                      "sum", spec=spec)
    sink = g.sink(counts, "out")
    return g, src, sink


def test_metrics_summary():
    sched, pg, web = _pagerank_sched(CpuExecutor())
    for _ in range(2):
        sched.push(pg.edges, web.churn(0.05))
        sched.tick()
    s = summarize(sched.history)
    assert s.ticks == 3 and s.quiesced_all
    assert s.delta_ops > 0 and s.delta_ops_per_s > 0
    assert s.tick_p95_s >= s.tick_p50_s


def test_device_minmax_insert_matches_cpu():
    def build():
        g = FlowGraph("mm")
        spec = Spec((), np.float32, key_space=32)
        src = g.source("src", spec)
        mx = g.reduce(src, "max", name="mx", spec=spec)
        g.sink(mx, "out")
        return g, src

    rng = np.random.default_rng(0)
    batches = [(rng.integers(0, 32, 40),
                rng.normal(size=40).astype(np.float32)) for _ in range(3)]
    views = {}
    for name in ("cpu", "tpu"):
        g, src = build()
        sched = DirtyScheduler(g, get_executor(name))
        for keys, vals in batches:
            sched.push(src, DeltaBatch(keys, vals))
            sched.tick()
        views[name] = {int(k): float(v)
                       for k, v in sched.view_dict("out").items()}
    assert views["cpu"] == views["tpu"]


def test_device_minmax_retraction_within_buffer_matches_cpu():
    """Scalar min/max retraction is EXACT while the per-key candidate
    buffer covers the churn (SURVEY.md §7 hard part c, bounded form)."""
    def build():
        g = FlowGraph("mm")
        spec = Spec((), np.float32, key_space=32)
        src = g.source("src", spec)
        mx = g.reduce(src, "max", name="mx", spec=spec, candidates=8)
        g.sink(mx, "out")
        return g, src

    rng = np.random.default_rng(5)
    inserted = []
    ticks = []
    for t in range(4):
        rows = []
        for _ in range(20):
            if inserted and rng.random() < 0.4:
                k, v = inserted.pop(int(rng.integers(0, len(inserted))))
                rows.append((k, v, -1))
            else:
                k, v = int(rng.integers(0, 32)), round(
                    float(rng.normal()), 3)
                rows.append((k, v, 1))
                inserted.append((k, v))
        ticks.append(rows)
    views = {}
    for name in ("cpu", "tpu"):
        g, src = build()
        sched = DirtyScheduler(g, get_executor(name))
        for rows in ticks:
            sched.push(src, DeltaBatch(
                np.array([r[0] for r in rows]),
                np.array([r[1] for r in rows], np.float32),
                np.array([r[2] for r in rows])))
            sched.tick()
        views[name] = {int(k): round(float(v), 4)
                       for k, v in sched.view_dict("out").items()}
    assert views["cpu"] == views["tpu"]


def test_device_minmax_buffer_exhaustion_flags_error():
    """Retraction churn beyond the candidate buffer fails loudly (never a
    silently wrong extremum): candidates=1, evict one value, then hollow
    the buffer."""
    g = FlowGraph("mm")
    spec = Spec((), np.float32, key_space=32)
    src = g.source("src", spec)
    mx = g.reduce(src, "max", name="mx", spec=spec, candidates=1)
    g.sink(mx, "out")
    sched = DirtyScheduler(g, get_executor("tpu"))
    sched.push(src, DeltaBatch(np.array([1, 1]),
                               np.array([2.0, 1.0], np.float32)))
    sched.tick()    # buffer holds 2.0; 1.0 evicted to overflow
    sched.push(src, DeltaBatch(np.array([1]), np.array([2.0], np.float32),
                               -np.ones(1, np.int64)))
    # the tick itself fails loudly (scheduler checks the sticky flag), so
    # corrupt deltas never reach sink views
    with pytest.raises(RuntimeError, match="min/max"):
        sched.tick()
    with pytest.raises(RuntimeError, match="min/max"):
        sched.read_table(mx)


def test_checkpoint_restores_arena_occupancy(tmp_path):
    """The arena occupancy counter (rcount) and sticky overflow flag
    travel inside the checkpointed state pytree, so the in-program
    high-water compaction (join_core's lax.cond) resumes against the true
    occupancy after restore — there is no host-side tracker to
    reconstruct (removed with the mid-stream readback it required)."""
    ex = get_executor("tpu")
    sched, pg, web = _pagerank_sched(ex)
    join_ids = [n.id for n in pg.graph.nodes
                if n.kind == "op" and n.op.kind == "join"]
    before = {nid: int(np.max(np.asarray(ex.states[nid]["rcount"])))
              for nid in join_ids}
    assert any(v > 0 for v in before.values())
    save_checkpoint(sched, str(tmp_path / "ck"))

    ex2 = get_executor("tpu")
    sched2 = DirtyScheduler(pg.graph, ex2, max_loop_iters=500)
    load_checkpoint(sched2, str(tmp_path / "ck"))
    for nid in join_ids:
        got = int(np.max(np.asarray(ex2.states[nid]["rcount"])))
        assert got == before[nid]
        assert not bool(np.asarray(ex2.states[nid]["error"]))
    # post-restore churn still ticks through the restored arena
    sched2.push(pg.edges, web.churn(0.2))
    assert sched2.tick().quiesced


def test_device_rejects_oversized_weight_mass():
    """ADVICE r1: a single batch whose |weight| mass reaches 2**24 would
    be folded through an inexact float32 scatter — rejected at upload."""
    from reflow_tpu.delta import Spec
    from reflow_tpu.executors.device_delta import to_device

    spec = Spec((), np.float32, key_space=8)
    b = DeltaBatch(np.zeros(2, np.int64), np.ones(2, np.float32),
                   np.array([1 << 23, 1 << 23], np.int64))
    with pytest.raises(ValueError, match="weight mass"):
        to_device(b, spec)


def test_fixpoint_declines_loop_carried_arena():
    """ADVICE r1: a Join whose right (arena) input is produced inside the
    loop region appends rows every while_loop iteration, invisible to the
    host overflow tracker — analyze() must send such graphs to the
    host-driven loop, which tracks every pass."""
    from reflow_tpu.executors.fixpoint import analyze
    from reflow_tpu.executors.tpu import TpuExecutor

    K = 8
    uniq = Spec((), np.float32, key_space=K, unique=True)
    raw = Spec((), np.float32, key_space=K)
    g = FlowGraph("loop_arena")
    x = g.loop("x", uniq)
    left = g.source("left", uniq)
    j = g.join(left, x, merge=lambda k, a, b: a * b, spec=raw,
               arena_capacity=256, name="j")
    nxt = g.reduce(j, "sum", tol=1e-3, name="nxt", spec=uniq)
    g.close_loop(x, nxt)
    g.validate()
    assert analyze(g) is None


def test_fault_injection_exactly_once():
    """SURVEY.md §5 fault hook: drop/duplicate/reorder source delivery
    under at-least-once retransmission + idempotent push == exactly-once;
    the faulty run's view must equal the clean run's."""
    import numpy as np

    from reflow_tpu import DeltaBatch, DirtyScheduler
    from reflow_tpu.utils.faults import FaultyChannel
    from reflow_tpu.workloads import wordcount

    def batches(rng):
        out = []
        for i in range(30):
            n = int(rng.integers(3, 10))
            words = [f"w{int(x)}" for x in rng.integers(0, 40, n)]
            out.append((f"b{i}", wordcount.ingest_lines([" ".join(words)])))
        return out

    g1, src1, sink1 = wordcount.build_graph()
    clean = DirtyScheduler(g1)
    for bid, b in batches(np.random.default_rng(2)):
        clean.push(src1, b, batch_id=bid)
        clean.tick()

    g2, src2, sink2 = wordcount.build_graph()
    faulty = DirtyScheduler(g2)
    chan = FaultyChannel(faulty, src2, drop_p=0.4, dup_p=0.4,
                         reorder_window=4, seed=7)
    for bid, b in batches(np.random.default_rng(2)):
        chan.send(b, batch_id=bid)
        faulty.tick()
    chan.flush()
    faulty.tick()

    assert chan.stats["dropped"] > 0, "no faults were injected"
    assert chan.stats["duplicated"] > 0
    assert dict(clean.view(sink1.name)) == dict(faulty.view(sink2.name))


def test_config_from_env_and_scheduler():
    """SURVEY.md §5 config/flag system: the executor choice is the
    load-bearing flag; env mapping builds a working scheduler."""
    import numpy as np

    from reflow_tpu import DeltaBatch, FlowGraph, Spec
    from reflow_tpu.utils.config import ReflowConfig

    cfg = ReflowConfig.from_env({"REFLOW_EXECUTOR": "tpu",
                                 "REFLOW_MAX_LOOP_ITERS": "77",
                                 "REFLOW_LINEAR_FIXPOINT": "0"})
    assert cfg.executor == "tpu" and cfg.max_loop_iters == 77
    g = FlowGraph()
    src = g.source("s", Spec((), np.float32, key_space=8))
    g.sink(g.reduce(src, "sum"), "out")
    sched = cfg.scheduler(g)
    assert sched.max_loop_iters == 77
    assert sched.executor.name == "tpu"
    assert not sched.executor._linear_fixpoint
    sched.push(src, DeltaBatch(np.array([2]), np.array([5.0], np.float32)))
    sched.tick()
    assert sched.view_dict("out") == {2: 5.0}

    sh = ReflowConfig.from_env({"REFLOW_EXECUTOR": "sharded",
                                "REFLOW_MESH_DEVICES": "8"})
    assert sh.make_executor().n == 8


def test_lazy_scalar_composition():
    """LazyScalar defers host ints, device scalars, arrays and thunks
    until int() — the mechanism keeping streaming ticks free of eager
    per-tick scalar dispatches."""
    import jax.numpy as jnp

    from reflow_tpu.scheduler import LazyScalar, lazy_add

    s = LazyScalar(3, jnp.asarray(4, jnp.int32))
    s = s + 5
    s = s + jnp.asarray([1, 2], jnp.int32)      # [K] stack sums
    s = s + (lambda: 10)                        # deferred host thunk
    assert int(s) == 3 + 4 + 5 + 3 + 10
    assert lazy_add(1, 2) == 3                  # pure-host stays plain int
    assert int(lazy_add(1, jnp.asarray(2, jnp.int32))) == 3


def test_tick_many_guards():
    """tick_many refuses pending push()es and non-source feeds."""
    import pytest

    from reflow_tpu.graph import GraphError
    from reflow_tpu.workloads import wordcount

    g, src, sink = wordcount.build_graph()
    sched = DirtyScheduler(g)
    sched.push(src, wordcount.ingest_lines(["a b"]))
    with pytest.raises(GraphError, match="pending"):
        sched.tick_many([{src: wordcount.ingest_lines(["c"])}])
    sched.tick()
    with pytest.raises(GraphError, match="sources"):
        sched.tick_many([{sink: wordcount.ingest_lines(["c"])}])
    # sink-bearing graph on the fallback path: sink deltas aggregate
    agg = sched.tick_many(
        [{src: wordcount.ingest_lines(["c d"])},
         {src: wordcount.ingest_lines(["d"])}]).block()
    assert agg.quiesced
    assert dict(sched.view(sink.name)) and agg.deltas_in == 3


def test_checkpoint_resume_buffered_minmax(tmp_path):
    """The candidate-buffer min/max state round-trips through
    checkpoint/resume — INCLUDING the monotone eviction latches
    (over_lo / over_maybe_pos): key 1 overflows its candidates=2 buffer
    before the save, so a post-restore retraction of the buffered best
    is only safe to refuse if the restored latches carry the eviction
    history. The restored scheduler must replay both the exact tick and
    the loud refusal identically."""
    g = FlowGraph("mm")
    spec = Spec((), np.float32, key_space=32)
    src = g.source("src", spec)
    mx = g.reduce(src, "max", name="mx", spec=spec, candidates=2)
    g.sink(mx, "out")
    sched = DirtyScheduler(g, get_executor("tpu"))
    # key 1: three distinct values -> 3.0 evicted (latches engage);
    # key 2: within buffer
    sched.push(src, DeltaBatch(np.array([1, 1, 1, 2]),
                               np.array([3.0, 5.0, 4.0, 7.0], np.float32)))
    sched.tick()
    save_checkpoint(sched, str(tmp_path / "mm"))

    # exact retraction (4.0 stays buffered, 5.0 remains the max)
    retract_ok = DeltaBatch(np.array([1]), np.array([4.0], np.float32),
                            -np.ones(1, np.int64))
    sched.push(src, retract_ok)
    sched.tick()
    after = {int(k): float(v) for k, v in sched.read_table(mx).items()}
    assert after == {1: 5.0, 2: 7.0}

    sched2 = DirtyScheduler(g, get_executor("tpu"))
    load_checkpoint(sched2, str(tmp_path / "mm"))
    sched2.push(src, retract_ok)
    sched2.tick()
    replay = {int(k): float(v) for k, v in sched2.read_table(mx).items()}
    assert replay == after

    # hollowing the buffer past the eviction watermark must refuse
    # loudly on the RESTORED scheduler too — only true if the latches
    # survived the round-trip
    sched2.push(src, DeltaBatch(np.array([1, 1]),
                                np.array([5.0, 4.0], np.float32),
                                -np.ones(2, np.int64)))
    with pytest.raises(RuntimeError, match="min/max"):
        sched2.tick()


def test_metrics_summary_over_streaming_history():
    """summarize must force streaming ticks' device-resident scalars
    (LazyScalar passes/delta_ops, deferred quiesced) before aggregating."""
    g, src, sink = _wordcountish()
    sched = DirtyScheduler(g, get_executor("tpu"))
    for i in range(3):
        sched.push(src, DeltaBatch(np.array([i]), np.ones(1, np.float32)))
        sched.tick(sync=False)
    s = summarize(sched.history)
    assert s.ticks == 3 and s.quiesced_all
    assert s.delta_ops > 0 and s.passes_mean >= 1.0


def test_minmax_latch_refresh_soak():
    """ROADMAP r3 #3 / VERDICT r3 #7: the over_lo/over_maybe_pos latches
    are one-way, so a long-running high-churn key eventually trips the
    loud error EVEN when the answer stays derivable from a replay.
    refresh_minmax resets the latches from a full-multiset replay: the
    same churn pattern that errors without refresh stays exact across a
    10k-tick soak with it."""
    import numpy as np

    from reflow_tpu import DeltaBatch, DirtyScheduler, FlowGraph, Spec
    from reflow_tpu.executors import get_executor

    spec = Spec((), np.float32, key_space=8)

    def build(candidates=2):
        g = FlowGraph("soak")
        src = g.source("s", spec)
        red = g.reduce(src, "min", name="m", candidates=candidates)
        return g, src, red

    def hollow_cycle(sched, src, lo):
        """insert {lo, lo+1, lo+2} (evicts lo+2 at candidates=2, latching
        the watermark), then retract lo and lo+1: the buffer hollows past
        the watermark -> unknowable from bounded state."""
        vals = np.array([lo, lo + 1.0, lo + 2.0], np.float32)
        sched.push(src, DeltaBatch(np.zeros(3, np.int64), vals,
                                   np.ones(3, np.int64)))
        sched.tick(sync=False)
        sched.push(src, DeltaBatch(np.zeros(2, np.int64), vals[:2],
                                   -np.ones(2, np.int64)))
        sched.tick(sync=False)
        return vals[2]   # the surviving row

    # without refresh: the very first hollow cycle must raise loudly
    g, src, red = build()
    sched = DirtyScheduler(g, get_executor("tpu"))
    hollow_cycle(sched, src, 100.0)
    with pytest.raises(RuntimeError, match="min/max"):
        sched.read_table(red)

    # refresh's real use: latches POLLUTED BY HISTORY over a multiset
    # that fits the buffer again. Epoch (4 ticks): insert {a,b,c} (c
    # evicts -> watermark latches), retract c (the evicted value!),
    # retract a, then refresh replays the true multiset {b} — resetting
    # the stale latches — and retract b empties the key CLEANLY.
    # Without the refresh the final retraction trips unknowable-state.
    def epoch(sched, src, base_v, refresh_red=None):
        vals = np.array([base_v, base_v + 1.0, base_v + 2.0], np.float32)
        k3 = np.zeros(3, np.int64)
        sched.push(src, DeltaBatch(k3, vals, np.ones(3, np.int64)))
        sched.tick(sync=False)
        for v in (vals[2], vals[0]):   # retract c (evicted), then a
            sched.push(src, DeltaBatch(np.zeros(1, np.int64),
                                       np.array([v], np.float32),
                                       -np.ones(1, np.int64)))
            sched.tick(sync=False)
        if refresh_red is not None:    # replay the full live multiset {b}
            sched.refresh_minmax(refresh_red, DeltaBatch(
                np.zeros(1, np.int64), vals[1:2], np.ones(1, np.int64)))
        sched.push(src, DeltaBatch(np.zeros(1, np.int64), vals[1:2],
                                   -np.ones(1, np.int64)))
        sched.tick(sync=False)

    # without refresh: the epoch's last retraction trips the error
    g, src, red = build()
    sched = DirtyScheduler(g, get_executor("tpu"))
    epoch(sched, src, 50.0)
    with pytest.raises(RuntimeError, match="min/max"):
        sched.read_table(red)

    # with refresh: 2500 epochs x 4 ticks = 10k ticks, exact throughout
    g, src, red = build()
    sched = DirtyScheduler(g, get_executor("tpu"))
    epochs = 2_500
    for i in range(epochs):
        epoch(sched, src, float(3 * i), refresh_red=red)
        if i % 500 == 499:
            assert sched.read_table(red) == {}   # sync point: no error
    assert sched.read_table(red) == {}


def test_minmax_latch_refresh_sharded():
    """The routed refresh path: same polluted-latch epoch pattern on the
    8-device mesh — replay rows reach their key's owner, latches reset
    per shard, the final retraction stays clean."""
    import numpy as np

    from reflow_tpu import DeltaBatch, DirtyScheduler, FlowGraph, Spec
    from reflow_tpu.parallel import make_mesh
    from reflow_tpu.parallel.shard import ShardedTpuExecutor

    spec = Spec((), np.float32, key_space=64)
    g = FlowGraph("soak_sh")
    src = g.source("s", spec)
    red = g.reduce(src, "min", name="m", candidates=2)
    sched = DirtyScheduler(g, ShardedTpuExecutor(make_mesh(8)))
    # spread the pattern across keys owned by different shards
    for i in range(6):
        k = np.full(3, 9 * i % 64, np.int64)
        vals = np.array([10.0 * i, 10.0 * i + 1, 10.0 * i + 2], np.float32)
        sched.push(src, DeltaBatch(k, vals, np.ones(3, np.int64)))
        sched.tick(sync=False)
        for v in (vals[2], vals[0]):
            sched.push(src, DeltaBatch(k[:1], np.array([v], np.float32),
                                       -np.ones(1, np.int64)))
            sched.tick(sync=False)
        sched.refresh_minmax(red, DeltaBatch(
            k[:1], vals[1:2], np.ones(1, np.int64)))
        sched.push(src, DeltaBatch(k[:1], vals[1:2],
                                   -np.ones(1, np.int64)))
        sched.tick(sync=False)
    assert sched.read_table(red) == {}


def test_forced_sync_counter_and_warning(monkeypatch):
    """VERDICT r3 weak #6: synchronous ticks / read_table on a device
    executor count as forced syncs (TickResult.forced_sync,
    MetricsSummary.forced_syncs, scheduler.forced_syncs), and the FIRST
    one on a tunnel runtime warns once."""
    import warnings

    from reflow_tpu.utils import runtime as rt
    from reflow_tpu.utils import summarize as _summarize

    monkeypatch.setattr(rt, "_warned", False)
    monkeypatch.setattr(rt, "_tunnel_active", lambda: True)

    g, src, sink = _wordcountish()
    sched = DirtyScheduler(g, get_executor("tpu"))
    sched.push(src, DeltaBatch(np.array([1]), np.ones(1, np.float32)))
    with pytest.warns(UserWarning, match="tunnel-attached"):
        r = sched.tick()          # sink graph: sync materialization
    assert r.forced_sync and sched.forced_syncs == 1

    # second sync: counter up, NO second warning
    sched.push(src, DeltaBatch(np.array([2]), np.ones(1, np.float32)))
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        sched.tick()
    assert sched.forced_syncs == 2

    s = _summarize(sched.history)
    assert s.forced_syncs == 2

    # the CPU oracle never forces a device sync
    g2, src2, _ = _wordcountish()
    cp = DirtyScheduler(g2)
    cp.push(src2, DeltaBatch(np.array([1]), np.ones(1, np.float32)))
    assert not cp.tick().forced_sync and cp.forced_syncs == 0


def test_streaming_ticks_do_not_force_sync():
    """A sink-free streaming run (the pipelined fast path) must not flip
    forced_sync until its explicit sync point."""
    pg = pagerank.build_graph(N, tol=1e-5)
    sched = DirtyScheduler(pg.graph, get_executor("tpu"),
                           max_loop_iters=500)
    web = pagerank.WebGraph.random(N, E, seed=2)
    sched.push(pg.teleport, pagerank.teleport_batch(N))
    sched.push(pg.edges, web.initial_batch())
    r = sched.tick(sync=False)
    assert not r.forced_sync and sched.forced_syncs == 0
    sched.read_table(pg.new_rank)     # explicit sync point
    assert sched.forced_syncs == 1


def test_source_cursor_mint_and_resume():
    """SourceCursor mints deterministic '<source>@<seq>' ids (the
    SPMD-identical exactly-once scheme) and resume() re-derives the
    position from a restored dedup window, skipping foreign ids."""
    import numpy as np

    from reflow_tpu.delta import DeltaBatch, Spec
    from reflow_tpu.graph import FlowGraph
    from reflow_tpu.scheduler import DirtyScheduler, SourceCursor

    g = FlowGraph("cur")
    src = g.source("s", Spec((), np.float32, key_space=8))
    g.sink(g.reduce(src, "sum"), "out")
    sched = DirtyScheduler(g)
    cur = SourceCursor(src)
    b = DeltaBatch(np.array([1]), np.array([1.0], np.float32),
                   np.ones(1, np.int64))
    ids = [cur.next_id() for _ in range(3)]
    assert ids == ["s@0", "s@1", "s@2"]
    for bid in ids:
        assert sched.push(src, b, batch_id=bid)
    assert not sched.push(src, b, batch_id="s@1")   # replay dedups
    sched._seen_batch_ids["other@99"] = None        # foreign id ignored
    sched._seen_batch_ids["s@junk"] = None          # malformed ignored
    cur2 = SourceCursor.resume(sched, src)
    assert cur2.seq == 3
    assert cur2.next_id() == "s@3"


def test_checkpoint_meta_digest_order_sensitive():
    """The multi-controller save guard digests the dedup window IN
    ORDER: two processes that accepted the same ids in different orders
    have genuinely diverged (their eviction horizons differ)."""
    from reflow_tpu.utils.checkpoint import meta_digest

    a = meta_digest(5, ["s@0", "s@1"])
    b = meta_digest(5, ["s@1", "s@0"])
    c = meta_digest(6, ["s@0", "s@1"])
    assert a != b and a != c
    assert a == meta_digest(5, ["s@0", "s@1"])


def test_drain_rejects_unreachable_source():
    """drain() must refuse a probe source that cannot structurally reach
    a deferred loop's region (its ticks would report quiescence without
    running the region's program on fallback executors)."""
    import numpy as np
    import pytest

    from reflow_tpu.delta import Spec
    from reflow_tpu.graph import FlowGraph, GraphError
    from reflow_tpu.scheduler import DirtyScheduler
    from reflow_tpu.workloads import pagerank

    pg = pagerank.build_graph(32, defer_passes=2, arena_capacity=1024)
    # an unrelated source grafted onto the same graph, pre-validation
    other = pg.graph.source("unrelated", Spec((), np.float32, key_space=8))
    pg.graph.sink(pg.graph.reduce(other, "sum"), "o")
    sched = DirtyScheduler(pg.graph)
    with pytest.raises(GraphError, match="does not reach"):
        sched.drain(other)
