"""Key-level WAL compaction (``reflow_tpu.wal.compact``): folded
segments must replay to exact state parity with the original history
(the bounded-history half of O(state) recovery), crashes anywhere in
the write-new → manifest-flip → swap → unlink sequence must leave a
replay-equivalent log, eligibility must respect the checkpoint anchor
and every attached follower's cursor, and a follower whose cursor
predates a compacted range must re-anchor through the checkpoint and
converge — the PR-10 leader-truncation re-anchor extended to
rewritten-in-place segments."""

import os

import numpy as np
import pytest

from reflow_tpu import DirtyScheduler
from reflow_tpu.serve import (ControlConfig, ControlPlane, ReplicaScheduler,
                              ServeTier)
from reflow_tpu.utils.checkpoint import CheckpointChain
from reflow_tpu.utils.faults import CrashInjector, CrashPoint
from reflow_tpu.wal import (DurableScheduler, SegmentShipper, WalCompactor,
                            WalError, recover)
from reflow_tpu.wal.compact import COMPACT_MANIFEST_FILE, read_compact_manifest
from reflow_tpu.wal.log import _MAGIC, list_segments, scan_wal
from reflow_tpu.wal.recovery import replay_records
from reflow_tpu.workloads import wordcount


# -- helpers ----------------------------------------------------------------

def make_feed(seed, n_ticks, tag=""):
    """Deterministic per-tick [(batch_id, batch)] lists with retractions
    mixed in, so folding exercises weight cancellation (zero rows must
    vanish), not just inserts. ``tag`` keeps ids disjoint when one
    scheduler consumes several feeds (a repeated id is deduped at push,
    silently shrinking the feed)."""
    rng = np.random.default_rng(seed)
    feed = []
    for t in range(n_ticks):
        batches = []
        for j in range(int(rng.integers(1, 3))):
            words = " ".join(
                f"w{int(x)}" for x in rng.integers(0, 25,
                                                   int(rng.integers(2, 8))))
            weight = -1 if (t > 2 and rng.random() < 0.2) else 1
            batches.append((f"{tag}t{t}b{j}",
                            wordcount.ingest_lines([words], weight=weight)))
        feed.append(batches)
    return feed


def build_log(wal_dir, feed, segment_bytes=1 << 12):
    """Drive a durable leader over ``feed`` (small segments force many
    rotations) and return its final live view."""
    g, src, sink = wordcount.build_graph()
    sched = DurableScheduler(g, wal_dir=wal_dir, fsync="tick",
                             segment_bytes=segment_bytes)
    for batches in feed:
        for bid, b in batches:
            sched.push(src, b, batch_id=bid)
        sched.tick()
    view = dict(sched.view(sink.name))
    tick = sched._tick
    sched.close()
    return view, tick


def recovered_view(wal_dir, ckpt_dir=None):
    g, _src, sink = wordcount.build_graph()
    sched = DirtyScheduler(g)
    rep = recover(sched, wal_dir, ckpt_dir)
    return dict(sched.view(sink.name)), sched._tick, rep


# -- fold parity ------------------------------------------------------------

def test_fold_replay_parity_and_manifest(tmp_path):
    wal_dir = str(tmp_path / "wal")
    oracle, tick = build_log(wal_dir, make_feed(7, 30))
    comp = WalCompactor(wal_dir=wal_dir, min_segments=2, keep_segments=1)
    assert comp.reclaimable_bytes() > 0
    ev = comp.compact_once()
    assert ev is not None and ev["kind"] == "wal_compact"
    assert ev["records_out"] < ev["records_in"]
    assert ev["reclaimed_bytes"] > 0
    m = read_compact_manifest(wal_dir)
    assert m["gen"] == 1 and len(m["ranges"]) == 1
    ent = m["ranges"][0]
    assert ent["out"] == ent["covers"][0] == ev["out"]
    # the folded log replays through the UNCHANGED recovery path to the
    # exact oracle state — same views, same tick counter
    got, got_tick, _rep = recovered_view(wal_dir)
    assert got == oracle and got_tick == tick
    # superseded originals are gone; the out segment holds stamped
    # folded records carrying every original batch id
    seqs = [s for s, _ in list_segments(wal_dir)]
    assert ent["covers"][1] not in seqs or ent["covers"][1] == ent["out"]
    records, _ = scan_wal(wal_dir)
    folded = [r for _p, r in records if r.get("compacted")]
    assert folded and all(r["kind"] == "push" for r in folded)
    assert any(len(r.get("batch_ids", [])) > 1 for r in folded)


def test_refold_extends_previous_range(tmp_path):
    wal_dir = str(tmp_path / "wal")
    build_log(wal_dir, make_feed(7, 30))
    comp = WalCompactor(wal_dir=wal_dir, min_segments=2, keep_segments=1)
    ev1 = comp.compact_once()
    assert ev1 is not None
    # extend the log (a restarted leader appends fresh segments after
    # the folded prefix), then fold again: the out segment re-folds
    # together with the new history under a bumped generation
    g, src, sink = wordcount.build_graph()
    sched = DurableScheduler(g, wal_dir=wal_dir, fsync="tick",
                             segment_bytes=1 << 12)
    recover(sched, wal_dir)
    for batches in make_feed(11, 40, tag="x"):
        for bid, b in batches:
            sched.push(src, b, batch_id=bid)
        sched.tick()
    oracle2 = dict(sched.view(sink.name))
    tick2 = sched._tick
    sched.close()
    ev2 = comp.compact_once()
    assert ev2 is not None
    m = read_compact_manifest(wal_dir)
    assert m["gen"] == 2
    assert ev2["covers"][0] == ev1["covers"][0]
    assert ev2["covers"][1] > ev1["covers"][1]
    got, got_tick, _rep = recovered_view(wal_dir)
    assert got == oracle2 and got_tick == tick2


def test_zero_weight_rows_vanish_from_fold(tmp_path):
    # insert-then-retract the same rows: the folded record must not
    # carry the cancelled keys at all (that is the O(state) bound)
    wal_dir = str(tmp_path / "wal")
    g, src, sink = wordcount.build_graph()
    sched = DurableScheduler(g, wal_dir=wal_dir, fsync="tick",
                             segment_bytes=1 << 10)
    for t in range(12):
        sched.push(src, wordcount.ingest_lines(["gone forever"]),
                   batch_id=f"in{t}")
        sched.tick()
    for t in range(12):
        sched.push(src, wordcount.ingest_lines(["gone forever"],
                                               weight=-1),
                   batch_id=f"out{t}")
        sched.tick()
    sched.push(src, wordcount.ingest_lines(["kept"]), batch_id="keep")
    sched.tick()
    oracle = dict(sched.view(sink.name))
    sched.close()
    comp = WalCompactor(wal_dir=wal_dir, min_segments=1, keep_segments=0)
    ev = comp.compact_once()
    assert ev is not None
    records, _ = scan_wal(wal_dir)
    folded = [r for _p, r in records if r.get("compacted")]
    assert folded
    for r in folded:
        assert all(w != 0 for w in r["weights"])
        assert not any("gone" in str(k) for k in r["keys"])
    got, _t, _rep = recovered_view(wal_dir)
    assert got == oracle


# -- crash seams ------------------------------------------------------------

@pytest.mark.parametrize("seam", ["compact_before_flip",
                                  "compact_after_flip",
                                  "compact_before_unlink",
                                  "compact_after_unlink"])
def test_compact_crash_seam_differential(tmp_path, seam):
    # kill the pass at each seam of write-new → flip → swap → unlink:
    # the raw crashed layout must ALREADY replay to the oracle (folded
    # records carry the covered batch ids, so surviving originals dedup
    # away), and the next pass's roll-forward/back must too
    wal_dir = str(tmp_path / "wal")
    oracle, tick = build_log(wal_dir, make_feed(3, 30))
    crash = CrashInjector(1, only=seam)
    comp = WalCompactor(wal_dir=wal_dir, min_segments=2, keep_segments=1,
                        crash=crash)
    with pytest.raises(CrashPoint):
        comp.compact_once()
    got, got_tick, _rep = recovered_view(wal_dir)
    assert got == oracle and got_tick == tick, f"{seam}: raw layout diverged"
    comp2 = WalCompactor(wal_dir=wal_dir, min_segments=2, keep_segments=1)
    comp2.compact_once()
    assert not [f for f in os.listdir(wal_dir) if f.endswith(".compact")]
    got, got_tick, _rep = recovered_view(wal_dir)
    assert got == oracle and got_tick == tick, f"{seam}: recovery diverged"


def test_interrupted_tmp_rolled_back(tmp_path):
    # a stray tmp with no manifest entry (crash before the flip) and a
    # torn tmp WITH an entry (flip landed, write was lied about) must
    # both roll back to the authoritative originals
    wal_dir = str(tmp_path / "wal")
    oracle, tick = build_log(wal_dir, make_feed(5, 20))
    seqs = [s for s, _ in list_segments(wal_dir)]
    stray = os.path.join(wal_dir, f"wal-{seqs[0]:08d}.log.compact")
    with open(stray, "wb") as f:
        f.write(b"garbage, not a segment")
    comp = WalCompactor(wal_dir=wal_dir, min_segments=64)  # fold nothing
    comp.compact_once()
    assert not os.path.exists(stray)
    got, got_tick, _rep = recovered_view(wal_dir)
    assert got == oracle and got_tick == tick

    # now a torn tmp alongside a manifest entry claiming it: the entry
    # must be dropped with the tmp (bytes mismatch -> not rolled forward)
    import json

    with open(stray, "wb") as f:
        f.write(_MAGIC + b"\x00" * 7)
    with open(os.path.join(wal_dir, COMPACT_MANIFEST_FILE), "w") as f:
        json.dump({"schema": "reflow.wal_compact/1", "gen": 1,
                   "reclaimed_bytes": 0,
                   "ranges": [{"out": seqs[0],
                               "covers": [seqs[0], seqs[1]], "gen": 1,
                               "bytes": 12345, "orig_bytes": 0,
                               "records_in": 0, "records_out": 0,
                               "tick_lo": None, "tick_hi": None}]}, f)
    comp.compact_once()
    assert not os.path.exists(stray)
    assert read_compact_manifest(wal_dir)["ranges"] == []
    got, got_tick, _rep = recovered_view(wal_dir)
    assert got == oracle and got_tick == tick


# -- eligibility ------------------------------------------------------------

def test_eligibility_respects_checkpoint_anchor(tmp_path):
    # records before the newest checkpoint anchor belong to the
    # checkpoint; a fold must start AT the anchor, never below it
    wal_dir = str(tmp_path / "wal")
    ckpt_dir = str(tmp_path / "ckpt")
    g, src, sink = wordcount.build_graph()
    sched = DurableScheduler(g, wal_dir=wal_dir, fsync="tick",
                             segment_bytes=1 << 12)
    chain = CheckpointChain(ckpt_dir, delta_every=4)
    for t, batches in enumerate(make_feed(9, 30)):
        for bid, b in batches:
            sched.push(src, b, batch_id=bid)
        sched.tick()
        if t == 14:
            chain.save(sched)
    oracle = dict(sched.view(sink.name))
    tick = sched._tick
    sched.close()
    from reflow_tpu.utils.checkpoint import chain_head_wal_pos

    anchor = chain_head_wal_pos(ckpt_dir)
    assert anchor is not None
    comp = WalCompactor(wal_dir=wal_dir, ckpt_dir=ckpt_dir,
                        min_segments=1, keep_segments=1)
    rng = comp.eligible_range()
    assert rng is not None and rng[0] >= anchor[0]
    ev = comp.compact_once()
    assert ev is not None and ev["covers"][0] >= anchor[0]
    got, got_tick, rep = recovered_view(wal_dir, ckpt_dir)
    assert got == oracle and got_tick == tick
    assert rep.checkpoint_loaded


def test_eligibility_min_and_keep_segments(tmp_path):
    wal_dir = str(tmp_path / "wal")
    build_log(wal_dir, make_feed(5, 20))
    n_sealed = len(list_segments(wal_dir)) - 1
    assert n_sealed >= 2
    # min_segments above the sealed count: nothing to do
    comp = WalCompactor(wal_dir=wal_dir, min_segments=n_sealed + 10,
                        keep_segments=0)
    assert comp.eligible_range() is None
    assert comp.compact_once() is None
    # keep_segments holds the newest sealed segments out of the fold
    comp2 = WalCompactor(wal_dir=wal_dir, min_segments=1, keep_segments=2)
    rng = comp2.eligible_range()
    seqs = [s for s, _ in list_segments(wal_dir)]
    assert rng is not None
    assert set(rng).isdisjoint(seqs[-3:])  # open + 2 kept sealed


def test_eligibility_respects_attached_follower_cursor(tmp_path):
    # an attached follower still mid-fetch pins the fold floor: the
    # compactor must never rewrite bytes an attached cursor still needs
    sched_dir = tmp_path
    g, src, sink = wordcount.build_graph()
    sched = DurableScheduler(g, wal_dir=str(sched_dir / "wal"),
                             fsync="tick", segment_bytes=1 << 12)
    ship = SegmentShipper(sched.wal, leader_tick=lambda: sched._tick,
                          max_chunk_bytes=1 << 10)
    g2, _s2, _k2 = wordcount.build_graph()
    replica = ReplicaScheduler(g2, str(sched_dir / "r0"), name="r0")
    ship.attach(replica)
    for batches in make_feed(2, 25):
        for bid, b in batches:
            sched.push(src, b, batch_id=bid)
        sched.tick()
    sched.wal.sync()
    ship.pump_once()  # one small chunk: cursor parked low in the log
    floor = ship.min_cursor()
    assert floor is not None
    comp = WalCompactor(sched.wal, shipper=ship, min_segments=1,
                        keep_segments=0)
    rng = comp.eligible_range()
    if rng is not None:
        assert max(rng) < floor.segment
    ev = comp.compact_once()
    if ev is not None:
        assert ev["covers"][1] < floor.segment
    sched.close()


# -- follower re-anchor across a compacted range (extends PR 10) ------------

def test_follower_cursor_in_compacted_range_reanchors(tmp_path):
    # a follower detaches mid-catch-up with its cursor parked inside a
    # range that is later compacted; on re-attach the shipper must
    # detect the stale-generation cursor, re-anchor it through the
    # checkpoint-anchored bootstrap (which RESETS replica state — a
    # folded record is all-or-nothing against the dedup window), and
    # converge to exact parity
    wal_dir = str(tmp_path / "wal")
    ckpt_dir = str(tmp_path / "ckpt")
    g, src, sink = wordcount.build_graph()
    sched = DurableScheduler(g, wal_dir=wal_dir, fsync="tick",
                             segment_bytes=1 << 12)
    chain = CheckpointChain(ckpt_dir, delta_every=4)
    chain.save(sched)  # anchor at the log head
    ship = SegmentShipper(sched.wal, ckpt_dir=ckpt_dir,
                          leader_tick=lambda: sched._tick)
    g2, _s2, sink2 = wordcount.build_graph()
    replica = ReplicaScheduler(g2, str(tmp_path / "r0"), name="r0")
    ship.attach(replica)
    # a few ticks only: the synced watermark — and thus the caught-up
    # cursor — parks MID-segment inside the anchor segment, which a
    # later pass rewrites in place (the out segment of the fold)
    for batches in make_feed(4, 3):
        for bid, b in batches:
            sched.push(src, b, batch_id=bid)
        sched.tick()
    sched.wal.sync()
    ship.pump_once()
    stale = replica.subscribe()
    assert stale is not None and stale[1] > len(_MAGIC)
    ship.detach("r0")
    # leader keeps going, then compacts the range the cursor sits in
    for batches in make_feed(6, 30, tag="x"):
        for bid, b in batches:
            sched.push(src, b, batch_id=bid)
        sched.tick()
    sched.wal.sync()
    comp = WalCompactor(sched.wal, ckpt_dir=ckpt_dir, min_segments=1,
                        keep_segments=1)
    ev = comp.compact_once()
    assert ev is not None
    assert ev["covers"][0] == stale[0], \
        "test setup: stale cursor must sit in the rewritten out segment"
    # re-attach: the persisted cursor names a pre-compaction era
    ship.attach(replica)
    sched.wal.sync()
    for _ in range(200):
        ship.pump_once()
        if replica.published_horizon() == sched._tick:
            break
    assert ship.compact_reanchors >= 1
    assert replica.published_horizon() == sched._tick
    h, got = replica.view_at(sink2.name)
    want = {kv: w for kv, w in sched.view(sink.name).items() if w != 0}
    assert h == sched._tick and got == want  # max_abs_diff == 0
    sched.close()


def test_compacted_record_partial_dedup_fails_loud(tmp_path):
    # a folded record whose batch ids are PARTIALLY in the restorer's
    # dedup window has no per-id slice to apply — silent divergence is
    # the one forbidden outcome, so replay must raise
    g, src, _sink = wordcount.build_graph()
    sched = DirtyScheduler(g)
    sched.push(src, wordcount.ingest_lines(["alpha"]), batch_id="a")
    sched.tick()
    b = wordcount.ingest_lines(["alpha beta"])
    rec = {"kind": "push", "tick": 0, "node": src.id,
           "node_name": src.name, "batch_id": "a", "compacted": True,
           "batch_ids": ["a", "b"], "keys": b.keys, "values": b.values,
           "weights": b.weights}
    with pytest.raises(WalError, match="folded range"):
        replay_records(sched, [(None, rec)])
    # fully-seen and fully-fresh folded records stay fine
    assert replay_records(sched, [(None, dict(rec, batch_ids=["a"],
                                              batch_id="a"))]) \
        == (0, 1, 0, 0)
    assert replay_records(sched, [(None, dict(rec, batch_ids=["x", "y"],
                                              batch_id="x"))]) \
        == (1, 0, 0, 0)


# -- control-plane supervision ----------------------------------------------

def test_control_plane_supervises_compactor(tmp_path):
    # the ControlPlane boots a cold compactor for free, surfaces pass
    # events as wal_compact actions, respawns a dead thread within the
    # budget, and fails fast past it (respawn-or-fail-fast, same stance
    # as the WAL committer)
    wal_dir = str(tmp_path / "wal")
    build_log(wal_dir, make_feed(8, 30))
    comp = WalCompactor(wal_dir=wal_dir, interval_s=3600.0,
                        min_segments=2, keep_segments=1)
    tier = ServeTier(max_bytes=1 << 20, pump_threads=1)
    cp = ControlPlane(tier, config=ControlConfig(max_compactor_restarts=2),
                      compactor=comp, sampler=lambda now: {"graphs": {}})
    try:
        cp.step(0.0)
        assert comp.alive  # free boot, no budget spent
        ev = comp.compact_once()  # synchronous pass queues an event
        assert ev is not None
        actions = cp.step(1.0)
        compacts = [a for a in actions if a["kind"] == "wal_compact"]
        assert len(compacts) == 1
        assert compacts[0]["covers"] == ev["covers"]
        assert compacts[0]["reclaimed_bytes"] == ev["reclaimed_bytes"]
        # kill the thread twice: budgeted respawns
        for i in (1, 2):
            comp.stop()
            acts = cp.step(1.0 + i)
            assert [a["kind"] for a in acts] == ["compactor_restart"]
            assert comp.alive
        # third death exhausts the budget: fail fast, stay failed
        comp.stop()
        acts = cp.step(10.0)
        assert [a["kind"] for a in acts] == ["compactor_failed"]
        assert not comp.alive
        assert cp.step(11.0) == []
    finally:
        cp.stop()
        comp.close()
        tier.close()


def test_compactor_metrics_publish_and_close(tmp_path):
    from reflow_tpu.obs import MetricsRegistry

    wal_dir = str(tmp_path / "wal")
    build_log(wal_dir, make_feed(1, 20))
    reg = MetricsRegistry()
    comp = WalCompactor(wal_dir=wal_dir, min_segments=2, keep_segments=1)
    comp.publish_metrics(reg)
    comp.compact_once()
    assert reg.value("compact.folds") == 1
    assert reg.value("compact.reclaimed_bytes") > 0
    assert reg.value("compact.log_bytes") == comp.log_bytes()
    comp.close()
    assert reg.value("compact.folds") is None  # unregistered on close
