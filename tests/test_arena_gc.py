"""Join-arena compaction (executors/arena.py): matched insert/retract
pairs cancel on device, so arena_capacity bounds LIVE rows and a
long-running churn stream survives at constant arena size (round-1
VERDICT item 7)."""

import numpy as np
import pytest

from reflow_tpu import DirtyScheduler
from reflow_tpu.executors.device_delta import bucket_capacity
from reflow_tpu.executors.tpu import TpuExecutor
from reflow_tpu.workloads import pagerank


def test_compact_arena_kernel():
    import jax.numpy as jnp

    from reflow_tpu.executors.arena import compact_arena

    R = 16
    # rows: (k=1,v=2.0,+1), (k=1,v=2.0,+1)  -> survives with net weight 2
    #       (k=3,v=5.0,+1), (k=3,v=5.0,-1)  -> cancels
    #       (k=4,v=7.0,-1)                  -> survives (net -1)
    rk = jnp.zeros(R, jnp.int32).at[:5].set(jnp.array([1, 3, 1, 3, 4]))
    rv = jnp.zeros((R, 1), jnp.float32).at[:5, 0].set(
        jnp.array([2.0, 5.0, 2.0, 5.0, 7.0]))
    rw = jnp.zeros(R, jnp.int32).at[:5].set(jnp.array([1, 1, 1, -1, -1]))
    state = {"lval": jnp.zeros((8,)), "lw": jnp.zeros((8,), jnp.int32),
             "rkeys": rk, "rvals": rv, "rw": rw,
             "rcount": jnp.asarray(5, jnp.int32)}
    out = compact_arena(state)
    assert int(out["rcount"]) == 2
    live = np.asarray(out["rw"]) != 0
    rows = sorted(zip(np.asarray(out["rkeys"])[live].tolist(),
                      np.asarray(out["rvals"])[live, 0].tolist(),
                      np.asarray(out["rw"])[live].tolist()))
    assert rows == [(1, 2.0, 2), (4, 7.0, -1)]


@pytest.mark.parametrize("make_ex,arena_mult,ticks", [
    (lambda: TpuExecutor(), 1, 50),
    # the sharded tracker bounds appends by worst-case key skew (every
    # all_gather'd row could land on one shard), so its live-row arena is
    # n_shards x larger — lifetime appends still exceed it several-fold
    pytest.param(lambda: _sharded(), 8, 12, id="sharded"),
])
def test_long_churn_constant_arena(make_ex, arena_mult, ticks):
    """50 churn ticks through an arena sized for LIVE rows only: lifetime
    appends exceed capacity several times over, so this passes only if
    compaction reclaims cancelled pairs."""
    N, E, churn = 48, 200, 0.2
    churn_cap = bucket_capacity(2 * int(churn * E) + 2)
    arena = (bucket_capacity(E) + 2 * churn_cap) * arena_mult
    web = pagerank.WebGraph.random(N, E, seed=4)
    pg = pagerank.build_graph(N, tol=1e-5, arena_capacity=arena)
    ex = make_ex()
    sched = DirtyScheduler(pg.graph, ex, max_loop_iters=500)
    sched.push(pg.teleport, pagerank.teleport_batch(N))
    sched.push(pg.edges, web.initial_batch())
    assert sched.tick().quiesced
    for i in range(ticks):
        sched.push(pg.edges, web.churn(churn))
        assert sched.tick().quiesced, f"tick {i}"
    # GC genuinely required: the lifetime append mass (bucketed ingress
    # capacities per tick) dwarfs the per-shard capacity
    assert bucket_capacity(E) + ticks * churn_cap > arena // arena_mult
    ref = pagerank.reference_ranks(web)
    ranks = sched.read_table(pg.new_rank)
    err = max(abs(float(ranks.get(k, 1 - pagerank.DAMPING)) - ref[k])
              for k in range(N))
    assert err < 5e-3, err


def _sharded():
    from reflow_tpu.parallel import make_mesh
    from reflow_tpu.parallel.shard import ShardedTpuExecutor

    return ShardedTpuExecutor(make_mesh(8))


def test_compact_arena_native_width_bit_identity():
    """ADVICE r2: distinct 64-bit values that alias as float32/int32 must
    NOT be grouped — the bit compare runs at native width."""
    import jax
    import jax.numpy as jnp

    from reflow_tpu.executors.arena import compact_arena

    jax.config.update("jax_enable_x64", True)
    try:
        R = 8
        a, b = 1.0, 1.0 + 2.0**-40        # equal after a float32 cast
        rk = jnp.zeros(R, jnp.int32).at[:2].set(
            jnp.array([5, 5], jnp.int32))
        rv = jnp.zeros((R, 1), jnp.float64).at[:2, 0].set(
            jnp.array([a, b], jnp.float64))
        rw = jnp.zeros(R, jnp.int32).at[:2].set(
            jnp.array([1, -1], jnp.int32))
        state = {"lval": jnp.zeros((4,)), "lw": jnp.zeros((4,), jnp.int32),
                 "rkeys": rk, "rvals": rv, "rw": rw,
                 "rcount": jnp.asarray(2, jnp.int32)}
        out = compact_arena(state)
        # the pair must survive (values differ bitwise), not cancel
        assert int(out["rcount"]) == 2
        live = np.asarray(out["rw"]) != 0
        vals = sorted(np.asarray(out["rvals"])[live, 0].tolist())
        assert vals == [a, b]
    finally:
        jax.config.update("jax_enable_x64", False)


def test_arena_overflow_sets_sticky_error():
    """Genuine overflow — live rows + appends exceed capacity and nothing
    cancels — must raise loudly at the next sync point via the join
    state's sticky error flag (the in-program lax.cond compaction found
    nothing to reclaim). The pre-round-3 host tracker raised *before*
    dispatch but cost a device readback mid-stream; the sticky flag keeps
    the failure loud without ever leaving the device mid-tick."""
    from reflow_tpu import DeltaBatch, FlowGraph, Spec

    K = 16
    uniq = Spec((), np.float32, key_space=K, unique=True)
    raw = Spec((), np.float32, key_space=K)
    g = FlowGraph("overflow")
    vals = g.source("vals", uniq)
    edges = g.source("edges", raw)
    tot = g.reduce(vals, "sum", name="uniq")
    j = g.join(tot, edges, merge=lambda k, va, vb: va + vb, spec=raw,
               arena_capacity=64, name="j")
    out = g.reduce(j, "sum", name="joined")
    g.sink(out, "out")

    sched = DirtyScheduler(g, TpuExecutor())
    sched.push(vals, DeltaBatch(np.arange(K, dtype=np.int64),
                                np.ones(K, np.float32),
                                np.ones(K, np.int64)))
    sched.tick()

    n, v0 = 48, 0
    with pytest.raises(RuntimeError, match="arena overflowed"):
        for _ in range(4):
            keys = (np.arange(n) % K).astype(np.int64)
            vals_b = np.arange(v0, v0 + n).astype(np.float32)  # all distinct
            v0 += n
            sched.push(edges, DeltaBatch(keys, vals_b, np.ones(n, np.int64)))
            sched.tick()
