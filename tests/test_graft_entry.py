"""The driver contract: entry() compiles single-chip; dryrun_multichip
executes the sharded step on a virtual 8-device mesh (conftest forces the
CPU platform with 8 virtual devices)."""

import jax


def test_entry_compiles_and_runs():
    import __graft_entry__ as ge

    fn, args = ge.entry()
    states, egress = jax.jit(fn)(*args)
    jax.block_until_ready((states, egress))
    assert egress, "tick pass produced no egress"


def test_dryrun_multichip_8():
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)


def test_dryrun_multichip_driver_init_order():
    """Reproduce the DRIVER's exact invocation: the backend is initialized
    first with a single device (``jax.devices()``), and only then is
    ``dryrun_multichip(8)`` called. Round 1 failed precisely here
    (MULTICHIP_r01.json: rc=1, "need 8 devices, have 1") because the old
    entry point mutated env in-process after backend init. The fixed entry
    point must detect the shortfall and re-exec a clean subprocess."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    # a single-device backend, initialized BEFORE dryrun_multichip runs —
    # exactly what the driver's one-real-chip invocation looks like
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    code = (
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "assert len(jax.devices()) == 1, jax.devices()\n"
        "import __graft_entry__ as ge\n"
        "ge.dryrun_multichip(8)\n"
    )
    proc = subprocess.run([sys.executable, "-c", code], env=env, cwd=repo,
                          capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, (
        f"driver-style dryrun failed rc={proc.returncode}\n"
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}")
