"""The driver contract: entry() compiles single-chip; dryrun_multichip
executes the sharded step on a virtual 8-device mesh (conftest forces the
CPU platform with 8 virtual devices)."""

import jax


def test_entry_compiles_and_runs():
    import __graft_entry__ as ge

    fn, args = ge.entry()
    states, egress = jax.jit(fn)(*args)
    jax.block_until_ready((states, egress))
    assert egress, "tick pass produced no egress"


def test_dryrun_multichip_8():
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)
