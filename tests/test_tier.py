"""Multi-graph serving-tier tests (``reflow_tpu.serve.tier``).

The contract under test, on top of ``test_serve.py``'s frontend
properties: (a) K pump threads serving N graphs preserve each graph's
differential equality with a bare loop AND the single-owner invariant
(one graph's macro-tick never runs concurrently with itself), (b) the
shared budget's floors/ceilings isolate tenants — a hot graph hits its
ceiling while a floored sibling keeps admitting, (c) lifecycle is
per-graph: drain/unregister/pump-crash on one graph leave its siblings
ticking, and only ``tier.close()`` stops the pool.
"""

from __future__ import annotations

import json
import threading
import time

import pytest

from reflow_tpu.graph import GraphError
from reflow_tpu.scheduler import DirtyScheduler
from reflow_tpu.serve import (AdmissionBudget, CoalesceWindow,
                              FrontendClosed, GraphConfig, GraphHandle,
                              PumpCrashed, ServeTier, dwrr_pick)
from reflow_tpu.utils.faults import CrashInjector
from reflow_tpu.utils.metrics import (summarize_serve, summarize_tier,
                                      summarize_wal)
from reflow_tpu.wal import DurableScheduler, WriteAheadLog, recover
from reflow_tpu.workloads import wordcount

WINDOW = CoalesceWindow(max_rows=256, max_ticks=8, max_latency_s=0.002)


def make_graph():
    g, src, sink = wordcount.build_graph()
    return DirtyScheduler(g), src, sink


def lines_batch(*words: str):
    return wordcount.ingest_lines([" ".join(words)])


def config(**kw):
    kw.setdefault("window", WINDOW)
    return GraphConfig(**kw)


# -- correctness across the pool --------------------------------------------

def test_multi_graph_differential_matches_bare_loops():
    tier = ServeTier(max_bytes=8 << 20, pump_threads=2)
    graphs = {}
    for i in range(3):
        sched, src, sink = make_graph()
        h = tier.register(f"g{i}", sched, config())
        graphs[f"g{i}"] = (h, sched, src, sink)
    payload = lambda g, p, j: lines_batch(f"{g}w{p}", f"w{(p + j) % 5}")

    def produce(name, p):
        h, _sched, src, _sink = graphs[name]
        for j in range(20):
            r = h.submit(src, payload(name, p, j)).result(timeout=10)
            assert r.applied

    threads = [threading.Thread(target=produce, args=(n, p))
               for n in graphs for p in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for name, (h, sched, src, sink) in graphs.items():
        h.flush(timeout=10)
        want_sched, want_src, want_sink = make_graph()
        for p in range(2):
            for j in range(20):
                want_sched.push(want_src, payload(name, p, j))
                want_sched.tick()
        assert dict(sched.view(sink.name)) == dict(
            want_sched.view(want_sink.name))
        assert sched.forced_syncs == 0
    tier.close()


def test_single_owner_latch_never_interleaves_one_graph():
    # wrap each scheduler's tick_many in a non-blocking per-graph mutex:
    # if the pool ever ran one graph's macro-tick concurrently with
    # itself, acquire(blocking=False) fails and the window crashes loud
    tier = ServeTier(max_bytes=8 << 20, pump_threads=4)
    graphs, violations = {}, []
    for i in range(3):
        sched, src, sink = make_graph()
        owner = threading.Lock()
        real = sched.tick_many

        def guarded(feeds, *a, owner=owner, real=real, **kw):
            if not owner.acquire(blocking=False):
                violations.append("concurrent tick_many on one graph")
                raise AssertionError(violations[-1])
            try:
                time.sleep(0.001)  # widen the race window
                return real(feeds, *a, **kw)
            finally:
                owner.release()

        sched.tick_many = guarded
        h = tier.register(f"g{i}", sched, config())
        graphs[f"g{i}"] = (h, src)

    def produce(name, p):
        h, src = graphs[name]
        for j in range(15):
            h.submit(src, lines_batch(f"{name}p{p}j{j}"))

    threads = [threading.Thread(target=produce, args=(n, p))
               for n in graphs for p in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for h, _src in graphs.values():
        h.flush(timeout=10)
    tier.close()
    assert not violations


# -- shared budget: floors and ceilings -------------------------------------

def test_ceiling_caps_hot_graph_while_floored_sibling_admits():
    tier = ServeTier(max_bytes=4096, pump_threads=2)
    hot_sched, hot_src, _ = make_graph()
    hot = tier.register("hot", hot_sched, config(
        policy="reject", ceiling_bytes=1024))
    quiet_sched, quiet_src, _ = make_graph()
    quiet = tier.register("quiet", quiet_sched, config(floor_bytes=1024))
    hot.frontend.pause()
    quiet.frontend.pause()
    # fill the hot graph to its ceiling: admissions then REJECT even
    # though the tier-wide budget still has room
    hot_results = []
    for j in range(4096):
        t = hot.submit(hot_src, lines_batch(f"h{j}", "x", "y"))
        if t.done() and t.result().status == "rejected":
            hot_results.append(t.result())
            break
    assert hot_results, "hot graph never hit its ceiling"
    assert "exceeds" not in (hot_results[0].reason or "")
    share = tier.budget.shares()["hot"]
    assert share.used <= 1024 < tier.budget.total_bytes
    # the floored sibling still admits instantly (block policy, but
    # room is guaranteed by its reservation)
    t = quiet.submit(quiet_src, lines_batch("q"), timeout=0.5)
    assert not t.done()  # queued (pump paused), not rejected
    quiet.frontend.resume()
    hot.frontend.resume()
    assert t.result(timeout=10).applied
    tier.close()


def test_budget_floor_validation():
    tier = ServeTier(max_bytes=1000, pump_threads=1)
    s1, *_ = make_graph()
    tier.register("a", s1, config(floor_bytes=700))
    s2, *_ = make_graph()
    with pytest.raises(ValueError, match="not reservable"):
        tier.register("b", s2, config(floor_bytes=400))
    with pytest.raises(ValueError, match="floor <= ceiling"):
        AdmissionBudget(1000).register("c", floor=500, ceiling=400)
    with pytest.raises(ValueError, match="exceeds"):
        AdmissionBudget(1000).register("d", ceiling=2000)
    tier.close()


def test_register_validation_and_close_refusal():
    tier = ServeTier(max_bytes=4096, pump_threads=1)
    sched, *_ = make_graph()
    tier.register("a", sched, config())
    dup, *_ = make_graph()
    with pytest.raises(ValueError, match="already registered"):
        tier.register("a", dup, config())
    bad, *_ = make_graph()
    with pytest.raises(ValueError, match="weight"):
        tier.register("b", bad, config(weight=0))
    tier.close()
    late, *_ = make_graph()
    with pytest.raises(GraphError, match="closed"):
        tier.register("late", late, config())
    with pytest.raises(KeyError):
        tier.unregister("never-there")


# -- DWRR scheduling ---------------------------------------------------------

def test_dwrr_pick_serves_proportionally_to_weight():
    tier = ServeTier(max_bytes=1 << 20, pump_threads=1)
    a = GraphHandle(tier, "a", None, GraphConfig(weight=3.0))
    b = GraphHandle(tier, "b", None, GraphConfig(weight=1.0))
    served = {"a": 0, "b": 0}
    for _ in range(400):
        h = dwrr_pick([a, b], quantum_rows=100)
        served[h.name] += 100
        h._deficit -= 100  # the pool charges rows served
    assert served["a"] / served["b"] == pytest.approx(3.0, rel=0.1)
    tier.close()


def test_dwrr_ignores_absent_graphs():
    tier = ServeTier(max_bytes=1 << 20, pump_threads=1)
    a = GraphHandle(tier, "a", None, GraphConfig(weight=1.0))
    b = GraphHandle(tier, "b", None, GraphConfig(weight=100.0))
    # b is never ready: only a is offered, so only a is replenished —
    # b cannot accumulate deficit in absentia and then starve a
    for _ in range(50):
        assert dwrr_pick([a], quantum_rows=10) is a
        a._deficit -= 10
    assert b._deficit == 0.0
    tier.close()


# -- lifecycle: per-graph vs tier-wide --------------------------------------

def test_unregister_releases_blocked_producers_and_spares_siblings():
    tier = ServeTier(max_bytes=1 << 20, pump_threads=2)
    vic_sched, vic_src, _ = make_graph()
    victim = tier.register("victim", vic_sched, config(
        ceiling_bytes=256))
    sib_sched, sib_src, sib_sink = make_graph()
    sib = tier.register("sib", sib_sched, config())
    victim.frontend.pause()
    # saturate the victim's tiny ceiling so the NEXT submit blocks:
    # stop while there is still room, the blocked thread takes the
    # first admission that does not fit
    from reflow_tpu.serve import batch_nbytes
    probe = batch_nbytes(lines_batch("v", "w", "x"))
    share = tier.budget.shares()["victim"]
    while share.room_for(probe):
        victim.submit(vic_src, lines_batch("v", "w", "x"))
    blocked_err = []

    def blocked():
        try:
            victim.submit(vic_src, lines_batch("blocked", "b", "c"))
        except FrontendClosed as e:
            blocked_err.append(e)

    th = threading.Thread(target=blocked)
    th.start()
    time.sleep(0.05)
    assert th.is_alive(), "producer should be blocked on admission"
    tier.unregister("victim", flush=False, timeout=10)
    th.join(timeout=5)
    assert not th.is_alive() and blocked_err
    assert "victim" not in tier.graphs()
    assert "victim" not in tier.budget.shares()
    # the sibling never noticed
    r = sib.submit(sib_src, lines_batch("still", "alive")).result(10)
    assert r.applied
    tier.close()


def test_tier_drain_quiesces_one_graph_while_sibling_ticks():
    tier = ServeTier(max_bytes=8 << 20, pump_threads=2)
    a_sched, a_src, a_sink = make_graph()
    a = tier.register("a", a_sched, config())
    b_sched, b_src, _ = make_graph()
    b = tier.register("b", b_sched, config())
    for j in range(10):
        a.submit(a_src, lines_batch(f"a{j}"))
    ticks = tier.drain("a")
    assert ticks >= 1
    assert a_sched.quiescent() if hasattr(a_sched, "quiescent") else True
    assert dict(a_sched.view(a_sink.name))  # backlog landed
    r = b.submit(b_src, lines_batch("b-live")).result(10)
    assert r.applied
    tier.close()


def test_tier_close_is_idempotent_and_final():
    tier = ServeTier(max_bytes=1 << 20, pump_threads=2)
    sched, src, sink = make_graph()
    h = tier.register("g", sched, config())
    tks = [h.submit(src, lines_batch(f"w{j}")) for j in range(25)]
    tier.close()
    assert all(t.result(timeout=5).applied for t in tks)
    assert dict(sched.view(sink.name))
    tier.close()  # idempotent
    with pytest.raises(FrontendClosed):
        h.submit(src, lines_batch("late"))


# -- pump-pool crash isolation ----------------------------------------------

def test_pool_crash_fails_only_the_latched_graph():
    crash = CrashInjector(at=1, only="pool_window@doomed")
    tier = ServeTier(max_bytes=8 << 20, pump_threads=2, crash=crash)
    d_sched, d_src, _ = make_graph()
    doomed = tier.register("doomed", d_sched, config())
    s_sched, s_src, _ = make_graph()
    sib = tier.register("sib", s_sched, config())
    assert sib.submit(s_src, lines_batch("before")).result(10).applied
    tks = []
    for j in range(10):
        try:
            tks.append(doomed.submit(d_src, lines_batch(f"d{j}")))
        except FrontendClosed:
            break  # the crash already landed mid-loop
    statuses = {"crashed": 0, "applied": 0}
    for t in tks:
        try:
            t.result(timeout=10)
            statuses["applied"] += 1
        except PumpCrashed:
            statuses["crashed"] += 1
    assert crash.fired and crash.fired_seam == "pool_window@doomed"
    assert statuses["crashed"] > 0
    assert tier.pool_crashes == 1
    assert doomed.frontend._state == "failed"
    # both workers outlived the crash: the sibling still applies
    for j in range(5):
        assert sib.submit(
            s_src, lines_batch(f"after{j}")).result(10).applied
    with pytest.raises(FrontendClosed):
        doomed.submit(d_src, lines_batch("dead"))
    tier.unregister("doomed", flush=False)
    tier.close()


def test_scoped_pump_seam_crashes_one_graph_mid_window():
    crash = CrashInjector(at=1, only="pump_before_tick@doomed")
    tier = ServeTier(max_bytes=8 << 20, pump_threads=2, crash=crash)
    d_sched, d_src, _ = make_graph()
    doomed = tier.register("doomed", d_sched, config())
    s_sched, s_src, _ = make_graph()
    sib = tier.register("sib", s_sched, config())
    t = doomed.submit(d_src, lines_batch("x"))
    with pytest.raises(PumpCrashed):
        t.result(timeout=10)
    assert crash.fired_seam == "pump_before_tick@doomed"
    assert sib.submit(s_src, lines_batch("fine")).result(10).applied
    tier.unregister("doomed", flush=False)
    tier.close()


def test_durable_graph_in_tier_recovers_exactly_once(tmp_path):
    wal_dir = str(tmp_path / "wal")
    crash = CrashInjector(at=2, only="pump_before_tick@wal")
    tier = ServeTier(max_bytes=8 << 20, pump_threads=2, crash=crash)
    g, src, sink = wordcount.build_graph()
    dsched = DurableScheduler(g, wal_dir=wal_dir, fsync="record")
    h = tier.register("wal", dsched, config())
    sent = [(f"m{j}", lines_batch(f"w{j % 4}", "c")) for j in range(30)]
    tks = []
    for bid, batch in sent:
        try:
            tks.append(h.submit(src, batch, batch_id=bid))
        except FrontendClosed:
            break
        time.sleep(0.001)  # several windows
    crashed = 0
    for t in tks:
        try:
            t.result(timeout=10)
        except PumpCrashed:
            crashed += 1
    assert crash.fired and crashed > 0
    tier.unregister("wal", flush=False)
    tier.close()

    # recover into a fresh tier and re-send EVERY id: exactly-once
    g2, src2, sink2 = wordcount.build_graph()
    rsched = DurableScheduler(g2, wal_dir=wal_dir, fsync="record")
    recover(rsched, wal_dir)
    tier2 = ServeTier(max_bytes=8 << 20, pump_threads=2)
    h2 = tier2.register("wal", rsched, config())
    results = [h2.submit(src2, batch, batch_id=bid).result(10)
               for bid, batch in sent]
    h2.flush(timeout=10)
    assert any(r.status == "deduped" for r in results)
    want_sched, want_src, want_sink = make_graph()
    for _bid, batch in sent:
        want_sched.push(want_src, batch)
        want_sched.tick()
    assert dict(rsched.view(sink2.name)) == dict(
        want_sched.view(want_sink.name))
    tier2.close()


# -- metrics -----------------------------------------------------------------

def test_tier_metrics_and_json_round_trip(tmp_path):
    tier = ServeTier(max_bytes=1 << 20, pump_threads=2)
    sched, src, sink = make_graph()
    h = tier.register("g", sched, config(weight=2.0, floor_bytes=1024))
    for j in range(20):
        h.submit(src, lines_batch(f"w{j % 3}")).result(10)
    h.flush(timeout=10)
    tm = summarize_tier(tier)
    assert tm.graphs == 1 and tm.pump_threads == 2
    assert tm.windows >= 1 and tm.pool_crashes == 0
    assert 0.0 <= tm.pump_utilization <= 1.0
    assert tm.budget_total_bytes == 1 << 20
    assert tm.budget_peak_bytes > 0
    g = tm.per_graph["g"]
    assert g["weight"] == 2.0 and g["floor_bytes"] == 1024
    assert g["applied"] == 20 and g["state"] == "running"
    assert g["windows"] >= 1 and g["rows_applied"] > 0
    # every export survives json round-trip (numpy scalars coerced)
    for payload in (tm.to_dict(), summarize_serve(h.frontend).to_dict()):
        assert json.loads(json.dumps(payload)) == payload
    wal = WriteAheadLog(str(tmp_path), fsync="record")
    wal.append({"kind": "tick", "tick": 0})
    wal.close()
    wm = summarize_wal(wal).to_dict()
    assert json.loads(json.dumps(wm)) == wm
    tier.close()
