"""Checkpoint seam: snapshot/restore round-trips (SURVEY.md §5)."""

from reflow_tpu import DirtyScheduler
from reflow_tpu.workloads import wordcount


def test_snapshot_is_isolated_from_live_state():
    g, src, sink = wordcount.build_graph()
    sched = DirtyScheduler(g)
    sched.push(src, wordcount.ingest_lines(["a b a"]))
    sched.tick()
    snap = sched.executor.state_snapshot()
    before = sched.view_dict(sink)

    sched.push(src, wordcount.ingest_lines(["a c"]))
    sched.tick()
    assert sched.view_dict(sink) != before

    # restoring the snapshot must bring back pre-mutation state:
    # replaying the second tick yields the same deltas as the first time
    sched.executor.state_restore(snap)
    sched.push(src, wordcount.ingest_lines(["a c"]))
    r = sched.tick()
    got = {k: w for (k, _v), w in r.sink_deltas["out"].to_counter().items()}
    assert ("a" in got) and ("c" in got)  # 'a' aggregate changed again


def test_restore_then_diverge():
    g, src, sink = wordcount.build_graph()
    sched = DirtyScheduler(g)
    sched.push(src, wordcount.ingest_lines(["x y"]))
    sched.tick()
    snap = sched.executor.state_snapshot()
    sched.push(src, wordcount.ingest_lines(["x"]))
    sched.tick()
    sched.executor.state_restore(snap)
    # after restore, retracting 'x y' must empty every group exactly
    sched.push(src, wordcount.ingest_lines(["x y"], weight=-1))
    sched.tick()
    assert all(
        st == {} for st in sched.executor.states.values() if isinstance(st, dict)
    )
