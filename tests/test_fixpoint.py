"""On-device fixpoint (executors/fixpoint.py): one compiled program per
tick, differential vs the host-driven loop, boundary-exit telescoping, and
fallback for unsupported region shapes."""

import numpy as np
import pytest

from reflow_tpu import DeltaBatch, DirtyScheduler, FlowGraph, Spec
from reflow_tpu.executors.tpu import TpuExecutor
from reflow_tpu.workloads import pagerank

N, E = 48, 200
TOL = 1e-5


def _run(executor, churn_ticks=2, sink=False, seed=3):
    web = pagerank.WebGraph.random(N, E, seed=seed)
    pg = pagerank.build_graph(N, tol=TOL)
    out = pg.graph.sink(pg.new_rank, "ranks_out") if sink else None
    sched = DirtyScheduler(pg.graph, executor, max_loop_iters=500)
    sched.push(pg.teleport, pagerank.teleport_batch(N))
    sched.push(pg.edges, web.initial_batch())
    results = [sched.tick()]
    for _ in range(churn_ticks):
        sched.push(pg.edges, web.churn(0.05))
        results.append(sched.tick())
    return sched, pg, results


def _ranks_arr(sched, pg):
    out = np.full(N, 1.0 - pagerank.DAMPING)
    for k, v in sched.read_table(pg.new_rank).items():
        out[int(k)] = float(v)
    return out


def test_fixpoint_used_and_matches_host_driven():
    s_fx, pg_fx, r_fx = _run(TpuExecutor(fixpoint=True))
    s_host, pg_host, r_host = _run(TpuExecutor(fixpoint=False))
    # a row-based while_loop tick, with the fused delta-vector program
    # disabled (PageRank declares a linear region, so fixpoint=True now
    # selects LinearFixpointProgram by default)
    ex_row = TpuExecutor(fixpoint=True, linear_fixpoint=False)
    s_row, pg_row, r_row = _run(ex_row)
    assert all(r.quiesced for r in r_fx + r_host + r_row)
    # all three are tol-converged fixpoints; distinct accumulation orders
    # bound their spread by ~tol/(1-damping) plus f32 noise
    bound = TOL / (1.0 - pagerank.DAMPING) + 1e-5
    np.testing.assert_allclose(
        _ranks_arr(s_fx, pg_fx), _ranks_arr(s_host, pg_host), atol=bound)
    np.testing.assert_allclose(
        _ranks_arr(s_fx, pg_fx), _ranks_arr(s_row, pg_row), atol=bound)
    # the fused program was actually selected on the default path
    assert s_fx.executor._linear_structure is not None
    assert s_row.executor._linear_structure is None


def test_fixpoint_matches_numpy_reference_after_churn():
    web = pagerank.WebGraph.random(N, E, seed=9)
    pg = pagerank.build_graph(N, tol=TOL)
    sched = DirtyScheduler(pg.graph, TpuExecutor(fixpoint=True),
                           max_loop_iters=500)
    sched.push(pg.teleport, pagerank.teleport_batch(N))
    sched.push(pg.edges, web.initial_batch())
    sched.tick()
    for _ in range(3):
        sched.push(pg.edges, web.churn(0.05))
        r = sched.tick()
        assert r.quiesced
    ref = pagerank.reference_ranks(web)
    np.testing.assert_allclose(_ranks_arr(sched, pg), ref, atol=5e-4)


def test_fixpoint_loop_rows_accounted():
    _, _, results = _run(TpuExecutor(fixpoint=True), churn_ticks=1)
    # the fused tick still reports loop traffic (deltas_in) and >1 passes
    assert results[0].passes > 2
    assert results[0].deltas_in > N + E  # ingress plus loop re-entries


def test_boundary_sink_matches_cpu_executor():
    """A sink fed by the in-region Reduce receives the telescoped table
    diff; its materialized view must equal the CPU executor's."""
    s_tpu, pg_tpu, _ = _run(TpuExecutor(fixpoint=True), sink=True, seed=5)
    from reflow_tpu.executors import CpuExecutor

    s_cpu, pg_cpu, _ = _run(CpuExecutor(), sink=True, seed=5)
    v_tpu = s_tpu.view_dict("ranks_out")
    v_cpu = s_cpu.view_dict("ranks_out")
    assert set(v_tpu) == set(v_cpu)
    for k in v_cpu:
        # f32 device accumulation vs f64 host oracle: relative-eps noise
        assert abs(float(v_tpu[k]) - float(v_cpu[k])) <= 1e-4


def test_non_reduce_boundary_falls_back_to_host_loop():
    """loop -> map (boundary, has outside sink) -> reduce -> back-edge:
    the map's emissions don't telescope, so the executor must decline the
    fused path and the host-driven loop must still converge."""
    K = 8
    spec = Spec((), np.float32, key_space=K, unique=True)
    raw = Spec((), np.float32, key_space=K)
    g = FlowGraph("decay")
    x = g.loop("x", spec)
    halved = g.map(x, lambda v: jnp_where_half(v), vectorized=True,
                   name="halve", spec=raw)
    out = g.sink(halved, "halves")
    nxt = g.reduce(halved, "sum", tol=1e-3, name="next", spec=spec)
    g.close_loop(x, nxt)
    ex = TpuExecutor(fixpoint=True)
    sched = DirtyScheduler(g, ex, max_loop_iters=200)
    sched.push(x, DeltaBatch(np.arange(K), np.ones(K, np.float32)))
    r = sched.tick()
    assert ex._fx_unsupported  # declined: map is a boundary producer
    assert r.quiesced and r.passes > 3


def jnp_where_half(v):
    return 0.5 * v
