"""reflow-lint: every rule gets a tripping fixture and a clean twin,
plus unit tests for the runtime lock-order monitor (NamedLock /
LockOrderMonitor) and the waiver grammar.

Fixture corpora are tiny repos written under tmp_path — the passes are
corpus-scoped (seam coverage needs a tests/ dir, lock cycles merge
edges across functions), so each fixture reproduces exactly the repo
layout the rule keys on.
"""

from __future__ import annotations

import threading

import pytest

from reflow_tpu.analysis import run
from reflow_tpu.utils.config import KNOBS, declare
from reflow_tpu.utils.runtime import (LockOrderError, LockOrderMonitor,
                                      NamedLock, named_lock)


def _lint(root, text_by_path, **kw):
    for rel, text in text_by_path.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(text)
    return run(str(root), **kw)


def _rules(report):
    return sorted({f["rule"] for f in report["findings"]})


# -- lock rules -------------------------------------------------------------

def test_lock_unnamed_trips_and_named_is_clean(tmp_path):
    bad = _lint(tmp_path / "a", {"reflow_tpu/m.py": (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n")},
        passes=["locks"])
    assert _rules(bad) == ["lock-unnamed"]
    ok = _lint(tmp_path / "b", {"reflow_tpu/m.py": (
        "from reflow_tpu.utils.runtime import named_lock\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = named_lock('m.c')\n")},
        passes=["locks"])
    assert ok["findings"] == []


def test_lock_order_cycle_detected_across_functions(tmp_path):
    src = (
        "from reflow_tpu.utils.runtime import named_lock\n"
        "A = named_lock('a')\n"
        "B = named_lock('b')\n"
        "def fwd():\n"
        "    with A:\n"
        "        with B:\n"
        "            pass\n"
        "def rev():\n"
        "    with B:\n"
        "        with A:\n"
        "            pass\n")
    bad = _lint(tmp_path / "a", {"reflow_tpu/m.py": src},
                passes=["locks"])
    assert _rules(bad) == ["lock-order-cycle"]
    assert "'a'" in bad["findings"][0]["msg"] or \
        "a" in bad["findings"][0]["msg"]
    # one consistent order: clean
    ok = _lint(tmp_path / "b", {"reflow_tpu/m.py": (
        "from reflow_tpu.utils.runtime import named_lock\n"
        "A = named_lock('a')\n"
        "B = named_lock('b')\n"
        "def fwd():\n"
        "    with A:\n"
        "        with B:\n"
        "            pass\n"
        "def fwd2():\n"
        "    with A:\n"
        "        with B:\n"
        "            pass\n")}, passes=["locks"])
    assert ok["findings"] == []


def test_lock_order_cycle_via_method_call_expansion(tmp_path):
    # m1 holds 'a' and calls a helper that takes 'b'; m2 nests b->a
    src = (
        "from reflow_tpu.utils.runtime import named_lock\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._a = named_lock('a')\n"
        "        self._b = named_lock('b')\n"
        "    def helper(self):\n"
        "        with self._b:\n"
        "            pass\n"
        "    def m1(self):\n"
        "        with self._a:\n"
        "            self.helper()\n"
        "    def m2(self):\n"
        "        with self._b:\n"
        "            with self._a:\n"
        "                pass\n")
    bad = _lint(tmp_path / "a", {"reflow_tpu/m.py": src},
                passes=["locks"])
    assert "lock-order-cycle" in _rules(bad)


def test_lock_blocking_call_trips_and_waiver_suppresses(tmp_path):
    body = (
        "import os\n"
        "from reflow_tpu.utils.runtime import named_lock\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = named_lock('m.c')\n"
        "    def f(self, fd):\n"
        "        with self._lock:\n"
        "            os.fsync(fd){}\n")
    bad = _lint(tmp_path / "a",
                {"reflow_tpu/m.py": body.format("")}, passes=["locks"])
    assert _rules(bad) == ["lock-blocking-call"]
    waived = _lint(tmp_path / "b", {"reflow_tpu/m.py": body.format(
        "  # reflow-lint: waive lock-blocking-call -- test")},
        passes=["locks"])
    assert waived["findings"] == []
    assert waived["waived"] == 1


def test_lock_wait_no_loop_trips_and_while_is_clean(tmp_path):
    tpl = (
        "import threading\n"
        "from reflow_tpu.utils.runtime import named_lock\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = named_lock('m.c')\n"
        "        self._cv = threading.Condition(self._lock)\n"
        "    def f(self):\n"
        "        with self._cv:\n"
        "{}\n")
    bad = _lint(tmp_path / "a", {"reflow_tpu/m.py": tpl.format(
        "            self._cv.wait()")}, passes=["locks"])
    assert _rules(bad) == ["lock-wait-no-loop"]
    ok = _lint(tmp_path / "b", {"reflow_tpu/m.py": tpl.format(
        "            while self.pending:\n"
        "                self._cv.wait()")}, passes=["locks"])
    assert ok["findings"] == []


# -- seam rules -------------------------------------------------------------

def test_seam_grammar_trips_on_bad_literal(tmp_path):
    bad = _lint(tmp_path / "a", {"reflow_tpu/m.py": (
        "class C:\n"
        "    def f(self):\n"
        "        self._crash.point('Bad-Seam')\n")}, passes=["seams"])
    assert _rules(bad) == ["seam-grammar"]


def test_seam_untested_trips_and_test_reference_cleans(tmp_path):
    mod = ("class C:\n"
           "    def f(self):\n"
           "        self._crash_point('lonely_seam')\n")
    bad = _lint(tmp_path / "a", {"reflow_tpu/m.py": mod,
                                 "tests/test_x.py": "# nothing\n"},
                passes=["seams"])
    assert _rules(bad) == ["seam-untested"]
    ok = _lint(tmp_path / "b", {
        "reflow_tpu/m.py": mod,
        "tests/test_x.py":
            "inj = CrashInjector(1, only='lonely_seam@g')\n"},
        passes=["seams"])
    assert ok["findings"] == []


def test_seam_dynamic_scope_prefix_checked(tmp_path):
    ok = _lint(tmp_path / "a", {"reflow_tpu/m.py": (
        "class C:\n"
        "    def f(self):\n"
        "        self._crash.point(f'pool_x@{self.name}')\n"),
        "tests/test_x.py": "only='pool_x@g0'\n"}, passes=["seams"])
    assert ok["findings"] == []
    bad = _lint(tmp_path / "b", {"reflow_tpu/m.py": (
        "class C:\n"
        "    def f(self):\n"
        "        self._crash.point(f'POOLX-{self.name}')\n")},
        passes=["seams"])
    assert _rules(bad) == ["seam-grammar"]


# -- metrics rules ----------------------------------------------------------

def test_metrics_unpaired_trips_and_unregister_cleans(tmp_path):
    reg = ("class C:\n"
           "    def publish(self, reg):\n"
           "        reg.register_source('c', lambda: {})\n")
    bad = _lint(tmp_path / "a", {"reflow_tpu/m.py": reg},
                passes=["metrics"])
    assert _rules(bad) == ["metrics-unpaired"]
    ok = _lint(tmp_path / "b", {"reflow_tpu/m.py": reg + (
        "    def close(self, reg):\n"
        "        reg.unregister_source('c')\n")}, passes=["metrics"])
    assert ok["findings"] == []


def test_metrics_name_grammar(tmp_path):
    bad = _lint(tmp_path / "a", {"reflow_tpu/m.py": (
        "def p(reg):\n"
        "    reg.gauge('Bad-Name', lambda: 1)\n"
        "    reg.unregister_prefix('x.')\n")}, passes=["metrics"])
    assert _rules(bad) == ["metrics-name"]
    ok = _lint(tmp_path / "b", {"reflow_tpu/m.py": (
        "def p(reg, key):\n"
        "    reg.gauge(f'{key}.fsync_rate', lambda: 1)\n"
        "    reg.unregister_prefix(f'{key}.')\n")}, passes=["metrics"])
    assert ok["findings"] == []


def test_metrics_registry_mismatch_trips_and_paired_release_cleans(
        tmp_path):
    """Registering into a caller-supplied registry while releasing only
    through the global REGISTRY satisfies the pairing rule but leaks
    every gauge on a private registry — the pre-fleet close-path bug."""
    bad = _lint(tmp_path / "a", {"reflow_tpu/m.py": (
        "from reflow_tpu.obs import REGISTRY\n"
        "class C:\n"
        "    def publish(self, reg):\n"
        "        reg.gauge('c.depth', lambda: 1)\n"
        "    def close(self):\n"
        "        REGISTRY.unregister_prefix('c.')\n")},
        passes=["metrics"])
    assert _rules(bad) == ["metrics-registry-mismatch"]
    assert "(registry, name)" in bad["findings"][0]["msg"]
    ok = _lint(tmp_path / "b", {"reflow_tpu/m.py": (
        "class C:\n"
        "    def publish(self, reg):\n"
        "        reg.gauge('c.depth', lambda: 1)\n"
        "        self._pairs = [(reg, 'c.')]\n"
        "    def close(self):\n"
        "        for reg, name in self._pairs:\n"
        "            reg.unregister_prefix(name)\n")},
        passes=["metrics"])
    assert ok["findings"] == []
    # global-only registrations released globally are the old (fine)
    # convention, not a mismatch
    ok2 = _lint(tmp_path / "c", {"reflow_tpu/m.py": (
        "from reflow_tpu.obs import REGISTRY\n"
        "def publish():\n"
        "    REGISTRY.gauge('c.depth', lambda: 1)\n"
        "def close():\n"
        "    REGISTRY.unregister_prefix('c.')\n")},
        passes=["metrics"])
    assert ok2["findings"] == []


def test_metrics_source_unreleased_is_corpus_wide(tmp_path):
    """register_source coverage crosses both the reflow_tpu/ boundary
    (a bench helper's source counts) and file boundaries (a release
    literal elsewhere in the corpus covers it)."""
    src = ("def hook(reg):\n"
           "    reg.register_source('orphan.src', lambda: {})\n")
    bad = _lint(tmp_path / "a", {"bench_helper.py": src},
                passes=["metrics"])
    assert _rules(bad) == ["metrics-source-unreleased"]
    assert bad["findings"][0]["path"] == "bench_helper.py"
    # a covering unregister literal in ANOTHER file is a release
    ok = _lint(tmp_path / "b", {
        "bench_helper.py": src,
        "reflow_tpu/sealer.py": (
            "def seal(reg):\n"
            "    reg.unregister_prefix('orphan.')\n")},
        passes=["metrics"])
    assert ok["findings"] == []
    # a release in the same file is the normal convention
    ok2 = _lint(tmp_path / "c", {"bench_helper.py": src + (
        "def unhook(reg):\n"
        "    reg.unregister_source('orphan.src')\n")},
        passes=["metrics"])
    assert ok2["findings"] == []


# -- env-knob rules ---------------------------------------------------------

def test_env_knob_direct_read_trips(tmp_path):
    bad = _lint(tmp_path / "a", {"reflow_tpu/m.py": (
        "import os\n"
        "x = os.environ.get('REFLOW_SOMETHING')\n")},
        passes=["envknobs"], rules=["env-knob-direct"])
    assert _rules(bad) == ["env-knob-direct"]
    # writes are exempt (the bench builds child environments)
    ok = _lint(tmp_path / "b", {"reflow_tpu/m.py": (
        "import os\n"
        "env = dict(os.environ)\n"
        "env['REFLOW_SOMETHING'] = '1'\n")},
        passes=["envknobs"], rules=["env-knob-direct"])
    assert ok["findings"] == []


def test_env_knob_undeclared_accessor_trips(tmp_path):
    bad = _lint(tmp_path / "a", {"reflow_tpu/m.py": (
        "from reflow_tpu.utils.config import env_int\n"
        "x = env_int('REFLOW_NEVER_DECLARED_XYZ')\n")},
        passes=["envknobs"], rules=["env-knob-undeclared"])
    assert _rules(bad) == ["env-knob-undeclared"]
    ok = _lint(tmp_path / "b", {"reflow_tpu/m.py": (
        "from reflow_tpu.utils.config import env_int\n"
        "x = env_int('REFLOW_WINDOW_DEPTH')\n")},
        passes=["envknobs"], rules=["env-knob-undeclared"])
    assert ok["findings"] == []


def test_env_knob_undocumented_against_fixture_guide(tmp_path):
    name = "REFLOW_TEST_UNDOC_KNOB"
    declare(name, "flag", False, "fixture-only knob")
    try:
        bad = _lint(tmp_path / "a", {"docs/guide.md": "# nothing\n"},
                    passes=["envknobs"],
                    rules=["env-knob-undocumented"])
        assert any(name in f["msg"] for f in bad["findings"])
        ok = _lint(tmp_path / "b", {"docs/guide.md": "\n".join(
            f"| `{k}` |" for k in KNOBS)},
            passes=["envknobs"], rules=["env-knob-undocumented"])
        assert ok["findings"] == []
    finally:
        del KNOBS[name]


# -- exception policy -------------------------------------------------------

def test_bare_assert_trips_and_raise_is_clean(tmp_path):
    bad = _lint(tmp_path / "a", {"reflow_tpu/m.py": (
        "def f(x):\n"
        "    assert x is not None\n"
        "    return x\n")}, passes=["exceptions"])
    assert _rules(bad) == ["bare-assert"]
    ok = _lint(tmp_path / "b", {"reflow_tpu/m.py": (
        "def f(x):\n"
        "    if x is None:\n"
        "        raise ValueError('x required')\n"
        "    return x\n")}, passes=["exceptions"])
    assert ok["findings"] == []
    # tests/ are exempt: pytest rewrites asserts
    ok2 = _lint(tmp_path / "c", {"tests/test_m.py": "assert True\n"},
                passes=["exceptions"])
    assert ok2["findings"] == []


# -- waiver grammar ---------------------------------------------------------

def test_waiver_without_reason_is_a_finding(tmp_path):
    # the marker is split so linting THIS file doesn't see a bad waiver
    rep = _lint(tmp_path / "a", {"reflow_tpu/m.py": (
        "def f(x):\n"
        "    # reflow-lint: " + "waive bare-assert\n"
        "    assert x\n")}, passes=["exceptions"])
    assert _rules(rep) == ["waiver-no-reason"]


def test_waiver_with_reason_suppresses_and_counts(tmp_path):
    rep = _lint(tmp_path / "a", {"reflow_tpu/m.py": (
        "def f(x):\n"
        "    # reflow-lint: waive bare-assert -- fixture says so\n"
        "    assert x\n")}, passes=["exceptions"])
    assert rep["findings"] == []
    assert rep["waived"] == 1


# -- socket rules -----------------------------------------------------------

def test_socket_naked_recv_and_connect_trip(tmp_path):
    rep = _lint(tmp_path / "a", {"reflow_tpu/m.py": (
        "import socket\n"
        "def pull(sock):\n"
        "    return sock.recv(4096)\n"
        "def dial(addr):\n"
        "    s = socket.socket()\n"
        "    s.connect(addr)\n"
        "    return s\n")}, passes=["sockets"])
    assert _rules(rep) == ["socket-no-timeout"]
    assert len(rep["findings"]) == 2


def test_socket_deadline_in_function_is_clean(tmp_path):
    rep = _lint(tmp_path / "a", {"reflow_tpu/m.py": (
        "import socket\n"
        "def pull(sock, deadline_s):\n"
        "    sock.settimeout(deadline_s)\n"
        "    return sock.recv(4096)\n"
        "def dial(addr):\n"
        "    return socket.create_connection(addr, timeout=2.0)\n"
        "def dial_kw(conn, addr):\n"
        "    conn.connect(addr, timeout=2.0)\n")}, passes=["sockets"])
    assert rep["findings"] == []


def test_socket_rule_scoped_to_socket_importers(tmp_path):
    # a scheduler's .connect() / .accept() must not trip the rule
    rep = _lint(tmp_path / "a", {"reflow_tpu/m.py": (
        "def wire(graph, a, b):\n"
        "    graph.connect(a, b)\n"
        "    return graph.accept()\n")}, passes=["sockets"])
    assert rep["findings"] == []


def test_socket_waiver_suppresses_with_reason(tmp_path):
    rep = _lint(tmp_path / "a", {"reflow_tpu/m.py": (
        "import socket\n"
        "def wait_forever(sock):\n"
        "    # reflow-lint: waive socket-no-timeout -- fixture blocks\n"
        "    return sock.recv(1)\n")}, passes=["sockets"])
    assert rep["findings"] == []
    assert rep["waived"] == 1


def test_report_schema_shape(tmp_path):
    rep = _lint(tmp_path / "a", {"reflow_tpu/m.py": "x = 1\n"})
    assert rep["schema"] == "reflow.lint/1"
    assert set(rep) >= {"root", "files_scanned", "passes", "findings",
                        "counts", "waived"}


def test_walker_skips_pycache(tmp_path):
    rep = _lint(tmp_path / "a", {
        "reflow_tpu/m.py": "x = 1\n",
        "reflow_tpu/__pycache__/m.py": "assert False\n"},
        passes=["exceptions"])
    assert rep["files_scanned"] == 1
    assert rep["findings"] == []


def test_repo_is_lint_clean():
    """The acceptance gate, as a test: the real tree has zero findings
    (everything pre-existing was fixed or waived with a reason)."""
    import os
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    rep = run(root)
    assert rep["findings"] == [], "\n".join(
        f"{f['path']}:{f['line']}: [{f['rule']}] {f['msg']}"
        for f in rep["findings"])


# -- runtime lock-order monitor --------------------------------------------

def _wrapped(name, mon, *, reentrant=False):
    inner = threading.RLock() if reentrant else threading.Lock()
    return NamedLock(name, inner, mon)


def test_lockcheck_cycle_across_two_threads():
    """The real AB/BA: thread 1 establishes a->b, thread 2 then tries
    b->a and must get LockOrderError instead of a deadlock."""
    mon = LockOrderMonitor()
    a, b = _wrapped("a", mon), _wrapped("b", mon)
    ready = threading.Event()
    err: list = []

    def t1():
        with a:
            with b:
                pass
        ready.set()

    def t2():
        ready.wait(5)
        try:
            with b:
                try:
                    with a:
                        pass
                except LockOrderError as e:
                    err.append(e)
        except LockOrderError as e:  # pragma: no cover - either site
            err.append(e)

    th1 = threading.Thread(target=t1)
    th2 = threading.Thread(target=t2)
    th1.start()
    th1.join(5)
    th2.start()
    th2.join(5)
    assert len(err) == 1
    msg = str(err[0])
    assert "'a'" in msg and "'b'" in msg and "cycle" in msg


def test_lockcheck_consistent_order_is_silent():
    mon = LockOrderMonitor()
    a, b = _wrapped("a", mon), _wrapped("b", mon)
    for _ in range(3):
        with a:
            with b:
                pass
    assert mon.edges() == {"a": {"b"}}


def test_lockcheck_transitive_cycle_detected():
    mon = LockOrderMonitor()
    a, b, c = (_wrapped(n, mon) for n in "abc")
    with a:
        with b:
            pass
    with b:
        with c:
            pass
    with pytest.raises(LockOrderError):
        with c:
            with a:
                pass


def test_lockcheck_rlock_reentry_is_not_a_cycle():
    mon = LockOrderMonitor()
    a = _wrapped("a", mon, reentrant=True)
    with a:
        with a:  # same instance: recursion, not a second acquisition
            pass
    assert mon.edges() == {}


def test_lockcheck_same_name_two_instances_raises():
    mon = LockOrderMonitor()
    a1, a2 = _wrapped("x", mon), _wrapped("x", mon)
    with a1:
        with pytest.raises(LockOrderError, match="distinct"):
            a2.acquire()


def test_lockcheck_condition_wait_keeps_held_list_balanced():
    mon = LockOrderMonitor()
    lk = _wrapped("cv.lock", mon, reentrant=True)
    cv = threading.Condition(lk)
    hit = threading.Event()
    leftover: list = []  # thread asserts don't reach pytest; collect

    def waiter():
        with cv:
            hit.set()
            cv.wait(timeout=5)
        # after the wait returns, this thread must hold nothing
        leftover.extend(mon.held_names())

    th = threading.Thread(target=waiter)
    th.start()
    hit.wait(5)
    with cv:
        cv.notify_all()
    th.join(5)
    assert not th.is_alive()
    assert leftover == []


def test_named_lock_factory_is_plain_when_disabled(monkeypatch):
    monkeypatch.delenv("REFLOW_LOCKCHECK", raising=False)
    lk = named_lock("plain.off")
    assert not isinstance(lk, NamedLock)
    monkeypatch.setenv("REFLOW_LOCKCHECK", "1")
    lk2 = named_lock("wrapped.on")
    assert isinstance(lk2, NamedLock)
    with lk2:
        pass  # acquire/release round-trips through the monitor
