"""Multiset-left device Join (VERDICT r4 #5 / ROADMAP r4 #2).

The device path holds BOTH join sides as append arenas and runs each
δ-product as a key-matched pair enumeration at a static budget
(``product_slack x delta_capacity`` slots). These tests pin the
semantics the fuzz can't target precisely: default-merge encoding,
vector values, budget overflow -> sticky error (never truncation), and
the bind-time spec validation. Differential coverage against the host
oracle also runs inside tests/test_fuzz_differential.py's grammar
(multiset-left joins are drawn there with default merge).
"""

from collections import Counter

import numpy as np
import pytest

from reflow_tpu import DirtyScheduler, FlowGraph
from reflow_tpu.delta import DeltaBatch, Spec
from reflow_tpu.executors import get_executor
from reflow_tpu.graph import GraphError
from reflow_tpu.parallel import make_mesh
from reflow_tpu.parallel.shard import ShardedTpuExecutor

K = 16


def _flat(v):
    if isinstance(v, tuple):
        out = []
        for x in v:
            out.extend(_flat(x) if isinstance(x, tuple) else [float(x)])
        return tuple(round(x, 3) for x in out)
    return tuple(round(float(x), 3) for x in np.asarray(v).ravel())


def _view(sched, sink):
    return Counter({(int(k), _flat(v)): w
                    for (k, v), w in sched.view(sink).items() if w})


def build_default(arena=2048, slack=4):
    g = FlowGraph("msj")
    a = g.source("a", Spec((), np.float32, key_space=K))
    b = g.source("b", Spec((), np.float32, key_space=K))
    j = g.join(a, b, spec=Spec((2,), np.float32, key_space=K),
               arena_capacity=arena, product_slack=slack)
    g.sink(j, "out")
    return g, a, b


def batch(keys, vals, w):
    return DeltaBatch(np.asarray(keys, np.int64),
                      np.asarray(vals, np.float32),
                      np.asarray(w, np.int64))


EXECUTORS = {
    "cpu": lambda: get_executor("cpu"),
    "tpu": lambda: get_executor("tpu"),
    "sharded": lambda: ShardedTpuExecutor(make_mesh(8)),
}


def drive_default(name):
    g, a, b = build_default()
    sched = DirtyScheduler(g, EXECUTORS[name]())
    # tick 1: multiset left (repeated key 3, weight-2 row), right rows
    sched.push(a, batch([3, 3, 5], [1., 2., 7.], [1, 2, 1]))
    sched.push(b, batch([3, 5, 5], [10., 20., 30.], [1, 1, 1]))
    sched.tick()
    # tick 2: left retraction + insert, another right row
    sched.push(a, batch([3, 5], [1., 9.], [-1, 1]))
    sched.push(b, batch([3], [40.], [1]))
    sched.tick()
    # tick 3: right retraction (pairs with ALL left rows of that key)
    sched.push(b, batch([5], [20.], [-1]))
    sched.tick()
    return _view(sched, "out")


def test_default_merge_differential_all_executors():
    ref = drive_default("cpu")
    assert ref  # non-trivial
    for name in ("tpu", "sharded"):
        got = drive_default(name)
        assert got == ref, (f"{name} disagrees: only-{name} {got - ref}, "
                            f"only-cpu {ref - got}")


def drive_custom(name):
    g = FlowGraph("msjc")
    a = g.source("a", Spec((2,), np.float32, key_space=K))
    b = g.source("b", Spec((), np.float32, key_space=K))

    def merge(k, va, vb):
        if getattr(va, "ndim", 1) <= 1:       # host per-row form
            return np.float64(va[0]) * vb + va[1]
        import jax.numpy as jnp
        return va[:, 0] * vb + va[:, 1]

    j = g.join(a, b, merge=merge, spec=Spec((), np.float32, key_space=K),
               arena_capacity=2048)
    g.sink(j, "out")
    sched = DirtyScheduler(g, EXECUTORS[name]())
    sched.push(a, batch([2, 2], [[2., 1.], [3., 0.]], [1, 1]))
    sched.push(b, batch([2, 2], [5., 6.], [1, 2]))
    sched.tick()
    sched.push(a, batch([2], [[2., 1.]], [-1]))
    sched.tick()
    return _view(sched, "out")


def test_custom_merge_vector_left_differential():
    ref = drive_custom("cpu")
    assert ref
    for name in ("tpu", "sharded"):
        assert drive_custom(name) == ref, name


def test_product_budget_overflow_sticky_error():
    """A true pair count beyond product_slack x delta_capacity must fail
    LOUDLY at the next sync — never silently truncate."""
    g, a, b = build_default(slack=1)
    sched = DirtyScheduler(g, get_executor("tpu"))
    # 60 left rows on ONE key, then 60 right rows on that key: the δB
    # product wants 60*60 = 3600 pairs against budget 1*64 = 64
    sched.push(a, batch(np.full(60, 3), np.arange(60), np.ones(60)))
    sched.tick()
    sched.push(b, batch(np.full(60, 3), np.arange(60), np.ones(60)))
    with pytest.raises(RuntimeError, match="sticky"):
        sched.tick()


def test_default_merge_spec_shape_validated_at_bind():
    g = FlowGraph("msv")
    a = g.source("a", Spec((), np.float32, key_space=K))
    b = g.source("b", Spec((), np.float32, key_space=K))
    g.join(a, b, arena_capacity=2048)   # default out spec: scalar (wrong)
    g.sink(g.nodes[-1], "out")
    with pytest.raises(GraphError, match="flat value elements"):
        DirtyScheduler(g, get_executor("tpu"))


def test_read_table_rejects_multiset_join():
    g, a, b = build_default()
    sched = DirtyScheduler(g, get_executor("tpu"))
    sched.push(a, batch([1], [1.], [1]))
    sched.tick()
    join_node = next(n for n in g.nodes
                     if n.kind == "op" and n.op.kind == "join")
    with pytest.raises(KeyError, match="multiset"):
        sched.read_table(join_node)
