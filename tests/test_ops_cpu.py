"""Unit tests per op on hand-built delta sequences (SURVEY.md §4a)."""

from collections import Counter

import numpy as np
import pytest

from reflow_tpu.delta import DeltaBatch
from reflow_tpu.ops import Filter, GroupBy, Join, Map, Reduce, Union


def batch(rows):
    """rows: list of (key, value, weight)."""
    return DeltaBatch(
        np.array([r[0] for r in rows], dtype=object),
        np.array([r[1] for r in rows], dtype=object),
        np.array([r[2] for r in rows], dtype=np.int64),
    )


def test_map():
    op = Map(lambda v: v * 10)
    out = op.apply(None, [batch([("a", 1, 1), ("b", 2, -1)])])
    assert out.to_counter() == {("a", 10): 1, ("b", 20): -1}


def test_map_vectorized():
    op = Map(lambda v: v + 1, vectorized=True)
    b = DeltaBatch(np.array([0, 1]), np.array([1.0, 2.0]), np.array([1, 1]))
    out = op.apply(None, [b])
    assert out.to_counter() == {(0, 2.0): 1, (1, 3.0): 1}


def test_filter():
    op = Filter(lambda v: v % 2 == 0)
    out = op.apply(None, [batch([("a", 1, 1), ("b", 2, 1), ("c", 4, -1)])])
    assert out.to_counter() == {("b", 2): 1, ("c", 4): -1}


def test_groupby_rekeys():
    op = GroupBy(lambda k, v: v % 3)
    out = op.apply(None, [batch([("x", 4, 1), ("y", 7, 1), ("z", 5, 1)])])
    assert out.to_counter() == {(1, 4): 1, (1, 7): 1, (2, 5): 1}


def test_reduce_sum_incremental():
    op = Reduce("sum")
    st = op.initial_state()
    out1 = op.apply(st, [batch([("a", 1.0, 1), ("a", 2.0, 1)])])
    assert out1.to_counter() == {("a", 3.0): 1}
    # retract one element: aggregate 3 -> 2, emitted as retract+insert
    out2 = op.apply(st, [batch([("a", 1.0, -1)])])
    assert out2.to_counter() == {("a", 3.0): -1, ("a", 2.0): 1}
    # retract the last element: group vanishes
    out3 = op.apply(st, [batch([("a", 2.0, -1)])])
    assert out3.to_counter() == {("a", 2.0): -1}
    assert st == {}


def test_reduce_count_weights():
    op = Reduce("count")
    st = op.initial_state()
    out = op.apply(st, [batch([("w", 1, 3), ("w", 1, 2)])])
    assert out.to_counter() == {("w", 5): 1}


def test_reduce_min_retract_nonlinear():
    op = Reduce("min")
    st = op.initial_state()
    op.apply(st, [batch([("a", 5, 1), ("a", 3, 1)])])
    out = op.apply(st, [batch([("a", 3, -1)])])  # min must climb back to 5
    assert out.to_counter() == {("a", 3): -1, ("a", 5): 1}


def test_reduce_tolerance_suppresses():
    op = Reduce("sum", tol=1e-6)
    st = op.initial_state()
    op.apply(st, [batch([("a", 1.0, 1)])])
    out = op.apply(st, [batch([("a", 1e-9, 1)])])
    assert len(out) == 0  # change below tol -> quiescent


def test_reduce_tol_drift_retracts_emitted_value():
    """Regression: tol-suppressed state drift must not corrupt later
    retractions — the retraction is against the last *emitted* aggregate."""
    op = Reduce("sum", tol=1e-6)
    st = op.initial_state()
    net = Counter()
    for kv, w in op.apply(st, [batch([("a", 1.0, 1)])]).to_counter().items():
        net[kv] += w
    op.apply(st, [batch([("a", 1e-9, 1)])])  # suppressed, state drifts
    out = op.apply(st, [batch([("a", 1.0, -1), ("a", 1e-9, -1)])])
    for kv, w in out.to_counter().items():
        net[kv] += w
    # group is empty again: all emissions must cancel exactly
    assert {kv: w for kv, w in net.items() if w != 0} == {}
    assert st == {}


def test_reduce_mixed_sign_multiset_preserved():
    """Regression: a multiset whose weights net to <= 0 is NOT 'vanished' —
    negative multiplicities are legal transients of the delta algebra."""
    op = Reduce("sum")
    st = op.initial_state()
    out1 = op.apply(st, [batch([("a", 5.0, -1), ("a", 3.0, 1)])])
    assert out1.to_counter() == {("a", -2.0): 1}  # 3 - 5
    out2 = op.apply(st, [batch([("a", 5.0, 1)])])  # cancels the retraction
    assert out2.to_counter() == {("a", -2.0): -1, ("a", 3.0): 1}


def test_join_differential():
    op = Join()
    st = op.initial_state()
    out1 = op.apply(st, [batch([("k", "a1", 1)]), batch([("k", "b1", 1)])])
    assert out1.to_counter() == {(("k"), ("a1", "b1")): 1}
    # new left row joins existing right state
    out2 = op.apply(st, [batch([("k", "a2", 1)]), DeltaBatch.empty()])
    assert out2.to_counter() == {("k", ("a2", "b1")): 1}
    # retract right row: both join outputs retract
    out3 = op.apply(st, [DeltaBatch.empty(), batch([("k", "b1", -1)])])
    assert out3.to_counter() == {("k", ("a1", "b1")): -1, ("k", ("a2", "b1")): -1}


def test_join_merge_fn():
    op = Join(merge=lambda k, va, vb: va + vb)
    st = op.initial_state()
    out = op.apply(st, [batch([("k", 1, 1)]), batch([("k", 10, 1)])])
    assert out.to_counter() == {("k", 11): 1}


def test_union():
    op = Union(2)
    out = op.apply(None, [batch([("a", 1, 1)]), batch([("b", 2, -1)])])
    assert out.to_counter() == {("a", 1): 1, ("b", 2): -1}


def test_join_incremental_vs_full_random():
    """Differential join == full A×B join on the accumulated input."""
    rng = np.random.default_rng(0)
    op = Join()
    st = op.initial_state()
    acc_a, acc_b, emitted = Counter(), Counter(), Counter()
    for _ in range(20):
        da = [(int(rng.integers(3)), int(rng.integers(4)), int(rng.choice([-1, 1])))
              for _ in range(rng.integers(0, 5))]
        db = [(int(rng.integers(3)), int(rng.integers(4)), int(rng.choice([-1, 1])))
              for _ in range(rng.integers(0, 5))]
        out = op.apply(st, [batch(da) if da else DeltaBatch.empty(),
                            batch(db) if db else DeltaBatch.empty()])
        for kv, w in out.to_counter().items():
            emitted[kv] += w  # NOT Counter.__iadd__, which drops ≤0 entries
        for k, v, w in da:
            acc_a[(k, v)] += w
        for k, v, w in db:
            acc_b[(k, v)] += w
    full = Counter()
    for (ka, va), wa in acc_a.items():
        for (kb, vb), wb in acc_b.items():
            if ka == kb and wa and wb:
                full[(ka, (va, vb))] += wa * wb
    emitted = Counter({kv: w for kv, w in emitted.items() if w != 0})
    full = Counter({kv: w for kv, w in full.items() if w != 0})
    assert emitted == full
