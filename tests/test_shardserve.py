"""Pod-scale serving (docs/guide.md "Sharded serving").

Two composable contracts on top of test_megatick.py's fused-window
semantics: (a) **tenant placement** — ``GraphConfig(device=...)`` /
``placement="spread"`` binds each tenant's executor to one mesh device
(distinct devices under spread, crash isolation and view parity
preserved), and (b) **sharded windows** — ``ShardedTpuExecutor`` runs
the SAME mega-tick window protocol with the ingress queue's stacked
buffers sharded along the capacity axis, view-identical to the CPU
per-tick oracle with zero fallbacks.
"""

import numpy as np
import pytest

from reflow_tpu import DirtyScheduler
from reflow_tpu.delta import DeltaBatch
from reflow_tpu.executors import get_executor
from reflow_tpu.graph import GraphError
from reflow_tpu.parallel import make_mesh
from reflow_tpu.parallel.shard import ShardedTpuExecutor
from reflow_tpu.serve import (CoalesceWindow, GraphConfig, PumpCrashed,
                              ServeTier)
from reflow_tpu.utils.faults import CrashInjector

from test_megatick import K_SPACE, _batch, _oracle, _small_graph, _table

WINDOW = CoalesceWindow(max_rows=256, max_ticks=8, max_latency_s=0.002)


def _mixed_ticks(seed, n_ticks=4, rows=6):
    """Ragged insert/retract feeds with integer-valued f32 payloads so
    every reduce sum is exact in f32 regardless of accumulation order
    (shard-local partial sums reorder the reduction)."""
    rng = np.random.default_rng(seed)
    ticks = []
    inserted = []
    for t in range(n_ticks):
        tick = {}
        for s_ix in (0, 1):
            if s_ix == 1 and t % 2 == 1:
                continue        # ragged: s1 absent on odd ticks
            rws = []
            for _ in range(rows):
                if inserted and rng.random() < 0.25:
                    k, v = inserted.pop(int(rng.integers(0, len(inserted))))
                    rws.append((k, v, -1))
                else:
                    k = int(rng.integers(0, K_SPACE))
                    v = float(rng.integers(0, 8))
                    rws.append((k, v, 1))
                    inserted.append((k, v))
            tick[s_ix] = rws
        ticks.append(tick)
    return ticks


def _sharded_window_drive(ticks, k, n):
    """Window drive of ``_small_graph`` on an ``n``-device mesh."""
    g, (s0, s1), r = _small_graph()
    sched = DirtyScheduler(g, ShardedTpuExecutor(make_mesh(n)))
    srcs = {0: s0, 1: s1}
    results = []
    for lo in range(0, len(ticks), k):
        feeds = [{srcs[s_ix]: _batch(rows) for s_ix, rows in tick.items()}
                 for tick in ticks[lo:lo + k]]
        results.append(sched.tick_many(feeds))
    for res in results:
        res.block()
    return _table(sched, r), sched


# -- sharded mega-tick windows: differential fuzz vs the CPU oracle --------

@pytest.mark.parametrize("n,k,seed", [(2, 2, 7), (2, 4, 8),
                                      (4, 2, 9), (4, 4, 10)])
def test_sharded_window_parity_fuzz(n, k, seed):
    """Mesh sizes x window sizes x seeds: the sharded window path must
    fuse (no fallback) and match the CPU per-tick oracle EXACTLY —
    inserts, retractions, and ragged zero-row padding included."""
    ticks = _mixed_ticks(seed, n_ticks=2 * k)
    want = _oracle(ticks)
    got, sched = _sharded_window_drive(ticks, k, n)
    assert got == want, f"n={n} k={k} seed={seed}"
    assert sched.megatick_fallbacks == 0
    assert sched.megatick_windows == 2
    assert sched.executor.device_label == f"mesh[{n}]"


def test_sharded_queue_buffers_are_sharded():
    """The ingress queue under a sharded executor must hold its stacked
    [K, cap] buffers with a NamedSharding along the capacity axis (not
    replicated): slot writes stay shard-local."""
    ticks = _mixed_ticks(31, n_ticks=2)
    _got, sched = _sharded_window_drive(ticks, k=2, n=2)
    qkeys = [key for key in sched.executor._cache
             if isinstance(key, tuple) and key and key[0] == "ingress_q"]
    assert len(qkeys) == 1
    queue = sched.executor._cache[qkeys[0]]
    stacked = queue.stacked()
    assert stacked, "queue holds no source buffers"
    axis = sched.executor.axis
    names = axis if isinstance(axis, tuple) else (axis,)
    for dd in stacked.values():
        sh = dd.keys.sharding
        spec_names = [p for p in sh.spec if p is not None]
        flat = []
        for p in spec_names:
            flat.extend(p if isinstance(p, tuple) else (p,))
        assert tuple(flat) == names, sh
        # leading axis (window slot K) stays unsharded
        assert sh.spec[0] is None, sh


# -- tenant placement --------------------------------------------------------

def _tpu_graph():
    g, (s0, s1), r = _small_graph()
    return DirtyScheduler(g, get_executor("tpu")), s0, r


def test_spread_placement_lands_distinct_devices():
    """placement="spread" round-robins tenants across jax.devices();
    each tenant's views still match a bare per-tick loop."""
    import jax
    n = min(4, len(jax.devices()))
    tier = ServeTier(max_bytes=8 << 20, pump_threads=2)
    handles = []
    try:
        for i in range(n):
            sched, src, r = _tpu_graph()
            h = tier.register(
                f"g{i}", sched,
                GraphConfig(window=WINDOW, placement="spread"))
            handles.append((h, sched, src, r))
        labels = [h.device_label for h, *_ in handles]
        assert all(labels), labels
        assert len(set(labels)) == n, labels
        for i, (h, sched, src, r) in enumerate(handles):
            for j in range(4):
                assert h.submit(src, _batch(
                    [(j, float(i + 1), 1)])).result(10).applied
            h.flush(timeout=10)
            want = {j: float(2 * (i + 1)) for j in range(4)}  # map doubles
            assert _table(sched, r) == want
    finally:
        tier.close()


def test_device_alone_implies_pin():
    import jax
    tier = ServeTier(max_bytes=8 << 20, pump_threads=1)
    try:
        sched, src, r = _tpu_graph()
        dev = jax.devices()[-1]
        h = tier.register("pin", sched, GraphConfig(window=WINDOW,
                                                    device=dev))
        assert h.device_label == f"{dev.platform}:{dev.id}"
        assert h.submit(src, _batch([(1, 3.0, 1)])).result(10).applied
        h.flush(timeout=10)
        assert _table(sched, r) == {1: 6.0}
    finally:
        tier.close()


def test_pin_accepts_device_index():
    """Integer device= pins by position in jax.devices()."""
    import jax
    tier = ServeTier(max_bytes=8 << 20, pump_threads=1)
    try:
        sched, _src, _r = _tpu_graph()
        h = tier.register("byix", sched,
                          GraphConfig(window=WINDOW, placement="pin",
                                      device=1))
        dev = jax.devices()[1]
        assert h.device_label == f"{dev.platform}:{dev.id}"
    finally:
        tier.close()


def test_placement_validation_errors():
    tier = ServeTier(max_bytes=8 << 20, pump_threads=1)
    try:
        sched, _s, _r = _tpu_graph()
        with pytest.raises(ValueError, match="placement"):
            tier.register("bad", sched,
                          GraphConfig(window=WINDOW, placement="stripe"))
        with pytest.raises(ValueError, match="device"):
            tier.register("bad", sched,
                          GraphConfig(window=WINDOW, placement="pin"))
        # an executor with no placement hook refuses loudly, not silently
        g, (_s0, _s1), _r2 = _small_graph()
        cpu_sched = DirtyScheduler(g, get_executor("cpu"))
        with pytest.raises(GraphError, match="place"):
            tier.register("cpu", cpu_sched,
                          GraphConfig(window=WINDOW, placement="spread"))
        assert "bad" not in tier.graphs()
        assert "cpu" not in tier.graphs()
    finally:
        tier.close()


def test_sharded_executor_refuses_single_device_placement():
    ex = ShardedTpuExecutor(make_mesh(2))
    with pytest.raises(GraphError, match="mesh"):
        ex.place(0)


def test_pinned_crash_isolates_to_its_device_tenant():
    """A pump crash on a pinned tenant leaves the sibling (pinned to a
    DIFFERENT device) applying — placement must not widen the blast
    radius of test_tier's crash-isolation contract."""
    crash = CrashInjector(at=1, only="pump_before_tick@doomed")
    tier = ServeTier(max_bytes=8 << 20, pump_threads=2, crash=crash)
    try:
        d_sched, d_src, _ = _tpu_graph()
        doomed = tier.register("doomed", d_sched,
                               GraphConfig(window=WINDOW, device=0))
        s_sched, s_src, s_r = _tpu_graph()
        sib = tier.register("sib", s_sched,
                            GraphConfig(window=WINDOW, device=1))
        assert doomed.device_label != sib.device_label
        t = doomed.submit(d_src, _batch([(1, 1.0, 1)]))
        with pytest.raises(PumpCrashed):
            t.result(timeout=10)
        assert crash.fired_seam == "pump_before_tick@doomed"
        assert sib.submit(s_src, _batch([(2, 4.0, 1)])).result(10).applied
        sib.flush(timeout=10)
        assert _table(s_sched, s_r) == {2: 8.0}
        tier.unregister("doomed", flush=False)
    finally:
        tier.close()


def test_placed_executor_runs_windows_on_its_device():
    """Direct executor-level check: place() moves state and the window
    path onto the chosen device, views unchanged."""
    import jax
    ticks = _mixed_ticks(17, n_ticks=4)
    want = _oracle(ticks)
    g, (s0, s1), r = _small_graph()
    ex = get_executor("tpu")
    ex.place(len(jax.devices()) - 1)
    sched = DirtyScheduler(g, ex)
    srcs = {0: s0, 1: s1}
    res = sched.tick_many(
        [{srcs[ix]: _batch(rows) for ix, rows in tick.items()}
         for tick in ticks])
    res.block()
    assert _table(sched, r) == want
    assert sched.megatick_fallbacks == 0
    dev = jax.devices()[-1]
    assert ex.device_label == f"{dev.platform}:{dev.id}"
    for v in ex.states.values():
        leaves = jax.tree.leaves(v)
        assert all(next(iter(l.devices())) == dev for l in leaves)
