"""Test env: force an 8-device virtual CPU mesh before jax import.

SURVEY.md §4d: mesh/collective/topo-partition tests run on CPU in CI via
``xla_force_host_platform_device_count`` — no TPU hardware required.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
