"""Test env: force an 8-device virtual CPU mesh before jax backend init.

SURVEY.md §4d: mesh/collective/topo-partition tests run on CPU in CI via
``xla_force_host_platform_device_count`` — no TPU hardware required.

Note: this environment exports ``JAX_PLATFORMS=axon`` (a live TPU tunnel)
and the axon plugin wins platform selection even when that env var is
overridden, so the platform must also be forced through ``jax.config``,
which works as long as it runs before first backend use.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
