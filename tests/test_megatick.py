"""Compiled mega-tick window path (docs/guide.md "Compiled mega-ticks").

The contract under test: ``tick_many`` over the device-resident ingress
queue (``TpuExecutor.run_window``) is view-identical to the per-tick
streaming path on the SAME feeds — ragged per-tick source sets are
padded to the window's union with zero-row deltas (weight-0 rows are
semantic no-ops), and every refusal (divergent dirty sets above the
waste threshold, over-capacity batches, unsupported graphs) falls back
cleanly to the stacked/per-tick paths with ``megatick_fallbacks``
counting the events, never a crash or a wrong view.
"""

import numpy as np
import pytest

from reflow_tpu import DirtyScheduler, FlowGraph
from reflow_tpu.delta import DeltaBatch, Spec
from reflow_tpu.executors import get_executor

K_SPACE = 32


def _batch(rows):
    return DeltaBatch(np.array([r[0] for r in rows], np.int64),
                      np.array([r[1] for r in rows], np.float32),
                      np.array([r[2] for r in rows], np.int64))


def _small_graph():
    """source -> map -> union(source2) -> reduce(sum): loop-free,
    sink-free, two sources so per-tick source sets can be ragged."""
    g = FlowGraph("megatick")
    spec = Spec((), np.float32, key_space=K_SPACE)
    s0 = g.source("s0", spec)
    s1 = g.source("s1", spec)
    m = g.map(s0, lambda v: v * np.float32(2), vectorized=True)
    u = g.union(m, s1)
    r = g.reduce(u, "sum", tol=0.0)
    return g, (s0, s1), r


def _ragged_ticks(n_ticks=4, rows=6, seed=3):
    """s0 fed every tick, s1 only on even ticks (pad share = 0.25)."""
    rng = np.random.default_rng(seed)
    ticks = []
    for t in range(n_ticks):
        tick = {0: [(int(rng.integers(0, K_SPACE)),
                     float(rng.integers(0, 8)), 1) for _ in range(rows)]}
        if t % 2 == 0:
            tick[1] = [(int(rng.integers(0, K_SPACE)),
                        float(rng.integers(0, 8)), 1) for _ in range(rows)]
        ticks.append(tick)
    return ticks


def _table(sched, node):
    return {int(k): round(float(np.asarray(v).reshape(())), 3)
            for k, v in sched.read_table(node).items()}


def _oracle(ticks):
    """CPU per-tick drive of the same feeds — the reference views."""
    g, (s0, s1), r = _small_graph()
    sched = DirtyScheduler(g, get_executor("cpu"))
    srcs = {0: s0, 1: s1}
    for tick in ticks:
        for s_ix, rows in tick.items():
            sched.push(srcs[s_ix], _batch(rows))
        sched.tick()
    return _table(sched, r)


def _window_drive(ticks, k, **tweak):
    """TPU tick_many drive in windows of ``k``; returns (table, sched)."""
    g, (s0, s1), r = _small_graph()
    ex = get_executor("tpu")
    for attr, v in tweak.pop("executor", {}).items():
        setattr(ex, attr, v)
    sched = DirtyScheduler(g, ex)
    for attr, v in tweak.items():
        setattr(sched, attr, v)
    srcs = {0: s0, 1: s1}
    results = []
    for lo in range(0, len(ticks), k):
        feeds = [{srcs[s_ix]: _batch(rows) for s_ix, rows in tick.items()}
                 for tick in ticks[lo:lo + k]]
        results.append(sched.tick_many(feeds))
    for res in results:
        res.block()
    return _table(sched, r), sched


def test_ragged_feeds_padded_to_window_union():
    """Ragged per-tick feeds ride ONE fused window (zero-row padding for
    the missing source slots) and the views match the per-tick oracle."""
    ticks = _ragged_ticks()
    want = _oracle(ticks)
    got, sched = _window_drive(ticks, k=4)
    assert got == want
    assert sched.megatick_windows == 1
    assert sched.megatick_fallbacks == 0


def test_divergent_dirty_sets_fall_back_cleanly():
    """With the waste threshold at zero, any padding means the dirty
    sets diverge 'too much': the window falls back (counter increments)
    and the per-tick path still produces the oracle views."""
    ticks = _ragged_ticks()
    want = _oracle(ticks)
    got, sched = _window_drive(ticks, k=4, megatick_waste=0.0)
    assert got == want
    assert sched.megatick_windows == 0
    assert sched.megatick_fallbacks == 1


def test_over_capacity_batches_fall_back_cleanly():
    """Batches above the executor's per-source row ceiling refuse the
    queue (no crash): fallback counter increments, views stay right."""
    ticks = _ragged_ticks(rows=12)
    want = _oracle(ticks)
    got, sched = _window_drive(
        ticks, k=4, executor={"megatick_max_rows": 8})
    assert got == want
    assert sched.megatick_windows == 0
    assert sched.megatick_fallbacks == 1


def test_queue_and_program_reused_across_windows():
    """Two same-shaped windows share one ingress queue and one compiled
    program: the second window is a pure dispatch."""
    ticks = _ragged_ticks(n_ticks=8)
    want = _oracle(ticks)
    got, sched = _window_drive(ticks, k=4)
    assert got == want
    assert sched.megatick_windows == 2
    assert sched.executor.window_dispatches == 2
    qkeys = [key for key in sched.executor._cache
             if isinstance(key, tuple) and key and key[0] == "ingress_q"]
    assert len(qkeys) == 1


def test_uniform_feeds_no_fallback_k2():
    """Uniform source sets (zero padding) fuse at any window size."""
    ticks = [{0: [(i, 1.0, 1)], 1: [(i, 2.0, 1)]} for i in range(4)]
    want = _oracle(ticks)
    got, sched = _window_drive(ticks, k=2)
    assert got == want
    assert sched.megatick_windows == 2
    assert sched.megatick_fallbacks == 0


# -- differential fuzz: window sizes x seeds vs the per-tick oracle --------

@pytest.mark.parametrize("k", [2, 4])
@pytest.mark.parametrize("seed", [10, 11, 12])
def test_fuzz_window_vs_pertick(seed, k):
    """test_fuzz_differential's streaming generator, driven through the
    fused window path in windows of ``k`` vs the cpu per-tick oracle:
    every aggregate table must agree (inserts AND retractions)."""
    from test_fuzz_differential import (build_streaming_graph, random_ticks,
                                        run_streaming)

    rng = np.random.default_rng(seed)
    graph_seed = rng.integers(0, 1 << 30)
    ticks_seed = rng.integers(0, 1 << 30)
    n_sources = len(build_streaming_graph(
        np.random.default_rng(graph_seed))[1])
    ticks = random_ticks(np.random.default_rng(ticks_seed), n_sources)

    g, sources, reduces = build_streaming_graph(
        np.random.default_rng(graph_seed))
    want = run_streaming(get_executor("cpu"), g, sources, reduces, ticks)

    g, sources, reduces = build_streaming_graph(
        np.random.default_rng(graph_seed))
    sched = DirtyScheduler(g, get_executor("tpu"))
    results = []
    for lo in range(0, len(ticks), k):
        feeds = []
        for tick in ticks[lo:lo + k]:
            feeds.append({sources[s_ix]: _batch(rows)
                          for s_ix, rows in tick})
        results.append(sched.tick_many(feeds))
    for res in results:
        res.block()
    got = {}
    for ix, node in enumerate(reduces):
        got[ix] = {int(key): round(float(np.asarray(v).reshape(())), 3)
                   for key, v in sched.read_table(node).items()}
    assert got == want, f"seed {seed} k {k}"
    assert sched.megatick_fallbacks == 0
    assert sched.megatick_windows == len(range(0, len(ticks), k))


def test_pagerank_loop_window_parity():
    """The fixpoint (loops) flavor of the window program: a churn window
    over PageRank matches a per-tick twin fed identical batches."""
    from reflow_tpu.workloads import pagerank

    n_nodes, n_edges, k = 128, 512, 4
    web = pagerank.WebGraph.random(n_nodes, n_edges, seed=5)
    init = web.initial_batch()
    churn = [web.churn(0.02) for _ in range(k)]

    tables = []
    scheds = []
    for _ in range(2):
        pr = pagerank.build_graph(n_nodes, tol=1e-5,
                                  arena_capacity=1 << 12)
        sched = DirtyScheduler(pr.graph, get_executor("tpu"))
        sched.push(pr.teleport, pagerank.teleport_batch(n_nodes))
        sched.push(pr.edges, init)
        sched.tick(sync=False)
        scheds.append((sched, pr))
    mega, pr_m = scheds[0]
    per, pr_p = scheds[1]
    mega.tick_many([{pr_m.edges: b} for b in churn]).block()
    for b in churn:
        per.push(pr_p.edges, b)
        per.tick(sync=False)
    ranks_m = pagerank.ranks_to_array(mega.read_table(pr_m.new_rank),
                                      n_nodes)
    ranks_p = pagerank.ranks_to_array(per.read_table(pr_p.new_rank),
                                      n_nodes)
    assert mega.megatick_windows == 1
    assert mega.megatick_fallbacks == 0
    np.testing.assert_allclose(ranks_m, ranks_p, atol=1e-6)


def test_window_donates_and_rebinds_queue_buffers():
    """The ingress stack is DONATED to the window program: after each
    window the queue must have adopted the program's fresh zeroed stack
    (old handles are dead), and the NEXT window over the same (now
    zeroed) buffers must still match the oracle — no stale rows, no
    use-after-donate."""
    ticks = _ragged_ticks(n_ticks=8)
    want = _oracle(ticks)
    got, sched = _window_drive(ticks, k=4)
    assert got == want
    assert sched.megatick_windows == 2
    qkeys = [key for key in sched.executor._cache
             if isinstance(key, tuple) and key and key[0] == "ingress_q"]
    queue = sched.executor._cache[qkeys[0]]
    for dd in queue.stacked().values():
        # rebind adopted the program's zeroed pass-through: every slot
        # is blank until the next window writes it
        assert int(np.asarray(dd.weights).sum()) == 0
        assert float(np.abs(np.asarray(dd.values)).sum()) == 0.0


def test_window_program_shared_across_identical_graphs():
    """Two tenants with identically-built graphs share ONE traced window
    program via the plan-signature cache: the second executor records
    cache hits instead of re-tracing, and its views still match."""
    ticks = _ragged_ticks(n_ticks=4, seed=9)
    want = _oracle(ticks)
    got_a, sched_a = _window_drive(ticks, k=4)
    got_b, sched_b = _window_drive(ticks, k=4)
    assert got_a == want and got_b == want
    assert sched_b.executor.megatick_cache_hits >= 1
    assert sched_a.megatick_fallbacks == 0
    assert sched_b.megatick_fallbacks == 0


# -- ingress queue unit behavior -------------------------------------------

def test_zero_padding_overwrites_stale_slot():
    """Queue buffers persist across windows: a padding (zero-row) write
    must CLEAR its slot, or the next window would replay last window's
    rows. The zero image is device-cached — counted in zero_writes."""
    from reflow_tpu.executors.ingress_queue import DeviceIngressQueue

    spec = Spec((), np.float32, key_space=8)
    q = DeviceIngressQueue({0: spec}, {0: 64}, 2)
    q.write(0, 0, _batch([(1, 2.0, 3)]))
    q.write(1, 0, _batch([(2, 1.0, 1)]))
    stacked = q.stacked()[0]
    assert int(np.asarray(stacked.weights[0]).sum()) == 3
    q.write(0, 0, _batch([]))          # next window, empty slot
    stacked = q.stacked()[0]
    assert int(np.asarray(stacked.weights[0]).sum()) == 0
    assert int(np.asarray(stacked.weights[1]).sum()) == 1
    assert q.zero_writes == 1


def test_queue_rejects_over_capacity_rows():
    from reflow_tpu.executors.ingress_queue import DeviceIngressQueue

    spec = Spec((), np.float32, key_space=8)
    q = DeviceIngressQueue({0: spec}, {0: 4}, 1)
    with pytest.raises(ValueError):
        q.write(0, 0, _batch([(i % 8, 1.0, 1) for i in range(5)]))


def test_slot_nbytes_is_bucketed_footprint():
    from reflow_tpu.executors.device_delta import bucket_capacity
    from reflow_tpu.executors.ingress_queue import slot_nbytes

    spec = Spec((), np.float32, key_space=8)
    cap = bucket_capacity(10)
    assert slot_nbytes(spec, 10) == cap * (4 + 4 + 4)
    vec = Spec((3,), np.float32, key_space=8)
    assert slot_nbytes(vec, 10) == cap * (4 + 4 + 12)


# -- serve wiring: admission keyed on device queue headroom ----------------

def test_frontend_advertises_megatick_and_device_admission():
    g, _srcs, _r = _small_graph()
    sched = DirtyScheduler(g, get_executor("tpu"))
    from reflow_tpu.serve import IngestFrontend

    fe = IngestFrontend(sched, start=False)
    assert fe.megatick is True
    assert fe.admission == "device"

    g2, _s, _r2 = _small_graph()
    cpu_sched = DirtyScheduler(g2, get_executor("cpu"))
    fe_cpu = IngestFrontend(cpu_sched, start=False)
    assert fe_cpu.megatick is False
    assert fe_cpu.admission == "host"

    g3, _s3, _r3 = _small_graph()
    fe_host = IngestFrontend(DirtyScheduler(g3, get_executor("tpu")),
                             start=False, admission="host")
    assert fe_host.admission == "host"
    with pytest.raises(ValueError):
        IngestFrontend(cpu_sched, start=False, admission="bogus")


def test_device_admission_charges_slot_bytes():
    """Under device-keyed admission a host batch charges its bucketed
    queue-slot footprint, not its payload bytes."""
    from reflow_tpu.executors.ingress_queue import slot_nbytes
    from reflow_tpu.serve import IngestFrontend
    from reflow_tpu.serve.queues import batch_nbytes

    g, (s0, _s1), _r = _small_graph()
    sched = DirtyScheduler(g, get_executor("tpu"))
    fe = IngestFrontend(sched, start=False)
    b = _batch([(1, 1.0, 1), (2, 2.0, 1)])
    assert fe._charge_bytes(s0, b, device=False) == slot_nbytes(s0.spec, 2)
    fe.admission = "host"
    assert fe._charge_bytes(s0, b, device=False) == batch_nbytes(b)


def test_frontend_pump_runs_fused_windows():
    """End to end through the serve pump: submissions over a tpu-backed
    sink-free scheduler commit via the fused window path."""
    from reflow_tpu.serve import IngestFrontend

    g, (s0, _s1), r = _small_graph()
    sched = DirtyScheduler(g, get_executor("tpu"))
    fe = IngestFrontend(sched)
    try:
        for i in range(8):
            fe.submit(s0, _batch([(i % K_SPACE, float(i), 1)]))
        fe.flush()
    finally:
        fe.close()
    assert sched.megatick_windows >= 1
    assert sched.megatick_fallbacks == 0
    total = sum(v * 2 for v in range(8))   # map doubles every value
    got = sum(float(np.asarray(v).reshape(()))
              for v in sched.read_table(r).values())
    assert got == pytest.approx(total)
