"""ShardedTpuExecutor on the 8-device virtual CPU mesh (SURVEY.md §4d):
collectives (psum_scatter, all_gather) + key-range sharding, differential
against the single-device TpuExecutor and the CPU oracle."""

import numpy as np
import pytest

from reflow_tpu import DeltaBatch, DirtyScheduler, FlowGraph, Spec
from reflow_tpu.executors import CpuExecutor
from reflow_tpu.executors.tpu import TpuExecutor
from reflow_tpu.parallel import make_mesh
from reflow_tpu.parallel.shard import ShardedTpuExecutor


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(8)


def _reduce_graph(K=64):
    spec = Spec((), np.float32, key_space=K)
    g = FlowGraph("wc")
    src = g.source("src", spec)
    ones = g.map(src, lambda v: v * 0 + 1, vectorized=True, name="ones")
    counts = g.reduce(ones, "sum", name="counts",
                      spec=Spec((), np.float32, key_space=K))
    out = g.sink(counts, "out")
    return g, src, out


def _push_ticks(sched, src, rng, K, ticks=3):
    views = []
    for t in range(ticks):
        n = 50 + 30 * t
        keys = rng.integers(0, K, n)
        w = np.where(rng.random(n) < 0.25, -1, 1)
        sched.push(src, DeltaBatch(keys, np.ones(n, np.float32), w))
        sched.tick()
        views.append(dict(sched.view_dict("out")))
    return views


def test_sharded_reduce_matches_cpu(mesh):
    K = 64
    g1, s1, _ = _reduce_graph(K)
    g2, s2, _ = _reduce_graph(K)
    sh = DirtyScheduler(g1, ShardedTpuExecutor(mesh))
    cp = DirtyScheduler(g2, CpuExecutor())
    v_sh = _push_ticks(sh, s1, np.random.default_rng(0), K)
    v_cp = _push_ticks(cp, s2, np.random.default_rng(0), K)
    for a, b in zip(v_sh, v_cp):
        assert {int(k): float(v) for k, v in a.items()} == \
               {int(k): float(v) for k, v in b.items()}


def test_sharded_pagerank_matches_single_device(mesh):
    from reflow_tpu.workloads import pagerank

    N, E = 64, 512
    ref_ranks = {}
    for ex in (ShardedTpuExecutor(mesh), TpuExecutor()):
        web = pagerank.WebGraph.random(N, E, seed=11)
        pg = pagerank.build_graph(N, tol=1e-5, arena_capacity=1 << 13)
        sched = DirtyScheduler(pg.graph, ex, max_loop_iters=500)
        sched.push(pg.teleport, pagerank.teleport_batch(N))
        sched.push(pg.edges, web.initial_batch())
        r = sched.tick()
        assert r.quiesced
        for _ in range(2):
            sched.push(pg.edges, web.churn(0.05))
            assert sched.tick().quiesced
        ref_ranks[ex.name] = sched.read_table(pg.new_rank)
        ref = pagerank.reference_ranks(web)

    a, b = ref_ranks["sharded"], ref_ranks["tpu"]
    assert set(a) == set(b)
    # distinct accumulation orders (row-based sharded vs fused linear)
    # give two tol-converged fixpoints within ~tol/(1-damping)
    bound = 1e-5 / (1.0 - pagerank.DAMPING) + 1e-4
    for k in a:
        assert abs(float(a[k]) - float(b[k])) < bound
    # and both match the NumPy oracle on the churned graph
    np.testing.assert_allclose(pagerank.ranks_to_array(a, N), ref,
                               atol=5e-4)


def test_sharded_join_matches_cpu(mesh):
    K = 32
    left_spec = Spec((), np.float32, key_space=K, unique=True)
    right_spec = Spec((), np.float32, key_space=K)

    def build():
        g = FlowGraph("j")
        a = g.source("a", left_spec)
        b = g.source("b", right_spec)
        j = g.join(a, b, merge=lambda k, va, vb: va * 10 + vb,
                   spec=right_spec, name="j", arena_capacity=1 << 10)
        out = g.sink(j, "out")
        return g, a, b

    ga, a1, b1 = build()
    gb, a2, b2 = build()
    sh = DirtyScheduler(ga, ShardedTpuExecutor(mesh))
    cp = DirtyScheduler(gb, CpuExecutor())

    def drive(sched, a, b):
        rng = np.random.default_rng(5)
        ka = rng.permutation(K)[:16]
        sched.push(a, DeltaBatch(ka, ka.astype(np.float32)))
        kb = rng.integers(0, K, 40)
        sched.push(b, DeltaBatch(kb, np.ones(40, np.float32)))
        sched.tick()
        # retract some right rows, add more left keys next tick
        sched.push(b, DeltaBatch(kb[:10], np.ones(10, np.float32),
                                 -np.ones(10, np.int64)))
        sched.tick()
        return {kv: w for kv, w in sched.view("out").items()}

    va = drive(sh, a1, b1)
    # CPU merge gets scalar args; device merge gets arrays — same formula
    vb = drive(cp, a2, b2)
    norm = lambda d: {(int(k), float(v)): int(w) for (k, v), w in d.items()}
    assert norm(va) == norm(vb)


def test_key_space_divisibility_enforced(mesh):
    g = FlowGraph("bad")
    src = g.source("s", Spec((), np.float32, key_space=30))
    r = g.reduce(src, "sum", spec=Spec((), np.float32, key_space=30))
    g.sink(r, "out")
    from reflow_tpu.graph import GraphError

    with pytest.raises(GraphError, match="multiple of the mesh"):
        DirtyScheduler(g, ShardedTpuExecutor(mesh))


def test_sharded_route_overflow_surfaces(mesh):
    """ADVICE r2 (high): pathological key skew past the ROUTE_SLACK budget
    must raise through check_errors for LINEAR reducers too — never a
    silently wrong aggregate."""
    K = 512  # Kl=64 per shard; delta cap 64 -> Cl=8 -> sparse regime
    g, src, _ = _reduce_graph(K)
    sh = DirtyScheduler(g, ShardedTpuExecutor(mesh))
    n = 64
    keys = np.arange(n) % 64  # every key owned by shard 0: worst-case skew
    sh.push(src, DeltaBatch(keys, np.ones(n, np.float32),
                            np.ones(n, np.int64)))
    with pytest.raises(RuntimeError, match="route overflow"):
        sh.tick()


def test_sharded_linear_fixpoint_engages(mesh):
    """VERDICT r2 item 5: the fused delta-vector loop must actually run on
    the sharded executor (not silently fall back to the row program), and
    match the single-device executor bit-for-bit on the ranks table."""
    from reflow_tpu.workloads import pagerank

    N, E = 64, 512
    results = {}
    for name, ex in (("sharded", ShardedTpuExecutor(mesh)),
                     ("single", TpuExecutor())):
        web = pagerank.WebGraph.random(N, E, seed=21)
        pg = pagerank.build_graph(N, tol=1e-6, arena_capacity=1 << 13)
        sched = DirtyScheduler(pg.graph, ex, max_loop_iters=500)
        sched.push(pg.teleport, pagerank.teleport_batch(N))
        sched.push(pg.edges, web.initial_batch())
        r = sched.tick()
        assert r.quiesced
        for _ in range(2):
            sched.push(pg.edges, web.churn(0.05))
            assert sched.tick().quiesced
        assert ex._linear_fixpoint, f"{name}: fused loop fell back"
        assert ex._linear_structure is not None
        results[name] = sched.read_table(pg.new_rank)
    assert set(results["sharded"]) == set(results["single"])
    for k in results["single"]:
        a = np.asarray(results["sharded"][k], np.float32)
        b = np.asarray(results["single"][k], np.float32)
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)


def test_sharded_join_routed_path_differential(mesh):
    """Large deltas take the routed (all_to_all) join path — per-dest
    budget >= _MIN_ROUTE_BUDGET rows — and must match the CPU oracle."""
    K = 1024
    rows = 2048  # Cl=256/shard, budget=128: routing engages on n=8
    spec = Spec((), np.float32, key_space=K)

    def build():
        g = FlowGraph("join")
        left_src = g.source("L", spec)
        right_src = g.source("R", spec)
        lt = g.reduce(left_src, "sum", name="lt")   # unique-keyed left
        j = g.join(lt, right_src, merge=lambda k, x, y: x + y,
                   spec=spec, name="j", arena_capacity=1 << 15)
        g.sink(j, "out")
        return g, left_src, right_src

    rng = np.random.default_rng(5)
    outs = []
    for ex in (ShardedTpuExecutor(mesh), CpuExecutor()):
        g, ls, rs = build()
        sched = DirtyScheduler(g, ex)
        r = np.random.default_rng(5)
        lk = r.integers(0, K, rows)
        sched.push(ls, DeltaBatch(
            lk, r.integers(0, 100, rows).astype(np.float32),
            np.ones(rows, np.int64)))
        sched.tick()
        rk = r.integers(0, K, rows)
        sched.push(rs, DeltaBatch(
            rk, r.integers(0, 100, rows).astype(np.float32),
            np.ones(rows, np.int64)))
        sched.tick()
        # second right batch incl. retractions of the first
        sched.push(rs, DeltaBatch(rk[:rows // 2],
                                  np.zeros(rows // 2, np.float32),
                                  -np.ones(rows // 2, np.int64)))
        sched.tick()
        outs.append(dict(sched.view("out")))
    a, b = outs
    assert set(a) == set(b)
    for k in a:
        assert a[k] == b[k], (k, a[k], b[k])


def test_sharded_minmax_matches_cpu(mesh):
    """Sharded scalar min/max: rows routed to key owners, candidate-buffer
    kernel per shard — exact under retraction churn within the buffer."""
    K = 64
    spec = Spec((), np.float32, key_space=K)
    for how in ("min", "max"):
        g = FlowGraph(how)
        src = g.source("s", spec)
        g.sink(g.reduce(src, how, name="m"), "out")
        g2 = FlowGraph(how)
        src2 = g2.source("s", spec)
        g2.sink(g2.reduce(src2, how, name="m"), "out")
        sh = DirtyScheduler(g, ShardedTpuExecutor(mesh))
        cp = DirtyScheduler(g2, CpuExecutor())
        # identical delta sequence on both: inserts + exact retractions
        rng = np.random.default_rng(8)
        inserted = []
        ticks = []
        for _ in range(3):
            rows = []
            for _ in range(96):
                if inserted and rng.random() < 0.3:
                    k, v = inserted.pop(int(rng.integers(0, len(inserted))))
                    rows.append((k, v, -1))
                else:
                    k = int(rng.integers(0, K))
                    v = float(rng.integers(-50, 50))
                    rows.append((k, v, 1))
                    inserted.append((k, v))
            ticks.append(rows)
        for sched, src_n in ((sh, src), (cp, src2)):
            for rows in ticks:
                sched.push(src_n, DeltaBatch(
                    np.array([r[0] for r in rows]),
                    np.array([r[1] for r in rows], np.float32),
                    np.array([r[2] for r in rows])))
                sched.tick()
        a = {int(k): float(v) for k, v in sh.view_dict("out").items()}
        b = {int(k): float(v) for k, v in cp.view_dict("out").items()}
        assert a == b, how


def test_sharded_minmax_buffer_exhaustion_flags_error(mesh):
    """candidates=1 on the mesh: hollowing a key's buffer past its one
    eviction trips the sticky error through the routed path too."""
    K = 64
    spec = Spec((), np.float32, key_space=K)
    g = FlowGraph("mm1")
    src = g.source("s", spec)
    g.sink(g.reduce(src, "max", name="m", candidates=1), "out")
    sh = DirtyScheduler(g, ShardedTpuExecutor(mesh))
    sh.push(src, DeltaBatch(np.array([3, 3]),
                            np.array([2.0, 1.0], np.float32),
                            np.ones(2, np.int64)))
    sh.tick()    # buffer [2.0], overflow {1.0}
    sh.push(src, DeltaBatch(np.array([3]), np.array([2.0], np.float32),
                            -np.ones(1, np.int64)))
    with pytest.raises(RuntimeError, match="min/max"):
        sh.tick()


def test_sharded_macro_tick_matches_sequential(mesh):
    """tick_many on the sharded executor: the scan-fused macro-tick must
    run the SPMD tick program per scan step and match sequential
    streaming ticks bit for bit."""
    from reflow_tpu.workloads import pagerank

    N, E, K = 64, 256, 3
    web_a = pagerank.WebGraph.random(N, E, seed=23)
    web_b = pagerank.WebGraph.random(N, E, seed=23)

    def prep(web):
        pg = pagerank.build_graph(N, tol=1e-5, arena_capacity=1 << 13)
        sched = DirtyScheduler(pg.graph, ShardedTpuExecutor(mesh),
                               max_loop_iters=500)
        sched.push(pg.teleport, pagerank.teleport_batch(N))
        sched.push(pg.edges, web.initial_batch())
        sched.tick()
        return pg, sched, [web.churn(0.1) for _ in range(K)]

    pg_a, sched_a, churns_a = prep(web_a)
    for b in churns_a:
        sched_a.push(pg_a.edges, b)
        sched_a.tick(sync=False)

    pg_b, sched_b, churns_b = prep(web_b)
    agg = sched_b.tick_many(
        [{pg_b.edges: b} for b in churns_b]).block()
    assert agg.quiesced

    ranks_a = sched_a.read_table(pg_a.new_rank)
    ranks_b = sched_b.read_table(pg_b.new_rank)
    assert set(ranks_a) == set(ranks_b)
    for k in ranks_a:
        assert float(ranks_a[k]) == float(ranks_b[k])


def test_shard_batch_presharded_ingress_matches_host_push(mesh):
    """parallel.mesh.shard_batch builds a row-sharded DeviceDelta from
    per-shard host chunks (the single-controller form of the multi-host
    ingestion recipe); pushing it must equal pushing the equivalent
    host batch."""
    from reflow_tpu.parallel.mesh import shard_batch

    K = 64
    rng = np.random.default_rng(21)
    n = 8 * 16
    keys = rng.integers(0, K, n)
    w = np.where(rng.random(n) < 0.25, -1, 1)
    vals = np.ones(n, np.float32)

    g1, s1, _ = _reduce_graph(K)
    a = DirtyScheduler(g1, ShardedTpuExecutor(mesh))
    a.push(s1, DeltaBatch(keys, vals, w))
    a.tick()

    g2, s2, _ = _reduce_graph(K)
    b = DirtyScheduler(g2, ShardedTpuExecutor(mesh))
    chunks = [DeltaBatch(keys[i::8], vals[i::8], w[i::8]) for i in range(8)]
    b.push(s2, shard_batch(chunks, s2.spec, mesh))
    b.tick()

    assert dict(a.view_dict("out")) == dict(b.view_dict("out"))


def test_two_axis_dcn_mesh_single_controller(mesh):
    """make_mesh(dcn=2) on one controller: the executor shards over the
    flattened (dcn, delta) product axis and matches the 1-axis result."""
    from reflow_tpu.parallel.mesh import shard_batch_process_local
    from reflow_tpu.workloads import pagerank

    N, E = 64, 512
    results = {}
    for name in ("flat", "dcn"):
        web = pagerank.WebGraph.random(N, E, seed=41)
        pg = pagerank.build_graph(N, tol=1e-5, arena_capacity=1 << 13)
        m = mesh if name == "flat" else make_mesh(dcn=2)
        ex = ShardedTpuExecutor(m)
        if name == "dcn":
            assert ex.axis == ("dcn", "delta") and ex.n == 8
        sched = DirtyScheduler(pg.graph, ex, max_loop_iters=500)
        # process-local ingestion helper (single-controller degenerate
        # form: one process holds everything)
        sched.push(pg.teleport, shard_batch_process_local(
            pagerank.teleport_batch(N), pg.teleport.spec, m,
            capacity=1 << 7))
        sched.push(pg.edges, shard_batch_process_local(
            web.initial_batch(), pg.edges.spec, m, capacity=1 << 10))
        assert sched.tick().quiesced
        sched.push(pg.edges, web.churn(0.05))
        assert sched.tick().quiesced
        results[name] = sched.read_table(pg.new_rank)
    assert set(results["flat"]) == set(results["dcn"])
    bound = 1e-5 / (1.0 - pagerank.DAMPING) + 1e-4
    for k in results["flat"]:
        assert abs(float(results["flat"][k])
                   - float(results["dcn"][k])) < bound
