"""Write-ahead delta log: framing, rotation, truncation, and the
crash-recovery differential — a killed/torn/recovered run's sink views
must equal an uninterrupted clean run's (exactly-once across process
death), extending the lossy-transport property of
``test_aux.test_fault_injection_exactly_once`` to crashes."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from reflow_tpu import DirtyScheduler
from reflow_tpu.utils.checkpoint import save_checkpoint
from reflow_tpu.utils.faults import (CrashInjector, CrashPoint,
                                     DeliveryError, FaultyChannel,
                                     tear_wal_tail)
from reflow_tpu.utils.metrics import summarize, summarize_wal
from reflow_tpu.wal import (DurableScheduler, WalError, WriteAheadLog,
                            recover, scan_wal)
from reflow_tpu.wal.log import LogPosition, list_segments
from reflow_tpu.workloads import wordcount

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- feed / drive helpers ---------------------------------------------------

def make_feed(seed: int, n_ticks: int = 10):
    """Deterministic per-tick [(batch_id, DeltaBatch)] lists, with
    retraction batches mixed in so the differential exercises the full
    delta algebra, not just inserts."""
    rng = np.random.default_rng(seed)
    feed = []
    for t in range(n_ticks):
        batches = []
        for j in range(int(rng.integers(1, 3))):
            words = " ".join(
                f"w{int(x)}" for x in rng.integers(0, 25,
                                                   int(rng.integers(2, 8))))
            weight = -1 if (t > 2 and rng.random() < 0.2) else 1
            batches.append((f"t{t}b{j}",
                            wordcount.ingest_lines([words], weight=weight)))
        feed.append(batches)
    return feed


def clean_run(feed):
    g, src, sink = wordcount.build_graph()
    sched = DirtyScheduler(g)
    for batches in feed:
        for bid, b in batches:
            sched.push(src, b, batch_id=bid)
        sched.tick()
    return dict(sched.view(sink.name))


def drive(sched, src, feed):
    for batches in feed:
        for bid, b in batches:
            sched.push(src, b, batch_id=bid)
        sched.tick()


def resume_from_cursor(sched, src, feed):
    """What a restarted upstream does: re-send EVERYTHING from its own
    cursor with the same batch ids; the dedup window keeps replayed
    batches from folding twice."""
    drive(sched, src, feed)


# -- log mechanics ----------------------------------------------------------

def test_append_scan_roundtrip(tmp_path):
    wal = WriteAheadLog(str(tmp_path), fsync="record")
    b = wordcount.ingest_lines(["a b a"])
    p0 = wal.append({"kind": "push", "tick": 0, "node": 0,
                     "node_name": "words", "batch_id": "b0",
                     "keys": b.keys, "values": b.values,
                     "weights": b.weights})
    p1 = wal.append({"kind": "tick", "tick": 1})
    wal.close()
    records, torn = scan_wal(str(tmp_path))
    assert torn is None
    assert [pos for pos, _ in records] == [p0, p1]
    assert records[0][1]["batch_id"] == "b0"
    assert list(records[0][1]["keys"]) == list(b.keys)
    assert records[1][1] == {"kind": "tick", "tick": 1}
    assert wal.appends == 2 and wal.fsyncs >= 2 and wal.bytes_written > 0


def test_segment_rotation_and_truncate(tmp_path):
    wal = WriteAheadLog(str(tmp_path), fsync="os", segment_bytes=256)
    for i in range(64):
        wal.append({"kind": "tick", "tick": i})
    wal.close()
    segs = list_segments(str(tmp_path))
    assert len(segs) > 1, "256-byte segments must have rotated"
    records, torn = scan_wal(str(tmp_path))
    assert torn is None
    assert [r["tick"] for _p, r in records] == list(range(64))

    # truncation drops sealed segments strictly before the position
    cut = segs[2][0]
    wal2 = WriteAheadLog(str(tmp_path), fsync="os")
    removed = wal2.truncate_until(LogPosition(cut, 8))
    wal2.close()
    assert len(removed) == 2
    assert all(seq >= cut for seq, _ in list_segments(str(tmp_path)))
    kept, _ = scan_wal(str(tmp_path))
    assert [r["tick"] for _p, r in kept if r["kind"] == "tick"] \
        == [r["tick"] for p, r in records
            if p.segment >= cut and r["kind"] == "tick"]


def test_torn_tail_tolerated_but_sealed_corruption_raises(tmp_path):
    # tear the last record: tolerated, scan stops at the tear
    torn_dir = str(tmp_path / "torn")
    wal = WriteAheadLog(torn_dir, fsync="os")
    for i in range(10):
        wal.append({"kind": "tick", "tick": i})
    wal.close()
    full, _ = scan_wal(torn_dir)
    assert tear_wal_tail(torn_dir, 5) is not None
    records, torn = scan_wal(torn_dir)
    assert torn is not None and "truncated" in torn.reason
    assert len(records) == len(full) - 1

    # flip a byte inside a SEALED (non-final) segment: real corruption
    sealed_dir = str(tmp_path / "sealed")
    wal = WriteAheadLog(sealed_dir, fsync="os", segment_bytes=200)
    for i in range(40):
        wal.append({"kind": "tick", "tick": i})
    wal.close()
    seg0 = list_segments(sealed_dir)[0][1]
    with open(seg0, "rb+") as f:
        f.seek(20)
        byte = f.read(1)
        f.seek(20)
        f.write(bytes([byte[0] ^ 0xFF]))
    with pytest.raises(WalError):
        scan_wal(sealed_dir)


def test_fresh_writer_never_appends_to_existing_segment(tmp_path):
    wal = WriteAheadLog(str(tmp_path), fsync="os")
    wal.append({"kind": "tick", "tick": 1})
    wal.close()
    tear_wal_tail(str(tmp_path), 3)  # crashed process left a torn tail
    wal2 = WriteAheadLog(str(tmp_path), fsync="os")
    wal2.append({"kind": "tick", "tick": 2})
    wal2.close()
    # the torn record is confined to the old segment; the new record
    # lives in a fresh segment and still parses
    records, torn = scan_wal(str(tmp_path))
    assert torn is None  # tear is not in the LAST segment...
    assert [r["tick"] for _p, r in records] == [2]


# -- crash-recovery differential (the acceptance property) -----------------

@pytest.mark.parametrize("seed", range(6))
def test_crash_recovery_differential(tmp_path, seed):
    """Kill at an arbitrary instrumented seam (including between push
    and tick), optionally tear the final record, recover, resume from
    the upstream cursor: sink views == clean run, no batch folded
    twice."""
    feed = make_feed(seed)
    want = clean_run(feed)
    rng = np.random.default_rng(1000 + seed)

    wal_dir = str(tmp_path / "wal")
    g, src, sink = wordcount.build_graph()
    crash = CrashInjector(int(rng.integers(1, 60)))
    sched = DurableScheduler(g, wal_dir=wal_dir, fsync="record",
                             crash=crash)
    with pytest.raises(CrashPoint):
        drive(sched, src, feed)
        raise CrashPoint("end-of-feed")  # feed exhausted before the kill
    sched.wal.drain()  # settle the committer: frames enqueued before the
    # "kill" land in the page cache, as a real death would leave them
    if crash.fired and rng.random() < 0.5:
        tear_wal_tail(wal_dir, int(rng.integers(1, 24)))

    g2, src2, sink2 = wordcount.build_graph()
    sched2 = DurableScheduler(g2, wal_dir=wal_dir, fsync="record")
    report = recover(sched2, wal_dir)
    resume_from_cursor(sched2, src2, feed)
    assert dict(sched2.view(sink2.name)) == want, (
        f"seed {seed}: crashed at {crash.seams[-1] if crash.seams else '?'} "
        f"after {len(crash.seams)} seams; report={report.as_dict()}")


@pytest.mark.parametrize("seam", ["before_append", "after_append",
                                  "after_push", "before_tick_mark"])
def test_crash_at_each_seam(tmp_path, seam):
    """Pin the kill to each seam class — the push-vs-tick windows the
    ISSUE calls out — instead of relying on the fuzz to land there."""
    feed = make_feed(99)
    want = clean_run(feed)
    wal_dir = str(tmp_path / seam)
    g, src, sink = wordcount.build_graph()
    crash = CrashInjector(7, only=seam)
    sched = DurableScheduler(g, wal_dir=wal_dir, fsync="tick", crash=crash)
    with pytest.raises(CrashPoint):
        drive(sched, src, feed)
    sched.wal.drain()  # deterministic page-cache state for the replay
    g2, src2, sink2 = wordcount.build_graph()
    sched2 = DurableScheduler(g2, wal_dir=wal_dir, fsync="tick")
    recover(sched2, wal_dir)
    resume_from_cursor(sched2, src2, feed)
    assert dict(sched2.view(sink2.name)) == want


@pytest.mark.parametrize("seed", range(4))
def test_checkpoint_plus_tail_recovery(tmp_path, seed):
    """Acceptance: after a checkpoint, sealed segments are dropped, and
    recovery from (checkpoint + remaining tail) still equals the clean
    run — with replayed pre-checkpoint pushes deduped, not re-folded."""
    feed = make_feed(200 + seed, n_ticks=12)
    want = clean_run(feed)
    rng = np.random.default_rng(300 + seed)
    wal_dir = str(tmp_path / "wal")
    ckpt_dir = str(tmp_path / "ckpt")

    g, src, sink = wordcount.build_graph()
    sched = DurableScheduler(g, wal_dir=wal_dir, fsync="tick",
                             segment_bytes=512)
    ckpt_at = int(rng.integers(3, 9))
    for t, batches in enumerate(feed):
        for bid, b in batches:
            sched.push(src, b, batch_id=bid)
        sched.tick()
        if t == ckpt_at:
            save_checkpoint(sched, ckpt_dir)
            # sealed pre-checkpoint segments are gone; the live segment
            # (and any later ones) remain
            import pickle
            with open(os.path.join(ckpt_dir, "meta.pkl"), "rb") as f:
                wal_pos = pickle.load(f)["wal_pos"]
            assert all(s >= wal_pos[0]
                       for s, _p in list_segments(wal_dir))
        if t == ckpt_at + 2:
            break  # simulated kill two ticks after the save
    sched.wal.drain()
    if rng.random() < 0.5:
        tear_wal_tail(wal_dir, int(rng.integers(1, 16)))

    g2, src2, sink2 = wordcount.build_graph()
    sched2 = DurableScheduler(g2, wal_dir=wal_dir, fsync="tick")
    report = recover(sched2, wal_dir, ckpt_dir)
    assert report.checkpoint_loaded and report.checkpoint_tick == ckpt_at + 1
    resume_from_cursor(sched2, src2, feed)
    assert dict(sched2.view(sink2.name)) == want, report.as_dict()


def test_recovery_without_resume_matches_prefix(tmp_path):
    """Recovery alone (no upstream re-send) reproduces every COMMITTED
    tick's view: the log is authoritative for accepted input."""
    feed = make_feed(7)
    wal_dir = str(tmp_path / "wal")
    g, src, sink = wordcount.build_graph()
    sched = DurableScheduler(g, wal_dir=wal_dir, fsync="record")
    drive(sched, src, feed)
    want = dict(sched.view(sink.name))

    g2, src2, sink2 = wordcount.build_graph()
    sched2 = DirtyScheduler(g2)  # recovery also works on a plain scheduler
    report = recover(sched2, wal_dir)
    assert report.replayed_pushes > 0 and report.replayed_ticks == len(feed)
    assert dict(sched2.view(sink2.name)) == want
    assert sched2._tick == sched._tick


def test_auto_minted_ids_replay_once(tmp_path):
    """Pushes without caller batch ids get durable auto ids: recovery
    folds them exactly once, and a resumed writer mints past them."""
    wal_dir = str(tmp_path / "wal")
    g, src, sink = wordcount.build_graph()
    sched = DurableScheduler(g, wal_dir=wal_dir, fsync="record")
    sched.push(src, wordcount.ingest_lines(["a b"]))
    sched.push(src, wordcount.ingest_lines(["b c"]))
    sched.tick()
    want = dict(sched.view(sink.name))

    g2, src2, sink2 = wordcount.build_graph()
    sched2 = DurableScheduler(g2, wal_dir=wal_dir, fsync="record")
    recover(sched2, wal_dir)
    assert dict(sched2.view(sink2.name)) == want
    # the resumed writer must not mint an id the replayed window holds
    assert sched2.push(src2, wordcount.ingest_lines(["d"]))
    sched2.tick()
    assert dict(sched2.view(sink2.name)) != want


def test_wal_metrics_and_summary(tmp_path):
    feed = make_feed(3, n_ticks=5)
    wal_dir = str(tmp_path / "wal")
    g, src, sink = wordcount.build_graph()
    sched = DurableScheduler(g, wal_dir=wal_dir, fsync="tick")
    drive(sched, src, feed)
    wm = summarize_wal(sched.wal)
    assert wm.fsync_policy == "tick"
    assert wm.appends == sched.wal.appends > len(feed)  # pushes + marks
    assert wm.fsyncs == len(feed)  # one barrier per tick
    assert wm.append_p95_s >= wm.append_p50_s > 0.0

    g2, src2, _ = wordcount.build_graph()
    sched2 = DurableScheduler(g2, wal_dir=wal_dir, fsync="tick")
    report = recover(sched2, wal_dir)
    wm2 = summarize_wal(sched2.wal, recovery=report)
    assert wm2.replayed_pushes == report.replayed_pushes > 0
    assert wm2.replayed_ticks == len(feed)


def test_wal_inspect_tool(tmp_path):
    feed = make_feed(5, n_ticks=4)
    wal_dir = str(tmp_path / "wal")
    g, src, _sink = wordcount.build_graph()
    sched = DurableScheduler(g, wal_dir=wal_dir, fsync="os")
    drive(sched, src, feed)
    sched.close()
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "wal_inspect.py"),
         wal_dir, "--json"], capture_output=True, text=True, env=env)
    assert out.returncode == 0, out.stderr
    summary = json.loads(out.stdout)
    assert summary["record_kinds"]["tick"] == len(feed)
    assert summary["record_kinds"]["push"] == sum(len(t) for t in feed)
    assert summary["torn_tail"] is None

    tear_wal_tail(wal_dir, 4)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "wal_inspect.py"),
         wal_dir, "--json", "--verify"],
        capture_output=True, text=True, env=env)
    assert out.returncode == 0, out.stderr  # torn tail is NOT corruption
    assert json.loads(out.stdout)["torn_tail"] is not None


# -- satellite: faults raise loudly even under python -O -------------------

def test_flush_raises_on_rejected_first_delivery():
    g, src, _sink = wordcount.build_graph()
    sched = DirtyScheduler(g)
    sched.push(src, wordcount.ingest_lines(["a"]), batch_id="b0")
    chan = FaultyChannel(sched, src, drop_p=0.0, dup_p=0.0, seed=1)
    # the transport still holds b0 (never delivered by IT), but the
    # scheduler's window already claims the id: flush must fail loudly
    chan._unacked.append(("b0", wordcount.ingest_lines(["a"])))
    with pytest.raises(DeliveryError):
        chan.flush()


def test_pump_raises_when_duplicate_accepted():
    g, src, _sink = wordcount.build_graph()
    sched = DirtyScheduler(g)
    sched.push = lambda *a, **k: True  # a scheduler that lost its dedup
    chan = FaultyChannel(sched, src, drop_p=0.0, dup_p=1.0, seed=0)
    with pytest.raises(DeliveryError):
        # dup_p=1: the pump retransmits b0 right after delivering it;
        # the dedup-less scheduler accepts the duplicate -> loud error
        chan.send(wordcount.ingest_lines(["a"]), "b0")


# -- satellite: empty-history summary stays field-aligned ------------------

def test_empty_history_summary_keyword_constructed():
    s = summarize([])
    assert s.ticks == 0 and s.delta_ops == 0
    assert s.quiesced_all is True and s.forced_syncs == 0


# -- group commit (fsync="record") ------------------------------------------

def test_append_group_one_fsync_covers_the_group(tmp_path):
    wal = WriteAheadLog(str(tmp_path), fsync="record")
    fsyncs0 = wal.fsyncs
    poss = wal.append_group([{"kind": "tick", "tick": i} for i in range(5)])
    assert len(poss) == 5
    assert wal.fsyncs == fsyncs0 + 1
    assert wal.group_sizes[-1] == 5
    wal.close()
    records, torn = scan_wal(str(tmp_path))
    assert torn is None
    assert [r["tick"] for _p, r in records] == list(range(5))


def test_individual_appends_record_group_size_one(tmp_path):
    wal = WriteAheadLog(str(tmp_path), fsync="record")
    for i in range(3):
        wal.append({"kind": "tick", "tick": i})
    wal.close()
    assert list(wal.group_sizes) == [1, 1, 1]
    assert wal.fsyncs >= 3


def test_group_commit_survives_rotation(tmp_path):
    # a group large enough to rotate mid-group must still land every
    # record durably and scan back in order
    wal = WriteAheadLog(str(tmp_path), fsync="record", segment_bytes=256)
    wal.append_group([{"kind": "tick", "tick": i} for i in range(64)])
    wal.close()
    assert len(list_segments(str(tmp_path))) > 1
    records, torn = scan_wal(str(tmp_path))
    assert torn is None
    assert [r["tick"] for _p, r in records] == list(range(64))


def test_append_group_rotation_mid_window_atomic_replay(tmp_path):
    """A coalesced macro-tick whose ``append_group`` starts in one
    segment and rotates mid-window: the sealed segment must be fsynced
    AT the rotation (even under the lazy ``"tick"`` policy — the crash
    here dies before any tick marker, so rotation is the only barrier),
    and every ``batch_ids`` replay unit must stay all-or-nothing across
    the segment boundary."""
    wal_dir = str(tmp_path / "wal")
    g, src, sink = wordcount.build_graph()
    crash = CrashInjector(at=1, only="after_append")
    sched = DurableScheduler(g, wal_dir=wal_dir, fsync="tick",
                             segment_bytes=1024, crash=crash)
    feeds, feed_ids = [], []
    for t in range(8):
        lines = [" ".join(f"w{(t * 7 + k) % 13}" for k in range(40))]
        feeds.append({src: wordcount.ingest_lines(lines)})
        feed_ids.append({src: [f"t{t}a", f"t{t}b"]})
    with pytest.raises(CrashPoint):
        sched.tick_many(feeds, feed_ids=feed_ids)
    sched.wal.drain()  # the enqueued window + its rotations hit disk
    segs = list_segments(wal_dir)
    assert len(segs) > 1, "window did not span a rotation; shrink segments"
    # the "tick" policy alone would have fsynced NOTHING yet (no tick
    # mark was reached): every fsync on the books is a rotation sealing
    # a full segment
    assert sched.wal.fsyncs == len(segs) - 1
    records, torn = scan_wal(wal_dir)
    assert torn is None and len(records) == 8

    g2, src2, sink2 = wordcount.build_graph()
    fresh = DurableScheduler(g2, wal_dir=wal_dir, fsync="tick")
    report = recover(fresh, wal_dir)
    # the crash died before execution, so no tick marker landed: the
    # replayed units sit as pending backlog until the next tick
    fresh.tick()
    fresh.close()
    assert report.replayed_pushes == 8
    g3, src3, sink3 = wordcount.build_graph()
    want = DirtyScheduler(g3)
    for feed in feeds:
        for _src, batch in feed.items():
            want.push(src3, batch)
        want.tick()
    assert dict(fresh.view(sink2.name)) == dict(want.view(sink3.name))

    # all-or-nothing across the boundary: pre-seeding ONE id of a
    # mid-log unit dedups that whole unit and only it
    g4, src4, sink4 = wordcount.build_graph()
    again = DurableScheduler(g4, wal_dir=wal_dir, fsync="tick")
    again._register_batch_id("t4a")
    report2 = recover(again, wal_dir)
    again.tick()
    again.close()
    assert report2.replayed_pushes == 7
    assert report2.deduped_pushes == 1


def test_empty_group_is_a_noop(tmp_path):
    wal = WriteAheadLog(str(tmp_path), fsync="record")
    fsyncs0 = wal.fsyncs
    assert wal.append_group([]) == []
    assert wal.fsyncs == fsyncs0 and wal.appends == 0
    wal.close()


def test_wal_metrics_report_group_shape(tmp_path):
    wal = WriteAheadLog(str(tmp_path), fsync="record")
    wal.append({"kind": "tick", "tick": 0})
    wal.append_group([{"kind": "tick", "tick": i} for i in range(1, 5)])
    wal.close()
    wm = summarize_wal(wal)
    assert wm.group_commits == len(wal.group_sizes)
    assert wm.group_max == 4.0
    assert wm.as_dict()["group_p50"] >= 1.0


def test_coalesced_batch_ids_replay_all_or_nothing(tmp_path):
    """A frontend-coalesced push record carries the merged micro-batch
    ids; its macro-tick committed them atomically, so replay must fold
    the merged batch once if NO id is known, and never if ANY is."""
    g, src, sink = wordcount.build_graph()
    sched = DurableScheduler(g, wal_dir=str(tmp_path / "wal"))
    sched.tick_many(
        [{src: wordcount.ingest_lines(["a b"])},
         {src: wordcount.ingest_lines(["b c"])}],
        feed_ids=[{src: ["m0", "m1"]}, {src: ["m2"]}])
    want = dict(sched.view(sink.name))
    sched.close()

    g2, src2, sink2 = wordcount.build_graph()
    fresh = DurableScheduler(g2, wal_dir=str(tmp_path / "wal"))
    report = recover(fresh, str(tmp_path / "wal"))
    fresh.close()
    assert dict(fresh.view(sink2.name)) == want
    assert report.replayed_pushes == 2
    # all three micro-ids are back in the dedup window after replay
    for bid in ("m0", "m1", "m2"):
        assert bid in fresh._seen_batch_ids

    g3, src3, sink3 = wordcount.build_graph()
    again = DurableScheduler(g3, wal_dir=str(tmp_path / "wal"))
    # pre-seed ONE of the merged ids: the whole record must dedup
    again._register_batch_id("m1")
    report2 = recover(again, str(tmp_path / "wal"))
    again.close()
    assert report2.deduped_pushes >= 1


# -- asynchronous committer pipeline ---------------------------------------

PIPELINE_SEAMS = ["wal_enqueue", "wal_before_write", "wal_after_write",
                  "wal_before_fsync", "wal_after_fsync"]


@pytest.mark.parametrize("seam", PIPELINE_SEAMS)
def test_committer_seam_crash_replays_exactly_once(tmp_path, seam):
    """Kill the durability pipeline at each of its own seams — frame
    enqueued but not written, written but not fsynced, fsynced but the
    acknowledgement path dead — then recover and resume from the
    upstream cursor: the sink view matches the clean run, nothing folds
    twice. ``wal_enqueue`` dies on the appending thread; the other four
    kill the committer itself, and the death must surface as the
    original CrashPoint from the next append/wait."""
    import contextlib

    feed = make_feed(7)
    want = clean_run(feed)
    wal_dir = str(tmp_path / seam)
    g, src, sink = wordcount.build_graph()
    crash = CrashInjector(3, only=seam)
    sched = DurableScheduler(g, wal_dir=wal_dir, fsync="record",
                             crash=crash)
    with pytest.raises(CrashPoint):
        drive(sched, src, feed)
    assert crash.fired
    with contextlib.suppress(CrashPoint):
        # settle surviving writes; a dead committer re-raises its cause
        sched.wal.drain()

    g2, src2, sink2 = wordcount.build_graph()
    sched2 = DurableScheduler(g2, wal_dir=wal_dir, fsync="record")
    recover(sched2, wal_dir)
    resume_from_cursor(sched2, src2, feed)
    assert dict(sched2.view(sink2.name)) == want


def test_committer_death_fails_waiters_and_callbacks(tmp_path):
    """A committer that dies before the fsync must (a) fail every
    registered ``when_durable`` continuation with its cause — no ticket
    may hang unresolved — and (b) re-raise that cause from later
    ``wait_durable``/``append`` calls instead of accepting writes it
    can never commit."""
    import threading

    crash = CrashInjector(1, only="wal_before_fsync")
    wal = WriteAheadLog(str(tmp_path), fsync="record", crash=crash)
    b = wordcount.ingest_lines(["a b"])
    rec = {"kind": "push", "tick": 0, "node": 0, "node_name": "w",
           "batch_id": "b0", "keys": b.keys, "values": b.values,
           "weights": b.weights}
    got = []
    fired = threading.Event()

    wal.append(rec, wait=False)
    lsn = wal.last_lsn()
    try:
        pending = wal.when_durable(
            lsn, lambda err: (got.append(err), fired.set()))
    except CrashPoint:
        pending = False  # death already visible at registration time
    if pending:
        assert fired.wait(timeout=10.0), "continuation never resolved"
        assert isinstance(got[0], CrashPoint)
    with pytest.raises(CrashPoint):
        wal.wait_durable(lsn)
    with pytest.raises(CrashPoint):
        wal.append(rec, wait=False)


def test_drain_is_write_barrier_not_fsync_barrier(tmp_path):
    """``drain()`` settles every enqueued frame into the segment file
    (the scan sees them) without spending an fsync or moving the
    durability watermark — the page-cache state a process death at that
    instant would leave behind."""
    wal = WriteAheadLog(str(tmp_path), fsync="tick")
    b = wordcount.ingest_lines(["a b a"])
    for j in range(3):
        wal.append({"kind": "push", "tick": 0, "node": 0,
                    "node_name": "w", "batch_id": f"b{j}",
                    "keys": b.keys, "values": b.values,
                    "weights": b.weights}, wait=False)
    fsyncs0 = wal.fsyncs
    wal.drain()
    assert wal.queue_depth() == 0
    records, torn = scan_wal(str(tmp_path))
    assert torn is None and len(records) == 3
    assert wal.fsyncs == fsyncs0          # no fsync spent
    assert wal.durable_lsn() < wal.last_lsn()  # ...so not durable yet
    wal.note_tick()
    wal.wait_durable(wal.last_lsn())
    assert wal.durable_lsn() == wal.last_lsn()
    wal.close()


def test_when_durable_fires_in_lsn_order(tmp_path):
    """Continuations fire in LSN order once the watermark passes them,
    each with ``None`` (success); already-durable LSNs report False so
    the caller resolves inline."""
    wal = WriteAheadLog(str(tmp_path), fsync="tick")
    b = wordcount.ingest_lines(["x"])
    lsns = []
    for j in range(4):
        wal.append({"kind": "push", "tick": 0, "node": 0,
                    "node_name": "w", "batch_id": f"b{j}",
                    "keys": b.keys, "values": b.values,
                    "weights": b.weights}, wait=False)
        lsns.append(wal.last_lsn())
    fired = []
    for lsn in lsns:
        assert wal.when_durable(lsn, lambda err, lsn=lsn:
                                fired.append((lsn, err)))
    wal.note_tick()
    wal.wait_durable(lsns[-1])
    assert fired == [(lsn, None) for lsn in lsns]
    # the watermark already covers them now: registration declines
    assert wal.when_durable(lsns[-1], lambda err: None) is False
    wal.close()


def test_idle_tick_and_seal_skip_fsync(tmp_path):
    """An idle tick boundary (nothing appended since the last barrier)
    and an already-durable seal must not pay a no-op fsync."""
    wal = WriteAheadLog(str(tmp_path), fsync="tick")
    b = wordcount.ingest_lines(["a b"])
    wal.append({"kind": "push", "tick": 0, "node": 0, "node_name": "w",
                "batch_id": "b0", "keys": b.keys, "values": b.values,
                "weights": b.weights}, wait=False)
    wal.note_tick()
    n = wal.fsyncs
    wal.note_tick()  # idle: watermark already covers every append
    wal.note_tick()
    assert wal.fsyncs == n
    wal.close()      # seal with no new bytes: no extra fsync either
    assert wal.fsyncs == n
