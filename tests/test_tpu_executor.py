"""Executor-differential tests (SURVEY.md §4c): CpuExecutor vs TpuExecutor
on identical graphs and delta sequences. Runs on the CPU JAX platform."""

from collections import Counter

import jax.numpy as jnp
import numpy as np
import pytest

from reflow_tpu import DirtyScheduler, FlowGraph
from reflow_tpu.delta import DeltaBatch, Spec
from reflow_tpu.executors import get_executor
from reflow_tpu.graph import GraphError
from reflow_tpu.workloads import wordcount

K = 32


def int_batch(rows):
    """rows: (int_key, float_value, weight)."""
    return DeltaBatch(
        np.array([r[0] for r in rows], dtype=np.int64),
        np.array([r[1] for r in rows], dtype=np.float32),
        np.array([r[2] for r in rows], dtype=np.int64),
    )


def view_of(sched, sink):
    return {k: round(float(v), 4) for k, v in sched.view_dict(sink).items()}


def both_executors(build, ticks):
    """Run the same graph + delta sequence on cpu and tpu executors."""
    views = []
    for ex in ("cpu", "tpu"):
        g, srcs, sink = build()
        sched = DirtyScheduler(g, get_executor(ex))
        for tick in ticks:
            for src_name, batch in tick:
                src = next(s for s in g.sources if s.name == src_name)
                sched.push(src, batch)
            sched.tick()
        views.append(view_of(sched, sink))
    return views


def build_sum_graph():
    spec = Spec((), np.float32, key_space=K)
    g = FlowGraph()
    src = g.source("in", spec)
    doubled = g.map(src, lambda v: v * 2.0, vectorized=True)
    total = g.reduce(doubled, "sum", name="sum")
    sink = g.sink(total, "out")
    return g, [src], sink


def test_map_reduce_sum_differential():
    ticks = [
        [("in", int_batch([(1, 1.0, 1), (1, 2.0, 1), (5, 3.0, 1)]))],
        [("in", int_batch([(1, 1.0, -1), (7, 4.0, 2)]))],
        [("in", int_batch([(5, 3.0, -1)]))],  # group 5 vanishes
    ]
    cpu, tpu = both_executors(build_sum_graph, ticks)
    assert cpu == tpu == {1: 4.0, 7: 16.0}


def test_filter_groupby_differential():
    def build():
        spec = Spec((), np.float32, key_space=K)
        g = FlowGraph()
        src = g.source("in", spec)
        big = g.filter(src, lambda v: v > 1.5, vectorized=True)
        rekey = g.group_by(big, lambda k, v: (k + 1) % K, vectorized=True)
        total = g.reduce(rekey, "sum", name="sum")
        sink = g.sink(total, "out")
        return g, [src], sink

    ticks = [
        [("in", int_batch([(0, 1.0, 1), (0, 2.0, 1), (3, 9.0, 1)]))],
        [("in", int_batch([(3, 9.0, -1), (3, 5.0, 1)]))],
    ]
    cpu, tpu = both_executors(build, ticks)
    assert cpu == tpu == {1: 2.0, 4: 5.0}


def test_reduce_count_and_mean_differential():
    for how, expect in (("count", {2: 3.0}), ("mean", {2: 2.0})):
        def build(how=how):
            spec = Spec((), np.float32, key_space=K)
            g = FlowGraph()
            src = g.source("in", spec)
            agg = g.reduce(src, how, name="agg")
            sink = g.sink(agg, "out")
            return g, [src], sink

        ticks = [
            [("in", int_batch([(2, 1.0, 1), (2, 2.0, 1)]))],
            [("in", int_batch([(2, 3.0, 1)]))],
        ]
        cpu, tpu = both_executors(build, ticks)
        assert cpu == tpu == expect, how


def test_join_differential_pagerank_shape():
    """Unique-keyed table (left) ⋈ growing arena (right), merge = product."""
    def build():
        vspec = Spec((), np.float32, key_space=K, unique=True)
        g = FlowGraph()
        vals = g.source("vals", vspec)     # unique per key (like ranks)
        edges = g.source("edges", Spec((), np.float32, key_space=K))
        tot = g.reduce(vals, "sum", name="uniq")   # makes left unique-keyed
        j = g.join(tot, edges, merge=lambda k, va, vb: va * vb,
                   spec=Spec((), np.float32, key_space=K), arena_capacity=256)
        out = g.reduce(j, "sum", name="joined")
        sink = g.sink(out, "out")
        return g, [vals, edges], sink

    ticks = [
        [("vals", int_batch([(1, 10.0, 1), (2, 20.0, 1)])),
         ("edges", int_batch([(1, 0.5, 1), (1, 0.25, 1), (2, 1.0, 1)]))],
        # change a left value: 10 -> 11 (retract+insert via source)
        [("vals", int_batch([(1, 10.0, -1), (1, 11.0, 1)]))],
        # add and retract edges
        [("edges", int_batch([(2, 2.0, 1), (1, 0.5, -1)]))],
    ]
    cpu, tpu = both_executors(build, ticks)
    # key1: 11*0.25 = 2.75 ; key2: 20*1 + 20*2 = 60
    assert cpu == tpu == {1: 2.75, 2: 60.0}


def test_wordcount_differential():
    texts = [["the quick brown fox", "the lazy dog"],
             ["quick quick dog"],
             []]
    vocab_cpu: dict = {}
    vocab_tpu: dict = {}
    views = []
    for ex, vocab in (("cpu", vocab_cpu), ("tpu", vocab_tpu)):
        g, src, sink = wordcount.build_graph(key_space=64)
        sched = DirtyScheduler(g, get_executor(ex))
        for lines in texts:
            batch = wordcount.ingest_lines(lines, vocab=vocab)
            if len(batch):
                sched.push(src, batch)
            sched.tick()
        views.append(view_of(sched, sink))
    assert vocab_cpu == vocab_tpu
    assert views[0] == views[1]
    assert views[0][vocab_cpu["quick"]] == 3.0


def test_tpu_rejects_unkeyed_spec():
    g = FlowGraph()
    src = g.source("in", Spec())  # key_space 0
    g.sink(g.reduce(src, "sum"), "out")
    with pytest.raises(GraphError, match="key_space"):
        DirtyScheduler(g, get_executor("tpu"))


def test_tpu_accepts_minmax_reducer():
    # min/max lower to the buffered candidate kernel (see tests/test_aux.py
    # for retraction exactness and the error-flag behavior)
    g = FlowGraph()
    src = g.source("in", Spec((), np.float32, key_space=8))
    g.sink(g.reduce(src, "min"), "out")
    sched = DirtyScheduler(g, get_executor("tpu"))
    sched.push(src, DeltaBatch(np.array([1, 1, 2]),
                               np.array([3.0, 1.0, 2.0], np.float32)))
    sched.tick()
    assert sched.view_dict("out") == {1: 1.0, 2: 2.0}


def test_tpu_join_nonunique_left_takes_multiset_path():
    """Round 5: a non-unique left is no longer a bind error — it lowers
    to the two-arena multiset path (state carries the left arena, not a
    dense left table). Semantics covered by tests/test_multiset_join.py
    and the fuzz grammar."""
    spec = Spec((), np.float32, key_space=8)
    g = FlowGraph()
    a = g.source("a", spec)
    b = g.source("b", spec)
    j = g.join(a, b, merge=lambda k, x, y: x + y, spec=spec,
               arena_capacity=256)
    g.sink(j, "out")
    ex = get_executor("tpu")
    DirtyScheduler(g, ex)
    assert "lkeys" in ex.states[j.id]          # multiset-left arena
    assert "lval" not in ex.states[j.id]       # no dense unique table


def test_groupby_clears_unique_flag():
    """Regression: re-keying can collapse keys, so a GroupBy output must
    lose Spec.unique — the device Join then takes the multiset-left
    path (it would silently under-join on the dense unique table)."""
    spec = Spec((), np.float32, key_space=8)
    g = FlowGraph()
    a = g.source("a", spec)
    b = g.source("b", spec)
    u = g.reduce(a, "sum")          # unique=True here
    grouped = g.group_by(u, lambda k, v: k // 2, vectorized=True)
    assert not grouped.spec.unique
    j = g.join(grouped, b, merge=lambda k, x, y: x + y, spec=spec,
               arena_capacity=256)
    g.sink(j, "out")
    ex = get_executor("tpu")
    DirtyScheduler(g, ex)
    assert "lkeys" in ex.states[j.id]


def test_rebind_clears_compiled_cache():
    """Regression: rebinding the same executor to a different graph must not
    replay pass programs compiled for the old graph."""
    ex = get_executor("tpu")
    g1, _, _ = build_sum_graph()
    s1 = DirtyScheduler(g1, ex)
    src1 = g1.sources[0]
    s1.push(src1, int_batch([(1, 1.0, 1)]))
    s1.tick()
    assert len(ex._cache) == 1

    def build_negated():
        spec = Spec((), np.float32, key_space=K)
        g = FlowGraph()
        src = g.source("in", spec)
        neg = g.map(src, lambda v: -v, vectorized=True)
        total = g.reduce(neg, "sum", name="sum")
        sink = g.sink(total, "out")
        return g, [src], sink

    g2, (src2,), sink2 = build_negated()
    s2 = DirtyScheduler(g2, ex)  # rebind same executor instance
    s2.push(src2, int_batch([(1, 1.0, 1)]))
    s2.tick()
    assert view_of(s2, sink2) == {1: -1.0}  # not the old graph's v*2


def test_full_retraction_leaves_no_phantom_group():
    """Regression: float scatter-add residue must not resurrect a fully
    retracted group when tol > 0 (device) — host is exact."""
    def build():
        spec = Spec((), np.float32, key_space=8)
        g = FlowGraph()
        src = g.source("in", spec)
        agg = g.reduce(src, "sum", tol=1e-5)
        sink = g.sink(agg, "out")
        return g, [src], sink

    ticks = [
        [("in", int_batch([(3, 0.1, 1), (3, 0.2, 1)]))],
        [("in", int_batch([(3, 0.1, -1), (3, 0.2, -1)]))],
    ]
    cpu, tpu = both_executors(build, ticks)
    assert cpu == tpu == {}


def test_tpu_reduce_tol_quiesces():
    spec = Spec((), np.float32, key_space=8)
    g = FlowGraph()
    src = g.source("in", spec)
    agg = g.reduce(src, "sum", tol=1e-3)
    sink = g.sink(agg, "out")
    sched = DirtyScheduler(g, get_executor("tpu"))
    sched.push(src, int_batch([(1, 1.0, 1)]))
    r1 = sched.tick()
    assert len(r1.sink_deltas["out"]) == 1
    sched.push(src, int_batch([(1, 1e-6, 1)]))
    r2 = sched.tick()
    assert r2.sink_deltas == {} or len(r2.sink_deltas.get("out", [])) == 0


def test_streaming_deferred_error_surfaces_at_block():
    """ADVICE r2: a sinkless streaming run must surface sticky error flags
    at ``block()`` (the documented streaming sync point), not never."""
    g = FlowGraph()
    src = g.source("in", Spec((), np.float32, key_space=8))
    # candidates=1: one eviction + retracting the buffered best exhausts
    # the bounded exactness window -> sticky flag
    g.reduce(src, "min", name="lo", candidates=1)  # no sink: check defers
    sched = DirtyScheduler(g, get_executor("tpu"))
    sched.push(src, DeltaBatch(np.array([1, 1]),
                               np.array([3.0, 5.0], np.float32)))
    sched.tick(sync=False).block()  # inserts: clean (5.0 evicted to over)
    sched.push(src, DeltaBatch(np.array([1]), np.array([3.0], np.float32),
                               np.array([-1])))
    res = sched.tick(sync=False)    # buffer hollowed -> sticky, deferred
    with pytest.raises(RuntimeError, match="min/max"):
        res.block()


def test_union_differential():
    """Two sources merged by a Union feeding a Reduce — device weight
    semantics across inserts and retractions must match the oracle."""
    def build():
        spec = Spec((), np.float32, key_space=K)
        g = FlowGraph()
        a = g.source("a", spec)
        b = g.source("b", spec)
        u = g.union(a, b, name="u")
        total = g.reduce(u, "sum", name="sum")
        sink = g.sink(total, "out")
        return g, [a, b], sink

    ticks = [
        [("a", int_batch([(1, 2.0, 1), (2, 3.0, 1)])),
         ("b", int_batch([(1, 5.0, 1)]))],
        [("b", int_batch([(2, 7.0, 1), (1, 5.0, -1)]))],
        [("a", int_batch([(1, 2.0, -1)]))],
    ]
    cpu, tpu = both_executors(build, ticks)
    assert cpu == tpu
    # key 1 fully retracted across both sources; key 2 = 3.0 + 7.0
    assert cpu == {2: 10.0}


def test_deep_chain_multi_tick_differential():
    """map -> filter -> groupby -> reduce chained into a join against a
    second reduced stream, driven by random inserts AND retractions over
    many ticks — the widest single differential surface in the suite."""
    rng = np.random.default_rng(42)

    def build():
        spec = Spec((), np.float32, key_space=K)
        uniq = Spec((), np.float32, key_space=K, unique=True)
        g = FlowGraph()
        a = g.source("a", spec)
        b = g.source("b", spec)
        scaled = g.map(a, lambda v: v * 0.5, vectorized=True)
        pos = g.filter(scaled, lambda v: v > 0.1, vectorized=True)
        regrouped = g.group_by(pos, key_fn=lambda k, v: (k * 7) % K,
                               vectorized=True)
        left = g.reduce(regrouped, "sum", name="lsum", spec=uniq)
        j = g.join(left, b, merge=lambda k, va, vb: va * vb, spec=spec,
                   arena_capacity=1 << 10, name="j")
        out = g.reduce(j, "sum", name="osum", tol=1e-6)
        sink = g.sink(out, "out")
        return g, [a, b], sink

    history = []
    ticks = []
    for _ in range(6):
        tick = []
        n = int(rng.integers(2, 8))
        rows = [(int(rng.integers(0, K)),
                 float(np.float32(rng.normal())), 1) for _ in range(n)]
        history.extend(rows)
        if history and rng.random() < 0.7:
            k0, v0, _ = history[int(rng.integers(0, len(history)))]
            rows.append((k0, v0, -1))
        tick.append(("a", int_batch(rows)))
        m = int(rng.integers(1, 4))
        tick.append(("b", int_batch([(int(rng.integers(0, K)),
                                  float(np.float32(rng.normal())), 1)
                                 for _ in range(m)])))
        ticks.append(tick)

    cpu, tpu = both_executors(build, ticks)
    assert set(cpu) == set(tpu)
    for k in cpu:
        assert abs(cpu[k] - tpu[k]) < 1e-3, (k, cpu[k], tpu[k])
