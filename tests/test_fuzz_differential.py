"""Randomized structured differential fuzzing (SURVEY.md §4b+§4c).

Seeded random graphs composed from the device-lowerable op grammar —
Map / Filter / GroupBy / Reduce(sum|count|mean) / Join(unique left) /
Union — driven with random multi-tick delta sequences that retract
exactly previously-inserted rows, and executed on all four executors:
cpu (oracle), tpu, sharded (8-device virtual mesh), staged. All sink
multisets must agree.

Constraints baked into the generator (the same ones the executors
enforce at bind): scalar f32 values, key_space divisible by the mesh,
Join left side a Reduce output (unique) with a vectorized merge,
arena capacities mesh-divisible, min/max with a candidate buffer wide
enough for the generated churn (scalar min/max retraction is exact
within the buffer; exhaustion would raise loudly, not mis-answer),
loop-free (fixpoint differentials live in test_pagerank/test_fixpoint),
integer-valued floats so sum/count stay exact and only mean introduces
rounding (compared at 3 decimals).
"""

from collections import Counter

import numpy as np
import pytest

from reflow_tpu import DirtyScheduler, FlowGraph
from reflow_tpu.delta import DeltaBatch, Spec
from reflow_tpu.executors import get_executor
from reflow_tpu.parallel import make_mesh
from reflow_tpu.parallel.shard import ShardedTpuExecutor
from reflow_tpu.parallel.topo import StagedTpuExecutor

K = 64          # key space: divisible by the 8-device mesh
N_TICKS = 4
ROWS_PER_TICK = 24


def build_random_graph(rng: np.random.Generator):
    """-> (graph, sources, sink). Construction order is topo order, so
    stage assignment by node id is automatically stage-monotone."""
    spec = Spec((), np.float32, key_space=K)
    g = FlowGraph("fuzz")
    sources = [g.source(f"s{i}", spec) for i in range(rng.integers(1, 3))]
    streams = list(sources)     # non-unique delta streams
    uniques = []                # Reduce outputs (unique-keyed)

    n_ops = int(rng.integers(4, 9))
    for ix in range(n_ops):
        kind = rng.choice(["map", "filter", "groupby", "reduce", "union",
                           "join"])
        if kind == "map":
            a, b = int(rng.integers(1, 4)), int(rng.integers(0, 5))
            node = g.map(rng.choice(streams),
                         lambda v, a=a, b=b: v * np.float32(a) + np.float32(b),
                         vectorized=True)
            streams.append(node)
        elif kind == "filter":
            c = float(rng.integers(0, 6))
            node = g.filter(rng.choice(streams),
                            lambda v, c=c: v > c, vectorized=True)
            streams.append(node)
        elif kind == "groupby":
            m, s = int(rng.integers(1, 5)), int(rng.integers(0, K))
            node = g.group_by(
                rng.choice(streams),
                key_fn=lambda k, v, m=m, s=s: (k * m + s) % K,
                vectorized=True)
            streams.append(node)
        elif kind == "reduce":
            # min/max ride the retraction-capable candidate buffer;
            # candidates=32 comfortably covers this generator's per-key
            # churn (a seed that exhausted it would raise, not mis-answer)
            how = rng.choice(["sum", "count", "mean", "min", "max"])
            node = g.reduce(rng.choice(streams), how,
                            tol=1e-6 if how in ("sum", "mean") else 0.0,
                            candidates=32)
            uniques.append(node)
            streams.append(node)   # emissions are themselves a stream
        elif kind == "union":
            a, b = rng.choice(streams), rng.choice(streams)
            streams.append(g.union(a, b))
        elif kind == "join":
            if uniques and rng.random() < 0.6:
                left = rng.choice(uniques)
                right = rng.choice(streams)
                w = int(rng.integers(1, 3))
                node = g.join(
                    left, right,
                    merge=lambda k, va, vb, w=w: va + np.float32(w) * vb,
                    arena_capacity=1 << 12)
                streams.append(node)
            else:
                # MULTISET-left join with the DEFAULT merge (VERDICT r4
                # #5): both sides are plain delta streams; the device
                # path runs the two-arena pair-enumeration kernel, the
                # default merge emits the flattened (va, vb) pair. A
                # projection Map + Reduce fold the pair stream back to a
                # compact unique stream — observing every product row in
                # the sums while keeping the (deliberately conservative)
                # static egress-capacity estimate of the pair stream out
                # of downstream Join arena checks.
                left = rng.choice(streams)
                right = rng.choice(streams)
                pair = g.join(
                    left, right,
                    spec=Spec((2,), np.float32, key_space=K),
                    arena_capacity=1 << 12, product_slack=16)
                proj = g.map(pair, lambda v: v[:, 0] + np.float32(2) * v[:, 1],
                             vectorized=True,
                             spec=Spec((), np.float32, key_space=K))
                node = g.reduce(proj, "sum", tol=1e-6)
                uniques.append(node)
                streams.append(node)
    sink = g.sink(streams[-1], "out")

    # stage assignment for the staged executor: two contiguous stages
    # split at the median op id (ids are topo order -> monotone edges)
    op_ids = [n.id for n in g.nodes if n.kind == "op"]
    if op_ids:
        cut = op_ids[len(op_ids) // 2]
        for n in g.nodes:
            if n.kind == "op":
                n.stage = 0 if n.id <= cut else 1
    return g, sources, sink


def random_ticks(rng: np.random.Generator, n_sources: int):
    """Delta sequence: inserts plus exact retractions of earlier rows."""
    ticks = []
    log = [[] for _ in range(n_sources)]   # per-source inserted rows
    for _ in range(N_TICKS):
        tick = []
        for s in range(n_sources):
            rows = []
            for _ in range(ROWS_PER_TICK):
                if log[s] and rng.random() < 0.3:
                    # pop: each inserted row is retracted at most once,
                    # so source collections never go net-negative
                    k, v, w = log[s].pop(int(rng.integers(0, len(log[s]))))
                    rows.append((k, v, -w))   # exact retraction
                else:
                    row = (int(rng.integers(0, K)),
                           float(rng.integers(0, 8)),
                           int(rng.integers(1, 3)))
                    rows.append(row)
                    log[s].append(row)
            tick.append((s, rows))
        ticks.append(tick)
    return ticks


def run_on(executor, g, sources, sink, ticks):
    sched = DirtyScheduler(g, executor)
    for tick in ticks:
        for s_ix, rows in tick:
            sched.push(sources[s_ix], DeltaBatch(
                np.array([r[0] for r in rows], np.int64),
                np.array([r[1] for r in rows], np.float32),
                np.array([r[2] for r in rows], np.int64)))
        sched.tick()
    return Counter({(int(k), round(float(v), 3)): w
                    for (k, v), w in sched.view(sink).items() if w})


@pytest.mark.parametrize("seed", list(range(8)))
def test_random_graph_all_executors_agree(seed):
    rng = np.random.default_rng(seed)
    graph_seed = rng.integers(0, 1 << 30)
    ticks_seed = rng.integers(0, 1 << 30)

    n_sources = len(build_random_graph(np.random.default_rng(graph_seed))[1])
    ticks = random_ticks(np.random.default_rng(ticks_seed), n_sources)

    views = {}
    for name in ("cpu", "tpu", "sharded", "staged"):
        # fresh graph per executor: schedulers freeze/bind their graph
        g, sources, sink = build_random_graph(np.random.default_rng(graph_seed))
        ex = {
            "cpu": lambda: get_executor("cpu"),
            "tpu": lambda: get_executor("tpu"),
            "sharded": lambda: ShardedTpuExecutor(make_mesh(8)),
            "staged": lambda: StagedTpuExecutor(),
        }[name]()
        views[name] = run_on(ex, g, sources, sink, ticks)

    for name in ("tpu", "sharded", "staged"):
        assert views[name] == views["cpu"], (
            f"seed {seed}: {name} disagrees with cpu oracle:\n"
            f"only-{name}: {views[name] - views['cpu']}\n"
            f"only-cpu: {views['cpu'] - views[name]}")


def run_streaming(executor, g, sources, reduces, ticks):
    """Sink-free drive in streaming mode: push + tick(sync=False) per
    tick, block at the end, read every Reduce table."""
    sched = DirtyScheduler(g, executor)
    results = []
    for tick in ticks:
        for s_ix, rows in tick:
            sched.push(sources[s_ix], DeltaBatch(
                np.array([r[0] for r in rows], np.int64),
                np.array([r[1] for r in rows], np.float32),
                np.array([r[2] for r in rows], np.int64)))
        results.append(sched.tick(sync=False))
    for r in results:
        r.block()
    out = {}
    for ix, node in enumerate(reduces):
        out[ix] = {int(k): round(float(np.asarray(v).reshape(())), 3)
                   for k, v in sched.read_table(node).items()}
    return out


def build_streaming_graph(rng: np.random.Generator):
    """Sink-free random graph (streaming ticks defer ALL readbacks when
    no sink forces materialization); every Reduce output is observable
    via read_table, so the differential compares every aggregate table."""
    spec = Spec((), np.float32, key_space=K)
    g = FlowGraph("fuzz_stream")
    sources = [g.source(f"s{i}", spec) for i in range(rng.integers(1, 3))]
    streams = list(sources)
    reduces = []
    for _ in range(int(rng.integers(3, 7))):
        kind = rng.choice(["map", "groupby", "reduce", "union"])
        if kind == "map":
            a = int(rng.integers(1, 4))
            streams.append(g.map(rng.choice(streams),
                                 lambda v, a=a: v * np.float32(a),
                                 vectorized=True))
        elif kind == "groupby":
            m = int(rng.integers(1, 5))
            streams.append(g.group_by(
                rng.choice(streams),
                key_fn=lambda k, v, m=m: (k * m) % K, vectorized=True))
        elif kind == "reduce":
            node = g.reduce(rng.choice(streams), rng.choice(["sum", "count"]),
                            tol=0.0)
            reduces.append(node)
            streams.append(node)
        else:
            streams.append(g.union(rng.choice(streams), rng.choice(streams)))
    if not reduces:
        reduces.append(g.reduce(streams[-1], "sum"))
    return g, sources, reduces


@pytest.mark.parametrize("seed", [10, 11, 12])
def test_streaming_random_graph_all_executors_agree(seed):
    """The streaming (sync=False) path — the headline benchmark's mode —
    fuzzed across executors on sink-free graphs."""
    rng = np.random.default_rng(seed)
    graph_seed = rng.integers(0, 1 << 30)
    ticks_seed = rng.integers(0, 1 << 30)
    n_sources = len(build_streaming_graph(
        np.random.default_rng(graph_seed))[1])
    ticks = random_ticks(np.random.default_rng(ticks_seed), n_sources)

    views = {}
    for name in ("cpu", "tpu", "sharded"):
        g, sources, reduces = build_streaming_graph(
            np.random.default_rng(graph_seed))
        ex = {
            "cpu": lambda: get_executor("cpu"),
            "tpu": lambda: get_executor("tpu"),
            "sharded": lambda: ShardedTpuExecutor(make_mesh(8)),
        }[name]()
        views[name] = run_streaming(ex, g, sources, reduces, ticks)
    assert views["tpu"] == views["cpu"], f"seed {seed}"
    assert views["sharded"] == views["cpu"], f"seed {seed}"


def build_vector_graph(rng: np.random.Generator):
    """Vector-valued collections: value_shape (3,) through Map / Reduce /
    Join (vector merge), exercising [K, V] state tables."""
    spec = Spec((3,), np.float32, key_space=K)
    g = FlowGraph("fuzz_vec")
    src = g.source("s0", spec)
    a = float(rng.integers(1, 4))
    m = g.map(src, lambda v, a=a: v * np.float32(a), vectorized=True)
    red = g.reduce(m, "sum", tol=1e-6)
    j = g.join(red, src, merge=lambda k, va, vb: va + vb,
               arena_capacity=1 << 12)
    total = g.reduce(j, rng.choice(["sum", "mean"]), tol=1e-6)
    sink = g.sink(total, "out")
    return g, [src], sink


def vector_ticks(rng: np.random.Generator):
    ticks = []
    log = []
    for _ in range(N_TICKS):
        rows = []
        for _ in range(ROWS_PER_TICK):
            if log and rng.random() < 0.3:
                k, v, w = log.pop(int(rng.integers(0, len(log))))
                rows.append((k, v, -w))
            else:
                row = (int(rng.integers(0, K)),
                       tuple(float(x) for x in rng.integers(0, 5, 3)),
                       int(rng.integers(1, 3)))
                rows.append(row)
                log.append(row)
        ticks.append(rows)
    return ticks


@pytest.mark.parametrize("seed", [20, 21, 22])
def test_vector_values_all_executors_agree(seed):
    rng = np.random.default_rng(seed)
    graph_seed = rng.integers(0, 1 << 30)
    ticks_seed = rng.integers(0, 1 << 30)
    ticks = vector_ticks(np.random.default_rng(ticks_seed))

    views = {}
    for name in ("cpu", "tpu", "sharded", "staged"):
        g, (src,), sink = build_vector_graph(
            np.random.default_rng(graph_seed))
        ex = {
            "cpu": lambda: get_executor("cpu"),
            "tpu": lambda: get_executor("tpu"),
            "sharded": lambda: ShardedTpuExecutor(make_mesh(8)),
            "staged": lambda: StagedTpuExecutor(),
        }[name]()
        sched = DirtyScheduler(g, ex)
        for rows in ticks:
            sched.push(src, DeltaBatch(
                np.array([r[0] for r in rows], np.int64),
                np.array([r[1] for r in rows], np.float32),
                np.array([r[2] for r in rows], np.int64)))
            sched.tick()
        views[name] = Counter(
            {(int(k), tuple(round(float(x), 3) for x in np.ravel(v))): w
             for (k, v), w in sched.view(sink).items() if w})
    for name in ("tpu", "sharded", "staged"):
        assert views[name] == views["cpu"], f"seed {seed}: {name} diverges"


# -- vector-valued min/max with retractions (VERDICT r3 #4) ----------------

def _vec_minmax_drive(executor, how, ticks, Kv, V):
    g = FlowGraph("vmm")
    spec = Spec((V,), np.float32, key_space=Kv)
    src = g.source("s", spec)
    red = g.reduce(src, how, name="m", candidates=32)
    sched = DirtyScheduler(g, executor)
    for rows in ticks:
        sched.push(src, DeltaBatch(
            np.array([r[0] for r in rows], np.int64),
            np.array([r[1] for r in rows], np.float32),
            np.array([r[2] for r in rows], np.int64)))
        sched.tick()
    return {int(k): np.asarray(v, np.float64).reshape(V)
            for k, v in sched.read_table(red).items()}


@pytest.mark.parametrize("how", ["min", "max"])
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_vector_minmax_retraction_differential(how, seed):
    """Vector-valued min/max on device, WITH retractions, vs the CPU
    oracle on cpu/tpu/sharded — no fallback, no error. Values are small
    integer vectors so f32 vs f64 comparison is exact; the aggregate is
    the lex-smallest/-largest value ROW (the oracle's tuple ordering)."""
    Kv, V = 32, 3
    rng = np.random.default_rng(500 + seed)
    log = []
    ticks = []
    for _ in range(4):
        rows = []
        for _ in range(32):
            if log and rng.random() < 0.35:
                k, v, w = log.pop(int(rng.integers(0, len(log))))
                rows.append((k, v, -w))
            else:
                row = (int(rng.integers(0, Kv)),
                       tuple(float(x) for x in rng.integers(0, 6, V)),
                       1)
                rows.append(row)
                log.append(row)
        ticks.append(rows)

    views = {}
    for name in ("cpu", "tpu", "sharded"):
        ex = {"cpu": lambda: get_executor("cpu"),
              "tpu": lambda: get_executor("tpu"),
              "sharded": lambda: ShardedTpuExecutor(make_mesh(8))}[name]()
        views[name] = _vec_minmax_drive(ex, how, ticks, Kv, V)
    for name in ("tpu", "sharded"):
        assert set(views[name]) == set(views["cpu"]), (how, seed, name)
        for k in views["cpu"]:
            np.testing.assert_array_equal(
                views[name][k], views["cpu"][k],
                err_msg=f"{how} seed {seed} {name} key {k}")


def test_vector_minmax_is_lexicographic_not_elementwise():
    """min over {[3,0], [2,9]} is [2,9] (the lex-smallest ROW of the
    multiset — the host oracle's tuple ordering), never the fabricated
    elementwise [2,0]; retraction of the winner resurfaces [3,0]."""
    for name in ("cpu", "tpu"):
        g = FlowGraph("lex")
        spec = Spec((2,), np.float32, key_space=8)
        src = g.source("s", spec)
        red = g.reduce(src, "min", name="m", candidates=8)
        sched = DirtyScheduler(g, get_executor(name))
        sched.push(src, DeltaBatch(
            np.array([1, 1]),
            np.array([[3.0, 0.0], [2.0, 9.0]], np.float32),
            np.ones(2, np.int64)))
        sched.tick()
        got = np.asarray(sched.read_table(red)[1]).reshape(2)
        np.testing.assert_array_equal(got, [2.0, 9.0], err_msg=name)
        sched.push(src, DeltaBatch(
            np.array([1]), np.array([[2.0, 9.0]], np.float32),
            -np.ones(1, np.int64)))
        sched.tick()
        got = np.asarray(sched.read_table(red)[1]).reshape(2)
        np.testing.assert_array_equal(got, [3.0, 0.0], err_msg=name)
