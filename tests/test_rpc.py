"""Ingestion RPC: the ``IngestFrontend.submit() -> Ticket`` contract
over the wire (``serve/rpc.py``).

Everything here runs hermetically over ``LoopbackTransport`` — same
framing, same protocol, no kernel; the multi-process bench and
``tests/test_proc.py`` soak the TCP twin. The load-bearing invariant is
exactly-once across producer death: a producer that dies mid-submit
resubmits the same ``batch_id`` after respawn, the ``hello`` dedup
handshake reports it admitted, and the fold count stays one.
"""

from reflow_tpu.net import LoopbackTransport
from reflow_tpu.serve import (APPLIED, DEDUPED, REJECTED,
                              IngestFrontend, RemoteProducer,
                              RpcIngestServer)
from reflow_tpu.wal import DurableScheduler
from reflow_tpu.workloads import wordcount


def make_stack(tmp_path, *, start=True, max_tickets=None):
    g, src, sink = wordcount.build_graph()
    sched = DurableScheduler(g, wal_dir=str(tmp_path / "wal"),
                             fsync="tick")
    fe = IngestFrontend(sched, start=start)
    lt = LoopbackTransport()
    srv = RpcIngestServer(fe, lt, max_tickets=max_tickets).start()
    return sched, fe, lt, srv, src, sink


def batch(words: str):
    return wordcount.ingest_lines([words])


def test_submit_applied_deduped_and_status(tmp_path):
    sched, fe, lt, srv, src, sink = make_stack(tmp_path)
    prod = RemoteProducer(lt, srv.address, name="p0")
    try:
        t = prod.submit(src, batch("aa bb aa"), batch_id="b0")
        res = t.result(10)
        assert res.status == APPLIED
        assert res.lsn is not None          # durable before the ack
        assert res.tick >= 0
        assert prod.in_doubt_ids() == ()
        # the hello handshake carried the server's identity
        assert prod.last_hello["graph"] == sched.graph.name
        assert prod.last_hello["epoch"] == 0

        # same id again: the dedup mirror collapses it, one fold total
        t2 = prod.submit(src, batch("aa bb aa"), batch_id="b0")
        assert t2.result(10).status == DEDUPED
        assert prod.deduped_total == 1
        fe.flush()
        assert sched.view(sink.name)[("aa", 2.0)] == 1
        assert srv.submits_total == 2
    finally:
        prod.close()
        srv.close()
        fe.close()
        sched.wal.close()


def test_unknown_source_rejects_deterministically(tmp_path):
    sched, fe, lt, srv, src, sink = make_stack(tmp_path)
    prod = RemoteProducer(lt, srv.address, name="p0")
    try:
        t = prod.submit("no-such-source", batch("xx"), batch_id="b0")
        res = t.result(10)
        # a protocol rejection resolves the ticket (retrying the same
        # request cannot succeed) instead of parking it in doubt
        assert res.status == REJECTED
        assert "no-such-source" in res.reason
        assert prod.in_doubt_ids() == ()
    finally:
        prod.close()
        srv.close()
        fe.close()
        sched.wal.close()


def test_resubmit_after_producer_death_exactly_once(tmp_path):
    """The reconnect-dedup satellite: producer dies mid-submit, the
    respawned producer resubmits the same batch_id — the hello
    handshake reports it admitted, the resolve says DEDUPED, and the
    batch folded exactly once."""
    sched, fe, lt, srv, src, sink = make_stack(tmp_path)
    prod1 = RemoteProducer(lt, srv.address, name="p0")
    # submit and die without learning the fate — the ack window is
    # exactly where a kill -9 leaves a real producer in doubt
    prod1.submit(src, batch("zz0 zz1 zz0"), batch_id="boom-1")
    prod1.close()

    prod2 = RemoteProducer(lt, srv.address, name="p0-respawn")
    try:
        t = prod2.submit(src, batch("zz0 zz1 zz0"), batch_id="boom-1")
        res = t.result(10)
        assert res.status == DEDUPED
        # the handshake made the outcome observable: the dial inside
        # submit() carried the in-doubt id, the mirror remembered it
        assert "boom-1" in prod2.last_hello["admitted"]
        assert prod2.deduped_total == 1
        fe.flush()
        view = sched.view(sink.name)
        assert view[("zz0", 2.0)] == 1   # one fold, not two
        assert view[("zz1", 1.0)] == 1
    finally:
        prod2.close()
        srv.close()
        fe.close()
        sched.wal.close()


def test_link_reset_resubmits_on_replacement_endpoint(tmp_path):
    """A server restart (the promoted-replacement shape: empty ticket
    table, recovered mirror) never double-folds and never loses an
    acked write — the producer re-dials, re-handshakes and resubmits."""
    sched, fe, lt, srv, src, sink = make_stack(tmp_path)
    prod = RemoteProducer(lt, srv.address, name="p0")
    srv2 = None
    try:
        assert prod.submit(src, batch("m0"),
                           batch_id="b0").result(10).status == APPLIED
        srv.close()                       # the link resets under us
        t = prod.submit(src, batch("m1 m1"), batch_id="b1")
        assert not t.done()               # in doubt, payload retained
        srv2 = RpcIngestServer(fe, lt).start()   # same frontend
        prod.retarget(srv2.address)
        res = t.result(10)
        assert res.status in (APPLIED, DEDUPED)
        assert prod.reconnects_total >= 1
        assert prod.submits_total >= 3    # b0 + b1 + the resubmit
        fe.flush()
        assert sched.view(sink.name)[("m1", 2.0)] == 1   # one fold
        assert prod.in_doubt_ids() == ()
    finally:
        prod.close()
        if srv2 is not None:
            srv2.close()
        srv.close()
        fe.close()
        sched.wal.close()


def test_ticket_eviction_resolves_unknown_then_dedups(tmp_path):
    """The bounded ticket table: an evicted in-flight ticket resolves
    "unknown", the producer resubmits, and the dedup mirror keeps the
    duplicate from folding twice."""
    # no pump: tickets stay undecided, making the eviction deterministic
    sched, fe, lt, srv, src, sink = make_stack(tmp_path, start=False,
                                               max_tickets=1)
    prod = RemoteProducer(lt, srv.address, name="p0")
    try:
        t0 = prod.submit(src, batch("e0"), batch_id="b0")
        prod.submit(src, batch("e1"), batch_id="b1")  # evicts b0
        assert srv.evicted_tickets == 1
        # driving b0 now resolves it: resolve -> "unknown" -> resubmit
        # -> DEDUPED against the mirror (b0 was admitted, just evicted)
        res = t0.result(10)
        assert res.status == DEDUPED
        assert prod.deduped_total == 1
        assert prod.resubmits_total >= 1
    finally:
        prod.close()
        srv.close()
        fe.close(flush=False)   # nothing pumps the queued batches
        sched.wal.close()


def test_flush_view_and_ping_ops(tmp_path):
    """The sideband ops the bench leans on: flush quiesces the
    frontend, view reads the sink at the current tick, ping reports
    graph/tick/lsn/state."""
    sched, fe, lt, srv, src, sink = make_stack(tmp_path)
    prod = RemoteProducer(lt, srv.address, name="p0")
    try:
        for i in range(3):
            prod.submit(src, batch("vv ww"), batch_id=f"b{i}")
        prod.flush(10)
        conn = lt.connect(srv.address)
        try:
            conn.send_msg(("flush", 10.0))
            assert conn.recv_msg(10.0) == ("ok",)
            conn.send_msg(("view", sink.name))
            ok, tick, view = conn.recv_msg(10.0)
            assert ok == "ok" and tick == sched._tick
            assert view[("vv", 3.0)] == 1
            conn.send_msg(("ping",))
            ok, st = conn.recv_msg(10.0)
            assert st["tick"] == sched._tick and st["state"] == "running"
            conn.send_msg(("bogus",))
            assert conn.recv_msg(10.0)[0] == "err"
        finally:
            conn.close()
    finally:
        prod.close()
        srv.close()
        fe.close()
        sched.wal.close()
