#!/usr/bin/env python3
"""Benchmark harness: the BASELINE.md configs, headline = config 3.

Headline (ONE JSON line on stdout): incremental PageRank under per-tick
edge churn (BASELINE.md config 3, the north-star workload) on the
TpuExecutor vs the CpuExecutor (the default path / baseline)::

    {"metric": ..., "value": <speedup>, "unit": "x", "vs_baseline": <v/20>}

``value`` is the delta-ops/sec throughput ratio TPU/CPU on churn ticks,
both sides measured SYNCHRONOUSLY: every measured tick ends with
``jax.block_until_ready`` on the full executor state pytree, so walls are
device-completion times, never dispatch times (VERDICT r2 weak #1/#4).
The pipelined streaming rate (``tick(sync=False)``, one block per batch)
is reported alongside on stderr — after the round-3 fixes (state-pytree
donation + bind-time GC-kernel warmup) it should meet or beat the synced
rate; round 2's "streaming 11x slower" was the arena-GC kernel's one-time
remote compile landing inside the measured window.

The CPU baseline measures the same graph shape scaled to
``REFLOW_BENCH_CPU_EDGES_CAP`` edges (default 200k) plus a scaling sweep
over smaller sizes (stderr) showing the per-row rate is flat-to-declining
in graph size, so extrapolating the 200k-edge rate to 1M edges is
conservative for the speedup claim. ``REFLOW_BENCH_CPU_FULL=1`` instead
measures the CPU executor at the full 1M-edge config (cold build alone
costs ~15 minutes of pure-Python fixpoint — 921s measured offline; see
README's benchmark notes).

Env knobs::

    REFLOW_BENCH_SMOKE=1          tiny scale (local sanity check)
    REFLOW_BENCH_NODES/EDGES      graph size        (default 100k / 1M)
    REFLOW_BENCH_CHURN            churn fraction    (default 0.01)
    REFLOW_BENCH_TICKS            measured synced ticks      (default 3)
    REFLOW_BENCH_STREAM_TICKS     pipelined streaming ticks  (default 8)
    REFLOW_BENCH_CPU_EDGES_CAP    CPU measured at <= this many edges
    REFLOW_BENCH_CPU_FULL=1       CPU at full scale (overrides cap; slow)
    REFLOW_BENCH_ALL=0            skip configs 1/2/4/5 (default: run them)
    REFLOW_BENCH_TRACE=<dir>      xprof device trace of one churn tick
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def log(*a) -> None:
    print(*a, file=sys.stderr, flush=True)


def _build_pagerank(n_nodes: int, n_edges: int, churn: float,
                    tol: float, seed: int = 7):
    from reflow_tpu.executors.device_delta import bucket_capacity
    from reflow_tpu.workloads import pagerank

    # arena sized for LIVE rows plus churn headroom — on-device compaction
    # (executors/arena.py) reclaims cancelled pairs when the high-water
    # check trips, so capacity no longer scales with tick count
    churn_cap = bucket_capacity(2 * int(churn * n_edges) + 2)
    arena = bucket_capacity(n_edges) + 8 * churn_cap
    pr = pagerank.build_graph(n_nodes, tol=tol, arena_capacity=arena)
    web = pagerank.WebGraph.random(n_nodes, n_edges, seed=seed)
    return pr, web


def _synced_tick(sched):
    """Tick measured to device completion (one shared helper — see
    bench_configs._timed_tick)."""
    from bench_configs import _timed_tick

    return _timed_tick(sched)


def run_pagerank(executor: str, n_nodes: int, n_edges: int, churn: float,
                 ticks: int, stream_ticks: int, tol: float,
                 measure_full: bool = True) -> dict:
    from reflow_tpu.executors import get_executor
    from reflow_tpu.scheduler import DirtyScheduler
    from reflow_tpu.workloads import pagerank

    pr, web = _build_pagerank(n_nodes, n_edges, churn, tol)
    sched = DirtyScheduler(pr.graph, get_executor(executor))

    sched.push(pr.teleport, pagerank.teleport_batch(n_nodes))
    sched.push(pr.edges, web.initial_batch())
    build_s, _ = _synced_tick(sched)

    # two unmeasured churn ticks absorb jit compiles of the churn shapes
    # (pointless for the no-jit CPU oracle, whose ticks cost real minutes)
    if executor != "cpu":
        for _ in range(2):
            sched.push(pr.edges, web.churn(churn))
            _synced_tick(sched)

    # synced per-tick walls: every wall is a device-completion time
    walls, dops = [], []
    for _ in range(ticks):
        sched.push(pr.edges, web.churn(churn))
        wall, res = _synced_tick(sched)
        walls.append(wall)
        dops.append(res.delta_ops)
    trace_dir = os.environ.get("REFLOW_BENCH_TRACE")
    if trace_dir and executor != "cpu":
        # xprof device trace of ONE extra steady-state churn tick, kept
        # out of the measured walls (trace start/stop + dump I/O would
        # distort the very metric being diagnosed)
        from reflow_tpu.utils.metrics import profile_trace
        sched.push(pr.edges, web.churn(churn))
        with profile_trace(trace_dir):
            _synced_tick(sched)

    # streaming: pipelined ticks, one sync per batch — the delta-ops/s
    # throughput a streaming deployment sees
    stream_dops, stream_wall = 0, float("nan")
    if stream_ticks:
        results = []
        t0 = time.perf_counter()
        for _ in range(stream_ticks):
            sched.push(pr.edges, web.churn(churn))
            results.append(sched.tick(sync=False))
        for r in results:
            r.block()
        stream_wall = time.perf_counter() - t0
        assert all(r.quiesced for r in results)
        stream_dops = sum(r.delta_ops for r in results)

    # warm full-recompute baseline: rebuild from scratch on the same (warm)
    # executor with the same scheduler settings, so the compiled program
    # cache applies and compile time isn't billed to "full recompute"
    full_s = float("nan")
    if measure_full:
        ex = sched.executor
        sched2 = DirtyScheduler(pr.graph, ex)
        sched2.push(pr.teleport, pagerank.teleport_batch(n_nodes))
        sched2.push(pr.edges, web.initial_batch())
        full_s, _ = _synced_tick(sched2)

    return {
        "executor": executor,
        "nodes": n_nodes,
        "edges": n_edges,
        "cold_build_s": build_s,
        "full_recompute_s": full_s,
        "tick_s_median": float(np.median(walls)),
        "delta_ops_per_s": float(sum(dops) / sum(walls)),
        "delta_ops_per_s_stream": (float(stream_dops / stream_wall)
                                   if stream_ticks else None),
        "delta_ops_per_tick": float(np.mean(dops)),
        "stream_ticks": stream_ticks,
    }


def main() -> None:
    smoke = os.environ.get("REFLOW_BENCH_SMOKE") == "1"
    n_nodes = int(os.environ.get(
        "REFLOW_BENCH_NODES", 1_000 if smoke else 100_000))
    n_edges = int(os.environ.get(
        "REFLOW_BENCH_EDGES", 10_000 if smoke else 1_000_000))
    churn = float(os.environ.get("REFLOW_BENCH_CHURN", 0.01))
    ticks = int(os.environ.get("REFLOW_BENCH_TICKS", 2 if smoke else 3))
    stream_ticks = int(os.environ.get(
        "REFLOW_BENCH_STREAM_TICKS", 2 if smoke else 8))
    cpu_cap = int(os.environ.get(
        "REFLOW_BENCH_CPU_EDGES_CAP", 10_000 if smoke else 200_000))
    cpu_full = os.environ.get("REFLOW_BENCH_CPU_FULL") == "1"
    tol = 1e-4

    import jax
    log(f"jax backend={jax.default_backend()} devices={len(jax.devices())}")

    # configs 1/2/4/5 first (records on stderr), headline (config 3) last
    # so the final stdout line stays the parseable result
    if os.environ.get("REFLOW_BENCH_ALL", "1") == "1":
        from bench_configs import run_all_configs
        run_all_configs(smoke, log)

    tpu = run_pagerank("tpu", n_nodes, n_edges, churn, ticks,
                       stream_ticks, tol)
    log("tpu:", json.dumps(tpu))
    incr_vs_full = tpu["full_recompute_s"] / tpu["tick_s_median"]
    log(f"incremental-vs-full (tpu executor, warm, synced): "
        f"{incr_vs_full:.1f}x")

    # CPU baseline: measured at the cap, with a scaling sweep making the
    # per-row-rate extrapolation explicit (the rate is flat-to-declining
    # in size, so quoting the cap-size rate at full scale is conservative)
    if cpu_full:
        cpu = run_pagerank("cpu", n_nodes, n_edges, churn, 1, 0, tol,
                           measure_full=False)
    else:
        sweep = []
        cap = min(cpu_cap, n_edges)
        e = max(256, cap // 4)
        while e <= cap:
            scale = e / n_edges
            r = run_pagerank("cpu", max(64, int(n_nodes * scale)), e,
                             churn, 1, 0, tol, measure_full=False)
            sweep.append(r)
            log(f"cpu sweep @ {e} edges: "
                f"{r['delta_ops_per_s']:.0f} delta-ops/s")
            e *= 2
        cpu = sweep[-1]
    log("cpu:", json.dumps(cpu))

    speedup = tpu["delta_ops_per_s"] / cpu["delta_ops_per_s"]
    print(json.dumps({
        "metric": "pagerank_incremental_delta_ops_per_s_speedup_vs_cpu_executor",
        "value": round(speedup, 2),
        "unit": "x",
        "vs_baseline": round(speedup / 20.0, 3),
        "tpu_delta_ops_per_s": round(tpu["delta_ops_per_s"]),
        "tpu_delta_ops_per_s_stream": round(tpu["delta_ops_per_s_stream"]
                                            or 0),
        "cpu_delta_ops_per_s": round(cpu["delta_ops_per_s"]),
        "cpu_edges": cpu["edges"],
        "incr_vs_full": round(incr_vs_full, 2),
    }))


if __name__ == "__main__":
    main()
