#!/usr/bin/env python3
"""Benchmark harness: the BASELINE.md configs, headline = config 3.

Headline (ONE JSON line on stdout): incremental PageRank under per-tick
edge churn (BASELINE.md config 3, the north-star workload) on the
TpuExecutor vs the CpuExecutor (the default path / baseline)::

    {"metric": ..., "value": <speedup>, "unit": "x", "vs_baseline": <v/20>}

``value`` is the delta-ops/sec throughput ratio TPU/CPU on churn ticks.

Measurement model (round 3). Two facts about the tunnel-attached device
drive the harness shape:

1. ``jax.block_until_ready`` does NOT wait for remote completion (it
   resolves the local handle only) — a wall "synced" with it is a
   dispatch wall. The only true barrier is a device->host readback.
2. The FIRST readback of the process permanently degrades the tunnel
   into a synchronous mode (~70-150ms per sync, chained dispatches
   ~66ms each; measured in tools/audit_constants.py's commentary and
   the round-3 investigation). So one honest window per process.

Therefore: every device-touching config runs in its OWN subprocess, and
each measures one PIPELINED WINDOW — N streaming ticks dispatched
back-to-back with zero readbacks, then a single readback that barriers
the in-order device stream (``bench_configs._stream_window``). The wall
covers dispatch + all device compute; the dispatch-only wall is reported
alongside as evidence the window was device-bound. The full-recompute
baseline gets its own subprocess for the same reason (its single tick's
barrier must be the process's first readback).

The CPU baseline measures the same graph shape scaled to
``REFLOW_BENCH_CPU_EDGES_CAP`` edges (default 200k) plus a scaling sweep
over smaller sizes (stderr) showing the per-row rate is flat-to-declining
in graph size, so extrapolating the 200k-edge rate to 1M edges is
conservative for the speedup claim. ``REFLOW_BENCH_CPU_FULL=1`` instead
measures the CPU executor at the full 1M-edge config (cold build alone
costs ~15 minutes of pure-Python fixpoint — 921s measured offline; see
README's benchmark notes).

Env knobs::

    REFLOW_BENCH_SMOKE=1          tiny scale (local sanity check)
    REFLOW_BENCH_NODES/EDGES      graph size        (default 100k / 1M)
    REFLOW_BENCH_CHURN            churn fraction    (default 0.01)
    REFLOW_BENCH_STREAM_TICKS     pipelined window length    (default 16)
    REFLOW_BENCH_CPU_EDGES_CAP    CPU measured at <= this many edges
    REFLOW_BENCH_CPU_FULL=1       CPU at full scale (overrides cap; slow)
    REFLOW_BENCH_ALL=0            skip configs 1/2/4/5 (default: run them)
    REFLOW_BENCH_TRACE=<dir>      xprof device trace of one churn tick
    REFLOW_BENCH_RECOVERY=1       WAL mode instead: ingestion overhead per
                                  fsync policy + time-to-first-tick after a
                                  simulated crash (CPU-only, no tunnel)
    REFLOW_BENCH_RECOVERY_TICKS   crash-backlog size  (default 1000)
    REFLOW_BENCH_RECOVERY_TPU_TICKS  device-path crash backlog
                                  (default backlog/10; the recovery mode
                                  also replays over TpuExecutor to price
                                  recompile-on-replay)
    REFLOW_BENCH_MEGATICK=1       mega-tick mode instead: the PageRank
                                  churn window fused into ONE compiled
                                  dispatch (tick_many -> run_window over
                                  the device-resident ingress queue),
                                  reporting tick_s_amortized vs
                                  window_dispatch_s plus view parity
                                  against an identically-fed per-tick
                                  twin (runs on the selected device)
    REFLOW_BENCH_PIPELINE=1       pipelined-window mode instead: the
                                  PageRank churn waves through an
                                  IngestFrontend at window depth 1 vs 2
                                  on identical batches — amortized tick,
                                  stage_overlap_frac, EXACT depth parity
                                  (max_abs_diff == 0), zero fallbacks
    REFLOW_BENCH_SERVE=1          serve mode instead: IngestFrontend
                                  sustained throughput at 1/4/16 concurrent
                                  producers vs the bare push+tick loop,
                                  coalesce factor, zero forced syncs
                                  (CPU-only, no tunnel)
    REFLOW_BENCH_SERVE_BATCHES    micro-batches per producer (default 250)
    REFLOW_BENCH_TIER=1           tier mode instead: ServeTier hosting 4
                                  graphs x 4 producers on a 2-thread pump
                                  pool vs 4 independent frontends, plus
                                  pump-crash isolation (exactly-once after
                                  recover) and hot/quiet-tenant QoS
                                  isolation (CPU-only, no tunnel)
    REFLOW_BENCH_TIER_BATCHES     micro-batches per producer (default 200)
    REFLOW_BENCH_SHARDSERVE=1     pod-scale serving mode instead: the
                                  same mega-tick tier load three ways —
                                  8 tenants on one device, 8 tenants
                                  spread one-per-device (placement=
                                  "spread", shared window programs via
                                  the plan-signature cache), and ONE
                                  sharded hot tenant spanning the mesh —
                                  with exact view parity vs a CPU oracle
                                  and zero fallbacks (cpu runs force 8
                                  host devices; real meshes use theirs)
    REFLOW_BENCH_SHARDSERVE_BATCHES  batches per producer (default 48)
    REFLOW_BENCH_CONTROL=1        control mode instead: self-healing
                                  ControlPlane under step load — a
                                  hot-tenant surge browned out per-graph
                                  (quiet sibling's admission p99 bounded,
                                  recovery within the configured control
                                  intervals after the surge ends) and a
                                  pump-crash storm tripping the circuit
                                  breaker then healing through half-open
                                  unattended (CPU-only, no tunnel)
    REFLOW_BENCH_OBS=1            obs mode instead: tracing + telemetry
                                  overhead on the 16-producer serve
                                  protocol over a durable scheduler, obs
                                  disabled vs enabled, plus the chrome
                                  trace export and the per-ticket stage
                                  decomposition check (CPU-only, no tunnel)
    REFLOW_BENCH_OBS_BATCHES      micro-batches per producer (default 250)
    REFLOW_BENCH_WALPIPE=1        durability-pipeline mode instead:
                                  device-resident pre-imaged submissions
                                  over fsync="record", inline (frame+
                                  write+fsync on the dispatch path) vs
                                  pipelined committer at 1/16 producers,
                                  asserting zero log readbacks, LSN-
                                  stamped tickets, and inline==pipelined
                                  ==replayed sink views (CPU-only)
    REFLOW_BENCH_WALPIPE_BATCHES  batches per producer at 16p (default 4)
    REFLOW_BENCH_REPLICA=1        read-replica mode instead: WAL shipping
                                  to N ReplicaSchedulers under sustained
                                  16-producer writes; aggregate ReadTier
                                  top-k QPS vs the single-leader
                                  baseline, bounded replay lag, and
                                  exact leader-vs-replica view parity at
                                  the published horizon (CPU-only)
    REFLOW_BENCH_REPLICA_N        follower count            (default 4)
    REFLOW_BENCH_REPLICA_READ_S   per-leg read window (s)   (default 2.0)
    REFLOW_BENCH_SUBS=1           reactive-reads mode instead: one
                                  replica's SubscriptionHub fans
                                  per-window deltas to N simulated
                                  subscribers (plus real wire
                                  subscribers through a mid-run
                                  partition + heal) under sustained
                                  16-producer writes; asserts exact
                                  push-vs-pull parity at equal
                                  horizons, zero gaps / zero duplicate
                                  applies on resume, and write-path
                                  admission p99 within 2x the
                                  no-subscriber baseline (CPU-only)
    REFLOW_BENCH_SUBS_N           simulated subscriber count
                                  (default 100_000, smoke 2000)
    REFLOW_BENCH_SUBS_RUN_S       per-leg write window (s)
                                  (default 2.0, smoke 0.6)
    REFLOW_BENCH_FAILOVER=1       failover mode instead: kill the leader
                                  (committer crash seam) under sustained
                                  16-producer writes; a
                                  FailoverCoordinator detects, fences the
                                  old epoch, elects + promotes a replica
                                  and re-binds ingestion; reports
                                  detection/promotion/first-window walls,
                                  asserts ZERO acked-write loss (final
                                  view == a fold of every acked batch)
                                  and exact old-vs-new view parity at the
                                  promotion horizon (CPU-only)
    REFLOW_BENCH_FAILOVER_N       follower count            (default 2)
    REFLOW_BENCH_FAILOVER_RUN_S   per-phase write window (s) (default 1.0)
    REFLOW_BENCH_COMPACT=1        bounded-history mode instead: two
                                  identically-fed 16-producer legs
                                  (unbounded oracle vs checkpoint chain
                                  + key-level WAL compaction); asserts
                                  history >= 10x live state, >= 5x
                                  faster leader crash-recovery AND
                                  fresh-replica bootstrap vs full-
                                  history replay, both within 2x of a
                                  fresh-full-checkpoint restore, exact
                                  view parity, zero acked-write loss,
                                  bounded on-disk footprint (CPU-only)
    REFLOW_BENCH_COMPACT_TICKS    batches per producer (default 480)
    REFLOW_BENCH_TILES=1          tiled-maintenance mode instead: two
                                  identically-fed bounded legs (chain +
                                  compactor), one monolithic and one
                                  with REFLOW_TILE_BYTES set at state
                                  >= 8x the budget; asserts compactor
                                  and checkpoint writer/reader peaks
                                  under 2x budget, exact recover /
                                  bootstrap parity, per-tile crash-seam
                                  survival, tile-unit bootstrap, top_k
                                  and lookup parity vs an untiled
                                  snapshot oracle, and tiled restore /
                                  bootstrap wall within 1.2x untiled
    REFLOW_BENCH_TILES_TICKS      batches per producer (default 320)
    REFLOW_BENCH_CHAOS=1          chaos-soak mode instead: ship the WAL
                                  to N replicas over REAL TCP links, each
                                  wrapped in a seeded fault injector
                                  (drop/dup/reorder/corrupt/delay, a
                                  scripted one-way partition + reset),
                                  then quiesce and kill the leader;
                                  asserts zero acked-write loss, exact
                                  view parity at equal horizons, lag <=
                                  one commit window after faults stop,
                                  and that the fenced ex-leader's
                                  post-fence shipments are all NACKed
                                  (CPU-only)
    REFLOW_BENCH_CHAOS_N          follower count            (default 3)
    REFLOW_BENCH_CHAOS_RUN_S      write window (s)          (default 1.2)
    REFLOW_BENCH_FLEETOBS=1       fleet-telemetry mode instead: the
                                  replicated TCP topology with a
                                  TelemetryShipper per node streaming
                                  registry snapshots to a live
                                  FleetAggregator; reports the write-
                                  path overhead (off vs on, best-of-2,
                                  <3% on an uncontended host), asserts
                                  aggregator horizons == ground truth
                                  at quiesce, >= 1 post-heal causal
                                  chain ship_segment->net_send->
                                  replica_replay, and that the fleet
                                  view serves stale-marked through a
                                  telemetry-link partition (CPU-only)
    REFLOW_BENCH_FLEETOBS_BATCHES fixed-work batches per producer for
                                  the A/B legs (default 320, smoke 160)
    REFLOW_BENCH_MULTIPROC=1      multi-process mode instead: a leader
                                  + N replica + M producer fleet of
                                  real OS processes (python -m
                                  reflow_tpu.proc) pumping over the
                                  ingestion RPC; a kill -9 storm takes
                                  every replica (respawn + WAL
                                  recovery + horizon-barrier rejoin)
                                  and then the leader (cross-process
                                  promotion; producers reconnect and
                                  resubmit exactly-once); asserts zero
                                  acked-write loss vs a deterministic
                                  oracle, exact parity at equal
                                  horizons on the survivors, an empty
                                  in-doubt set on every producer, and
                                  full fleet-telemetry coverage
                                  (CPU-only)
    REFLOW_BENCH_MULTIPROC_N      replica-process count     (default 3)
    REFLOW_BENCH_MULTIPROC_PRODUCERS  producer-process count
                                  (default 4)
    REFLOW_BENCH_MULTIPROC_RUN_S  per-phase write window (s)
                                  (default 1.5, smoke 0.6)
    REFLOW_TRACE_OUT              obs-mode chrome trace path
                                  (default /tmp/reflow_obs_trace.json;
                                  fleetobs default
                                  /tmp/reflow_fleet_trace.json)

Every mode also accepts ``--json-out PATH``: the final result object is
written there (pretty-printed) in addition to the stdout JSON line.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np

from reflow_tpu.utils.config import (env_flag, env_float, env_int, env_str)


def log(*a) -> None:
    print(*a, file=sys.stderr, flush=True)


def _build_pagerank(n_nodes: int, n_edges: int, churn: float,
                    tol: float, seed: int = 7, defer=None):
    from reflow_tpu.executors.device_delta import bucket_capacity
    from reflow_tpu.workloads import pagerank

    # arena sized for LIVE rows plus churn headroom — in-program
    # compaction (executors/arena.py via join_core's lax.cond) reclaims
    # cancelled pairs at high water, so capacity doesn't scale with ticks
    churn_cap = bucket_capacity(2 * int(churn * n_edges) + 2)
    arena = bucket_capacity(n_edges) + 8 * churn_cap
    pr = pagerank.build_graph(n_nodes, tol=tol, arena_capacity=arena,
                              defer_passes=defer)
    web = pagerank.WebGraph.random(n_nodes, n_edges, seed=seed)
    return pr, web


def _synced_tick(sched):
    from bench_configs import _timed_tick

    return _timed_tick(sched)


def _params():
    smoke = env_flag("REFLOW_BENCH_SMOKE")
    return {
        "smoke": smoke,
        "n_nodes": env_int("REFLOW_BENCH_NODES", 1_000 if smoke else 100_000),
        "n_edges": env_int("REFLOW_BENCH_EDGES", 10_000 if smoke else 1_000_000),
        "churn": env_float("REFLOW_BENCH_CHURN", 0.01),
        "stream_ticks": env_int("REFLOW_BENCH_STREAM_TICKS", 4 if smoke else 16),
        "cpu_cap": env_int("REFLOW_BENCH_CPU_EDGES_CAP", 10_000 if smoke else 200_000),
        "cpu_full": env_flag("REFLOW_BENCH_CPU_FULL"),
        "tol": 1e-4,
        # cross-tick residual deferral (close_loop defer_passes) for the
        # pr_tpu_defer child — the incr_vs_full lever (VERDICT r4 #1);
        # accuracy verified in-record against reference_ranks. Unset
        # defaults to defer=1 (the measured-dominant mode); set to 0,
        # empty, or a non-integer to skip the deferred child.
        "defer": _defer_env(),
    }


def _defer_env():
    # defer=1 dominates defer=2 on this workload: same worst-key
    # mid-stream rel lag (0.352 vs 0.367 measured) and the same drained
    # band (rel ~1.4e-4), at 74.5 vs 92 ms per tick
    raw = env_str("REFLOW_BENCH_DEFER", "1").strip()
    try:
        v = int(raw)
    except ValueError:
        return None
    return v if v > 0 else None


# -- WAL / crash-recovery mode (REFLOW_BENCH_RECOVERY=1) -------------------

def run_recovery_bench() -> dict:
    """Durable-ingestion numbers (docs/guide.md "Write-ahead delta log"):

    1. WAL append overhead: the same wordcount drive with no WAL vs each
       fsync policy (``os`` / ``tick`` / ``record``) — the per-tick
       policy is the default, so its overhead is the headline cost of
       durability.
    2. Recovery: abandon the per-tick run mid-flight with the full
       backlog in the log (the simulated kill, final record torn), then
       time ``recover()`` + the first post-recovery tick on a fresh
       scheduler — time-to-first-tick after a crash at N ticks of
       backlog.

    Host-side end to end (the WAL is host-boundary machinery); runs on
    the CPU executor so no tunnel protocol applies."""
    import shutil
    import tempfile

    from reflow_tpu.scheduler import DirtyScheduler
    from reflow_tpu.utils.faults import tear_wal_tail
    from reflow_tpu.utils.metrics import summarize_wal
    from reflow_tpu.wal import DurableScheduler, recover
    from reflow_tpu.workloads import wordcount

    backlog = env_int("REFLOW_BENCH_RECOVERY_TICKS", "1000")
    rows_per_tick = 8

    def drive(sched, src):
        rng = np.random.default_rng(11)
        t0 = time.perf_counter()
        for t in range(backlog):
            words = " ".join(f"w{int(x)}"
                             for x in rng.integers(0, 1000, rows_per_tick))
            sched.push(src, wordcount.ingest_lines([words]),
                       batch_id=f"t{t}")
            sched.tick()
        return time.perf_counter() - t0

    out = {"backlog_ticks": backlog, "rows_per_tick": rows_per_tick}
    g, src, _sink = wordcount.build_graph()
    base_s = drive(DirtyScheduler(g), src)
    out["no_wal_s"] = round(base_s, 3)
    tmp = tempfile.mkdtemp(prefix="reflow_wal_bench_")
    try:
        crash_dir = None
        for policy in ("os", "tick", "record"):
            wal_dir = os.path.join(tmp, policy)
            g, src, _sink = wordcount.build_graph()
            sched = DurableScheduler(g, wal_dir=wal_dir, fsync=policy)
            wall = drive(sched, src)
            wm = summarize_wal(sched.wal)
            out[f"wal_{policy}_s"] = round(wall, 3)
            out[f"wal_{policy}_overhead_x"] = round(wall / base_s, 3)
            out[f"wal_{policy}_append_p50_us"] = round(
                wm.append_p50_s * 1e6, 1)
            out[f"wal_{policy}_fsync_p50_us"] = round(
                wm.fsync_p50_s * 1e6, 1)
            log(f"wal[{policy}]: {wall:.3f}s "
                f"({out[f'wal_{policy}_overhead_x']}x of no-WAL "
                f"{base_s:.3f}s; append p50 "
                f"{out[f'wal_{policy}_append_p50_us']}us)")
            if policy == "tick":
                crash_dir = wal_dir  # the default policy's log is the
                # crash corpus; the writer is simply abandoned (killed)
        tear_wal_tail(crash_dir, 7)   # the kill also tore a record
        g, src, _sink = wordcount.build_graph()
        fresh = DirtyScheduler(g)
        t0 = time.perf_counter()
        report = recover(fresh, crash_dir)
        recover_s = time.perf_counter() - t0
        words = " ".join(f"w{i}" for i in range(rows_per_tick))
        fresh.push(src, wordcount.ingest_lines([words]),
                   batch_id="post-crash")
        t1 = time.perf_counter()
        fresh.tick()
        first_tick_s = time.perf_counter() - t1
        out.update({
            "recover_s": round(recover_s, 3),
            "recovered_ticks_per_s": round(report.replayed_ticks
                                           / max(recover_s, 1e-9)),
            "replayed_pushes": report.replayed_pushes,
            "replayed_ticks": report.replayed_ticks,
            "torn_tail_tolerated": report.torn_tail is not None,
            "first_tick_s": round(first_tick_s, 4),
            "time_to_first_tick_s": round(recover_s + first_tick_s, 3),
        })
        log("recovery:", json.dumps(report.as_dict()))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    # 3. Device-path recovery: the same crash protocol over the jit
    #    executor (TpuExecutor), where replay re-executes through compiled
    #    programs — the first replayed tick pays the recompile, the rest
    #    stream. Records the post-crash first-tick and the backlog-drain
    #    (replay) wall on the device path, next to the host-oracle numbers
    #    above. Runs on whatever backend JAX_PLATFORMS selects (the mode
    #    defaults to cpu), so by default this measures the jit/recompile
    #    cost, not tunnel transport.
    from reflow_tpu import FlowGraph
    from reflow_tpu.delta import DeltaBatch, Spec
    from reflow_tpu.executors import get_executor

    tpu_backlog = env_int(
        "REFLOW_BENCH_RECOVERY_TPU_TICKS", max(8, backlog // 10))

    def build_dev():
        g = FlowGraph("recovery_dev")
        src = g.source("s", Spec((), np.float32, key_space=64))
        red = g.reduce(src, "sum", tol=0.0)
        return g, src, red

    def dev_batch(rng):
        return DeltaBatch(
            rng.integers(0, 64, rows_per_tick).astype(np.int64),
            rng.integers(0, 8, rows_per_tick).astype(np.float32),
            np.ones(rows_per_tick, np.int64))

    tmp = tempfile.mkdtemp(prefix="reflow_wal_bench_tpu_")
    try:
        wal_dir = os.path.join(tmp, "tick")
        g, src, _red = build_dev()
        sched = DurableScheduler(g, get_executor("tpu"), wal_dir=wal_dir,
                                 fsync="tick")
        rng = np.random.default_rng(23)
        t0 = time.perf_counter()
        for t in range(tpu_backlog):
            sched.push(src, dev_batch(rng), batch_id=f"d{t}")
            sched.tick(sync=False)
        tpu_ingest_s = time.perf_counter() - t0
        # abandon mid-flight (the simulated kill also tore a record)
        tear_wal_tail(wal_dir, 7)
        g2, src2, _red2 = build_dev()
        fresh = DirtyScheduler(g2, get_executor("tpu"))
        t0 = time.perf_counter()
        report = recover(fresh, wal_dir)
        tpu_recover_s = time.perf_counter() - t0
        fresh.push(src2, dev_batch(np.random.default_rng(99)),
                   batch_id="post-crash")
        t1 = time.perf_counter()
        fresh.tick()
        tpu_first_tick_s = time.perf_counter() - t1
        out.update({
            "tpu_backlog_ticks": tpu_backlog,
            "tpu_ingest_s": round(tpu_ingest_s, 3),
            "tpu_recover_s": round(tpu_recover_s, 3),
            "tpu_replayed_ticks": report.replayed_ticks,
            "tpu_recovered_ticks_per_s": round(
                report.replayed_ticks / max(tpu_recover_s, 1e-9)),
            "tpu_first_tick_s": round(tpu_first_tick_s, 4),
            "tpu_time_to_first_tick_s": round(
                tpu_recover_s + tpu_first_tick_s, 3),
        })
        log(f"recovery[tpu]: replay {report.replayed_ticks} ticks in "
            f"{tpu_recover_s:.3f}s, first tick {tpu_first_tick_s:.4f}s")
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return out


# -- compiled mega-tick mode (REFLOW_BENCH_MEGATICK=1) ---------------------

def run_megatick_bench() -> dict:
    """Compiled mega-tick numbers (docs/guide.md "Compiled mega-ticks").

    The PageRank churn-window protocol with the whole K-tick commit
    window fused into ONE jit'd dispatch: ``tick_many`` routes through
    ``TpuExecutor.run_window``, whose scan body consumes slots of the
    device-resident ingress queue. The reported pair is the acceptance
    metric: ``tick_s_amortized`` — full window wall including the
    closing readback barrier, divided by K — vs ``window_dispatch_s`` —
    the host-side cost of dispatching the entire window (queue slot
    writes + one program enqueue). Dispatch-bound means the ratio stays
    small: the host pays per-WINDOW cost, not per-tick cost.

    Parity is asserted in-record: a twin scheduler is driven per-tick
    (push + tick(sync=False)) with the IDENTICAL pre-generated churn
    batches, and both drained rank tables must agree."""
    from bench_configs import _median_window, _pad_batch, _settle, _sync_read
    from reflow_tpu.executors import get_executor
    from reflow_tpu.scheduler import DirtyScheduler
    from reflow_tpu.workloads import pagerank

    p = _params()
    k = p["stream_ticks"]
    n_windows = 3
    n_churn = 2 * max(1, int(p["churn"] * p["n_edges"]))

    pr, web = _build_pagerank(p["n_nodes"], p["n_edges"], p["churn"],
                              p["tol"])
    # pre-generate EVERYTHING before building the twin: WebGraph.churn
    # mutates its edge set, so the batches are minted once and both
    # drives consume the same list (and the same initial batch). Padding
    # to a fixed row count keeps every window on ONE queue/program
    # signature (weight-0 rows are semantic no-ops).
    init = web.initial_batch()
    churn = [_pad_batch(web.churn(p["churn"]), n_churn)
             for _ in range((1 + n_windows) * k)]   # 1 warm + measured

    sched = DirtyScheduler(pr.graph, get_executor("tpu"))
    sched.push(pr.teleport, pagerank.teleport_batch(p["n_nodes"]))
    sched.push(pr.edges, init)
    sched.tick(sync=False)                       # cold build (compile)
    warm = sched.tick_many([{pr.edges: b} for b in churn[:k]])
    _settle(0 if p["smoke"] else 10, log, "drain build + warm window")

    win_ix = [0]

    def run_window_once():
        lo = (1 + win_ix[0]) * k
        feeds = [{pr.edges: b} for b in churn[lo:lo + k]]
        win_ix[0] += 1
        t0 = time.perf_counter()
        res = sched.tick_many(feeds)
        dwall = time.perf_counter() - t0    # host released: window queued
        _sync_read(sched.executor)
        wall = time.perf_counter() - t0
        res.block()
        assert res.quiesced
        return wall, dwall, res.delta_ops

    wall, dwall, dops, windows = _median_window(
        run_window_once, log, f"megatick churn x{k}", n=n_windows)
    warm.block()
    assert sched.megatick_fallbacks == 0, (
        f"window path fell back {sched.megatick_fallbacks}x — the bench "
        f"must measure the fused path")
    assert sched.megatick_windows == 1 + n_windows, sched.megatick_windows

    # twin drive: identical batches through the per-tick streaming crank.
    # It runs after the fused windows (on a tunnel device it lands in the
    # degraded post-readback mode), so its wall is a reference point, not
    # a head-to-head — table parity is the assertion here.
    pr2, _ = _build_pagerank(p["n_nodes"], p["n_edges"], p["churn"],
                             p["tol"])
    per = DirtyScheduler(pr2.graph, get_executor("tpu"))
    per.push(pr2.teleport, pagerank.teleport_batch(p["n_nodes"]))
    per.push(pr2.edges, init)
    per.tick(sync=False)
    t0 = time.perf_counter()
    results = []
    for b in churn:
        per.push(pr2.edges, b)
        results.append(per.tick(sync=False))
    _sync_read(per.executor)
    pertick_wall_s = time.perf_counter() - t0
    for r in results:
        r.block()

    ranks_m = pagerank.ranks_to_array(sched.read_table(pr.new_rank),
                                      p["n_nodes"])
    ranks_p = pagerank.ranks_to_array(per.read_table(pr2.new_rank),
                                      p["n_nodes"])
    max_abs_diff = float(np.abs(ranks_m - ranks_p).max())
    out = {
        "executor": "tpu", "nodes": p["n_nodes"], "edges": p["n_edges"],
        "window_ticks": k,
        "window_wall_s": round(wall, 4),
        "window_dispatch_s": round(dwall, 4),
        "tick_s_amortized": round(wall / k, 5),
        "amortized_over_dispatch_x": round(
            (wall / k) / max(dwall, 1e-9), 3),
        "delta_ops_per_s": round(dops / wall),
        "pertick_wall_s": round(pertick_wall_s, 4),
        "megatick_windows": sched.megatick_windows,
        "megatick_fallbacks": sched.megatick_fallbacks,
        "window_dispatches": getattr(sched.executor,
                                     "window_dispatches", 0),
        "views_match": bool(max_abs_diff <= 1e-6),
        "max_abs_diff": max_abs_diff,
        "windows": [{"wall_s": round(w, 4), "dispatch_s": round(d, 4),
                     "delta_ops": o} for w, d, o in windows],
    }
    log("megatick:", json.dumps(out))
    return out


# -- pipelined-window mode (REFLOW_BENCH_PIPELINE=1) -----------------------

def run_pipeline_bench() -> dict:
    """Pipelined window execution numbers (docs/guide.md "Pipelined
    windows"): the PageRank churn workload driven through a standalone
    ``IngestFrontend`` at window depth 1 (stage and execute strictly
    alternating — the serial pump) vs depth 2 (stage(N+1) overlaps the
    in-flight dispatch of window N), on IDENTICAL pre-generated
    batches. The pause → submit wave → resume → flush protocol forces
    each wave to drain as one multi-chunk backlog, so consecutive
    window chunks actually pipeline.

    Per depth: amortized tick wall (flush + device sync over total
    ticks) and ``stage_overlap_frac``. Across depths: EXACT table
    parity (``max_abs_diff`` must be 0.0 — same fused program, same
    slot contents, same dispatch order), zero mega-tick fallbacks, and
    the not-slower check (depth 2 within 5% of depth 1; on real
    accelerators the overlap is the win, on CPU it must at least not
    regress). A per-tick twin on the same executor bounds both drives
    the way the mega-tick bench does."""
    from bench_configs import _pad_batch, _settle, _sync_read
    from reflow_tpu.executors import get_executor
    from reflow_tpu.scheduler import DirtyScheduler
    from reflow_tpu.serve import CoalesceWindow, IngestFrontend
    from reflow_tpu.workloads import pagerank

    p = _params()
    k = p["stream_ticks"]
    n_windows = 3     # chunks per measured wave (>= 2 so chunks overlap)
    n_waves = 2       # measured waves per depth; best wall wins (noise)
    n_churn = 2 * max(1, int(p["churn"] * p["n_edges"]))

    _, web = _build_pagerank(p["n_nodes"], p["n_edges"], p["churn"],
                             p["tol"])
    # mint every batch once (WebGraph.churn mutates its edge set): both
    # depths and the per-tick twin consume the same list; fixed-row
    # padding keeps every window on one queue/program signature
    init = web.initial_batch()
    churn = [_pad_batch(web.churn(p["churn"]), n_churn)
             for _ in range((1 + n_waves * n_windows) * k)]
    warm, measured = churn[:k], churn[k:]

    out = {"executor": "tpu", "nodes": p["n_nodes"],
           "edges": p["n_edges"], "window_ticks": k,
           "windows_per_wave": n_windows, "waves": n_waves}
    tables = {}
    for d in (1, 2):
        pr, _ = _build_pagerank(p["n_nodes"], p["n_edges"], p["churn"],
                                p["tol"])
        sched = DirtyScheduler(pr.graph, get_executor("tpu"))
        sched.push(pr.teleport, pagerank.teleport_batch(p["n_nodes"]))
        sched.push(pr.edges, init)
        sched.tick(sync=False)                   # cold build (compile)
        fe = IngestFrontend(
            sched, max_bytes=1 << 30, depth=d,
            window=CoalesceWindow(max_rows=n_churn, max_ticks=k,
                                  max_latency_s=0.005))

        def wave(batches, fe=fe, src=pr.edges, sched=sched):
            fe.pause()
            tks = [fe.submit(src, b) for b in batches]
            t0 = time.perf_counter()
            fe.resume()
            fe.flush(timeout=600)
            _sync_read(sched.executor)
            wall = time.perf_counter() - t0
            assert all(t.result(timeout=60).applied for t in tks)
            return wall

        wave(warm)
        _settle(0 if p["smoke"] else 5, log, f"depth {d}: warm wave")
        walls = []
        for w in range(n_waves):
            lo = w * n_windows * k
            walls.append(wave(measured[lo:lo + n_windows * k]))
        wall = min(walls)
        ticks = n_windows * k
        out[f"depth{d}_tick_s_amortized"] = round(wall / ticks, 5)
        out[f"depth{d}_wave_walls_s"] = [round(w, 4) for w in walls]
        out[f"depth{d}_windows_staged"] = fe.windows_staged
        out[f"depth{d}_windows_pipelined"] = fe.windows_pipelined
        out[f"depth{d}_stage_overlap_frac"] = round(
            fe.stage_overlap_frac, 4)
        out[f"depth{d}_megatick_windows"] = sched.megatick_windows
        out[f"depth{d}_megatick_fallbacks"] = sched.megatick_fallbacks
        log(f"pipeline[depth {d}]: {wall:.3f}s best wave "
            f"({out[f'depth{d}_tick_s_amortized']}s/tick; "
            f"staged {fe.windows_staged}, pipelined "
            f"{fe.windows_pipelined}, overlap "
            f"{out[f'depth{d}_stage_overlap_frac']:.0%}, fallbacks "
            f"{sched.megatick_fallbacks})")
        fe.close()
        tables[d] = pagerank.ranks_to_array(
            sched.read_table(pr.new_rank), p["n_nodes"])

    # per-tick twin on the same executor: the proven-parity reference
    pr2, _ = _build_pagerank(p["n_nodes"], p["n_edges"], p["churn"],
                             p["tol"])
    per = DirtyScheduler(pr2.graph, get_executor("tpu"))
    per.push(pr2.teleport, pagerank.teleport_batch(p["n_nodes"]))
    per.push(pr2.edges, init)
    per.tick(sync=False)
    results = []
    for b in churn:
        per.push(pr2.edges, b)
        results.append(per.tick(sync=False))
    _sync_read(per.executor)
    for r in results:
        r.block()
    ranks_t = pagerank.ranks_to_array(per.read_table(pr2.new_rank),
                                      p["n_nodes"])

    max_abs_diff = float(np.abs(tables[2] - tables[1]).max())
    twin_diff = float(np.abs(tables[1] - ranks_t).max())
    out.update({
        # the acceptance set: depth parity is EXACT, the twin is the
        # usual float-tolerance check, the pipeline never fell back,
        # depth 2 genuinely overlapped, and it paid no throughput tax
        "max_abs_diff": max_abs_diff,
        "views_match": bool(max_abs_diff == 0.0),
        "twin_max_abs_diff": twin_diff,
        "twin_views_match": bool(twin_diff <= 1e-6),
        "zero_fallbacks": bool(
            out["depth1_megatick_fallbacks"] == 0
            and out["depth2_megatick_fallbacks"] == 0),
        "overlap_at_depth2": bool(
            out["depth2_stage_overlap_frac"] > 0.0),
        "depth2_not_slower": bool(
            out["depth2_tick_s_amortized"]
            <= 1.05 * out["depth1_tick_s_amortized"]),
        "depth2_vs_depth1_x": round(
            out["depth1_tick_s_amortized"]
            / max(out["depth2_tick_s_amortized"], 1e-9), 3),
    })
    log("pipeline:", json.dumps(out))
    return out


# -- serve / ingestion-frontend mode (REFLOW_BENCH_SERVE=1) ----------------

def run_serve_bench() -> dict:
    """Ingestion-frontend numbers (docs/guide.md "Serving ingestion"):
    sustained micro-batch throughput through ``IngestFrontend`` at
    1 / 4 / 16 concurrent producers vs the bare single-threaded
    ``push()+tick()`` loop on the same workload, plus the coalescing
    factor (micro-batches folded per scheduler tick) and the
    zero-forced-syncs check (the pump only ever calls ``tick_many``).

    Host-side end to end (admission/coalescing are host-boundary
    machinery); runs on the CPU executor so no tunnel protocol applies.
    """
    import threading

    from reflow_tpu.scheduler import DirtyScheduler
    from reflow_tpu.serve import CoalesceWindow, IngestFrontend
    from reflow_tpu.utils.metrics import summarize, summarize_serve
    from reflow_tpu.workloads import wordcount

    smoke = env_flag("REFLOW_BENCH_SMOKE")
    per_producer = env_int("REFLOW_BENCH_SERVE_BATCHES", "40" if smoke else "250")
    rows_per_batch = 8

    def make_lines(producer: int, j: int) -> list:
        rng = np.random.default_rng(producer * 100_003 + j)
        return [" ".join(f"w{int(x)}"
                         for x in rng.integers(0, 1000, rows_per_batch))]

    out = {"per_producer_batches": per_producer,
           "rows_per_batch": rows_per_batch}

    # bare-loop baseline: one thread, one tick per micro-batch
    g, src, _sink = wordcount.build_graph()
    sched = DirtyScheduler(g)
    t0 = time.perf_counter()
    for j in range(per_producer):
        sched.push(src, wordcount.ingest_lines(make_lines(0, j)))
        sched.tick()
    bare_s = time.perf_counter() - t0
    bare_rate = per_producer * rows_per_batch / bare_s
    out["bare_loop_rows_per_s"] = round(bare_rate)
    log(f"bare loop: {per_producer} batches in {bare_s:.3f}s "
        f"({bare_rate:.0f} rows/s)")

    for n_prod in (1, 4, 16):
        g, src, _sink = wordcount.build_graph()
        sched = DirtyScheduler(g)
        fe = IngestFrontend(sched, window=CoalesceWindow(
            max_rows=4096, max_ticks=8, max_latency_s=0.005))
        tickets = []
        tk_lock = threading.Lock()

        def produce(pid, fe=fe, src=src):
            mine = [fe.submit(src, wordcount.ingest_lines(
                make_lines(pid, j))) for j in range(per_producer)]
            with tk_lock:
                tickets.extend(mine)

        threads = [threading.Thread(target=produce, args=(pid,))
                   for pid in range(n_prod)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        fe.flush()
        wall = time.perf_counter() - t0
        assert all(t.result(timeout=10).applied for t in tickets)
        sm = summarize_serve(fe)
        ms = summarize(sched.history)
        fe.close()
        n_batches = n_prod * per_producer
        rate = n_batches * rows_per_batch / wall
        out[f"serve_{n_prod}p_rows_per_s"] = round(rate)
        out[f"serve_{n_prod}p_vs_bare_x"] = round(rate / bare_rate, 3)
        out[f"serve_{n_prod}p_coalesce_factor"] = round(
            sm.coalesce_factor, 2)
        out[f"serve_{n_prod}p_ticks"] = sm.ticks
        out[f"serve_{n_prod}p_admission_p95_us"] = round(
            sm.admission_p95_s * 1e6, 1)
        out[f"serve_{n_prod}p_forced_syncs"] = ms.forced_syncs
        log(f"serve[{n_prod}p]: {n_batches} batches in {wall:.3f}s "
            f"({rate:.0f} rows/s, {out[f'serve_{n_prod}p_vs_bare_x']}x "
            f"bare; coalesce {sm.coalesce_factor:.2f} over {sm.ticks} "
            f"ticks; forced_syncs={ms.forced_syncs})")
    # the acceptance pair: heavy concurrency must actually coalesce, and
    # the pump must never have forced a mid-stream sync
    out["coalesce_gt_1_at_16p"] = out["serve_16p_coalesce_factor"] > 1.0
    out["zero_forced_syncs"] = all(
        out[f"serve_{n}p_forced_syncs"] == 0 for n in (1, 4, 16))
    from reflow_tpu import obs
    if obs.enabled():
        # REFLOW_TRACE=1 at bench time: export what the run recorded
        out["trace_file"] = obs.export_chrome_trace()
        log(f"serve: chrome trace -> {out['trace_file']}")
    return out


# -- obs / tracing-overhead mode (REFLOW_BENCH_OBS=1) ----------------------

def run_obs_bench() -> dict:
    """Observability-overhead numbers (docs/guide.md "Observability"):
    the 16-producer serve protocol from ``run_serve_bench`` driven over
    a ``DurableScheduler`` (``fsync="record"``, so the per-ticket fsync
    stage is real work), run twice — obs fully disabled, then with
    tracing enabled plus a live ``MetricsRegistry`` and a fast-interval
    ``SnapshotEmitter``. Reports the throughput overhead fraction
    (acceptance: <3% enabled, <1% merely importable), exports the
    chrome trace, and checks the per-ticket stage decomposition: each
    sampled ticket's six stage durations must sum to within 10% of its
    measured end-to-end latency.

    Host-side CPU work; no tunnel protocol applies.
    """
    import shutil
    import tempfile
    import threading

    from reflow_tpu import obs
    from reflow_tpu.serve import CoalesceWindow, IngestFrontend
    from reflow_tpu.wal import DurableScheduler
    from reflow_tpu.workloads import wordcount

    smoke = env_flag("REFLOW_BENCH_SMOKE")
    per_producer = env_int("REFLOW_BENCH_OBS_BATCHES", "40" if smoke else "250")
    rows_per_batch = 8
    n_prod = 16

    def make_lines(producer: int, j: int) -> list:
        rng = np.random.default_rng(producer * 100_003 + j)
        return [" ".join(f"w{int(x)}"
                         for x in rng.integers(0, 1000, rows_per_batch))]

    def run_once(wal_dir: str, registry=None) -> float:
        g, src, _sink = wordcount.build_graph()
        sched = DurableScheduler(g, wal_dir=wal_dir, fsync="record")
        fe = IngestFrontend(sched, window=CoalesceWindow(
            max_rows=4096, max_ticks=8, max_latency_s=0.005))
        if registry is not None:
            fe.publish_metrics(registry)
            sched.publish_metrics(registry)
            sched.wal.publish_metrics(registry)
        tickets = []
        tk_lock = threading.Lock()

        def produce(pid, fe=fe, src=src):
            mine = [fe.submit(src, wordcount.ingest_lines(
                make_lines(pid, j))) for j in range(per_producer)]
            with tk_lock:
                tickets.extend(mine)

        threads = [threading.Thread(target=produce, args=(pid,))
                   for pid in range(n_prod)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        fe.flush()
        wall = time.perf_counter() - t0
        assert all(t.result(timeout=30).applied for t in tickets)
        fe.close()
        sched.wal.close()
        return n_prod * per_producer * rows_per_batch / wall

    out = {"per_producer_batches": per_producer,
           "rows_per_batch": rows_per_batch, "producers": n_prod}
    tmp = tempfile.mkdtemp(prefix="reflow-obs-bench-")
    try:
        obs.disable()
        obs.trace.reset()
        rate_off = run_once(os.path.join(tmp, "wal-off"))
        out["disabled_rows_per_s"] = round(rate_off)
        log(f"obs[off]: {rate_off:.0f} rows/s")

        obs.trace.reset()
        obs.enable()
        reg = obs.MetricsRegistry()
        snap_path = os.path.join(tmp, "snapshots.jsonl")
        emitter = obs.SnapshotEmitter(snap_path, interval_s=0.2,
                                      registry=reg)
        emitter.start()
        try:
            rate_on = run_once(os.path.join(tmp, "wal-on"), registry=reg)
        finally:
            emitter.stop()
            obs.disable()
        out["enabled_rows_per_s"] = round(rate_on)
        overhead = 1.0 - rate_on / rate_off
        out["obs_overhead_frac"] = round(overhead, 4)
        out["obs_overhead_lt_3pct"] = overhead < 0.03
        log(f"obs[on]: {rate_on:.0f} rows/s "
            f"(overhead {100 * overhead:.2f}%)")

        with open(snap_path) as f:
            snaps = [json.loads(ln) for ln in f if ln.strip()]
        out["snapshot_lines"] = len(snaps)
        out["snapshot_schema_ok"] = bool(snaps) and all(
            s.get("schema") == obs.SNAPSHOT_SCHEMA for s in snaps)

        # export + decomposition check on the enabled run's rings
        events = obs.chrome_events()
        trace_path = env_str("REFLOW_TRACE_OUT", "/tmp/reflow_obs_trace.json")
        obs.export_chrome_trace(trace_path)
        out["trace_file"] = trace_path
        out["trace_events"] = sum(1 for e in events if e.get("ph") == "X")
        timelines = obs.ticket_timelines(events)
        out["sampled_tickets"] = len(timelines)
        max_dev = 0.0
        for t in timelines.values():
            if t["e2e_us"] > 0:
                max_dev = max(max_dev, abs(t["sum_us"] - t["e2e_us"])
                              / t["e2e_us"])
        out["decomposition_max_dev_frac"] = round(max_dev, 4)
        out["decomposition_ok"] = bool(timelines) and max_dev <= 0.10
        log(f"obs: {out['trace_events']} spans, "
            f"{len(timelines)} sampled tickets, stage-sum deviation max "
            f"{100 * max_dev:.2f}% -> {trace_path}")
        obs.trace.reset()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return out


# -- walpipe / asynchronous-durability mode (REFLOW_BENCH_WALPIPE=1) -------

def run_walpipe_bench() -> dict:
    """Durability-pipeline numbers (docs/guide.md "Durability pipeline"):
    the serve protocol over a ``DurableScheduler`` with
    ``fsync="record"`` — every window's WAL barrier must reach the disk
    before its tickets resolve — comparing ``committer="inline"`` (the
    pre-pipeline behavior: frame+write+fsync all on the pump, on the
    dispatch path) against ``committer="thread"`` (the pump only
    pickles and enqueues; a dedicated committer frames, writes and
    fsyncs while the pump merges and dispatches the next window,
    tickets resolving at the durable watermark via ``when_durable``).

    The workload is the streaming ingest path end to end: 16 producers
    submit **device-resident** 8192-row batches of ``(64,)``-vector
    values with ingest-time pre-images (``submit(..., preimage=host)``)
    into a sum-reduce graph on a real device executor; every batch
    fills one coalescing window, so each window is one ~2 MB WAL group
    commit + one fsync. Payloads are pre-generated and pre-uploaded —
    the timed region contains only submit/merge/dispatch/durability.

    Property checks ride along:

    - **zero-readback logging** — ``DurableScheduler.log_readbacks``
      stays 0 on every leg (no forced materialize on the logging path);
    - **committed evidence** — every pipelined ticket resolves with its
      covering LSN;
    - **view equality** — inline and pipelined legs reach the same sink
      view (pipelining changed the *when* of durability, not the math);
    - **replay equality** — the pipelined 16-producer log replays
      through ``recover()`` into a fresh host scheduler that reaches
      the same sink view (durability was never traded for throughput).

    Host-side CPU work; runs on the CPU executor/platform so no tunnel
    protocol applies."""
    import shutil
    import tempfile
    import threading

    from reflow_tpu import FlowGraph
    from reflow_tpu.delta import DeltaBatch, Spec
    from reflow_tpu.executors import get_executor
    from reflow_tpu.executors.device_delta import to_device
    from reflow_tpu.scheduler import DirtyScheduler
    from reflow_tpu.serve import CoalesceWindow, IngestFrontend
    from reflow_tpu.wal import DurableScheduler, recover

    smoke = env_flag("REFLOW_BENCH_SMOKE")
    key_space, feat = 64, 64
    rows = 8192  # one batch == one window == one ~2 MB group commit

    def build():
        spec = Spec((feat,), np.float32, key_space=key_space)
        g = FlowGraph()
        src = g.source("in", spec)
        total = g.reduce(g.map(src, lambda v: v * 2.0, vectorized=True),
                         "sum", name="sum")
        sink = g.sink(total, "out")
        return g, src, sink, spec

    def pregen(spec, n_prod, per_prod):
        # pre-generated + pre-uploaded: data creation never pollutes the
        # timed region, and both committer legs replay identical bytes
        payloads = {}
        for pid in range(n_prod):
            rng = np.random.default_rng(1000 + pid)
            payloads[pid] = []
            for j in range(per_prod):
                host = DeltaBatch(
                    rng.integers(0, key_space, rows).astype(np.int64),
                    rng.random((rows, feat)).astype(np.float32),
                    np.ones(rows, np.int64))
                payloads[pid].append(
                    (f"p{pid}-{j}", host, to_device(host, spec)))
        return payloads

    def views_equal(a, b):
        # sink views are row multisets keyed by (key, value-tuple);
        # device and host float32 sums differ in the last ulp, so
        # compare per-key aggregates with tolerance instead of exact
        # row identity
        def as_map(view):
            m = {}
            for (k, v), w in view.items():
                if w:
                    m[int(k)] = np.asarray(v)
            return m

        ma, mb = as_map(a), as_map(b)
        return (set(ma) == set(mb)
                and all(np.allclose(ma[k], mb[k], rtol=1e-3, atol=1e-4)
                        for k in ma))

    def run_once(wal_dir, committer, payloads, n_prod, per_prod, spec):
        g, src, sink, _ = build()
        sched = DurableScheduler(g, get_executor("tpu"), wal_dir=wal_dir,
                                 fsync="record", committer=committer)
        fe = IngestFrontend(sched, window=CoalesceWindow(
            max_rows=rows, max_ticks=1, max_latency_s=0.001))
        # warmup window outside the timed region compiles the jit path;
        # os.sync() flushes unrelated dirty pages so the timed fsyncs
        # pay only for their own bytes
        warm = DeltaBatch(np.zeros(4, np.int64),
                          np.zeros((4, feat), np.float32),
                          np.ones(4, np.int64))
        fe.submit(src, to_device(warm, spec), batch_id="warm",
                  preimage=warm).result(timeout=60)
        os.sync()
        tickets, tk_lock = [], threading.Lock()

        def produce(pid):
            mine = [fe.submit(src, dev, batch_id=bid, preimage=host)
                    for bid, host, dev in payloads[pid]]
            with tk_lock:
                tickets.extend(mine)

        threads = [threading.Thread(target=produce, args=(pid,))
                   for pid in range(n_prod)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        fe.flush()
        results = [t.result(timeout=120) for t in tickets]
        wall = time.perf_counter() - t0
        assert all(r.applied for r in results)
        rate = n_prod * per_prod * rows / wall
        view = dict(sched.view(sink))
        fsyncs = sched.wal.fsyncs
        readbacks = sched.log_readbacks
        fe.close()
        return rate, view, fsyncs, readbacks, results

    # (n_producers, batches_per_producer, paired trials): the 16p point
    # is the acceptance number, so it gets best-of-N paired trials to
    # shave ext4 writeback noise; smoke keeps the same window shape
    # (the speedup comes from the shape) but trims the run
    per16 = env_int("REFLOW_BENCH_WALPIPE_BATCHES", "2" if smoke else "4")
    legs = [(16, per16, 1 if smoke else 2)]
    if not smoke:
        legs.insert(0, (4, 8, 1))
        legs.insert(0, (1, 16, 1))

    out = {"rows_per_batch": rows, "value_shape": [feat],
           "key_space": key_space, "fsync": "record"}
    tmp = tempfile.mkdtemp(prefix="reflow-walpipe-")
    all_zero_readbacks = True
    try:
        pipelined_dir_16p = None
        view_16p = None
        for n_prod, per_prod, trials in legs:
            spec = build()[3]
            payloads = pregen(spec, n_prod, per_prod)
            best = None
            for trial in range(trials):
                rates, views = {}, {}
                for committer in ("inline", "thread"):
                    wal_dir = os.path.join(
                        tmp, f"{committer}-{n_prod}p-{trial}")
                    rate, view, fsyncs, readbacks, results = run_once(
                        wal_dir, committer, payloads, n_prod, per_prod,
                        spec)
                    rates[committer] = rate
                    views[committer] = view
                    all_zero_readbacks &= readbacks == 0
                    assert readbacks == 0  # pre-imaged: no materialize
                    if committer == "thread":
                        # pipelined resolution still carries the commit
                        # evidence: every APPLIED ticket names its LSN
                        assert all(r.lsn for r in results)
                    if committer == "thread" and n_prod == 16:
                        if pipelined_dir_16p is not None:
                            shutil.rmtree(pipelined_dir_16p,
                                          ignore_errors=True)
                        pipelined_dir_16p = wal_dir
                        view_16p = view
                    else:
                        # drop the leg's WAL right away: ~136 MB of
                        # stale log per leg left on the bench disk
                        # perturbs the next leg's fsync latencies
                        shutil.rmtree(wal_dir, ignore_errors=True)
                    tag = ("pipelined" if committer == "thread"
                           else "inline")
                    out[f"walpipe_{n_prod}p_{tag}_rows_per_s"] = round(
                        rate)
                    out[f"walpipe_{n_prod}p_{tag}_fsyncs"] = fsyncs
                    log(f"walpipe[{n_prod}p/{tag}#{trial}]: "
                        f"{rate:.0f} rows/s ({fsyncs} fsyncs)")
                assert views_equal(views["inline"], views["thread"])
                sp = rates["thread"] / rates["inline"]
                if best is None or sp > best:
                    best = sp
            out[f"walpipe_speedup_{n_prod}p"] = round(best, 3)
        out["pipelined_ge_inline"] = out["walpipe_speedup_16p"] >= 1.0
        out["zero_materialize_readbacks"] = all_zero_readbacks

        # replay equality: the pipelined 16p log (host pre-images of
        # every device batch) drives a fresh host scheduler to the same
        # sink view
        g, _src, sink, _spec = build()
        fresh = DirtyScheduler(g)
        report = recover(fresh, pipelined_dir_16p)
        out["replayed_pushes"] = report.replayed_pushes
        out["replay_view_matches"] = views_equal(
            dict(fresh.view(sink)), view_16p)
        log(f"walpipe[replay]: {report.replayed_pushes} pushes, "
            f"matches={out['replay_view_matches']}")
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return out


# -- WAL shipping / read-replica mode (REFLOW_BENCH_REPLICA=1) -------------

def run_replica_bench() -> dict:
    """Read-replica scaling (docs/guide.md "Read replicas"): a
    wordcount leader (``DurableScheduler`` + ``IngestFrontend``) under
    sustained 16-producer writes, with a ``SegmentShipper`` streaming
    its synced WAL prefix to N ``ReplicaScheduler`` followers and a
    ``ReadTier`` fanning top-k reads across them.

    Two read legs run back to back under the SAME write load:

    - **leader baseline**: 4 reader threads on the
      ``LeaderReadAdapter`` — every read copies the live, mutable sink
      view under one lock (the leader's views have no other consistent
      read point), then ranks in Python;
    - **replica aggregate**: the same 4 reader threads through the
      ``ReadTier`` — each replica serves immutable per-horizon snapshot
      arrays, so the hot path is a lock-free ``np.argpartition``.

    Property checks ride along:

    - **exact parity** — after quiesce (flush + sync + catch-up) every
      replica's view at the published horizon equals the leader's with
      ``max_abs_diff == 0`` (replicas replay the same WAL bytes through
      the same idempotent machinery; there is nothing to be off by);
    - **bounded lag** — final replica lag is 0 ticks and never exceeded
      one commit window (``window_ticks``) at any sampled steady-state
      point except transient shipping bursts (max sampled lag is
      reported);
    - **read-your-writes** — a writer that observed its tick can read
      it back through the tier at ``min_horizon=`` without error.

    Host-side CPU work; runs on the CPU executor/platform."""
    import shutil
    import tempfile
    import threading

    from reflow_tpu.obs import REGISTRY
    from reflow_tpu.serve import (CoalesceWindow, IngestFrontend,
                                  LeaderReadAdapter, ReadTier,
                                  ReplicaScheduler)
    from reflow_tpu.wal import DurableScheduler, SegmentShipper
    from reflow_tpu.workloads import wordcount

    smoke = env_flag("REFLOW_BENCH_SMOKE")
    n_replicas = env_int("REFLOW_BENCH_REPLICA_N", "4")
    n_producers = 16
    n_readers = 4
    window_ticks = 4
    vocab = 2_000 if smoke else 20_000
    read_s = env_float("REFLOW_BENCH_REPLICA_READ_S", "0.6" if smoke else "2.0")
    topk = 10

    tmp = tempfile.mkdtemp(prefix="reflow-replica-")
    out = {"replicas": n_replicas, "producers": n_producers,
           "readers": n_readers, "window_ticks": window_ticks,
           "read_s": read_s, "vocab": vocab}
    fe = ship = None
    replicas = []
    try:
        g, src, sink = wordcount.build_graph()
        sched = DurableScheduler(g, wal_dir=os.path.join(tmp, "wal"),
                                 fsync="tick", committer="thread",
                                 segment_bytes=1 << 20)
        fe = IngestFrontend(sched, window=CoalesceWindow(
            max_rows=65536, max_ticks=window_ticks, max_latency_s=0.002))
        ship = SegmentShipper(sched.wal, leader_tick=lambda: sched._tick,
                              poll_s=0.001)
        for i in range(n_replicas):
            gr, _s, _k = wordcount.build_graph()
            r = ReplicaScheduler(gr, os.path.join(tmp, f"r{i}"),
                                 name=f"r{i}")
            ship.attach(r)
            r.publish_metrics()
            replicas.append(r)
        leader = LeaderReadAdapter(sched)
        tier = ReadTier(replicas, leader=leader)
        ship.publish_metrics()
        tier.publish_metrics()
        ship.start()

        # -- sustained 16-producer writes for the whole measured region
        stop = threading.Event()
        submitted = [0] * n_producers

        def produce(pid):
            rng = np.random.default_rng(1000 + pid)
            seq = 0
            while not stop.is_set():
                words = " ".join(
                    f"w{int(x)}" for x in rng.integers(0, vocab, 24))
                try:
                    fe.submit(src, wordcount.ingest_lines([words]),
                              batch_id=f"p{pid}-{seq}")
                except Exception:
                    break
                seq += 1
            submitted[pid] = seq

        producers = [threading.Thread(target=produce, args=(pid,))
                     for pid in range(n_producers)]
        for t in producers:
            t.start()

        lag_samples: list = []
        lag_stop = threading.Event()

        def sample_lag():
            while not lag_stop.is_set():
                lag_samples.append(max(r.lag_ticks() for r in replicas))
                lag_stop.wait(0.02)

        lag_thread = threading.Thread(target=sample_lag)
        lag_thread.start()
        time.sleep(0.5)  # build up a real view before measuring reads

        def read_qps(fn) -> float:
            counts = [0] * n_readers

            def reader(i):
                end = time.perf_counter() + read_s
                c = 0
                while time.perf_counter() < end:
                    fn()
                    c += 1
                counts[i] = c

            threads = [threading.Thread(target=reader, args=(i,))
                       for i in range(n_readers)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            return sum(counts) / read_s

        # warm both read paths before measuring (first replica reads
        # pay one-off snapshot builds; first leader read pays the view
        # copy's allocator warmup) so short smoke legs compare steady
        # states, not cold starts
        for _ in range(8):
            leader.top_k(sink.name, topk, by="value")
            tier.top_k(sink.name, topk, by="value")

        leader_qps = read_qps(
            lambda: leader.top_k(sink.name, topk, by="value"))
        log(f"replica[leader-baseline]: {leader_qps:.0f} reads/s "
            f"under {n_producers}p writes")
        replica_qps = read_qps(
            lambda: tier.top_k(sink.name, topk, by="value"))
        log(f"replica[{n_replicas}-replica tier]: {replica_qps:.0f} "
            f"reads/s under {n_producers}p writes")

        # read-your-writes: a writer that saw its window land can pin
        # the tier to at least that horizon
        fe.submit(src, wordcount.ingest_lines(["ryw probe words"]),
                  batch_id="ryw-1").result(timeout=60)
        h = sched._tick
        res = tier.top_k(sink.name, topk, min_horizon=h, by="value")
        out["ryw_min_horizon"] = h
        out["ryw_horizon"] = res.horizon
        out["ryw_source"] = res.source
        assert res.horizon >= h

        # -- quiesce: stop writers, land everything, let replicas catch up
        stop.set()
        for t in producers:
            t.join()
        fe.flush()
        sched.wal.sync()
        deadline = time.monotonic() + 60
        while (any(r.published_horizon() != sched._tick
                   for r in replicas)
               and time.monotonic() < deadline):
            time.sleep(0.005)
        lag_stop.set()
        lag_thread.join()
        ship.stop()
        ship.pump_once()  # final deterministic pass (thread is down)

        final_lag = max(r.lag_ticks() for r in replicas)
        out["final_lag_ticks"] = final_lag
        out["max_sampled_lag_ticks"] = max(lag_samples, default=0)
        out["lag_bound_ok"] = final_lag <= window_ticks
        assert all(r.published_horizon() == sched._tick
                   for r in replicas), \
            (sched._tick, [r.published_horizon() for r in replicas])

        # -- exact parity at the shared horizon
        leader_view = {kv: w for kv, w in sched.view(sink.name).items()
                       if w != 0}
        max_abs_diff = 0
        for r in replicas:
            rh, rv = r.view_at(sink.name)
            assert rh == sched._tick, (r.name, rh, sched._tick)
            for kv in set(leader_view) | set(rv):
                max_abs_diff = max(
                    max_abs_diff,
                    abs(leader_view.get(kv, 0) - rv.get(kv, 0)))
        out["parity_max_abs_diff"] = max_abs_diff
        assert max_abs_diff == 0

        out["total_batches"] = sum(submitted)
        out["leader_ticks"] = sched._tick
        out["leader_read_qps"] = round(leader_qps, 1)
        out["replica_read_qps"] = round(replica_qps, 1)
        out["read_scaling_x"] = round(replica_qps / leader_qps, 3) \
            if leader_qps else 0.0
        out["ship_bytes_total"] = ship.bytes_total
        out["ship_nacks"] = ship.nacks
        out["ship_backlog_segments"] = ship.backlog_segments()
        out["lag_gauge"] = REGISTRY.value("replica.lag_ticks", -1)
        log(f"replica[scaling]: {out['read_scaling_x']}x "
            f"({n_replicas} replicas vs leader), parity diff "
            f"{max_abs_diff}, final lag {final_lag} tick(s), "
            f"{ship.bytes_total} bytes shipped, {ship.nacks} nacks")
    finally:
        if fe is not None:
            fe.close()
        if ship is not None:
            ship.close()
        for r in replicas:
            r.close()
        shutil.rmtree(tmp, ignore_errors=True)
    return out


# -- reactive-reads mode (REFLOW_BENCH_SUBS=1) ------------------------------


def _subs_query_pool(sink_name: str, vocab: int, n: int) -> list:
    """``n`` distinct standing queries mixing the three kinds. Lookups
    dominate (they are what 100k real subscribers look like: each
    watching its own key); topk/view ride along so every fan-out round
    exercises the expensive paths too. The hub keys fan-out state by
    *distinct* query, so subscriber count and query diversity are
    independent axes — the bench stresses both."""
    pool = []
    for i in range(n):
        m = i % 8
        if m < 5:
            pool.append((sink_name, "lookup", ((f"w{i % vocab}", 1.0),)))
        elif m < 7:
            pool.append((sink_name, "topk", (5 + 5 * (m - 4), "weight")))
        else:
            pool.append((sink_name, "view", ()))
    return pool


def run_subs_bench() -> dict:
    """Reactive reads (docs/guide.md "Reactive reads"): one replica's
    :class:`~reflow_tpu.subs.hub.SubscriptionHub` fanning per-window
    deltas to ``REFLOW_BENCH_SUBS_N`` simulated subscribers (in-process
    :class:`SubHandle`\\ s — the same state machine the wire client
    wraps) while 16 producers write through the durable leader.

    Two identically-loaded write legs run back to back:

    - **baseline**: leader + shipper + replica, no hub — the write
      path's admission p99 with nobody watching;
    - **subs**: the same topology with the hub attached, N in-process
      subscribers standing on a mixed query pool, and a few real wire
      subscribers over loopback that live through a mid-run
      partition + heal of their endpoint.

    Property checks, each a hard assert:

    - **push == pull**: sampled subscribers' delta-reconstructed
      answers equal ``view_at``/``lookup``/top-k at the same horizon
      with ``max_abs_diff == 0``, and reach it with zero gaps and zero
      duplicate applies;
    - **partition/heal**: every wire subscriber resumes (``mode ==
      "resume"`` — cursor, not re-snapshot) with ``gaps_total == 0``
      and ``dups_skipped_total == 0``;
    - **write path immune**: the subs leg's admission p99 stays within
      2x the no-subscriber baseline (plus a small absolute floor so a
      sub-millisecond baseline doesn't turn timer noise into a fail).

    Host-side CPU work; runs on the CPU executor/platform."""
    import shutil
    import tempfile
    import threading

    from reflow_tpu.net import LoopbackTransport, ReconnectPolicy
    from reflow_tpu.serve import (CoalesceWindow, IngestFrontend,
                                  ReplicaScheduler)
    from reflow_tpu.subs import (Subscriber, SubscriptionHub,
                                 SubscriptionServer)
    from reflow_tpu.subs.query import topk_rows
    from reflow_tpu.wal import DurableScheduler, SegmentShipper
    from reflow_tpu.workloads import wordcount

    smoke = env_flag("REFLOW_BENCH_SMOKE")
    n_subs = env_int("REFLOW_BENCH_SUBS_N") or (2_000 if smoke
                                                else 100_000)
    run_s = env_float("REFLOW_BENCH_SUBS_RUN_S") or (0.6 if smoke
                                                     else 2.0)
    n_producers = 16
    n_wire = 3
    window_ticks = 4
    vocab = 2_000 if smoke else 20_000
    n_distinct = min(n_subs, 64 if smoke else 512)
    n_sampled = min(n_subs, 32)

    out = {"subscribers": n_subs, "distinct_queries": n_distinct,
           "wire_subscribers": n_wire, "producers": n_producers,
           "run_s": run_s, "vocab": vocab}

    def write_leg(tag: str, with_subs: bool) -> dict:
        tmp = tempfile.mkdtemp(prefix=f"reflow-subs-{tag}-")
        fe = ship = rep = hub = srv = srv2 = None
        wire_subs = []
        pumpers = []
        pump_stop = threading.Event()
        leg = {}
        try:
            g, src, sink = wordcount.build_graph()
            sched = DurableScheduler(g, wal_dir=os.path.join(tmp, "wal"),
                                     fsync="tick", committer="thread",
                                     segment_bytes=1 << 20)
            fe = IngestFrontend(sched, window=CoalesceWindow(
                max_rows=65536, max_ticks=window_ticks,
                max_latency_s=0.002))
            ship = SegmentShipper(sched.wal,
                                  leader_tick=lambda: sched._tick,
                                  poll_s=0.001)
            gr, _s, _k = wordcount.build_graph()
            rep = ReplicaScheduler(gr, os.path.join(tmp, "r0"),
                                   name="r0")
            ship.attach(rep)
            ship.start()

            handles = []
            sampled = []
            pool = _subs_query_pool(sink.name, vocab, n_distinct)
            if with_subs:
                hub = SubscriptionHub(rep, name="r0")
                rep.attach_hub(hub)
                t0 = time.perf_counter()
                for i in range(n_subs):
                    q = pool[i % len(pool)]
                    handles.append((hub.open(q[0], q[1], q[2]), q))
                leg["open_s"] = round(time.perf_counter() - t0, 3)
                step = max(1, n_subs // n_sampled)
                sampled = handles[::step][:n_sampled]
                lt = LoopbackTransport()
                srv = SubscriptionServer(hub, lt).start()
                for i in range(n_wire):
                    q = pool[i % len(pool)]
                    wire_subs.append(Subscriber(
                        lt, srv.address, q[0], kind=q[1], params=q[2],
                        name=f"bench-wire-{i}",
                        policy=ReconnectPolicy(f"bench-wire-{i}",
                                               base_s=0.01, cap_s=0.05,
                                               jitter=0.0)))

                def pump_forever(sub):
                    # never raises while the link is down — the whole
                    # point of the partition leg
                    while not pump_stop.is_set():
                        sub.pump(wait_s=0.05)

                pumpers = [threading.Thread(target=pump_forever,
                                            args=(s,))
                           for s in wire_subs]
                for t in pumpers:
                    t.start()

            # -- sustained 16-producer writes for the measured window
            stop = threading.Event()
            submitted = [0] * n_producers

            def produce(pid):
                rng = np.random.default_rng(1000 + pid)
                seq = 0
                while not stop.is_set():
                    words = " ".join(
                        f"w{int(x)}" for x in rng.integers(0, vocab, 24))
                    try:
                        fe.submit(src, wordcount.ingest_lines([words]),
                                  batch_id=f"p{pid}-{seq}")
                    except Exception:
                        break
                    seq += 1
                submitted[pid] = seq

            producers = [threading.Thread(target=produce, args=(pid,))
                         for pid in range(n_producers)]
            for t in producers:
                t.start()

            if with_subs:
                # partition the subscription endpoint mid-run, heal it
                # while writes are still flowing — the resume contract
                # has to hold under load, not at quiesce
                time.sleep(run_s * 0.5)
                srv.close()
                time.sleep(run_s * 0.25)
                srv2 = SubscriptionServer(hub, lt).start()
                for s in wire_subs:
                    s.retarget(srv2.address)
                time.sleep(run_s * 0.25)
            else:
                time.sleep(run_s)

            # -- quiesce: land everything, replica catches up
            stop.set()
            for t in producers:
                t.join()
            p99 = float(np.percentile(list(fe.admission_s), 99)) \
                if fe.admission_s else 0.0
            fe.flush()
            sched.wal.sync()
            deadline = time.monotonic() + 60
            while (rep.published_horizon() != sched._tick
                   and time.monotonic() < deadline):
                time.sleep(0.005)
            ship.stop()
            ship.pump_once()
            assert rep.published_horizon() == sched._tick, \
                (rep.published_horizon(), sched._tick)
            horizon = sched._tick
            leg["admission_p99_us"] = round(p99 * 1e6, 1)
            leg["total_batches"] = sum(submitted)
            leg["leader_ticks"] = horizon

            if with_subs:
                # fan-out settles to the replica's published horizon
                deadline = time.monotonic() + 30
                while (hub.fanout_horizon < horizon
                       and time.monotonic() < deadline):
                    time.sleep(0.005)
                assert hub.fanout_horizon == horizon, \
                    (hub.fanout_horizon, horizon)

                # push == pull, zero gaps, zero duplicate applies
                view = rep.view_at(sink.name)[1]
                max_abs_diff = 0.0
                gaps = dups = 0
                for h, q in sampled:
                    assert h.wait_horizon(horizon, timeout_s=10.0), \
                        (q, h.horizon, horizon)
                    got = h.value()
                    if q[1] == "view":
                        for kv in set(got) | set(view):
                            max_abs_diff = max(
                                max_abs_diff,
                                abs(got.get(kv, 0) - view.get(kv, 0)))
                    elif q[1] == "lookup":
                        max_abs_diff = max(
                            max_abs_diff,
                            abs(got - view.get(q[2][0], 0)))
                    else:
                        k, by = q[2]
                        assert got == topk_rows(view, k, by), (q, got)
                    gaps += h.state.gaps
                    dups += h.state.dups_skipped
                assert max_abs_diff == 0, max_abs_diff
                assert gaps == 0 and dups == 0, (gaps, dups)

                # wire subscribers: gap-free, dup-free resume through
                # the partition/heal
                pump_stop.set()
                for t in pumpers:
                    t.join()
                for s in wire_subs:
                    deadline = time.monotonic() + 10
                    while (s.horizon < horizon
                           and time.monotonic() < deadline):
                        s.pump(wait_s=0.05)
                    assert s.horizon >= horizon, (s.name, s.horizon,
                                                  horizon)
                    assert s.mode == "resume", (s.name, s.mode)
                    assert s.gaps_total == 0, s.name
                    assert s.dups_skipped_total == 0, s.name
                    assert s.reconnects_total >= 1, s.name
                    if s.query.kind == "view":
                        assert s.value() == view
                    elif s.query.kind == "lookup":
                        assert s.value() == view.get(s.query.params[0],
                                                     0)
                    else:
                        k, by = s.query.params
                        assert s.value() == topk_rows(view, k, by)

                leg["sampled_subscribers"] = len(sampled)
                leg["parity_max_abs_diff"] = max_abs_diff
                leg["frames_total"] = hub.frames_total
                leg["fanout_rows_total"] = hub.fanout_rows_total
                leg["fanout_rows_per_s"] = round(
                    hub.fanout_rows_total / run_s, 1)
                leg["conflations_total"] = hub.conflations_total
                leg["sheds_total"] = hub.sheds_total
                leg["active_subs"] = hub.active_subs()
                leg["slowest_lag"] = hub.slowest_lag()
                leg["wire_reconnects"] = sum(s.reconnects_total
                                             for s in wire_subs)
        finally:
            pump_stop.set()
            for t in pumpers:
                t.join(timeout=5.0)
            for s in wire_subs:
                s.close()
            for s in (srv, srv2):
                if s is not None:
                    s.close()
            if hub is not None:
                hub.close()
            if fe is not None:
                fe.close()
            if ship is not None:
                ship.close()
            if rep is not None:
                rep.close()
            shutil.rmtree(tmp, ignore_errors=True)
        return leg

    base = write_leg("base", with_subs=False)
    log(f"subs[baseline]: admission p99 "
        f"{base['admission_p99_us']:.0f}us, "
        f"{base['total_batches']} batches, no subscribers")
    subs = write_leg("subs", with_subs=True)
    log(f"subs[{n_subs}-subscriber leg]: admission p99 "
        f"{subs['admission_p99_us']:.0f}us, "
        f"{subs['total_batches']} batches, "
        f"{subs['fanout_rows_per_s']} fan-out rows/s, "
        f"{subs['conflations_total']} conflations, "
        f"{subs['sheds_total']} sheds, parity diff "
        f"{subs['parity_max_abs_diff']}, "
        f"{subs['wire_reconnects']} wire reconnects")

    p99_base = base["admission_p99_us"]
    p99_subs = subs["admission_p99_us"]
    out["baseline"] = base
    out["subs"] = subs
    out["write_p99_overhead_x"] = round(p99_subs / p99_base, 3) \
        if p99_base else 0.0
    # the bound: 2x the baseline, with an absolute floor so a
    # microsecond-scale baseline doesn't turn scheduler jitter into a
    # spurious fail on a loaded host
    bound_us = max(2.0 * p99_base, p99_base + 5_000.0)
    out["write_p99_bound_us"] = round(bound_us, 1)
    out["write_p99_bounded"] = p99_subs <= bound_us
    assert p99_subs <= bound_us, (p99_subs, bound_us)
    log(f"subs[overhead]: write p99 {out['write_p99_overhead_x']}x "
        f"baseline (bounded={out['write_p99_bounded']})")
    return out


# -- bounded-history mode (REFLOW_BENCH_COMPACT=1) -------------------------

def run_compact_bench() -> dict:
    """Bounded history (docs/guide.md "Bounded history"): incremental
    checkpoint chains + key-level WAL compaction must buy O(state)
    recovery and fast replica bootstrap without giving up a byte of
    exactly-once.

    Two identically-fed legs run back to back — 16 producer threads
    each submit a fixed, deterministic batch stream (every odd batch
    retracts its predecessor, so live state stays tiny while history
    grows without bound) through an ``IngestFrontend`` into a durable
    wordcount leader:

    - **unbounded oracle**: no checkpoints, no compaction — the WAL
      keeps the full history (the "before" condition);
    - **bounded**: a ``CheckpointChain`` element every ``save_every``
      leader ticks (full every ``delta_every``-th save, lag-one WAL
      truncation) with a ``WalCompactor`` folding the sealed replay
      tail between saves.

    Then four cold starts are timed:

    1. leader crash-recovery by full-history replay (oracle WAL);
    2. leader crash-recovery from {chain + compacted tail};
    3. fresh-replica bootstrap streaming the full oracle WAL;
    4. fresh-replica bootstrap from {chain + compacted tail};

    plus the floor everything is measured against: restoring a fresh
    full checkpoint of the final state (the O(state) lower bound).

    Acceptance: WAL history >= 10x live-state bytes; (2) and (4) each
    >= 5x faster than their full-history twin AND within 2x (+ a fixed
    50ms epsilon for fsync/transport constants) of the fresh-full
    floor; EXACT view parity (max_abs_diff == 0) between every
    recovered/bootstrapped view and its leg's leader view, and between
    the two legs' quiesced final views (identical batch multiset ->
    identical fold); zero acked-write loss; the reclaimable-bytes gauge
    settles near zero after the final pass (bounded footprint).

    Host-side CPU work; runs on the CPU executor/platform."""
    import shutil
    import tempfile
    import threading

    from reflow_tpu.obs import MetricsRegistry
    from reflow_tpu.scheduler import DirtyScheduler
    from reflow_tpu.serve import (CoalesceWindow, IngestFrontend,
                                  ReplicaScheduler)
    from reflow_tpu.utils.checkpoint import (CheckpointChain,
                                             load_checkpoint,
                                             save_checkpoint)
    from reflow_tpu.wal import (DurableScheduler, SegmentShipper,
                                WalCompactor, recover)
    from reflow_tpu.workloads import wordcount

    smoke = env_flag("REFLOW_BENCH_SMOKE")
    per_prod = env_int("REFLOW_BENCH_COMPACT_TICKS") \
        or (160 if smoke else 480)
    n_producers = 16
    vocab = 300
    save_every = 24          # leader ticks between chain elements
    delta_every = 6          # full checkpoint every 6th element
    eps_s = 0.05             # fixed epsilon on the within-2x floors
    out = {"producers": n_producers, "per_producer_batches": per_prod,
           "vocab": vocab, "save_every": save_every,
           "delta_every": delta_every}

    def words_for(pid, seq):
        rng = np.random.default_rng(pid * 100_000 + seq)
        return " ".join(f"w{int(x)}" for x in rng.integers(0, vocab, 24))

    def batch_for(pid, seq):
        if seq % 2 == 1:
            # retract the predecessor: live state stays O(recent),
            # history keeps both records — the compactor's whole case
            return wordcount.ingest_lines([words_for(pid, seq - 1)],
                                          weight=-1)
        return wordcount.ingest_lines([words_for(pid, seq)])

    def du(path):
        total = 0
        for base, _dirs, files in os.walk(path):
            for f in files:
                total += os.path.getsize(os.path.join(base, f))
        return total

    def run_leg(tmp, bounded):
        wal_dir = os.path.join(tmp, "wal-bounded" if bounded
                               else "wal-full")
        root = os.path.join(tmp, "ckpt") if bounded else None
        g, src, sink = wordcount.build_graph()
        sched = DurableScheduler(g, wal_dir=wal_dir, fsync="tick",
                                 committer="thread",
                                 segment_bytes=1 << 15)
        fe = IngestFrontend(sched, window=CoalesceWindow(
            max_rows=65536, max_ticks=4, max_latency_s=0.002))
        chain = comp = None
        if bounded:
            chain = CheckpointChain(root, delta_every=delta_every)
            comp = WalCompactor(sched.wal, ckpt_dir=root,
                                min_segments=2, keep_segments=1)
        acked = [0] * n_producers
        n_saves = 0
        last_save = 0

        def produce(pid, lo, hi):
            n = 0
            tickets = []

            def resolve():
                nonlocal n
                for t in tickets:
                    if t.result(timeout=120).applied:
                        n += 1
                tickets.clear()

            for seq in range(lo, hi):
                tickets.append(fe.submit(src, batch_for(pid, seq),
                                         batch_id=f"p{pid}-{seq}"))
                if len(tickets) >= 64:
                    resolve()
            resolve()
            acked[pid] += n

        def save_and_compact():
            nonlocal n_saves, last_save
            fe.pause()
            try:
                chain.save(sched)
            finally:
                fe.resume()
            n_saves += 1
            last_save = sched._tick
            comp.compact_once()

        def drive(lo, hi):
            threads = [threading.Thread(target=produce,
                                        args=(pid, lo, hi))
                       for pid in range(n_producers)]
            for t in threads:
                t.start()
            while any(t.is_alive() for t in threads):
                if bounded and sched._tick - last_save >= save_every:
                    save_and_compact()
                time.sleep(0.002)
            for t in threads:
                t.join()

        # two write phases around a guaranteed chain save: heavy
        # coalescing can finish a smoke run in fewer leader ticks than
        # ``save_every``, and the bounded leg MUST exercise {chain +
        # compacted tail}, not compaction alone — phase 2's records are
        # the replay tail past the last anchor
        split = (4 * per_prod) // 5
        drive(0, split)
        if bounded:
            fe.flush()
            save_and_compact()
        drive(split, per_prod)
        fe.flush()
        sched.wal.sync()
        if bounded:
            while comp.compact_once() is not None:
                pass  # drain: fold the sealed tail completely
        view = {kv: w for kv, w in sched.view(sink.name).items()
                if w != 0}
        tick = sched._tick
        fe.close()
        sched.close()
        return {"wal_dir": wal_dir, "root": root, "view": view,
                "tick": tick, "acked": sum(acked), "chain": chain,
                "comp": comp, "sink": sink.name, "saves": n_saves}

    def diff(a, b):
        return max((abs(a.get(kv, 0) - b.get(kv, 0))
                    for kv in set(a) | set(b)), default=0)

    def timed_recover(wal_dir, root):
        g, _s, sink = wordcount.build_graph()
        sched = DirtyScheduler(g)
        t0 = time.perf_counter()
        recover(sched, wal_dir, root)
        dt = time.perf_counter() - t0
        view = {kv: w for kv, w in sched.view(sink.name).items()
                if w != 0}
        return dt, view, sched._tick, sched

    def timed_bootstrap(tmp, wal_dir, root, target_tick, name):
        ship = SegmentShipper(wal_dir=wal_dir, ckpt_dir=root)
        g, _s, sink = wordcount.build_graph()
        r = ReplicaScheduler(g, os.path.join(tmp, name), name=name)
        t0 = time.perf_counter()
        ship.attach(r)
        stalls = 0
        while r.published_horizon() < target_tick:
            if ship.pump_once() == 0:
                stalls += 1
                if stalls > 3:
                    break
            else:
                stalls = 0
        dt = time.perf_counter() - t0
        assert r.published_horizon() == target_tick, \
            (name, r.published_horizon(), target_tick)
        _h, view = r.view_at(sink)
        ship.close()
        r.close()
        return dt, view

    tmp = tempfile.mkdtemp(prefix="reflow-compact-")
    try:
        full = run_leg(tmp, bounded=False)
        bounded = run_leg(tmp, bounded=True)
        assert full["acked"] == bounded["acked"] \
            == n_producers * per_prod, "acked-write loss at submit time"
        out["acked_batches"] = bounded["acked"]
        # identical batch multiset -> identical final fold, exactly
        out["legs_parity_max_abs_diff"] = diff(full["view"],
                                               bounded["view"])
        assert out["legs_parity_max_abs_diff"] == 0

        comp = bounded["comp"]
        reg = MetricsRegistry()
        comp.publish_metrics(reg)
        full_bytes = du(full["wal_dir"])
        bounded_bytes = du(bounded["wal_dir"]) + du(bounded["root"])
        out["wal_full_bytes"] = full_bytes
        out["wal_bounded_bytes"] = du(bounded["wal_dir"])
        out["ckpt_chain_bytes"] = du(bounded["root"])
        out["chain_saves"] = bounded["saves"]
        out["leader_ticks"] = bounded["tick"]
        assert bounded["saves"] >= 1 and out["ckpt_chain_bytes"] > 0, \
            "bounded leg never cut a checkpoint chain element"
        out["compact_folds"] = comp.folds
        out["compact_reclaimed_bytes"] = comp.reclaimed_bytes
        out["reclaimable_bytes_final"] = reg.value(
            "compact.reclaimable_bytes", comp.reclaimable_bytes())

        # -- leader crash-recovery ------------------------------------
        t_full, v_full, tick_full, _ = timed_recover(
            full["wal_dir"], None)
        assert tick_full == full["tick"]
        assert diff(v_full, full["view"]) == 0
        t_bounded, v_bounded, tick_b, sched_b = timed_recover(
            bounded["wal_dir"], bounded["root"])
        assert tick_b == bounded["tick"]
        assert diff(v_bounded, bounded["view"]) == 0
        log(f"compact[recover]: full replay {t_full:.3f}s vs "
            f"chain+tail {t_bounded:.3f}s")

        # -- the O(state) floor: a fresh full checkpoint --------------
        fresh_dir = os.path.join(tmp, "fresh-full")
        save_checkpoint(sched_b, fresh_dir)
        g2, _s2, _k2 = wordcount.build_graph()
        t0 = time.perf_counter()
        load_checkpoint(DirtyScheduler(g2), fresh_dir)
        t_fresh = time.perf_counter() - t0
        state_bytes = du(fresh_dir)
        out["state_bytes"] = state_bytes
        out["history_ratio"] = round(full_bytes / max(1, state_bytes), 2)

        # -- fresh-replica bootstrap ----------------------------------
        tb_full, rv_full = timed_bootstrap(
            tmp, full["wal_dir"], None, full["tick"], "boot-full")
        assert diff(rv_full, full["view"]) == 0
        tb_bounded, rv_bounded = timed_bootstrap(
            tmp, bounded["wal_dir"], bounded["root"], bounded["tick"],
            "boot-bounded")
        assert diff(rv_bounded, bounded["view"]) == 0
        log(f"compact[bootstrap]: full stream {tb_full:.3f}s vs "
            f"chain+tail {tb_bounded:.3f}s (fresh-full floor "
            f"{t_fresh:.3f}s)")

        out["recover_full_s"] = round(t_full, 4)
        out["recover_bounded_s"] = round(t_bounded, 4)
        out["bootstrap_full_s"] = round(tb_full, 4)
        out["bootstrap_bounded_s"] = round(tb_bounded, 4)
        out["fresh_full_restore_s"] = round(t_fresh, 4)
        out["recover_speedup_x"] = round(t_full / max(t_bounded, 1e-9), 2)
        out["bootstrap_speedup_x"] = round(
            tb_full / max(tb_bounded, 1e-9), 2)
        out["parity_max_abs_diff"] = max(
            out["legs_parity_max_abs_diff"], diff(v_full, full["view"]),
            diff(v_bounded, bounded["view"]),
            diff(rv_full, full["view"]),
            diff(rv_bounded, bounded["view"]))
        out["history_ratio_ok"] = out["history_ratio"] >= 10
        out["recover_speedup_ok"] = out["recover_speedup_x"] >= 5
        out["bootstrap_speedup_ok"] = out["bootstrap_speedup_x"] >= 5
        out["recover_near_floor_ok"] = \
            t_bounded <= 2 * t_fresh + eps_s
        out["bootstrap_near_floor_ok"] = \
            tb_bounded <= 2 * t_fresh + eps_s
        out["footprint_bounded_ok"] = bounded_bytes * 3 <= full_bytes
        out["zero_acked_loss"] = (out["parity_max_abs_diff"] == 0
                                  and out["acked_batches"]
                                  == n_producers * per_prod)
        log(f"compact[summary]: history {out['history_ratio']}x state, "
            f"recover {out['recover_speedup_x']}x, bootstrap "
            f"{out['bootstrap_speedup_x']}x, footprint "
            f"{bounded_bytes}/{full_bytes} bytes, "
            f"{comp.folds} fold(s), reclaimed "
            f"{comp.reclaimed_bytes} bytes")
        comp.close()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return out


# -- tiled-maintenance mode (REFLOW_BENCH_TILES=1) -------------------------

def run_tiles_bench() -> dict:
    """Tiled maintenance (docs/guide.md "Tiled maintenance"): with
    ``REFLOW_TILE_BYTES`` set, every O(state) maintenance path —
    compaction folds, checkpoint base/delta elements, published replica
    snapshots, and bootstrap shipping — must bound its peak resident
    bytes by the tile budget (enforced 2x) without giving up a byte of
    parity or exactly-once.

    Two identically-fed legs run back to back, BOTH bounded (checkpoint
    chain + compactor, the REFLOW_BENCH_COMPACT shape) and differing
    only in the tile budget: the **untiled** leg runs the monolithic
    paths (budget 0), the **tiled** leg runs with a budget the final
    state exceeds by >= 8x (so no maintenance step may ever hold the
    whole state). Then:

    1. both legs' final views must agree exactly (identical batch
       multiset -> identical fold, ``max_abs_diff == 0``);
    2. the tiled leg's ``compact.peak_tile_bytes`` and the checkpoint
       writer/reader peak frame bytes must stay under 2x the budget;
    3. crashed-leader recovery from {chain + compacted tail} and a
       fresh-replica bootstrap must hit exact parity on both legs,
       with the tiled leg's bootstrap going through the per-file
       tile-unit protocol (``tile_bootstraps >= 1``);
    4. a per-tile crash-seam sweep kills a maintenance pass at every
       new seam (``compact_tile_before_progress`` /
       ``compact_tile_after_progress`` / ``ckpt_tile_full_append`` /
       ``ckpt_tile_append``) and proves the next pass resumes to exact
       parity — zero acked-write loss at every seam;
    5. the tiled replica's ``top_k`` / ``lookup`` answers must match an
       untiled snapshot oracle bootstrapped from the same leg;
    6. a dedicated small-state pair — identical direct-push feeds, no
       coalescing, so both legs' WAL shapes are byte-identical and the
       walls compare tiled-vs-monolithic work and nothing else — must
       show tiled restore and bootstrap within 1.2x of untiled (+ a
       fixed 50ms epsilon): the bound costs sequential passes, not a
       slowdown where tiling barely engages.

    Host-side CPU work; runs on the CPU executor/platform."""
    import shutil
    import tempfile
    import threading

    from reflow_tpu.obs import MetricsRegistry
    from reflow_tpu.scheduler import DirtyScheduler
    from reflow_tpu.serve import (CoalesceWindow, IngestFrontend,
                                  ReplicaScheduler)
    from reflow_tpu.utils import tiles as _tiles
    from reflow_tpu.utils.checkpoint import (TILE_IO_STATS, CheckpointChain,
                                             reset_tile_io_stats)
    from reflow_tpu.utils.faults import CrashInjector, CrashPoint
    from reflow_tpu.wal import (DurableScheduler, SegmentShipper,
                                WalCompactor, recover)
    from reflow_tpu.workloads import wordcount

    smoke = env_flag("REFLOW_BENCH_SMOKE")
    per_prod = env_int("REFLOW_BENCH_TILES_TICKS") \
        or (120 if smoke else 320)
    n_producers = 16
    vocab = 4000             # wide key space: live state >> tile budget
    tile_b = 8192            # the tiled leg's REFLOW_TILE_BYTES
    save_every = 24          # leader ticks between chain elements
    delta_every = 4          # full checkpoint every 4th element
    eps_s = 0.05             # fixed epsilon on the within-1.2x walls
    out = {"producers": n_producers, "per_producer_batches": per_prod,
           "vocab": vocab, "tile_bytes": tile_b,
           "save_every": save_every, "delta_every": delta_every}

    def set_budget(b):
        if b > 0:
            os.environ["REFLOW_TILE_BYTES"] = str(b)
        else:
            os.environ.pop("REFLOW_TILE_BYTES", None)

    def words_for(pid, seq):
        rng = np.random.default_rng(pid * 100_000 + seq)
        return " ".join(f"w{int(x)}" for x in rng.integers(0, vocab, 24))

    def batch_for(pid, seq):
        if seq % 7 == 6:
            # an occasional retraction keeps the fold's cancellation
            # path hot without shrinking live state below 8x budget
            return wordcount.ingest_lines([words_for(pid, seq - 1)],
                                          weight=-1)
        return wordcount.ingest_lines([words_for(pid, seq)])

    def diff(a, b):
        return max((abs(a.get(kv, 0) - b.get(kv, 0))
                    for kv in set(a) | set(b)), default=0)

    def run_leg(tmp, label):
        wal_dir = os.path.join(tmp, f"wal-{label}")
        root = os.path.join(tmp, f"ckpt-{label}")
        g, src, sink = wordcount.build_graph()
        sched = DurableScheduler(g, wal_dir=wal_dir, fsync="tick",
                                 committer="thread",
                                 segment_bytes=1 << 12)
        fe = IngestFrontend(sched, window=CoalesceWindow(
            max_rows=65536, max_ticks=4, max_latency_s=0.002))
        chain = CheckpointChain(root, delta_every=delta_every)
        comp = WalCompactor(sched.wal, ckpt_dir=root,
                            min_segments=2, keep_segments=1)
        acked = [0] * n_producers
        last_save = 0

        def produce(pid, lo, hi):
            n = 0
            tickets = []

            def resolve():
                nonlocal n
                for t in tickets:
                    if t.result(timeout=120).applied:
                        n += 1
                tickets.clear()

            for seq in range(lo, hi):
                tickets.append(fe.submit(src, batch_for(pid, seq),
                                         batch_id=f"p{pid}-{seq}"))
                if len(tickets) >= 64:
                    resolve()
            resolve()
            acked[pid] += n

        def save_and_compact():
            nonlocal last_save
            fe.pause()
            try:
                chain.save(sched)
            finally:
                fe.resume()
            last_save = sched._tick
            comp.compact_once()

        def drive(lo, hi):
            threads = [threading.Thread(target=produce,
                                        args=(pid, lo, hi))
                       for pid in range(n_producers)]
            for t in threads:
                t.start()
            while any(t.is_alive() for t in threads):
                if sched._tick - last_save >= save_every:
                    save_and_compact()
                time.sleep(0.002)
            for t in threads:
                t.join()

        # a guaranteed mid-stream save so the replay tail crosses a
        # chain element (the compact bench's two-phase shape)
        split = (4 * per_prod) // 5
        drive(0, split)
        fe.flush()
        save_and_compact()
        drive(split, per_prod)
        fe.flush()
        sched.wal.sync()
        while comp.compact_once() is not None:
            pass  # drain: fold the sealed tail completely
        view = {kv: w for kv, w in sched.view(sink.name).items()
                if w != 0}
        tick = sched._tick
        fe.close()
        sched.close()
        return {"wal_dir": wal_dir, "root": root, "view": view,
                "tick": tick, "acked": sum(acked), "chain": chain,
                "comp": comp, "sink": sink}

    def timed_recover(wal_dir, root):
        g, _s, sink = wordcount.build_graph()
        sched = DirtyScheduler(g)
        t0 = time.perf_counter()
        recover(sched, wal_dir, root)
        dt = time.perf_counter() - t0
        view = {kv: w for kv, w in sched.view(sink.name).items()
                if w != 0}
        return dt, view, sched._tick

    def boot(tmp, wal_dir, root, target_tick, name, tile_param=None):
        """Bootstrap a fresh replica from {chain + tail}; the caller
        reads/asserts and must close both handles."""
        ship = SegmentShipper(wal_dir=wal_dir, ckpt_dir=root)
        g, _s, sink = wordcount.build_graph()
        kw = {} if tile_param is None else {"tile_bytes": tile_param}
        r = ReplicaScheduler(g, os.path.join(tmp, name), name=name, **kw)
        t0 = time.perf_counter()
        ship.attach(r)
        t_attach = time.perf_counter() - t0
        stalls = 0
        while r.published_horizon() < target_tick:
            if ship.pump_once() == 0:
                stalls += 1
                if stalls > 3:
                    break
            else:
                stalls = 0
        dt = time.perf_counter() - t0
        log(f"tiles[boot:{name}]: attach {t_attach:.3f}s, "
            f"tail pump {dt - t_attach:.3f}s, "
            f"{ship.tile_units_shipped} unit(s), "
            f"{ship.tile_unit_retries} retr(y/ies), "
            f"{ship.tile_bootstraps} tile boot(s)")
        assert r.published_horizon() == target_tick, \
            (name, r.published_horizon(), target_tick)
        _h, view = r.view_at(sink)
        return ship, r, sink, dt, view

    # -- per-tile crash-seam sweep ------------------------------------

    def seam_feed(tag, n_ticks=36):
        rng = np.random.default_rng(hash(tag) % (1 << 32))
        feed = []
        for t in range(n_ticks):
            words = " ".join(f"s{int(x)}"
                             for x in rng.integers(0, 220, 16))
            feed.append((f"{tag}-t{t}", wordcount.ingest_lines([words])))
        return feed

    def seam_log(wal_dir, feed, *, chain=None, crash_on=None):
        """Drive a small durable leader; optionally cut chain elements
        mid-feed, letting a CrashInjector kill a tiled save. Returns
        (live view, tick, acked, fired)."""
        g, src, sink = wordcount.build_graph()
        sched = DurableScheduler(g, wal_dir=wal_dir, fsync="tick",
                                 segment_bytes=1 << 12)
        acked = 0
        fired = False
        for i, (bid, b) in enumerate(feed):
            sched.push(src, b, batch_id=bid)
            sched.tick()
            acked += 1
            if chain is not None and not fired and (i + 1) % 12 == 0:
                try:
                    chain.save(sched)
                except CrashPoint:
                    fired = True
                    assert crash_on is not None and crash_on.fired
        view = {kv: w for kv, w in sched.view(sink.name).items()
                if w != 0}
        tick = sched._tick
        sched.close()
        return view, tick, acked, fired

    def sweep_compact_seam(base, seam):
        d = os.path.join(base, f"seam-{seam}")
        oracle, tick, acked, _ = seam_log(d, seam_feed(seam))
        inj = CrashInjector(at=2, only=seam)  # die PAST the first tile
        comp = WalCompactor(wal_dir=d, min_segments=2, keep_segments=1,
                            crash=inj)
        try:
            comp.compact_once()
            fired = False
        except CrashPoint:
            fired = True
        assert fired and inj.fired_seam == seam, (seam, inj.fired_seam)
        # next pass rolls forward (finished tiles are NOT refolded) and
        # the unchanged recovery path must land on exact parity
        comp2 = WalCompactor(wal_dir=d, min_segments=2, keep_segments=1)
        while comp2.compact_once() is not None:
            pass
        _dt, view, tick2 = timed_recover(d, None)
        assert tick2 == tick and diff(view, oracle) == 0, seam
        comp.close()
        comp2.close()
        return acked

    def sweep_ckpt_seam(base, seam):
        d = os.path.join(base, f"seam-{seam}")
        root = os.path.join(base, f"seam-{seam}-ckpt")
        inj = CrashInjector(at=2, only=seam)
        chain = CheckpointChain(root, delta_every=delta_every,
                                crash=inj)
        oracle, tick, acked, fired = seam_log(
            d, seam_feed(seam, n_ticks=48), chain=chain, crash_on=inj)
        assert fired and inj.fired_seam == seam, (seam, inj.fired_seam)
        # the torn save never flipped a manifest: recovery restores the
        # previous element (or replays from scratch) + the WAL tail
        _dt, view, tick2 = timed_recover(d, root)
        assert tick2 == tick and diff(view, oracle) == 0, seam
        return acked

    tmp = tempfile.mkdtemp(prefix="reflow-tiles-")
    prev_budget = env_int("REFLOW_TILE_BYTES")
    legs = {}
    try:
        for label, budget in (("untiled", 0), ("tiled", tile_b)):
            set_budget(budget)
            if budget:
                reset_tile_io_stats()
            leg = run_leg(tmp, label)
            assert leg["acked"] == n_producers * per_prod, \
                "acked-write loss at submit time"
            if budget:
                out["ckpt_writer_peak_bytes"] = \
                    TILE_IO_STATS["writer_peak_frame_bytes"]
                reset_tile_io_stats()
            t_rec, v_rec, tick_rec = timed_recover(leg["wal_dir"],
                                                   leg["root"])
            assert tick_rec == leg["tick"]
            assert diff(v_rec, leg["view"]) == 0
            if budget:
                out["ckpt_reader_peak_bytes"] = \
                    TILE_IO_STATS["reader_peak_frame_bytes"]
            ship, rep, sink, t_boot, v_boot = boot(
                tmp, leg["wal_dir"], leg["root"], leg["tick"],
                f"boot-{label}")
            assert diff(v_boot, leg["view"]) == 0
            leg.update(recover_s=t_rec, bootstrap_s=t_boot,
                       ship=ship, rep=rep, sink=sink)
            legs[label] = leg
            log(f"tiles[{label}]: recover {t_rec:.3f}s, "
                f"bootstrap {t_boot:.3f}s, {leg['tick']} tick(s)")

        full, tiled = legs["untiled"], legs["tiled"]
        out["acked_batches"] = tiled["acked"]
        out["leader_ticks"] = tiled["tick"]
        out["legs_parity_max_abs_diff"] = diff(full["view"],
                                               tiled["view"])
        assert out["legs_parity_max_abs_diff"] == 0

        # -- bound checks: nothing held more than ~2x the budget ------
        state_bytes = int(sum(
            _tiles.approx_row_bytes(kv, w)
            for kv, w in tiled["view"].items()))
        out["state_est_bytes"] = state_bytes
        out["state_over_budget_x"] = round(state_bytes / tile_b, 2)
        assert state_bytes >= 8 * tile_b, \
            f"state {state_bytes}B < 8x budget — the bench proves nothing"
        comp = tiled["comp"]
        chain = tiled["chain"]
        reg = MetricsRegistry()
        comp.publish_metrics(reg)
        out["compact_folds"] = comp.folds
        out["compact_peak_tile_bytes"] = reg.value(
            "compact.peak_tile_bytes", comp.peak_tile_bytes)
        out["ckpt_tile_count"] = chain.tile_count
        out["ckpt_peak_tile_bytes"] = chain.peak_tile_bytes
        assert 0 < out["compact_peak_tile_bytes"] <= 2 * tile_b, \
            f"compact peak {out['compact_peak_tile_bytes']}B " \
            f"vs budget {tile_b}B"
        assert 0 < out["ckpt_writer_peak_bytes"] <= 2 * tile_b, \
            f"ckpt writer peak {out['ckpt_writer_peak_bytes']}B " \
            f"vs budget {tile_b}B"
        assert 0 < out["ckpt_reader_peak_bytes"] <= 2 * tile_b, \
            f"ckpt reader peak {out['ckpt_reader_peak_bytes']}B " \
            f"vs budget {tile_b}B"
        assert chain.tile_count >= 4, \
            f"budget only planned {chain.tile_count} tile(s)"

        # -- tile-unit bootstrap actually ran -------------------------
        ship_t = tiled["ship"]
        out["tile_units_shipped"] = ship_t.tile_units_shipped
        out["tile_unit_retries"] = ship_t.tile_unit_retries
        out["tile_bootstraps"] = ship_t.tile_bootstraps
        assert ship_t.tile_bootstraps >= 1 \
            and ship_t.tile_units_shipped > 0, \
            "tiled bootstrap fell back to the monolithic path"
        rep_t = tiled["rep"]
        out["snapshot_tiles_reused"] = rep_t.snapshot_tiles_reused

        # -- read parity vs an untiled snapshot oracle ----------------
        # same leg, same WAL, same horizon — only snapshot publication
        # differs (tile_bytes=0 forces monolithic arrays)
        ship_o, rep_o, sink_o, _dt, v_o = boot(
            tmp, tiled["wal_dir"], tiled["root"], tiled["tick"],
            "boot-oracle", tile_param=0)
        assert diff(v_o, tiled["view"]) == 0
        k = 10
        h_t, top_t = rep_t.top_k(tiled["sink"], k, by="weight")
        h_o, top_o = rep_o.top_k(sink_o, k, by="weight")
        assert h_t == h_o == tiled["tick"]
        # tie order may differ between a per-tile merge and one global
        # argpartition: compare the rank sequence, then validate every
        # member's weight against the oracle's full view
        assert [w for _kv, w in top_t] == [w for _kv, w in top_o]
        assert all(v_o.get(kv) == w for kv, w in top_t)
        probe = list(tiled["view"])[:: max(1, len(tiled["view"]) // 64)]
        for kv in probe + [("w-never-seen", None)]:
            assert rep_t.lookup(tiled["sink"], kv) \
                == rep_o.lookup(sink_o, kv), kv
        out["topk_parity_ok"] = True
        out["lookup_probes"] = len(probe) + 1
        log(f"tiles[reads]: top_{k} + {len(probe) + 1} lookups match "
            f"the untiled oracle at horizon {h_t}")
        ship_o.close()
        rep_o.close()

        # -- per-tile crash-seam sweep --------------------------------
        set_budget(2048)  # small budget: even the seam feeds tile
        seam_acked = {}
        for seam in ("compact_tile_before_progress",
                     "compact_tile_after_progress"):
            seam_acked[seam] = sweep_compact_seam(tmp, seam)
        for seam in ("ckpt_tile_full_append", "ckpt_tile_append"):
            seam_acked[seam] = sweep_ckpt_seam(tmp, seam)
        set_budget(tile_b)
        out["crash_seams_survived"] = sorted(seam_acked)
        out["crash_seam_acked_batches"] = sum(seam_acked.values())
        log(f"tiles[seams]: {len(seam_acked)} per-tile seam(s) killed "
            f"and recovered to exact parity")

        # -- small-state walls: the bound must not cost a slowdown ----
        # the big legs coalesce nondeterministically (tick/anchor
        # layouts differ per leg), so their walls are reported but the
        # 1.2x criterion is measured on identical deterministic feeds
        def small_leg(label, budget):
            set_budget(budget)
            wal_dir = os.path.join(tmp, f"small-wal-{label}")
            root = os.path.join(tmp, f"small-ckpt-{label}")
            g, src, sink = wordcount.build_graph()
            sched = DurableScheduler(g, wal_dir=wal_dir, fsync="tick",
                                     segment_bytes=1 << 12)
            chain = CheckpointChain(root, delta_every=delta_every)
            comp = WalCompactor(sched.wal, ckpt_dir=root,
                                min_segments=2, keep_segments=1)
            for t in range(60):
                rng = np.random.default_rng(t)
                words = " ".join(f"w{int(x)}"
                                 for x in rng.integers(0, 600, 24))
                sched.push(src, wordcount.ingest_lines([words]),
                           batch_id=f"t{t}")
                sched.tick()
                if t == 44:
                    chain.save(sched)
                    comp.compact_once()
            sched.wal.sync()
            while comp.compact_once() is not None:
                pass
            view = {kv: w for kv, w in sched.view(sink.name).items()
                    if w != 0}
            tick = sched._tick
            sched.close()
            t_rec = 1e9
            for _ in range(3):
                dt, v_rec, tick_rec = timed_recover(wal_dir, root)
                assert tick_rec == tick and diff(v_rec, view) == 0
                t_rec = min(t_rec, dt)
            ship, rep, _sink_n, t_boot, v_boot = boot(
                tmp, wal_dir, root, tick, f"small-boot-{label}")
            assert diff(v_boot, view) == 0
            n_tiles = chain.tile_count
            ship.close()
            rep.close()
            comp.close()
            chain.close()
            return t_rec, t_boot, n_tiles

        small_rec_u, small_boot_u, _nt = small_leg("untiled", 0)
        small_rec_t, small_boot_t, n_tiles = small_leg("tiled", tile_b)
        assert n_tiles >= 2, \
            f"small-state leg planned {n_tiles} tile(s) — trivial pass"
        out["recover_untiled_s"] = round(full["recover_s"], 4)
        out["recover_tiled_s"] = round(tiled["recover_s"], 4)
        out["bootstrap_untiled_s"] = round(full["bootstrap_s"], 4)
        out["bootstrap_tiled_s"] = round(tiled["bootstrap_s"], 4)
        out["big_restore_wall_ratio_x"] = round(
            tiled["recover_s"] / max(full["recover_s"], 1e-9), 2)
        out["big_bootstrap_wall_ratio_x"] = round(
            tiled["bootstrap_s"] / max(full["bootstrap_s"], 1e-9), 2)
        out["small_recover_untiled_s"] = round(small_rec_u, 4)
        out["small_recover_tiled_s"] = round(small_rec_t, 4)
        out["small_bootstrap_untiled_s"] = round(small_boot_u, 4)
        out["small_bootstrap_tiled_s"] = round(small_boot_t, 4)
        out["small_state_tiles"] = n_tiles
        out["restore_wall_ratio_x"] = round(
            small_rec_t / max(small_rec_u, 1e-9), 2)
        out["bootstrap_wall_ratio_x"] = round(
            small_boot_t / max(small_boot_u, 1e-9), 2)
        out["restore_wall_ok"] = \
            small_rec_t <= 1.2 * small_rec_u + eps_s
        out["bootstrap_wall_ok"] = \
            small_boot_t <= 1.2 * small_boot_u + eps_s
        assert out["restore_wall_ok"], \
            f"tiled restore {small_rec_t:.3f}s vs untiled " \
            f"{small_rec_u:.3f}s at small state"
        assert out["bootstrap_wall_ok"], \
            f"tiled bootstrap {small_boot_t:.3f}s vs untiled " \
            f"{small_boot_u:.3f}s at small state"
        out["peak_bounds_ok"] = True
        out["zero_acked_loss"] = (
            out["legs_parity_max_abs_diff"] == 0
            and tiled["acked"] == full["acked"]
            == n_producers * per_prod)
        log(f"tiles[summary]: state {out['state_over_budget_x']}x "
            f"budget, compact peak {out['compact_peak_tile_bytes']}B, "
            f"ckpt peaks {out['ckpt_writer_peak_bytes']}/"
            f"{out['ckpt_reader_peak_bytes']}B (budget {tile_b}B), "
            f"{out['tile_units_shipped']} unit(s) shipped, walls "
            f"{out['restore_wall_ratio_x']}x/"
            f"{out['bootstrap_wall_ratio_x']}x untiled")
        comp.close()
        chain.close()
        full["comp"].close()
        full["chain"].close()
    finally:
        set_budget(prev_budget)
        for leg in legs.values():
            for h in ("ship", "rep"):
                try:
                    if h in leg:
                        leg[h].close()
                except Exception:  # noqa: BLE001 - teardown best-effort
                    pass
        shutil.rmtree(tmp, ignore_errors=True)
    return out


# -- leader-failover mode (REFLOW_BENCH_FAILOVER=1) ------------------------

def run_failover_bench() -> dict:
    """Promote-on-failure under load (docs/guide.md "Leader failover"):
    a wordcount leader (``DurableScheduler`` + ``IngestFrontend``) under
    sustained 16-producer writes with a ``SegmentShipper`` feeding N
    replicas — then the leader is killed mid-stream (a crash seam inside
    the WAL committer: the fsync raises, the committer dies, the pump
    crashes on its next window) and a ``FailoverCoordinator`` runs the
    whole failover: detect → final drain → fence → elect → promote →
    re-ship → re-point reads and ingestion.

    Producers use FIXED batch ids and a resubmit-until-acked loop: a
    ticket that dies with ``PumpCrashed`` is resubmitted with the same
    id after the rebind, so the WAL dedup — not the producer — decides
    exactly-once. The bench reports:

    - **detection_s / promotion_s / first_window_s**: kill → the
      coordinator confirms death; the promotion step's wall; promotion
      → the first commit window applied on the new leader;
    - **zero acked-write loss**: the new leader's final view exactly
      equals a fresh fold of every batch any producer got an ack for
      (applied or deduped) — acked ⊆ synced ⊆ shipped-after-drain;
    - **old-vs-new parity at the promotion horizon**: captured inside
      the promotion callback, before any new-epoch write lands.

    Host-side CPU work; runs on the CPU executor/platform."""
    import shutil
    import tempfile
    import threading

    from reflow_tpu.obs import REGISTRY
    from reflow_tpu.serve import (CoalesceWindow, FailoverCoordinator,
                                  IngestFrontend, LeaderReadAdapter,
                                  ReadTier, ReplicaScheduler)
    from reflow_tpu.utils.faults import CrashInjector
    from reflow_tpu.wal import DurableScheduler, FencedWrite, SegmentShipper
    from reflow_tpu.workloads import wordcount

    smoke = env_flag("REFLOW_BENCH_SMOKE")
    n_replicas = env_int("REFLOW_BENCH_FAILOVER_N", "2")
    n_producers = 16
    window_ticks = 4
    vocab = 2_000 if smoke else 20_000
    run_s = env_float("REFLOW_BENCH_FAILOVER_RUN_S", "0.3" if smoke else "1.0")

    tmp = tempfile.mkdtemp(prefix="reflow-failover-")
    out = {"replicas": n_replicas, "producers": n_producers,
           "window_ticks": window_ticks, "run_s": run_s, "vocab": vocab}
    fe = ship = coord = new_sched = None
    replicas = []
    try:
        g, src, sink = wordcount.build_graph()
        sched = DurableScheduler(g, wal_dir=os.path.join(tmp, "wal"),
                                 fsync="tick", committer="thread",
                                 segment_bytes=1 << 20)
        fe = IngestFrontend(sched, window=CoalesceWindow(
            max_rows=65536, max_ticks=window_ticks, max_latency_s=0.002))
        ship = SegmentShipper(sched.wal, leader_tick=lambda: sched._tick,
                              poll_s=0.001)
        for i in range(n_replicas):
            gr, _s, _k = wordcount.build_graph()
            r = ReplicaScheduler(gr, os.path.join(tmp, f"r{i}"),
                                 name=f"r{i}")
            ship.attach(r)
            replicas.append(r)
        tier = ReadTier(replicas, leader=LeaderReadAdapter(sched))
        ship.start()

        # old-vs-new parity at the promotion horizon, captured INSIDE
        # the promotion (before any new-epoch write can land)
        parity = {}

        def promote_fn(winner, epoch):
            # the winner's published view IS the old leader's durable
            # prefix at the promotion horizon (mirrored bytes, replayed
            # through the same machinery) — the new leader must equal
            # it exactly. The old leader's live in-memory view may be
            # ahead by its final un-synced (never-acked) window; that
            # overhang is reported, not an error.
            ph, pre = winner.view_at(sink.name)
            ns = winner.promote(epoch=epoch, fsync="tick",
                                committer="thread")
            new_view = {kv: w for kv, w in ns.view(sink.name).items()
                        if w != 0}
            diff = 0
            for kv in set(pre) | set(new_view):
                diff = max(diff, abs(pre.get(kv, 0)
                                     - new_view.get(kv, 0)))
            parity.update(horizon=ph, old_ticks=sched._tick,
                          overhang_ticks=sched._tick - ph,
                          max_abs_diff=diff)
            return ns

        coord = FailoverCoordinator(
            replicas, shipper=ship, handle=fe, read_tier=tier,
            confirm_intervals=2, promote_fn=promote_fn)
        coord.publish_metrics()

        # -- sustained writes with fixed ids + resubmit-until-acked
        stop = threading.Event()
        rebound = threading.Event()
        acked_lock = threading.Lock()
        acked: list = []   # (batch_id, words) with a terminal ack
        lost = [0]         # batches given up on (must stay 0)

        def produce(pid):
            rng = np.random.default_rng(1000 + pid)
            seq = 0
            while not stop.is_set():
                words = " ".join(
                    f"w{int(x)}" for x in rng.integers(0, vocab, 24))
                bid = f"p{pid}-{seq}"
                batch = wordcount.ingest_lines([words])
                deadline = time.monotonic() + 60
                ok = False
                while time.monotonic() < deadline:
                    try:
                        res = fe.submit(src, batch,
                                        batch_id=bid).result(timeout=60)
                    except Exception:  # noqa: BLE001 - PumpCrashed /
                        # FrontendClosed mid-failover: wait out the
                        # rebind, then resubmit the SAME id — the WAL
                        # dedup decides exactly-once, not this loop
                        rebound.wait(timeout=30)
                        time.sleep(0.002)
                        continue
                    if res.status in ("applied", "deduped"):
                        ok = True
                        break
                    time.sleep(0.001)
                if ok:
                    with acked_lock:
                        acked.append((bid, words))
                else:
                    lost[0] += 1
                seq += 1

        producers = [threading.Thread(target=produce, args=(pid,))
                     for pid in range(n_producers)]
        for t in producers:
            t.start()
        time.sleep(run_s)

        # -- kill the leader: the committer's next fsync dies
        sched.wal._crash = CrashInjector(at=1, only="wal_before_fsync")
        t_kill = time.perf_counter()
        log(f"failover: leader killed at tick {sched._tick}")

        t_detect = t_promoted = None
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            t0 = time.perf_counter()
            acts = coord.step()
            if any(a["kind"] == "failover_promote" for a in acts):
                t_detect, t_promoted = t0, time.perf_counter()
            if coord.promoted and not coord._pending_rebind:
                break
            time.sleep(0.002)
        assert coord.promoted, "failover never fired"
        rebound.set()
        new_sched = coord.leader_sched
        out["detection_s"] = round(t_detect - t_kill, 4)
        out["promotion_s"] = round(t_promoted - t_detect, 4)
        out["winner"] = coord.winner.name
        out["epoch"] = coord.epoch
        out["drained_bytes"] = coord.drained_bytes

        # first commit window on the new leader, through the SAME
        # frontend handle the producers are already using
        probe = fe.submit(src, wordcount.ingest_lines(["probe words"]),
                          batch_id="probe-1")
        probe.result(timeout=60)
        out["first_window_s"] = round(time.perf_counter() - t_promoted, 4)
        with acked_lock:
            acked.append(("probe-1", "probe words"))
        log(f"failover: {out['winner']} promoted to epoch "
            f"{out['epoch']} — detect {out['detection_s']}s, promote "
            f"{out['promotion_s']}s, first window "
            f"{out['first_window_s']}s")

        # reads survived the swing: the tier now falls back to the new
        # leader for fresh horizons
        res = tier.top_k(sink.name, 10, min_horizon=new_sched._tick,
                         by="value")
        out["post_failover_read_source"] = res.source

        time.sleep(run_s)  # keep writing on the new leader
        stop.set()
        for t in producers:
            t.join()
        fe.flush()
        new_sched.wal.sync()

        # the zombie is fenced: its log refuses appends, counted
        try:
            sched.wal.append({"kind": "tick", "tick": 10 ** 9})
            assert False, "zombie append was accepted"
        except FencedWrite:
            pass
        out["fence_rejected_appends"] = sched.wal.fence_rejected_appends

        # -- zero acked-write loss: every acked batch folded exactly once
        assert lost[0] == 0, f"{lost[0]} producer batch(es) gave up"
        from reflow_tpu.scheduler import DirtyScheduler
        go, so, ko = wordcount.build_graph()
        oracle = DirtyScheduler(go)
        with acked_lock:
            for bid, words in acked:
                oracle.push(so, wordcount.ingest_lines([words]),
                            batch_id=bid)
        oracle.tick()
        want = {kv: w for kv, w in oracle.view(ko.name).items() if w != 0}
        got = {kv: w for kv, w in new_sched.view(sink.name).items()
               if w != 0}
        diff = 0
        for kv in set(want) | set(got):
            diff = max(diff, abs(want.get(kv, 0) - got.get(kv, 0)))
        out["acked_batches"] = len(acked)
        out["acked_loss_max_abs_diff"] = diff
        assert diff == 0, f"acked-write loss: max_abs_diff={diff}"

        out["promotion_horizon"] = parity.get("horizon")
        out["promotion_overhang_ticks"] = parity.get("overhang_ticks")
        out["promotion_parity_max_abs_diff"] = parity.get("max_abs_diff")
        assert parity.get("max_abs_diff") == 0
        out["epoch_gauge"] = REGISTRY.value("failover.epoch", -1)
        out["new_leader_ticks"] = new_sched._tick
        log(f"failover: {len(acked)} acked batch(es), zero loss "
            f"(diff {diff}), promotion parity diff "
            f"{parity.get('max_abs_diff')} at horizon "
            f"{parity.get('horizon')}")
    finally:
        if fe is not None:
            fe.close()
        if coord is not None:
            coord.close()
        if ship is not None:
            ship.close()
        for r in replicas:
            r.close()
        if new_sched is not None:
            new_sched.close()
        shutil.rmtree(tmp, ignore_errors=True)
    return out


# -- chaos-soak mode (REFLOW_BENCH_CHAOS=1) --------------------------------

def run_chaos_bench() -> dict:
    """Replication-over-the-wire chaos soak (docs/guide.md
    "Replication over the wire"): a wordcount leader under sustained
    16-producer writes ships its WAL to N replicas over REAL TCP
    links, every link wrapped in a seeded :class:`WireFaults` /
    ``FaultyTransport`` pair, while a scripted schedule runs:

    A. **probabilistic storm** — drop (both directions), duplicate,
       reorder, frame corruption, payload corruption, delay on every
       link, under full write load;
    B. **scripted faults** — a one-way partition on the last link
       (driven to ``unreachable``, ejected from the read tier) and a
       connection reset on the first (forcing the reconnect path);
    C. **quiesce** — all faults stop; replicas must converge to lag
       <= one commit window within a bounded wall;
    D. **leader kill** — the last link is re-partitioned (so the
       ex-leader keeps undrained bytes for it), the committer is
       killed mid-fsync, and the coordinator runs the epoch-fenced
       promotion; after healing, the ex-leader's shipper is pumped at
       the re-anchored replicas and every shipment it offers must be
       NACKed ``fenced:`` — acked zero times, merged never.

    Producers use fixed batch ids with resubmit-until-acked, so the
    final zero-loss check is exact: the new leader's view equals a
    fresh fold of every acked batch, and every surviving replica's
    view at the shared horizon equals the new leader's with
    ``max_abs_diff == 0``.

    Host-side CPU work; runs on the CPU executor/platform."""
    import shutil
    import tempfile
    import threading

    from reflow_tpu.net import (FaultyTransport, ReconnectPolicy,
                                RemoteFollower, ReplicaServer,
                                TcpTransport)
    from reflow_tpu.obs import REGISTRY
    from reflow_tpu.serve import (CoalesceWindow, FailoverCoordinator,
                                  IngestFrontend, LeaderReadAdapter,
                                  ReadTier, ReplicaScheduler)
    from reflow_tpu.utils.faults import CrashInjector, WireFaults
    from reflow_tpu.wal import DurableScheduler, SegmentShipper
    from reflow_tpu.workloads import wordcount

    smoke = env_flag("REFLOW_BENCH_SMOKE")
    n_replicas = max(2, env_int("REFLOW_BENCH_CHAOS_N", "3"))
    n_producers = 16
    window_ticks = 4
    vocab = 2_000 if smoke else 20_000
    run_s = env_float("REFLOW_BENCH_CHAOS_RUN_S", "0.4" if smoke else "1.2")
    fault_seed = env_int("REFLOW_NET_FAULT_SEED", "0")

    tmp = tempfile.mkdtemp(prefix="reflow-chaos-")
    out = {"replicas": n_replicas, "producers": n_producers,
           "window_ticks": window_ticks, "run_s": run_s, "vocab": vocab,
           "fault_seed": fault_seed}
    fe = ship = coord = new_sched = None
    replicas, servers, links, faults = [], [], [], []
    producers: list = []
    stop = threading.Event()
    rebound = threading.Event()
    try:
        g, src, sink = wordcount.build_graph()
        sched = DurableScheduler(g, wal_dir=os.path.join(tmp, "wal"),
                                 fsync="tick", committer="thread",
                                 segment_bytes=1 << 20)
        fe = IngestFrontend(sched, window=CoalesceWindow(
            max_rows=65536, max_ticks=window_ticks, max_latency_s=0.002))
        ship = SegmentShipper(sched.wal, leader_tick=lambda: sched._tick,
                              poll_s=0.001)
        for i in range(n_replicas):
            gr, _s, _k = wordcount.build_graph()
            r = ReplicaScheduler(gr, os.path.join(tmp, f"r{i}"),
                                 name=f"r{i}")
            srv = ReplicaServer(r, TcpTransport()).start()
            # born quiet so attach()'s subscribe handshake lands; the
            # storm switches on (set_rates) once producers are running
            wf = WireFaults(seed=fault_seed + 17 * i + 1)
            # fast-recovery policy: bench wall-time, not prod defaults
            link = RemoteFollower(
                FaultyTransport(TcpTransport(), wf), srv.address,
                name=f"r{i}",
                policy=ReconnectPolicy(f"r{i}", base_s=0.005,
                                       cap_s=0.05, seed=fault_seed),
                io_timeout_s=0.05)
            ship.attach(link)
            replicas.append(r)
            servers.append(srv)
            links.append(link)
            faults.append(wf)
        tier = ReadTier(replicas, leader=LeaderReadAdapter(sched))
        for r, link in zip(replicas, links):
            tier.bind_link(r, link)
        ship.publish_metrics()
        tier.publish_metrics()
        ship.start()

        parity = {}

        def promote_fn(winner, epoch):
            ph, pre = winner.view_at(sink.name)
            ns = winner.promote(epoch=epoch, fsync="tick",
                                committer="thread")
            new_view = {kv: w for kv, w in ns.view(sink.name).items()
                        if w != 0}
            diff = 0
            for kv in set(pre) | set(new_view):
                diff = max(diff, abs(pre.get(kv, 0)
                                     - new_view.get(kv, 0)))
            parity.update(horizon=ph, max_abs_diff=diff)
            return ns

        coord = FailoverCoordinator(
            replicas, shipper=ship, handle=fe, read_tier=tier,
            confirm_intervals=2, promote_fn=promote_fn,
            drain_timeout_s=0.8)
        coord.publish_metrics()

        # -- sustained writes, fixed ids, resubmit-until-acked
        acked_lock = threading.Lock()
        acked: list = []
        lost = [0]

        def produce(pid):
            rng = np.random.default_rng(1000 + pid)
            seq = 0
            while not stop.is_set():
                words = " ".join(
                    f"w{int(x)}" for x in rng.integers(0, vocab, 24))
                bid = f"p{pid}-{seq}"
                batch = wordcount.ingest_lines([words])
                deadline = time.monotonic() + 60
                ok = False
                while time.monotonic() < deadline:
                    try:
                        res = fe.submit(src, batch,
                                        batch_id=bid).result(timeout=60)
                    except Exception:  # noqa: BLE001 - PumpCrashed /
                        # FrontendClosed mid-failover: wait out the
                        # rebind, resubmit the SAME id; the WAL dedup
                        # decides exactly-once
                        rebound.wait(timeout=30)
                        time.sleep(0.002)
                        continue
                    if res.status in ("applied", "deduped"):
                        ok = True
                        break
                    time.sleep(0.001)
                if ok:
                    with acked_lock:
                        acked.append((bid, words))
                else:
                    lost[0] += 1
                seq += 1

        producers.extend(threading.Thread(target=produce, args=(pid,))
                         for pid in range(n_producers))
        for t in producers:
            t.start()

        # -- phase A: probabilistic storm under load
        for wf in faults:
            wf.set_rates(drop_c2s=0.04, drop_s2c=0.04, dup=0.04,
                         reorder=0.04, corrupt_frame=0.01,
                         corrupt_payload=0.01, delay_p=0.08,
                         delay_s=0.002)
        time.sleep(run_s)

        # -- phase B: scripted one-way partition + connection reset
        target = n_replicas - 1
        faults[target].partition("c2s")
        faults[0].reset_once(1)
        deadline = time.monotonic() + 10
        while (links[target].conn_state != "unreachable"
               and time.monotonic() < deadline):
            time.sleep(0.005)
        out["partition_conn_state"] = links[target].conn_state
        # a few routed reads eject the dead-linked replica
        for _ in range(2 * n_replicas):
            tier.top_k(sink.name, 5, by="value")
        out["ejected_during_partition"] = any(
            r is replicas[target] for r in tier.ejected_replicas)
        time.sleep(0.1)

        # -- phase C: faults stop; converge to <= one commit window
        for wf in faults:
            wf.quiesce()
        t_quiesce = time.perf_counter()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if max(r.lag_ticks() for r in replicas) <= window_ticks:
                break
            time.sleep(0.005)
        out["converge_s"] = round(time.perf_counter() - t_quiesce, 4)
        lag_after = max(r.lag_ticks() for r in replicas)
        out["lag_after_quiesce_ticks"] = lag_after
        assert lag_after <= window_ticks, \
            f"lag {lag_after} > one commit window ({window_ticks})"
        # routed reads probe the healed link back into rotation
        for _ in range(2 * n_replicas):
            tier.top_k(sink.name, 5, by="value")
        out["tier_ejects"] = tier.ejects
        out["tier_restores"] = tier.restores
        log(f"chaos: converged {out['converge_s']}s after quiesce "
            f"(lag {lag_after}), ejects={tier.ejects} "
            f"restores={tier.restores}")

        # -- phase D: re-partition the last link, kill the leader
        faults[target].partition("c2s")
        time.sleep(0.05)  # writes land that the ex-leader can't drain
        # stop the pump thread: promote_now still drains via pump_once,
        # and a threadless old shipper means the coordinator's new
        # shipper starts threadless too — so the partitioned replica
        # stays BEHIND the old horizon until we pump it, making the
        # ex-leader's post-fence offer (and its fenced NACK) a
        # deterministic exchange instead of a race against catch-up
        ship.stop()
        sched.wal._crash = CrashInjector(at=1, only="wal_before_fsync")
        t_kill = time.perf_counter()
        t_detect = t_promoted = None
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            t0 = time.perf_counter()
            acts = coord.step()
            if any(a["kind"] == "failover_promote" for a in acts):
                t_detect, t_promoted = t0, time.perf_counter()
            if coord.promoted and not coord._pending_rebind:
                break
            time.sleep(0.002)
        assert coord.promoted, "failover never fired"
        rebound.set()
        new_sched = coord.leader_sched
        out["detection_s"] = round(t_detect - t_kill, 4)
        out["promotion_s"] = round(t_promoted - t_detect, 4)
        out["winner"] = coord.winner.name
        out["epoch"] = coord.epoch
        out["drained_bytes"] = coord.drained_bytes
        out["promotion_parity_max_abs_diff"] = parity.get("max_abs_diff")
        assert parity.get("max_abs_diff") == 0
        log(f"chaos: {out['winner']} promoted to epoch {out['epoch']} "
            f"— detect {out['detection_s']}s, promote "
            f"{out['promotion_s']}s")

        # the partitioned ex-leader heals and keeps shipping its OLD
        # epoch at the re-anchored replicas: every offer must be NACKed
        # fenced, ACKed never (the shipments counter is ACKs only)
        faults[target].heal()
        acks_before = ship.shipments
        deadline = time.monotonic() + 10
        while ship.fence_nacks == 0 and time.monotonic() < deadline:
            ship.pump_once()
            time.sleep(0.005)
        out["ex_leader_fence_nacks"] = ship.fence_nacks
        out["ex_leader_post_fence_acks"] = ship.shipments - acks_before
        assert ship.fence_nacks >= 1, "ex-leader was never fenced"
        assert ship.shipments == acks_before, \
            "a post-fence shipment from the ex-leader was ACKed"

        # now let the new epoch's shipper catch the survivors up
        coord.new_shipper.start()

        # -- keep writing on the new leader, then settle and check
        time.sleep(run_s / 2)
        stop.set()
        for t in producers:
            t.join()
        fe.flush()
        new_sched.wal.sync()
        survivors = [r for r in replicas if not r.promoted]
        deadline = time.monotonic() + 60
        while (any(r.published_horizon() != new_sched._tick
                   for r in survivors)
               and time.monotonic() < deadline):
            time.sleep(0.005)

        # zero acked-write loss: every acked batch folded exactly once
        assert lost[0] == 0, f"{lost[0]} producer batch(es) gave up"
        from reflow_tpu.scheduler import DirtyScheduler
        go, so, ko = wordcount.build_graph()
        oracle = DirtyScheduler(go)
        with acked_lock:
            for bid, words in acked:
                oracle.push(so, wordcount.ingest_lines([words]),
                            batch_id=bid)
        oracle.tick()
        want = {kv: w for kv, w in oracle.view(ko.name).items() if w != 0}
        got = {kv: w for kv, w in new_sched.view(sink.name).items()
               if w != 0}
        diff = 0
        for kv in set(want) | set(got):
            diff = max(diff, abs(want.get(kv, 0) - got.get(kv, 0)))
        out["acked_batches"] = len(acked)
        out["acked_loss_max_abs_diff"] = diff
        assert diff == 0, f"acked-write loss: max_abs_diff={diff}"

        # exact parity at equal horizons on every surviving replica
        parity_diff = 0
        for r in survivors:
            rh, rv = r.view_at(sink.name)
            assert rh == new_sched._tick, (r.name, rh, new_sched._tick)
            for kv in set(got) | set(rv):
                parity_diff = max(
                    parity_diff, abs(got.get(kv, 0) - rv.get(kv, 0)))
        out["parity_max_abs_diff"] = parity_diff
        assert parity_diff == 0

        # wire-level accounting: the storm really exercised the paths
        out["retransmit_bytes"] = ship.retransmit_bytes
        out["link_stalls"] = ship.link_stalls
        out["ship_nacks"] = ship.nacks
        out["reconnects_total"] = sum(l.reconnects_total for l in links)
        out["fault_stats"] = {
            f"r{i}": dict(wf.stats) for i, wf in enumerate(faults)}
        out["conn_state_gauge"] = REGISTRY.value(
            "replica.r0.conn_state", "?")
        assert ship.retransmit_bytes > 0, \
            "no retransmissions: the WAL-as-retransmit path never ran"
        assert out["reconnects_total"] >= 1, \
            "no reconnects: the backoff path never ran"
        log(f"chaos: {len(acked)} acked batch(es), zero loss, parity "
            f"diff {parity_diff}; {ship.retransmit_bytes} retransmit "
            f"byte(s), {out['reconnects_total']} reconnect(s), "
            f"{ship.nacks} nack(s), fenced ex-leader "
            f"({ship.fence_nacks} fence nack(s))")
    finally:
        # producers must see both events even on an assert mid-flight,
        # or their non-daemon threads outlive the bench
        stop.set()
        rebound.set()
        for t in producers:
            t.join(timeout=30)
        if fe is not None:
            fe.close()
        if coord is not None:
            coord.close()
        if ship is not None:
            ship.close()
        for srv in servers:
            srv.close()
        for r in replicas:
            r.close()
        if new_sched is not None:
            new_sched.close()
        shutil.rmtree(tmp, ignore_errors=True)
    return out


# -- fleet-telemetry mode (REFLOW_BENCH_FLEETOBS=1) ------------------------

def run_fleetobs_bench() -> dict:
    """Fleet-telemetry-plane numbers (docs/guide.md "Fleet telemetry"),
    two parts on the replicated topology (leader + N replicas over
    real TCP, 16 producers):

    A. **write-path overhead** — the same fixed work (16 producers x K
       batches through the frontend, WAL shipped to every replica) run
       with the telemetry plane fully off vs fully on (tracing +
       per-node registries + per-node :class:`TelemetryShipper` at the
       production ship interval streaming to a live
       :class:`FleetAggregator` over TCP), best-of-2 walls per mode;
       acceptance: overhead < 3% on an uncontended host. Like the obs
       bench's bound this is *recorded*, not asserted — on a shared
       1-core CI box the wall noise between identical legs dwarfs 3% —
       while the structural proofs in part B are hard asserts.
    B. **fleet proofs under chaos** — the telemetry-enabled topology
       with every data link behind seeded :class:`WireFaults` runs a
       storm, then a partition/heal cycle on the last data link; after
       the heal the trace rings are reset so every causal chain in the
       export is post-heal evidence (``trace_inspect
       --require-chain ship_segment,net_send,replica_replay`` >= 1).
       At quiesce the aggregator's per-node horizons / lag / spread
       must EQUAL ground truth read directly off the replicas. Then
       the telemetry link of one node is partitioned: the aggregator
       must keep answering ``fetch_fleet`` with that node stale-marked
       (never an error), and recover once the link heals.

    Host-side CPU work; runs on the CPU executor/platform."""
    import importlib.util
    import shutil
    import tempfile
    import threading

    from reflow_tpu import obs
    from reflow_tpu.net import (FaultyTransport, ReconnectPolicy,
                                RemoteFollower, ReplicaServer,
                                TcpTransport)
    from reflow_tpu.obs.fleet import FleetAggregator, TelemetryShipper
    from reflow_tpu.obs.wire import TelemetryLink, TelemetryServer
    from reflow_tpu.serve import (CoalesceWindow, IngestFrontend,
                                  LeaderReadAdapter, ReadTier,
                                  ReplicaScheduler)
    from reflow_tpu.utils.faults import WireFaults
    from reflow_tpu.wal import DurableScheduler, SegmentShipper
    from reflow_tpu.workloads import wordcount

    smoke = env_flag("REFLOW_BENCH_SMOKE")
    n_replicas = max(2, env_int("REFLOW_BENCH_CHAOS_N", "3"))
    n_prod = 16
    rows_per_batch = 8
    per_producer = env_int("REFLOW_BENCH_FLEETOBS_BATCHES",
                           "160" if smoke else "320")
    run_s = env_float("REFLOW_BENCH_CHAOS_RUN_S",
                      "0.3" if smoke else "0.8")
    fault_seed = env_int("REFLOW_NET_FAULT_SEED", "0")
    ship_interval = 0.05
    window_ticks = 4

    out = {"replicas": n_replicas, "producers": n_prod,
           "per_producer_batches": per_producer,
           "rows_per_batch": rows_per_batch, "run_s": run_s,
           "fault_seed": fault_seed}
    tmp = tempfile.mkdtemp(prefix="reflow-fleetobs-")

    def make_lines(producer: int, j: int) -> list:
        rng = np.random.default_rng(producer * 100_003 + j)
        return [" ".join(f"w{int(x)}"
                         for x in rng.integers(0, 1000, rows_per_batch))]

    # -- part A: fixed-work A/B on the clean replicated topology ----------

    def run_fixed(root: str, telemetry: bool) -> float:
        """One fixed-work pass; rows/s. Identical topology both ways —
        only the telemetry plane differs."""
        fe = ship = tsrv = agg = sched = None
        replicas, servers, shippers, regs = [], [], [], []
        try:
            g, src, _sink = wordcount.build_graph()
            sched = DurableScheduler(g, wal_dir=os.path.join(root, "wal"),
                                     fsync="tick", committer="thread",
                                     segment_bytes=1 << 20)
            fe = IngestFrontend(sched, window=CoalesceWindow(
                max_rows=65536, max_ticks=window_ticks,
                max_latency_s=0.002))
            ship = SegmentShipper(sched.wal,
                                  leader_tick=lambda: sched._tick,
                                  poll_s=0.001)
            for i in range(n_replicas):
                gr, _s, _k = wordcount.build_graph()
                r = ReplicaScheduler(gr, os.path.join(root, f"r{i}"),
                                     name=f"r{i}")
                srv = ReplicaServer(r, TcpTransport()).start()
                link = RemoteFollower(
                    TcpTransport(), srv.address, name=f"r{i}",
                    policy=ReconnectPolicy(f"r{i}", base_s=0.005,
                                           cap_s=0.05, seed=fault_seed),
                    io_timeout_s=0.2)
                ship.attach(link)
                replicas.append(r)
                servers.append(srv)
            if telemetry:
                obs.trace.reset()
                obs.enable()
                agg = FleetAggregator(retention=64, stale_after_s=2.0)
                tsrv = TelemetryServer(agg, TcpTransport()).start()
                reg_leader = obs.MetricsRegistry()
                fe.publish_metrics(reg_leader)
                ship.publish_metrics(reg_leader)
                regs.append(("leader", reg_leader))
                for i, r in enumerate(replicas):
                    reg_r = obs.MetricsRegistry()
                    r.publish_metrics(reg_r)
                    regs.append((f"r{i}", reg_r))
                for node, reg in regs:
                    # production-default ship interval: the A/B legs
                    # price the plane as deployed, not the fast beat
                    # part B uses to exercise staleness
                    sh = TelemetryShipper(
                        reg, TcpTransport(), tsrv.address, node=node,
                        policy=ReconnectPolicy(f"tele/{node}",
                                               base_s=0.005, cap_s=0.05,
                                               seed=fault_seed),
                        io_timeout_s=0.5)
                    sh.publish_metrics()
                    shippers.append(sh.start())
            else:
                obs.disable()
                obs.trace.reset()
            ship.start()

            tickets: list = []
            tk_lock = threading.Lock()

            def produce(pid, fe=fe, src=src):
                mine = [fe.submit(src, wordcount.ingest_lines(
                    make_lines(pid, j))) for j in range(per_producer)]
                with tk_lock:
                    tickets.extend(mine)

            threads = [threading.Thread(target=produce, args=(pid,))
                       for pid in range(n_prod)]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            fe.flush()
            wall = time.perf_counter() - t0
            assert all(t.result(timeout=30).applied for t in tickets)
            return n_prod * per_producer * rows_per_batch / wall
        finally:
            for sh in shippers:
                sh.close()
            if tsrv is not None:
                tsrv.close()
            if agg is not None:
                agg.close()
            if fe is not None:
                fe.close()
            if ship is not None:
                ship.close()
            for srv in servers:
                srv.close()
            for r in replicas:
                r.close()
            if sched is not None:
                sched.wal.close()
            obs.disable()

    try:
        rate_off = max(run_fixed(os.path.join(tmp, f"off{k}"), False)
                       for k in range(2))
        rate_on = max(run_fixed(os.path.join(tmp, f"on{k}"), True)
                      for k in range(2))
        out["disabled_rows_per_s"] = round(rate_off)
        out["enabled_rows_per_s"] = round(rate_on)
        overhead = 1.0 - rate_on / rate_off
        out["fleetobs_overhead_frac"] = round(overhead, 4)
        out["fleetobs_overhead_lt_3pct"] = overhead < 0.03
        log(f"fleetobs: off {rate_off:.0f} rows/s, on {rate_on:.0f} "
            f"rows/s (overhead {100 * overhead:.2f}%)")

        # -- part B: fleet proofs on the faulted topology ------------------
        fe = ship = tsrv = agg = probe = sched = None
        replicas, servers, links, faults = [], [], [], []
        shippers, tele_faults, producers = [], [], []
        stop = threading.Event()
        try:
            obs.trace.reset()
            obs.enable()
            g, src, sink = wordcount.build_graph()
            sched = DurableScheduler(g, wal_dir=os.path.join(tmp, "wal"),
                                     fsync="tick", committer="thread",
                                     segment_bytes=1 << 20)
            fe = IngestFrontend(sched, window=CoalesceWindow(
                max_rows=65536, max_ticks=window_ticks,
                max_latency_s=0.002))
            ship = SegmentShipper(sched.wal,
                                  leader_tick=lambda: sched._tick,
                                  poll_s=0.001)
            for i in range(n_replicas):
                gr, _s, _k = wordcount.build_graph()
                r = ReplicaScheduler(gr, os.path.join(tmp, f"br{i}"),
                                     name=f"r{i}")
                srv = ReplicaServer(r, TcpTransport()).start()
                wf = WireFaults(seed=fault_seed + 17 * i + 1)
                link = RemoteFollower(
                    FaultyTransport(TcpTransport(), wf), srv.address,
                    name=f"r{i}",
                    policy=ReconnectPolicy(f"r{i}", base_s=0.005,
                                           cap_s=0.05, seed=fault_seed),
                    io_timeout_s=0.05)
                ship.attach(link)
                replicas.append(r)
                servers.append(srv)
                links.append(link)
                faults.append(wf)
            tier = ReadTier(replicas, leader=LeaderReadAdapter(sched))
            for r, link in zip(replicas, links):
                tier.bind_link(r, link)

            # the telemetry plane: one registry + shipper per node,
            # every telemetry link behind its OWN WireFaults pair
            agg = FleetAggregator(retention=64, stale_after_s=0.35)
            tsrv = TelemetryServer(agg, TcpTransport()).start()
            reg_leader = obs.MetricsRegistry()
            fe.publish_metrics(reg_leader)
            ship.publish_metrics(reg_leader)
            tier.publish_metrics(reg_leader)
            node_regs = [("leader", reg_leader)]
            for i, r in enumerate(replicas):
                reg_r = obs.MetricsRegistry()
                r.publish_metrics(reg_r)
                node_regs.append((f"r{i}", reg_r))
            for node, reg in node_regs:
                tf = WireFaults(seed=fault_seed + 91 + len(tele_faults))
                sh = TelemetryShipper(
                    reg, FaultyTransport(TcpTransport(), tf),
                    tsrv.address, node=node, interval_s=ship_interval,
                    policy=ReconnectPolicy(f"tele/{node}", base_s=0.005,
                                           cap_s=0.05, seed=fault_seed),
                    io_timeout_s=0.25)
                sh.publish_metrics()
                tele_faults.append(tf)
                shippers.append(sh.start())
            ship.start()

            def produce(pid):
                rng = np.random.default_rng(1000 + pid)
                seq = 0
                while not stop.is_set():
                    words = " ".join(
                        f"w{int(x)}" for x in rng.integers(0, 1000, 24))
                    bid = f"p{pid}-{seq}"
                    batch = wordcount.ingest_lines([words])
                    deadline = time.monotonic() + 60
                    while time.monotonic() < deadline:
                        res = fe.submit(src, batch,
                                        batch_id=bid).result(timeout=60)
                        if res.status in ("applied", "deduped"):
                            break
                        time.sleep(0.001)
                    seq += 1

            producers.extend(
                threading.Thread(target=produce, args=(pid,))
                for pid in range(n_prod))
            for t in producers:
                t.start()

            # storm on every data link, then partition + heal the last
            for wf in faults:
                wf.set_rates(drop_c2s=0.03, drop_s2c=0.03, dup=0.03,
                             reorder=0.03, corrupt_frame=0.01,
                             delay_p=0.05, delay_s=0.002)
            time.sleep(run_s)
            target = n_replicas - 1
            faults[target].partition("c2s")
            time.sleep(0.15)
            faults[target].heal()
            for wf in faults:
                wf.quiesce()
            # post-heal evidence window: reset the rings so every
            # complete causal chain in the export was minted AFTER the
            # partition healed
            obs.trace.reset()
            time.sleep(run_s / 2)
            stop.set()
            for t in producers:
                t.join(timeout=60)
            fe.flush()
            sched.wal.sync()
            deadline = time.monotonic() + 30
            while (any(r.published_horizon() != sched._tick
                       for r in replicas)
                   and time.monotonic() < deadline):
                time.sleep(0.005)
            lag_after = max(r.lag_ticks() for r in replicas)
            out["lag_after_quiesce_ticks"] = lag_after
            assert lag_after == 0, f"replicas never converged: {lag_after}"

            # (b) aggregator vs ground truth at quiesce: force fresh
            # snapshots (twice, spaced, so the qps window exists)
            for _ in range(2 * n_replicas):
                tier.top_k(sink.name, 5, by="value")
            for sh in shippers:
                sh.ship_once()
            time.sleep(0.08)
            for _ in range(2 * n_replicas):
                tier.top_k(sink.name, 5, by="value")
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if all(sh.ship_once() for sh in shippers):
                    break
                time.sleep(0.02)
            truth = {r.name: r.published_horizon() for r in replicas}
            snap = agg.fleet_snapshot()
            agg_h = {n: e["horizon"] for n, e in snap["nodes"].items()
                     if n != "leader"}
            assert agg_h == truth, (agg_h, truth)
            assert all(e["lag_ticks"] == 0
                       for n, e in snap["nodes"].items()
                       if n != "leader"), snap["nodes"]
            spread_truth = max(truth.values()) - min(truth.values())
            out["lag_spread_agg"] = snap["gauges"]["lag_spread"]
            out["lag_spread_truth"] = spread_truth
            assert snap["gauges"]["lag_spread"] == spread_truth
            assert snap["gauges"]["epoch_agree"] is True
            out["aggregate_read_qps"] = snap["gauges"][
                "aggregate_read_qps"]
            assert out["aggregate_read_qps"] is not None, \
                "fleet read-qps window never formed"
            out["fleet_nodes"] = snap["gauges"]["nodes_total"]
            assert out["fleet_nodes"] == n_replicas + 1
            log(f"fleetobs: aggregator horizons == ground truth "
                f"{truth}, spread {spread_truth}, "
                f"qps {out['aggregate_read_qps']}")

            # (c) causal chains survived the partition/heal cycle
            trace_path = os.path.join(tmp, "fleet_trace.json")
            obs.export_chrome_trace(trace_path)
            spec = importlib.util.spec_from_file_location(
                "trace_inspect", os.path.join(
                    os.path.dirname(os.path.abspath(__file__)),
                    "tools", "trace_inspect.py"))
            ti = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(ti)
            causal = ti.inspect(trace_path, require_chain=[
                "ship_segment", "net_send", "replica_replay"])["causal"]
            out["post_heal_chains"] = causal["chains"]
            out["post_heal_complete_chains"] = causal["complete_chains"]
            out["post_heal_required_chains"] = causal["required_chains"]
            assert causal["required_chains"] >= 1, \
                "no post-heal causal chain spans ship->send->replay"
            keep_trace = env_str("REFLOW_TRACE_OUT",
                                 "/tmp/reflow_fleet_trace.json")
            shutil.copyfile(trace_path, keep_trace)
            out["trace_file"] = keep_trace
            log(f"fleetobs: {causal['required_chains']} post-heal "
                f"causal chain(s) ship_segment->net_send->"
                f"replica_replay -> {keep_trace}")

            # (d) telemetry-link partition: the aggregator keeps
            # serving with r0 stale-marked, then recovers on heal
            tele_faults[1].partition("c2s")  # node_regs[1] == r0
            deadline = time.monotonic() + 15
            stale = []
            while time.monotonic() < deadline:
                stale = agg.stale_nodes()
                if "r0" in stale:
                    break
                time.sleep(0.02)
            assert "r0" in stale, "telemetry partition never went stale"
            probe = TelemetryLink(TcpTransport(), tsrv.address,
                                  node="bench-probe", io_timeout_s=2.0)
            during = probe.fetch_fleet()
            assert during is not None, \
                "aggregator stopped serving during telemetry partition"
            assert during["nodes"]["r0"]["stale"] is True
            assert any(a.startswith("stale: r0")
                       for a in during["alerts"]), during["alerts"]
            out["stale_during_partition"] = sorted(
                n for n, e in during["nodes"].items() if e["stale"])
            r0_shipper = shippers[1]
            assert r0_shipper.dropped > 0, \
                "partitioned shipper never dropped a snapshot"
            out["telemetry_dropped_r0"] = r0_shipper.dropped
            tele_faults[1].heal()
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                if "r0" not in agg.stale_nodes():
                    break
                time.sleep(0.02)
            after = probe.fetch_fleet()
            assert after is not None \
                and after["nodes"]["r0"]["stale"] is False, \
                "telemetry link never recovered after heal"
            out["telemetry_partition_recovered"] = True
            out["snapshots_total"] = agg.snapshots_total
            fleet_path = "/tmp/reflow_fleet_snapshot.json"
            with open(fleet_path, "w") as f:
                json.dump(after, f, indent=2, sort_keys=True)
            out["fleet_snapshot_file"] = fleet_path
            log(f"fleetobs: aggregator served through the telemetry "
                f"partition (stale={out['stale_during_partition']}, "
                f"{r0_shipper.dropped} dropped) and recovered "
                f"-> {fleet_path}")
        finally:
            stop.set()
            for t in producers:
                t.join(timeout=30)
            if probe is not None:
                probe.close()
            for sh in shippers:
                sh.close()
            if tsrv is not None:
                tsrv.close()
            if agg is not None:
                agg.close()
            if fe is not None:
                fe.close()
            if ship is not None:
                ship.close()
            for srv in servers:
                srv.close()
            for r in replicas:
                r.close()
            if sched is not None:
                sched.wal.close()
            obs.disable()
            obs.trace.reset()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return out


# -- multi-process mode (REFLOW_BENCH_MULTIPROC=1) -------------------------

def run_multiproc_bench() -> dict:
    """The multi-controller leg as real OS processes (docs/guide.md
    "Multi-process deployment"): a leader + N replica + M producer
    *process* fleet under a kill -9 storm.

    Storm script: spawn the fleet (every child ships telemetry to the
    parent's FleetAggregator), let the producers pump over the
    ingestion RPC, then kill -9 every replica in turn (respawn each
    over its state directory; it recovers from its mirrored WAL and
    rejoins through the cross-process horizon barrier), then kill -9
    the *leader* and drive a FailoverCoordinator whose candidates are
    the replica processes — the winner promotes in-child and starts
    serving ingestion; producers reconnect, resubmit their in-doubt
    batches, and the dedup mirror keeps them exactly-once.

    Hard asserts: zero acked-write loss (a DirtyScheduler oracle
    refolds every acked batch — content regenerated from (producer,
    seq) alone — and must equal the new leader's wire-read view
    exactly); exact parity at equal horizons on every surviving
    replica; the promotion happened (epoch 1, winner is a replica);
    every producer exited with an empty in-doubt set; the reconnect /
    resubmit paths actually fired; the fleet aggregator saw every
    process. Host-side CPU work; children run with JAX_PLATFORMS=cpu.
    """
    import shutil
    import tempfile

    from reflow_tpu.proc import ProcHarness
    from reflow_tpu.proc.worker import producer_batch_words
    from reflow_tpu.proc.harness import ControlClient
    from reflow_tpu.scheduler import DirtyScheduler
    from reflow_tpu.workloads import wordcount

    smoke = env_flag("REFLOW_BENCH_SMOKE")
    n_replicas = max(2, env_int("REFLOW_BENCH_MULTIPROC_N", "3"))
    n_prod = max(1, env_int("REFLOW_BENCH_MULTIPROC_PRODUCERS", "4"))
    run_s = env_float("REFLOW_BENCH_MULTIPROC_RUN_S",
                      "0.6" if smoke else "1.5")

    # an oversubscribed host (fleet processes > cores) needs paced
    # producers, or the spin-looping fleet starves a recovering child
    n_procs = 1 + n_replicas + n_prod
    pace_s = 0.02 if (os.cpu_count() or 1) < n_procs else 0.0
    out = {"replicas": n_replicas, "producers": n_prod, "run_s": run_s,
           "producer_pace_s": pace_s}
    root = tempfile.mkdtemp(prefix="reflow-multiproc-")
    h = ProcHarness(root, child_env={"JAX_PLATFORMS": "cpu"})
    try:
        h.spawn_leader(fsync="tick", epoch=0)
        rnames = [f"r{i}" for i in range(n_replicas)]
        for nm in rnames:
            h.spawn_replica(nm)
        h.attach_replicas()
        for i in range(n_prod):
            h.spawn_producer(f"p{i}", index=i, pace_s=pace_s)
        fleet_target = 1 + n_replicas + n_prod
        out["fleet_nodes_expected"] = fleet_target
        out["fleet_nodes_seen"] = (
            h.aggregator.await_nodes(fleet_target, timeout_s=15.0))
        assert out["fleet_nodes_seen"], \
            f"fleet aggregator saw {h.aggregator.node_count()} nodes, " \
            f"wanted {fleet_target}"
        time.sleep(run_s)

        # -- kill -9 storm over the replica tier, one at a time -------
        for nm in rnames:
            h.kill9(nm)
            time.sleep(0.1)
            h.respawn(nm)
            h.attach_replicas([nm])
            h.barrier(timeout_s=60.0)  # the respawn rejoins the cut
        time.sleep(run_s / 2)

        # -- then the leader: cross-process failover ------------------
        coord = h.coordinator(epoch=0, confirm_intervals=2,
                              drain_timeout_s=10.0)
        h.kill9("leader")
        t_kill = time.monotonic()
        promote_evt = None
        now = 0.0
        while promote_evt is None and time.monotonic() - t_kill < 60.0:
            for e in coord.step(now):
                if e.get("kind") == "failover_promote":
                    promote_evt = e
            now += 1.0
            time.sleep(0.02)
        assert promote_evt is not None, "leader death never promoted"
        out["promotion_s"] = time.monotonic() - t_kill
        out["winner"] = promote_evt["winner"]
        out["epoch"] = promote_evt["epoch"]
        out["drained_bytes"] = promote_evt["drained_bytes"]
        assert out["winner"] in rnames
        assert out["epoch"] == 1
        assert h.leader_name == out["winner"]

        # producers reconnect + resubmit against the recovered mirror
        time.sleep(run_s)

        # -- quiesce: stop producers (each drains its in-flight batch
        # to a terminal ack), then flush the new leader over the wire
        prod_exits = []
        for i in range(n_prod):
            st = h.child(f"p{i}").stop()
            assert st is not None and st.get("ok"), \
                f"producer p{i} died dirty: {st!r}"
            prod_exits.append(st)
        out["reconnects_total"] = sum(s["reconnects"]
                                      for s in prod_exits)
        out["resubmits_total"] = sum(s["resubmits"] for s in prod_exits)
        out["deduped_total"] = sum(s["deduped"] for s in prod_exits)
        for st in prod_exits:
            assert st["in_doubt"] == [], \
                f"{st['name']} exited in doubt: {st['in_doubt']}"
        assert out["reconnects_total"] >= n_prod, \
            "the leader kill never forced a producer reconnect"
        assert out["resubmits_total"] >= 1

        g, src, sink = wordcount.build_graph()
        ingest = ControlClient(h.ingest_address, io_timeout_s=30.0)
        ingest.call("flush", 20.0)
        _, leader_tick, leader_view = ingest.call("view", sink.name)

        # zero acked-write loss: refold every acked batch from
        # (producer index, seq) alone — the content is deterministic
        oracle = DirtyScheduler(g)
        acked_batches = 0
        for i, st in enumerate(prod_exits):
            for seq, _status in st["acked"]:
                words = " ".join(producer_batch_words(i, seq))
                oracle.push(src, wordcount.ingest_lines([words]),
                            batch_id=f"p{i}-{seq}")
                acked_batches += 1
        oracle.tick()
        want = {kv: w for kv, w in oracle.view(sink.name).items()
                if w != 0}
        got = {kv: w for kv, w in leader_view.items() if w != 0}
        diff = 0
        for kv in set(want) | set(got):
            diff = max(diff, abs(want.get(kv, 0) - got.get(kv, 0)))
        out["acked_batches"] = acked_batches
        out["acked_loss_max_abs_diff"] = diff
        assert diff == 0, f"acked-write loss: max_abs_diff={diff}"

        # exact parity at equal horizons on every surviving replica,
        # read over each child's own wire protocol
        survivors = [nm for nm in rnames if nm != h.leader_name]
        h.barrier(names=survivors, min_horizon=leader_tick,
                  timeout_s=30.0)
        parity_diff = 0
        for nm in survivors:
            _, rh, rv = h.control(nm).call("view", sink.name)
            assert rh == leader_tick, (nm, rh, leader_tick)
            for kv in set(got) | set(rv):
                parity_diff = max(
                    parity_diff, abs(got.get(kv, 0) - rv.get(kv, 0)))
        out["parity_max_abs_diff"] = parity_diff
        assert parity_diff == 0

        out["leader_tick"] = leader_tick
        out["kills"] = h.kills
        out["respawns"] = h.respawns
        assert h.kills == n_replicas + 1 and h.respawns == n_replicas
    finally:
        h.close()
        shutil.rmtree(root, ignore_errors=True)
    return out


# -- end-to-end tracing mode (REFLOW_BENCH_E2ETRACE=1) ---------------------

def run_e2etrace_bench() -> dict:
    """Follow-the-write under chaos (docs/guide.md "End-to-end tracing
    & flight recorder"): the multi-process topology — a leader + 2
    replica + N producer *processes* over the ingestion RPC, live wire
    subscribers pumped in the parent — with tracing AND flight
    recorders on in every child, then kill -9 of a replica and of the
    leader mid-run (cross-process promotion, producers and subscribers
    retargeted).

    Hard asserts, all structural:

    - **full chains** — merging every clean-exit child's exported
      trace plus the parent's own onto one timeline
      (``trace_inspect`` multi-file, ``baseTimeS``-anchored), at least
      one sampled write's causal group carries all nine links
      ``producer_submit -> rpc_admit -> admission -> wal_append ->
      ship_segment -> net_send -> replica_replay -> sub_fanout ->
      sub_deliver``, and at least one ``producer_submit`` was minted
      in the post-promotion epoch (the chain survived the failover);
    - **freshness tiles** — the ack->deliver decomposition of the
      full chains sums to their end-to-end latency within 10%;
    - **flight recordings survive kill -9** — the dead leader's disk
      corner (and the killed replica's archived ``.prev`` incarnation)
      merge via ``tools/reflow_flight`` into a timeline that carries
      the failover evidence, even though those processes never flushed
      a trace export;
    - **wire compat** — with tracing off, ``SubmitReq`` /
      ``SubmitAck`` / ``DeltaFrame`` wire forms pickle byte-identically
      to the pre-trace protocol (the trailing-``cause`` trim).

    Host-side CPU work; children run with ``JAX_PLATFORMS=cpu``.
    """
    import importlib.util
    import pickle
    import shutil
    import tempfile
    import threading

    from reflow_tpu import obs
    from reflow_tpu.net.transport import TcpTransport
    from reflow_tpu.proc import ProcHarness
    from reflow_tpu.proc.harness import ControlClient
    from reflow_tpu.serve.rpc import SubmitAck, SubmitReq, _trim
    from reflow_tpu.subs.client import Subscriber
    from reflow_tpu.subs.query import DeltaFrame, frames_to_wire
    from reflow_tpu.workloads import wordcount

    smoke = env_flag("REFLOW_BENCH_SMOKE")
    n_replicas = 2
    n_prod = max(1, env_int("REFLOW_BENCH_E2ETRACE_PRODUCERS",
                            "4" if smoke else "16"))
    run_s = env_float("REFLOW_BENCH_E2ETRACE_RUN_S",
                      "0.6" if smoke else "1.5")
    n_procs = 2 + n_replicas + n_prod  # + the parent pumping subs
    pace_s = 0.02 if (os.cpu_count() or 1) < n_procs else 0.0
    out = {"replicas": n_replicas, "producers": n_prod, "run_s": run_s,
           "producer_pace_s": pace_s}

    # -- wire compat: tracing-off frames byte-identical -----------------
    # (in-process, before the parent enables tracing: the trim must
    # reduce unstamped requests/acks/frames to the exact pre-trace
    # pickle bytes, and a stamped frame must still parse one-sided)
    req = SubmitReq("b-0", "words", ("payload",), 5.0)
    assert pickle.dumps(_trim(tuple(req))) == \
        pickle.dumps(("b-0", "words", ("payload",), 5.0))
    ack = SubmitAck("b-0", "applied", ("r",), None)
    assert pickle.dumps(_trim(tuple(ack))) == \
        pickle.dumps(("b-0", "applied", ("r",), None))
    frame = DeltaFrame(0, 4, "view", ((("k", "v"), 1),), False)
    assert pickle.dumps(frames_to_wire([frame])) == \
        pickle.dumps(((0, 4, "view", ((("k", "v"), 1),), False),))
    stamped = DeltaFrame(0, 4, "view", (), False, ("n#0#1",))
    assert frames_to_wire([stamped])[0][-1] == ("n#0#1",)
    out["wire_compat_identical"] = True

    root = tempfile.mkdtemp(prefix="reflow-e2etrace-")
    keep_dir = os.path.join(tempfile.gettempdir(),
                            "reflow_e2etrace_traces")
    child_env = {"JAX_PLATFORMS": "cpu", "REFLOW_TRACE": "1",
                 "REFLOW_FLIGHT": "1"}
    h = ProcHarness(root, child_env=child_env)
    obs.trace.reset()
    obs.enable()  # the parent records sub_deliver — the chain's last link
    subs: dict = {}
    pumpers: list = []
    stop_pump = threading.Event()
    g, src, sink = wordcount.build_graph()
    try:
        h.spawn_leader(fsync="tick", epoch=0)
        rnames = [f"r{i}" for i in range(n_replicas)]
        for nm in rnames:
            h.spawn_replica(nm)
        h.attach_replicas()
        for i in range(n_prod):
            h.spawn_producer(f"p{i}", index=i, pace_s=pace_s)

        # live subscribers in the parent, one per replica, pumped from
        # background threads for the whole run (kills included)
        for nm in rnames:
            sub = Subscriber(TcpTransport(),
                             tuple(h.child(nm).ready["subs"]),
                             sink.name, kind="view", name=f"sub-{nm}")
            subs[nm] = sub

            def pump(sub=sub):
                while not stop_pump.is_set():
                    sub.pump(wait_s=0.1)

            t = threading.Thread(target=pump, name=f"pump/{nm}",
                                 daemon=True)
            t.start()
            pumpers.append(t)
        log("e2etrace: fleet up, load running")
        time.sleep(run_s)

        # -- kill -9 a replica mid-run: its flight ring survives on
        # disk; the respawn archives it as the .prev generation -------
        h.kill9(rnames[0])
        time.sleep(0.1)
        h.respawn(rnames[0])
        h.attach_replicas([rnames[0]])
        h.barrier(timeout_s=60.0)
        subs[rnames[0]].retarget(
            tuple(h.child(rnames[0]).ready["subs"]))
        log("e2etrace: replica kill/respawn healed")
        time.sleep(run_s / 2)

        # -- then the leader: cross-process failover ------------------
        coord = h.coordinator(epoch=0, confirm_intervals=2,
                              drain_timeout_s=10.0)
        h.kill9("leader")
        t_kill = time.monotonic()
        promote_evt = None
        now = 0.0
        while promote_evt is None and time.monotonic() - t_kill < 60.0:
            for e in coord.step(now):
                if e.get("kind") == "failover_promote":
                    promote_evt = e
            now += 1.0
            time.sleep(0.02)
        assert promote_evt is not None, "leader death never promoted"
        out["promotion_s"] = time.monotonic() - t_kill
        out["winner"] = promote_evt["winner"]
        out["epoch"] = promote_evt["epoch"]
        assert out["epoch"] == 1
        winner = out["winner"]
        log(f"e2etrace: promoted {winner} in {out['promotion_s']:.1f}s")
        survivors = [nm for nm in rnames if nm != winner]
        # the winner now serves ingestion; keep its subscriber on a
        # replica that still replays shipped windows
        subs[winner].retarget(
            tuple(h.child(survivors[0]).ready["subs"]))
        time.sleep(run_s)  # post-promotion writes: epoch-1 chains

        # -- quiesce + drain the last deltas to the subscribers -------
        prod_exits = []
        for i in range(n_prod):
            st = h.child(f"p{i}").stop()
            assert st is not None and st.get("ok"), \
                f"producer p{i} died dirty: {st!r}"
            assert st["in_doubt"] == [], \
                f"{st['name']} exited in doubt: {st['in_doubt']}"
            prod_exits.append(st)
        out["reconnects_total"] = sum(s["reconnects"]
                                      for s in prod_exits)
        log("e2etrace: producers stopped; draining")

        # -- deterministically mint a sampled write in the NEW epoch --
        # in-doubt resubmits keep their epoch-0 tokens, and on a 1-CPU
        # box the paced producers may never draw a 1-in-N sample inside
        # the short post-promotion window — so the parent probes the
        # promoted leader until one token carries epoch 1 (at most
        # ~2*SAMPLE_EVERY submits: the first mint happens before the
        # hello that learns the new epoch). Probing after the producer
        # quiesce keeps it off the saturated admission queue.
        from reflow_tpu.proc.worker import producer_batch_words
        from reflow_tpu.serve import APPLIED, DEDUPED, RemoteProducer
        probe = RemoteProducer(TcpTransport(), h.ingest_address,
                               name="probe")
        try:
            probe_cause = None
            t_probe0 = time.monotonic()
            for i in range(2 * obs.trace.SAMPLE_EVERY + 2):
                pbatch = wordcount.ingest_lines(
                    [" ".join(producer_batch_words(97, i))])
                ticket = probe.submit(src.name, pbatch, timeout=30.0)
                while True:
                    assert time.monotonic() - t_probe0 < 120.0, \
                        f"probe submit never acked ({i} sent)"
                    try:
                        res = ticket.result(timeout=0.3)
                    except TimeoutError:
                        continue
                    if res.status in (APPLIED, DEDUPED):
                        break
                    assert res.status != "rejected" or \
                        "backpressure" in str(res.reason), \
                        f"probe rejected: {res.reason}"
                    # backpressure/SHED: same id, retry
                    time.sleep(0.05)
                    ticket = probe.submit(src.name, pbatch,
                                          batch_id=ticket.batch_id,
                                          timeout=30.0)
                if ticket.cause is not None and "#1#" in ticket.cause:
                    probe_cause = ticket.cause
                    break
            assert probe_cause is not None, \
                "no probe token minted in the new epoch"
            out["probe_cause"] = probe_cause
            log(f"e2etrace: epoch-1 probe token {probe_cause}")
        finally:
            probe.close()

        ingest = ControlClient(h.ingest_address, io_timeout_s=30.0)
        ingest.call("flush", 20.0)
        _, leader_tick, _view = ingest.call("view", sink.name)
        out["leader_tick"] = leader_tick
        h.barrier(names=survivors, min_horizon=leader_tick,
                  timeout_s=30.0)
        stop_pump.set()
        for t in pumpers:
            t.join(timeout=30)
        for nm, sub in subs.items():
            assert sub.wait_horizon(leader_tick, timeout_s=30.0), \
                f"subscriber {nm} stalled at {sub.horizon}/{leader_tick}"
            assert sub.gaps_total == 0, f"subscriber {nm} saw a gap"
        out["sub_frames_applied"] = sum(
            s.frames_applied_total for s in subs.values())

        # -- fleet gauges: the new freshness/flight planes are visible
        # from the aggregator (children ship REGISTRY snapshots) ------
        deadline = time.monotonic() + 15.0
        fleet_f50 = fleet_flight = None
        while time.monotonic() < deadline:
            snap = h.aggregator.fleet_snapshot()
            fleet_f50 = snap["gauges"].get("subs.freshness_p50")
            fleet_flight = snap["gauges"].get("flight.events_total")
            if fleet_f50 is not None and fleet_flight is not None:
                break
            time.sleep(0.1)
        out["fleet_freshness_p50"] = fleet_f50
        out["fleet_flight_events"] = fleet_flight
        assert fleet_f50 is not None, \
            "subs.freshness_p50 never reached the fleet aggregator"
        assert fleet_flight is not None and fleet_flight >= 1, \
            "flight.events_total never reached the fleet aggregator"

        for sub in subs.values():
            sub.close()
        h.close()  # clean exits: every child exports <root>/<name>/trace.json

        # -- merge every process's trace onto one timeline ------------
        parent_trace = os.path.join(root, "parent-trace.json")
        obs.export_chrome_trace(parent_trace)
        trace_files = [parent_trace]
        for nm in h.children:
            p = os.path.join(root, nm, "trace.json")
            if os.path.exists(p):
                trace_files.append(p)
        # the killed leader never exported — by design; its story is
        # the flight recording below
        assert not os.path.exists(
            os.path.join(root, "leader", "trace.json"))
        out["trace_files_merged"] = len(trace_files)
        assert len(trace_files) >= 2 + n_replicas + n_prod - 1

        # keep the traces where the tier-1 smoke can re-check them —
        # copied BEFORE the structural asserts so a failing run leaves
        # its evidence behind
        shutil.rmtree(keep_dir, ignore_errors=True)
        os.makedirs(keep_dir, exist_ok=True)
        kept = []
        for p in trace_files:
            dst = os.path.join(
                keep_dir,
                f"{os.path.basename(os.path.dirname(p))}-trace.json")
            shutil.copyfile(p, dst)
            kept.append(dst)
        out["trace_files"] = kept

        spec = importlib.util.spec_from_file_location(
            "trace_inspect", os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                "tools", "trace_inspect.py"))
        ti = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(ti)
        report = ti.inspect(trace_files,
                            require_chain=list(ti.FULL_CHAIN))
        causal = report["causal"]
        assert causal is not None, "no causal tokens in any trace"
        out["causal_groups"] = causal["groups"]
        out["full_chains"] = causal["full_chains"]
        out["required_chains"] = causal["required_chains"]
        if causal["full_chains"] < 1:
            # per-file cause-span inventory: WHICH process dropped its
            # link tells you where the chain broke
            per_file = {}
            for p in trace_files:
                evs, _ = ti.load_traces([p])
                names = sorted({
                    e["name"] for e in evs if e.get("ph") == "X"
                    and ((e.get("args") or {}).get("cause")
                         or (e.get("args") or {}).get("causes"))})
                per_file[os.path.basename(os.path.dirname(p))] = names
            raise AssertionError(
                f"no full submit->deliver chain: {causal['span_names']} "
                f"per-file: {per_file}")
        assert causal["required_chains"] >= 1
        fresh = report["freshness"]
        assert fresh is not None
        out["freshness_e2e_p50_us"] = fresh["e2e_p50_us"]
        out["freshness_max_dev_frac"] = fresh["max_dev_frac"]
        out["freshness_stages"] = {
            s: fresh["stages"][s]["p50_us"]
            for s in ti.FRESHNESS_STAGES}
        assert fresh["max_dev_frac"] <= 0.10, \
            f"freshness tiling off by {fresh['max_dev_frac']:.1%} " \
            f"(worst chain: {fresh['worst']}; traces kept in {keep_dir})"
        # at least one chain was minted AFTER the promotion: its token
        # carries the new epoch (origin#1#seq)
        events, _files = ti.load_traces(trace_files)
        post_promo = sum(
            1 for e in events
            if e.get("ph") == "X" and e.get("name") == "producer_submit"
            and "#1#" in str((e.get("args") or {}).get("cause", "")))
        out["post_promotion_submits"] = post_promo
        assert post_promo >= 1, "no sampled write in the new epoch"
        log(f"e2etrace: {causal['full_chains']} full chain(s) across "
            f"{len(trace_files)} trace file(s), freshness e2e p50 "
            f"{fresh['e2e_p50_us']:.0f}us (tiling dev "
            f"{100 * fresh['max_dev_frac']:.2f}%), {post_promo} "
            f"post-promotion sampled submit(s)")

        # -- post-mortem: the killed processes' flight recordings ------
        spec = importlib.util.spec_from_file_location(
            "reflow_flight", os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                "tools", "reflow_flight.py"))
        rf = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(rf)
        flight = rf.merge([root])
        out["flight_nodes"] = sorted(flight["nodes"])
        assert "leader" in flight["nodes"] \
            and flight["nodes"]["leader"]["events"] >= 1, \
            "the kill -9'd leader left no flight recording"
        # the killed replica's dead incarnation survives as .prev
        # beside its respawn's live ring: two distinct pids recorded
        # under one corner (a short run may never flip a->b, so file
        # count alone proves less than recovered-pid count)
        assert len(flight["nodes"][rnames[0]]["pids"]) >= 2 and \
            flight["nodes"][rnames[0]]["files"] >= 2, \
            flight["nodes"][rnames[0]]
        assert any(ev["name"] in ("failover_elect", "failover_replay")
                   for ev in flight["events"]), \
            "no failover evidence in the merged flight timeline"
        out["flight_events_total"] = len(flight["events"])
        log(f"e2etrace: flight recordings from "
            f"{len(flight['nodes'])} node(s) "
            f"({out['flight_events_total']} event(s)) — killed "
            f"leader + {rnames[0]}'s .prev incarnation recovered")

        flight_path = os.path.join(keep_dir, "flight_merged.json")
        with open(flight_path, "w") as f:
            json.dump(flight, f, indent=2, sort_keys=True)
        out["flight_merged_file"] = flight_path
        out["kills"] = h.kills
        out["respawns"] = h.respawns
    finally:
        stop_pump.set()
        for sub in subs.values():
            try:
                sub.close()
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass
        h.close()
        obs.disable()
        obs.trace.reset()
        shutil.rmtree(root, ignore_errors=True)
    return out


# -- tier / multi-graph serving mode (REFLOW_BENCH_TIER=1) -----------------

def run_tier_bench() -> dict:
    """Multi-graph serving-tier numbers (docs/guide.md "Serving tier"),
    three phases:

    A. **throughput** — 4 graphs x 4 producers each on a 2-thread
       ``ServeTier`` pump pool vs the same load on 4 independent
       ``IngestFrontend``\\ s (4 private pump threads), asserting zero
       forced syncs on every scheduler (the pool only ever calls
       ``tick_many``);
    B. **crash isolation** — a ``pool_window@<name>`` kill on one
       durable graph: its undecided tickets fail ``PumpCrashed``,
       siblings keep applying on the surviving pool, and WAL
       ``recover()`` + same-id re-send lands exactly-once;
    C. **QoS isolation** — a hot tenant saturating its budget ceiling
       next to a quiet tenant with a byte floor: the quiet tenant's
       admission p99 must stay bounded.

    Host-side CPU work (no tunnel protocol applies).
    """
    import tempfile
    import threading

    from reflow_tpu.scheduler import DirtyScheduler
    from reflow_tpu.serve import (CoalesceWindow, GraphConfig,
                                  IngestFrontend, PumpCrashed, ServeTier)
    from reflow_tpu.utils.faults import CrashInjector
    from reflow_tpu.utils.metrics import summarize, summarize_tier
    from reflow_tpu.wal import DurableScheduler, recover
    from reflow_tpu.workloads import wordcount

    smoke = env_flag("REFLOW_BENCH_SMOKE")
    per_producer = env_int("REFLOW_BENCH_TIER_BATCHES", "30" if smoke else "200")
    rows_per_batch = 8
    n_graphs = n_prod = 4
    window = CoalesceWindow(max_rows=4096, max_ticks=8,
                            max_latency_s=0.005)

    def make_lines(graph: int, producer: int, j: int) -> list:
        rng = np.random.default_rng(
            (graph * 101 + producer) * 100_003 + j)
        return [" ".join(f"w{int(x)}"
                         for x in rng.integers(0, 1000, rows_per_batch))]

    out = {"graphs": n_graphs, "producers_per_graph": n_prod,
           "per_producer_batches": per_producer,
           "rows_per_batch": rows_per_batch}
    n_batches = n_graphs * n_prod * per_producer

    def drive(submit_targets):
        # submit_targets: list of (submitfn, src) per graph; returns wall
        tickets, tk_lock = [], threading.Lock()

        def produce(gi, pid, submitfn, src):
            mine = [submitfn(src, wordcount.ingest_lines(
                make_lines(gi, pid, j))) for j in range(per_producer)]
            with tk_lock:
                tickets.extend(mine)

        threads = [threading.Thread(target=produce,
                                    args=(gi, pid, fn, src))
                   for gi, (fn, src) in enumerate(submit_targets)
                   for pid in range(n_prod)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return tickets, t0

    # -- phase A: tier (2 pump threads) vs 4 independent frontends --------
    tier = ServeTier(max_bytes=64 << 20, pump_threads=2)
    scheds, targets, handles = [], [], []
    for gi in range(n_graphs):
        g, src, _sink = wordcount.build_graph()
        sched = DirtyScheduler(g)
        h = tier.register(f"g{gi}", sched, GraphConfig(window=window))
        scheds.append(sched)
        targets.append((h.submit, src))
        handles.append(h)
    tickets, t0 = drive(targets)
    for h in handles:
        h.flush()
    tier_wall = time.perf_counter() - t0
    assert all(t.result(timeout=30).applied for t in tickets)
    tm = summarize_tier(tier)
    forced = sum(summarize(s.history).forced_syncs for s in scheds)
    tier.close()
    tier_rate = n_batches * rows_per_batch / tier_wall
    out["tier_rows_per_s_4g_2threads"] = round(tier_rate)
    out["tier_pump_utilization"] = round(tm.pump_utilization, 3)
    out["tier_windows"] = tm.windows
    out["tier_sched_delay_p99_us"] = round(tm.sched_delay_p99_s * 1e6, 1)
    out["tier_budget_occupancy_peak"] = round(tm.budget_occupancy_peak, 4)
    out["tier_forced_syncs"] = forced
    log(f"tier[4g x 4p, 2 threads]: {n_batches} batches in "
        f"{tier_wall:.3f}s ({tier_rate:.0f} rows/s, util "
        f"{tm.pump_utilization:.2f}, forced_syncs={forced})")

    scheds, targets, fes = [], [], []
    for gi in range(n_graphs):
        g, src, _sink = wordcount.build_graph()
        sched = DirtyScheduler(g)
        fe = IngestFrontend(sched, window=window, max_bytes=16 << 20)
        scheds.append(sched)
        targets.append((fe.submit, src))
        fes.append(fe)
    tickets, t0 = drive(targets)
    for fe in fes:
        fe.flush()
    indep_wall = time.perf_counter() - t0
    assert all(t.result(timeout=30).applied for t in tickets)
    forced_i = sum(summarize(s.history).forced_syncs for s in scheds)
    for fe in fes:
        fe.close()
    indep_rate = n_batches * rows_per_batch / indep_wall
    out["indep_rows_per_s_4g_4threads"] = round(indep_rate)
    out["tier_vs_indep_x"] = round(tier_rate / indep_rate, 3)
    out["indep_forced_syncs"] = forced_i
    out["zero_forced_syncs"] = forced + forced_i == 0
    log(f"indep[4 frontends, 4 threads]: {indep_wall:.3f}s "
        f"({indep_rate:.0f} rows/s); tier/indep = "
        f"{out['tier_vs_indep_x']}x")

    # -- phase B: pump-crash on one durable graph; siblings + recovery ----
    with tempfile.TemporaryDirectory() as tmp:
        crash = CrashInjector(at=3, only="pump_before_tick@crashy")
        tier = ServeTier(max_bytes=64 << 20, pump_threads=2, crash=crash)
        g, src, sink = wordcount.build_graph()
        dsched = DurableScheduler(g, wal_dir=tmp, fsync="record")
        hc = tier.register("crashy", dsched, GraphConfig(window=window))
        g2, src2, sink2 = wordcount.build_graph()
        ok_sched = DirtyScheduler(g2)
        hok = tier.register("ok", ok_sched, GraphConfig(window=window))

        n_crash_batches = 40
        sent = [(f"c{j}", wordcount.ingest_lines(make_lines(9, 0, j)))
                for j in range(n_crash_batches)]
        crashy_tk = []
        for bid, batch in sent:
            try:
                crashy_tk.append(hc.submit(src, batch, batch_id=bid))
            except Exception:  # FrontendClosed once the crash lands
                break
            time.sleep(0.0005)  # several windows, not one giant one
        ok_before = hok.submit(src2, wordcount.ingest_lines(
            make_lines(8, 0, 0))).result(10)
        assert ok_before.applied
        statuses = {"applied": 0, "crashed": 0}
        for t in crashy_tk:
            try:
                t.result(timeout=10)
                statuses["applied"] += 1
            except PumpCrashed:
                statuses["crashed"] += 1
        assert crash.fired and statuses["crashed"] > 0, statuses
        assert tier.pool_crashes == 1
        # the pool survived: the sibling keeps applying AFTER the crash
        ok_after = hok.submit(src2, wordcount.ingest_lines(
            make_lines(8, 0, 1))).result(10)
        assert ok_after.applied
        tier.unregister("crashy", flush=False)
        tier.close()
        out["crash_applied_before"] = statuses["applied"]
        out["crash_failed_tickets"] = statuses["crashed"]

        # recover the WAL and re-send EVERY id: exactly-once means the
        # union lands once — replayed-or-reapplied, never doubled
        g3, src3, sink3 = wordcount.build_graph()
        rsched = DurableScheduler(g3, wal_dir=tmp, fsync="record")
        recover(rsched, tmp)
        fe = IngestFrontend(rsched, window=window)
        results = [fe.submit(src3, batch, batch_id=bid).result(10)
                   for bid, batch in sent]
        fe.flush()
        fe.close()
        deduped = sum(r.status == "deduped" for r in results)
        g4, src4, sink4 = wordcount.build_graph()
        want = DirtyScheduler(g4)
        for _bid, batch in sent:
            want.push(src4, batch)
            want.tick()
        assert dict(rsched.view(sink3.name)) == dict(want.view(sink4.name))
        out["crash_recover_deduped"] = deduped
        out["crash_exactly_once"] = True
        log(f"crash[@crashy]: {statuses['applied']} applied, "
            f"{statuses['crashed']} failed PumpCrashed; sibling ok "
            f"before+after; recover+resend exactly-once "
            f"({deduped} deduped)")

    # -- phase C: hot tenant vs quiet tenant isolation --------------------
    # budget sized so the hot tenant genuinely hits its byte ceiling
    # (wordcount micro-batches are tiny): saturation has to be real for
    # the quiet-tenant p99 bound to mean anything
    budget = 8 << 10
    tier = ServeTier(max_bytes=budget, pump_threads=2)
    g, src, sink = wordcount.build_graph()
    hot = tier.register("hot", DirtyScheduler(g), GraphConfig(
        weight=1.0, ceiling_bytes=budget // 2, window=window))
    g2, src2, sink2 = wordcount.build_graph()
    quiet = tier.register("quiet", DirtyScheduler(g2), GraphConfig(
        weight=4.0, floor_bytes=budget // 4, window=window))
    stop = threading.Event()

    def hammer(pid):
        # fire-and-forget: never waits on tickets, so the hot tenant
        # queues until ADMISSION (its byte ceiling) is what stops it —
        # real saturation, the scenario the quiet tenant must survive
        j = 0
        while not stop.is_set():
            hot.submit(src, wordcount.ingest_lines(
                make_lines(7, pid, j)), timeout=0.2)
            j += 1

    hammers = [threading.Thread(target=hammer, args=(pid,))
               for pid in range(3)]
    for t in hammers:
        t.start()
    quiet_n = 60 if smoke else 200
    t0 = time.perf_counter()
    applied0 = hot.frontend.applied
    for j in range(quiet_n):
        quiet.submit(src2, wordcount.ingest_lines(
            make_lines(6, 0, j))).result(timeout=30)
    hot_elapsed = time.perf_counter() - t0
    hot_applied = hot.frontend.applied - applied0
    stop.set()
    for t in hammers:
        t.join()
    quiet.flush()
    hot.flush()
    p99 = (float(np.percentile(quiet.frontend.admission_s, 99))
           if quiet.frontend.admission_s else 0.0)
    tm = summarize_tier(tier)
    tier.close()
    out["hot_rows_per_s"] = round(
        hot_applied * rows_per_batch / hot_elapsed)
    out["quiet_admission_p99_us"] = round(p99 * 1e6, 1)
    out["quiet_p99_bounded"] = p99 < 0.05
    out["hot_budget_peak_frac"] = round(
        tm.per_graph["hot"]["bytes_peak"] / budget, 3)
    log(f"isolation: hot {out['hot_rows_per_s']} rows/s (peak "
        f"{out['hot_budget_peak_frac']} of budget), quiet admission "
        f"p99 {p99 * 1e6:.0f}us (bounded={out['quiet_p99_bounded']})")
    return out


# -- pod-scale serving mode (REFLOW_BENCH_SHARDSERVE=1) --------------------

def run_shardserve_bench() -> dict:
    """Pod-scale serving numbers (docs/guide.md "Sharded serving").

    Three tiers over the same loop-free aggregation workload (source ->
    vectorized map -> reduce(sum), integer-valued f32 values so every
    view comparison is EXACT — elementwise math is sharding-invariant
    bit-for-bit, and integer-valued sums below 2^24 make the cross-row
    reduction order irrelevant), all committing through the fused
    mega-tick window path:

    A. **single-device baseline** — 8 tenants on one ``ServeTier``,
       every executor on the default device (windows serialize on one
       chip — the PR-7 state of the world);
    B. **spread placement** — the same 8 tenants with
       ``GraphConfig(placement="spread")``: one executor per mesh
       device, windows dispatch concurrently, and the structurally-
       identical tenants adopt ONE traced window program from the
       plan-signature cache (``megatick_cache_hits``);
    C. **sharded hot tenant** — the same total load on ONE graph whose
       ``ShardedTpuExecutor`` spans the mesh: queue buffers NamedSharded
       along the capacity axis, the window scan running under shard_map.

    Every tier's reduce tables are compared exactly (max_abs_diff must
    be 0.0) against a CPU per-tick oracle fed the identical batches, and
    the fallback counters must be 0 — the happy path has to BE the
    fused spread/sharded path, not a silent per-tick fallback.

    CPU-CI note: under ``--xla_force_host_platform_device_count=8`` all
    "devices" share the host cores (this container: one), so neither
    spread nor sharded can beat the baseline WALL here — the
    ``*_ge_baseline`` flags relax to ``ge_slack`` of baseline on cpu
    (1.0 on a real mesh) and the raw rows/s + ratios are the artifact;
    scaling headroom shows on real multi-chip hardware.
    """
    import threading

    import jax

    from reflow_tpu.delta import DeltaBatch, Spec
    from reflow_tpu.executors import get_executor
    from reflow_tpu.graph import FlowGraph
    from reflow_tpu.scheduler import DirtyScheduler
    from reflow_tpu.serve import CoalesceWindow, GraphConfig, ServeTier

    smoke = env_flag("REFLOW_BENCH_SMOKE")
    n_graphs = 8
    key_space = 256
    rows_per_batch = 64
    per_producer = env_int("REFLOW_BENCH_SHARDSERVE_BATCHES", "8" if smoke else "48")
    window = CoalesceWindow(max_rows=4096, max_ticks=4,
                            max_latency_s=0.003)
    n_devices = len(jax.devices())
    platform = jax.default_backend()
    ge_slack = 1.0 if platform == "tpu" else 0.25
    total_rows = n_graphs * per_producer * rows_per_batch

    def build():
        g = FlowGraph("shardserve")
        spec = Spec((), np.float32, key_space=key_space)
        src = g.source("events", spec)
        m = g.map(src, lambda v: v * np.float32(3) + np.float32(1),
                  vectorized=True)
        r = g.reduce(m, "sum", tol=0.0)
        return g, src, r

    def make_batch(gi: int, j: int) -> DeltaBatch:
        rng = np.random.default_rng(gi * 7919 + j + 1)
        keys = rng.integers(0, key_space, rows_per_batch).astype(np.int64)
        vals = rng.integers(0, 8, rows_per_batch).astype(np.float32)
        return DeltaBatch(keys, vals,
                          np.ones(rows_per_batch, np.int64))

    def table(sched, r):
        return {int(k): float(np.asarray(v).reshape(()))
                for k, v in sched.read_table(r).items()}

    def oracle(graph_ids):
        g, src, r = build()
        sched = DirtyScheduler(g, get_executor("cpu"))
        for gi in graph_ids:
            for j in range(per_producer):
                sched.push(src, make_batch(gi, j))
                sched.tick()
        return table(sched, r)

    def max_diff(got, want):
        ks = set(got) | set(want)
        return max((abs(got.get(k, 0.0) - want.get(k, 0.0)) for k in ks),
                   default=0.0)

    def drive(targets):
        # targets: (handle, src, gi) per producer thread; the wall covers
        # submission through the last committed window (flush)
        def produce(h, src, gi):
            for j in range(per_producer):
                h.submit(src, make_batch(gi, j))

        threads = [threading.Thread(target=produce, args=t)
                   for t in targets]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for h, _src, _gi in targets:
            h.flush()
        return time.perf_counter() - t0

    def run_tier(placement):
        tier = ServeTier(max_bytes=64 << 20,
                         pump_threads=min(n_graphs, 8))
        scheds, targets, reduces = [], [], []
        for gi in range(n_graphs):
            g, src, r = build()
            sched = DirtyScheduler(g, get_executor("tpu"))
            cfg = (GraphConfig(window=window, placement=placement)
                   if placement else GraphConfig(window=window))
            h = tier.register(f"g{gi}", sched, cfg)
            scheds.append(sched)
            reduces.append(r)
            targets.append((h, src, gi))
        wall = drive(targets)
        tables = [table(s, r) for s, r in zip(scheds, reduces)]
        stats = {
            "windows": sum(s.megatick_windows for s in scheds),
            "fallbacks": sum(s.megatick_fallbacks for s in scheds),
            "cache_hits": sum(s.executor.megatick_cache_hits
                              for s in scheds),
            "devices": sorted({s.executor.device_label or "(default)"
                               for s in scheds}),
        }
        tier.close()
        return wall, tables, stats

    want = [oracle([gi]) for gi in range(n_graphs)]
    out = {"graphs": n_graphs, "per_producer_batches": per_producer,
           "rows_per_batch": rows_per_batch, "key_space": key_space,
           "devices": n_devices, "platform": platform,
           "ge_slack": ge_slack}

    # -- A: single-device baseline ----------------------------------------
    base_wall, base_tables, base_stats = run_tier(None)
    base_diff = max(max_diff(t, w) for t, w in zip(base_tables, want))
    base_rate = total_rows / base_wall
    out["single_rows_per_s"] = round(base_rate)
    out["single_windows"] = base_stats["windows"]
    out["single_fallbacks"] = base_stats["fallbacks"]
    log(f"shardserve[single]: {total_rows} rows in {base_wall:.3f}s "
        f"({base_rate:.0f} rows/s, windows={base_stats['windows']}, "
        f"fallbacks={base_stats['fallbacks']})")

    # -- B: 8 spread tenants ----------------------------------------------
    spread_wall, spread_tables, spread_stats = run_tier("spread")
    spread_diff = max(max_diff(t, w)
                      for t, w in zip(spread_tables, want))
    spread_rate = total_rows / spread_wall
    out["spread_rows_per_s"] = round(spread_rate)
    out["spread_vs_single_x"] = round(spread_rate / base_rate, 3)
    out["spread_ge_baseline"] = bool(
        spread_rate >= ge_slack * base_rate)
    out["spread_windows"] = spread_stats["windows"]
    out["spread_fallbacks"] = spread_stats["fallbacks"]
    out["spread_cache_hits"] = spread_stats["cache_hits"]
    out["spread_devices"] = spread_stats["devices"]
    out["spread_devices_distinct"] = bool(
        len(spread_stats["devices"]) == min(n_graphs, n_devices))
    out["spread_max_abs_diff"] = spread_diff
    log(f"shardserve[spread]: {spread_wall:.3f}s "
        f"({spread_rate:.0f} rows/s, {out['spread_vs_single_x']}x, "
        f"devices={len(spread_stats['devices'])}, "
        f"cache_hits={spread_stats['cache_hits']}, "
        f"fallbacks={spread_stats['fallbacks']})")

    # -- C: one sharded hot tenant ----------------------------------------
    from reflow_tpu.parallel.shard import ShardedTpuExecutor

    tier = ServeTier(max_bytes=64 << 20, pump_threads=2)
    g, src, r = build()
    hot = DirtyScheduler(g, ShardedTpuExecutor())
    h = tier.register("hot", hot, GraphConfig(window=window))
    sharded_wall = drive([(h, src, gi) for gi in range(n_graphs)])
    sharded_tab = table(hot, r)
    sharded_stats = {
        "windows": hot.megatick_windows,
        "fallbacks": hot.megatick_fallbacks,
        "device": hot.executor.device_label,
    }
    tier.close()
    want_all = oracle(range(n_graphs))
    sharded_diff = max_diff(sharded_tab, want_all)
    sharded_rate = total_rows / sharded_wall
    out["sharded_rows_per_s"] = round(sharded_rate)
    out["sharded_vs_single_x"] = round(sharded_rate / base_rate, 3)
    out["sharded_ge_baseline"] = bool(
        sharded_rate >= ge_slack * base_rate)
    out["sharded_windows"] = sharded_stats["windows"]
    out["sharded_fallbacks"] = sharded_stats["fallbacks"]
    out["sharded_device"] = sharded_stats["device"]
    out["sharded_max_abs_diff"] = sharded_diff
    log(f"shardserve[sharded {sharded_stats['device']}]: "
        f"{sharded_wall:.3f}s ({sharded_rate:.0f} rows/s, "
        f"{out['sharded_vs_single_x']}x, "
        f"windows={sharded_stats['windows']}, "
        f"fallbacks={sharded_stats['fallbacks']})")

    # hard correctness: exact per-tick view parity + no silent fallback
    assert base_diff == 0.0, f"baseline views diverged: {base_diff}"
    assert spread_diff == 0.0, f"spread views diverged: {spread_diff}"
    assert sharded_diff == 0.0, f"sharded views diverged: {sharded_diff}"
    fb = (base_stats["fallbacks"] + spread_stats["fallbacks"]
          + sharded_stats["fallbacks"])
    assert fb == 0, f"window path fell back {fb}x on the happy path"
    assert spread_stats["windows"] > 0 and sharded_stats["windows"] > 0
    out["views_match"] = True
    return out


def run_control_bench() -> dict:
    """Self-healing control-plane step-load scenario (docs/guide.md
    "Control plane"), two phases, both under a LIVE ``ControlPlane``
    thread (no manual intervention anywhere):

    A. **hot-tenant surge** — a hot graph saturates its budget ceiling
       while a quiet sibling keeps submitting. The controller must
       brown out ONLY the surging graph (the quiet tenant's brownout
       level stays 0 and its admission p99 stays bounded), and once the
       surge stops, walk the hot graph back to its configured policy
       within the analytic bound of control intervals (ladder rungs x
       ``recover_intervals`` + drain slack);
    B. **pump-crash storm** — every macro-tick of one graph crashes
       (``StormInjector``): the controller's breaker must open after K
       crashes (quarantining the graph while its sibling keeps
       applying), then — once the storm ends — heal it through a
       half-open probe back to closed, after which submissions apply
       again.

    Host-side CPU work (no tunnel protocol applies).
    """
    import threading

    from bench_configs import control_scenario
    from reflow_tpu.obs import MetricsRegistry
    from reflow_tpu.scheduler import DirtyScheduler
    from reflow_tpu.serve import (CoalesceWindow, ControlConfig,
                                  ControlPlane, GraphConfig, SLOSpec,
                                  ServeTier)
    from reflow_tpu.utils.faults import StormInjector
    from reflow_tpu.workloads import wordcount

    smoke = env_flag("REFLOW_BENCH_SMOKE")
    kn = control_scenario(smoke)
    rows_per_batch = 8
    window = CoalesceWindow(max_rows=4096, max_ticks=8,
                            max_latency_s=0.005)
    reg = MetricsRegistry()   # private: don't pollute the global obs

    def make_lines(graph: int, producer: int, j: int) -> list:
        rng = np.random.default_rng(
            (graph * 101 + producer) * 100_003 + j)
        return [" ".join(f"w{int(x)}"
                         for x in rng.integers(0, 1000, rows_per_batch))]

    out = dict(kn)

    # -- phase A: hot-tenant surge, brownout confined to the offender -----
    budget = kn["budget_bytes"]
    tier = ServeTier(max_bytes=budget,
                     pump_threads=kn["pump_threads"])
    g, src, _sink = wordcount.build_graph()
    hot = tier.register("hot", DirtyScheduler(g), GraphConfig(
        weight=1.0, ceiling_bytes=budget // 2, window=window))
    g2, src2, _sink2 = wordcount.build_graph()
    quiet = tier.register("quiet", DirtyScheduler(g2), GraphConfig(
        weight=4.0, floor_bytes=budget // 4, window=window))
    slo = SLOSpec(budget_occupancy=kn["occupancy_slo"],
                  breach_intervals=kn["breach_intervals"],
                  recover_intervals=kn["recover_intervals"])
    # BOTH graphs carry the same SLO: the quiet tenant staying at level
    # 0 then proves per-graph confinement, not a missing spec
    cp = ControlPlane(
        tier, specs={"hot": slo, "quiet": slo},
        config=ControlConfig(interval_s=kn["interval_s"]),
        registry=reg).start()
    stop = threading.Event()

    def hammer(pid):
        # fire-and-forget saturation; under brownout the submits turn
        # into fast rejections/sheds, which IS the degraded mode
        j = 0
        while not stop.is_set():
            try:
                hot.submit(src, wordcount.ingest_lines(
                    make_lines(7, pid, j)), timeout=0.2)
            except Exception:  # noqa: BLE001 - saturation is the point
                pass
            j += 1

    hammers = [threading.Thread(target=hammer, args=(pid,))
               for pid in range(kn["hammers"])]
    for t in hammers:
        t.start()
    quiet_n = kn["quiet_batches"]
    hot_peak_level = 0
    quiet_peak_level = 0
    t0 = time.perf_counter()
    for j in range(quiet_n):
        quiet.submit(src2, wordcount.ingest_lines(
            make_lines(6, 0, j))).result(timeout=30)
        hot_peak_level = max(hot_peak_level, cp.level("hot"))
        quiet_peak_level = max(quiet_peak_level, cp.level("quiet"))
    surge_s = time.perf_counter() - t0
    # -- step-load falling edge: surge ends; measure recovery in ticks --
    stop.set()
    for t in hammers:
        t.join()
    ticks_at_surge_end = cp.ticks
    rungs = len(slo.ladder)
    recovery_bound = (rungs * kn["recover_intervals"]
                      + kn["recovery_slack_ticks"])
    deadline = time.perf_counter() + 30
    while cp.level("hot") > 0 and time.perf_counter() < deadline:
        time.sleep(kn["interval_s"])
    recovery_ticks = cp.ticks - ticks_at_surge_end
    p99 = (float(np.percentile(quiet.frontend.admission_s, 99))
           if quiet.frontend.admission_s else 0.0)
    out["quiet_admission_p99_us"] = round(p99 * 1e6, 1)
    out["quiet_p99_bounded"] = p99 < kn["quiet_p99_bound_s"]
    out["hot_peak_brownout_level"] = hot_peak_level
    out["quiet_peak_brownout_level"] = quiet_peak_level
    out["only_hot_degraded"] = (hot_peak_level > 0
                                and quiet_peak_level == 0
                                and quiet.frontend.policy == "block")
    out["hot_policy_after_recovery"] = hot.frontend.policy
    out["recovery_ticks"] = recovery_ticks
    out["recovery_bound_ticks"] = recovery_bound
    out["recovered_within_bound"] = (
        cp.level("hot") == 0 and hot.frontend.policy == "block"
        and recovery_ticks <= recovery_bound)
    out["brownouts_entered"] = reg.value("control.brownouts_entered", 0)
    out["brownouts_exited"] = reg.value("control.brownouts_exited", 0)
    out["quiet_rows_per_s_during_surge"] = round(
        quiet_n * rows_per_batch / surge_s)
    log(f"surge: hot browned to level {hot_peak_level}, quiet stayed "
        f"level {quiet_peak_level} (p99 {p99 * 1e6:.0f}us, bounded="
        f"{out['quiet_p99_bounded']}); recovered in {recovery_ticks} "
        f"ticks (bound {recovery_bound})")
    cp.stop()
    tier.close()

    # -- phase B: pump-crash storm -> breaker -> half-open heal -----------
    storm = StormInjector(only="pool_window@stormy")
    tier = ServeTier(max_bytes=budget, pump_threads=kn["pump_threads"],
                     crash=storm)
    g3, src3, _ = wordcount.build_graph()
    stormy = tier.register("stormy", DirtyScheduler(g3),
                           GraphConfig(window=window))
    g4, src4, _ = wordcount.build_graph()
    steady = tier.register("steady", DirtyScheduler(g4),
                           GraphConfig(window=window))
    reg2 = MetricsRegistry()
    cp = ControlPlane(
        tier,
        config=ControlConfig(
            interval_s=kn["interval_s"],
            max_crashes=kn["max_crashes"],
            crash_window_s=kn["crash_window_s"],
            respawn_backoff_s=kn["respawn_backoff_s"],
            respawn_backoff_max_s=kn["respawn_backoff_max_s"],
            breaker_cooldown_s=kn["breaker_cooldown_s"],
            breaker_cooldown_max_s=kn["breaker_cooldown_max_s"],
            probe_intervals=kn["probe_intervals"]),
        registry=reg2).start()
    t0 = time.perf_counter()
    deadline = t0 + 60
    j = 0
    while (cp.breaker_state("stormy") != "open"
           and time.perf_counter() < deadline):
        try:
            stormy.submit(src3, wordcount.ingest_lines(
                make_lines(5, 0, j)), timeout=0.1)
        except Exception:  # noqa: BLE001 - failed/quarantined mid-storm
            pass
        j += 1
        time.sleep(0.002)
    open_s = time.perf_counter() - t0
    out["breaker_opened"] = cp.breaker_state("stormy") == "open"
    out["breaker_open_after_s"] = round(open_s, 3)
    out["storm_crashes"] = storm.crashes
    # the sibling keeps applying while the storm rages / is quarantined
    sib = steady.submit(src4, wordcount.ingest_lines(
        make_lines(4, 0, 0))).result(timeout=30)
    out["sibling_applied_during_storm"] = sib.applied
    # storm ends: the breaker must heal the graph unattended
    storm.disarm()
    t0 = time.perf_counter()
    deadline = t0 + 60
    while (cp.breaker_state("stormy") != "closed"
           and time.perf_counter() < deadline):
        time.sleep(kn["interval_s"])
    heal_s = time.perf_counter() - t0
    out["breaker_recovered"] = cp.breaker_state("stormy") == "closed"
    out["breaker_heal_s"] = round(heal_s, 3)
    out["breaker_probes"] = reg2.value("control.breaker_probes", 0)
    out["respawns"] = reg2.value("control.respawns", 0)
    post = stormy.submit(src3, wordcount.ingest_lines(
        make_lines(5, 1, 0))).result(timeout=30)
    out["post_recovery_applied"] = post.applied
    out["pool_live_workers"] = tier.live_workers
    log(f"storm: opened={out['breaker_opened']} after {open_s:.2f}s "
        f"({storm.crashes} crashes), healed={out['breaker_recovered']} "
        f"in {heal_s:.2f}s ({out['respawns']} respawns, "
        f"{out['breaker_probes']} probes); post-recovery applied="
        f"{post.applied}")
    cp.stop()
    tier.close()
    return out


# -- config 3 measurements -------------------------------------------------

def run_pagerank_cpu(n_nodes: int, n_edges: int, churn: float, ticks: int,
                     tol: float) -> dict:
    """CPU oracle churn ticks (synchronous by construction)."""
    from reflow_tpu.executors import get_executor
    from reflow_tpu.scheduler import DirtyScheduler
    from reflow_tpu.workloads import pagerank

    pr, web = _build_pagerank(n_nodes, n_edges, churn, tol)
    sched = DirtyScheduler(pr.graph, get_executor("cpu"))
    sched.push(pr.teleport, pagerank.teleport_batch(n_nodes))
    sched.push(pr.edges, web.initial_batch())
    build_s, _ = _synced_tick(sched)

    walls, dops = [], []
    for _ in range(ticks):
        sched.push(pr.edges, web.churn(churn))
        wall, res = _synced_tick(sched)
        walls.append(wall)
        dops.append(res.delta_ops)
    return {
        "executor": "cpu", "nodes": n_nodes, "edges": n_edges,
        "cold_build_s": build_s,
        "tick_s_median": float(np.median(walls)),
        "delta_ops_per_s": float(sum(dops) / sum(walls)),
        "delta_ops_per_tick": float(np.mean(dops)),
    }


def run_pagerank_tpu_child(defer=None) -> dict:
    """Child process: the headline pipelined churn window on the device.

    Zero readbacks happen before the window (cold build, churn-shape
    compile absorption and all pushes are streaming); the window's
    closing readback is the process's FIRST, so the whole window runs
    with the tunnel in pipelined mode and the wall is a true
    device-completion time for all N ticks.

    ``defer`` (pr_tpu_defer child): the same window under cross-tick
    residual deferral — quiescence is NOT asserted per tick; instead
    the child drains after the windows and verifies the drained ranks
    against the independent dense power-iteration oracle, recording the
    mid-stream and drained error bounds alongside the throughput."""
    from bench_configs import _timed_tick
    from reflow_tpu.executors import get_executor
    from reflow_tpu.scheduler import DirtyScheduler
    from reflow_tpu.workloads import pagerank

    p = _params()
    pr, web = _build_pagerank(p["n_nodes"], p["n_edges"], p["churn"],
                              p["tol"], defer=defer)
    sched = DirtyScheduler(pr.graph, get_executor("tpu"))
    sched.push(pr.teleport, pagerank.teleport_batch(p["n_nodes"]))
    sched.push(pr.edges, web.initial_batch())
    t0 = time.perf_counter()
    sched.tick(sync=False)
    build_dispatch_s = time.perf_counter() - t0   # includes the compile
    warm = 2 if defer is None else max(2, 24 // defer)
    for _ in range(warm):  # absorb the churn-shape compile + (deferred:
        sched.push(pr.edges, web.churn(p["churn"]))   # converge the cold
        sched.tick(sync=False)                        # build's residue)
    from bench_configs import _settle
    _settle(0 if p["smoke"] else 15, log,
            "drain cold build + warmup ticks before the window")
    if defer is not None:
        # converge the cold build's residue before measuring: the window
        # then measures steady-state churn tracking, not amortized
        # initial convergence. drain() is synchronous, which flips the
        # tunnel into degraded dispatch — that's the regime the median
        # window lands in anyway (window 1's pipelined mode is the
        # documented outlier), so the windows stay comparable.
        # probe at the churn batch size so drain ticks reuse the churn
        # program signature (a 1-row probe's 64-capacity bucket would
        # compile a fresh program, ~60s on the tunnel)
        n_churn = 2 * max(1, int(p["churn"] * p["n_edges"]))
        cold_drain_ticks = sched.drain(pr.edges, probe_rows=n_churn)
        log(f"cold-build residue drained in {cold_drain_ticks} ticks")

    # NOTE on tick_many (the lax.scan macro-tick): it amortizes the
    # tunnel's fixed per-execution overhead K-fold and is the right shape
    # for directly-attached chips, but on THIS tunnel the runtime
    # timeslices long executions (~2-3x intra-execution stretch, high
    # variance), so the per-tick streaming window below measures better
    # and is the headline path.
    #
    # THREE windows, median throughput: the shared tunnel shows rare
    # far-outlier windows (one recorded 8x the steady wall); the median
    # outvotes them. Window 1 runs in the tunnel's pipelined mode, which
    # carries a ~2x intra-execution stretch; its closing barrier flips
    # the runtime into synchronous mode, where chained big-tick windows
    # run at true device speed (measured: 8.1s -> 3.7s for 16 ticks).
    # Every window is a genuine completion-time wall (dispatch chains
    # serialize with the in-order device stream and the closing barrier
    # reads a value the last tick produced), so the median is honest
    # whichever mode it lands in.
    n = p["stream_ticks"]
    from bench_configs import _median_window, _stream_window

    def run_churn_window():
        wall, dwall, results = _stream_window(
            sched, lambda i: sched.push(pr.edges, web.churn(p["churn"])), n)
        if defer is None:
            assert all(r.quiesced for r in results)
        return wall, dwall, sum(r.delta_ops for r in results)

    wall, dwall, dops, windows = _median_window(
        run_churn_window, log, f"pagerank churn x{n}"
        + (f" defer={defer}" if defer else ""))
    windows = [{"wall_s": round(w, 3), "dispatch_s": round(d, 3),
                "delta_ops": o} for w, d, o in windows]

    extra = {}
    if defer is None and not p["smoke"]:
        # the quiescent mode's own accuracy vs the independent oracle:
        # the fair baseline band for the deferred child's error fields
        # (both modes carry tol-lag; deferral must not add beyond it)
        import numpy as _np
        from reflow_tpu.workloads import pagerank as _pg
        ranks_q = _pg.ranks_to_array(sched.read_table(pr.new_rank),
                                     p["n_nodes"])
        ref_q = _pg.reference_ranks(web)
        extra["max_abs_err_vs_reference"] = round(
            float(_np.abs(ranks_q - ref_q).max()), 6)
        extra["max_rel_err_vs_reference"] = round(float(
            (_np.abs(ranks_q - ref_q) / _np.maximum(ref_q, 1.0)).max()), 6)
        log(f"quiescent accuracy vs reference: "
            f"abs={extra['max_abs_err_vs_reference']} "
            f"rel={extra['max_rel_err_vs_reference']}")
    if defer is not None:
        # the deferred mode's accuracy contract, measured in-record:
        # mid-stream lag right after the last window, then drained ranks
        # vs the INDEPENDENT dense power-iteration oracle (5e-4 is the
        # VERDICT-prescribed bound on the drained side)
        import numpy as _np
        from reflow_tpu.workloads import pagerank as _pg
        ref = _pg.reference_ranks(web)
        mid = _pg.ranks_to_array(sched.read_table(pr.new_rank),
                                 p["n_nodes"])
        t_dr = time.perf_counter()
        drain_ticks = sched.drain(
            pr.edges, probe_rows=2 * max(1, int(p["churn"] * p["n_edges"])))
        drain_s = time.perf_counter() - t_dr
        drained = _pg.ranks_to_array(sched.read_table(pr.new_rank),
                                     p["n_nodes"])
        rel = lambda a: float((_np.abs(a - ref)
                               / _np.maximum(ref, 1.0)).max())
        extra = {
            "defer_passes": defer,
            "mid_stream_max_abs_err": round(
                float(_np.abs(mid - ref).max()), 6),
            "mid_stream_max_rel_err": round(rel(mid), 6),
            "drain_ticks": drain_ticks,
            "drain_s": round(drain_s, 2),
            "drained_max_abs_err": round(
                float(_np.abs(drained - ref).max()), 6),
            "drained_max_rel_err": round(rel(drained), 6),
        }
        log(f"deferred accuracy: mid={extra['mid_stream_max_abs_err']} "
            f"(rel {extra['mid_stream_max_rel_err']}) "
            f"drained={extra['drained_max_abs_err']} "
            f"(rel {extra['drained_max_rel_err']}) "
            f"(drain {drain_ticks} ticks / {drain_s:.1f}s)")

    # post-window extras (tunnel now degraded — every sync pays ~0.1s, so
    # these are conservative upper bounds, never enqueue times)
    sched.push(pr.edges, web.churn(p["churn"]))
    synced_s, _ = _timed_tick(sched)

    trace_dir = env_str("REFLOW_BENCH_TRACE", None)
    if trace_dir:
        from reflow_tpu.utils.metrics import profile_trace
        sched.push(pr.edges, web.churn(p["churn"]))
        with profile_trace(trace_dir):
            _timed_tick(sched)

    return {
        "executor": "tpu", "nodes": p["n_nodes"], "edges": p["n_edges"],
        "build_dispatch_s": round(build_dispatch_s, 2),
        "window_ticks": n,
        "window_wall_s": round(wall, 3),
        "window_dispatch_s": round(dwall, 3),
        "windows": windows,
        "tick_s_amortized": round(wall / n, 4),
        "delta_ops_per_s": round(dops / wall),
        "delta_ops_per_tick": round(dops / n),
        "tick_s_synced_degraded": round(synced_s, 3),
        **extra,
    }


def run_pagerank_full_child() -> dict:
    """Child process: warm full-recompute baseline. Own process so the
    first measured round's closing readback is the first of the process
    (clean pipelined dispatch); see the min-of-3 rationale below."""
    from bench_configs import _sync_read
    from reflow_tpu.executors import get_executor
    from reflow_tpu.scheduler import DirtyScheduler
    from reflow_tpu.workloads import pagerank

    p = _params()
    pr, web = _build_pagerank(p["n_nodes"], p["n_edges"], p["churn"],
                              p["tol"])
    ex = get_executor("tpu")
    sched = DirtyScheduler(pr.graph, ex)
    sched.push(pr.teleport, pagerank.teleport_batch(p["n_nodes"]))
    sched.push(pr.edges, web.initial_batch())
    sched.tick(sync=False)   # absorb the compile; leaves cache warm

    # fresh states over the same graph each round: bind() resets state,
    # keeps the compiled-program cache. Three measurements, MINIMUM wall:
    # full_recompute_s is the NUMERATOR of incr_vs_full, so the outlier
    # guard must never inflate it. Round 0 runs in the tunnel's pipelined
    # mode (~2x intra-execution stretch); rounds 1-2 run post-readback at
    # true device speed (measured 6.7s -> 2.1s) — min() picks the wall
    # closest to real device cost, matching the regime the churn-window
    # median lands in, so the ratio compares like with like.
    from bench_configs import _settle
    walls = []
    for ix in range(3):
        sched2 = DirtyScheduler(pr.graph, ex)
        sched2.push(pr.teleport, pagerank.teleport_batch(p["n_nodes"]))
        sched2.push(pr.edges, web.initial_batch())
        if ix == 0:
            _settle(0 if p["smoke"] else 15, log,
                    "drain the absorption tick before timing full recompute")
        t0 = time.perf_counter()
        sched2.tick(sync=False)
        _sync_read(ex)       # round 0: first readback of the process
        walls.append(time.perf_counter() - t0)
        log(f"full recompute {ix}: {walls[-1]:.2f}s")
    return {"executor": "tpu",
            "full_recompute_s": round(min(walls), 3),
            "full_recompute_walls_s": [round(w, 2) for w in walls]}


# -- subprocess orchestration ----------------------------------------------

_CHILDREN = {}


def _child(name):
    def deco(fn):
        _CHILDREN[name] = fn
        return fn
    return deco


@_child("pr_tpu")
def _c_pr_tpu():
    return run_pagerank_tpu_child()


@_child("pr_tpu_defer")
def _c_pr_tpu_defer():
    return run_pagerank_tpu_child(defer=_params()["defer"])


@_child("pr_full")
def _c_pr_full():
    return run_pagerank_full_child()


def _cfg_child(name, fn_name):
    @_child(name)
    def _run():
        import bench_configs
        getattr(bench_configs, fn_name)(_params()["smoke"], log)
        return {"ok": True}
    return _run


_cfg_child("cfg1", "cfg1_wordcount")
_cfg_child("cfg2", "cfg2_tfidf")
_cfg_child("cfg4", "cfg4_knn")
_cfg_child("cfg5", "cfg5_image_embed")


def _spawn(name: str) -> dict:
    """Run one measurement in a fresh process (fresh tunnel mode — see
    the module docstring). Child stderr streams through (records/logs);
    child stdout's last line is its JSON result."""
    env = dict(os.environ)
    env["REFLOW_BENCH_CHILD"] = name
    t0 = time.perf_counter()
    p = subprocess.run([sys.executable, os.path.abspath(__file__)],
                       stdout=subprocess.PIPE, env=env, text=True)
    log(f"[{name}] child finished in {time.perf_counter()-t0:.0f}s "
        f"rc={p.returncode}")
    lines = [ln for ln in (p.stdout or "").strip().splitlines() if ln]
    if p.returncode == 0 and lines:
        try:
            return json.loads(lines[-1])
        except json.JSONDecodeError:
            pass
    return {"error": f"child {name} rc={p.returncode}",
            "stdout_tail": lines[-3:]}


def _emit(result: dict, json_out=None, mode: str = None) -> None:
    """Print the final result as the one parseable stdout line; when
    ``--json-out`` was given, also write it there pretty-printed (the
    machine-comparison artifact — stdout stays the contract). Every
    result carries the ``reflow.bench/1`` schema stamp plus its bench
    ``mode`` so directory-level readers (``fleet_inspect
    --bench-dir``) can classify artifacts without guessing from
    filenames; pre-stamp files remain readable there by design."""
    result = {"schema": "reflow.bench/1", "mode": mode, **result}
    print(json.dumps(result))
    if json_out:
        with open(json_out, "w") as f:
            json.dump(result, f, indent=2, sort_keys=True)
            f.write("\n")
        log(f"result written to {json_out}")


def main() -> None:
    ap = argparse.ArgumentParser(add_help=False)
    ap.add_argument("--json-out", default=None, metavar="PATH")
    cli, _ = ap.parse_known_args()
    json_out = cli.json_out

    if env_flag("REFLOW_BENCH_TIER"):
        # tier mode is host-side CPU work — no tunnel, no subprocesses
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        out = run_tier_bench()
        _emit({
            "metric": "tier_rows_per_s_4g_2threads",
            "value": out["tier_rows_per_s_4g_2threads"],
            "unit": "rows/s",
            **out,
        }, json_out, mode="tier")
        return

    if env_flag("REFLOW_BENCH_SHARDSERVE"):
        # pod-scale serving mode: on cpu, force 8 host devices BEFORE jax
        # imports so the spread/sharded tiers have a mesh to span (a real
        # TPU platform uses its native device set)
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        if "cpu" in os.environ.get("JAX_PLATFORMS", ""):
            flags = os.environ.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags:
                os.environ["XLA_FLAGS"] = (
                    flags + " --xla_force_host_platform_device_count=8"
                ).strip()
        out = run_shardserve_bench()
        _emit({
            "metric": "shardserve_spread_rows_per_s",
            "value": out["spread_rows_per_s"],
            "unit": "rows/s",
            **out,
        }, json_out, mode="shardserve")
        return

    if env_flag("REFLOW_BENCH_CONTROL"):
        # control mode is host-side CPU work — no tunnel, no subprocesses
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        out = run_control_bench()
        _emit({
            "metric": "control_quiet_admission_p99_us_during_surge",
            "value": out["quiet_admission_p99_us"],
            "unit": "us",
            **out,
        }, json_out, mode="control")
        return

    if env_flag("REFLOW_BENCH_SERVE"):
        # serve mode is host-side CPU work — no tunnel, no subprocesses
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        out = run_serve_bench()
        _emit({
            "metric": "serve_ingest_rows_per_s_16_producers",
            "value": out["serve_16p_rows_per_s"],
            "unit": "rows/s",
            **out,
        }, json_out, mode="serve")
        return

    if env_flag("REFLOW_BENCH_WALPIPE"):
        # walpipe mode is host-side CPU work — no tunnel, no subprocesses
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        out = run_walpipe_bench()
        _emit({
            "metric": "walpipe_speedup_16p",
            "value": out["walpipe_speedup_16p"],
            "unit": "x",
            **out,
        }, json_out, mode="walpipe")
        return

    if env_flag("REFLOW_BENCH_REPLICA"):
        # replica mode is host-side CPU work — no tunnel, no subprocesses
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        out = run_replica_bench()
        _emit({
            "metric": "replica_read_scaling_x",
            "value": out["read_scaling_x"],
            "unit": "x",
            **out,
        }, json_out, mode="replica")
        return

    if env_flag("REFLOW_BENCH_SUBS"):
        # subs mode is host-side CPU work over loopback — no tunnel
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        out = run_subs_bench()
        _emit({
            "metric": "subs_write_p99_overhead_x",
            "value": out["write_p99_overhead_x"],
            "unit": "x",
            **out,
        }, json_out, mode="subs")
        return

    if env_flag("REFLOW_BENCH_COMPACT"):
        # bounded-history mode is host-side CPU work — no tunnel
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        out = run_compact_bench()
        _emit({
            "metric": "compact_recover_speedup_x",
            "value": out["recover_speedup_x"],
            "unit": "x",
            **out,
        }, json_out, mode="compact")
        return

    if env_flag("REFLOW_BENCH_TILES"):
        # tiles mode is host-side CPU work — no tunnel, no subprocesses
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        out = run_tiles_bench()
        _emit({
            "metric": "tiles_restore_wall_ratio_x",
            "value": out["restore_wall_ratio_x"],
            "unit": "x",
            **out,
        }, json_out, mode="tiles")
        return

    if env_flag("REFLOW_BENCH_CHAOS"):
        # chaos mode is host-side CPU work over local TCP — no tunnel
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        out = run_chaos_bench()
        _emit({
            "metric": "chaos_converge_s",
            "value": out["converge_s"],
            "unit": "s",
            **out,
        }, json_out, mode="chaos")
        return

    if env_flag("REFLOW_BENCH_FAILOVER"):
        # failover mode is host-side CPU work — no tunnel, no subprocesses
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        out = run_failover_bench()
        _emit({
            "metric": "failover_promotion_s",
            "value": out["promotion_s"],
            "unit": "s",
            **out,
        }, json_out, mode="failover")
        return

    if env_flag("REFLOW_BENCH_FLEETOBS"):
        # fleetobs mode is host-side CPU work over local TCP — no tunnel
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        out = run_fleetobs_bench()
        _emit({
            "metric": "fleetobs_overhead_frac",
            "value": out["fleetobs_overhead_frac"],
            "unit": "frac",
            **out,
        }, json_out, mode="fleetobs")
        return

    if env_flag("REFLOW_BENCH_MULTIPROC"):
        # multiproc mode spawns its own CPU-pinned children; the
        # parent does host-side control work only — no tunnel
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        out = run_multiproc_bench()
        _emit({
            "metric": "multiproc_promotion_s",
            "value": out["promotion_s"],
            "unit": "s",
            **out,
        }, json_out, mode="multiproc")
        return

    if env_flag("REFLOW_BENCH_E2ETRACE"):
        # e2etrace mode spawns its own CPU-pinned children; the parent
        # pumps subscribers and merges traces — no tunnel
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        out = run_e2etrace_bench()
        _emit({
            "metric": "e2etrace_full_chains",
            "value": out["full_chains"],
            "unit": "chains",
            **out,
        }, json_out, mode="e2etrace")
        return

    if env_flag("REFLOW_BENCH_OBS"):
        # obs mode is host-side CPU work — no tunnel, no subprocesses
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        out = run_obs_bench()
        _emit({
            "metric": "serve_obs_overhead_frac",
            "value": out["obs_overhead_frac"],
            "unit": "frac",
            **out,
        }, json_out, mode="obs")
        return

    if env_flag("REFLOW_BENCH_RECOVERY"):
        # WAL mode is mostly host-side work; the device-path section runs
        # on whatever backend JAX_PLATFORMS selects (default cpu)
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        out = run_recovery_bench()
        _emit({
            "metric": "wal_recovery_time_to_first_tick_s",
            "value": out["time_to_first_tick_s"],
            "unit": "s",
            **out,
        }, json_out, mode="recovery")
        return

    if env_flag("REFLOW_BENCH_PIPELINE"):
        # pipelined-window mode measures the device window path — do NOT
        # force cpu; the tier-1 smoke sets JAX_PLATFORMS=cpu explicitly
        out = run_pipeline_bench()
        _emit({
            "metric": "pipeline_depth2_vs_depth1_x",
            "value": out["depth2_vs_depth1_x"],
            "unit": "x",
            **out,
        }, json_out, mode="pipeline")
        return

    if env_flag("REFLOW_BENCH_MEGATICK"):
        # mega-tick mode measures the device window path — do NOT force
        # cpu here; the tier-1 smoke sets JAX_PLATFORMS=cpu explicitly
        out = run_megatick_bench()
        _emit({
            "metric": "megatick_amortized_tick_over_window_dispatch_x",
            "value": out["amortized_over_dispatch_x"],
            "unit": "x",
            **out,
        }, json_out, mode="megatick")
        return

    child = env_str("REFLOW_BENCH_CHILD", None)
    if child:
        try:
            out = _CHILDREN[child]()
        except Exception as e:  # noqa: BLE001 - report, don't die silently
            out = {"error": f"{type(e).__name__}: {e}"}
            import traceback
            traceback.print_exc(file=sys.stderr)
        print(json.dumps(out), flush=True)
        return

    p = _params()
    import jax
    log(f"jax backend={jax.default_backend()} devices={len(jax.devices())}")

    # configs 1/2/4/5 first (records on stderr), headline (config 3) last
    # so the final stdout line stays the parseable result
    if env_flag("REFLOW_BENCH_ALL"):
        for name in ("cfg1", "cfg2", "cfg4", "cfg5"):
            r = _spawn(name)
            if "error" in r:
                log(json.dumps({"config": name, **r}))

    tpu = _spawn("pr_tpu")
    log("tpu:", json.dumps(tpu))
    if "error" in tpu:
        _emit({
            "metric": ("pagerank_incremental_delta_ops_per_s_speedup"
                       "_vs_cpu_executor"),
            "value": 0.0, "unit": "x", "vs_baseline": 0.0,
            "error": tpu["error"],
        }, json_out, mode="pagerank")
        return
    # the deferred window (cross-tick residual deferral, defer_passes):
    # the incr_vs_full lever, with its accuracy contract measured in the
    # child (mid-stream + drained error vs the independent oracle)
    tpud = None
    if p["defer"]:
        tpud = _spawn("pr_tpu_defer")
        log("tpu_defer:", json.dumps(tpud))
        if "error" in tpud:
            tpud = None

    # full-recompute baseline: MEDIAN OF 3 SUBPROCESSES (VERDICT r4 #2 —
    # one subprocess snapshot was the bottom of the variance band). Each
    # child still takes min-of-3 in-process rounds (the outlier guard on
    # the numerator's pipelined-vs-degraded regimes); the cross-process
    # median guards the day-dependent tunnel.
    full_runs = []
    for i in range(1 if p["smoke"] else 3):
        r = _spawn("pr_full")
        log(f"full[{i}]:", json.dumps(r))
        if "full_recompute_s" in r:
            full_runs.append(r["full_recompute_s"])
    incr_vs_full = incr_vs_full_q = None
    incr_vs_full_runs = []
    full_med = float(np.median(full_runs)) if full_runs else None
    if full_med is not None:
        incr_vs_full_q = full_med / tpu["tick_s_amortized"]
        log(f"incremental-vs-full (quiescent window): "
            f"{incr_vs_full_q:.1f}x")
        if tpud is not None:
            incr_vs_full = full_med / tpud["tick_s_amortized"]
            incr_vs_full_runs = [
                round(f / tpud["tick_s_amortized"], 2) for f in full_runs]
            log(f"incremental-vs-full (deferred window, "
                f"defer={tpud.get('defer_passes')}): {incr_vs_full:.1f}x "
                f"runs={incr_vs_full_runs}")
        else:
            incr_vs_full = incr_vs_full_q
            incr_vs_full_runs = [
                round(f / tpu["tick_s_amortized"], 2) for f in full_runs]

    # CPU baseline: measured at the cap, with a scaling sweep making the
    # per-row-rate extrapolation explicit (the rate is flat-to-declining
    # in size, so quoting the cap-size rate at full scale is conservative)
    if p["cpu_full"]:
        cpu = run_pagerank_cpu(p["n_nodes"], p["n_edges"], p["churn"], 1,
                               p["tol"])
    else:
        sweep = []
        cap = min(p["cpu_cap"], p["n_edges"])
        e = max(256, cap // 4)
        while e <= cap:
            scale = e / p["n_edges"]
            r = run_pagerank_cpu(max(64, int(p["n_nodes"] * scale)), e,
                                 p["churn"], 1, p["tol"])
            sweep.append(r)
            log(f"cpu sweep @ {e} edges: "
                f"{r['delta_ops_per_s']:.0f} delta-ops/s")
            e *= 2
        cpu = sweep[-1]
    log("cpu:", json.dumps(cpu))

    speedup = tpu["delta_ops_per_s"] / cpu["delta_ops_per_s"]
    _emit({
        "metric": "pagerank_incremental_delta_ops_per_s_speedup_vs_cpu_executor",
        "value": round(speedup, 2),
        "unit": "x",
        "vs_baseline": round(speedup / 20.0, 3),
        "tpu_delta_ops_per_s": round(tpu["delta_ops_per_s"]),
        "tpu_window_ticks": tpu.get("window_ticks"),
        "tpu_window_dispatch_s": tpu.get("window_dispatch_s"),
        "cpu_delta_ops_per_s": round(cpu["delta_ops_per_s"]),
        "cpu_edges": cpu["edges"],
        "incr_vs_full": (round(incr_vs_full, 2)
                         if incr_vs_full is not None else None),
        "incr_vs_full_runs": incr_vs_full_runs,
        "incr_vs_full_quiescent": (round(incr_vs_full_q, 2)
                                   if incr_vs_full_q is not None else None),
        "full_recompute_runs_s": full_runs,
        **({"defer_passes": tpud.get("defer_passes"),
            "deferred_tick_s_amortized": tpud.get("tick_s_amortized"),
            "deferred_mid_stream_max_abs_err":
                tpud.get("mid_stream_max_abs_err"),
            "deferred_mid_stream_max_rel_err":
                tpud.get("mid_stream_max_rel_err"),
            "deferred_drained_max_abs_err":
                tpud.get("drained_max_abs_err"),
            "deferred_drained_max_rel_err":
                tpud.get("drained_max_rel_err"),
            "quiescent_max_rel_err":
                tpu.get("max_rel_err_vs_reference")} if tpud else {}),
    }, json_out, mode="pagerank")


if __name__ == "__main__":
    main()
