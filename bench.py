#!/usr/bin/env python3
"""Benchmark harness: incremental PageRank (BASELINE.md config 3).

Runs the north-star workload — incremental PageRank under per-tick edge
churn — on the TpuExecutor at full scale and on the CpuExecutor (the
default path / baseline), and prints ONE JSON line to stdout::

    {"metric": ..., "value": <speedup>, "unit": "x", "vs_baseline": <v/20>}

``value`` is the delta-ops/sec throughput ratio TPU/CPU on the churn ticks
(the "delta-ops/sec/chip + incremental-vs-full speedup" metric from
BASELINE.md; the 20x divisor is the BASELINE.json north-star target).
Detail (per-executor build/tick walls, incremental-vs-full speedup) goes to
stderr.

Env knobs::

    REFLOW_BENCH_SMOKE=1          tiny scale (local sanity check)
    REFLOW_BENCH_NODES/EDGES      graph size        (default 100k / 1M)
    REFLOW_BENCH_CHURN            churn fraction    (default 0.01)
    REFLOW_BENCH_TICKS            measured ticks    (default 3)
    REFLOW_BENCH_CPU_EDGES_CAP    CPU run is scaled down to at most this
                                  many edges (Python-loop baseline; its
                                  per-row throughput is scale-independent)
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def log(*a) -> None:
    print(*a, file=sys.stderr, flush=True)


def run_pagerank(executor: str, n_nodes: int, n_edges: int, churn: float,
                 ticks: int, tol: float) -> dict:
    from reflow_tpu.executors import get_executor
    from reflow_tpu.scheduler import DirtyScheduler
    from reflow_tpu.workloads import pagerank

    # the executor's conservative overflow tracker counts padded ingress
    # *capacities* (power-of-two bucketed), so size the arena in those terms
    from reflow_tpu.executors.device_delta import bucket_capacity
    churn_cap = bucket_capacity(2 * int(churn * n_edges) + 2)
    # 2x the full-edge capacity: the warm full-recompute baseline rebuilds
    # the graph once more on the same executor (same arena tracker)
    arena = 2 * bucket_capacity(n_edges) + (ticks + 3) * churn_cap
    pr = pagerank.build_graph(n_nodes, tol=tol, arena_capacity=arena)
    sched = DirtyScheduler(pr.graph, get_executor(executor))
    web = pagerank.WebGraph.random(n_nodes, n_edges, seed=7)

    sched.push(pr.teleport, pagerank.teleport_batch(n_nodes))
    sched.push(pr.edges, web.initial_batch())
    t0 = time.perf_counter()
    sched.tick()
    build_s = time.perf_counter() - t0

    # one unmeasured churn tick to absorb jit compiles of the churn shapes
    sched.push(pr.edges, web.churn(churn))
    sched.tick()

    walls, dops = [], []
    for _ in range(ticks):
        sched.push(pr.edges, web.churn(churn))
        res = sched.tick()
        walls.append(res.wall_s)
        dops.append(res.delta_ops)

    # warm full-recompute baseline: rebuild from scratch on the same (warm)
    # executor, so jit compile time isn't billed to "full recompute"
    ex = sched.executor
    sched2 = DirtyScheduler(pr.graph, ex)
    sched2.push(pr.teleport, pagerank.teleport_batch(n_nodes))
    sched2.push(pr.edges, web.initial_batch())
    t0 = time.perf_counter()
    sched2.tick()
    full_s = time.perf_counter() - t0

    return {
        "executor": executor,
        "nodes": n_nodes,
        "edges": n_edges,
        "cold_build_s": build_s,
        "full_recompute_s": full_s,
        "tick_s_median": float(np.median(walls)),
        "delta_ops_per_s": float(sum(dops) / sum(walls)),
        "delta_ops_per_tick": float(np.mean(dops)),
    }


def main() -> None:
    smoke = os.environ.get("REFLOW_BENCH_SMOKE") == "1"
    n_nodes = int(os.environ.get(
        "REFLOW_BENCH_NODES", 1_000 if smoke else 100_000))
    n_edges = int(os.environ.get(
        "REFLOW_BENCH_EDGES", 10_000 if smoke else 1_000_000))
    churn = float(os.environ.get("REFLOW_BENCH_CHURN", 0.01))
    ticks = int(os.environ.get("REFLOW_BENCH_TICKS", 2 if smoke else 3))
    cpu_cap = int(os.environ.get(
        "REFLOW_BENCH_CPU_EDGES_CAP", 10_000 if smoke else 100_000))
    tol = 1e-4

    import jax
    log(f"jax backend={jax.default_backend()} devices={len(jax.devices())}")

    tpu = run_pagerank("tpu", n_nodes, n_edges, churn, ticks, tol)
    log("tpu:", json.dumps(tpu))
    incr_vs_full = tpu["full_recompute_s"] / tpu["tick_s_median"]
    log(f"incremental-vs-full (tpu executor, warm): {incr_vs_full:.1f}x")

    scale = min(1.0, cpu_cap / n_edges)
    cpu = run_pagerank("cpu", max(64, int(n_nodes * scale)),
                       max(256, int(n_edges * scale)), churn,
                       max(1, min(ticks, 2)), tol)
    log("cpu:", json.dumps(cpu))

    speedup = tpu["delta_ops_per_s"] / cpu["delta_ops_per_s"]
    print(json.dumps({
        "metric": "pagerank_incremental_delta_ops_per_s_speedup_vs_cpu_executor",
        "value": round(speedup, 2),
        "unit": "x",
        "vs_baseline": round(speedup / 20.0, 3),
    }))


if __name__ == "__main__":
    main()
