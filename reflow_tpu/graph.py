"""FlowGraph: the dataflow IR (SURVEY.md §2 item 1, §3 stack 1).

A ``FlowGraph`` is a DAG of nodes (sources, ops, sinks) plus optional
*back-edges* for fixpoint iteration (SURVEY.md §2 item 13). Nodes carry an
output :class:`~reflow_tpu.delta.Spec` so the TPU executor can build
static-shape device buffers; host-only graphs may leave specs at their
defaults.

Graph construction performs static validation (arity, spec compatibility,
acyclicity modulo declared back-edges, deterministic topo order — the graph
validator the survey calls for in §5 in lieu of a data-race sanitizer).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from reflow_tpu.delta import DeltaBatch, Spec
from reflow_tpu.ops import (Filter, GroupBy, Join, KnnIndex, Map, Op, Reduce,
                            Union)

__all__ = ["Node", "FlowGraph", "GraphError"]


class GraphError(ValueError):
    pass


@dataclasses.dataclass(eq=False)
class Node:
    """One vertex: a source, an operator, or a sink."""

    id: int
    name: str
    kind: str                      # 'source' | 'op' | 'sink' | 'loop'
    op: Optional[Op]
    inputs: Tuple["Node", ...]     # ordered input ports
    spec: Spec
    # loop nodes: the node whose output feeds back into this one (back-edge)
    back_input: Optional["Node"] = None
    # optional per-node sharding hint consumed by the TPU executor:
    # 'key' (shard by key over the mesh), 'replicate', or None (inherit)
    sharding: Optional[str] = None
    # optional partition/stage assignment for topo-partitioned execution
    stage: Optional[int] = None
    # loop nodes: cap on fixpoint passes per tick (close_loop defer_passes).
    # None = run to quiescence every tick. When set, device fixpoint
    # programs may stop after this many passes and carry the residual
    # loop deltas into the next tick (cross-tick residual deferral — see
    # docs/guide.md "Deferred fixpoint"); the CPU oracle and the
    # row-based device program always run to quiescence (strictly more
    # converged, same fixpoint).
    defer_passes: Optional[int] = None

    def __hash__(self):
        return self.id

    def __repr__(self):
        return f"<{self.kind}:{self.name}#{self.id}>"


class FlowGraph:
    """Builder + container for the dataflow graph.

    Typical usage::

        g = FlowGraph()
        lines = g.source("lines", Spec((), np.int64, key_space=V))
        words = g.map(lines, tokenize)
        counts = g.reduce(words, "count", name="counts")
        out = g.sink(counts, "out")
    """

    def __init__(self, name: str = "flow"):
        self.name = name
        self.nodes: List[Node] = []
        self.sources: List[Node] = []
        self.sinks: List[Node] = []
        self.loops: List[Node] = []
        self._consumers: Dict[int, List[Tuple[Node, int]]] = {}
        self._frozen = False

    # -- construction ------------------------------------------------------

    def _add(self, name: Optional[str], kind: str, op: Optional[Op],
             inputs: Sequence[Node], spec: Spec) -> Node:
        if self._frozen:
            raise GraphError("graph is frozen (already validated/executed)")
        for inp in inputs:
            if inp not in self.nodes:
                raise GraphError(f"input {inp} is not a node of this graph")
            if inp.kind == "sink":
                raise GraphError("sinks have no output to consume")
        node = Node(
            id=len(self.nodes),
            name=name or f"{kind}{len(self.nodes)}",
            kind=kind,
            op=op,
            inputs=tuple(inputs),
            spec=spec,
        )
        self.nodes.append(node)
        for port, inp in enumerate(node.inputs):
            self._consumers.setdefault(inp.id, []).append((node, port))
        return node

    def source(self, name: str, spec: Spec = Spec()) -> Node:
        node = self._add(name, "source", None, (), spec)
        self.sources.append(node)
        return node

    def sink(self, input: Node, name: str) -> Node:
        node = self._add(name, "sink", None, (input,), input.spec)
        self.sinks.append(node)
        return node

    def loop(self, name: str, spec: Spec = Spec()) -> Node:
        """Declare a loop variable (a source-like node fed by a back-edge).

        Close it with :meth:`close_loop`; the scheduler then re-ticks the
        cyclic region until deltas quiesce (host-driven), and the TPU
        executor may lower the whole fixpoint to ``lax.while_loop``.
        """
        node = self._add(name, "loop", None, (), spec)
        self.loops.append(node)
        return node

    def close_loop(self, loop: Node, result: Node, *,
                   defer_passes: Optional[int] = None) -> None:
        """Close a loop's back-edge. ``defer_passes`` opts the region into
        cross-tick residual deferral: a device fixpoint program may stop
        after that many passes per tick, carrying the un-propagated loop
        deltas (as dense linear observables) into the next tick instead
        of iterating to quiescence. Amortizes convergence across a churn
        stream at a documented accuracy trade (docs/guide.md "Deferred
        fixpoint"); ``DirtyScheduler.drain`` flushes the residue."""
        if loop.kind != "loop":
            raise GraphError(f"{loop} is not a loop node")
        if loop.back_input is not None:
            raise GraphError(f"{loop} already closed")
        if result not in self.nodes:
            raise GraphError(f"{result} is not a node of this graph")
        if defer_passes is not None and defer_passes < 1:
            raise GraphError(f"defer_passes must be >= 1, got {defer_passes}")
        loop.back_input = result
        loop.defer_passes = defer_passes

    # op sugar -------------------------------------------------------------

    def add_op(self, op: Op, inputs: Sequence[Node], name: Optional[str] = None,
               spec: Optional[Spec] = None) -> Node:
        if len(inputs) != op.arity:
            raise GraphError(
                f"{op!r} expects {op.arity} inputs, got {len(inputs)}")
        out = spec if spec is not None else op.out_spec([n.spec for n in inputs])
        return self._add(name, "op", op, inputs, out)

    def map(self, input: Node, fn: Callable, *, vectorized: bool = False,
            linear: bool = False, name: Optional[str] = None,
            spec: Optional[Spec] = None, params=None,
            param_specs=None) -> Node:
        op = Map(fn, vectorized=vectorized, linear=linear, out_spec=spec,
                 params=params, param_specs=param_specs)
        return self.add_op(op, [input], name=name)

    def filter(self, input: Node, pred: Callable, *, vectorized: bool = False,
               name: Optional[str] = None) -> Node:
        return self.add_op(Filter(pred, vectorized=vectorized), [input], name=name)

    def group_by(self, input: Node, key_fn: Callable,
                 value_fn: Optional[Callable] = None, *, vectorized: bool = False,
                 name: Optional[str] = None, spec: Optional[Spec] = None,
                 stable_key: bool = False) -> Node:
        op = GroupBy(key_fn, value_fn, vectorized=vectorized, out_spec=spec,
                     stable_key=stable_key)
        return self.add_op(op, [input], name=name)

    def reduce(self, input: Node, how: str = "sum", *, tol: float = 0.0,
               name: Optional[str] = None, spec: Optional[Spec] = None,
               candidates: int = 8) -> Node:
        op = Reduce(how, tol=tol, out_spec=spec, candidates=candidates)
        return self.add_op(op, [input], name=name)

    def join(self, left: Node, right: Node, merge: Optional[Callable] = None,
             *, name: Optional[str] = None, spec: Optional[Spec] = None,
             arena_capacity: int = 1 << 16,
             linear_left: bool = False,
             left_arena_capacity: Optional[int] = None,
             product_slack: int = 4) -> Node:
        op = Join(merge, out_spec=spec, arena_capacity=arena_capacity,
                  linear_left=linear_left,
                  left_arena_capacity=left_arena_capacity,
                  product_slack=product_slack)
        return self.add_op(op, [left, right], name=name)

    def union(self, *inputs: Node, name: Optional[str] = None) -> Node:
        return self.add_op(Union(arity=len(inputs)), list(inputs), name=name)

    def knn(self, queries: Node, docs: Node, k: int, dim: int, *,
            name: Optional[str] = None, scan_chunk: int = 8192,
            precision: str = "highest") -> Node:
        op = KnnIndex(k, dim, scan_chunk=scan_chunk, precision=precision)
        return self.add_op(op, [queries, docs], name=name)

    # -- structure queries -------------------------------------------------

    def consumers(self, node: Node) -> List[Tuple[Node, int]]:
        """(consumer, input-port) pairs fed by ``node``'s output (DAG edges
        only; back-edges are reached via ``Node.back_input``)."""
        return self._consumers.get(node.id, [])

    def back_consumers(self, node: Node) -> List[Node]:
        return [l for l in self.loops if l.back_input is node]

    def topo_order(self) -> List[Node]:
        """Deterministic topological order ignoring back-edges.

        Node ids are assigned in construction order and inputs must already
        exist, so construction order *is* a topo order; we validate that
        invariant rather than re-sorting, keeping the order deterministic
        across runs (SURVEY.md §5: determinism in place of race detection).
        """
        for node in self.nodes:
            for inp in node.inputs:
                if inp.id >= node.id:
                    raise GraphError(
                        f"forward reference {inp} -> {node}; DAG edges must "
                        f"flow in construction order (use loop() for cycles)")
        return list(self.nodes)

    def validate(self) -> None:
        self.topo_order()
        for loop in self.loops:
            if loop.back_input is None:
                raise GraphError(f"{loop} was never closed (close_loop)")
        for sink in self.sinks:
            (inp,) = sink.inputs
            if inp.kind == "sink":
                raise GraphError("sink of sink")
        self._frozen = True

    def loop_region(self) -> List[Node]:
        """Nodes on a path loop -> ... -> back_input (the cyclic region)."""
        region: set = set()
        for loop in self.loops:
            if loop.back_input is None:
                continue
            reach_fwd = {loop.id}
            changed = True
            while changed:
                changed = False
                for n in self.nodes:
                    if n.id not in reach_fwd and any(i.id in reach_fwd for i in n.inputs):
                        reach_fwd.add(n.id)
                        changed = True
            back = {loop.back_input.id}
            changed = True
            while changed:
                changed = False
                for n in self.nodes:
                    if n.id in back:
                        for i in n.inputs:
                            if i.id not in back:
                                back.add(i.id)
                                changed = True
            region |= (reach_fwd & back) | {loop.id}
        return [n for n in self.nodes if n.id in region]
