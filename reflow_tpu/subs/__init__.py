"""Reactive reads: push-based standing queries with delta fan-out.

The incremental dataflow computes exactly what changed every commit
window; this package stops throwing that away. Clients register
standing queries (``view`` / ``lookup`` / ``topk``) against a replica
and receive only the per-query delta per window — over the wire or
in-process — with a one-integer cursor making reconnect resume
gap-free and duplicate-free. See docs/guide.md "Reactive reads".
"""

from reflow_tpu.subs.client import Subscriber
from reflow_tpu.subs.hub import SubHandle, SubscriptionHub
from reflow_tpu.subs.query import (DeltaFrame, QueryState, StandingQuery,
                                   canon_query, merge_frames)
from reflow_tpu.subs.wire import SubAck, SubscribeReq, SubscriptionServer

__all__ = ["Subscriber", "SubHandle", "SubscriptionHub", "DeltaFrame",
           "QueryState", "StandingQuery", "canon_query", "merge_frames",
           "SubAck", "SubscribeReq", "SubscriptionServer"]
