"""``python -m reflow_tpu.subs`` — see :mod:`reflow_tpu.subs.cli`."""

from __future__ import annotations

import sys

from reflow_tpu.subs.cli import main

if __name__ == "__main__":
    sys.exit(main())
