"""``python -m reflow_tpu.subs`` — tail one standing query.

The operator-facing face of reactive reads (docs/guide.md "Reactive
reads"): dial a replica's subscription endpoint (the ``subs`` address
on its ready line / ``status``), register a standing query, and print
one line per applied commit window. Human mode renders the
reconstructed answer compactly; ``--json`` emits one
``reflow.sub/1`` document per update for scripting::

    python tools/reflow_sub.py --connect 127.0.0.1:45131 \\
        --sink counts --kind topk --k 5
    python tools/reflow_sub.py --connect 127.0.0.1:45131 \\
        --sink counts --kind lookup --key the,2 --json

Exit is clean on ``--rounds`` / ``--duration`` expiry or Ctrl-C; a
down link is survived silently (the subscriber resumes from its
cursor when the replica heals — gap-free, duplicate-free).
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Any, Dict

SUB_SCHEMA = "reflow.sub/1"

__all__ = ["SUB_SCHEMA", "main", "make_update", "render_update"]


def _addr(text: str):
    host, _, port = text.rpartition(":")
    return (host or "127.0.0.1", int(port))


def _key(text: str):
    """Parse a ``--key`` operand. View keys are often ``(key, value)``
    pairs (the multiset the dataflow maintains), so a comma builds a
    tuple; numeric parts become floats (the dataflow's value type) —
    ``the,2`` means ``("the", 2.0)``."""
    parts = []
    for p in text.split(","):
        try:
            parts.append(float(p))
        except ValueError:
            parts.append(p)
    return tuple(parts) if len(parts) > 1 else parts[0]


def _json_rows(kind: str, value) -> Any:
    """The reconstructed answer in JSON-able shape: lookup is a bare
    number; view/topk are ``[key, weight]`` pairs (view sorted by key
    for stable diffs, topk in rank order; tuple keys become lists)."""
    if kind == "lookup":
        return value
    if kind == "view":
        items = sorted(value.items(), key=lambda it: str(it[0]))
    else:
        items = list(value)
    return [[list(kv) if isinstance(kv, tuple) else kv, w]
            for kv, w in items]


def make_update(sub, *, ts_wall: float) -> Dict[str, Any]:
    """One ``reflow.sub/1`` document from a live subscriber."""
    kind = sub.query.kind
    return {
        "schema": SUB_SCHEMA,
        "ts_wall": round(ts_wall, 3),
        "sink": sub.query.sink,
        "kind": kind,
        "params": list(sub.query.params),
        "horizon": sub.horizon,
        "rows": _json_rows(kind, sub.value()),
        "frames_applied": sub.frames_applied_total,
        "gaps": sub.gaps_total,
        "dups_skipped": sub.dups_skipped_total,
        "rebases": sub.rebases_total,
        "link": sub.conn_state,
    }


def render_update(update: Dict[str, Any], max_rows: int = 8) -> str:
    """One human line per update (pure; the tests call this)."""
    kind = update["kind"]
    rows = update["rows"]
    if kind == "lookup":
        body = f"value={rows}"
    else:
        shown = rows[:max_rows]
        cells = " ".join(f"{r[0]}={r[1]}" for r in shown)
        more = f" …(+{len(rows) - len(shown)})" \
            if len(rows) > len(shown) else ""
        body = f"rows={len(rows)}: {cells}{more}"
    return (f"h={update['horizon']} {update['sink']}/{kind} {body}  "
            f"[link={update['link']} frames={update['frames_applied']} "
            f"gaps={update['gaps']} dups={update['dups_skipped']}]")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m reflow_tpu.subs",
        description="tail one standing query over the wire "
                    "(docs/guide.md 'Reactive reads')")
    ap.add_argument("--connect", required=True, metavar="HOST:PORT",
                    help="a replica's subscription endpoint (the "
                         "'subs' address on its ready line)")
    ap.add_argument("--sink", required=True,
                    help="sink name the query stands against")
    ap.add_argument("--kind", default="topk",
                    choices=("view", "lookup", "topk"))
    ap.add_argument("--key", default=None,
                    help="the key to stand on (lookup only); a comma "
                         "builds a (key, value) tuple — 'the,2' "
                         "means ('the', 2.0)")
    ap.add_argument("--k", type=int, default=10,
                    help="result size (topk only)")
    ap.add_argument("--by", default="weight",
                    choices=("weight", "value"),
                    help="topk ranking: multiset weight or scalar "
                         "value")
    ap.add_argument("--min-horizon", type=int, default=0,
                    help="refuse snapshots below this horizon "
                         "(read-your-writes)")
    ap.add_argument("--rounds", type=int, default=0,
                    help="stop after N printed updates (0 = forever)")
    ap.add_argument("--duration", type=float, default=0.0,
                    help="stop after S seconds (0 = forever)")
    ap.add_argument("--interval", type=float, default=1.0,
                    help="long-poll wait per pump (s)")
    ap.add_argument("--name", default="reflow-sub")
    ap.add_argument("--json", action="store_true",
                    help="emit reflow.sub/1 JSON lines instead of "
                         "the human rendering")
    args = ap.parse_args(argv)

    if args.kind == "lookup" and not args.key:
        ap.error("--kind lookup requires --key")

    from reflow_tpu.net.transport import TcpTransport
    from reflow_tpu.subs.client import Subscriber

    host, port = _addr(args.connect)
    if args.kind == "lookup":
        params = (_key(args.key),)
    elif args.kind == "topk":
        params = (args.k, args.by)
    else:
        params = ()
    sub = Subscriber(TcpTransport(host), (host, port), args.sink,
                     kind=args.kind, params=params, name=args.name,
                     min_horizon=args.min_horizon)
    printed, last_h = 0, None
    deadline = (time.monotonic() + args.duration) if args.duration \
        else None
    try:
        while True:
            sub.pump(wait_s=args.interval)
            if sub.horizon >= 0 and sub.horizon != last_h:
                last_h = sub.horizon
                update = make_update(sub, ts_wall=time.time())
                line = json.dumps(update, sort_keys=True) \
                    if args.json else render_update(update)
                print(line, flush=True)
                printed += 1
                if args.rounds and printed >= args.rounds:
                    break
            if deadline is not None \
                    and time.monotonic() >= deadline:
                break
    except KeyboardInterrupt:
        pass
    finally:
        sub.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
