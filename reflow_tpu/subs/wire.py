"""Subscription wire protocol: standing queries over ``net/`` framing.

Pickled tuples over the shared length-prefixed CRC framing (the same
carrier as replication and the ingestion RPC)::

    ("sub",) + SubscribeReq       -> ("ok",) + SubAck + (anchor,)
                                     | ("err", text)
    ("sub_poll", token, acked,
                 wait_s)          -> ("ok", frames, horizon)
                                     | ("gone", token) | ("err", text)
    ("sub_close", token)          -> ("ok",)
    ("ping",)                     -> ("ok", {name, horizon, active,
                                             shed_level})
    anything else                 -> ("err", text)

``frames`` is a tuple of plain-tuple :class:`~reflow_tpu.subs.query
.DeltaFrame`\\ s. An empty ``frames`` reply is the heartbeat: it
certifies the query unchanged through ``horizon``, which lets the
client advance its cursor without data. ``acked`` rides every poll so
the server drops delivered frames exactly when the client has durably
applied them — the cursor is the whole resume protocol. ``("gone",
token)`` means the server no longer knows the token (expired while the
client was partitioned away, or the replica restarted): the client
re-handshakes and the hub decides resume-vs-snapshot from the cursor.

The server is intentionally dumb: every decision (resume rules,
conflation, shedding, parking) lives in the
:class:`~reflow_tpu.subs.hub.SubscriptionHub`; this module only frames
it. Long polls are capped by ``REFLOW_SUB_POLL_WAIT_S`` so a subscriber
cannot pin a handler thread past the stop flag's patience.
"""

from __future__ import annotations

import threading
from typing import NamedTuple, Optional

from reflow_tpu.net.framing import TransportError, WireTimeout
from reflow_tpu.net.transport import Conn, Transport
from reflow_tpu.subs.query import frames_to_wire
from reflow_tpu.utils.config import env_float, env_int
from reflow_tpu.utils.runtime import named_lock

__all__ = ["SubscribeReq", "SubAck", "SubscriptionServer"]

#: accept/recv poll slice (matches net/server.py)
_POLL_S = 0.2


class SubscribeReq(NamedTuple):
    """Register (or resume) one standing query over the wire.
    ``cursor`` is the client's local horizon (-1 = none); ``token``
    lets a reconnecting client reclaim its server-side outbox."""

    sink: str
    kind: str = "view"
    params: tuple = ()
    cursor: int = -1
    min_horizon: int = 0
    token: Optional[str] = None


class SubAck(NamedTuple):
    """``mode`` is ``"resume"`` (stream continues from the cursor,
    gap-free and duplicate-free) or ``"snapshot"`` (a full snapshot
    frame precedes the stream)."""

    token: str
    horizon: int
    mode: str


class SubscriptionServer:
    """Host one hub's subscription endpoint over ``transport``.

    Same shape as :class:`~reflow_tpu.serve.rpc.RpcIngestServer`: an
    accept-loop thread plus one handler thread per connection, so one
    subscriber's long poll never delays another's handshake."""

    def __init__(self, hub, transport: Transport) -> None:
        self.hub = hub
        self.transport = transport
        self._poll_cap = env_float("REFLOW_SUB_POLL_WAIT_S")
        self._max_frames = env_int("REFLOW_SUB_MAX_FRAMES")
        self._listener = None
        self._stop = threading.Event()
        self._accept_thread = None
        self._lock = named_lock("subs.server")
        self._conns: list = []
        self._handlers: list = []
        self.connections_total = 0
        self.requests_total = 0
        self.subscribes_total = 0
        self.polls_total = 0

    @property
    def address(self):
        if self._listener is None:
            raise TransportError("server not started")
        return self._listener.address

    def start(self) -> "SubscriptionServer":
        if self._accept_thread is not None:
            return self
        self._listener = self.transport.listen()
        self._stop.clear()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="subs-accept", daemon=True)
        self._accept_thread.start()
        return self

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn = self._listener.accept(timeout_s=_POLL_S)
            except WireTimeout:
                continue
            except TransportError:
                return  # listener closed under us
            with self._lock:
                if self._stop.is_set():
                    conn.close()
                    return
                self.connections_total += 1
                t = threading.Thread(
                    target=self._serve_conn, args=(conn,),
                    name=f"subs-serve/{self.connections_total}",
                    daemon=True)
                self._conns.append(conn)
                self._handlers.append(t)
            t.start()

    def _serve_conn(self, conn: Conn) -> None:
        try:
            while not self._stop.is_set():
                try:
                    msg = conn.recv_msg(timeout_s=_POLL_S)
                except WireTimeout:
                    continue
                except TransportError:
                    return
                try:
                    reply = self._dispatch(msg)
                except TransportError:
                    raise
                except Exception as e:  # noqa: BLE001 - a poisoned
                    # request must not kill the endpoint for the others
                    reply = ("err", f"{type(e).__name__}: {e}")
                try:
                    conn.send_msg(reply)
                except TransportError:
                    return
        finally:
            conn.close()
            with self._lock:
                if conn in self._conns:
                    self._conns.remove(conn)

    # -- ops -----------------------------------------------------------

    def _dispatch(self, msg):
        if not isinstance(msg, tuple) or not msg:
            return ("err", f"malformed request {type(msg).__name__}")
        self.requests_total += 1
        op, args = msg[0], msg[1:]
        if op == "sub":
            return self._op_sub(SubscribeReq(*args))
        if op == "sub_poll":
            return self._op_poll(*args)
        if op == "sub_close":
            self.hub.unsubscribe(args[0])
            return ("ok",)
        if op == "ping":
            load = self.hub.load()
            return ("ok", {"name": self.hub.name,
                           "horizon": load["horizon"],
                           "active": load["active"],
                           "shed_level": load["shed_level"]})
        return ("err", f"unknown op {op!r}")

    def _op_sub(self, req: SubscribeReq):
        self.subscribes_total += 1
        token, mode = self.hub.subscribe(
            req.sink, req.kind, req.params, token=req.token,
            cursor=req.cursor, min_horizon=req.min_horizon, wire=True)
        # trailing clock anchor (obs.wire.clock_anchor) piggybacks on
        # the handshake so post-mortem tools can align this process's
        # monotonic clock; older clients ignore extra elements.
        from reflow_tpu.obs.wire import clock_anchor
        return ("ok",) + tuple(
            SubAck(token, self.hub.fanout_horizon, mode)) + (
            clock_anchor(),)

    def _op_poll(self, token, acked, wait_s):
        self.polls_total += 1
        wait = min(max(float(wait_s), 0.0), self._poll_cap)
        try:
            frames, horizon = self.hub.poll(
                token, acked=acked, wait_s=wait,
                max_frames=self._max_frames)
        except KeyError:
            return ("gone", token)
        return ("ok", frames_to_wire(frames), horizon)

    def close(self) -> None:
        self._stop.set()
        if self._listener is not None:
            self._listener.close()
        with self._lock:
            conns = list(self._conns)
            handlers = list(self._handlers)
        for c in conns:
            c.close()
        t, self._accept_thread = self._accept_thread, None
        if t is not None:
            t.join(timeout=5.0)
        for h in handlers:
            h.join(timeout=5.0)
