"""Standing queries and delta frames — the data model of reactive reads.

A *standing query* is a pull-path read (``view_at`` / ``lookup`` /
``top_k``) turned persistent: instead of recomputing the answer on
every call, the subscriber holds the answer locally and the hub pushes
only what changed per applied commit window. Three kinds:

- ``view``: the whole sink projection (``view_at``). Deltas are
  ``((key, value), dweight)`` rows — additive weight changes.
- ``lookup``: one key's aggregate weight (``lookup``). Deltas are the
  ``view`` rows filtered to that key.
- ``topk``: the ranked top-``k`` (``top_k``). Rank entries/exits don't
  compose additively, so topk frames always carry the full ranked list
  (absolute, not additive) and the client replaces wholesale.

**Frames and contiguity.** A :class:`DeltaFrame` spans the half-open
horizon interval ``(from_h, to_h]``. The hub skips empty windows (no
frame when nothing changed for the query), so consecutive frames are
contiguous *per query*: ``from_h`` is always the previous frame's
``to_h`` (or the snapshot horizon for the first). A client at local
horizon ``h`` applies a frame iff ``from_h <= h < to_h`` — the overlap
region ``(from_h, h]`` is provably changeless for this query (had it
changed, a frame ending there would have been emitted), so applying
the whole span is exact. ``to_h <= h`` means duplicate (skip, count);
``from_h > h`` means gap (count, rebase via snapshot). This rule is
what makes reconnect-resume duplicate-free *and* gap-free with only a
scalar cursor.

:class:`QueryState` is the client-side apply engine, shared by the
in-process :class:`~reflow_tpu.subs.hub.SubHandle` and the wire
:class:`~reflow_tpu.subs.client.Subscriber`; :func:`merge_frames` is
the conflation kernel the hub uses when a slow subscriber's outbox
overflows.
"""

from __future__ import annotations

import heapq
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

KINDS = ("view", "lookup", "topk")


class StandingQuery(NamedTuple):
    """Canonical, hashable identity of a standing query. Subscribers
    with the same ``StandingQuery`` share one fan (one delta stream
    computed once, appended to every member's outbox)."""
    sink: str
    kind: str      # "view" | "lookup" | "topk"
    params: tuple  # () | (key,) | (k, by)


class DeltaFrame(NamedTuple):
    """One push over the interval ``(from_h, to_h]``.

    ``rows`` for view/lookup: ``((key_value_pair, dweight), ...)``
    (absolute weights when ``snapshot``); for topk: the full ranked
    ``((key_value_pair, weight), ...)`` — always absolute.

    ``cause`` is the optional tuple of causality tokens
    (``obs.trace.mint_cause``) of the sampled writes folded into this
    frame's window — the ``Shipment`` pattern extended to the push
    path: trailing + defaulted, trimmed off the wire form when None
    (:func:`frames_to_wire`) so tracing-off frames stay byte-identical
    to the pre-trace protocol."""
    from_h: int
    to_h: int
    kind: str
    rows: tuple
    snapshot: bool
    cause: Optional[tuple] = None


def canon_query(sink: str, kind: str, params: Sequence = ()) -> StandingQuery:
    """Validate and canonicalize into a hashable :class:`StandingQuery`.
    Lists (e.g. JSON-decoded keys) become tuples so equal queries hash
    equal across the wire."""
    if kind not in KINDS:
        raise ValueError(f"unknown query kind {kind!r} (want one of {KINDS})")
    p = tuple(params)
    if kind == "view":
        if p:
            raise ValueError("view query takes no params")
    elif kind == "lookup":
        if len(p) != 1:
            raise ValueError("lookup query wants params=(key,)")
        key = p[0]
        if isinstance(key, list):
            key = tuple(key)
        p = (key,)
    else:  # topk
        if len(p) == 1:
            p = (int(p[0]), "weight")
        if len(p) != 2 or p[1] not in ("weight", "value"):
            raise ValueError("topk query wants params=(k,) or "
                             "(k, 'weight'|'value')")
        p = (int(p[0]), p[1])
        if p[0] <= 0:
            raise ValueError("topk k must be positive")
    return StandingQuery(str(sink), kind, p)


def _rank_key(by: str):
    if by == "value":
        return lambda item: item[0][1]
    return lambda item: item[1]


def topk_rows(view: Dict, k: int, by: str) -> tuple:
    """Deterministic ranked tuple over a sink view mapping
    ``(key, value) -> weight``. Ties break on the string form of the
    pair so equal views always rank identically (frame-change detection
    and cross-path parity both rely on this)."""
    rank = _rank_key(by)
    top = heapq.nsmallest(k, view.items(),
                          key=lambda it: (-rank(it), str(it[0])))
    return tuple((kv, w) for kv, w in top)


def query_value(query: StandingQuery, view: Dict):
    """Evaluate ``query`` against a full sink view (the pull-path
    answer shape): dict for view, float for lookup, ranked tuple for
    topk."""
    if query.kind == "view":
        return dict(view)
    if query.kind == "lookup":
        return float(view.get(query.params[0], 0.0))
    return topk_rows(view, *query.params)


def snapshot_rows(query: StandingQuery, view: Dict) -> tuple:
    """Absolute rows for a snapshot frame of ``query``."""
    if query.kind == "view":
        return tuple(view.items())
    if query.kind == "lookup":
        key = query.params[0]
        return ((key, view[key]),) if key in view else ()
    return topk_rows(view, *query.params)


def delta_rows(query: StandingQuery, deltas: Dict, view: Dict,
               last_topk: Optional[tuple]) -> Optional[tuple]:
    """Rows for a delta frame, or ``None`` when this window is empty
    for the query (no frame emitted — contiguity is per query).

    ``deltas`` maps ``(key, value) -> dweight`` accumulated over the
    window; ``view`` is the post-window mirror; ``last_topk`` is the
    previously emitted ranked tuple for topk change detection."""
    if query.kind == "view":
        rows = tuple((kv, dw) for kv, dw in deltas.items() if dw != 0)
        return rows or None
    if query.kind == "lookup":
        key = query.params[0]
        dw = deltas.get(key, 0)
        return ((key, dw),) if dw != 0 else None
    ranked = topk_rows(view, *query.params)
    if last_topk is not None and ranked == last_topk:
        return None
    return ranked


class QueryState:
    """Client-side state of one standing query: applies frames by the
    contiguity rule, counts duplicates and gaps, reconstructs the
    current value. ``horizon`` is ``-1`` until the first snapshot."""

    __slots__ = ("query", "horizon", "applied", "dups_skipped", "gaps",
                 "rebases", "_view", "_weight", "_ranked")

    def __init__(self, query: StandingQuery):
        self.query = query
        self.horizon = -1
        self.applied = 0
        self.dups_skipped = 0
        self.gaps = 0
        self.rebases = 0
        self._view: Dict = {}
        self._weight = 0.0
        self._ranked: tuple = ()

    def apply(self, frame: DeltaFrame) -> bool:
        """Apply one frame. Returns True when the frame advanced local
        state; False for duplicates (skipped) and gaps (counted — the
        caller should request a rebase snapshot)."""
        if frame.snapshot:
            if frame.to_h == self.horizon:
                self.dups_skipped += 1
                return False
            # to_h < horizon is a deliberate rewind (replica bootstrap
            # / promote moved state non-monotonically) — accept it.
            self._load_snapshot(frame.rows)
            self.horizon = frame.to_h
            self.applied += 1
            self.rebases += 1
            return True
        if frame.to_h <= self.horizon:
            self.dups_skipped += 1
            return False
        if self.horizon < 0 or frame.from_h > self.horizon:
            self.gaps += 1
            return False
        self._apply_rows(frame.rows)
        self.horizon = frame.to_h
        self.applied += 1
        return True

    def note_horizon(self, horizon: int) -> None:
        """Advance past changeless windows: an empty poll that reports
        fan-out horizon ``h`` proves no frame was emitted in
        ``(local, h]``, i.e. the query's answer did not change there.
        No-op until the first snapshot has seeded state."""
        if self.horizon >= 0 and horizon > self.horizon:
            self.horizon = horizon

    def _load_snapshot(self, rows: tuple) -> None:
        q = self.query
        if q.kind == "view":
            self._view = {kv: w for kv, w in rows}
        elif q.kind == "lookup":
            self._weight = float(rows[0][1]) if rows else 0.0
        else:
            self._ranked = tuple(rows)

    def _apply_rows(self, rows: tuple) -> None:
        q = self.query
        if q.kind == "view":
            view = self._view
            for kv, dw in rows:
                w = view.get(kv, 0) + dw
                if w == 0:
                    view.pop(kv, None)
                else:
                    view[kv] = w
        elif q.kind == "lookup":
            key = q.params[0]
            for kv, dw in rows:
                if kv == key:
                    self._weight += dw
        else:
            self._ranked = tuple(rows)

    def value(self):
        """The reconstructed answer in the pull-path shape: dict /
        float / ranked tuple."""
        if self.query.kind == "view":
            return dict(self._view)
        if self.query.kind == "lookup":
            return float(self._weight)
        return self._ranked


def merge_frames(frames: Sequence[DeltaFrame]) -> DeltaFrame:
    """Conflate an ordered run of frames for one query into a single
    equivalent frame (the slow-subscriber escape hatch). Additive kinds
    fold deltas key-wise (restarting from the newest snapshot if one is
    present); topk keeps only the newest ranked list. The merged span
    covers ``(first.from_h, last.to_h]``."""
    if not frames:
        raise ValueError("merge_frames needs at least one frame")
    if len(frames) == 1:
        return frames[0]
    kind = frames[0].kind
    first, last = frames[0], frames[-1]
    cause = _merge_causes(frames)
    if kind == "topk":
        return DeltaFrame(first.from_h, last.to_h, kind, last.rows,
                          any(f.snapshot for f in frames), cause)
    start = 0
    snapshot = False
    for i in range(len(frames) - 1, -1, -1):
        if frames[i].snapshot:
            start, snapshot = i, True
            break
    acc: Dict = {}
    for f in frames[start:]:
        for kv, w in f.rows:
            acc[kv] = acc.get(kv, 0) + w
    rows = tuple((kv, w) for kv, w in acc.items() if w != 0)
    return DeltaFrame(first.from_h, last.to_h, kind, rows, snapshot,
                      cause)


def _merge_causes(frames: Sequence[DeltaFrame]) -> Optional[tuple]:
    """Union (ordered, deduplicated) of the merged frames' causality
    tokens — conflation must not orphan a sampled write's chain."""
    out: List = []
    for f in frames:
        for c in getattr(f, "cause", None) or ():
            if c not in out:
                out.append(c)
    return tuple(out) if out else None


def frames_to_wire(frames: Sequence[DeltaFrame]) -> Tuple[tuple, ...]:
    """Plain-tuple form for pickling over ``net/`` framing. Each
    frame's one trailing None (an unstamped ``cause``) is trimmed — the
    ``Shipment`` compat pattern — so tracing-off frames pickle
    byte-identically to the pre-``cause`` protocol, and
    :func:`frames_from_wire` refills the default."""
    out = []
    for f in frames:
        fields = tuple(f)
        if fields and fields[-1] is None:
            fields = fields[:-1]
        out.append(fields)
    return tuple(out)


def frames_from_wire(raw: Sequence[tuple]) -> List[DeltaFrame]:
    return [DeltaFrame(*t) for t in raw]
