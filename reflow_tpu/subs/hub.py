"""SubscriptionHub — per-replica fan-out of standing-query deltas.

The hub sits beside a :class:`~reflow_tpu.serve.replica.ReplicaScheduler`
(attached via ``replica.attach_hub(hub)``) and turns the replica's
apply path into a push stream. The contract that keeps the write path
safe:

- **The apply path never blocks on subscribers.** The replica's only
  obligation is :meth:`on_window` — an append to a bounded work queue
  under a dedicated lock plus a condition notify. Everything expensive
  (mirror advance, per-query delta computation, 100k outbox appends)
  happens on the hub's own fan-out thread.
- **Slow subscribers degrade, never stall.** Each subscriber has a
  bounded outbox; overflow conflates the backlog into one merged frame
  (:func:`~reflow_tpu.subs.query.merge_frames`), and a backlog too
  large even to conflate sheds the subscriber to snapshot semantics
  (outbox cleared, rebase flag set — the next round delivers a fresh
  snapshot). Both are counted.
- **Shed ladder** (driven by :class:`~reflow_tpu.serve.control
  .ControlPlane`): level 0 normal; level 1 conflates eagerly (outbox
  never holds more than one frame); level 2 pauses emission entirely —
  mirrors still advance so correctness is preserved, and recovery
  re-snapshots every subscriber.

**Fan-out rounds.** Each round drains queued windows, advances one
per-sink *mirror* (a full view the fan-out thread owns exclusively),
computes at most one frame per distinct query (a *fan* — subscribers
sharing a query share the stream), appends it to member outboxes under
sharded locks, then services rebase-flagged subscribers with snapshot
frames and finally advances the published fan-out horizon. Frames are
appended *before* the horizon advances, and :meth:`poll` reads the
horizon *before* inspecting the outbox — that ordering is what lets an
empty poll double as a heartbeat that safely advances the client's
cursor past changeless windows.

``min_horizon=`` inherits the :class:`~reflow_tpu.serve.read.ReadTier`
semantics: a subscription parks (no snapshot, no deltas) until the
fan-out horizon reaches ``min_horizon`` — read-your-writes for
subscribers.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

from reflow_tpu.obs import trace as _trace
from reflow_tpu.obs.registry import REGISTRY
from reflow_tpu.subs.query import (DeltaFrame, QueryState, StandingQuery,
                                   canon_query, delta_rows, merge_frames,
                                   snapshot_rows)
from reflow_tpu.utils.config import env_float, env_int
from reflow_tpu.utils.faults import CrashPoint
from reflow_tpu.utils.runtime import named_lock

_POLL_S = 0.2
#: windows queued beyond this are folded into a rebase (fan-out thread
#: dead or badly behind) — on_window stays O(1) and bounded either way.
_WQ_MAX = 4096


class _Mirror:
    """Fan-out-thread-owned copy of one sink view at horizon ``h``."""
    __slots__ = ("h", "view")

    def __init__(self, h: int, view: Dict):
        self.h = h
        self.view = view


class _Fan:
    """One distinct standing query and its member tokens. The delta
    stream is computed once per fan per round."""
    __slots__ = ("query", "tokens", "last_emit_h", "last_topk")

    def __init__(self, query: StandingQuery):
        self.query = query
        self.tokens: set = set()
        self.last_emit_h: Optional[int] = None
        self.last_topk: Optional[tuple] = None


class _Sub:
    __slots__ = ("token", "query", "outbox", "acked", "rebase",
                 "min_horizon", "wire", "expire_s", "last_seen")

    def __init__(self, token: str, query: StandingQuery, *,
                 min_horizon: int, wire: bool, expire_s: Optional[float],
                 now: float):
        self.token = token
        self.query = query
        self.outbox: deque = deque()
        self.acked = -1
        self.rebase = True
        self.min_horizon = min_horizon
        self.wire = wire
        self.expire_s = expire_s
        self.last_seen = now


class _Shard:
    __slots__ = ("lock", "cond", "subs")

    def __init__(self, name: str):
        self.lock = named_lock(name)
        self.cond = threading.Condition(self.lock)
        self.subs: Dict[str, _Sub] = {}


class SubHandle:
    """In-process subscriber: drains its hub outbox directly into a
    :class:`~reflow_tpu.subs.query.QueryState`. This is both the
    programmatic API and the unit the 100k-subscriber bench simulates
    (the wire :class:`~reflow_tpu.subs.client.Subscriber` wraps the
    same state machine around a transport)."""

    def __init__(self, hub: "SubscriptionHub", token: str,
                 query: StandingQuery):
        self.hub = hub
        self.token = token
        self.state = QueryState(query)

    def drain(self, wait_s: float = 0.0,
              max_frames: Optional[int] = None) -> int:
        """Poll once and apply; returns frames that advanced state."""
        frames, horizon = self.hub.poll(self.token,
                                        acked=self.state.horizon,
                                        wait_s=wait_s,
                                        max_frames=max_frames)
        n = 0
        for f in frames:
            if self.state.apply(f):
                n += 1
        self.state.note_horizon(horizon)
        return n

    def wait_horizon(self, horizon: int, timeout_s: float = 5.0) -> bool:
        """Drain until local state reaches ``horizon`` (or timeout)."""
        deadline = time.monotonic() + timeout_s
        while self.state.horizon < horizon:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return False
            self.drain(wait_s=min(remaining, _POLL_S))
        return True

    @property
    def horizon(self) -> int:
        return self.state.horizon

    def value(self):
        return self.state.value()

    def close(self) -> None:
        self.hub.unsubscribe(self.token)


class SubscriptionHub:
    """Standing-query fan-out for one replica. See module docstring.

    ``start=False`` leaves the fan-out thread unstarted so tests can
    drive rounds deterministically with :meth:`pump_once`."""

    def __init__(self, replica, *, name: Optional[str] = None,
                 shards: int = 8,
                 outbox_max: Optional[int] = None,
                 conflate_max_rows: Optional[int] = None,
                 idle_poll_s: Optional[float] = None,
                 expire_s: Optional[float] = None,
                 crash=None, start: bool = True):
        self.replica = replica
        self.name = name or getattr(replica, "name", "hub")
        self.outbox_max = (outbox_max if outbox_max is not None
                           else env_int("REFLOW_SUB_OUTBOX"))
        self.conflate_max_rows = (
            conflate_max_rows if conflate_max_rows is not None
            else env_int("REFLOW_SUB_CONFLATE_MAX_ROWS"))
        self._idle_poll_s = (idle_poll_s if idle_poll_s is not None
                             else env_float("REFLOW_SUB_IDLE_POLL_S"))
        self._expire_s = (expire_s if expire_s is not None
                          else env_float("REFLOW_SUB_EXPIRE_S"))
        self._crash = crash
        # registry lock: fans + token issuance. Ordered before shard
        # locks; never acquired from under one.
        self._reg = named_lock(f"subs.hub.{self.name}")
        self._fans: Dict[StandingQuery, _Fan] = {}
        self._seq = 0
        # work queue: the only lock the replica apply path ever touches.
        self._wq_lock = named_lock(f"subs.hub.{self.name}.wq")
        self._wq_cond = threading.Condition(self._wq_lock)
        self._wq: deque = deque()
        self._rebase_all = False
        self._kick = False
        self._shed_level = 0
        self._shards: List[_Shard] = [
            _Shard(f"subs.hub.{self.name}.shard{i}") for i in range(shards)]
        self._mirrors: Dict[str, _Mirror] = {}   # fan-out thread only
        self._fanout_h = -1
        # counters (plain ints; exported as gauges by publish_metrics)
        self.windows_total = 0
        self.rounds_total = 0
        self.frames_total = 0
        self.fanout_rows_total = 0
        self.conflations_total = 0
        self.sheds_total = 0
        self.snapshots_total = 0
        self.rebases_total = 0
        self.reaped_total = 0
        self.wq_overflows = 0
        # causality tokens drained from windows but not yet stamped on
        # an emitted frame (a sampled write whose window produced no
        # frame for any fan yet rides the next frame that does emit —
        # chains must not tear on quiet queries). Fan-out thread only.
        self._pending_causes: List[str] = []
        # reservoir of window-recv → frame-emit latencies (seconds):
        # the in-hub slice of ack→push freshness, exported as
        # subs.freshness_p50/p99.
        self._freshness: deque = deque(maxlen=512)
        self.pump_errors = 0
        self.pump_error: Optional[BaseException] = None
        self._metric_names: List[Tuple[object, str]] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if start:
            self.start()

    # -- replica-facing ----------------------------------------------------

    def on_window(self, from_h: int, to_h: int, results: tuple,
                  causes: Optional[tuple] = None) -> None:
        """Called by the replica after applying a commit window
        ``(from_h, to_h]``; ``results`` holds one ``TickResult`` per
        tick. ``causes`` carries the causality tokens of any sampled
        writes in the window (tracing on) — they ride the emitted
        :class:`DeltaFrame`\\ s so the chain reaches subscribers. O(1),
        bounded, never blocks the apply path."""
        with self._wq_lock:
            if len(self._wq) >= _WQ_MAX:
                self._wq.clear()
                self._rebase_all = True
                self.wq_overflows += 1
            self._wq.append((from_h, to_h, results, causes,
                             time.perf_counter()))
            self.windows_total += 1
            self._wq_cond.notify_all()

    def rebase(self) -> None:
        """Discard mirrors and re-snapshot every subscriber on the next
        round — called when replica state moved non-monotonically
        (bootstrap / promote / re-anchor) or after a fan-out crash."""
        with self._wq_lock:
            self._wq.clear()
            self._rebase_all = True
            self._wq_cond.notify_all()

    # -- subscriber registration -------------------------------------------

    def subscribe(self, sink, kind: str = "view", params: Sequence = (), *,
                  token: Optional[str] = None, cursor: int = -1,
                  min_horizon: int = 0, wire: bool = False,
                  expire_s: Optional[float] = None) -> Tuple[str, str]:
        """Register (or resume) a standing query. Returns
        ``(token, mode)`` where mode is ``"resume"`` when the
        subscriber's cursor lets the stream continue without a
        snapshot, else ``"snapshot"``.

        Resume rules: a known ``token`` with the same query always
        resumes (its outbox still holds any unacked frames); an unknown
        token resumes iff ``cursor`` is inside the fan's changeless
        tail (``last_emit_h <= cursor <= fan-out horizon``) — nothing
        was emitted past the cursor, so the subscriber is provably
        current."""
        q = canon_query(sink, kind, params)
        now = time.monotonic()
        exp = self._expire_s if (wire and expire_s is None) else expire_s
        with self._reg:
            if token is None:
                self._seq += 1
                token = f"{self.name}-sub-{self._seq}"
            fan = self._fans.get(q)
            if fan is None:
                fan = self._fans[q] = _Fan(q)
            shard = self._shard(token)
            with shard.lock:
                sub = shard.subs.get(token)
                if sub is not None and sub.query == q:
                    sub.last_seen = now
                    fan.tokens.add(token)
                    mode = "resume" if not sub.rebase else "snapshot"
                    shard.cond.notify_all()
                    self._kick_round()
                    return token, mode
                if sub is not None:       # token reused for a new query
                    self._drop_membership(sub)
                sub = _Sub(token, q, min_horizon=min_horizon, wire=wire,
                           expire_s=exp, now=now)
                if (cursor is not None and cursor >= 0
                        and fan.last_emit_h is not None
                        and fan.last_emit_h <= cursor <= self._fanout_h
                        and cursor >= min_horizon):
                    sub.rebase = False
                    sub.acked = cursor
                    mode = "resume"
                else:
                    mode = "snapshot"
                shard.subs[token] = sub
                fan.tokens.add(token)
        self._kick_round()
        return token, mode

    def open(self, sink, kind: str = "view", params: Sequence = (), *,
             min_horizon: int = 0, token: Optional[str] = None) -> SubHandle:
        """Subscribe and wrap in an in-process :class:`SubHandle`."""
        token, _ = self.subscribe(sink, kind, params, token=token,
                                  min_horizon=min_horizon)
        return SubHandle(self, token, canon_query(sink, kind, params))

    def unsubscribe(self, token: str) -> bool:
        with self._reg:
            shard = self._shard(token)
            with shard.lock:
                sub = shard.subs.pop(token, None)
                if sub is None:
                    return False
                self._drop_membership(sub)
                shard.cond.notify_all()
        return True

    def _drop_membership(self, sub: _Sub) -> None:
        # caller holds self._reg
        fan = self._fans.get(sub.query)
        if fan is not None:
            fan.tokens.discard(sub.token)
            if not fan.tokens:
                del self._fans[sub.query]

    # -- subscriber polling ------------------------------------------------

    def poll(self, token: str, *, acked: int = -1, wait_s: float = 0.0,
             max_frames: Optional[int] = None
             ) -> Tuple[List[DeltaFrame], int]:
        """Drain up to ``max_frames`` pending frames for ``token``,
        long-polling up to ``wait_s``. Returns ``(frames, horizon)``;
        an empty list is a heartbeat — ``horizon`` certifies the query
        unchanged through it. Raises ``KeyError`` for unknown/expired
        tokens (the wire layer maps this to ``gone``)."""
        if max_frames is None:
            max_frames = env_int("REFLOW_SUB_MAX_FRAMES")
        shard = self._shard(token)
        deadline = time.monotonic() + max(0.0, wait_s)
        with shard.lock:
            while True:
                sub = shard.subs.get(token)
                if sub is None:
                    raise KeyError(token)
                sub.last_seen = time.monotonic()
                if acked is not None and acked > sub.acked:
                    sub.acked = acked
                outbox = sub.outbox
                while outbox and not outbox[0].snapshot \
                        and outbox[0].to_h <= sub.acked:
                    outbox.popleft()
                # read the horizon before deciding "empty" (the pump
                # appends frames before advancing it, so an empty
                # outbox at this horizon proves changelessness)... but
                # a rebase-flagged subscriber's stream is broken (shed,
                # paused at level 2, or parked below min_horizon):
                # frames stopped flowing, so the fan-out horizon
                # certifies nothing for it — heartbeat -1, the client
                # holds its horizon until the snapshot lands.
                horizon = -1 if sub.rebase else self._fanout_h
                if outbox:
                    frames = []
                    while outbox and len(frames) < max_frames:
                        frames.append(outbox.popleft())
                    return frames, horizon
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return [], horizon
                shard.cond.wait(min(remaining, _POLL_S))

    # -- fan-out rounds ----------------------------------------------------

    def _crash_point(self, point: str) -> None:
        if self._crash is not None:
            self._crash.point(point)

    def _shard(self, token: str) -> _Shard:
        return self._shards[hash(token) % len(self._shards)]

    def _kick_round(self) -> None:
        with self._wq_lock:
            self._kick = True
            self._wq_cond.notify_all()

    def pump_once(self, wait_s: float = 0.0) -> int:
        """One fan-out round; returns frames appended. Tests call this
        directly (``start=False``) for deterministic rounds."""
        t0 = time.perf_counter()
        with self._wq_lock:
            deadline = time.monotonic() + max(0.0, wait_s)
            while not (self._wq or self._kick or self._rebase_all):
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self._stop.is_set():
                    break
                self._wq_cond.wait(min(remaining, _POLL_S))
            windows = list(self._wq)
            self._wq.clear()
            rebase_all = self._rebase_all
            self._rebase_all = False
            self._kick = False
            shed_level = self._shed_level
        self.rounds_total += 1
        # the seam sits at the most dangerous point: windows drained
        # from the queue but not yet folded into mirrors. Recovery is
        # rebase() — tests prove a crash here never corrupts a
        # subscriber, it only costs a snapshot.
        self._crash_point("sub_fanout")
        with self._reg:
            fans = [(fan.query, fan, set(fan.tokens))
                    for fan in self._fans.values()]
        sinks = {q.sink for q, _, _ in fans}
        for s in list(self._mirrors):
            if s not in sinks:
                del self._mirrors[s]
        if rebase_all:
            self._mirrors.clear()
            windows = []
            self._pending_causes.clear()
            self._flag_all_rebase()
            self.rebases_total += 1
        for w in windows:
            for c in (w[3] or ()):
                if c not in self._pending_causes:
                    self._pending_causes.append(c)
        for s in sinks:
            if s not in self._mirrors:
                h, view = self.replica.view_at(s)
                self._mirrors[s] = _Mirror(h, dict(view))
        round_deltas = self._advance_mirrors(windows)
        appended = 0
        rows_out = 0
        emitted_causes: Optional[tuple] = None
        if shed_level >= 2:
            # paused: mirrors advanced (correctness kept), nothing
            # emitted; every live subscriber owes a snapshot on resume.
            for _, fan, _ in fans:
                mirror = self._mirrors.get(fan.query.sink)
                if mirror is not None:
                    fan.last_emit_h = mirror.h
                    fan.last_topk = None
            self._flag_all_rebase()
        else:
            causes = tuple(self._pending_causes) or None
            delta_frames = 0
            for q, fan, tokens in fans:
                mirror = self._mirrors.get(q.sink)
                if mirror is None:
                    continue
                if fan.last_emit_h is None:
                    fan.last_emit_h = mirror.h
                    continue
                if mirror.h <= fan.last_emit_h:
                    continue
                rows = delta_rows(q, round_deltas.get(q.sink, {}),
                                  mirror.view, fan.last_topk)
                if rows is None:
                    continue
                frame = DeltaFrame(fan.last_emit_h, mirror.h, q.kind,
                                   rows, False, causes)
                if q.kind == "topk":
                    fan.last_topk = rows
                fan.last_emit_h = mirror.h
                delta_frames += 1
                n = self._fan_out(frame, tokens)
                appended += n
                rows_out += n * len(rows)
            if delta_frames:
                emitted_causes = causes
                self._pending_causes.clear()
                if windows:
                    emit_t = time.perf_counter()
                    for w in windows:
                        self._freshness.append(emit_t - w[4])
            appended += self._service_rebases()
        reaped = self._reap_expired()
        # order matters: frames land in outboxes (above) before the
        # horizon moves, so a poll that sees the new horizon also sees
        # every frame at or below it.
        if self._mirrors:
            self._fanout_h = min(m.h for m in self._mirrors.values())
        elif windows:
            self._fanout_h = max(self._fanout_h, windows[-1][1])
        for shard in self._shards:
            with shard.lock:
                shard.cond.notify_all()
        self.frames_total += appended
        self.fanout_rows_total += rows_out
        if _trace.ENABLED and emitted_causes:
            _trace.evt("sub_fanout", t0, time.perf_counter() - t0,
                       track=f"subs/{self.name}",
                       args={"frames": appended,
                             "causes": list(emitted_causes),
                             "horizon": self._fanout_h})
        if _trace.ENABLED and (appended or windows or reaped):
            _trace.evt("sub_push", t0, time.perf_counter() - t0,
                       track=f"subs/{self.name}",
                       args={"frames": appended, "windows": len(windows),
                             "fans": len(fans), "horizon": self._fanout_h,
                             "shed_level": shed_level})
        return appended

    def _advance_mirrors(self, windows) -> Dict[str, Dict]:
        """Fold queued windows into the per-sink mirrors; returns the
        per-sink delta accumulated over exactly the span each mirror
        advanced this round."""
        round_deltas: Dict[str, Dict] = {}
        for from_h, to_h, results, _causes, _recv in windows:
            for s, mirror in self._mirrors.items():
                if mirror.h >= to_h:
                    continue
                if mirror.h < from_h:
                    # continuity lost (shouldn't happen outside races
                    # with bootstrap) — heal via rebase next round.
                    self.rebase()
                    continue
                acc = round_deltas.setdefault(s, {})
                view = mirror.view
                while mirror.h < to_h:
                    batch = results[mirror.h - from_h].sink_deltas.get(s)
                    if batch is not None:
                        for k, v, w in batch.rows():
                            kv = (k, v)
                            nw = view.get(kv, 0) + w
                            if nw == 0:
                                view.pop(kv, None)
                            else:
                                view[kv] = nw
                            acc[kv] = acc.get(kv, 0) + w
                    mirror.h += 1
        return round_deltas

    def _fan_out(self, frame: DeltaFrame, tokens: set) -> int:
        by_shard: Dict[int, List[str]] = {}
        for token in tokens:
            by_shard.setdefault(hash(token) % len(self._shards),
                                []).append(token)
        appended = 0
        for idx, toks in by_shard.items():
            shard = self._shards[idx]
            with shard.lock:
                for token in toks:
                    sub = shard.subs.get(token)
                    if sub is None or sub.rebase:
                        continue
                    self._append(sub, frame)
                    appended += 1
        return appended

    def _append(self, sub: _Sub, frame: DeltaFrame) -> None:
        # caller holds the sub's shard lock
        sub.outbox.append(frame)
        overflow = len(sub.outbox) > self.outbox_max
        eager = self._shed_level >= 1 and len(sub.outbox) > 1
        if not (overflow or eager):
            return
        merged = merge_frames(list(sub.outbox))
        if len(merged.rows) > self.conflate_max_rows:
            sub.outbox.clear()
            sub.rebase = True
            sub.acked = -1
            self.sheds_total += 1
        else:
            sub.outbox.clear()
            sub.outbox.append(merged)
            self.conflations_total += 1

    def _service_rebases(self) -> int:
        """Deliver snapshot frames to rebase-flagged subscribers whose
        sink mirror has reached their ``min_horizon`` (parking)."""
        snap_cache: Dict[StandingQuery, DeltaFrame] = {}
        appended = 0
        for shard in self._shards:
            with shard.lock:
                for sub in shard.subs.values():
                    if not sub.rebase:
                        continue
                    mirror = self._mirrors.get(sub.query.sink)
                    if mirror is None or mirror.h < sub.min_horizon:
                        continue          # parked below min_horizon
                    frame = snap_cache.get(sub.query)
                    if frame is None:
                        frame = DeltaFrame(
                            -1, mirror.h, sub.query.kind,
                            snapshot_rows(sub.query, mirror.view), True)
                        snap_cache[sub.query] = frame
                    sub.outbox.clear()
                    sub.outbox.append(frame)
                    sub.rebase = False
                    sub.acked = -1
                    self.snapshots_total += 1
                    appended += 1
        return appended

    def _flag_all_rebase(self) -> None:
        for shard in self._shards:
            with shard.lock:
                for sub in shard.subs.values():
                    sub.rebase = True

    def _reap_expired(self) -> int:
        now = time.monotonic()
        reaped: List[str] = []
        for shard in self._shards:
            with shard.lock:
                for token, sub in list(shard.subs.items()):
                    if sub.expire_s is not None \
                            and now - sub.last_seen > sub.expire_s:
                        del shard.subs[token]
                        reaped.append(token)
                        shard.cond.notify_all()
        if reaped:
            with self._reg:
                for token in reaped:
                    for fan in list(self._fans.values()):
                        if token in fan.tokens:
                            fan.tokens.discard(token)
                            if not fan.tokens:
                                del self._fans[fan.query]
                            break
            self.reaped_total += len(reaped)
        return len(reaped)

    # -- shedding ----------------------------------------------------------

    @property
    def shed_level(self) -> int:
        return self._shed_level

    def set_shed_level(self, level: int) -> None:
        """0 = normal, 1 = conflate eagerly, 2 = pause emission."""
        level = max(0, min(2, int(level)))
        with self._wq_lock:
            self._shed_level = level
            self._kick = True
            self._wq_cond.notify_all()

    def load(self) -> Dict:
        """Control-plane view of fan-out pressure."""
        with self._wq_lock:
            backlog = len(self._wq)
        return {"active": self.active_subs(),
                "backlog_windows": backlog,
                "slowest_lag": self.slowest_lag(),
                "shed_level": self._shed_level,
                "horizon": self._fanout_h}

    def active_subs(self) -> int:
        return sum(len(s.subs) for s in self._shards)

    def slowest_lag(self) -> Optional[int]:
        """Fan-out horizon minus the slowest subscriber's acked cursor
        (in ticks); ``None`` with no measurable subscriber."""
        horizon = self._fanout_h
        worst = None
        for shard in self._shards:
            with shard.lock:
                for sub in shard.subs.values():
                    if sub.rebase or sub.acked < 0:
                        continue
                    lag = horizon - sub.acked
                    if worst is None or lag > worst:
                        worst = lag
        return max(worst, 0) if worst is not None else None

    @property
    def fanout_horizon(self) -> int:
        return self._fanout_h

    # -- lifecycle ---------------------------------------------------------

    @property
    def alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> None:
        """Start (or restart after a crash) the fan-out thread. A
        restart rebases: whatever the dead thread had in flight is
        replaced by fresh snapshots."""
        if self.alive:
            return
        restarted = self._thread is not None
        self._stop.clear()
        if restarted:
            self.rebase()
        self._thread = threading.Thread(target=self._run,
                                        name=f"subs-hub-{self.name}",
                                        daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.pump_once(wait_s=self._idle_poll_s)
            except CrashPoint as e:
                # simulated process death (the sub_fanout seam): record
                # and exit the loop — supervision notices ``not alive``
                # and restarts, which rebases. Recorded, not re-raised:
                # the fault model kills the *loop*, and an exception
                # escaping a thread is just noise on top of that.
                self.pump_error = e
                return
            except Exception:  # noqa: BLE001 - fan-out is advisory; a poisoned round must not kill push for every subscriber. Count and rebase.
                self.pump_errors += 1
                self.rebase()

    def close(self) -> None:
        self._stop.set()
        with self._wq_lock:
            self._wq_cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        for reg, base in self._metric_names:
            reg.unregister_prefix(base)
        self._metric_names = []

    # -- observability -----------------------------------------------------

    def publish_metrics(self, registry=None,
                        name: Optional[str] = None) -> None:
        reg = registry if registry is not None else REGISTRY
        base = name or "subs"
        reg.gauge(f"{base}.active", self.active_subs)
        reg.gauge(f"{base}.horizon", lambda: self._fanout_h)
        reg.gauge(f"{base}.backlog_windows", lambda: len(self._wq))
        reg.gauge(f"{base}.frames_total", lambda: self.frames_total)
        reg.gauge(f"{base}.fanout_rows_total",
                  lambda: self.fanout_rows_total)
        reg.gauge(f"{base}.conflations_total",
                  lambda: self.conflations_total)
        reg.gauge(f"{base}.sheds_total", lambda: self.sheds_total)
        reg.gauge(f"{base}.snapshots_total", lambda: self.snapshots_total)
        reg.gauge(f"{base}.slowest_lag",
                  lambda: self.slowest_lag() or 0)
        reg.gauge(f"{base}.shed_level", lambda: self._shed_level)
        reg.gauge(f"{base}.freshness_p50",
                  lambda: self.freshness_pct(0.50))
        reg.gauge(f"{base}.freshness_p99",
                  lambda: self.freshness_pct(0.99))
        self._metric_names.append((reg, base))

    def freshness_pct(self, q: float) -> float:
        """Percentile (seconds) of window-recv → frame-emit latency
        over the recent reservoir; 0.0 until the first emission."""
        snap = sorted(self._freshness)
        if not snap:
            return 0.0
        i = min(len(snap) - 1, int(q * (len(snap) - 1) + 0.5))
        return snap[i]
