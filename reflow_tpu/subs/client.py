"""Subscriber — the client half of reactive reads.

Mirrors the :class:`~reflow_tpu.serve.rpc.RemoteProducer` lifecycle
for the read direction: :class:`~reflow_tpu.net.backoff
.ReconnectPolicy` gates every re-dial, a down link never raises out of
:meth:`Subscriber.pump` (state simply stops advancing until the link
heals), and every fresh connection re-runs the ``("sub", ...)``
handshake carrying the local cursor — the server's hub then decides
*resume* (stream continues, provably gap-free and duplicate-free) or
*snapshot* (full rebase frame first). The client never needs more
resume state than one integer.

The duplicate/gap proof is mechanical: every received frame runs
through :class:`~reflow_tpu.subs.query.QueryState`'s contiguity rule,
so ``gaps_total`` / ``dups_applied`` on a live subscriber are the
test assertions, not log forensics. A detected gap (which the protocol
should never produce) triggers an automatic re-handshake so the stream
self-heals via snapshot rather than serving wrong values.
"""

from __future__ import annotations

import itertools
import os
import time
from typing import Optional, Sequence

from reflow_tpu.net.backoff import ReconnectPolicy
from reflow_tpu.net.framing import TransportError
from reflow_tpu.net.transport import Conn, Transport
from reflow_tpu.obs import trace as _trace
from reflow_tpu.subs.query import QueryState, canon_query, frames_from_wire
from reflow_tpu.subs.wire import SubAck, SubscribeReq
from reflow_tpu.utils.config import env_float
from reflow_tpu.utils.runtime import named_lock

__all__ = ["Subscriber"]

_POLL_S = 0.2
_SEQ = itertools.count()


class Subscriber:
    """One standing query tailed over the wire.

    Drive it with :meth:`pump` (one poll round-trip, long-polling up
    to ``wait_s`` server-side) or :meth:`wait_horizon`; read the
    reconstructed answer with :meth:`value` — it matches the pull path
    (`view_at`/`lookup`/`top_k`) exactly at :attr:`horizon`.
    """

    def __init__(self, transport: Transport, address, sink, *,
                 kind: str = "view", params: Sequence = (),
                 name: str = "subscriber", min_horizon: int = 0,
                 token: Optional[str] = None,
                 policy: Optional[ReconnectPolicy] = None,
                 io_timeout_s: Optional[float] = None) -> None:
        self.transport = transport
        self.address = address
        self.name = name
        self.query = canon_query(sink, kind, params)
        self.state = QueryState(self.query)
        self.min_horizon = min_horizon
        self.token = token if token is not None \
            else f"{name}-{os.getpid()}-{next(_SEQ)}"
        self.policy = policy if policy is not None \
            else ReconnectPolicy(name)
        self.io_timeout_s = (io_timeout_s if io_timeout_s is not None
                             else env_float("REFLOW_SUB_IO_TIMEOUT_S"))
        self._lock = named_lock("subs.client")
        self._conn: Optional[Conn] = None
        #: server's answer to the last handshake
        self.last_ack: Optional[SubAck] = None
        self.mode: Optional[str] = None
        #: server clock anchor from the last handshake (rtt_s /
        #: wall_offset_s added client-side) — post-mortem alignment
        self.anchor: Optional[dict] = None
        self.polls_total = 0
        self.heartbeats_total = 0
        self.handshakes_total = 0
        self.reconnects_total = 0
        self.link_failures = 0

    # -- read surface ----------------------------------------------------

    @property
    def horizon(self) -> int:
        return self.state.horizon

    def value(self):
        return self.state.value()

    @property
    def gaps_total(self) -> int:
        return self.state.gaps

    @property
    def dups_skipped_total(self) -> int:
        return self.state.dups_skipped

    @property
    def frames_applied_total(self) -> int:
        return self.state.applied

    @property
    def rebases_total(self) -> int:
        return self.state.rebases

    @property
    def conn_state(self) -> str:
        return self.policy.state

    # -- driving ---------------------------------------------------------

    def pump(self, wait_s: float = 0.0) -> int:
        """One pump: (re)dial + handshake if needed, then one poll.
        Returns frames that advanced state; 0 while the link is down
        (never raises for link trouble)."""
        deadline = time.perf_counter() + max(0.0, wait_s)
        while True:
            applied = None
            with self._lock:
                if self._ensure_link():
                    left = max(0.0, deadline - time.perf_counter())
                    applied = self._poll_once(left)
            if applied is not None:
                return applied
            remaining = deadline - time.perf_counter()
            if remaining <= 0:
                return 0
            nap = max(self.policy.seconds_until_due(), 0.01)
            time.sleep(min(nap, remaining, _POLL_S))

    def wait_horizon(self, horizon: int, timeout_s: float = 10.0) -> bool:
        """Pump until the reconstructed view reaches ``horizon``."""
        deadline = time.perf_counter() + timeout_s
        while self.state.horizon < horizon:
            remaining = deadline - time.perf_counter()
            if remaining <= 0:
                return False
            self.pump(wait_s=min(remaining, _POLL_S))
        return True

    def retarget(self, address) -> None:
        """Point at a different endpoint (e.g. another replica). The
        cursor rides the next handshake, so the stream resumes or
        rebases there by the same rules."""
        with self._lock:
            self.address = address
            if self._conn is not None:
                self._conn.close()
                self._conn = None
            self.policy.failed()

    def close(self) -> None:
        with self._lock:
            conn, self._conn = self._conn, None
            if conn is not None:
                try:
                    conn.send_msg(("sub_close", self.token),
                                  self.io_timeout_s)
                    conn.recv_msg(self.io_timeout_s)
                except TransportError:
                    pass  # best-effort: the hub reaps expired tokens
                conn.close()

    # -- link machinery --------------------------------------------------

    def _fail(self, err: Exception) -> None:
        if self._conn is not None:
            self._conn.close()
            self._conn = None
        self.link_failures += 1
        self.policy.failed()

    def _sub_req(self) -> SubscribeReq:
        return SubscribeReq(self.query.sink, self.query.kind,
                            self.query.params,
                            cursor=self.state.horizon,
                            min_horizon=self.min_horizon,
                            token=self.token)

    def _ensure_link(self) -> bool:
        """Dial + subscribe handshake if down and backoff allows.
        Caller holds the lock. True if live."""
        if self._conn is not None:
            return True
        if not self.policy.due():
            return False
        t0 = time.perf_counter()
        try:
            conn = self.transport.connect(self.address)
            conn.send_msg(("sub",) + tuple(self._sub_req()),
                          self.io_timeout_s)
            resp = conn.recv_msg(self.io_timeout_s)
        except TransportError as e:
            self._fail(e)
            if _trace.ENABLED:
                _trace.evt("net_reconnect", t0,
                           time.perf_counter() - t0,
                           track=f"subs/{self.name}",
                           args={"ok": False, "error": str(e)[:120],
                                 "state": self.policy.state})
            return False
        if not (isinstance(resp, tuple) and len(resp) >= 4
                and resp[0] == "ok"):
            conn.close()
            self._fail(TransportError(f"bad sub response {resp!r}"))
            return False
        recovered = self.policy.ok()
        if recovered:
            self.reconnects_total += 1
        self._conn = conn
        self._accept_ack(resp, rtt=time.perf_counter() - t0)
        if _trace.ENABLED:
            _trace.evt("net_reconnect", t0, time.perf_counter() - t0,
                       track=f"subs/{self.name}",
                       args={"ok": True, "recovered": recovered,
                             "mode": self.mode,
                             "cursor": self.state.horizon})
        return True

    def _roundtrip(self, msg: tuple):
        conn = self._conn
        if conn is None:
            return None
        try:
            conn.send_msg(msg, self.io_timeout_s)
            return conn.recv_msg(self.io_timeout_s)
        except TransportError as e:
            self._fail(e)
            return None

    def _rehandshake(self) -> bool:
        """Re-run the subscribe op on the live connection (after a
        ``gone`` or a detected gap). Caller holds the lock."""
        t0 = time.perf_counter()
        resp = self._roundtrip(("sub",) + tuple(self._sub_req()))
        if not (isinstance(resp, tuple) and len(resp) >= 4
                and resp[0] == "ok"):
            if self._conn is not None:
                self._fail(TransportError(f"bad sub response {resp!r}"))
            return False
        self._accept_ack(resp, rtt=time.perf_counter() - t0)
        return True

    def _accept_ack(self, resp: tuple, rtt: Optional[float] = None) -> None:
        """Record a successful handshake reply; parses the trailing
        clock anchor when the server sends one (older servers reply
        without it — both directions stay compatible)."""
        self.last_ack = SubAck(*resp[1:4])
        self.mode = self.last_ack.mode
        self.handshakes_total += 1
        if len(resp) >= 5 and isinstance(resp[4], dict):
            anchor = dict(resp[4])
            if rtt is not None:
                anchor["rtt_s"] = rtt
                anchor["wall_offset_s"] = anchor.get("wall", 0.0) - (
                    time.time() - rtt / 2.0)
            self.anchor = anchor

    def _poll_once(self, wait_s: float) -> Optional[int]:
        """One poll round-trip. Caller holds the lock. None on link
        failure (caller backs off), else frames applied."""
        # the server also caps; staying under the io timeout keeps the
        # long poll from looking like a dead link
        wait = min(wait_s, max(self.io_timeout_s / 2.0, 0.0))
        self.polls_total += 1
        resp = self._roundtrip(
            ("sub_poll", self.token, self.state.horizon, wait))
        if resp is None:
            return None
        if isinstance(resp, tuple) and resp and resp[0] == "gone":
            # expired while we were away (or the replica restarted):
            # re-register; the cursor decides resume-vs-snapshot
            self._rehandshake()
            return 0
        if not (isinstance(resp, tuple) and len(resp) == 3
                and resp[0] == "ok"):
            return 0
        frames = frames_from_wire(resp[1])
        horizon = resp[2]
        gaps_before = self.state.gaps
        applied = 0
        for frame in frames:
            t_apply = time.perf_counter()
            ok = self.state.apply(frame)
            if ok:
                applied += 1
            if _trace.ENABLED and ok and getattr(frame, "cause", None):
                # the last link of the write's chain: a sampled write
                # is now visible in this subscriber's local answer.
                _trace.evt("sub_deliver", t_apply,
                           time.perf_counter() - t_apply,
                           track=f"subs/{self.name}",
                           args={"from_h": frame.from_h,
                                 "to_h": frame.to_h,
                                 "causes": list(frame.cause)})
        self.state.note_horizon(horizon)
        if not frames:
            self.heartbeats_total += 1
        if self.state.gaps > gaps_before:
            # protocol violation (or a server that lost our outbox
            # without noticing): self-heal via snapshot rather than
            # serve values we can't prove. Drop the server-side sub
            # first so the cursor rules — not the suspect outbox —
            # decide what comes next.
            self._roundtrip(("sub_close", self.token))
            self._rehandshake()
        return applied
