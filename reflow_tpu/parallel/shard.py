"""ShardedTpuExecutor: the tick pass as an explicit SPMD program.

SURVEY.md §7.8 / north star: delta buffers row-sharded over the mesh, keyed
state tables key-range-sharded, cross-shard combines as explicit
collectives (``psum_scatter`` in Reduce, ``all_gather`` key-routing in
Join) under ``jax.shard_map``. Composes with the on-device fixpoint
unchanged: ``build_pass_fn`` keeps the global ``(states, ingress) ->
(states', egress)`` signature, so ``FixpointProgram`` wraps the shard_map'd
pass in its ``lax.while_loop`` exactly like the single-device one.

Divisibility contract (validated at bind): the mesh size must be a power
of two no larger than the minimum delta capacity (so every bucketed delta
capacity is a multiple of it), and every keyed op's ``key_space`` and
every Join's ``arena_capacity`` must be multiples of the mesh size.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from reflow_tpu.executors.device_delta import MIN_CAPACITY, DeviceDelta
from reflow_tpu.executors.tpu import TpuExecutor
from reflow_tpu.graph import FlowGraph, GraphError, Node
from reflow_tpu.parallel.mesh import make_mesh, shard_state_tree
from reflow_tpu.parallel.shard_lowerings import lower_node_sharded

__all__ = ["ShardedTpuExecutor"]


class ShardedTpuExecutor(TpuExecutor):
    name = "sharded"

    def __init__(self, mesh: Optional[Mesh] = None, *, fixpoint: bool = True):
        super().__init__(fixpoint=fixpoint)
        self.mesh = mesh if mesh is not None else make_mesh()
        self.axis = self.mesh.axis_names[0]
        self.n = self.mesh.shape[self.axis]
        if self.n & (self.n - 1) or self.n > MIN_CAPACITY:
            raise GraphError(
                f"mesh size {self.n} must be a power of two <= "
                f"{MIN_CAPACITY} so bucketed delta capacities shard evenly")
        self._arena_divisor = self.n

    # -- bind: divisibility validation + sharded state placement -----------

    def bind(self, graph: FlowGraph) -> None:
        super().bind(graph)
        n = self.n
        for node in graph.nodes:
            if node.kind == "op" and node.op.kind == "knn":
                raise GraphError(
                    f"{node}: knn has no sharded lowering yet; run it on "
                    f"the single-device TpuExecutor")
            if node.kind != "op" or node.op.kind not in ("reduce", "join"):
                continue
            K = node.inputs[0].spec.key_space
            if K % n:
                raise GraphError(
                    f"{node}: key_space {K} must be a multiple of the mesh "
                    f"size {n} (round it up)")
            if node.op.kind == "reduce":
                from reflow_tpu.executors.lowerings import \
                    LINEAR_DEVICE_REDUCERS

                if node.op.how not in LINEAR_DEVICE_REDUCERS:
                    raise GraphError(
                        f"{node}: {node.op.how} has no sharded lowering "
                        f"yet; use the single-device TpuExecutor or the "
                        f"CPU oracle")
                # sparse-route overflow is surfaced through the same sticky
                # per-node error scalar min/max use (ADVICE r2 high: without
                # this key the route_rows overflow flag would be dropped)
                self.states[node.id]["error"] = jnp.zeros((), jnp.bool_)
            if node.op.kind == "join":
                if node.op.arena_capacity % n:
                    raise GraphError(
                        f"{node}: arena_capacity {node.op.arena_capacity} "
                        f"must be a multiple of the mesh size {n}")
                # per-shard append counters (one scalar per mesh slot)
                self.states[node.id]["rcount"] = jnp.zeros((n,), jnp.int32)
        self.states = shard_state_tree(self.states, self.mesh,
                                       axis_name=self.axis)

    def _state_spec(self, x) -> P:
        if getattr(x, "ndim", 0) >= 1 and x.shape[0] % self.n == 0:
            return P(self.axis)
        return P()

    def _gc_fn(self):
        """Per-shard arena compaction under shard_map: rows never migrate
        between shards; each shard repacks its slice and its slot of the
        rcount vector."""
        import jax

        from reflow_tpu.executors.arena import compact_arena

        fn = self._cache.get("gc")
        if fn is None:
            def sharded_gc(state):
                specs = jax.tree.map(self._state_spec, state)
                return jax.shard_map(compact_arena, mesh=self.mesh,
                                     in_specs=(specs,), out_specs=specs,
                                     check_vma=False)(state)
            fn = sharded_gc
            self._cache["gc"] = fn
        return fn

    # -- the SPMD pass program ---------------------------------------------

    def _lower(self, node: Node, state, ins):
        return lower_node_sharded(node, state, ins, self.axis, self.n)

    def build_pass_fn(self, plan: List[Node]):
        graph = self.graph
        mesh, axis = self.mesh, self.axis
        # the shared traversal from TpuExecutor (with this class's _lower
        # hook) becomes the per-shard body under shard_map
        local_pass = super().build_pass_fn(plan)
        sink_inputs = [(s.inputs[0].id, s.id) for s in graph.sinks]
        back_edges = [(l.back_input.id, l.id) for l in graph.loops
                      if l.back_input is not None]
        dspec = DeviceDelta(P(axis), P(axis), P(axis))

        def _egress_ids(ingress_ids):
            # mirror of the traversal's reachability, capacities aside
            outs = set(ingress_ids)
            for node in plan:
                if (node.id in outs or
                        node.kind in ("source", "loop", "sink")):
                    continue
                if any(i.id in outs for i in node.inputs):
                    outs.add(node.id)
            eg = [sid for src, sid in sink_inputs if src in outs]
            eg += [lid for bid, lid in back_edges if bid in outs]
            return eg

        def pass_fn(states, ingress):
            # ingress structure is static at trace time: derive the
            # shard_map partitioning specs for exactly this signature
            state_specs = jax.tree.map(self._state_spec, states)
            in_specs = (state_specs, {nid: dspec for nid in ingress})
            out_specs = (state_specs, {eid: dspec
                                       for eid in _egress_ids(ingress)})
            fn = jax.shard_map(local_pass, mesh=mesh, in_specs=in_specs,
                               out_specs=out_specs, check_vma=False)
            return fn(states, ingress)

        return pass_fn
