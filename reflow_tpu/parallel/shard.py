"""ShardedTpuExecutor: the tick pass as an explicit SPMD program.

SURVEY.md §7.8 / north star: delta buffers row-sharded over the mesh, keyed
state tables key-range-sharded, cross-shard combines as explicit
collectives (``psum_scatter``/``all_to_all`` row routing in Reduce and
Join, ``pmax`` extrema combine in min/max, ``all_gather`` candidate merge
in k-NN) under ``jax.shard_map``. Composes with the on-device fixpoint
unchanged: ``build_pass_fn`` keeps the global ``(states, ingress) ->
(states', egress)`` signature, so ``FixpointProgram`` wraps the shard_map'd
pass in its ``lax.while_loop`` exactly like the single-device one — and
the fused linear fixpoint runs its whole loop inside one shard_map region
(linear_fixpoint.py).

Divisibility contract (validated at bind): the mesh size must be a power
of two no larger than the minimum delta capacity (so every bucketed delta
capacity is a multiple of it), and every keyed op's ``key_space`` and
every Join's ``arena_capacity`` must be multiples of the mesh size.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from reflow_tpu.executors.device_delta import MIN_CAPACITY, DeviceDelta
from reflow_tpu.executors.tpu import TpuExecutor
from reflow_tpu.graph import FlowGraph, GraphError, Node
from reflow_tpu.parallel.mesh import make_mesh, replicate
from reflow_tpu.parallel.shard_lowerings import lower_node_sharded

__all__ = ["ShardedTpuExecutor", "shard_map"]


def _resolve_shard_map():
    """Version-tolerant ``shard_map``: newer jax exposes ``jax.shard_map``
    (replication check kwarg ``check_vma``); the pinned older releases
    only have ``jax.experimental.shard_map.shard_map`` (kwarg
    ``check_rep``). Resolve whichever exists and normalize the kwarg so
    every call site can use the modern spelling."""
    import inspect

    fn = getattr(jax, "shard_map", None)
    if fn is None:
        from jax.experimental.shard_map import shard_map as fn
    check_kw = ("check_vma" if "check_vma" in inspect.signature(fn).parameters
                else "check_rep")

    def _shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        return fn(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  **{check_kw: check_vma})

    return _shard_map


shard_map = _resolve_shard_map()


class ShardedTpuExecutor(TpuExecutor):
    name = "sharded"

    def __init__(self, mesh: Optional[Mesh] = None, *, fixpoint: bool = True,
                 model_axis: Optional[str] = None):
        super().__init__(fixpoint=fixpoint)
        self.mesh = mesh if mesh is not None else make_mesh()
        #: tensor-parallel axis (VERDICT r4 #8): delta rows and keyed
        #: state shard over the remaining (data) axes and REPLICATE over
        #: this one; Map params with ``param_specs`` shard over it, and
        #: the map fn runs its own model-axis collectives
        #: (models.vit.vit_forward_tp). None = every mesh axis is data.
        self.model_axis = model_axis
        names = self.mesh.axis_names
        if model_axis is not None:
            if model_axis not in names:
                raise GraphError(
                    f"model_axis {model_axis!r} not in mesh axes {names}")
            names = tuple(a for a in names if a != model_axis)
            if not names:
                raise GraphError("a pure-model mesh has no data axis; "
                                 "add a delta axis")
        #: a 2-axis (dcn, ici) data mesh shards over the flattened
        #: PRODUCT axis (dcn-major — jax.lax.axis_index's flat order):
        #: key ranges span all chips, intra-slice legs of the
        #: collectives ride ICI, only the cross-slice legs cross DCN.
        #: Every collective this executor emits accepts the tuple form.
        self.axis = names[0] if len(names) == 1 else tuple(names)
        import numpy as _np
        self.n = int(_np.prod([self.mesh.shape[a] for a in names]))
        #: per-axis extents for 2-axis data meshes (the hierarchical
        #: router needs static (n_dcn, n_ici)); None on 1-axis meshes
        self._axis_sizes = (tuple(self.mesh.shape[a] for a in names)
                            if len(names) > 1 else None)
        if self.n & (self.n - 1) or self.n > MIN_CAPACITY:
            raise GraphError(
                f"mesh size {self.n} must be a power of two <= "
                f"{MIN_CAPACITY} so bucketed delta capacities shard evenly")
        self._arena_divisor = self.n

    #: sharded pass programs close over this executor's mesh/axis (via
    #: ``_lower`` and ``_state_tree_specs``), so the process-wide
    #: window-program share would cross-wire meshes — per-executor only
    _share_window_programs = False

    def place(self, device) -> None:
        """A sharded executor spans the whole mesh — it cannot be pinned
        to one device. Use a plain TpuExecutor for tenant placement, or
        the sharded path for one hot tenant across the mesh."""
        raise GraphError(
            "ShardedTpuExecutor spans the device mesh and cannot be "
            "placed on a single device; use TpuExecutor with "
            "GraphConfig(device=...) / placement='spread' instead")

    @property
    def device_label(self) -> str:
        return f"mesh[{self.n}]"

    def _ingress_placement(self):
        # queue buffers / stacked feeds shard their capacity axis over
        # the mesh so slot writes and padding land shard-local and the
        # window program dispatches SPMD
        return (self.mesh, self.axis)

    # -- bind: divisibility validation + sharded state placement -----------

    def bind(self, graph: FlowGraph) -> None:
        super().bind(graph)
        n = self.n
        #: node ids whose state is mesh-REPLICATED (Map params: every
        #: shard runs the full model on its delta slice — data parallel),
        #: vs the default key/row sharding of table/arena states
        self._replicated_ids = {
            node.id for node in graph.nodes
            if node.kind == "op" and node.op.kind == "map"
            and node.op.params is not None}
        self._knn_ids = set()
        for node in graph.nodes:
            if node.kind == "op" and node.op.kind == "knn":
                if isinstance(self.axis, tuple):
                    raise GraphError(
                        f"{node}: sharded k-NN's ring merge (ppermute) "
                        f"needs a 1-axis mesh; run knn graphs on the ICI "
                        f"mesh (make_mesh() without dcn=)")
                Q = node.inputs[0].spec.key_space
                D = node.inputs[1].spec.key_space
                if Q % n or D % n:
                    raise GraphError(
                        f"{node}: query space {Q} and corpus space {D} "
                        f"must be multiples of the mesh size {n}")
                if (D // n) % min(node.op.scan_chunk, D // n):
                    raise GraphError(
                        f"{node}: per-shard corpus {D // n} must be a "
                        f"multiple of scan_chunk {node.op.scan_chunk}")
                self._knn_ids.add(node.id)
                continue
            if node.kind != "op" or node.op.kind not in ("reduce", "join"):
                continue
            K = node.inputs[0].spec.key_space
            if K % n:
                raise GraphError(
                    f"{node}: key_space {K} must be a multiple of the mesh "
                    f"size {n} (round it up)")
            if node.op.kind == "reduce":
                from reflow_tpu.executors.lowerings import \
                    LINEAR_DEVICE_REDUCERS

                if node.op.how in LINEAR_DEVICE_REDUCERS:
                    # sparse-route overflow is surfaced through the same
                    # sticky per-node error scalar min/max use (ADVICE r2
                    # high: without this key the route_rows overflow flag
                    # would be dropped)
                    self.states[node.id]["error"] = jnp.zeros((), jnp.bool_)
                # min/max states (agg/wcnt/emitted tables) key-shard like
                # the linear ones; their error scalar ships in reduce_state
            if node.op.kind == "join":
                if node.op.arena_capacity % n:
                    raise GraphError(
                        f"{node}: arena_capacity {node.op.arena_capacity} "
                        f"must be a multiple of the mesh size {n}")
                # per-shard append counters and arena generations (one
                # scalar per mesh slot) + the sticky route-overflow flag
                # (large meshes route both delta sides to key owners via
                # all_to_all)
                self.states[node.id]["rcount"] = jnp.zeros((n,), jnp.int32)
                self.states[node.id]["gen"] = jnp.zeros((n,), jnp.int32)
                self.states[node.id]["error"] = jnp.zeros((), jnp.bool_)
                if "lkeys" in self.states[node.id]:
                    La = node.op.left_arena_capacity or node.op.arena_capacity
                    if La % n:
                        raise GraphError(
                            f"{node}: left_arena_capacity {La} must be a "
                            f"multiple of the mesh size {n}")
                    self.states[node.id]["lcount"] = jnp.zeros((n,),
                                                               jnp.int32)
                    self.states[node.id]["lgen"] = jnp.zeros((n,),
                                                             jnp.int32)
        # placement derives from the SAME per-leaf specs shard_map uses
        # (one source of truth: _state_tree_specs), so the bound layout
        # can never disagree with the pass programs' in_specs
        from jax.sharding import NamedSharding

        specs = self._state_tree_specs(self.states)
        self.states = {
            nid: jax.tree.map(
                lambda x, s: jax.device_put(x, NamedSharding(self.mesh, s)),
                st, specs[nid])
            for nid, st in self.states.items()}

    def _state_spec(self, x) -> P:
        if getattr(x, "ndim", 0) >= 1 and x.shape[0] % self.n == 0:
            return P(self.axis)
        return P()

    def _state_tree_specs(self, states):
        """Per-node shard_map partition specs: replicated nodes (Map
        params) get P() on every leaf regardless of divisibility — a
        weight matrix whose dim 0 happens to divide the mesh must NOT be
        row-sharded — and knn states use their per-leaf layout."""
        from reflow_tpu.parallel.shard_lowerings import knn_state_specs

        repl = getattr(self, "_replicated_ids", frozenset())
        knn_ids = getattr(self, "_knn_ids", frozenset())
        knn_axes = knn_state_specs(self.axis)

        pspec_ids = {
            node.id: node.op.param_specs for node in self.graph.nodes
            if node.kind == "op" and node.op.kind == "map"
            and node.op.param_specs is not None
        } if getattr(self, "graph", None) is not None else {}

        def specs(nid, st):
            if nid in pspec_ids:
                # tensor-parallel Map: params shard per the op's declared
                # specs (typically over the model axis)
                return {"params": pspec_ids[nid]}
            if nid in repl:
                return jax.tree.map(lambda _: P(), st)
            if nid in knn_ids:
                return {k: P(knn_axes[k]) if knn_axes[k] else P()
                        for k in st}
            return jax.tree.map(self._state_spec, st)

        return {nid: specs(nid, st) for nid, st in states.items()}

    def update_params(self, node: Node, params) -> None:
        super().update_params(node, params)
        if node.op.param_specs is not None:
            from jax.sharding import NamedSharding

            specs = self._state_tree_specs(
                {node.id: self.states[node.id]})[node.id]
            self.states[node.id] = jax.tree.map(
                lambda x, s: jax.device_put(x, NamedSharding(self.mesh, s)),
                self.states[node.id], specs)
        else:
            self.states[node.id] = replicate(self.states[node.id], self.mesh)

    def refresh_minmax(self, node: Node, batch) -> None:
        """Sharded latch refresh: replay rows reach their key's owner
        (the min/max comm policy), then the shared refresh kernel runs
        per shard on the owned key slice."""
        from reflow_tpu.executors.device_delta import to_device
        from reflow_tpu.executors.lowerings import minmax_refresh_core
        from reflow_tpu.parallel.shard_lowerings import deliver_to_owner

        d = to_device(batch, node.inputs[0].spec)
        K = node.inputs[0].spec.key_space
        n, axis, mesh = self.n, self.axis, self.mesh
        sig = ("mmrefresh", node.id, d.capacity)
        fn = self._cache.get(sig)
        if fn is None:
            op = node.op
            oshape, odt = tuple(node.spec.value_shape), node.spec.value_dtype
            Kl = K // n

            sizes = self._axis_sizes

            def body(st, dd):
                import jax.numpy as jnp
                base = (jax.lax.axis_index(axis) * Kl).astype(jnp.int32)
                dl, route_err = deliver_to_owner(dd, axis, n, Kl,
                                                 sizes=sizes)
                err = st["error"] | route_err
                st2 = minmax_refresh_core(op, Kl, oshape, odt,
                                          {**st, "error": err}, dl,
                                          key_offset=base)
                st2["error"] = (jax.lax.pmax(
                    st2["error"].astype(jnp.int32), axis) > 0)
                return st2

            from jax.sharding import PartitionSpec as P2

            sspec = self._state_tree_specs(
                {node.id: self.states[node.id]})[node.id]
            dspec = DeviceDelta(P2(axis), P2(axis), P2(axis))
            fn = self._cache[sig] = jax.jit(shard_map(
                body, mesh=mesh, in_specs=(sspec, dspec),
                out_specs=sspec, check_vma=False), donate_argnums=0)
        self.states[node.id] = fn(self.states[node.id], d)

    # -- the SPMD pass program ---------------------------------------------

    def _lower(self, node: Node, state, ins):
        return lower_node_sharded(node, state, ins, self.axis, self.n,
                                  sizes=self._axis_sizes)

    def build_pass_fn(self, plan: List[Node], extra_egress=()):
        graph = self.graph
        mesh, axis = self.mesh, self.axis
        # the shared traversal from TpuExecutor (with this class's _lower
        # hook) becomes the per-shard body under shard_map
        local_pass = super().build_pass_fn(plan, extra_egress)
        sink_inputs = [(s.inputs[0].id, s.id) for s in graph.sinks]
        back_edges = [(l.back_input.id, l.id) for l in graph.loops
                      if l.back_input is not None]
        extra = tuple(extra_egress)
        dspec = DeviceDelta(P(axis), P(axis), P(axis))

        def _egress_ids(ingress_ids):
            # mirror of the traversal's reachability, capacities aside
            outs = set(ingress_ids)
            for node in plan:
                if (node.id in outs or
                        node.kind in ("source", "loop", "sink")):
                    continue
                if any(i.id in outs for i in node.inputs):
                    outs.add(node.id)
            eg = [sid for src, sid in sink_inputs if src in outs]
            eg += [lid for bid, lid in back_edges if bid in outs]
            eg += [nid for nid in extra if nid in outs]
            return eg

        def pass_fn(states, ingress):
            # ingress structure is static at trace time: derive the
            # shard_map partitioning specs for exactly this signature
            state_specs = self._state_tree_specs(states)
            in_specs = (state_specs, {nid: dspec for nid in ingress})
            out_specs = (state_specs, {eid: dspec
                                       for eid in _egress_ids(ingress)})
            fn = shard_map(local_pass, mesh=mesh, in_specs=in_specs,
                           out_specs=out_specs, check_vma=False)
            return fn(states, ingress)

        return pass_fn
