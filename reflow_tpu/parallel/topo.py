"""Topo-partitioned execution: FlowGraph stages on separate devices.

SURVEY.md §2 parallelism checklist — "graph topo-partitioning across
chips" (the pipeline-parallel analog over the *dataflow graph*, not model
layers). ``Node.stage`` assigns each operator to a contiguous topological
stage; the :class:`StagedTpuExecutor` compiles ONE pass program per stage,
pins each stage's operator state to its own device, and hands
stage-boundary deltas to the next stage's device with an explicit
``jax.device_put`` (the ICI hop).

Pipelining falls out of XLA's async dispatch: each stage program runs on
a different device, so once tick ``t``'s stage 0 has been dispatched the
host immediately dispatches stage 1 while stage 0 of tick ``t+1`` can
start — the classic 1F pipeline schedule without any bespoke scheduler
(the host is the pipeline driver; device queues are the pipeline).

**Measured bound (round 5, tools/staged_pipeline_probe.py):** the
overlap requires the runtime to execute different devices' programs
CONCURRENTLY. The 8-virtual-device CPU mesh does not (raw two-device
probe: 2.3x one-program wall — fully serial; a single program already
owns the host's intra-op pool), so staged-vs-single measures 0.95-1.04x
there — parity, with the ``device_put`` handoffs costing nothing
measurable (bounded by ``tests/test_topo.py::test_staged_overhead``).
On real distinct chips the dispatch schedule above overlaps by
construction, but this environment exposes ONE chip. Until multi-chip
hardware is attached, the staged executor's measured value is
state-capacity partitioning (each stage's arenas/tables on its own
device's HBM) at bounded overhead — not throughput.

Validation (at bind): every DAG edge must be stage-monotone
(``stage(src) <= stage(dst)``), and a loop's entire cyclic region must
live inside one stage (pipelining across a fixpoint is not meaningful).
Unassigned nodes inherit stage 0; sources/loops take the minimum stage of
their consumers, sinks the stage of their producer.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax

from reflow_tpu.delta import DeltaBatch
from reflow_tpu.executors.device_delta import DeviceDelta, to_device
from reflow_tpu.executors.tpu import TpuExecutor
from reflow_tpu.graph import FlowGraph, GraphError, Node

__all__ = ["StagedTpuExecutor"]


class StagedTpuExecutor(TpuExecutor):
    name = "staged"

    def __init__(self, devices: Optional[Sequence] = None):
        # the on-device fixpoint fuses a whole tick into one program on
        # one device — incompatible with cross-device staging, so staged
        # graphs with loops use the scheduler's host-driven loop (the
        # loop's region still runs on its stage's device each pass)
        super().__init__(fixpoint=False, linear_fixpoint=False)
        self._devices = list(devices) if devices is not None else None

    # -- bind: stage assignment, validation, per-stage state placement ----

    def bind(self, graph: FlowGraph) -> None:
        super().bind(graph)
        stage_of: Dict[int, int] = {}
        for node in graph.nodes:
            if node.kind == "op":
                stage_of[node.id] = node.stage if node.stage is not None else 0
        # sources/loops ride with their first consumer; sinks with their
        # producer; isolated nodes default to stage 0
        for node in graph.nodes:
            if node.kind in ("source", "loop"):
                cons = [stage_of.get(c.id, 0)
                        for c, _ in graph.consumers(node)]
                stage_of[node.id] = min(cons) if cons else 0
            elif node.kind == "sink":
                stage_of[node.id] = stage_of.get(node.inputs[0].id, 0)
        for node in graph.nodes:
            for inp in node.inputs:
                if stage_of[inp.id] > stage_of[node.id]:
                    raise GraphError(
                        f"edge {inp} -> {node} goes backwards in stages "
                        f"({stage_of[inp.id]} -> {stage_of[node.id]}); "
                        f"stages must be monotone along dataflow edges")
        # each loop's OWN cyclic region must live inside one stage
        # (independent loops may live in different stages)
        for loop in graph.loops:
            if loop.back_input is None:
                continue
            fwd = {loop.id}
            changed = True
            while changed:
                changed = False
                for nd in graph.nodes:
                    if nd.id not in fwd and any(i.id in fwd
                                                for i in nd.inputs):
                        fwd.add(nd.id)
                        changed = True
            back = {loop.back_input.id}
            changed = True
            while changed:
                changed = False
                for nd in graph.nodes:
                    if nd.id in back:
                        for i in nd.inputs:
                            if i.id not in back:
                                back.add(i.id)
                                changed = True
            region = (fwd & back) | {loop.id}
            stages = {stage_of[nid] for nid in region}
            if len(stages) > 1:
                raise GraphError(
                    f"{loop}'s cyclic region spans stages {sorted(stages)}; "
                    f"a fixpoint region must live inside one stage")
        self._stage_of = stage_of
        self._stage_list = sorted(set(stage_of.values()))

        devs = self._devices if self._devices is not None else jax.devices()
        self._dev = {s: devs[i % len(devs)]
                     for i, s in enumerate(self._stage_list)}

        # pin each op's state to its stage's device
        for nid, st in self.states.items():
            dev = self._dev[stage_of[nid]]
            self.states[nid] = jax.device_put(st, dev)

        # per-stage boundary egress: nodes with a consumer in a LATER
        # stage must be returned by their stage's program
        self._boundary_of: Dict[int, List[int]] = {s: [] for s in
                                                   self._stage_list}
        for node in graph.nodes:
            if node.kind == "sink":
                continue
            s = stage_of[node.id]
            if any(stage_of[c.id] > s for c, _ in graph.consumers(node)):
                self._boundary_of[s].append(node.id)

    # -- the staged pass ---------------------------------------------------

    def run_pass(self, plan: Sequence[Node],
                 ingress: Dict[int, DeltaBatch]) -> Dict[int, object]:
        stage_of = self._stage_of
        dev_ingress: Dict[int, DeviceDelta] = {}
        for nid, b in ingress.items():
            d = (b if isinstance(b, DeviceDelta)
                 else to_device(b, self.graph.nodes[nid].spec))
            # uploads land directly on the consuming stage's device
            dev_ingress[nid] = jax.device_put(d, self._dev[stage_of[nid]])

        self._track_arena(plan, {nid: d.capacity
                                 for nid, d in dev_ingress.items()})

        outs: Dict[int, DeviceDelta] = dict(dev_ingress)
        egress: Dict[int, object] = {}
        sink_inputs = {s.inputs[0].id: s.id for s in self.graph.sinks}
        back_edges = {l.back_input.id: l.id for l in self.graph.loops
                      if l.back_input is not None}
        for s in self._stage_list:
            sub = [n for n in plan if stage_of[n.id] == s]
            if not sub:
                continue
            # seeds: anything already computed (external ingress or an
            # earlier stage's boundary egress) that this stage consumes
            # or that seeds one of its nodes — moved to this stage's
            # device (the pipeline handoff)
            need = {i.id for n in sub for i in n.inputs} | {n.id for n in sub}
            seeds = {nid: jax.device_put(d, self._dev[s])
                     for nid, d in outs.items() if nid in need}
            if not seeds:
                continue
            sig = ("stage", s, tuple(n.id for n in sub),
                   tuple(sorted((nid, d.capacity)
                                for nid, d in seeds.items())))
            fn = self._cache.get(sig)
            if fn is None:
                fn = jax.jit(
                    self.build_pass_fn(sub, self._boundary_of[s]),
                    donate_argnums=0)
                self._cache[sig] = fn
            stage_states = {nid: st for nid, st in self.states.items()
                            if stage_of[nid] == s}
            new_states, stage_eg = fn(stage_states, seeds)
            self.states.update(new_states)
            for nid, d in stage_eg.items():
                if nid in sink_inputs.values() or nid in back_edges.values():
                    egress[nid] = d
                else:
                    outs[nid] = d         # boundary -> later stages
        return egress
