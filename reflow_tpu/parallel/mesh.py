"""Mesh construction and NamedSharding placement for delta buffers + state.

Design (tpu-first): the mesh has one primary ``delta`` axis. Delta buffers
shard along their row axis (each chip processes a slice of the tick's
changes); keyed state tables shard along the key axis (each chip owns a key
range). Under ``jax.jit`` the GSPMD partitioner inserts the collectives the
north star names — scatter-adds into a key-sharded Reduce table become
on-chip partial sums + ``psum``-style combines; re-keying (GroupBy) becomes
``all_to_all`` key routing. The explicit ``shard_map`` lowering (for ops
XLA shouldn't re-derive, e.g. the Join arena product) lives in
``parallel/shard.py``.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["DELTA_AXIS", "make_mesh", "shard_state_tree", "replicate"]

#: name of the mesh axis delta rows and key ranges are sharded over
DELTA_AXIS = "delta"


def make_mesh(n_devices: Optional[int] = None, *,
              axis_name: str = DELTA_AXIS) -> Mesh:
    """A 1-D device mesh over the first ``n_devices`` local devices.

    On real hardware the device order jax reports follows the ICI torus, so
    a 1-D mesh keeps neighbor collectives on ICI links.
    """
    devs = jax.devices()
    n = len(devs) if n_devices is None else n_devices
    if len(devs) < n:
        raise ValueError(
            f"need {n} devices, have {len(devs)} "
            f"(set XLA_FLAGS=--xla_force_host_platform_device_count={n} "
            f"JAX_PLATFORMS=cpu for a virtual mesh)")
    return Mesh(np.array(devs[:n]), (axis_name,))


def _dim0_sharding(mesh: Mesh, axis_name: str, x) -> NamedSharding:
    """Shard dim 0 if it divides the mesh axis; replicate otherwise.

    Scalars (Join's ``rcount``) and ragged dims stay replicated — a
    conservative, always-correct placement.
    """
    n = mesh.shape[axis_name]
    if getattr(x, "ndim", 0) >= 1 and x.shape[0] % n == 0:
        return NamedSharding(mesh, P(axis_name))
    return NamedSharding(mesh, P())


def shard_state_tree(states, mesh: Mesh, *, axis_name: str = DELTA_AXIS):
    """Place per-node state tables key-sharded over the mesh (tp analog).

    Every leaf whose dim 0 divides the mesh shards along it (Reduce tables
    along the key space, Join arenas along the append log); odd-shaped and
    scalar leaves replicate.
    """
    return jax.tree.map(
        lambda x: jax.device_put(x, _dim0_sharding(mesh, axis_name, x)),
        states)


def replicate(tree, mesh: Mesh):
    """Fully replicate a pytree over the mesh."""
    sh = NamedSharding(mesh, P())
    return jax.tree.map(lambda x: jax.device_put(x, sh), tree)
