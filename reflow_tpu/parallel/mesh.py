"""Mesh construction and NamedSharding placement for delta buffers + state.

Design (tpu-first): the mesh has one primary ``delta`` axis. Delta buffers
shard along their row axis (each chip processes a slice of the tick's
changes); keyed state tables shard along the key axis (each chip owns a key
range). Under ``jax.jit`` the GSPMD partitioner inserts the collectives the
north star names — scatter-adds into a key-sharded Reduce table become
on-chip partial sums + ``psum``-style combines; re-keying (GroupBy) becomes
``all_to_all`` key routing. The explicit ``shard_map`` lowering (for ops
XLA shouldn't re-derive, e.g. the Join arena product) lives in
``parallel/shard.py``.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["DELTA_AXIS", "make_mesh", "shard_batch", "shard_state_tree",
           "replicate"]

#: name of the mesh axis delta rows and key ranges are sharded over
DELTA_AXIS = "delta"


def make_mesh(n_devices: Optional[int] = None, *,
              axis_name: str = DELTA_AXIS) -> Mesh:
    """A 1-D device mesh over the first ``n_devices`` local devices.

    On real hardware the device order jax reports follows the ICI torus, so
    a 1-D mesh keeps neighbor collectives on ICI links.
    """
    devs = jax.devices()
    n = len(devs) if n_devices is None else n_devices
    if len(devs) < n:
        raise ValueError(
            f"need {n} devices, have {len(devs)} "
            f"(set XLA_FLAGS=--xla_force_host_platform_device_count={n} "
            f"JAX_PLATFORMS=cpu for a virtual mesh)")
    return Mesh(np.array(devs[:n]), (axis_name,))


def _dim0_sharding(mesh: Mesh, axis_name: str, x) -> NamedSharding:
    """Shard dim 0 if it divides the mesh axis; replicate otherwise.

    Scalars (Join's ``rcount``) and ragged dims stay replicated — a
    conservative, always-correct placement.
    """
    n = mesh.shape[axis_name]
    if getattr(x, "ndim", 0) >= 1 and x.shape[0] % n == 0:
        return NamedSharding(mesh, P(axis_name))
    return NamedSharding(mesh, P())


def shard_state_tree(states, mesh: Mesh, *, axis_name: str = DELTA_AXIS):
    """Place per-node state tables key-sharded over the mesh (tp analog).

    Every leaf whose dim 0 divides the mesh shards along it (Reduce tables
    along the key space, Join arenas along the append log); odd-shaped and
    scalar leaves replicate.
    """
    return jax.tree.map(
        lambda x: jax.device_put(x, _dim0_sharding(mesh, axis_name, x)),
        states)


def replicate(tree, mesh: Mesh):
    """Fully replicate a pytree over the mesh."""
    sh = NamedSharding(mesh, P())
    return jax.tree.map(lambda x: jax.device_put(x, sh), tree)


def shard_batch(chunks, spec, mesh: Mesh, *, capacity=None,
                axis_name: str = DELTA_AXIS):
    """Assemble a row-sharded DeviceDelta from per-shard host chunks.

    ``chunks`` is one host :class:`~reflow_tpu.delta.DeltaBatch` per mesh
    device (length = mesh size), each padded to ``capacity // n`` rows
    with weight-0 padding and transferred host->owner-device in one hop —
    ``jax.make_array_from_single_device_arrays`` then stitches them into
    one global row-sharded array per column with no cross-device traffic.
    Push the result like any batch: the scheduler and the sharded
    executor accept device-resident ingress as-is.

    This is the single-controller form of the multi-host ingestion
    recipe: under multi-controller JAX each process builds its LOCAL
    chunks the same way and uses
    ``jax.make_array_from_process_local_data`` with the same sharding —
    the SPMD tick consumes either identically.
    """
    from reflow_tpu.executors.device_delta import (DeviceDelta,
                                                   bucket_capacity, to_device)

    if len(mesh.axis_names) != 1:
        raise ValueError("shard_batch expects a 1-D mesh (one row axis); "
                         f"got axes {mesh.axis_names}")
    n = mesh.shape[axis_name]
    if len(chunks) != n:
        raise ValueError(f"need one chunk per mesh device ({n}), "
                         f"got {len(chunks)}")
    if capacity is not None and (capacity <= 0 or capacity % n):
        raise ValueError(
            f"capacity {capacity} must be a positive multiple of the "
            f"mesh size {n}")
    per = (capacity // n if capacity is not None
           else bucket_capacity(max(len(c) for c in chunks)))
    # the SAME exactness bound every host->device path enforces — checked
    # on the GLOBAL batch: after key routing all shards' contributions
    # fold into one f32 table, so per-chunk mass alone would under-guard
    total_mass = sum(int(np.abs(np.asarray(c.weights)).sum())
                     for c in chunks if len(c))
    if total_mass >= 1 << 24:
        raise ValueError(
            "batch weight mass >= 2**24 exceeds the device path's exact "
            "float32 range; split the batch across ticks")

    devs = list(mesh.devices.ravel())
    # one host->owner transfer per chunk (to_device pads/casts exactly as
    # the ordinary push path and lands on d directly; routing through the
    # default device would double-hop n-1 chunks)
    locals_ = [to_device(c, spec, capacity=per, device=d)
               for c, d in zip(chunks, devs)]
    sharding = NamedSharding(mesh, P(axis_name))

    def stitch(col):
        shards = [getattr(l, col) for l in locals_]
        shape = (n * per,) + shards[0].shape[1:]
        return jax.make_array_from_single_device_arrays(
            shape, sharding, shards)

    return DeviceDelta(stitch("keys"), stitch("values"), stitch("weights"))
