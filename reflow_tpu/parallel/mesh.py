"""Mesh construction and NamedSharding placement for delta buffers + state.

Design (tpu-first): the mesh has one primary ``delta`` axis. Delta buffers
shard along their row axis (each chip processes a slice of the tick's
changes); keyed state tables shard along the key axis (each chip owns a key
range). Under ``jax.jit`` the GSPMD partitioner inserts the collectives the
north star names — scatter-adds into a key-sharded Reduce table become
on-chip partial sums + ``psum``-style combines; re-keying (GroupBy) becomes
``all_to_all`` key routing. The explicit ``shard_map`` lowering (for ops
XLA shouldn't re-derive, e.g. the Join arena product) lives in
``parallel/shard.py``.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["DELTA_AXIS", "DCN_AXIS", "MODEL_AXIS", "make_mesh",
           "make_model_mesh", "shard_batch", "shard_batch_process_local",
           "shard_state_tree", "replicate"]

#: name of the mesh axis delta rows and key ranges are sharded over
DELTA_AXIS = "delta"
#: name of the slow (cross-host / data-center-network) mesh axis of a
#: 2-axis mesh — the multi-slice dimension
DCN_AXIS = "dcn"
#: name of the tensor-parallel axis of a (delta, model) mesh
MODEL_AXIS = "model"


def make_mesh(n_devices: Optional[int] = None, *,
              axis_name: str = DELTA_AXIS,
              dcn: Optional[int] = None) -> Mesh:
    """A device mesh for the sharded executor.

    1-D (default): the first ``n_devices`` local devices on one
    ``axis_name`` axis. On real hardware the device order jax reports
    follows the ICI torus, so a 1-D mesh keeps neighbor collectives on
    ICI links.

    2-D (``dcn=k``): a ``(DCN_AXIS, axis_name)`` mesh of shape
    ``[k, n//k]`` over the GLOBAL device list, ordered so each dcn row
    holds one process's (slice's) devices — under multi-controller JAX
    set ``dcn = jax.process_count()`` and intra-row collectives ride
    ICI while only the cross-row legs of the product-axis collectives
    cross DCN. The sharded executor consumes either form: on a 2-axis
    mesh it shards over the flattened ``(dcn, delta)`` product axis
    (dcn-major, matching ``jax.lax.axis_index``'s flat order).
    """
    devs = jax.devices()
    n = len(devs) if n_devices is None else n_devices
    if len(devs) < n:
        raise ValueError(
            f"need {n} devices, have {len(devs)} "
            f"(set XLA_FLAGS=--xla_force_host_platform_device_count={n} "
            f"JAX_PLATFORMS=cpu for a virtual mesh)")
    if dcn is None:
        return Mesh(np.array(devs[:n]), (axis_name,))
    if n % dcn:
        raise ValueError(f"n_devices {n} not divisible by dcn {dcn}")
    # dcn rows group by process (slice) so the fast axis stays intra-host;
    # within a process, jax's device order follows the ICI torus
    ordered = sorted(devs[:n], key=lambda d: (d.process_index, d.id))
    return Mesh(np.array(ordered).reshape(dcn, n // dcn),
                (DCN_AXIS, axis_name))


def make_model_mesh(n_delta: int, n_model: int, *,
                    axis_name: str = DELTA_AXIS,
                    model_axis: str = MODEL_AXIS) -> Mesh:
    """A 2-D (delta, model) mesh (VERDICT r4 #8): delta rows and key
    ranges shard over ``axis_name``; Map params with ``param_specs``
    shard tensor-parallel over ``model_axis`` (pair with
    ``ShardedTpuExecutor(mesh, model_axis=...)``). Delta-major device
    order keeps each model group on adjacent (ICI-neighbor) devices —
    the two per-block psums ride the fast links."""
    devs = jax.devices()
    n = n_delta * n_model
    if len(devs) < n:
        raise ValueError(f"need {n} devices, have {len(devs)}")
    return Mesh(np.array(devs[:n]).reshape(n_delta, n_model),
                (axis_name, model_axis))


def _dim0_sharding(mesh: Mesh, axis_name: str, x) -> NamedSharding:
    """Shard dim 0 if it divides the mesh axis; replicate otherwise.

    Scalars (Join's ``rcount``) and ragged dims stay replicated — a
    conservative, always-correct placement.
    """
    n = mesh.shape[axis_name]
    if getattr(x, "ndim", 0) >= 1 and x.shape[0] % n == 0:
        return NamedSharding(mesh, P(axis_name))
    return NamedSharding(mesh, P())


def shard_state_tree(states, mesh: Mesh, *, axis_name: str = DELTA_AXIS):
    """Place per-node state tables key-sharded over the mesh (tp analog).

    Every leaf whose dim 0 divides the mesh shards along it (Reduce tables
    along the key space, Join arenas along the append log); odd-shaped and
    scalar leaves replicate.
    """
    return jax.tree.map(
        lambda x: jax.device_put(x, _dim0_sharding(mesh, axis_name, x)),
        states)


def replicate(tree, mesh: Mesh):
    """Fully replicate a pytree over the mesh."""
    sh = NamedSharding(mesh, P())
    return jax.tree.map(lambda x: jax.device_put(x, sh), tree)


def shard_batch(chunks, spec, mesh: Mesh, *, capacity=None,
                axis_name: str = DELTA_AXIS):
    """Assemble a row-sharded DeviceDelta from per-shard host chunks.

    ``chunks`` is one host :class:`~reflow_tpu.delta.DeltaBatch` per mesh
    device (length = mesh size), each padded to ``capacity // n`` rows
    with weight-0 padding and transferred host->owner-device in one hop —
    ``jax.make_array_from_single_device_arrays`` then stitches them into
    one global row-sharded array per column with no cross-device traffic.
    Push the result like any batch: the scheduler and the sharded
    executor accept device-resident ingress as-is.

    This is the single-controller form of the multi-host ingestion
    recipe: under multi-controller JAX each process builds its LOCAL
    chunks the same way and uses
    ``jax.make_array_from_process_local_data`` with the same sharding —
    the SPMD tick consumes either identically.
    """
    from reflow_tpu.executors.device_delta import (DeviceDelta,
                                                   bucket_capacity, to_device)

    axes = (tuple(mesh.axis_names) if len(mesh.axis_names) > 1
            else axis_name)
    n = int(np.prod([mesh.shape[a] for a in mesh.axis_names]))
    if len(chunks) != n:
        raise ValueError(f"need one chunk per mesh device ({n}), "
                         f"got {len(chunks)}")
    if capacity is not None and (capacity <= 0 or capacity % n):
        raise ValueError(
            f"capacity {capacity} must be a positive multiple of the "
            f"mesh size {n}")
    per = (capacity // n if capacity is not None
           else bucket_capacity(max(len(c) for c in chunks)))
    # the SAME exactness bound every host->device path enforces — checked
    # on the GLOBAL batch: after key routing all shards' contributions
    # fold into one f32 table, so per-chunk mass alone would under-guard
    from reflow_tpu.executors.device_delta import check_weight_mass_value

    check_weight_mass_value(sum(int(np.abs(np.asarray(c.weights)).sum())
                                for c in chunks if len(c)))

    devs = list(mesh.devices.ravel())
    # one host->owner transfer per chunk (to_device pads/casts exactly as
    # the ordinary push path and lands on d directly; routing through the
    # default device would double-hop n-1 chunks)
    locals_ = [to_device(c, spec, capacity=per, device=d)
               for c, d in zip(chunks, devs)]
    sharding = NamedSharding(mesh, P(axes))

    def stitch(col):
        shards = [getattr(l, col) for l in locals_]
        shape = (n * per,) + shards[0].shape[1:]
        return jax.make_array_from_single_device_arrays(
            shape, sharding, shards)

    return DeviceDelta(stitch("keys"), stitch("values"), stitch("weights"))


def shard_batch_process_local(chunk, spec, mesh: Mesh, *, capacity: int):
    """Multi-controller ingestion: each PROCESS contributes its local
    rows and the global row-sharded DeviceDelta assembles via
    ``jax.make_array_from_process_local_data`` — the multi-host form of
    :func:`shard_batch`, consumed identically by the SPMD tick.

    ``chunk`` is this process's host :class:`DeltaBatch`;
    ``capacity`` is the GLOBAL row capacity (a multiple of the mesh
    size). Every process must call this (and the subsequent push/tick)
    collectively with the same capacity. The f32-exactness mass guard
    runs on the GLOBAL batch via one ``process_allgather`` of the local
    masses — the same bound every host->device path enforces.
    """
    n = int(np.prod([mesh.shape[a] for a in mesh.axis_names]))
    if capacity <= 0 or capacity % n:
        raise ValueError(f"capacity {capacity} must be a positive "
                         f"multiple of the mesh size {n}")
    n_proc = jax.process_count()
    n_local = capacity // n_proc
    if len(chunk) > n_local:
        raise ValueError(
            f"local chunk ({len(chunk)} rows) exceeds this process's "
            f"share {n_local} of capacity {capacity}")

    local_mass = float(np.abs(np.asarray(chunk.weights)).sum()) \
        if len(chunk) else 0.0
    from reflow_tpu.executors.device_delta import check_weight_mass_value

    if n_proc > 1:
        from jax.experimental import multihost_utils
        total_mass = float(np.sum(multihost_utils.process_allgather(
            np.float64(local_mass))))
    else:
        total_mass = local_mass
    check_weight_mass_value(total_mass)

    m = len(chunk)
    keys = np.zeros((n_local,), np.int32)
    weights = np.zeros((n_local,), np.int32)
    values = np.zeros((n_local,) + tuple(spec.value_shape),
                      spec.value_dtype)
    if m:
        keys[:m] = np.asarray(chunk.keys, np.int64)
        weights[:m] = np.asarray(chunk.weights)
        values[:m] = np.asarray(chunk.values).reshape(
            (m,) + tuple(spec.value_shape))

    from reflow_tpu.executors.device_delta import DeviceDelta

    axes = (tuple(mesh.axis_names) if len(mesh.axis_names) > 1
            else mesh.axis_names[0])
    sharding = NamedSharding(mesh, P(axes))

    def assemble(local):
        return jax.make_array_from_process_local_data(
            sharding, local, (capacity,) + local.shape[1:])

    return DeviceDelta(assemble(keys), assemble(values), assemble(weights))
