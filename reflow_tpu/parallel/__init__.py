"""Mesh + sharding layer (SURVEY.md §2 parallelism checklist, §7.8).

The parallelism strategies native to this framework class:

- **delta-parallel (dp analog)**: delta buffers sharded along their row
  (capacity) axis over the mesh — each chip ingests a slice of the tick's
  changes.
- **key-parallel (tp analog)**: keyed state tables (Reduce aggregates, Join
  left tables) sharded along the key axis — each chip owns a key range;
  cross-shard combination is ``psum_scatter`` (dense) or ``all_to_all``
  key routing (sparse Reduce; large-delta Join sides — see
  ``shard_lowerings.route_rows``).
- **topo-partitioning (pp analog)**: contiguous FlowGraph stages pinned to
  separate devices with per-stage pass programs and explicit
  ``device_put`` boundary handoff — ``topo.StagedTpuExecutor``, driven by
  ``Node.stage``.

This package provides the mesh construction + NamedSharding placement
helpers shared by the sharded executor, ``__graft_entry__.dryrun_multichip``
and the benchmark harness.
"""

from reflow_tpu.parallel.mesh import (DELTA_AXIS, make_mesh, replicate,
                                      shard_batch, shard_state_tree)

__all__ = ["DELTA_AXIS", "make_mesh", "replicate", "shard_batch",
           "shard_state_tree",
           "StagedTpuExecutor", "ShardedTpuExecutor"]


def __getattr__(name):
    # lazy: keep `import reflow_tpu.parallel` jax-free until an executor
    # class is actually requested
    if name == "StagedTpuExecutor":
        from reflow_tpu.parallel.topo import StagedTpuExecutor
        return StagedTpuExecutor
    if name == "ShardedTpuExecutor":
        from reflow_tpu.parallel.shard import ShardedTpuExecutor
        return ShardedTpuExecutor
    raise AttributeError(name)
