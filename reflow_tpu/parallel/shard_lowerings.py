"""Shard-aware op lowerings: the per-shard kernels under ``shard_map``.

Design (the scaling-book recipe — route rows to their key's owner, shard
what's big):

- **Map / Filter / GroupBy / Union** are local on row-sharded delta
  buffers: no communication. A GroupBy re-key leaves rows in place; routing
  happens where a *keyed* op consumes them.
- **Row routing** (:func:`route_rows`): one ``all_to_all`` on
  shard-of-key delivers every live delta row to the shard owning its key
  range — traffic O(slack x delta rows), independent of both the mesh
  size (vs all_gather's O(n x rows)) and the key space (vs a dense
  reduce-scatter's O(K)). Static shapes force a per-destination budget
  (``ROUTE_SLACK`` x balanced share); overflow beyond the budget sets a
  sticky per-node error flag surfaced by ``check_errors`` — loud, never
  silent truncation.
- **Reduce**: sparse regime (delta capacity well under K) routes rows to
  their owners and scatter-adds locally — per-pass comms scale with the
  delta, not the key space. Dense regime (delta ~ K, e.g. full rebuild
  passes) keeps the full-K contribution table + one ``psum_scatter``
  (reduce-scatter), which is optimal when most keys are touched. State
  tables (``wsum``/``wcnt``/``emitted``) live key-sharded; emission covers
  the owned range with global key ids.
- **Join**: both delta sides are routed to key owners (``all_to_all``)
  and fed to the shared :func:`join_core` over the shard's slice of the
  left table and append arena; meshes too small for routing to win
  (n <= ROUTE_SLACK) keep the tiled ``all_gather`` + mask. Output rows
  stay on the owning shard (row-sharded), keys global.

Keyed state is range-sharded: shard ``i`` of ``n`` owns keys
``[i*K/n, (i+1)*K/n)``. Range (not hash) sharding keeps key<->shard
arithmetic trivial and lets emission use a contiguous ``arange``.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from reflow_tpu.executors.device_delta import DeviceDelta
from reflow_tpu.executors.lowerings import (_LOWERINGS, _agg_tables,
                                            _bcast_w, _differs,
                                            _scatter_contribs, join_core)
from reflow_tpu.graph import Node

__all__ = ["lower_node_sharded", "route_rows", "ROUTE_SLACK"]

#: per-destination row budget = ROUTE_SLACK x the perfectly-balanced
#: share. 4x absorbs realistic key skew; pathological skew trips the
#: sticky overflow flag instead of truncating.
ROUTE_SLACK = 4


def route_rows(d: DeviceDelta, axis: str, n: int, Kl: int,
               slack: int = ROUTE_SLACK
               ) -> Tuple[DeviceDelta, jax.Array]:
    """Deliver each live row to the shard owning its key (one all_to_all).

    ``d`` is this shard's local slice (capacity Cl) of a row-sharded
    delta. Rows are bucketed by owner shard (``key // Kl``), each bucket
    padded to the static budget ``B = ceil(slack*Cl/n)``, exchanged, and
    returned as a local-keyed delta of capacity ``n*B`` (re-based keys,
    weight-0 padding). Second return is the per-shard overflow flag (any
    live row beyond its bucket's budget was NOT sent).
    """
    Cl = d.keys.shape[0]
    B = max(1, -(-slack * Cl // n))
    live = d.weights != 0
    owner = jnp.where(live, jnp.clip(d.keys // Kl, 0, n - 1), n)
    order = jnp.argsort(owner, stable=True)
    so = owner[order]
    sk, sv, sw = d.keys[order], d.values[order], d.weights[order]
    start = jnp.searchsorted(so, jnp.arange(n, dtype=so.dtype))
    slot = jnp.arange(Cl, dtype=jnp.int32) - start[jnp.minimum(so, n - 1)]
    ok = (so < n) & (slot < B)
    err = jnp.any((so < n) & (slot >= B))
    pos = jnp.where(ok, so.astype(jnp.int32) * B + slot, n * B)
    send_k = jnp.zeros((n * B,), jnp.int32).at[pos].set(sk, mode="drop")
    send_v = jnp.zeros((n * B,) + d.values.shape[1:],
                       d.values.dtype).at[pos].set(sv, mode="drop")
    send_w = jnp.zeros((n * B,), jnp.int32).at[pos].set(sw, mode="drop")

    def xchg(a):
        trail = a.shape[1:]
        out = jax.lax.all_to_all(a.reshape((n, B) + trail), axis, 0, 0)
        return out.reshape((n * B,) + trail)

    rk, rv, rw = xchg(send_k), xchg(send_v), xchg(send_w)
    base = (jax.lax.axis_index(axis) * Kl).astype(jnp.int32)
    lk = jnp.where(rw != 0, rk - base, 0)
    return DeviceDelta(lk, rv, rw), err


def _localize(d: DeviceDelta, base, Kl: int) -> DeviceDelta:
    """Mask a gathered delta to this shard's key range and re-base keys.

    Non-owned rows become weight-0 padding at local key 0 — no-ops of the
    multiset algebra, so the downstream kernel needs no other masking.
    """
    own = (d.keys >= base) & (d.keys < base + Kl)
    return DeviceDelta(
        keys=jnp.where(own, d.keys - base, 0),
        values=d.values,
        weights=jnp.where(own, d.weights, 0),
    )


def _lower_reduce_sharded(op, node: Node, state, ins, axis: str, n: int
                          ) -> Tuple[DeviceDelta, dict]:
    (d,) = ins                      # local delta rows [Cl]
    in_spec = node.inputs[0].spec
    K = in_spec.key_space
    Kl = K // n
    Cl = d.keys.shape[0]
    vdtype = node.spec.value_dtype
    base = (jax.lax.axis_index(axis) * Kl).astype(jnp.int32)
    vshape = d.values.shape[1:]
    # linear reducers get their error scalar at sharded bind time, so the
    # route-overflow flag below is never silently dropped (ADVICE r2 high)
    err = state.get("error", jnp.zeros((), jnp.bool_))

    if ROUTE_SLACK * Cl < Kl:
        # sparse regime: route rows to their key's owner and fold locally
        # — comms O(slack*Cl), independent of K
        dl, route_err = route_rows(d, axis, n, Kl)
        dws, dwc = _scatter_contribs(dl, Kl)
        wsum = state["wsum"] + dws
        wcnt = state["wcnt"] + dwc
        err = err | (jax.lax.pmax(route_err.astype(jnp.int32), axis) > 0)
    else:
        # dense regime (most keys touched, e.g. rebuild passes): full-K
        # local contributions + one reduce-scatter
        dws, dwc = _scatter_contribs(d, K)
        stacked = jnp.concatenate(
            [dws.reshape(K, -1), dwc.astype(jnp.float32)[:, None]], axis=-1)
        combined = jax.lax.psum_scatter(stacked, axis, scatter_dimension=0,
                                        tiled=True)
        wsum = state["wsum"] + combined[:, :-1].reshape((Kl,) + vshape)
        wcnt = state["wcnt"] + combined[:, -1].astype(jnp.int32)

    # dense diff over the owned slice (mirrors _lower_reduce dense mode)
    emitted, em_has = state["emitted"], state["emitted_has"]
    agg, exists = _agg_tables(op, wsum, wcnt, vdtype)
    changed = _differs(agg, emitted, op.tol)
    ins_m = exists & (~em_has | changed)
    ret_m = em_has & (~exists | changed)
    gkeys = base + jnp.arange(Kl, dtype=jnp.int32)
    out = DeviceDelta(
        keys=jnp.concatenate([gkeys, gkeys]),
        values=jnp.concatenate([emitted, agg]),
        weights=jnp.concatenate(
            [-ret_m.astype(jnp.int32), ins_m.astype(jnp.int32)]),
    )
    ins_b = _bcast_w(ins_m, agg)
    new_emitted = jnp.where(ins_b, agg, emitted)
    new_has = jnp.where(ins_m, True, jnp.where(ret_m & ~exists, False, em_has))
    new_state = {"wsum": wsum, "wcnt": wcnt,
                 "emitted": new_emitted, "emitted_has": new_has,
                 "error": err}
    return out, new_state


def _lower_join_sharded(op, node: Node, state, ins, axis: str, n: int
                        ) -> Tuple[DeviceDelta, dict]:
    da, db = ins                    # local delta rows
    K = node.inputs[0].spec.key_space
    Kl = K // n
    Rl = op.arena_capacity // n
    base = (jax.lax.axis_index(axis) * Kl).astype(jnp.int32)

    # deltas are small: gather both sides everywhere, keep only owned rows
    def _route(d):
        if d is None:
            return None
        g = jax.tree.map(lambda x: jax.lax.all_gather(x, axis, tiled=True), d)
        return _localize(g, base, Kl)

    da_l = _route(da)
    db_l = _route(db)

    # per-shard scalar append counter is stored as a length-1 slice of a
    # mesh-length vector; the core kernel wants a scalar
    core_state = dict(state)
    core_state["rcount"] = state["rcount"][0]
    out, new_state = join_core(op, Kl, Rl, node.spec.value_dtype,
                               core_state, da_l, db_l, key_offset=base)
    new_state["rcount"] = new_state["rcount"][None]
    return out, new_state


def lower_node_sharded(node: Node, state, ins: Sequence[DeviceDelta],
                       axis: str, n: int) -> Tuple[DeviceDelta, dict]:
    kind = node.op.kind
    if kind == "reduce":
        return _lower_reduce_sharded(node.op, node, state, ins, axis, n)
    if kind == "join":
        return _lower_join_sharded(node.op, node, state, ins, axis, n)
    # stateless row ops are shard-local
    return _LOWERINGS[kind](node.op, node, state, ins)
