"""Shard-aware op lowerings: the per-shard kernels under ``shard_map``.

Design (the scaling-book recipe — route rows to their key's owner, shard
what's big):

- **Map / Filter / GroupBy / Union** are local on row-sharded delta
  buffers: no communication. A GroupBy re-key leaves rows in place; routing
  happens where a *keyed* op consumes them.
- **Row routing** (:func:`route_rows`): one ``all_to_all`` on
  shard-of-key delivers every live delta row to the shard owning its key
  range — traffic O(slack x delta rows), independent of both the mesh
  size (vs all_gather's O(n x rows)) and the key space (vs a dense
  reduce-scatter's O(K)). Static shapes force a per-destination budget
  (``ROUTE_SLACK`` x balanced share); overflow beyond the budget sets a
  sticky per-node error flag surfaced by ``check_errors`` — loud, never
  silent truncation.
- **Reduce**: sparse regime (delta capacity well under K) routes rows to
  their owners and scatter-adds locally — per-pass comms scale with the
  delta, not the key space. Dense regime (delta ~ K, e.g. full rebuild
  passes) keeps the full-K contribution table + one ``psum_scatter``
  (reduce-scatter), which is optimal when most keys are touched. State
  tables (``wsum``/``wcnt``/``emitted``) live key-sharded; emission covers
  the owned range with global key ids.
- **Join**: both delta sides are routed to key owners (``all_to_all``)
  and fed to the shared :func:`join_core` over the shard's slice of the
  left table and append arena; meshes too small for routing to win
  (n <= ROUTE_SLACK) and deltas whose per-destination budget would fall
  under ``_MIN_ROUTE_BUDGET`` rows keep the tiled ``all_gather`` + mask.
  Output rows stay on the owning shard (row-sharded), keys global. Arena
  rows therefore always carry shard-LOCAL keys — the invariant the
  sharded linear fixpoint's per-shard CSR relies on.

Keyed state is range-sharded: shard ``i`` of ``n`` owns keys
``[i*K/n, (i+1)*K/n)``. Range (not hash) sharding keeps key<->shard
arithmetic trivial and lets emission use a contiguous ``arange``.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from reflow_tpu.executors.device_delta import DeviceDelta
from reflow_tpu.executors.lowerings import (_LOWERINGS, LINEAR_DEVICE_REDUCERS,
                                            _agg_tables, _bcast_w, _differs,
                                            _scatter_contribs, join_core)
from reflow_tpu.graph import Node

__all__ = ["lower_node_sharded", "route_rows", "deliver_to_owner",
           "ROUTE_SLACK"]

#: per-destination row budget = ROUTE_SLACK x the perfectly-balanced
#: share. 4x absorbs realistic key skew; pathological skew trips the
#: sticky overflow flag instead of truncating.
ROUTE_SLACK = 4
#: the Join routes a delta side only when its per-destination budget is at
#: least this many rows — thin budgets trip on ordinary randomness, and
#: replicating a small delta costs next to nothing
_MIN_ROUTE_BUDGET = 64


def _should_route(n: int, Cl: int) -> bool:
    """The shared routed-vs-replicated comm policy (Join, min/max):
    route when the mesh is big enough for all_to_all to beat all_gather
    AND the per-destination budget is thick enough not to trip on
    ordinary key randomness."""
    return n > ROUTE_SLACK and ROUTE_SLACK * Cl >= _MIN_ROUTE_BUDGET * n


def deliver_to_owner(d: DeviceDelta, axis, n: int, Kl: int,
                     sizes: Optional[Tuple[int, ...]] = None
                     ) -> Tuple[DeviceDelta, jax.Array]:
    """Deliver every live row of a row-sharded delta to the shard owning
    its key range, returning a LOCAL-keyed delta plus the (pmax-combined)
    route-overflow flag. ONE definition of the routed-vs-replicated
    policy, shared by every keyed consumer (Reduce, Join, min/max, the
    latch refresh) so no path can drift to a different policy.

    On a 2-axis (dcn, ici) mesh (``axis`` a tuple, ``sizes`` its per-axis
    extents) the routed path is HIERARCHICAL: an intra-slice ICI leg
    delivers each row to its destination's ICI column, then ONE DCN
    exchange crosses slices — each row crosses the slow network exactly
    once, in per-slice aggregated messages, instead of the flat product
    ``all_to_all`` treating every DCN link like an ICI link
    (ROADMAP r4 #1 / VERDICT r4 #4)."""
    Cl = d.keys.shape[0]
    if _should_route(n, Cl):
        if isinstance(axis, tuple) and sizes is not None:
            dl, route_err = _route_rows_hier(d, axis, sizes, Kl)
        else:
            dl, route_err = route_rows(d, axis, n, Kl)
        return dl, jax.lax.pmax(route_err.astype(jnp.int32), axis) > 0
    base = (jax.lax.axis_index(axis) * Kl).astype(jnp.int32)
    g = jax.tree.map(lambda x: jax.lax.all_gather(x, axis, tiled=True), d)
    return _localize(g, base, Kl), jnp.zeros((), jnp.bool_)


def _bucket_exchange(d: DeviceDelta, dest: jax.Array, n_sub: int, B: int,
                     axis_name: str) -> Tuple[DeviceDelta, jax.Array]:
    """One bucketed ``all_to_all`` leg: rows with ``dest`` in
    ``[0, n_sub)`` pack into per-destination buckets of ``B`` slots
    (``dest == n_sub`` drops — dead rows), exchange along ``axis_name``,
    and return the received ``n_sub * B`` rows (keys untouched — global)
    plus this shard's overflow flag."""
    Cl = d.keys.shape[0]
    order = jnp.argsort(dest, stable=True)
    so = dest[order]
    sk, sv, sw = d.keys[order], d.values[order], d.weights[order]
    start = jnp.searchsorted(so, jnp.arange(n_sub, dtype=so.dtype))
    slot = (jnp.arange(Cl, dtype=jnp.int32)
            - start[jnp.minimum(so, n_sub - 1)])
    ok = (so < n_sub) & (slot < B)
    err = jnp.any((so < n_sub) & (slot >= B))
    pos = jnp.where(ok, so.astype(jnp.int32) * B + slot, n_sub * B)
    send_k = jnp.zeros((n_sub * B,), jnp.int32).at[pos].set(sk, mode="drop")
    send_v = jnp.zeros((n_sub * B,) + d.values.shape[1:],
                       d.values.dtype).at[pos].set(sv, mode="drop")
    send_w = jnp.zeros((n_sub * B,), jnp.int32).at[pos].set(sw, mode="drop")

    def xchg(a):
        trail = a.shape[1:]
        out = jax.lax.all_to_all(a.reshape((n_sub, B) + trail), axis_name,
                                 0, 0)
        return out.reshape((n_sub * B,) + trail)

    return DeviceDelta(xchg(send_k), xchg(send_v), xchg(send_w)), err


def _route_rows_hier(d: DeviceDelta, axes: Tuple[str, str],
                     sizes: Tuple[int, int], Kl: int,
                     slack: int = ROUTE_SLACK
                     ) -> Tuple[DeviceDelta, jax.Array]:
    """Two-stage owner delivery on a (dcn, ici) mesh: ICI leg to the
    destination's ici column (intra-slice), then ONE DCN exchange to the
    destination slice. Flat owner ids are dcn-major (the executor's
    product-axis order), so ``owner = key // Kl``,
    ``(own_dcn, own_ici) = divmod(owner, n_ici)``."""
    dcn_ax, ici_ax = axes
    n_dcn, n_ici = sizes
    n = n_dcn * n_ici
    Cl = d.keys.shape[0]
    live = d.weights != 0
    owner = jnp.where(live, jnp.clip(d.keys // Kl, 0, n - 1), n)
    own_ici = jnp.where(owner < n, owner % n_ici, n_ici)
    # stage 1 (ICI): to my slice's device in the destination's column
    B1 = max(1, -(-slack * Cl // n_ici))
    d1, err1 = _bucket_exchange(d, own_ici, n_ici, B1, ici_ax)
    # stage 2 (DCN): to the destination slice (column now correct).
    # Bucket size derives from the ORIGINAL live-row bound Cl, not the
    # padded stage-1 capacity (which is already slack-inflated): the
    # balanced per-device share after stage 1 is ~Cl rows split over
    # n_dcn destinations, so slack*Cl/n_dcn gives the same skew headroom
    # as the flat route at the same total capacity (~slack*Cl).
    live1 = d1.weights != 0
    owner1 = jnp.where(live1, jnp.clip(d1.keys // Kl, 0, n - 1), n)
    own_dcn = jnp.where(owner1 < n, owner1 // n_ici, n_dcn)
    B2 = max(1, -(-slack * Cl // n_dcn))
    d2, err2 = _bucket_exchange(d1, own_dcn, n_dcn, B2, dcn_ax)
    base = (jax.lax.axis_index(axes) * Kl).astype(jnp.int32)
    lk = jnp.where(d2.weights != 0, d2.keys - base, 0)
    return DeviceDelta(lk, d2.values, d2.weights), err1 | err2


def route_rows(d: DeviceDelta, axis: str, n: int, Kl: int,
               slack: int = ROUTE_SLACK
               ) -> Tuple[DeviceDelta, jax.Array]:
    """Deliver each live row to the shard owning its key (one all_to_all).

    ``d`` is this shard's local slice (capacity Cl) of a row-sharded
    delta. Rows are bucketed by owner shard (``key // Kl``), each bucket
    padded to the static budget ``B = ceil(slack*Cl/n)``, exchanged, and
    returned as a local-keyed delta of capacity ``n*B`` (re-based keys,
    weight-0 padding). Second return is the per-shard overflow flag (any
    live row beyond its bucket's budget was NOT sent).
    """
    Cl = d.keys.shape[0]
    B = max(1, -(-slack * Cl // n))
    live = d.weights != 0
    owner = jnp.where(live, jnp.clip(d.keys // Kl, 0, n - 1), n)
    order = jnp.argsort(owner, stable=True)
    so = owner[order]
    sk, sv, sw = d.keys[order], d.values[order], d.weights[order]
    start = jnp.searchsorted(so, jnp.arange(n, dtype=so.dtype))
    slot = jnp.arange(Cl, dtype=jnp.int32) - start[jnp.minimum(so, n - 1)]
    ok = (so < n) & (slot < B)
    err = jnp.any((so < n) & (slot >= B))
    pos = jnp.where(ok, so.astype(jnp.int32) * B + slot, n * B)
    send_k = jnp.zeros((n * B,), jnp.int32).at[pos].set(sk, mode="drop")
    send_v = jnp.zeros((n * B,) + d.values.shape[1:],
                       d.values.dtype).at[pos].set(sv, mode="drop")
    send_w = jnp.zeros((n * B,), jnp.int32).at[pos].set(sw, mode="drop")

    def xchg(a):
        trail = a.shape[1:]
        out = jax.lax.all_to_all(a.reshape((n, B) + trail), axis, 0, 0)
        return out.reshape((n * B,) + trail)

    rk, rv, rw = xchg(send_k), xchg(send_v), xchg(send_w)
    base = (jax.lax.axis_index(axis) * Kl).astype(jnp.int32)
    lk = jnp.where(rw != 0, rk - base, 0)
    return DeviceDelta(lk, rv, rw), err


def _localize(d: DeviceDelta, base, Kl: int) -> DeviceDelta:
    """Mask a gathered delta to this shard's key range and re-base keys.

    Non-owned rows become weight-0 padding at local key 0 — no-ops of the
    multiset algebra, so the downstream kernel needs no other masking.
    """
    own = (d.keys >= base) & (d.keys < base + Kl)
    return DeviceDelta(
        keys=jnp.where(own, d.keys - base, 0),
        values=d.values,
        weights=jnp.where(own, d.weights, 0),
    )


def _lower_reduce_sharded(op, node: Node, state, ins, axis, n: int,
                          sizes=None) -> Tuple[DeviceDelta, dict]:
    (d,) = ins                      # local delta rows [Cl]
    in_spec = node.inputs[0].spec
    K = in_spec.key_space
    Kl = K // n
    Cl = d.keys.shape[0]
    vdtype = node.spec.value_dtype
    base = (jax.lax.axis_index(axis) * Kl).astype(jnp.int32)
    vshape = d.values.shape[1:]
    # linear reducers get their error scalar at sharded bind time, so the
    # route-overflow flag below is never silently dropped (ADVICE r2 high)
    err = state.get("error", jnp.zeros((), jnp.bool_))

    if ROUTE_SLACK * Cl < Kl:
        # sparse regime: route rows to their key's owner and fold locally
        # — comms O(slack*Cl), independent of K (hierarchical two-stage
        # on a 2-axis mesh: one DCN crossing per row)
        if isinstance(axis, tuple) and sizes is not None:
            dl, route_err = _route_rows_hier(d, axis, sizes, Kl)
        else:
            dl, route_err = route_rows(d, axis, n, Kl)
        dws, dwc = _scatter_contribs(dl, Kl)
        wsum = state["wsum"] + dws
        wcnt = state["wcnt"] + dwc
        err = err | (jax.lax.pmax(route_err.astype(jnp.int32), axis) > 0)
    else:
        # dense regime (most keys touched, e.g. rebuild passes): full-K
        # local contributions + one reduce-scatter
        dws, dwc = _scatter_contribs(d, K)
        stacked = jnp.concatenate(
            [dws.reshape(K, -1), dwc.astype(jnp.float32)[:, None]], axis=-1)
        combined = jax.lax.psum_scatter(stacked, axis, scatter_dimension=0,
                                        tiled=True)
        wsum = state["wsum"] + combined[:, :-1].reshape((Kl,) + vshape)
        wcnt = state["wcnt"] + combined[:, -1].astype(jnp.int32)

    # dense diff over the owned slice (mirrors _lower_reduce dense mode)
    emitted, em_has = state["emitted"], state["emitted_has"]
    agg, exists = _agg_tables(op, wsum, wcnt, vdtype)
    changed = _differs(agg, emitted, op.tol)
    ins_m = exists & (~em_has | changed)
    ret_m = em_has & (~exists | changed)
    gkeys = base + jnp.arange(Kl, dtype=jnp.int32)
    out = DeviceDelta(
        keys=jnp.concatenate([gkeys, gkeys]),
        values=jnp.concatenate([emitted, agg]),
        weights=jnp.concatenate(
            [-ret_m.astype(jnp.int32), ins_m.astype(jnp.int32)]),
    )
    ins_b = _bcast_w(ins_m, agg)
    new_emitted = jnp.where(ins_b, agg, emitted)
    new_has = jnp.where(ins_m, True, jnp.where(ret_m & ~exists, False, em_has))
    new_state = {"wsum": wsum, "wcnt": wcnt,
                 "emitted": new_emitted, "emitted_has": new_has,
                 "error": err}
    return out, new_state


def _lower_reduce_minmax_sharded(op, node: Node, state, ins,
                                 axis, n: int, sizes=None
                                 ) -> Tuple[DeviceDelta, dict]:
    """Retraction-capable min/max (scalar AND vector rows), key-sharded:
    delta rows reach their key's owner (routed ``all_to_all`` on large
    meshes, tiled ``all_gather`` + mask on small ones — the Join's comm
    policy), then the shared candidate-buffer kernel (``minmax_core``)
    runs on the owned key slice. Error flags (route overflow, buffer
    exhaustion) combine with ``pmax``."""
    from reflow_tpu.executors.lowerings import minmax_core

    (d,) = ins
    K = node.inputs[0].spec.key_space
    Kl = K // n
    base = (jax.lax.axis_index(axis) * Kl).astype(jnp.int32)
    dl, route_err = deliver_to_owner(d, axis, n, Kl, sizes=sizes)
    err = state["error"] | route_err

    core_state = dict(state)
    core_state["error"] = err
    out, new_state = minmax_core(op, Kl, tuple(node.spec.value_shape),
                                 node.spec.value_dtype, core_state, dl,
                                 key_offset=base)
    new_state["error"] = (jax.lax.pmax(
        new_state["error"].astype(jnp.int32), axis) > 0)
    return out, new_state


def _lower_join_sharded(op, node: Node, state, ins, axis, n: int,
                        sizes=None) -> Tuple[DeviceDelta, dict]:
    da, db = ins                    # local delta rows
    K = node.inputs[0].spec.key_space
    Kl = K // n
    Rl = op.arena_capacity // n
    base = (jax.lax.axis_index(axis) * Kl).astype(jnp.int32)
    err = state.get("error", jnp.zeros((), jnp.bool_))

    # both delta sides reach their key's owner: routed (one all_to_all,
    # O(slack x rows) traffic) on meshes where routing beats replication;
    # small meshes (n <= ROUTE_SLACK) and small deltas (per-destination
    # budget under _MIN_ROUTE_BUDGET rows — skew trips a thin budget far
    # too easily, and tiny batches are cheap to replicate) keep the tiled
    # all_gather + mask, whose O(n x rows) traffic is then no worse
    def _route(d):
        nonlocal err
        if d is None:
            return None
        dl, route_err = deliver_to_owner(d, axis, n, Kl, sizes=sizes)
        err = err | route_err
        return dl

    da_l = _route(da)
    db_l = _route(db)

    # per-shard scalar append counter / arena generation are stored as
    # length-1 slices of mesh-length vectors; the core kernel wants scalars
    core_state = dict(state)
    core_state["rcount"] = state["rcount"][0]
    core_state["gen"] = state["gen"][0]
    multiset = "lkeys" in state
    if multiset:
        core_state["lcount"] = state["lcount"][0]
        core_state["lgen"] = state["lgen"][0]
    out, new_state = join_core(op, Kl, Rl, node.spec.value_dtype,
                               core_state, da_l, db_l, key_offset=base,
                               oshape=tuple(node.spec.value_shape))
    new_state["rcount"] = new_state["rcount"][None]
    new_state["gen"] = new_state["gen"][None]
    if multiset:
        new_state["lcount"] = new_state["lcount"][None]
        new_state["lgen"] = new_state["lgen"][None]
    # join_core's arena-overflow flag is per-shard; the state leaf is
    # replicated, so fold it with pmax before OR-ing the route error in
    new_state["error"] = err | (jax.lax.pmax(
        new_state["error"].astype(jnp.int32), axis) > 0)
    return out, new_state


def _lower_knn_sharded(op, node: Node, state, ins, axis: str, n: int
                       ) -> Tuple[DeviceDelta, dict]:
    """Corpus row-sharded k-NN: each shard scans its corpus slice, one
    all_gather merges k candidates per query (SURVEY.md §2 item 14,
    'sharded' aspiration of BASELINE config 4).

    Layout: ``dvec``/``dlive`` sharded over the corpus axis; queries and
    the emitted table replicated (every shard needs every query against
    its slice, and the merged result is identical everywhere). Emission is
    partitioned by query range so the egress delta stays row-sharded.
    """
    from reflow_tpu.executors.lowerings import _fold_vectors, _norm_rows
    from reflow_tpu.kernels.topk import (NEG, chunked_corpus_topk,
                                         score_form, topk)

    dq, dd = ins
    if dq is None:
        dq = DeviceDelta.empty(node.inputs[0].spec)
    if dd is None:
        dd = DeviceDelta.empty(node.inputs[1].spec)
    Q = node.inputs[0].spec.key_space
    D = node.inputs[1].spec.key_space
    Ql, Dl = Q // n, D // n
    k = op.k
    base_q = (jax.lax.axis_index(axis) * Ql).astype(jnp.int32)
    base_d = (jax.lax.axis_index(axis) * Dl).astype(jnp.int32)

    # deltas are replicated by one gather: queries fold everywhere (the
    # query table is replicated); docs fold only into the owned slice
    gq = jax.tree.map(lambda x: jax.lax.all_gather(x, axis, tiled=True), dq)
    gd = jax.tree.map(lambda x: jax.lax.all_gather(x, axis, tiled=True), dd)
    gd_l = _localize(gd, base_d, Dl)

    qvec, qlive = _fold_vectors(state["qvec"], state["qlive"], gq)
    dvec, dlive = _fold_vectors(state["dvec"], state["dlive"], gd_l)
    emitted, em_has = state["emitted"], state["em_has"]
    prec = (jax.lax.Precision.HIGHEST if op.precision == "highest"
            else jax.lax.Precision.DEFAULT)

    # uniform across shards (computed from the gathered deltas), so every
    # device takes the same lax.cond branch and collectives line up
    need_full = jnp.any(gd.weights < 0) | jnp.any(gq.weights > 0)

    def _merge2(av, ai, bv, bi):
        """Merge two [Q, k] candidate sets; ties break to the lowest id.

        (score desc, id asc) is a total order, so pairwise merging is
        associative and the ring result matches a flat n*k sort."""
        cv = jnp.concatenate([av, bv], axis=1)
        ci = jnp.concatenate([ai, bi], axis=1)
        order = jnp.argsort(jnp.where(ci < 0, jnp.iinfo(jnp.int32).max, ci),
                            axis=1, stable=True)
        ci = jnp.take_along_axis(ci, order, axis=1)
        cv = jnp.take_along_axis(cv, order, axis=1)
        vals, sel = topk(cv, k)
        return vals, jnp.take_along_axis(ci, sel, axis=1)

    def full_path(_):
        chunk = min(op.scan_chunk, Dl)
        vals_l, ids_l = chunked_corpus_topk(qvec, dvec, dlive, k, chunk,
                                            precision=prec)
        ids_g = jnp.where(vals_l <= NEG, -1, ids_l + base_d)
        # ring merge over ICI neighbors (ppermute): n-1 hops, each passing
        # a [Q, k] candidate window and merging into the local best —
        # peak buffer [Q, 2k] vs an all_gather's [Q, n*k]
        perm = [(i, (i + 1) % n) for i in range(n)]
        acc_v, acc_i = vals_l, ids_g
        cur_v, cur_i = vals_l, ids_g
        for _ in range(n - 1):
            cur_v = jax.lax.ppermute(cur_v, axis, perm)
            cur_i = jax.lax.ppermute(cur_i, axis, perm)
            acc_v, acc_i = _merge2(acc_v, acc_i, cur_v, cur_i)
        return acc_v, acc_i

    def incr_path(_):
        em_ids = emitted[:, :, 0].astype(jnp.int32)
        em_vals = jnp.where(em_has[:, None] & (em_ids >= 0),
                            emitted[:, :, 1], NEG)
        # per-entry scores from the OWNED folded vectors (exactly the
        # single-device dvec[di] semantics), combined with one pmax —
        # non-owned entries contribute NEG
        di = gd.keys
        own = (di >= base_d) & (di < base_d + Dl)
        di_l = jnp.where(own, di - base_d, 0)
        s_loc = jnp.dot(score_form(qvec), score_form(dvec[di_l]).T,
                        preferred_element_type=jnp.float32,
                        precision=prec)                        # [Q, Cd]
        s_loc = jnp.where((own & (gd.weights > 0))[None, :], s_loc, NEG)
        s_new = jax.lax.pmax(s_loc, axis)
        cand_vals = jnp.concatenate([em_vals, s_new], axis=1)
        cand_ids = jnp.concatenate(
            [em_ids, jnp.broadcast_to(di, (Q, di.shape[0]))], axis=1)
        order = jnp.argsort(cand_ids, axis=1, stable=True)
        cand_ids = jnp.take_along_axis(cand_ids, order, axis=1)
        cand_vals = jnp.take_along_axis(cand_vals, order, axis=1)
        vals, sel = topk(cand_vals, k)
        return vals, jnp.take_along_axis(cand_ids, sel, axis=1)

    vals, ids = jax.lax.cond(need_full, full_path, incr_path, None)
    ids = jnp.where(vals <= NEG, -1, ids)
    new_row = jnp.stack([ids.astype(jnp.float32), vals], axis=-1)  # [Q,k,2]

    changed = jnp.any(new_row != emitted, axis=(1, 2))
    ins_m = qlive & (~em_has | changed)
    ret_m = em_has & (~qlive | changed)
    # replicated masks/table; each shard EMITS its owned query range so
    # the egress delta is row-sharded like every other op's
    sl = lambda x: jax.lax.dynamic_slice_in_dim(x, base_q, Ql, 0)
    qkeys = base_q + jnp.arange(Ql, dtype=jnp.int32)
    out = DeviceDelta(
        keys=jnp.concatenate([qkeys, qkeys]),
        values=jnp.concatenate([sl(emitted), sl(new_row)]),
        weights=jnp.concatenate(
            [-sl(ret_m).astype(jnp.int32), sl(ins_m).astype(jnp.int32)]),
    )
    new_emitted = jnp.where(ins_m[:, None, None], new_row, emitted)
    new_has = jnp.where(ins_m, True, jnp.where(ret_m & ~qlive, False, em_has))
    return out, {"qvec": qvec, "qlive": qlive, "dvec": dvec, "dlive": dlive,
                 "emitted": new_emitted, "em_has": new_has}


#: per-leaf shard_map specs for the knn state: corpus sharded, queries +
#: emitted table replicated (consumed by ShardedTpuExecutor)
def knn_state_specs(axis: str):
    return {"qvec": None, "qlive": None, "dvec": axis, "dlive": axis,
            "emitted": None, "em_has": None}


def lower_node_sharded(node: Node, state, ins: Sequence[DeviceDelta],
                       axis, n: int, sizes=None
                       ) -> Tuple[DeviceDelta, dict]:
    kind = node.op.kind
    if kind == "reduce":
        if node.op.how in LINEAR_DEVICE_REDUCERS:
            return _lower_reduce_sharded(node.op, node, state, ins, axis,
                                         n, sizes=sizes)
        return _lower_reduce_minmax_sharded(node.op, node, state, ins,
                                            axis, n, sizes=sizes)
    if kind == "join":
        return _lower_join_sharded(node.op, node, state, ins, axis, n,
                                   sizes=sizes)
    if kind == "knn":
        return _lower_knn_sharded(node.op, node, state, ins, axis, n)
    # stateless row ops are shard-local
    return _LOWERINGS[kind](node.op, node, state, ins)
